"""Rule engine: file walking, waiver parsing, finding plumbing.

Everything here is stdlib-only (``ast`` + ``tokenize``): the container
has no network and nothing may be pip-installed, so graftlint carries
zero dependencies by construction.

A rule is an object with:

- ``id``       — stable slug, shown in output and used by ``--rule``;
- ``waiver``   — the token accepted in ``# graftlint: token(reason)``;
- ``doc``      — one-line description for ``--list-rules``;
- ``check(ctx) -> list[Finding]``            (per-file rules), or
- ``check_repo(root, ctxs) -> list[Finding]`` (repo-wide rules);
- ``applies(rel) -> bool``                   (per-file rules only).

Waivers attach to the flagged line or the line directly above it, and
MUST carry a non-empty reason — an empty waiver is converted into its
own unwaived finding, so "silence it later" can never land.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import re
import tokenize
from pathlib import Path

WAIVER_RE = re.compile(r"#\s*graftlint:\s*([a-z_-]+)\(([^()]*)\)")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str  # repo-relative posix path
    line: int
    message: str
    end_line: int = 0  # inclusive; 0 = same as ``line``
    waived: bool = False
    reason: str = ""

    def __post_init__(self):
        if not self.end_line:
            self.end_line = self.line

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "message": self.message, "waived": self.waived,
            "reason": self.reason,
        }

    def render(self) -> str:
        tag = f" [waived: {self.reason}]" if self.waived else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{tag}"


class Context:
    """One parsed source file plus the lookup structures rules share."""

    def __init__(self, root: Path, path: Path, source: str):
        self.root = root
        self.path = path
        self.rel = path.resolve().relative_to(root.resolve()).as_posix()
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=str(path))
        self.waivers = _parse_waivers(source)
        self._parents: dict[ast.AST, ast.AST] | None = None

    @property
    def parents(self) -> dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    self._parents[child] = node
        return self._parents

    def ancestors(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None


def _parse_waivers(source: str) -> dict[int, list[tuple[str, str]]]:
    """{line: [(token, reason), ...]} from ``# graftlint:`` comments."""
    out: dict[int, list[tuple[str, str]]] = {}
    try:
        toks = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in toks:
            if tok.type != tokenize.COMMENT:
                continue
            for m in WAIVER_RE.finditer(tok.string):
                out.setdefault(tok.start[0], []).append(
                    (m.group(1), m.group(2).strip())
                )
    except tokenize.TokenError:
        pass
    return out


def callee_name(node: ast.AST) -> str:
    """Best-effort name of a call's target: the attribute/identifier,
    or — for immediately-invoked accessors like ``self._window_fn()(…)``
    — the INNER accessor's name (what the repo's rules key on)."""
    func = node.func if isinstance(node, ast.Call) else node
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Call):
        return callee_name(func)
    return ""


def dotted_name(node: ast.AST) -> str:
    """``time.monotonic`` → "time.monotonic" (Attribute chains only)."""
    parts: list[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return ""


def _apply_waivers(findings: list[Finding], ctxs: dict[str, Context],
                   token_for_rule: dict[str, str]) -> list[Finding]:
    out: list[Finding] = []
    for f in findings:
        ctx = ctxs.get(f.path)
        token = token_for_rule.get(f.rule, f.rule)
        waiver = None
        if ctx is not None:
            for ln in range(f.line - 1, f.end_line + 1):
                for tok, reason in ctx.waivers.get(ln, ()):
                    if tok == token:
                        waiver = (ln, reason)
                        break
                if waiver:
                    break
        if waiver is None:
            out.append(f)
        elif not waiver[1]:
            out.append(Finding(
                f.rule, f.path, waiver[0],
                f"waiver `{token}(...)` has no reason — write why this "
                f"site is exempt (finding was: {f.message})",
            ))
        else:
            f.waived = True
            f.reason = waiver[1]
            out.append(f)
    return out


def find_repo_root(start: Path) -> Path:
    cur = start.resolve()
    if cur.is_file():
        cur = cur.parent
    for cand in (cur, *cur.parents):
        if (cand / "pyproject.toml").exists():
            return cand
    return cur


def rules() -> list:
    from .rules import ALL_RULES

    return [cls() for cls in ALL_RULES]


def _collect_files(paths: list[Path]) -> list[Path]:
    files: list[Path] = []
    for p in paths:
        if p.is_dir():
            files.extend(sorted(
                f for f in p.rglob("*.py") if "__pycache__" not in f.parts
            ))
        elif p.suffix == ".py":
            files.append(p)
    return files


def lint_paths(paths: list[str | Path], root: Path | None = None,
               only: str | None = None) -> list[Finding]:
    """Run every rule (or just ``only``) over ``paths``; returns the
    waiver-resolved finding list (waived findings included, marked)."""
    pl = [Path(p) for p in paths]
    if root is None:
        root = find_repo_root(pl[0] if pl else Path.cwd())
    ctxs: dict[str, Context] = {}
    for f in _collect_files(pl):
        try:
            source = f.read_text()
        except (OSError, UnicodeDecodeError):
            continue
        try:
            ctx = Context(root, f, source)
        except SyntaxError as e:
            ctxs_rel = f.resolve().relative_to(root.resolve()).as_posix()
            ctxs[ctxs_rel] = None  # type: ignore[assignment]
            return [Finding("parse", ctxs_rel, e.lineno or 1,
                            f"syntax error: {e.msg}")]
        ctxs[ctx.rel] = ctx

    active = [r for r in rules() if only is None or r.id == only]
    findings: list[Finding] = []
    token_for_rule: dict[str, str] = {}
    for rule in active:
        token_for_rule[rule.id] = getattr(rule, "waiver", rule.id)
        if hasattr(rule, "check_repo"):
            findings.extend(rule.check_repo(root, ctxs))
        else:
            for ctx in ctxs.values():
                if rule.applies(ctx.rel):
                    findings.extend(rule.check(ctx))
    findings = _apply_waivers(findings, ctxs, token_for_rule)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def lint_source(source: str, rel: str, rule_id: str,
                root: Path | None = None) -> list[Finding]:
    """Test helper: run ONE per-file rule over an in-memory snippet as
    if it lived at ``rel`` inside the repo."""
    root = root or Path.cwd()
    ctx = Context(root, root / rel, source)
    ctx.rel = rel  # honor the caller's virtual location exactly
    rule = next(r for r in rules() if r.id == rule_id)
    if not rule.applies(rel):
        return []
    findings = rule.check(ctx)
    return _apply_waivers(
        findings, {rel: ctx}, {rule.id: getattr(rule, "waiver", rule.id)}
    )


def render_report(findings: list[Finding], as_json: bool) -> tuple[str, int]:
    """(report text, exit code)."""
    unwaived = [f for f in findings if not f.waived]
    if as_json:
        body = json.dumps({
            "findings": [f.to_dict() for f in findings],
            "total": len(findings),
            "waived": len(findings) - len(unwaived),
            "unwaived": len(unwaived),
        }, indent=2)
        return body, (1 if unwaived else 0)
    out = [f.render() for f in findings]
    out.append(
        f"graftlint: {len(findings)} finding(s), "
        f"{len(findings) - len(unwaived)} waived, "
        f"{len(unwaived)} unwaived"
    )
    return "\n".join(out), (1 if unwaived else 0)
