"""knob drift: every config knob stays validated and documented.

``utils/config.py`` is the single source of truth for the service's
env-var surface, but nothing used to force the rest of the repo to
keep up: a knob added without a validator accepts garbage at boot
instead of failing fast, and a knob missing from the README table is
invisible to operators (r8's ``SEQ_BUCKETS`` routing bug went
unnoticed partly because the interaction was undocumented).

For every ``ServiceConfig`` field this repo-wide rule requires:

1. **a validator** — the field is named in a ``field_validator``
   decorator, or read (``self.<field>``) inside a ``model_validator``.
   Exempt by construction: ``bool`` fields (pydantic coerces, there is
   no range to check) and optional free-form strings (``str | None`` —
   paths/URLs with no vocabulary).
2. **a README knob-table row** — a markdown table row containing
   `` `ENV_NAME` ``.
3. **a docs mention** — ``ENV_NAME`` appears somewhere in README.md or
   ``docs/*.md``.

Findings anchor at the field's declaration line in config.py; waive
with ``# graftlint: knob(<reason>)`` there.
"""

from __future__ import annotations

import ast
from pathlib import Path

from ..core import Context, Finding

_CONFIG_REL = "mlmicroservicetemplate_tpu/utils/config.py"


def _ann_str(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


def _config_fields(tree: ast.Module) -> list[tuple[str, str, int]]:
    """(field, annotation, line) for every ServiceConfig field."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "ServiceConfig":
            for stmt in node.body:
                if (
                    isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)
                    and not stmt.target.id.startswith("_")
                ):
                    out.append((
                        stmt.target.id, _ann_str(stmt.annotation),
                        stmt.lineno,
                    ))
    return out


def _validated_fields(tree: ast.Module) -> set[str]:
    """Fields covered by a field_validator decorator or read inside a
    model_validator body."""
    covered: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        for dec in node.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            dec_name = dec.func.attr if isinstance(
                dec.func, ast.Attribute
            ) else getattr(dec.func, "id", "")
            if dec_name == "field_validator":
                for arg in dec.args:
                    if isinstance(arg, ast.Constant) and isinstance(
                        arg.value, str
                    ):
                        covered.add(arg.value)
            elif dec_name == "model_validator":
                for sub in ast.walk(node):
                    if (
                        isinstance(sub, ast.Attribute)
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id == "self"
                    ):
                        covered.add(sub.attr)
    return covered


def _validator_exempt(annotation: str) -> bool:
    ann = annotation.replace(" ", "")
    if ann == "bool":
        return True
    # Optional free-form strings: paths, URLs, raw prefix text.
    return ann in ("str|None", "Optional[str]", "None|str")


class KnobDriftRule:
    id = "knob-drift"
    waiver = "knob"
    doc = ("every utils/config.py knob needs a validator, a README "
           "knob-table row, and a docs mention")

    def check_repo(self, root: Path, ctxs: dict[str, Context]
                   ) -> list[Finding]:
        ctx = ctxs.get(_CONFIG_REL)
        if ctx is None:
            path = root / _CONFIG_REL
            if not path.exists():
                return []
            ctx = Context(root, path, path.read_text())
            ctxs[_CONFIG_REL] = ctx  # waivers resolve in config.py
        fields = _config_fields(ctx.tree)
        covered = _validated_fields(ctx.tree)

        readme = (root / "README.md")
        readme_text = readme.read_text() if readme.exists() else ""
        # A knob-table row is any markdown table line naming the knob
        # in backticks (combined rows like `| \`A\` / \`B\` |` count).
        table_text = "\n".join(
            ln for ln in readme_text.splitlines() if ln.startswith("|")
        )
        docs_text = readme_text
        docs_dir = root / "docs"
        if docs_dir.is_dir():
            for md in sorted(docs_dir.glob("*.md")):
                docs_text += md.read_text()

        findings: list[Finding] = []
        for field, ann, line in fields:
            env = field.upper()
            if field not in covered and not _validator_exempt(ann):
                findings.append(Finding(
                    self.id, _CONFIG_REL, line,
                    f"knob `{field}` ({env}) has no validator — a typo'd "
                    f"value boots instead of failing fast",
                ))
            if f"`{env}`" not in table_text:
                findings.append(Finding(
                    self.id, _CONFIG_REL, line,
                    f"knob `{env}` has no README knob-table row "
                    f"(`| \\`{env}\\` | default | meaning |`)",
                ))
            if env not in docs_text:
                findings.append(Finding(
                    self.id, _CONFIG_REL, line,
                    f"knob `{env}` is mentioned nowhere in README.md or "
                    f"docs/*.md",
                ))
        return findings
