"""metric drift: the /metrics surface, its test and its dashboard agree.

``utils/metrics.py`` declares the observability contract; the
metrics-surface test and the Grafana dashboard are its two consumers.
Three ways they historically drifted, each now a finding:

1. **dashboard drift** — a series declared in utils/metrics.py that
   appears nowhere in ``docs/grafana-serving.json``: it is invisible
   to operators (the r11 dashboard predates five PRs of new series).
2. **test drift** — ``tests/test_metrics_surface.py`` must keep its
   declaration-introspection pin (`_declared_families` + the
   "missing from /metrics" assertion).  While the pin is present every
   declared series is checked against a real scrape automatically; if
   someone deletes the pin, every series fires here.
3. **inline metric creation** — ``Counter``/``Gauge``/``Histogram``
   construction (or a ``prometheus_client`` import) outside
   utils/metrics.py: series created elsewhere dodge both consumers.

Plus a **label-cardinality bound**: ≤ 3 labels per family and no
request-unique label names (``rid``/``request_id``/…) — a leaked
label blows up Prometheus before any dashboard notices.

Waive with ``# graftlint: metric(<reason>)`` at the declaration.
"""

from __future__ import annotations

import ast
from pathlib import Path

from ..core import Context, Finding, callee_name

_METRICS_REL = "mlmicroservicetemplate_tpu/utils/metrics.py"
_TEST_REL = "tests/test_metrics_surface.py"
_GRAFANA_REL = "docs/grafana-serving.json"
_FACTORIES = {"Counter", "Gauge", "Histogram", "Summary"}
_MAX_LABELS = 3
_UNBOUNDED_LABELS = {"rid", "request_id", "stream_id", "jid", "job_id"}


def _declared_series(tree: ast.Module) -> list[tuple[str, list[str], int]]:
    """(series_name, labels, line) for each module-level declaration."""
    out = []
    for node in tree.body:
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        call = node.value
        if callee_name(call) not in _FACTORIES:
            continue
        if not (call.args and isinstance(call.args[0], ast.Constant)):
            continue
        name = call.args[0].value
        labels: list[str] = []
        label_arg = call.args[2] if len(call.args) > 2 else None
        for kw in call.keywords:
            if kw.arg in ("labelnames", "labels"):
                label_arg = kw.value
        if isinstance(label_arg, (ast.List, ast.Tuple)):
            labels = [
                e.value for e in label_arg.elts
                if isinstance(e, ast.Constant)
            ]
        out.append((str(name), labels, node.lineno))
    return out


class MetricDriftRule:
    id = "metric-drift"
    waiver = "metric"
    doc = ("every utils/metrics.py series must reach the surface test "
           "and the Grafana dashboard; no inline metric creation; "
           "bounded label sets")

    def check_repo(self, root: Path, ctxs: dict[str, Context]
                   ) -> list[Finding]:
        ctx = ctxs.get(_METRICS_REL)
        if ctx is None:
            path = root / _METRICS_REL
            if not path.exists():
                return []
            ctx = Context(root, path, path.read_text())
            ctxs[_METRICS_REL] = ctx
        series = _declared_series(ctx.tree)

        grafana_path = root / _GRAFANA_REL
        grafana = grafana_path.read_text() if grafana_path.exists() else ""
        test_path = root / _TEST_REL
        test_text = test_path.read_text() if test_path.exists() else ""
        has_pin = (
            "_declared_families" in test_text
            and "missing from /metrics" in test_text
        )

        findings: list[Finding] = []
        if not has_pin:
            findings.append(Finding(
                self.id, _METRICS_REL, 1,
                f"{_TEST_REL} lost its declaration-introspection pin "
                f"(_declared_families + 'missing from /metrics') — "
                f"series drift is no longer tested",
            ))
        for name, labels, line in series:
            if name not in grafana:
                findings.append(Finding(
                    self.id, _METRICS_REL, line,
                    f"series `{name}` appears nowhere in {_GRAFANA_REL} "
                    f"— declared observability that no dashboard shows",
                ))
            if not has_pin and name not in test_text:
                findings.append(Finding(
                    self.id, _METRICS_REL, line,
                    f"series `{name}` unchecked by {_TEST_REL}",
                ))
            if len(labels) > _MAX_LABELS:
                findings.append(Finding(
                    self.id, _METRICS_REL, line,
                    f"series `{name}` has {len(labels)} labels (cap "
                    f"{_MAX_LABELS}) — cardinality risk",
                ))
            bad = sorted(set(labels) & _UNBOUNDED_LABELS)
            if bad:
                findings.append(Finding(
                    self.id, _METRICS_REL, line,
                    f"series `{name}` labels {bad} look request-unique "
                    f"— unbounded cardinality",
                ))

        # Inline metric creation outside utils/metrics.py.
        for rel, fctx in ctxs.items():
            if fctx is None or rel == _METRICS_REL:
                continue
            if not rel.startswith("mlmicroservicetemplate_tpu/"):
                continue
            for node in ast.walk(fctx.tree):
                if (
                    isinstance(node, ast.ImportFrom)
                    and node.module == "prometheus_client"
                ):
                    findings.append(Finding(
                        self.id, rel, node.lineno,
                        "prometheus_client import outside "
                        "utils/metrics.py — inline series dodge the "
                        "surface test and the dashboard",
                    ))
                elif (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _FACTORIES
                    and getattr(node.func.value, "id", "") == "metrics"
                ):
                    findings.append(Finding(
                        self.id, rel, node.lineno,
                        f"inline metrics.{node.func.attr}(...) outside "
                        f"utils/metrics.py",
                    ))
        return findings
