"""dispatch-guard coverage: every device dispatch rides the guard.

The r9 fault-tolerance layer (engine/faults.py) only sees dispatches
that flow through ``InferenceEngine.dispatch_guard(site, fn)`` — the
watchdog deadline, transient retries, fault injection, per-site host
attribution (``dispatch_host_seconds{site}``) and the fleet breaker
hooks all live there.  A dispatch that bypasses it is invisible to
every one of them: the r8 "legacy path" routing bug was exactly this
class (streams silently served outside the deadline queue), and an
unguarded fetch can wedge the decode loop forever with the watchdog
none the wiser.

This rule flags calls inside ``engine/`` and ``scheduler/`` that hit a
device-dispatch surface — registry decode/prefill executables
(``generate_chunk*``, ``prefill_chunk*``, ``*_window*``), the repo's
immediately-invoked jit accessors (``self._window_fn()(…)``,
``self._paged_handoff_fn()(…)``, …) and host↔device syncs
(``jax.device_get`` / ``device_put`` / ``block_until_ready``) — unless
the call sits inside a callable passed to ``dispatch_guard`` (or the
watchdog's ``run``), or carries an explicit waiver::

    # graftlint: unguarded(<why this site is exempt>)

Three structural exemptions, by construction rather than waiver:

- calls inside a function handed to ``jax.jit`` (or a ``lax`` control-
  flow body nested in one) are TRACE-TIME composition, not host
  dispatches — the dispatch is wherever the jitted callable is later
  invoked;
- calls inside the definition of a dispatch surface itself (e.g.
  ``run_batch``'s internals, ``start_fused``): the guard belongs at
  the CALL boundary, where the site label is known;
- calls inside warm-up functions (``warmup`` / ``warm`` / ``_warm_*``):
  pre-serving by construction — boot/spawn failures are owned by the
  supervisor and the scaling governor, and guarding them would
  re-number every deterministic ``FAULT_SPEC`` schedule the chaos
  suites have pinned since r9.
"""

from __future__ import annotations

import ast
import re

from ..core import Context, Finding, callee_name, dotted_name

# Immediately-invoked jit-accessor idiom: ``self._paged_chunk_fn()(…)``.
_ACCESSOR_RE = re.compile(
    r"^_?[a-z0-9_]*(chunk|prefill|window|handoff|scatter|gather|swap)"
    r"[a-z0-9_]*_fn$"
)
# Direct dispatch / sync surfaces.
_DIRECT_RE = re.compile(
    r"^(generate_chunk\w*|generate_window\w*|prefill_chunk\w*|"
    r"paged_prefill\w*|device_get|device_put|block_until_ready|"
    r"_gen_chunk|_spec_chunk|_start|start_fused|_start_prefixed\w*|"
    r"run_batch)$"
)

_WARM_RE = re.compile(r"^_?warm")

# Perf-observatory timestamp-capture APIs (r20, utils/perfobs.py):
# submit stamps and completion samples are only honest when they ride
# the dispatch_guard boundary or a fetch seam — a capture site in a
# function that never dispatches under the guard is inventing device
# timestamps the estimator will faithfully mis-account.  The
# ``_perf_complete`` helper is the streams-side seam wrapper; its
# CALLERS are checked, its own body is the definition.
_PERF_CAPTURE = {"note_submit", "note_complete", "on_guard",
                 "_perf_complete"}
_PERF_EXEMPT_FUNCS = {"dispatch_guard", "_perf_complete"}

_SCOPES = (
    "mlmicroservicetemplate_tpu/engine/",
    "mlmicroservicetemplate_tpu/scheduler/",
)
# The guard machinery itself dispatches bare by definition.
_EXEMPT_FILES = {"mlmicroservicetemplate_tpu/engine/faults.py"}
_EXEMPT_FUNCS = {"dispatch_guard"}


def _is_dispatch_call(node: ast.Call) -> str | None:
    """The matched surface name, or None."""
    func = node.func
    if isinstance(func, ast.Call):
        inner = callee_name(func)
        if _ACCESSOR_RE.match(inner):
            return f"{inner}()"
        return None
    name = callee_name(node)
    if _DIRECT_RE.match(name):
        return name
    return None


class DispatchGuardRule:
    id = "dispatch-guard"
    waiver = "unguarded"
    doc = ("device dispatches in engine//scheduler/ must run under "
           "dispatch_guard(site, ...) — else the watchdog, fault "
           "injection, breaker and attribution never see them; perf "
           "timestamp-capture calls (note_submit/note_complete/"
           "on_guard) must live in functions that dispatch under the "
           "guard (the r20 zero-sync estimator's honesty contract)")

    def applies(self, rel: str) -> bool:
        return (
            rel.startswith(_SCOPES) and rel not in _EXEMPT_FILES
        )

    def check(self, ctx: Context) -> list[Finding]:
        guarded_ids: set[int] = set()
        guarded_fn_names: set[str] = set()
        traced_ids: set[int] = set()
        traced_fn_names: set[str] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = callee_name(node)
            is_guard = name in ("dispatch_guard", "guard") or (
                name == "run"
                and "watchdog" in dotted_name(node.func).lower()
            )
            is_trace = name in ("jit", "while_loop", "scan", "cond",
                                "fori_loop")
            if not (is_guard or is_trace):
                continue
            ids = guarded_ids if is_guard else traced_ids
            names = guarded_fn_names if is_guard else traced_fn_names
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name):
                    names.add(arg.id)
                for sub in ast.walk(arg):
                    ids.add(id(sub))

        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            surface = _is_dispatch_call(node)
            if surface is None:
                continue
            if id(node) in guarded_ids or id(node) in traced_ids:
                continue
            skip = False
            for anc in ctx.ancestors(node):
                if not isinstance(anc, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    continue
                if (
                    anc.name in guarded_fn_names
                    or anc.name in traced_fn_names
                    or anc.name in _EXEMPT_FUNCS
                    or _DIRECT_RE.match(anc.name)  # the surface itself
                    or _WARM_RE.match(anc.name)    # pre-serving warm-up
                ):
                    skip = True
                    break
            if skip:
                continue
            findings.append(Finding(
                self.id, ctx.rel, node.lineno,
                f"device dispatch `{surface}` outside dispatch_guard — "
                f"the watchdog/fault-injector/attribution never see it "
                f"(wrap it, or waive: # graftlint: unguarded(reason))",
                end_line=getattr(node, "end_lineno", node.lineno),
            ))
        findings.extend(self._check_perf_capture(ctx))
        return findings

    def _check_perf_capture(self, ctx: Context) -> list[Finding]:
        """Perf-observatory capture sites (r20): a ``note_submit`` /
        ``note_complete`` / ``on_guard`` / ``_perf_complete`` call must
        sit inside a function that itself dispatches under
        ``dispatch_guard`` (the fetch/dispatch seams) — anywhere else
        the timestamp it captures describes no device event."""
        # Functions whose body contains a dispatch_guard/watchdog-run
        # call: the legitimate seams.
        guard_fns: set[str] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    name = callee_name(sub)
                    if name in ("dispatch_guard", "guard") or (
                        name == "run"
                        and "watchdog" in dotted_name(sub.func).lower()
                    ):
                        guard_fns.add(node.name)
                        break
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = callee_name(node)
            if name not in _PERF_CAPTURE:
                continue
            enclosing = None
            for anc in ctx.ancestors(node):
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    enclosing = anc.name
                    break
            if enclosing is not None and (
                enclosing in guard_fns
                or enclosing in _PERF_EXEMPT_FUNCS
                or _WARM_RE.match(enclosing)
            ):
                continue
            findings.append(Finding(
                self.id, ctx.rel, node.lineno,
                f"perf capture `{name}` in a function that never "
                f"dispatches under dispatch_guard — the timestamp "
                f"describes no device event (move it to a guard/fetch "
                f"seam, or waive: # graftlint: unguarded(reason))",
                end_line=getattr(node, "end_lineno", node.lineno),
            ))
        return findings
