"""Rule registry.  Order is presentation order in ``--list-rules``."""

from .dispatch_guard import DispatchGuardRule
from .write_ahead import WriteAheadRule
from .clock_injection import ClockInjectionRule
from .knob_drift import KnobDriftRule
from .metric_drift import MetricDriftRule
from .exceptions import ExceptionDisciplineRule
from .exec_cache import ExecCacheRule

ALL_RULES = [
    DispatchGuardRule,
    WriteAheadRule,
    ClockInjectionRule,
    KnobDriftRule,
    MetricDriftRule,
    ExceptionDisciplineRule,
    ExecCacheRule,
]
