"""exec-cache coverage: serving-layer executables ride the shared cache.

The r19 zero-compile-spawn invariant and the r21 kernel autotuner both
hang off one property: every jitted executable the serving layers
construct is keyed in the process-level ExecutableCache
(``runtime/compile_cache.py``) — via the engine's ``_shared_jit`` or
``shared_executable`` directly — so replica spawns, supervised
rebuilds and journal replays resolve the SAME wrapper (and its jit
cache) instead of re-tracing, and the compile-counting tests
(``CompileWindow``) actually see every compile the layer can cause.

A bare ``jax.jit(...)`` (or a raw ``pl.pallas_call(...)`` kernel
construction) in ``engine/`` or ``scheduler/`` is invisible to all of
that: it re-traces per engine object, breaks the spawn invariant
silently, and — for kernels — bypasses the autotuner's variant keying
(``docs/kernel_tuning.md``).  This rule flags any such call unless it
sits inside an argument to ``_shared_jit`` / ``shared_executable``
(the builder-lambda idiom: ``self._shared_jit("kind", lambda:
jax.jit(fn), statics=(...))``), or carries an explicit waiver::

    # graftlint: uncached-jit(<why this executable may bypass the cache>)
"""

from __future__ import annotations

import ast

from ..core import Context, Finding, callee_name

_SCOPES = (
    "mlmicroservicetemplate_tpu/engine/",
    "mlmicroservicetemplate_tpu/scheduler/",
)
# The cache machinery itself wraps bare jits by definition.
_CACHE_ROUTES = {"_shared_jit", "shared_executable"}
_FLAGGED = {"jit", "pallas_call"}


class ExecCacheRule:
    id = "exec-cache"
    waiver = "uncached-jit"
    doc = ("jax.jit / pallas_call in engine//scheduler/ must be built "
           "through _shared_jit/shared_executable — a bare wrapper "
           "re-traces per engine, breaks the zero-compile spawn "
           "invariant and bypasses the autotuner's variant keying")

    def applies(self, rel: str) -> bool:
        return rel.startswith(_SCOPES)

    def check(self, ctx: Context) -> list[Finding]:
        routed_ids: set[int] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if callee_name(node) not in _CACHE_ROUTES:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    routed_ids.add(id(sub))
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = callee_name(node)
            if name not in _FLAGGED:
                continue
            if id(node) in routed_ids:
                continue
            findings.append(Finding(
                self.id, ctx.rel, node.lineno,
                f"`{name}(...)` built outside the ExecutableCache route "
                f"— wrap it in _shared_jit/shared_executable so spawns "
                f"share it and CompileWindow sees it, or waive: "
                f"# graftlint: uncached-jit(reason)",
                end_line=getattr(node, "end_lineno", node.lineno),
            ))
        return findings
