"""write-ahead ordering: the journal learns before the consumer does.

The durability contract (runtime/durability.py, jobs/store.py) is that
after ``kill -9`` the journal covers EVERYTHING any client observed —
reconnects dedup with zero double emission, job lines re-run at most
the in-flight tail.  That holds only while every consumer-visible
emission is dominated by its matching journal append *in the same
function*: a crash in the gap between "append" and "emit" must err on
the journal-knows-more side, never the client-knows-more side.

Checked surfaces:

- ``engine/streams.py`` and ``engine/fleet.py``: every
  ``st.emit(...)`` call must be preceded (same function, earlier
  line) by a journal append (``.tokens(…)`` / ``.done(…)`` /
  ``.admit(…)`` — one-plus-argument calls, so ``future.done()``
  probes never count);
- ``jobs/store.py``: every assignment into ``job.results[...]`` (the
  in-memory view GET results serves) must be preceded by a frame
  ``._append(...)``.

Waive with ``# graftlint: write-ahead(<reason>)`` — e.g. replay
readers that materialize records already on disk.
"""

from __future__ import annotations

import ast

from ..core import Context, Finding, callee_name

_JOURNAL_ATTRS = {
    "tokens", "done", "admit", "_append", "result",
    # The loop's write-ahead terminal helper (idempotent j.done).
    "_journal_done",
}


def _journal_lines(fn: ast.AST) -> list[int]:
    out = []
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Call)
            and callee_name(node) in _JOURNAL_ATTRS
            and (node.args or node.keywords)
        ):
            out.append(node.lineno)
    return out


class WriteAheadRule:
    id = "write-ahead"
    waiver = "write-ahead"
    doc = ("consumer-visible emits in streams.py/jobs must be dominated "
           "by the matching journal append in the same function")

    def applies(self, rel: str) -> bool:
        return rel in (
            "mlmicroservicetemplate_tpu/engine/streams.py",
            "mlmicroservicetemplate_tpu/engine/fleet.py",
            "mlmicroservicetemplate_tpu/jobs/store.py",
        )

    def check(self, ctx: Context) -> list[Finding]:
        findings: list[Finding] = []
        streams = not ctx.rel.endswith("store.py")
        for node in ast.walk(ctx.tree):
            if streams:
                if not (
                    isinstance(node, ast.Call)
                    and callee_name(node) == "emit"
                ):
                    continue
                what = "`.emit(...)`"
            else:
                # jobs/store.py: results become consumer-visible the
                # moment they land in ``job.results``.
                if not (
                    isinstance(node, ast.Assign)
                    and any(
                        isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Attribute)
                        and t.value.attr == "results"
                        for t in node.targets
                    )
                ):
                    continue
                what = "`job.results[...] = ...`"
            fn = ctx.enclosing_function(node)
            if fn is None or fn.name == "emit":
                continue  # the emit definition itself delivers, only
            if any(ln < node.lineno for ln in _journal_lines(fn)):
                continue
            findings.append(Finding(
                self.id, ctx.rel, node.lineno,
                f"{what} in `{fn.name}` with no dominating journal "
                f"append — a crash here leaves the client knowing more "
                f"than the journal (waive: # graftlint: "
                f"write-ahead(reason))",
                end_line=getattr(node, "end_lineno", node.lineno),
            ))
        return findings
