"""exception discipline: no bare excepts, classify guarded faults.

Two checks:

1. **No bare ``except:``** anywhere in the package or tools — a bare
   handler swallows ``KeyboardInterrupt``/``SystemExit`` and turns a
   dead decode loop into a silent hang.

2. **Guarded-site classification** (``engine/`` + ``scheduler/``): an
   ``except`` handler whose ``try`` body runs a
   ``dispatch_guard``/watchdog call must route the exception through
   the fault taxonomy — reference ``faults.is_transient`` /
   ``is_fatal_device`` / ``classify``, delegate to a classify-routing
   helper (``_fail_streams`` / ``_recover``), or re-``raise``.  A
   handler that reacts identically to a poison request and a dead
   device is how a client input ends up opening a circuit breaker
   (the r18 batcher finding was exactly this).

Waive with ``# graftlint: except(<reason>)`` on the handler line.
"""

from __future__ import annotations

import ast

from ..core import Context, Finding, callee_name, dotted_name

_CLASSIFY_NAMES = {
    "is_transient", "is_fatal_device", "classify", "classify_exception",
    "_fail_streams", "_recover",
}
_GUARD_SCOPES = (
    "mlmicroservicetemplate_tpu/engine/",
    "mlmicroservicetemplate_tpu/scheduler/",
)


def _has_guard_call(nodes: list[ast.stmt]) -> bool:
    for stmt in nodes:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                name = callee_name(node)
                if name in ("dispatch_guard", "guard") or (
                    name == "run"
                    and "watchdog" in dotted_name(node.func).lower()
                ):
                    return True
    return False


def _handler_classifies(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, (ast.Name, ast.Attribute)):
            name = node.attr if isinstance(node, ast.Attribute) else node.id
            if name in _CLASSIFY_NAMES:
                return True
    return False


def _catches_broadly(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [dotted_name(e) or getattr(e, "id", "") for e in t.elts]
    else:
        names = [dotted_name(t) or getattr(t, "id", "")]
    return any(n.split(".")[-1] in ("Exception", "BaseException")
               for n in names)


class ExceptionDisciplineRule:
    id = "exception-discipline"
    waiver = "except"
    doc = ("no bare except:; broad handlers around guarded dispatches "
           "must classify via engine.faults (or re-raise)")

    def applies(self, rel: str) -> bool:
        return rel.startswith(("mlmicroservicetemplate_tpu/", "tools/"))

    def check(self, ctx: Context) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                findings.append(Finding(
                    self.id, ctx.rel, node.lineno,
                    "bare `except:` — swallows KeyboardInterrupt/"
                    "SystemExit; catch Exception (or narrower)",
                ))
        if not ctx.rel.startswith(_GUARD_SCOPES):
            return findings
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Try):
                continue
            if not _has_guard_call(node.body):
                continue
            for handler in node.handlers:
                if not _catches_broadly(handler):
                    continue
                if _handler_classifies(handler):
                    continue
                findings.append(Finding(
                    self.id, ctx.rel, handler.lineno,
                    "broad handler around a guarded dispatch reacts "
                    "identically to poison input and dead devices — "
                    "route through faults.is_transient/is_fatal_device "
                    "(or re-raise / waive: # graftlint: except(reason))",
                ))
        return findings
