"""clock injection: policy code never reads the wall clock directly.

``scheduler/policy.py`` and ``engine/supervisor.py`` hold pure,
clock-injected policy (scaling decisions, restart windows, deadline
expiry) precisely so tests pin their behavior without sleeping through
real cooldowns — the r12/r17 test suites depend on it.  A direct
``time.time()`` / ``time.monotonic()`` call in these files silently
re-couples the policy to the wall clock.

The injected-clock DEFAULT stays legal because it is a bare reference,
not a call::

    self._clock = clock if clock is not None else time.monotonic  # ok
    now = time.monotonic()                                        # flagged

Waive with ``# graftlint: clock(<reason>)``.
"""

from __future__ import annotations

import ast

from ..core import Context, Finding, dotted_name

_FORBIDDEN = {"time.time", "time.monotonic", "time.perf_counter"}

_SCOPED_FILES = (
    "mlmicroservicetemplate_tpu/scheduler/policy.py",
    "mlmicroservicetemplate_tpu/engine/supervisor.py",
)


class ClockInjectionRule:
    id = "clock-injection"
    waiver = "clock"
    doc = ("time.time()/time.monotonic() calls are forbidden in "
           "scheduler/policy.py and engine/supervisor.py — route "
           "through the injected clock")

    def applies(self, rel: str) -> bool:
        return rel in _SCOPED_FILES

    def check(self, ctx: Context) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name in _FORBIDDEN:
                findings.append(Finding(
                    self.id, ctx.rel, node.lineno,
                    f"direct `{name}()` call in clock-injected policy "
                    f"code — use the injected clock (`self._clock()`), "
                    f"keeping the bare `{name}` default legal",
                ))
        return findings
