"""graftlint: repo-invariant static analysis (docs/static-analysis.md).

Twelve PRs of review discipline, encoded as checkers.  The serving
stack's correctness rests on conventions no general-purpose linter
knows about — every device dispatch flows through ``dispatch_guard``,
journal appends dominate consumer emits, policy code is clock-injected,
knobs and metrics stay in sync with their docs and dashboards, and
exceptions from guarded sites route through ``faults`` classification.
This package enforces them with stdlib ``ast``/``tokenize`` only (the
container has no network; nothing may be pip-installed).

Usage::

    python -m tools.graftlint mlmicroservicetemplate_tpu/
    python -m tools.graftlint --json mlmicroservicetemplate_tpu/
    python -m tools.graftlint --list-rules

Waivers: ``# graftlint: <token>(<reason>)`` on the flagged line or the
line directly above silences one rule at one site.  The reason is
REQUIRED — an empty waiver is itself a finding.  Exit status is
non-zero iff any unwaived finding remains.
"""

from .core import Finding, lint_paths, lint_source, rules  # noqa: F401
