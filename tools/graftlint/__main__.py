"""CLI: ``python -m tools.graftlint [paths] [--json] [--rule ID]``."""

from __future__ import annotations

import argparse
import sys

from .core import lint_paths, render_report, rules


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="repo-invariant static analysis "
                    "(docs/static-analysis.md)",
    )
    ap.add_argument("paths", nargs="*", default=["mlmicroservicetemplate_tpu"],
                    help="files/dirs to lint (default: the package)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--rule", default=None, metavar="ID",
                    help="run a single rule")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in rules():
            print(f"{r.id:22s} waiver={getattr(r, 'waiver', r.id):12s} "
                  f"{r.doc}")
        return 0
    if args.rule is not None and args.rule not in {r.id for r in rules()}:
        print(f"graftlint: unknown rule {args.rule!r} "
              f"(see --list-rules)", file=sys.stderr)
        return 2

    findings = lint_paths(args.paths, only=args.rule)
    report, code = render_report(findings, args.as_json)
    print(report)
    return code


if __name__ == "__main__":
    sys.exit(main())
