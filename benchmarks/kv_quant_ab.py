"""int8 KV-cache A/B (QUANT_KV, VERDICT r3 item 7).

At the shapes where continuous batching pays (B=8, long context), KV
reads are the SECOND HBM-bandwidth term of the decode step after
weights: B=8, S=1024 llama-1.1B reads ~185 MB of bf16 KV per step
against 1.1 GB of int8 weights.  int8 KV halves that term; this
measures whether the saving survives the quantize/dequant work, per
the repo's "measure it or cut it" standard.

Two-scan differencing per config (relay RTT cancels); decode-step time
for dense vs int8 KV at several context lengths, on int8 weights
(where the KV share is largest — QUANTIZE=0 remeasures on bf16).

    MODEL_NAME=llama python benchmarks/kv_quant_ab.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

BATCH = int(os.environ.get("KV_BATCH", "8"))
CONTEXTS = tuple(
    int(x) for x in os.environ.get("KV_CONTEXTS", "512,1024,1792").split(",")
)


def step_ms(kv_quant: bool, s_len: int, pallas: bool = False) -> tuple[float, bool]:
    import jax

    from timing import chunked_time_per_step

    from mlmicroservicetemplate_tpu.engine import InferenceEngine
    from mlmicroservicetemplate_tpu.models.registry import build_model
    from mlmicroservicetemplate_tpu.parallel import ReplicaSet, make_mesh
    from mlmicroservicetemplate_tpu.utils.config import ServiceConfig

    # Explicit both ways: pallas_decode now AUTO-enables with kv_quant
    # on TPU, so the XLA baseline arm must force it OFF (popping the
    # env would silently measure Pallas-vs-Pallas).
    os.environ["USE_PALLAS_DECODE"] = "1" if pallas else "0"
    cfg = ServiceConfig(
        device=os.environ.get("DEVICE", "tpu"),
        model_name=os.environ.get("MODEL_NAME", "llama"),
        quantize=(os.environ.get("QUANTIZE", "int8") or None),
        quant_kv="int8" if kv_quant else None,
        warmup=False,
        batch_buckets=(BATCH,),
        seq_buckets=(s_len,),
        max_decode_len=32,
        stream_chunk_tokens=16,
        continuous_batching=False,
    )
    bundle = build_model(cfg)
    eng = InferenceEngine(bundle, cfg, ReplicaSet(make_mesh(1)))
    rng = np.random.default_rng(0)
    feats = [
        {"input_ids": rng.integers(5, bundle.cfg.vocab_size, s_len).astype(np.int32),
         "length": np.int32(s_len)}
        for _ in range(BATCH)
    ]
    with eng._lock:
        ids, mask, _ = eng._collate_text(feats)
        sp, _ = eng._collate_sample(feats, ids.shape[0])
        ids, mask = eng.replicas.place_batch(ids, mask)
        state, _ = eng._start(
            eng.params, ids, mask, sp, eng.max_decode_len, eng.chunk_tokens, False
        )
        jax.block_until_ready(state.done)
    per, noisy = chunked_time_per_step(
        eng._gen_chunk, eng.params, state,
        iters=int(os.environ.get("CHUNK_ITERS", "48")),
    )
    return per * 1e3, noisy


def main() -> None:
    from mlmicroservicetemplate_tpu.runtime.device import apply_device_env
    from mlmicroservicetemplate_tpu.utils.config import ServiceConfig

    apply_device_env(ServiceConfig(device=os.environ.get("DEVICE", "tpu")))
    rows = []
    # Pallas decode-attention columns (VERDICT r4 next #5): in-kernel
    # int8 dequant tests the hypothesis behind the measured XLA
    # kv-quant loss, and the dense kernel removes the GQA repeat.
    # KV_PALLAS=0 skips them.
    do_pallas = os.environ.get("KV_PALLAS", "1").lower() not in (
        "0", "false", "no"
    )
    for s_len in CONTEXTS:
        dense_ms, n1 = step_ms(False, s_len)
        q_ms, n2 = step_ms(True, s_len)
        row = {
            "context": s_len,
            "batch": BATCH,
            "dense_kv_step_ms": round(dense_ms, 3),
            "int8_kv_step_ms": round(q_ms, 3),
            "timing_noisy": bool(n1 or n2),
            "speedup": round(dense_ms / max(q_ms, 1e-9), 3),
        }
        if do_pallas:
            pd_ms, n3 = step_ms(False, s_len, pallas=True)
            pq_ms, n4 = step_ms(True, s_len, pallas=True)
            row.update({
                "dense_pallas_step_ms": round(pd_ms, 3),
                "int8_pallas_step_ms": round(pq_ms, 3),
                "pallas_dense_speedup": round(dense_ms / max(pd_ms, 1e-9), 3),
                "pallas_int8_vs_dense_xla": round(
                    dense_ms / max(pq_ms, 1e-9), 3
                ),
                "timing_noisy_pallas": bool(n3 or n4),
            })
        rows.append(row)
        print(json.dumps(rows[-1]), flush=True)
    print(json.dumps({
        "model": os.environ.get("MODEL_NAME", "llama"),
        "weights": os.environ.get("QUANTIZE", "int8") or "bf16",
        "rows": rows,
    }))


if __name__ == "__main__":
    main()
