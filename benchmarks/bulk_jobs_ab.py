"""Bulk-jobs A/B: does idle-compute backfill cost the interactive lane?

The judged claims (ISSUE 11):

1. **Non-interference**: interactive streaming TTFT/TBT with a bulk
   ``/v1/batches`` job running stays within noise of the
   interactive-only arm — bulk lines are batch-class streams behind
   the deadline queue, pacer and preemption, so they yield at chunk
   boundaries the moment interactive work arrives.
2. **Reclaimed throughput**: the bulk job makes strictly positive
   token progress during the same window — compute the interactive
   lane wasn't using.

Two in-process arms over tiny-dims llama (``LLAMA_CONFIG``, so the
arms measure scheduling, not model compute):

- ``interactive_only``       — N sequential streaming requests.
- ``interactive_plus_bulk``  — the same N requests while a JOBS_ENABLED
  server chews a bulk job; bulk tokens/s is read off the job's own
  per-line token counts before/after the window.

    python benchmarks/bulk_jobs_ab.py              # current backend
    DEVICE=cpu python benchmarks/bulk_jobs_ab.py   # CPU sanity run

One JSON line per arm to stdout, a markdown table to stderr.
"""

from __future__ import annotations

import asyncio
import json
import os
import statistics
import sys
import tempfile
import time

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(_here))

os.environ["LLAMA_CONFIG"] = json.dumps({
    "vocab_size": 300, "d_model": 32, "num_heads": 4, "num_kv_heads": 2,
    "num_layers": 2, "d_ff": 64, "max_position": 256,
})

from harness import ServiceUnderTest, pctile  # noqa: E402

ROUNDS = int(os.environ.get("JOBS_AB_ROUNDS", "10"))
BULK_LINES = int(os.environ.get("JOBS_AB_LINES", "24"))
PROMPT = "the quick brown fox jumps over the lazy dog"

BASE = {
    "MODEL_NAME": "llama",
    "SEQ_BUCKETS": "16,32", "BATCH_BUCKETS": "1,2,4",
    "MAX_DECODE_LEN": "24", "STREAM_CHUNK_TOKENS": "4",
    "MAX_STREAMS": "4", "MAX_STREAM_QUEUE": "8",
    "WARMUP": "0",
}


async def interactive_round(svc, i: int) -> tuple[float, list[float]]:
    """One streaming request: (ttft, inter-chunk gaps)."""
    t0 = time.perf_counter()
    resp = await svc.client.post(
        "/predict", json={"text": f"{PROMPT} {i}", "stream": True},
        headers={"X-Priority": "interactive"},
    )
    assert resp.status == 200, await resp.text()
    ttft = None
    gaps, prev = [], None
    async for line in resp.content:
        now = time.perf_counter()
        if ttft is None:
            ttft = now - t0
        if prev is not None:
            gaps.append(now - prev)
        prev = now
        if json.loads(line).get("done"):
            break
    return ttft if ttft is not None else time.perf_counter() - t0, gaps


async def drive_interactive(svc) -> dict:
    # One untimed warm round: WARMUP=0 puts the first-stream compiles
    # on the request path, and both arms would otherwise report that
    # one-off as their p99.
    await interactive_round(svc, -1)
    ttfts, gaps = [], []
    t0 = time.perf_counter()
    for i in range(ROUNDS):
        ttft, g = await interactive_round(svc, i)
        ttfts.append(ttft)
        gaps.extend(g)
    wall = time.perf_counter() - t0
    return {
        "ttft_p50_ms": round(statistics.median(ttfts) * 1000, 2),
        "ttft_p99_ms": round(pctile(ttfts, 0.99) * 1000, 2),
        "tbt_p99_ms": (
            round(pctile(gaps, 0.99) * 1000, 2) if gaps else None
        ),
        "interactive_wall_s": round(wall, 2),
    }


async def job_tokens(svc, jid: str) -> int:
    resp = await svc.client.get(f"/v1/batches/{jid}/results")
    assert resp.status == 200
    text = await resp.text()
    return sum(
        json.loads(ln)["tokens"] for ln in text.splitlines() if ln
    )


async def arm_interactive_only() -> dict:
    async with ServiceUnderTest(BASE) as svc:
        row = await drive_interactive(svc)
    return {"arm": "interactive_only", **row}


async def arm_interactive_plus_bulk() -> dict:
    jdir = tempfile.mkdtemp(prefix="jobs-ab-")
    env = {
        **BASE, "JOURNAL_DIR": jdir, "JOURNAL_FSYNC": "off",
        "JOBS_ENABLED": "1", "JOB_MAX_CONCURRENT_LINES": "2",
    }
    async with ServiceUnderTest(env) as svc:
        payload = "\n".join(
            json.dumps({"text": f"{PROMPT} bulk {i}"})
            for i in range(BULK_LINES)
        )
        resp = await svc.client.post(
            "/v1/batches", data=payload,
            headers={"Content-Type": "application/x-ndjson"},
        )
        assert resp.status == 201, await resp.text()
        jid = (await resp.json())["id"]
        # Let the backfill spin up before the interactive window opens.
        await asyncio.sleep(0.5)
        tok0 = await job_tokens(svc, jid)
        t0 = time.perf_counter()
        row = await drive_interactive(svc)
        window = time.perf_counter() - t0
        tok1 = await job_tokens(svc, jid)
        # Drain the rest of the job (bounded) so the arm also reports
        # whether the job completes cleanly.
        status = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            body = await (await svc.client.get(f"/v1/batches/{jid}")).json()
            status = body["status"]
            if status == "completed":
                break
            await asyncio.sleep(0.25)
        row.update({
            "bulk_tokens_in_window": tok1 - tok0,
            "bulk_tokens_s": round((tok1 - tok0) / window, 2),
            "job_status": status,
            "bulk_lines": BULK_LINES,
        })
    return {"arm": "interactive_plus_bulk", **row}


async def main() -> None:
    rows = [await arm_interactive_only(), await arm_interactive_plus_bulk()]
    import jax

    backend = jax.default_backend()
    print("\n| arm | metrics | backend |", file=sys.stderr)
    print("|---|---|---|", file=sys.stderr)
    for row in rows:
        m = ", ".join(f"{k}={v}" for k, v in row.items() if k != "arm")
        print(f"| {row['arm']} | {m} | {backend} |", file=sys.stderr)
        print(json.dumps({**row, "backend": backend}))
    a, b = rows
    delta = b["ttft_p99_ms"] - a["ttft_p99_ms"]
    print(
        f"\ninteractive p99 TTFT delta with bulk running: {delta:+.2f} ms; "
        f"bulk reclaimed {b['bulk_tokens_s']} tok/s from idle compute",
        file=sys.stderr,
    )


if __name__ == "__main__":
    asyncio.run(main())
