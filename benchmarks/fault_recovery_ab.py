"""Fault-recovery A/B: goodput + p99 TTFT under an injected fault
schedule, supervised vs unsupervised.

The judged claim (ISSUE 4): with the SAME deterministic ``FAULT_SPEC``
(a transient, a fatal device loss, a 2-second hang, another transient,
all on the continuous loop's chunk dispatches), the supervised engine
(watchdog + retry + checkpoint/rebuild/resume) delivers strictly more
goodput than the unsupervised seed behavior, where a transient or
fatal chunk fault error-terminates every live stream and the hang
stalls the loop for its full duration.

Three arms over the same gpt2 service (random-init weights — recovery
economics depend on dispatch structure, not weights):

- **clean**:        no faults (the reference ceiling).
- **supervised**:   FAULT_SPEC + DISPATCH_TIMEOUT_S/RETRIES + SUPERVISE=1.
- **unsupervised**: same FAULT_SPEC, watchdog and supervisor off.

N streams arrive in two waves; each stream reports TTFT, tokens and
whether it terminated cleanly (a mid-stream in-band ``error`` line
counts as a failed stream).  Goodput = tokens delivered by error-free
streams / wall.

    python benchmarks/fault_recovery_ab.py              # current backend
    DEVICE=cpu python benchmarks/fault_recovery_ab.py   # CPU sanity run

One JSON line per arm to stdout, a markdown table to stderr.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(_here))
from harness import ServiceUnderTest, pctile  # noqa: E402

N_STREAMS = int(os.environ.get("FAULT_AB_N", "8"))
# Deterministic schedule on the chunk site: transient (retryable),
# fatal (engine rebuild), a FINITE 45 s hang (so the unsupervised arm
# stalls measurably instead of forever), one more transient.
FAULT_SPEC = os.environ.get(
    "FAULT_AB_SPEC",
    "chunk:transient@2;chunk:fatal@4;chunk:hang(45)@6;chunk:transient@8",
)
# Watchdog deadline for the supervised arm: must sit ABOVE this host's
# honest dispatch time (real gpt2 on a 1-vCPU CPU backend runs ~2-5 s
# per batched dispatch; a too-tight deadline crash-loops on false
# positives — measured, see BASELINE.md round 9) and BELOW the hang.
TIMEOUT_S = os.environ.get("FAULT_AB_TIMEOUT_S", "20")

PROMPTS = [
    "the quick brown fox jumps",
    "pack my box with five dozen",
    "a longer prompt that spans a few more tokens than the others do",
    "short one",
]


async def _one(client, i: int):
    text = PROMPTS[i % len(PROMPTS)]
    t0 = time.perf_counter()
    try:
        # Mixed budgets: waves don't finish in lockstep, so follow-up
        # chunk dispatches keep flowing and the later schedule entries
        # (the hang) actually land.
        resp = await client.post(
            "/predict",
            json={"text": text, "stream": True,
                  "max_tokens": 16 if i % 2 == 0 else 8},
        )
        if resp.status != 200:
            await resp.read()
            return {"ok": False, "status": resp.status, "tokens": 0}
        ttft = None
        n_tok = 0
        failed = False
        async for line in resp.content:
            if not line.strip():
                continue
            if ttft is None:
                ttft = time.perf_counter() - t0
            row = json.loads(line)
            if "error" in row:
                failed = True
                break
            if row.get("done"):
                n_tok = int(row.get("tokens_generated", 0))
                break
        return {"ok": not failed and n_tok > 0, "status": 200,
                "tokens": 0 if failed else n_tok, "ttft": ttft}
    except Exception:
        return {"ok": False, "status": -1, "tokens": 0}


async def run_arm(name: str, extra: dict, dev: dict) -> dict:
    overrides = {
        "MODEL_NAME": "gpt2",
        "BATCH_BUCKETS": "1,4",
        "SEQ_BUCKETS": "64",
        "MAX_DECODE_LEN": "16",
        "MAX_STREAMS": "4",
        "MAX_STREAM_QUEUE": "16",
        "WARMUP_SAMPLING": "0",  # greedy-only workload: halve warmup
        **extra,
        **dev,
    }
    async with ServiceUnderTest(overrides) as s:
        t0 = time.perf_counter()
        # Two waves: the second arrives while the schedule's faults are
        # landing on the first, so recovery economics show in BOTH
        # queued and in-flight streams.
        first = asyncio.gather(
            *(_one(s.client, i) for i in range(N_STREAMS // 2))
        )
        await asyncio.sleep(0.2)
        second = asyncio.gather(
            *(_one(s.client, i) for i in range(N_STREAMS // 2, N_STREAMS))
        )
        rows = (await first) + (await second)
        wall = time.perf_counter() - t0
        ok = [r for r in rows if r["ok"]]
        ttfts = [r["ttft"] for r in rows if r.get("ttft") is not None]
        return {
            "arm": name,
            "offered": N_STREAMS,
            "completed": len(ok),
            "failed": N_STREAMS - len(ok),
            "wall_s": round(wall, 2),
            "goodput_tok_s": round(sum(r["tokens"] for r in ok) / wall, 1),
            "p99_ttft_ms": round(pctile(ttfts, 0.99) * 1000, 1) if ttfts else None,
        }


async def main() -> None:
    dev = {"DEVICE": os.environ["DEVICE"]} if os.environ.get("DEVICE") else {}
    guarded = {
        "FAULT_SPEC": FAULT_SPEC,
        "DISPATCH_TIMEOUT_S": TIMEOUT_S,
        "DISPATCH_RETRIES": "2",
        "DISPATCH_BACKOFF_S": "0.02",
        "ENGINE_RESTARTS_MAX": "8",
        "SUPERVISE": "1",
    }
    bare = {
        "FAULT_SPEC": FAULT_SPEC,
        "DISPATCH_TIMEOUT_S": "0",
        "DISPATCH_RETRIES": "0",
        "SUPERVISE": "0",
    }
    rows = [
        await run_arm("clean", {}, dev),
        await run_arm("supervised", guarded, dev),
        await run_arm("unsupervised", bare, dev),
    ]

    import jax

    backend = jax.default_backend()
    print("\n| arm | completed | goodput tok/s | p99 TTFT (ms) | wall (s) |",
          file=sys.stderr)
    print("|---|---|---|---|---|", file=sys.stderr)
    for r in rows:
        print(
            f"| {r['arm']} | {r['completed']}/{r['offered']} "
            f"| {r['goodput_tok_s']} | {r['p99_ttft_ms']} | {r['wall_s']} |",
            file=sys.stderr,
        )
        print(json.dumps({**r, "fault_spec": FAULT_SPEC, "backend": backend}))


if __name__ == "__main__":
    asyncio.run(main())
