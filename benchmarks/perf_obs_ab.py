"""Perf-observatory overhead A/B (r20 acceptance pin).

Interleaved passes of the SAME gpt2 streaming workload with the
always-on attribution layer ON (PERF_OBS=1, the default) vs OFF —
alternating arm order per pass so box weather lands on both arms
equally (the r11 interleaving methodology).  The claim under test:
the zero-sync estimator's overhead stays within the box-noise
envelope (r11 measured ±10–19% between *identical-code* passes on
this 1-vCPU box; TRACE=1 attribution mode costs 8–15% — the thing
this layer exists to avoid).

Also asserts the structural pin directly: both arms issue identical
chunk-dispatch counts (the layer adds zero device syncs).

    PERFOBS_AB=0 skips it in run_all.py.
"""

from __future__ import annotations

import asyncio
import json
import os
import statistics
import sys

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(_here))
from harness import ServiceUnderTest  # noqa: E402

PASSES = int(os.environ.get("PERFOBS_AB_PASSES", "3"))
N_STREAMS = int(os.environ.get("PERFOBS_AB_STREAMS", "6"))

# Greedy-only workload: skip the sampled warm variants (halves the
# seq2seq warm grid per service instance; the in-process
# ExecutableCache then makes every arm past the first warm-fast).
os.environ.setdefault("WARMUP_SAMPLING", "0")


async def run_arm(perf_obs: bool) -> dict:
    overrides = {
        "MODEL_NAME": "gpt2",
        "BATCH_BUCKETS": "1,8",
        "SEQ_BUCKETS": "64",
        "MAX_DECODE_LEN": "32",
        "PERF_OBS": "1" if perf_obs else "0",
    }
    if os.environ.get("DEVICE"):
        overrides["DEVICE"] = os.environ["DEVICE"]
    async with ServiceUnderTest(overrides) as s:
        r = await s.stream_stats(
            "the quick brown fox jumps over the lazy dog and", n=N_STREAMS
        )
        cdl = getattr(s.batcher, "_cdl", None)
        snap = (
            s.engine.perf.snapshot()
            if getattr(s.engine, "perf", None) is not None else {}
        )
        return {
            **r,
            "chunk_dispatches": getattr(cdl, "chunk_dispatches", 0),
            "tokens": getattr(cdl, "tokens_emitted", 0),
            "busy_ratio": snap.get("busy_ratio"),
            "mfu_epoch": snap.get("mfu_epoch"),
            "pending": snap.get("pending_dispatches"),
        }


async def main() -> None:
    on_rates, off_rates = [], []
    on_last = off_last = None
    for p in range(PASSES):
        order = [(True,), (False,)] if p % 2 == 0 else [(False,), (True,)]
        for (flag,) in order:
            r = await run_arm(flag)
            (on_rates if flag else off_rates).append(r["decode_steps_s"])
            if flag:
                on_last = r
            else:
                off_last = r
    on_med = statistics.median(on_rates)
    off_med = statistics.median(off_rates)
    delta = (on_med - off_med) / off_med if off_med else 0.0
    structural_identical = (
        on_last["chunk_dispatches"] == off_last["chunk_dispatches"]
        and on_last["tokens"] == off_last["tokens"]
    )
    out = {
        "ab": "perf_obs_overhead",
        "passes": PASSES,
        "on_decode_steps_s": on_rates,
        "off_decode_steps_s": off_rates,
        "on_median": round(on_med, 3),
        "off_median": round(off_med, 3),
        "overhead_frac": round(delta, 4),
        "chunk_dispatches_identical": structural_identical,
        "on_busy_ratio": on_last.get("busy_ratio"),
        "on_pending_after": on_last.get("pending"),
    }
    print(json.dumps(out))
    if not structural_identical:
        print(
            "STRUCTURAL PIN FAILED: PERF_OBS changed dispatch counts",
            file=sys.stderr,
        )
        sys.exit(1)


if __name__ == "__main__":
    asyncio.run(main())
