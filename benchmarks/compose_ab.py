"""Composed decode levers A/B (round-6 tentpole): PREFIX_CACHE ×
SPEC_CONTINUOUS × QUANT_KV stacked in ONE deployment vs each single
lever, on the north-star workload — long-context chat/summarization
with shared prompt prefixes served at widths 1–8.

Before round 6 the registry forced operators to pick exactly one of
{per-request prefix cache, continuous speculation, int8 KV + fused
Pallas decode}; this measures whether the now-composable stack earns
its keep: aggregate tokens/s through the continuous-batching loop for
five configs —

  base     continuous batching only (int8 weights, like all rows)
  prefix   + PREFIX_CACHE=1        (suffix-only prefill on hits)
  spec     + SPEC_CONTINUOUS=1     (draft→verify rounds in the loop)
  kv8      + QUANT_KV=int8         (int8 KV; Pallas decode on TPU)
  stacked  all three at once

over shared prefixes of 512/768 tokens (COMPOSE_PREFIXES), distinct
per-stream suffixes, widths 1/2/4/8 (COMPOSE_WIDTHS), decode budget
128 (COMPOSE_DECODE) on repetition-heavy traffic (the quoting regime
speculation targets; prefix caches are seeded by one solo request
before the clock starts, so measured admissions HIT).  Per cell the
summary records stacked vs the best single lever — honest negatives
stay in the table.

    python benchmarks/compose_ab.py               # TPU, llama-1.1B int8
    DEVICE=cpu python benchmarks/compose_ab.py    # tiny-dims sanity run
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

DEVICE = os.environ.get("DEVICE", "tpu")
CPU_SANITY = DEVICE == "cpu" and "LLAMA_CONFIG" not in os.environ
if CPU_SANITY:
    # A 1.1B llama on a CPU host is not a benchmark, it is a hang:
    # shrink to tiny dims + short prefixes so the HARNESS stays
    # exercisable anywhere.  Numbers from this mode are labeled and
    # must never be quoted as performance.
    os.environ["LLAMA_CONFIG"] = json.dumps(dict(
        vocab_size=512, d_model=64, num_heads=4, num_kv_heads=2,
        num_layers=2, d_ff=128, max_position=512,
    ))

_dflt = "32" if CPU_SANITY else "512,768"
PREFIXES = tuple(
    int(x) for x in os.environ.get("COMPOSE_PREFIXES", _dflt).split(",")
)
WIDTHS = tuple(
    int(x) for x in os.environ.get("COMPOSE_WIDTHS", "1,2,4,8").split(",")
)
DECODE = int(os.environ.get("COMPOSE_DECODE", "32" if CPU_SANITY else "128"))
CHUNK = int(os.environ.get("COMPOSE_CHUNK", "8" if CPU_SANITY else "16"))
SUFFIX_LEN = int(os.environ.get("COMPOSE_SUFFIX", "12" if CPU_SANITY else "48"))
SUFFIX_BUCKET = int(
    os.environ.get("COMPOSE_SUFFIX_BUCKET", "16" if CPU_SANITY else "64")
)
SPEC_K = int(os.environ.get("SPEC_K", "8"))

CONFIGS: dict[str, dict] = {
    "base": {},
    "prefix": {"prefix_cache": True},
    "spec": {"spec_decode": "ngram", "spec_continuous": True,
             "spec_k": SPEC_K},
    "kv8": {"quant_kv": "int8"},
    "stacked": {"prefix_cache": True, "quant_kv": "int8",
                "spec_decode": "ngram", "spec_continuous": True,
                "spec_k": SPEC_K},
}


def build_engine(levers: dict, p_len: int):
    from mlmicroservicetemplate_tpu.engine import InferenceEngine
    from mlmicroservicetemplate_tpu.models.registry import build_model
    from mlmicroservicetemplate_tpu.parallel import ReplicaSet, make_mesh
    from mlmicroservicetemplate_tpu.utils.config import ServiceConfig

    cfg = ServiceConfig(
        device=DEVICE,
        model_name="llama",
        quantize=(os.environ.get("QUANTIZE", "int8") or None),
        warmup=False,
        batch_buckets=(1,),
        # Suffix bucket for hit prefills, the prefix bucket itself, and
        # the full-prompt bucket for misses; the prefix guard needs
        # p_len + suffix bucket <= the max bucket, satisfied exactly.
        seq_buckets=(SUFFIX_BUCKET, p_len, p_len + SUFFIX_BUCKET),
        max_decode_len=DECODE,
        stream_chunk_tokens=CHUNK,
        max_streams=max(WIDTHS),
        **levers,
    )
    bundle = build_model(cfg)
    eng = InferenceEngine(bundle, cfg, ReplicaSet(make_mesh(1)))
    return eng, cfg, bundle


def make_prompts(p_len: int, n: int, vocab: int, seed: int = 0):
    """Shared repetition-heavy prefix + distinct suffixes that continue
    the pattern (the quoting regime: prompt-lookup drafts land)."""
    rng = np.random.default_rng(seed)
    pat = rng.integers(5, vocab - 1, 16).astype(np.int32)
    prefix = np.tile(pat, p_len // pat.size + 1)[:p_len]
    prompts = []
    for i in range(n):
        suf = np.tile(pat, SUFFIX_LEN // pat.size + 1)[:SUFFIX_LEN].copy()
        suf[:4] = rng.integers(5, vocab - 1, 4)  # distinct per stream
        prompts.append(np.concatenate([prefix, suf]))
    seed_suf = np.tile(pat, SUFFIX_LEN // pat.size + 1)[:SUFFIX_LEN].copy()
    seed_suf[:4] = rng.integers(5, vocab - 1, 4)
    return np.concatenate([prefix, seed_suf]), prompts


def feats(ids: np.ndarray) -> dict:
    return {"input_ids": ids, "length": np.int32(ids.size)}


def measure(cdl, prompts: list[np.ndarray], n: int) -> dict:
    """Aggregate tokens/s for ``n`` concurrent streams through the
    continuous loop (streams_scaling's measurement, prefix-aware)."""

    async def consume(gen):
        toks = 0
        async for chunk in gen:
            toks += int(np.asarray(chunk).size)
        return toks

    async def body():
        gens = [cdl.submit_stream(feats(prompts[i])) for i in range(n)]
        return await asyncio.gather(*[consume(g) for g in gens])

    pre_chunks = cdl.chunk_dispatches
    pre_fills = cdl.prefill_dispatches
    t0 = time.perf_counter()
    counts = asyncio.run(body())
    wall = time.perf_counter() - t0
    # This bench reuses ONE loop across widths but runs each width
    # under its own short-lived asyncio.run loop, which can close
    # before the thread-safe admission-release callbacks land (a
    # long-lived server loop never does).  Wait for the drain, then
    # reset the counter to the drained truth so later widths aren't
    # shed by leaked admissions.
    deadline = time.monotonic() + 30
    while (cdl.active or cdl.queue.qsize() > 0) and time.monotonic() < deadline:
        time.sleep(0.01)
    cdl._admitted = 0
    return {
        "tokens": int(sum(counts)),
        "wall_s": round(wall, 3),
        "tok_s": round(sum(counts) / wall, 1),
        "chunk_dispatches": cdl.chunk_dispatches - pre_chunks,
        "prefill_dispatches": cdl.prefill_dispatches - pre_fills,
    }


def run_config(name: str, levers: dict, p_len: int) -> dict:
    from mlmicroservicetemplate_tpu.engine.streams import ContinuousDecodeLoop

    eng, cfg, bundle = build_engine(levers, p_len)
    vocab = int(bundle.cfg.vocab_size)
    seed_prompt, prompts = make_prompts(p_len, max(WIDTHS), vocab)
    # Seed the prefix cache off the clock (one solo request donates at
    # bucket p_len), and warm the solo path's executables for every
    # config so no cell pays a first-compile.
    for _ in eng.generate_stream(feats(seed_prompt)):
        pass
    if eng.prefix_cache is not None:
        assert eng.prefix_cache.stats()["entries"] >= 1, "seeding failed"
    cdl = ContinuousDecodeLoop(eng, cfg)
    cdl.warm()
    cells = {}
    for n in WIDTHS:
        cells[f"w{n}"] = measure(cdl, prompts, n)
    hits = eng.prefix_cache.stats() if eng.prefix_cache is not None else None
    cdl.stop()
    out = {"config": name, "prefix": p_len, **{
        k: v["tok_s"] for k, v in cells.items()
    }, "cells": cells}
    if hits is not None:
        out["prefix_cache"] = {k: hits[k] for k in ("hits", "misses", "entries")}
    return out


def main() -> None:
    from mlmicroservicetemplate_tpu.runtime.device import apply_device_env

    apply_device_env(DEVICE)
    import jax

    rows = []
    for p_len in PREFIXES:
        per_cfg = {}
        for name, levers in CONFIGS.items():
            row = run_config(name, levers, p_len)
            per_cfg[name] = row
            rows.append(row)
            print(json.dumps(row), flush=True)
        # Per-cell verdict: stacked vs the best single lever (honest
        # negatives print as ratios < 1).
        verdict = {"prefix": p_len}
        for n in WIDTHS:
            k = f"w{n}"
            singles = {c: per_cfg[c][k] for c in ("base", "prefix", "spec", "kv8")}
            best = max(singles, key=singles.get)
            stacked = per_cfg["stacked"][k]
            verdict[k] = {
                "stacked_tok_s": stacked,
                "best_single": best,
                "best_single_tok_s": singles[best],
                "stacked_vs_best": round(
                    stacked / max(singles[best], 1e-9), 3
                ),
            }
        rows.append({"verdict": verdict})
        print(json.dumps({"verdict": verdict}), flush=True)
    print(json.dumps({
        "bench": "compose_ab",
        "model": "llama",
        "weights": os.environ.get("QUANTIZE", "int8") or "bf16",
        "decode": DECODE, "chunk": CHUNK, "suffix": SUFFIX_LEN,
        "widths": list(WIDTHS), "prefixes": list(PREFIXES),
        "backend": jax.default_backend(),
        "cpu_sanity": CPU_SANITY,
        "rows": rows,
    }))


if __name__ == "__main__":
    main()
