"""KV-occupancy A/B: paged vs contiguous admission at a FIXED KV budget.

The judged claim (ISSUE 3): with ``PAGED_KV=1`` at fixed
``KV_BUDGET_MB``, a mixed-length streaming workload runs MORE streams
concurrently than the contiguous layout — because the contiguous
ledger charges every stream its prompt bucket + the FULL server decode
budget for its whole lifetime, while the paged ledger charges prompt
blocks + one chunk and grows block-by-block, freeing on EOS.

Two arms over the same gpt2 service (random-init weights — occupancy
and throughput depend on shapes, not weights):

- **contig**: ``PAGED_KV=0`` + ``KV_BUDGET_MB`` (the round-7 ceiling
  ledger gates dequeue).
- **paged**: ``PAGED_KV=1`` + the same budget (exact block ledger).

N streams with mixed prompt lengths and small per-request max_tokens
arrive at once and wait in a deep stream queue; the KV ledger is the
only thing gating how many decode concurrently.  Reported per arm:
peak concurrent streams (max overlap of [first-token, done]
intervals), total wall time, aggregate tokens/s, sheds.

    python benchmarks/kv_occupancy_ab.py              # current backend
    DEVICE=cpu python benchmarks/kv_occupancy_ab.py   # CPU sanity run

One JSON line per arm to stdout, a markdown table to stderr.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(_here))
from harness import ServiceUnderTest  # noqa: E402

N_STREAMS = int(os.environ.get("KV_AB_N", "12"))
BUDGET_MB = float(os.environ.get("KV_AB_BUDGET_MB", "16"))
# Mixed lengths: mostly short chats, some longer prompts — the shape
# where worst-case reservations waste the most budget.  Lengths are
# CHARACTER counts (the byte-fallback tokenizer is 1 token/char) and
# all fit the largest seq bucket so every stream rides the continuous
# loop, where both ledgers bind.
PROMPTS = [
    ("short", "the quick fox", 4),
    ("short", "a tiny prompt", 6),
    ("medium", "a medium prompt in the larger bucket....", 8),
    ("long", "a longer prompt that fills most of the big seq bucket :)", 16),
]


async def _one(client, i: int):
    kind, text, max_tokens = PROMPTS[i % len(PROMPTS)]
    t0 = time.perf_counter()
    try:
        resp = await client.post(
            "/predict",
            json={"text": text, "stream": True, "max_tokens": max_tokens},
        )
        if resp.status != 200:
            await resp.read()
            return {"kind": kind, "status": resp.status}
        ttft = None
        n_tok = 0
        async for line in resp.content:
            if ttft is None:
                ttft = time.perf_counter() - t0
            row = json.loads(line)
            if row.get("done"):
                n_tok = int(row.get("tokens_generated", 0))
                break
        return {
            "kind": kind, "status": 200, "t_first": t0 + (ttft or 0.0),
            "t_end": time.perf_counter(), "tokens": n_tok,
        }
    except Exception:
        return {"kind": kind, "status": -1}


def _peak_overlap(rows: list[dict]) -> int:
    events = []
    for r in rows:
        if r.get("status") == 200 and "t_first" in r:
            events.append((r["t_first"], 1))
            events.append((r["t_end"], -1))
    events.sort()
    peak = cur = 0
    for _, d in events:
        cur += d
        peak = max(peak, cur)
    return peak


async def run_arm(paged: bool, dev: dict) -> dict:
    overrides = {
        "MODEL_NAME": "gpt2",
        "BATCH_BUCKETS": "1,4",
        "SEQ_BUCKETS": "32,64",
        "MAX_DECODE_LEN": "32",
        "MAX_STREAMS": "8",
        "MAX_STREAM_QUEUE": "16",
        "KV_BUDGET_MB": str(BUDGET_MB),
        "PAGED_KV": "1" if paged else "0",
        "KV_BLOCK_SIZE": "16",
        **dev,
    }
    async with ServiceUnderTest(overrides) as s:
        t0 = time.perf_counter()
        rows = await asyncio.gather(
            *(_one(s.client, i) for i in range(N_STREAMS))
        )
        wall = time.perf_counter() - t0
        served = [r for r in rows if r.get("status") == 200]
        toks = sum(r.get("tokens", 0) for r in served)
        return {
            "arm": "paged" if paged else "contig",
            "budget_mb": BUDGET_MB,
            "offered": N_STREAMS,
            "served": len(served),
            "peak_concurrent": _peak_overlap(rows),
            "wall_s": round(wall, 2),
            "tokens_per_s": round(toks / wall, 1),
            "shed": sum(1 for r in rows if r.get("status") not in (200,)),
        }


async def main() -> None:
    dev = {"DEVICE": os.environ["DEVICE"]} if os.environ.get("DEVICE") else {}
    rows = [await run_arm(False, dev), await run_arm(True, dev)]

    import jax

    backend = jax.default_backend()
    print("\n| arm | served | peak concurrent | wall (s) | tokens/s "
          "| shed |", file=sys.stderr)
    print("|---|---|---|---|---|---|", file=sys.stderr)
    for r in rows:
        print(
            f"| {r['arm']} | {r['served']}/{r['offered']} "
            f"| {r['peak_concurrent']} | {r['wall_s']} "
            f"| {r['tokens_per_s']} | {r['shed']} |",
            file=sys.stderr,
        )
        print(json.dumps({**r, "backend": backend}))


if __name__ == "__main__":
    asyncio.run(main())
