"""Overload A/B: the SLA scheduler vs FIFO at 1×/2×/4× offered load.

Two arms over the SAME service (t5-small streaming through the
continuous-batching loop, bounded stream wait queue):

- **fifo**: no scheduling headers — every request is default-class with
  no deadline, i.e. the seed's behavior (FIFO queue, shed at the bound).
- **sched**: a 50/50 interactive/batch mix where interactive requests
  carry ``X-Priority: interactive`` + ``X-Deadline-Ms``; batch requests
  ride ``X-Priority: batch``.  The deadline queue serves interactive
  first (class-weighted EDF), sheds stale waiters as fast 504s before
  dispatch, and preempts batch-class slot holders for interactive
  arrivals.

Reported per (load, arm): interactive goodput (completions that
finished INSIDE the deadline, per second), p99 TTFT over served
interactive requests, and shed counts (503/504).  The judged claim
(ISSUE 2): at 2× load, interactive goodput under ``sched`` ≥ ``fifo``,
and every deadline miss is shed as a 504 BEFORE dispatch rather than
served stale.

    python benchmarks/overload_ab.py               # current backend
    DEVICE=cpu python benchmarks/overload_ab.py    # CPU sanity run

One JSON line per row to stdout, a markdown table to stderr.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(_here))
from harness import ServiceUnderTest, pctile  # noqa: E402

PROMPT = "summarize: the quick brown fox jumps over the lazy dog again"
LOADS = (1.0, 2.0, 4.0)
N_PER_ARM = int(os.environ.get("OVERLOAD_N", "48"))

# Load shapes (round-8 satellite): round 7's single shape (12-deep
# queue, 2.5× deadline, deadlines on interactive only) always hit the
# queue BOUND before any waiter aged out, so its 504 column was
# structurally zero — and class-weighted dequeue serves interactive
# fast enough that a loose deadline never lapses in the queue.  The
# "deep" shape — deeper queue, ~solo-tight deadline, deadlines on
# BOTH classes, overload only — lets waiters age out INSIDE the
# queue, exercising the fast-504 path in the table (not just in unit
# tests).  Fields: (name, queue depth, deadline factor, deadline on
# both classes, loads).  OVERLOAD_SHAPES filters.
SHAPES = (
    ("base", "12", 2.5, False, LOADS),
    ("deep", "24", 1.2, True, (4.0,)),
)


async def _one(client, i: int, sched: bool, deadline_ms: float,
               deadline_all: bool = False):
    """One streamed request; returns (klass, status, ttft_s, wall_s)."""
    klass = "interactive" if i % 2 == 0 else "batch"
    headers = {}
    if sched:
        headers["X-Priority"] = klass
        if klass == "interactive" or deadline_all:
            headers["X-Deadline-Ms"] = str(int(deadline_ms))
    t0 = time.perf_counter()
    try:
        resp = await client.post(
            "/predict", json={"text": PROMPT, "stream": True},
            headers=headers,
        )
        if resp.status != 200:
            await resp.read()
            return klass, resp.status, None, None
        ttft = None
        async for line in resp.content:
            if ttft is None:
                ttft = time.perf_counter() - t0
            if json.loads(line).get("done"):
                break
        return klass, 200, ttft, time.perf_counter() - t0
    except Exception:
        return klass, -1, None, None


async def run_arm(s, sched: bool, rate_sps: float, deadline_ms: float,
                  deadline_all: bool = False):
    """Offered load at ``rate_sps`` arrivals/s, 50/50 class mix.
    Returns raw per-arm tallies; cells aggregate across repeats."""
    tasks = []
    interval = 1.0 / rate_sps
    t0 = time.perf_counter()
    for i in range(N_PER_ARM):
        tasks.append(asyncio.create_task(
            _one(s.client, i, sched, deadline_ms, deadline_all)
        ))
        await asyncio.sleep(interval)
    results = await asyncio.gather(*tasks)
    wall = time.perf_counter() - t0  # makespan: arrivals + drain tail
    inter = [r for r in results if r[0] == "interactive"]
    served = [r for r in inter if r[1] == 200]
    good = [r for r in served if r[3] is not None and r[3] * 1e3 <= deadline_ms]
    return {
        "arm": "sched" if sched else "fifo",
        "offered": len(inter),
        "good": len(good),
        "wall": wall,
        "ttfts": [r[2] for r in served if r[2] is not None],
        "shed_503": sum(1 for r in results if r[1] == 503),
        "shed_504": sum(1 for r in results if r[1] == 504),
    }


async def run_shape(shape: str, queue_depth: str, deadline_factor: float,
                    deadline_all: bool, loads, dev: dict,
                    rows: list) -> None:
    overrides = {
        "MODEL_NAME": "t5-small",
        "BATCH_BUCKETS": "1,4",
        # The prompt byte-tokenizes to 61 tokens: the max seq bucket
        # must COVER it or every stream silently routes to the legacy
        # per-stream path, where the deadline queue, priorities and
        # preemption never bind (round 7 ran with SEQ_BUCKETS=32 and
        # measured exactly that — recorded in BASELINE.md r8).
        "SEQ_BUCKETS": "32,64",
        "MAX_DECODE_LEN": "8",
        # Narrow slot pool + deep wait queue: time spent waiting lands
        # in the SCHEDULABLE queue (where EDF/priorities/expiry bind)
        # instead of as in-slot compute sharing the scheduler can't
        # reorder — that is also the right shape for a compute-bound
        # backend (slots beyond the parallelism the chip actually has
        # only dilute every stream's cadence).
        "MAX_STREAMS": "2",
        "MAX_STREAM_QUEUE": queue_depth,
        "CLASS_WEIGHT": "4",
        **dev,
    }
    async with ServiceUnderTest(overrides) as s:
        # Capacity calibration: how fast the slot pool ACTUALLY drains
        # a full concurrent wave (on a shared-core CPU host the slots
        # contend, so solo-latency × slots would overestimate badly).
        # First probe discarded: it may still pay one-time lazy costs.
        await _one(s.client, 0, False, 1e9)
        lat = []
        for _ in range(3):
            _, _, _, wall = await _one(s.client, 0, False, 1e9)
            if wall:
                lat.append(wall)
        solo_s = sorted(lat)[len(lat) // 2]
        t0 = time.perf_counter()
        waves = 3
        for _ in range(waves):
            await asyncio.gather(
                *(_one(s.client, 0, False, 1e9) for _ in range(2))
            )
        capacity_sps = waves * 2 / (time.perf_counter() - t0)
        # Deadline budget: a promptly-served request fits comfortably
        # (the base shape's 2.5× a solo run); one that waited out an
        # overloaded FIFO queue does not — that's the SLA the
        # scheduler defends.  The "deep" shape tightens the factor so
        # deep-queued waiters age out IN the queue (the 504 path).
        deadline_ms = max(deadline_factor * solo_s * 1e3, 200.0)
        # Repeats with arm-order alternation: on a shared-core host the
        # run-to-run variance rivals the effect size, so each (load,
        # arm) cell aggregates across repeats and neither arm always
        # runs on a freshly-drained pool.
        repeats = int(os.environ.get("OVERLOAD_REPEATS", "2"))
        cells: dict = {}
        for rep in range(repeats):
            for mult in loads:
                arm_order = (False, True) if rep % 2 == 0 else (True, False)
                for sched in arm_order:
                    r = await run_arm(
                        s, sched, capacity_sps * mult, deadline_ms,
                        deadline_all,
                    )
                    c = cells.setdefault((mult, r["arm"]), {
                        "offered": 0, "good": 0, "wall": 0.0,
                        "ttfts": [], "shed_503": 0, "shed_504": 0,
                    })
                    for k in ("offered", "good", "shed_503", "shed_504"):
                        c[k] += r[k]
                    c["wall"] += r["wall"]
                    c["ttfts"].extend(r["ttfts"])
                    await asyncio.sleep(1.0)  # drain the slot pool
        for (mult, arm), c in sorted(cells.items()):
            rows.append({
                "shape": shape,
                "load_x": mult,
                "arm": arm,
                "interactive_offered": c["offered"],
                "interactive_in_deadline": c["good"],
                "interactive_goodput_rps": round(c["good"] / c["wall"], 3),
                "ttft_p99_ms": (
                    round(pctile(c["ttfts"], 0.99) * 1000, 1)
                    if c["ttfts"] else None
                ),
                "shed_503": c["shed_503"],
                "shed_504": c["shed_504"],
                "solo_ms": round(solo_s * 1e3, 1),
                "deadline_ms": round(deadline_ms, 1),
            })


async def main() -> None:
    dev = {"DEVICE": os.environ["DEVICE"]} if os.environ.get("DEVICE") else {}
    want = tuple(
        s.strip()
        for s in os.environ.get("OVERLOAD_SHAPES", "base,deep").split(",")
        if s.strip()
    )
    rows: list = []
    for shape, queue_depth, factor, deadline_all, loads in SHAPES:
        if shape in want:
            await run_shape(
                shape, queue_depth, factor, deadline_all, loads, dev, rows
            )

    import jax

    backend = jax.default_backend()
    print("\n| shape | load | arm | goodput (rps) | in-deadline "
          "| ttft p99 (ms) | 503 | 504 |", file=sys.stderr)
    print("|---|---|---|---|---|---|---|---|", file=sys.stderr)
    for r in rows:
        print(
            f"| {r['shape']} | {r['load_x']}x | {r['arm']} "
            f"| {r['interactive_goodput_rps']} "
            f"| {r['interactive_in_deadline']}/{r['interactive_offered']} "
            f"| {r['ttft_p99_ms']} | {r['shed_503']} | {r['shed_504']} |",
            file=sys.stderr,
        )
        print(json.dumps({**r, "backend": backend}))


if __name__ == "__main__":
    asyncio.run(main())
