"""Run the full BASELINE.md §6 benchmark table (all five configs).

    python benchmarks/run_all.py              # current backend (tpu)
    DEVICE=cpu python benchmarks/run_all.py   # CPU sanity run

Writes one JSON line per config to stdout and a markdown table to
stderr.  ``bench.py`` at the repo root stays the driver-facing headline
(config 3); this harness is the complete judged surface:

  1. ResNet-50 single-image /predict       -> p50/p99
  2. BERT-base text /predict, batch=1      -> p50/p99
  3. ResNet-50 dynamic batching, max_batch -> req/s/chip
  4. BERT-base replica serving             -> req/s over all devices
  5. T5-small streaming seq2seq            -> TTFT, chunks/s
  6. gpt2 streaming causal-LM              -> TTFT, chunks/s
"""

from __future__ import annotations

import asyncio
import json
import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(_here))  # repo root, for the package
from harness import ServiceUnderTest, png_bytes, post_image, post_text  # noqa: E402
from perf_ledger import append_row, structural_counters  # noqa: E402


def _ledger(config: str, s: ServiceUnderTest) -> None:
    """One structural-counter row per measured config (r20 satellite:
    the perf-regression ledger, PERF_LEDGER.jsonl — counters, not
    wall-clock, so the longitudinal diff is CPU-noise-immune)."""
    cdl = getattr(s.batcher, "_cdl", None) if s.batcher is not None else None
    append_row(config, structural_counters(s.engine, cdl))


async def main() -> None:
    rows = []
    dev = {"DEVICE": os.environ["DEVICE"]} if os.environ.get("DEVICE") else {}
    png = png_bytes()

    async with ServiceUnderTest(
        {"MODEL_NAME": "resnet50", "BATCH_BUCKETS": "1,8,32", **dev}
    ) as s:
        r1 = await s.latency(post_image(png))
        rows.append({"config": "resnet50 single-image latency", **r1})
        r3 = await s.throughput(post_image(png))
        rows.append({"config": "resnet50 dynamic batching max_batch=32", **r3})
        _ledger("resnet50 dynamic batching", s)

    async with ServiceUnderTest(
        {"MODEL_NAME": "bert-base", "BATCH_BUCKETS": "1,8,32", "SEQ_BUCKETS": "32,128", **dev}
    ) as s:
        r2 = await s.latency(post_text("a short benchmark sentence"))
        rows.append({"config": "bert-base batch=1 latency", **r2})
        n_dev = s.engine.replicas.n_devices
        r4 = await s.throughput(post_text("a short benchmark sentence"))
        rows.append(
            {"config": f"bert-base replica serving ({n_dev} device)", **r4}
        )
        _ledger("bert-base replica serving", s)

    async with ServiceUnderTest(
        {
            "MODEL_NAME": "t5-small",
            "BATCH_BUCKETS": "1,8",
            "SEQ_BUCKETS": "32,64",
            "MAX_DECODE_LEN": "32",
            **dev,
        }
    ) as s:
        r5 = await s.stream_stats("summarize: the quick brown fox jumps over the lazy dog")
        rows.append({"config": "t5-small streaming seq2seq", **r5})
        _ledger("t5-small streaming", s)

    async with ServiceUnderTest(
        {
            "MODEL_NAME": "gpt2",
            "BATCH_BUCKETS": "1,8",
            "SEQ_BUCKETS": "64",
            "MAX_DECODE_LEN": "32",
            **dev,
        }
    ) as s:
        r6 = await s.stream_stats("the quick brown fox jumps over the lazy dog and")
        rows.append({"config": "gpt2 streaming causal-LM", **r6})
        _ledger("gpt2 streaming", s)

    # The flagship generative config: llama at TinyLlama-1.1B dims,
    # int8 weights (the measured recommendation at this scale).
    async with ServiceUnderTest(
        {
            "MODEL_NAME": "llama",
            "QUANTIZE": "int8",
            "BATCH_BUCKETS": "1,8",
            "SEQ_BUCKETS": "64",
            "MAX_DECODE_LEN": "32",
            **dev,
        }
    ) as s:
        r7 = await s.stream_stats("the quick brown fox jumps over the lazy dog and")
        rows.append({"config": "llama-1.1B int8 streaming causal-LM", **r7})
        _ledger("llama int8 streaming", s)

    import jax

    backend = jax.default_backend()
    print(f"\n| config | metrics | backend |", file=sys.stderr)
    print("|---|---|---|", file=sys.stderr)
    for row in rows:
        metrics = ", ".join(f"{k}={v}" for k, v in row.items() if k != "config")
        print(f"| {row['config']} | {metrics} | {backend} |", file=sys.stderr)
        print(json.dumps({**row, "backend": backend}))

    # Composed decode levers (round-6 tentpole): the stacked
    # PREFIX_CACHE × SPEC_CONTINUOUS × QUANT_KV llama deployment vs
    # each single lever, in a subprocess so its five engine builds
    # can't disturb the table above.  COMPOSE_AB=0 skips.
    import subprocess

    if os.environ.get("COMPOSE_AB", "1").lower() not in ("0", "false", "no"):
        subprocess.run(
            [sys.executable, os.path.join(_here, "compose_ab.py")],
            check=False,
        )

    # SLA scheduler under overload (round-7 tentpole): interactive
    # goodput + p99 TTFT at 1×/2×/4× offered load, FIFO baseline vs
    # priority/deadline headers.  OVERLOAD_AB=0 skips.
    if os.environ.get("OVERLOAD_AB", "1").lower() not in ("0", "false", "no"):
        subprocess.run(
            [sys.executable, os.path.join(_here, "overload_ab.py")],
            check=False,
        )

    # Paged-KV occupancy (round-8 tentpole): max concurrent streams +
    # decode throughput at fixed KV_BUDGET_MB, exact block ledger vs
    # the contiguous ceiling.  KV_AB=0 skips.
    if os.environ.get("KV_AB", "1").lower() not in ("0", "false", "no"):
        subprocess.run(
            [sys.executable, os.path.join(_here, "kv_occupancy_ab.py")],
            check=False,
        )

    # Fault recovery (round-9 tentpole): goodput + p99 TTFT under an
    # injected fault schedule, supervised (watchdog + checkpoint/
    # rebuild/resume) vs the unsupervised seed behavior.  FAULT_AB=0
    # skips.
    if os.environ.get("FAULT_AB", "1").lower() not in ("0", "false", "no"):
        subprocess.run(
            [sys.executable, os.path.join(_here, "fault_recovery_ab.py")],
            check=False,
        )

    # Chunked prefill (round-10 tentpole): decode TBT p99 under the
    # long-prompt interference shape, monolithic seed vs a
    # PREFILL_CHUNK sweep.  PREFILL_AB=0 skips.
    if os.environ.get("PREFILL_AB", "1").lower() not in ("0", "false", "no"):
        subprocess.run(
            [sys.executable, os.path.join(_here, "prefill_interference_ab.py")],
            check=False,
        )

    # Fused decode windows (round-12 tentpole): host syncs per token,
    # tokens/s and decode TBT p99 vs DECODE_WINDOW ∈ {1, 2, 4, 8},
    # plus the interactive-lane TBT guard under the auto governor.
    # FUSION_AB=0 skips.
    if os.environ.get("FUSION_AB", "1").lower() not in ("0", "false", "no"):
        subprocess.run(
            [sys.executable, os.path.join(_here, "decode_fusion_ab.py")],
            check=False,
        )

    # Tiered KV (round-14 tentpole): resume latency + goodput under
    # memory pressure, host-RAM swap vs the recompute checkpoint path.
    # TIER_AB=0 skips.
    if os.environ.get("TIER_AB", "1").lower() not in ("0", "false", "no"):
        subprocess.run(
            [sys.executable, os.path.join(_here, "kv_tier_ab.py")],
            check=False,
        )

    # Durable serving (round-15 tentpole): SIGKILL-mid-traffic recovery
    # ledger (journal vs none) + journal fsync-policy overhead.
    # CRASH_AB=0 skips.
    if os.environ.get("CRASH_AB", "1").lower() not in ("0", "false", "no"):
        subprocess.run(
            [sys.executable, os.path.join(_here, "crash_resume_ab.py")],
            check=False,
        )

    # Bulk jobs (round-16 tentpole): interactive p99 TTFT with a
    # /v1/batches job backfilling idle compute vs interactive-only,
    # plus the bulk tokens/s reclaimed.  JOBS_AB=0 skips.
    if os.environ.get("JOBS_AB", "1").lower() not in ("0", "false", "no"):
        subprocess.run(
            [sys.executable, os.path.join(_here, "bulk_jobs_ab.py")],
            check=False,
        )

    # Replica fleet (round-13 tentpole): goodput + p99 TTFT through a
    # deterministic replica kill and recovery, FLEET_REPLICAS=2 with
    # token-identical failover vs the single-replica blast radius.
    # FLEET_AB=0 skips.
    if os.environ.get("FLEET_AB", "1").lower() not in ("0", "false", "no"):
        subprocess.run(
            [sys.executable, os.path.join(_here, "replica_failover_ab.py")],
            check=False,
        )

    # Perf observatory (round-20 tentpole): overhead of the always-on
    # zero-sync attribution layer vs PERF_OBS=0, interleaved, plus the
    # structural dispatch-count pin.  PERFOBS_AB=0 skips.
    if os.environ.get("PERFOBS_AB", "1").lower() not in ("0", "false", "no"):
        subprocess.run(
            [sys.executable, os.path.join(_here, "perf_obs_ab.py")],
            check=False,
        )

    # Pallas kernels (round-21 tentpole): the paged-decode autotuner's
    # tuned-vs-default sweep (dense + int8; interpret-mode on CPU) plus
    # the r1 fused-attention A/B on TPU — appends its own structural
    # ledger row (winner variant, speedups, autotuner counters).
    # PALLAS_AB=0 skips.
    if os.environ.get("PALLAS_AB", "1").lower() not in ("0", "false", "no"):
        subprocess.run(
            [sys.executable, os.path.join(_here, "pallas_ab.py")],
            check=False,
        )

    # Elastic autoscaling (round-17 tentpole): goodput + shed rate +
    # scale-event latency under a burst→lull→burst arrival curve,
    # static R=1 vs elastic [1..3] (donor-broadcast scale-up,
    # drain-based scale-down).  SCALE_AB=0 skips.
    if os.environ.get("SCALE_AB", "1").lower() not in ("0", "false", "no"):
        subprocess.run(
            [sys.executable, os.path.join(_here, "autoscale_ab.py")],
            check=False,
        )

    # Device loss (round-24 tentpole): goodput + streams-lost ledger
    # through a lost chip mid-decode, fleet-with-spare TP groups
    # (FLEET_TP_GROUPS=2,2, r1-scoped device_lost) vs a single TP
    # group (every stream dies with the group).  DEVLOSS_AB=0 skips.
    if os.environ.get("DEVLOSS_AB", "1").lower() not in ("0", "false", "no"):
        subprocess.run(
            [sys.executable, os.path.join(_here, "device_loss_ab.py")],
            check=False,
        )

    # Tenant fairness (round-22 tentpole): light-tenant TTFT p99 under
    # a heavy-tenant backlog, weighted fair-share dequeue (TENANTS set)
    # vs the plain class-weighted EDF queue.  TENANT_AB=0 skips.
    if os.environ.get("TENANT_AB", "1").lower() not in ("0", "false", "no"):
        subprocess.run(
            [sys.executable, os.path.join(_here, "tenant_fairness_ab.py")],
            check=False,
        )

    # Tensor-parallel decode scaling (round-23 tentpole): TP∈{1,2} ×
    # {dense,int8-KV} decode-step time through the production TP
    # placement path (docs/tensor-parallel.md).  On CPU the virtual
    # devices share one core — record the honest negative; the
    # throughput claim is the relay-TPU run's.  TP_AB=0 skips.
    if os.environ.get("TP_AB", "1").lower() not in ("0", "false", "no"):
        subprocess.run(
            [sys.executable, os.path.join(_here, "tp_scaling_ab.py")],
            check=False,
        )


if __name__ == "__main__":
    asyncio.run(main())
