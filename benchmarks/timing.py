"""Shared device-time measurement: the two-scan-length method.

Wall time of K on-device iterations inside ONE executable is
``K x device_time + RTT``.  Timing scans of K and 2K iterations and
differencing makes the per-dispatch round-trip cancel EXACTLY —
instead of subtracting a separately-sampled RTT that jitters ±10 ms
through the relay (the round-2 verdict's weak #1 against
pallas_ab.py's old method).

Every scan body carries a scalar data dependency into the next
iteration (input + carry*0 — numerically a no-op XLA must still
honor), so the loop cannot be collapsed or hoisted.
"""

from __future__ import annotations

import time

REPS = 5


def device_time_per_call(fn, args, carry_idx: int = -1, iters: int = 8,
                         reps: int = REPS):
    """Median device-seconds per ``fn(*args)`` call.

    Returns (per_call_s, noisy): ``noisy`` means the 2K scan measured
    no slower than the K scan (relay jitter swamped the signal) and the
    value fell back to wall_K / K — an UPPER bound, flagged so tables
    can say so.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    def make(n: int):
        def scan_k(*xs):
            def body(carry, _):
                xs2 = list(xs)
                xs2[carry_idx] = xs2[carry_idx] + (carry * 0).astype(
                    xs2[carry_idx].dtype
                )
                out = fn(*xs2)
                return out.astype(jnp.float32).ravel()[0], ()

            carry, _ = lax.scan(body, jnp.float32(0), None, length=n)
            return carry

        return jax.jit(scan_k)

    s1, s2 = make(iters), make(2 * iters)
    dev = jax.device_put(tuple(args))
    float(jax.device_get(s1(*dev)))  # compile
    float(jax.device_get(s2(*dev)))

    def med(f) -> float:
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            float(jax.device_get(f(*dev)))
            ts.append(time.perf_counter() - t0)
        return sorted(ts)[len(ts) // 2]

    w1, w2 = med(s1), med(s2)
    noisy = w2 <= w1
    per = (max(w1, 1e-9) / iters) if noisy else (w2 - w1) / iters
    return per, noisy


def chunked_time_per_step(jit_chunk, params, state, iters: int | None = None,
                          reps: int = REPS):
    """Per-decode-step device seconds for a generate_chunk-style
    executable (``jit_chunk(params, state, n_steps) -> (state, toks)``,
    n_steps static).  Same differencing idea: the chunk IS the scan, so
    time n_steps=K vs 2K calls and difference.

    The state is NOT threaded between timed calls (each call re-decodes
    from the same state — steady-state work per step, no drift in shapes
    or content), so ``jit_chunk`` must not donate its state argument.

    iters defaults to CHUNK_ITERS (64): per-step times are fractions of
    a millisecond, so short chunks drown in relay jitter — K must be
    large enough that K x step_time clears ±10 ms.  Steps past the
    decode budget are harmless (token/cache writes are mode="drop").
    """
    import os

    import jax

    if iters is None:
        iters = int(os.environ.get("CHUNK_ITERS", "64"))

    def wall(n: int) -> float:
        jax.device_get(jit_chunk(params, state, n)[1])  # compile
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.device_get(jit_chunk(params, state, n)[1])
            ts.append(time.perf_counter() - t0)
        return sorted(ts)[len(ts) // 2]

    w1, w2 = wall(iters), wall(2 * iters)
    noisy = w2 <= w1
    per = (max(w1, 1e-9) / iters) if noisy else (w2 - w1) / iters
    return per, noisy
