"""Concurrent-streams scaling: continuous batching vs per-stream decode.

The round-2 judged gap: N concurrent generative streams each held a
dedicated worker running batch=1 chunk dispatches — N× the dispatches
ONE batched loop needs.  This measures exactly that A/B on the serving
engine (no HTTP noise): aggregate tokens/s and device dispatches at
concurrency {1, 2, 4, 8} for the same prompt set, legacy
(engine.generate_stream per stream) vs continuous
(engine/streams.ContinuousDecodeLoop shared batch).

On a relay-attached TPU every dispatch costs a fixed ~100 ms RTT, so
dispatch count ~= wall time and the shared loop's aggregate tokens/s
should scale ~linearly with concurrency while legacy stays ~flat
(its streams contend for the same dispatch pipeline).

    python benchmarks/streams_scaling.py            # TPU (default)
    DEVICE=cpu python benchmarks/streams_scaling.py # CPU sanity run
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MODEL = os.environ.get("MODEL_NAME", "gpt2")
# BENCH_PROMPT picks the traffic shape: the default is generic English
# (the spec_continuous column's honest base case); a repetition-heavy
# prompt (e.g. "a b c a b c ...") measures the quoting regime the
# speculative loop targets.
PROMPT = os.environ.get(
    "BENCH_PROMPT",
    "the quick brown fox jumps over the lazy dog and keeps going",
)
DECODE = int(os.environ.get("BENCH_DECODE_LEN", "32"))
CHUNK = int(os.environ.get("BENCH_CHUNK", "8"))
LEVELS = (1, 2, 4, 8)


def _build(device: str, spec: bool = False):
    from mlmicroservicetemplate_tpu.engine import InferenceEngine
    from mlmicroservicetemplate_tpu.models.registry import build_model
    from mlmicroservicetemplate_tpu.parallel import ReplicaSet, make_mesh
    from mlmicroservicetemplate_tpu.utils.config import ServiceConfig

    cfg = ServiceConfig(
        device=device, model_name=MODEL, warmup=False,
        batch_buckets=(1,), seq_buckets=(64,),
        max_decode_len=DECODE, stream_chunk_tokens=CHUNK, max_streams=max(LEVELS),
        quantize=os.environ.get("QUANTIZE") or None,
        **(
            {"spec_decode": "ngram", "spec_continuous": True,
             "spec_k": int(os.environ.get("SPEC_K", "8"))}
            if spec else {}
        ),
    )
    bundle = build_model(cfg)
    eng = InferenceEngine(bundle, cfg, ReplicaSet(make_mesh(1)))
    feats = bundle.preprocess(_raw_item(bundle))
    return eng, cfg, feats


def _raw_item(bundle):
    from mlmicroservicetemplate_tpu.models.registry import RawItem

    return RawItem(text=PROMPT)


def _legacy(eng, feats, n: int) -> dict:
    """n dedicated threads, each a full batch=1 chunked generation."""
    counts = [0] * n

    def run(i):
        toks = 0
        for chunk in eng.generate_stream(dict(feats)):
            toks += int(chunk.size)
        counts[i] = toks

    threads = [threading.Thread(target=run, args=(i,)) for i in range(n)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    total = sum(counts)
    # Every stream pays its own dispatch sequence: 1 start + chunks.
    dispatches = n * (1 + (DECODE // CHUNK - 1))
    return {"tokens": total, "wall_s": round(wall, 3),
            "tok_s": round(total / wall, 1), "dispatches_max": dispatches}


def _continuous(eng, cfg, feats, n: int) -> dict:
    from mlmicroservicetemplate_tpu.engine.streams import ContinuousDecodeLoop

    cdl = ContinuousDecodeLoop(eng, cfg)
    cdl.warm()

    async def consume(gen):
        toks = 0
        async for chunk in gen:
            toks += int(chunk.size)
        return toks

    async def body():
        gens = [cdl.submit_stream(dict(feats)) for _ in range(n)]
        return await asyncio.gather(*[consume(g) for g in gens])

    t0 = time.perf_counter()
    counts = asyncio.run(body())
    wall = time.perf_counter() - t0
    stats = {
        "tokens": sum(counts), "wall_s": round(wall, 3),
        "tok_s": round(sum(counts) / wall, 1),
        "prefill_dispatches": cdl.prefill_dispatches,
        "chunk_dispatches": cdl.chunk_dispatches,
    }
    cdl.stop()
    return stats


def _admission_stall(eng, cfg, feats, overlap: bool) -> dict:
    """Inter-chunk gaps of LIVE streams while a late wave joins — the
    number that exposes admission head-of-line blocking (round-3
    verdict missing #2).  4 streams run; after their second chunk, 4
    more are admitted; gaps on the live streams are recorded
    throughout.  ``overlap`` toggles ADMIT_OVERLAP (the fix vs the
    round-3 blocking order)."""
    from mlmicroservicetemplate_tpu.engine.streams import ContinuousDecodeLoop

    os.environ["ADMIT_OVERLAP"] = "1" if overlap else "0"
    cdl = ContinuousDecodeLoop(eng, cfg)
    cdl.warm()
    gaps: list[float] = []
    flowing = None  # set inside body (needs the running loop)

    async def consume_live(gen):
        last = None
        n = 0
        async for chunk in gen:
            now = time.perf_counter()
            if last is not None:
                gaps.append(now - last)
            last = now
            n += 1
            if n == 2:
                flowing.set()

    async def consume(gen):
        async for _ in gen:
            pass

    async def body():
        nonlocal flowing
        flowing = asyncio.Event()
        live = [cdl.submit_stream(dict(feats)) for _ in range(4)]
        tasks = [asyncio.create_task(consume_live(g)) for g in live]
        await flowing.wait()
        late = [cdl.submit_stream(dict(feats)) for _ in range(4)]
        tasks += [asyncio.create_task(consume(g)) for g in late]
        await asyncio.gather(*tasks)

    asyncio.run(body())
    cdl.stop()
    gaps.sort()
    n = len(gaps)
    return {
        "overlap": overlap,
        "gaps": n,
        "p50_ms": round(gaps[n // 2] * 1e3, 1) if n else None,
        "p99_ms": round(gaps[min(n - 1, int(n * 0.99))] * 1e3, 1) if n else None,
        "max_ms": round(gaps[-1] * 1e3, 1) if n else None,
    }


def main() -> None:
    device = os.environ.get("DEVICE", "tpu")
    from mlmicroservicetemplate_tpu.runtime.device import apply_device_env

    apply_device_env(device)
    eng, cfg, feats = _build(device)
    # Warm both paths' executables off the clock.
    for _ in eng.generate_stream(dict(feats)):
        pass
    # Third column: SPEC_CONTINUOUS (draft→verify rounds inside the
    # shared chunk) — the VERDICT-r4 question is whether it holds >= the
    # plain loop at every width.  BENCH_SPEC=0 skips it.
    spec_on = os.environ.get("BENCH_SPEC", "1").lower() not in (
        "0", "false", "no"
    )
    eng_s = cfg_s = None
    if spec_on:
        try:
            eng_s, cfg_s, _ = _build(device, spec=True)
        except Exception as e:
            print(json.dumps({"spec_continuous_skipped": str(e)}), flush=True)
            spec_on = False

    rows = []
    for n in LEVELS:
        legacy = _legacy(eng, feats, n)
        cont = _continuous(eng, cfg, feats, n)
        row = {
            "streams": n,
            "legacy": legacy,
            "continuous": cont,
            "speedup": round(cont["tok_s"] / max(legacy["tok_s"], 1e-9), 2),
        }
        if spec_on:
            spec = _continuous(eng_s, cfg_s, feats, n)
            row["spec_continuous"] = spec
            row["spec_vs_continuous"] = round(
                spec["tok_s"] / max(cont["tok_s"], 1e-9), 2
            )
        rows.append(row)
        print(json.dumps(rows[-1]), flush=True)
    # Live-stream inter-token latency during admission, fix off vs on.
    stall = {
        "blocking": _admission_stall(eng, cfg, feats, overlap=False),
        "overlapped": _admission_stall(eng, cfg, feats, overlap=True),
    }
    print(json.dumps({"admission_stall": stall}), flush=True)
    print(json.dumps({
        "model": MODEL, "decode_len": DECODE, "chunk": CHUNK,
        "device": device, "rows": rows, "admission_stall": stall,
    }))


if __name__ == "__main__":
    main()
