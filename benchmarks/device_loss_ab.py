"""Device-loss A/B: goodput + streams-lost ledger through a lost chip,
fleet-with-spare TP groups vs a single TP group.

The judged claim (ISSUE 19): with the SAME deterministic device-loss
schedule (``chunk:device_lost@3`` — a runtime-shaped ``XlaRuntimeError``
naming a lost chip fires on the third chunk dispatch, mid-decode), a
multi-chip fleet with a spare TP group (``FLEET_TP_GROUPS=2,2``) fails
the dead group's streams over to the survivor and completes 100% of
them token-identically, while the single-group deployment loses every
live stream — losing a chip costs latency, not output, but ONLY when
there is somewhere to go.

Three arms over the same TP=2 gpt2 service (random-init weights —
device-loss economics depend on dispatch structure, not weights):

- **single-clean**: one TP=2 group, no faults (the ceiling).
- **single-loss**:  one TP=2 group, ``chunk:device_lost@3``.  A lost
                    chip cannot be rebuilt in place (on real hardware
                    the device stays gone; here ENGINE_RESTARTS_MAX=0
                    models that honestly on the virtual devices), so
                    the whole listener's streams die with the group.
- **fleet-spare**:  FLEET_REPLICAS=2 over ``FLEET_TP_GROUPS=2,2``,
                    the ``r1:``-scoped schedule: replica 1's group
                    dies the same death; its streams evacuate via
                    placement-agnostic checkpoints onto replica 0's
                    group, the lost chip is retired from the carve
                    pool, and ``/readyz`` names it.

N streams arrive in two waves; each reports TTFT, tokens and whether
it terminated cleanly (a mid-stream in-band ``error`` line counts as
failed).  Goodput = tokens delivered by error-free streams / wall.
The streams-lost ledger (``streams_lost_total`` /
``streams_recovered_total`` deltas per arm) rides along so the table
shows WHERE the failed arm's tokens went.

HONEST-NEGATIVE NOTE (BASELINE.md round 24): on CPU the 8 virtual
host devices share ONE core, so the fleet-spare arm's two TP groups
add dispatch + collective overhead with zero added FLOP throughput —
its goodput ceiling is BELOW single-clean by construction.  The CPU
run proves the recovery ledger (0 lost vs all lost); the capacity
claim belongs to a real multi-chip host.

    DEVICE=cpu python benchmarks/device_loss_ab.py
    DEVLOSS_AB=0 skips it in run_all.py.

One JSON line per arm to stdout, a markdown table to stderr.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(_here))

# Two TP=2 groups need >=4 devices; on the host platform force the
# virtual-device split before the first jax import (no-op on TPU).
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )

from harness import ServiceUnderTest, pctile  # noqa: E402

N_STREAMS = int(os.environ.get("DEVLOSS_AB_N", "8"))
LOSS_AT = os.environ.get("DEVLOSS_AB_AT", "3")

PROMPTS = [
    "the quick brown fox jumps",
    "pack my box with five dozen",
    "a longer prompt that spans a few more tokens than the others do",
    "short one",
]


async def _one(client, i: int):
    text = PROMPTS[i % len(PROMPTS)]
    t0 = time.perf_counter()
    try:
        resp = await client.post(
            "/predict",
            json={"text": text, "stream": True,
                  "max_tokens": 16 if i % 2 == 0 else 8},
        )
        if resp.status != 200:
            await resp.read()
            return {"ok": False, "status": resp.status, "tokens": 0}
        ttft = None
        n_tok = 0
        failed = False
        async for line in resp.content:
            if not line.strip():
                continue
            if ttft is None:
                ttft = time.perf_counter() - t0
            row = json.loads(line)
            if "error" in row:
                failed = True
                break
            if row.get("done"):
                n_tok = int(row.get("tokens_generated", 0))
                break
        return {"ok": not failed and n_tok > 0, "status": 200,
                "tokens": 0 if failed else n_tok, "ttft": ttft}
    except Exception:
        return {"ok": False, "status": -1, "tokens": 0}


async def _stream_ledger(client) -> dict:
    """Sum streams_lost_total / streams_recovered_total over all label
    children from one /metrics scrape (the prometheus registry is
    process-global across arms, so callers diff before/after)."""
    text = await (await client.get("/metrics")).text()
    out = {"lost": 0.0, "recovered": 0.0}
    for line in text.splitlines():
        if line.startswith("streams_lost_total{"):
            out["lost"] += float(line.rsplit(" ", 1)[1])
        elif line.startswith("streams_recovered_total{"):
            out["recovered"] += float(line.rsplit(" ", 1)[1])
    return out


async def run_arm(name: str, extra: dict, dev: dict) -> dict:
    overrides = {
        "MODEL_NAME": "gpt2",
        "TP": "2",
        "BATCH_BUCKETS": "1,4",
        "SEQ_BUCKETS": "64",
        "MAX_DECODE_LEN": "16",
        "MAX_STREAMS": "4",
        "MAX_STREAM_QUEUE": "16",
        "WARMUP_SAMPLING": "0",
        **extra,
        **dev,
    }
    async with ServiceUnderTest(overrides) as s:
        before = await _stream_ledger(s.client)
        t0 = time.perf_counter()
        first = asyncio.gather(
            *(_one(s.client, i) for i in range(N_STREAMS // 2))
        )
        await asyncio.sleep(0.2)
        second = asyncio.gather(
            *(_one(s.client, i) for i in range(N_STREAMS // 2, N_STREAMS))
        )
        rows = (await first) + (await second)
        wall = time.perf_counter() - t0
        after = await _stream_ledger(s.client)
        status = await (await s.client.get("/status")).json()
        fleet = status.get("fleet") or {}
        readyz = await s.client.get("/readyz")
        ok = [r for r in rows if r["ok"]]
        ttfts = [r["ttft"] for r in rows if r.get("ttft") is not None]
        return {
            "arm": name,
            "offered": N_STREAMS,
            "completed": len(ok),
            "failed": N_STREAMS - len(ok),
            "wall_s": round(wall, 2),
            "goodput_tok_s": round(sum(r["tokens"] for r in ok) / wall, 1),
            "p99_ttft_ms": round(pctile(ttfts, 0.99) * 1000, 1) if ttfts else None,
            "streams_lost": after["lost"] - before["lost"],
            "streams_recovered": after["recovered"] - before["recovered"],
            "failovers": fleet.get("failovers"),
            "lost_devices": fleet.get("lost_devices"),
            "readyz": readyz.status,
        }


async def main() -> None:
    dev = {"DEVICE": os.environ["DEVICE"]} if os.environ.get("DEVICE") else {}
    loss_single = {
        "FAULT_SPEC": f"chunk:device_lost@{LOSS_AT}",
        "ENGINE_RESTARTS_MAX": "0",
        "SUPERVISE": "1",
    }
    loss_fleet = {
        "FLEET_REPLICAS": "2",
        "FLEET_TP_GROUPS": "2,2",
        # Round-robin so the doomed replica 1 deterministically serves
        # streams: least-loaded + prefix affinity parks this small
        # repeated-prompt workload entirely on replica 0 and the
        # r1-scoped schedule would never fire.
        "FLEET_ROUTE": "rr",
        "FAULT_SPEC": f"r1:chunk:device_lost@{LOSS_AT}",
        "SUPERVISE": "1",
    }
    rows = [
        await run_arm("single-clean", {}, dev),
        await run_arm("single-loss", loss_single, dev),
        await run_arm("fleet-spare", loss_fleet, dev),
    ]

    import jax

    backend = jax.default_backend()
    print("\n| arm | completed | goodput tok/s | lost/recovered "
          "| p99 TTFT (ms) | readyz | wall (s) |", file=sys.stderr)
    print("|---|---|---|---|---|---|---|", file=sys.stderr)
    for r in rows:
        print(
            f"| {r['arm']} | {r['completed']}/{r['offered']} "
            f"| {r['goodput_tok_s']} "
            f"| {r['streams_lost']:.0f}/{r['streams_recovered']:.0f} "
            f"| {r['p99_ttft_ms']} | {r['readyz']} | {r['wall_s']} |",
            file=sys.stderr,
        )
        print(json.dumps({**r, "loss_at": LOSS_AT, "backend": backend}))


if __name__ == "__main__":
    asyncio.run(main())
