"""Shared serving-benchmark machinery: spin the real service in-process
(HTTP → batcher → engine → chip) and measure what the judge measures
(SURVEY.md §6): p50/p99 latency, req/s/chip, TTFT, tokens/s."""

from __future__ import annotations

import asyncio
import io
import json
import math
import statistics
import time


def png_bytes(size: int = 224, seed: int = 0) -> bytes:
    import numpy as np
    from PIL import Image

    rng = np.random.default_rng(seed)
    img = Image.fromarray(rng.integers(0, 255, (size, size, 3), dtype=np.uint8))
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    return buf.getvalue()


def pctile(xs: list[float], q: float) -> float:
    return sorted(xs)[max(0, math.ceil(len(xs) * q) - 1)]


class ServiceUnderTest:
    """Async context manager: a fully-started in-process service."""

    def __init__(self, overrides: dict):
        self.overrides = {"LOG_LEVEL": "WARNING", **overrides}
        self.client = None
        self.engine = None
        self.batcher = None

    async def __aenter__(self):
        from aiohttp.test_utils import TestClient, TestServer

        from mlmicroservicetemplate_tpu.serve import build_service

        cfg, bundle, engine, batcher, app = build_service(self.overrides)
        self.engine = engine
        self.batcher = batcher
        self.client = TestClient(TestServer(app))
        await self.client.start_server()
        for _ in range(2400):
            resp = await self.client.get("/readyz")
            if resp.status == 200:
                return self
            await asyncio.sleep(0.25)
        raise RuntimeError("service never became ready")

    async def __aexit__(self, *exc):
        await self.client.close()

    # ------------------------------------------------------------------
    async def latency(self, make_request, n: int = 40) -> dict:
        """Sequential single-request latencies (the p50 config)."""
        lats = []
        for _ in range(n):
            t0 = time.perf_counter()
            resp = await make_request(self.client)
            assert resp.status == 200, await resp.text()
            await resp.read()
            lats.append(time.perf_counter() - t0)
        return {
            "p50_ms": round(statistics.median(lats) * 1000, 2),
            "p99_ms": round(pctile(lats, 0.99) * 1000, 2),
        }

    async def throughput(
        self, make_request, n: int = 192, concurrency: int = 64
    ) -> dict:
        sem = asyncio.Semaphore(concurrency)

        async def one():
            async with sem:
                resp = await make_request(self.client)
                assert resp.status == 200
                await resp.read()

        t0 = time.perf_counter()
        await asyncio.gather(*(one() for _ in range(n)))
        wall = time.perf_counter() - t0
        return {"req_s": round(n / wall, 2)}

    async def stream_stats(self, text: str, n: int = 8) -> dict:
        """TTFT + tokens/s through the chunked ndjson stream."""
        ttfts, tok_rates = [], []
        for _ in range(n):
            t0 = time.perf_counter()
            resp = await self.client.post(
                "/predict", json={"text": text, "stream": True}
            )
            assert resp.status == 200
            first, tokens = None, 0
            async for line in resp.content:
                if first is None:
                    first = time.perf_counter() - t0
                msg = json.loads(line)
                if msg.get("done"):
                    # decode_steps measures device decode throughput even
                    # when random-init weights produce no visible text.
                    tokens = int(msg.get("decode_steps", 0))
                    break
            wall = time.perf_counter() - t0
            ttfts.append(first if first is not None else wall)
            tok_rates.append(tokens / wall if wall else 0.0)
        return {
            "ttft_p50_ms": round(statistics.median(ttfts) * 1000, 2),
            "decode_steps_s": round(statistics.median(tok_rates), 2),
        }


async def scrape_histogram(client, name: str) -> dict:
    """One Prometheus histogram family from a ``/metrics`` scrape,
    summed over label children: ``{"count": float, "sum": float,
    "buckets": {le: cumulative_count}}`` (le keys are floats,
    ``math.inf`` for ``+Inf``).  Scrape-before/scrape-after plus
    ``hist_delta`` isolates one measured section even though the
    prometheus registry is process-global across service instances."""
    resp = await client.get("/metrics")
    assert resp.status == 200, await resp.text()
    text = await resp.text()
    out = {"count": 0.0, "sum": 0.0, "buckets": {}}
    for line in text.splitlines():
        if not line.startswith(name) or line.startswith("#"):
            continue
        head, value = line.rsplit(" ", 1)
        value = float(value)
        if head.startswith(f"{name}_count"):
            out["count"] += value
        elif head.startswith(f"{name}_sum"):
            out["sum"] += value
        elif head.startswith(f"{name}_bucket"):
            labels = head.split("{", 1)[1].rstrip("}")
            le = next(
                kv.split("=", 1)[1].strip('"')
                for kv in labels.split(",") if kv.startswith("le=")
            )
            le = math.inf if le == "+Inf" else float(le)
            out["buckets"][le] = out["buckets"].get(le, 0.0) + value
    return out


def hist_delta(after: dict, before: dict) -> dict:
    """Histogram delta (after − before) in ``scrape_histogram`` form."""
    return {
        "count": after["count"] - before["count"],
        "sum": after["sum"] - before["sum"],
        "buckets": {
            le: c - before["buckets"].get(le, 0.0)
            for le, c in after["buckets"].items()
        },
    }


def hist_pctile(h: dict, q: float) -> float | None:
    """Percentile estimate from cumulative buckets (linear
    interpolation inside the landing bucket — the same arithmetic as
    PromQL ``histogram_quantile``).  None on an empty histogram; a
    percentile landing in the +Inf bucket reports that bucket's lower
    edge (the largest finite ``le``)."""
    total = h["count"]
    if total <= 0:
        return None
    target = q * total
    lo_edge, lo_count = 0.0, 0.0
    for le in sorted(h["buckets"]):
        c = h["buckets"][le]
        if c >= target:
            if math.isinf(le):
                return lo_edge
            span = c - lo_count
            frac = (target - lo_count) / span if span > 0 else 1.0
            return lo_edge + (le - lo_edge) * frac
        lo_edge, lo_count = (0.0 if math.isinf(le) else le), c
    return lo_edge


def post_image(png: bytes):
    def make(client):
        return client.post(
            "/predict", data=png, headers={"Content-Type": "image/png"}
        )

    return make


def post_text(text: str):
    def make(client):
        return client.post("/predict", json={"text": text})

    return make
