"""Pallas kernel A/B: device time with vs without / tuned vs default.

Round-1 verdict: the fused-attention kernel shipped with no measured
win.  This measures it with the two-scan-length method
(benchmarks/timing.py): scans of K and 2K forwards inside one
executable are differenced, so the per-dispatch relay round-trip
cancels exactly — the round-2 weak #1 (subtracting a
separately-sampled ±10 ms RTT) is gone, and REPS=5.

    python benchmarks/pallas_ab.py          # TPU; prints one JSON line

Configs measured: BERT-base (B=32, S=512) — the shape the verdict asked
for — and the T5-small encoder (B=8, S=512) now that the kernel takes
the rel-pos bias.

Round 21 adds the **paged decode autotuner A/B** (tuned vs default
variant of ``ops/paged_attention.paged_decode_attention``, dense and
int8 caches): ``ensure_tuned`` runs its verify-then-time sweep and the
per-variant timings + the winner's delta against the ``b1`` default
are recorded, along with the autotuner's decision counters — the
structural half rides the PERF_LEDGER via ``run_all.py``.  On a
non-TPU backend the fused sections are skipped (no CPU lowering) and
the paged sweep runs interpret-mode: timings are then *relative* CPU
numbers, honest only about kernel-vs-kernel structure, and the JSON
says so (``backend: cpu-interpret``).
"""

from __future__ import annotations

import json
import os

import numpy as np

SCAN_ITERS = int(os.environ.get("SCAN_ITERS", "8"))


def paged_decode_ab() -> dict:
    """Tuned-vs-default paged-decode sweep at a llama-shaped decode
    problem (GQA n_rep=2), dense and int8; returns the sweep detail
    plus the autotuner counters."""
    import jax

    from mlmicroservicetemplate_tpu.ops import autotune

    backend = jax.default_backend()
    interpret = backend != "tpu"
    if interpret:
        # CPU interpret mode: same kernel code path, toy shapes so the
        # sweep stays in seconds; numbers are structural, not absolute.
        shapes = dict(b=2, kvh=2, n_rep=2, d=16, block_size=8, t=8)
        dtype = "float32"
    else:
        shapes = dict(b=8, kvh=4, n_rep=2, d=64, block_size=16, t=32)
        dtype = "bfloat16"

    class _Bundle:
        name = "pallas_ab"

    out: dict = {
        "backend": "cpu-interpret" if interpret else backend,
        "shapes": dict(shapes, dtype=dtype),
    }
    autotune.clear()
    for quant, label in ((False, "dense"), (True, "int8")):
        winner = autotune.ensure_tuned(
            "paged_decode", _Bundle(), None, **shapes, dtype=dtype,
            quant=quant, interpret=interpret, table_path=None,
        )
        stats = autotune.stats()
        key = autotune.tune_key("paged_decode", **shapes, dtype=dtype,
                                quant=quant)
        sweep = stats["sweeps"].get(key, {})
        per = sweep.get("per_call_us", {})
        default_us = per.get("b1")
        tuned_us = per.get(winner)
        out[label] = {
            "variant": winner,
            "default_us": default_us,
            "tuned_us": tuned_us,
            "speedup": (
                round(default_us / tuned_us, 3)
                if default_us and tuned_us else None
            ),
            "noisy": sweep.get("noisy", False),
            "per_variant_us": per,
        }
    out["autotune"] = autotune.stats()["counts"]
    return out


def main() -> None:
    import jax
    import jax.numpy as jnp

    from timing import device_time_per_call
    from mlmicroservicetemplate_tpu.models import bert as bert_mod
    from mlmicroservicetemplate_tpu.models import t5 as t5_mod

    out: dict = {"scan_iters": SCAN_ITERS, "method": "two-scan-length (K vs 2K)"}

    # -- paged decode: tuned vs default variant (r21) -------------------
    if os.environ.get("PAGED_AB", "1").lower() not in ("0", "false", "no"):
        out["paged_decode"] = paged_decode_ab()
        try:
            from perf_ledger import append_row

            pd = out["paged_decode"]
            append_row("pallas_paged_ab", {
                "autotune": pd["autotune"],
                "paged_variant_dense": pd["dense"]["variant"],
                "paged_variant_int8": pd["int8"]["variant"],
                "paged_speedup_dense": pd["dense"]["speedup"],
                "paged_speedup_int8": pd["int8"]["speedup"],
            }, extra={"backend": pd["backend"]})
        except Exception as e:
            print(f"paged A/B ledger append failed: {e}")

    if jax.default_backend() != "tpu":
        # The fused-attention kernels have no CPU lowering; the paged
        # section above already ran interpret-mode.  Record the skip
        # honestly rather than crash or fake a number.
        out["fused_skipped"] = "backend!=tpu (no CPU lowering)"
        print(json.dumps(out))
        return

    # -- BERT-base, B=32, S=512 (the verdict's shape) -------------------
    b, s = 32, 512
    cfg = bert_mod.BertConfig()
    params = bert_mod.init_params(jax.random.PRNGKey(0), cfg=cfg)
    params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
    ids = np.ones((b, s), np.int32)
    mask_np = np.ones((b, s), np.int32)
    mask_np[:, s // 2 :] = 0  # realistic padding: half the keys masked
    mask = jnp.asarray(mask_np)

    for use_pallas, key in ((False, "bert_xla_ms"), (True, "bert_pallas_ms")):
        def fwd(p, m, i):
            return bert_mod.classify(p, cfg, i, m, dtype=jnp.bfloat16,
                                     use_pallas=use_pallas)

        dt, noisy = device_time_per_call(
            fwd, (params, mask, jnp.asarray(ids)), iters=SCAN_ITERS
        )
        out[key] = round(dt * 1000, 3)
        if noisy:
            out[key + "_noisy"] = True

    out["bert_speedup"] = round(out["bert_xla_ms"] / out["bert_pallas_ms"], 3)

    # -- T5-small encoder, B=8, S=512 (rel-pos bias path) ---------------
    b = 8
    tcfg = t5_mod.T5Config()
    tparams = t5_mod.init_params(jax.random.PRNGKey(1), tcfg)
    tparams = jax.tree.map(lambda x: x.astype(jnp.bfloat16), tparams)
    t_mask = jnp.asarray(np.ones((b, s), np.int32))
    t_ids = jnp.asarray(np.ones((b, s), np.int32))

    for use_pallas, key in ((False, "t5_enc_xla_ms"), (True, "t5_enc_pallas_ms")):
        def enc(p, m, i):
            return t5_mod.encode(p, tcfg, i, m, dtype=jnp.bfloat16,
                                 use_pallas=use_pallas)

        dt, noisy = device_time_per_call(
            enc, (tparams, t_mask, t_ids), iters=SCAN_ITERS
        )
        out[key] = round(dt * 1000, 3)
        if noisy:
            out[key + "_noisy"] = True

    out["t5_enc_speedup"] = round(out["t5_enc_xla_ms"] / out["t5_enc_pallas_ms"], 3)
    print(json.dumps(out))


if __name__ == "__main__":
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    main()
