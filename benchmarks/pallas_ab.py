"""Pallas fused-attention A/B: device time with vs without the kernel.

Round-1 verdict: the kernel shipped with no measured win.  This
measures it with the two-scan-length method (benchmarks/timing.py):
scans of K and 2K forwards inside one executable are differenced, so
the per-dispatch relay round-trip cancels exactly — the round-2 weak
#1 (subtracting a separately-sampled ±10 ms RTT) is gone, and REPS=5.

    python benchmarks/pallas_ab.py          # TPU; prints one JSON line

Configs measured: BERT-base (B=32, S=512) — the shape the verdict asked
for — and the T5-small encoder (B=8, S=512) now that the kernel takes
the rel-pos bias.
"""

from __future__ import annotations

import json
import os

import numpy as np

SCAN_ITERS = int(os.environ.get("SCAN_ITERS", "8"))


def main() -> None:
    import jax
    import jax.numpy as jnp

    from timing import device_time_per_call
    from mlmicroservicetemplate_tpu.models import bert as bert_mod
    from mlmicroservicetemplate_tpu.models import t5 as t5_mod

    out: dict = {"scan_iters": SCAN_ITERS, "method": "two-scan-length (K vs 2K)"}

    # -- BERT-base, B=32, S=512 (the verdict's shape) -------------------
    b, s = 32, 512
    cfg = bert_mod.BertConfig()
    params = bert_mod.init_params(jax.random.PRNGKey(0), cfg=cfg)
    params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
    ids = np.ones((b, s), np.int32)
    mask_np = np.ones((b, s), np.int32)
    mask_np[:, s // 2 :] = 0  # realistic padding: half the keys masked
    mask = jnp.asarray(mask_np)

    for use_pallas, key in ((False, "bert_xla_ms"), (True, "bert_pallas_ms")):
        def fwd(p, m, i):
            return bert_mod.classify(p, cfg, i, m, dtype=jnp.bfloat16,
                                     use_pallas=use_pallas)

        dt, noisy = device_time_per_call(
            fwd, (params, mask, jnp.asarray(ids)), iters=SCAN_ITERS
        )
        out[key] = round(dt * 1000, 3)
        if noisy:
            out[key + "_noisy"] = True

    out["bert_speedup"] = round(out["bert_xla_ms"] / out["bert_pallas_ms"], 3)

    # -- T5-small encoder, B=8, S=512 (rel-pos bias path) ---------------
    b = 8
    tcfg = t5_mod.T5Config()
    tparams = t5_mod.init_params(jax.random.PRNGKey(1), tcfg)
    tparams = jax.tree.map(lambda x: x.astype(jnp.bfloat16), tparams)
    t_mask = jnp.asarray(np.ones((b, s), np.int32))
    t_ids = jnp.asarray(np.ones((b, s), np.int32))

    for use_pallas, key in ((False, "t5_enc_xla_ms"), (True, "t5_enc_pallas_ms")):
        def enc(p, m, i):
            return t5_mod.encode(p, tcfg, i, m, dtype=jnp.bfloat16,
                                 use_pallas=use_pallas)

        dt, noisy = device_time_per_call(
            enc, (tparams, t_mask, t_ids), iters=SCAN_ITERS
        )
        out[key] = round(dt * 1000, 3)
        if noisy:
            out[key + "_noisy"] = True

    out["t5_enc_speedup"] = round(out["t5_enc_xla_ms"] / out["t5_enc_pallas_ms"], 3)
    print(json.dumps(out))


if __name__ == "__main__":
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    main()
