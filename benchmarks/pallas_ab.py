"""Pallas fused-attention A/B: device time with vs without the kernel.

Round-1 verdict: the kernel shipped with no measured win. This measures
it, isolated from the ~100 ms relay by scanning K forwards inside one
executable (same method as device_bench.py): wall = K x device_time +
1 RTT.

    python benchmarks/pallas_ab.py          # TPU; prints one JSON line

Configs measured: BERT-base (B=32, S=512) — the shape the verdict asked
for — and the T5-small encoder (B=8, S=512) now that the kernel takes
the rel-pos bias.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

SCAN_ITERS = int(os.environ.get("SCAN_ITERS", "8"))
REPS = 3


def _timed_scan(fn, args, rtt: float) -> float:
    """Median device-seconds per fn() call, via an in-executable scan."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def scan_k(*xs):
        def body(carry, _):
            out = fn(*xs[:-1], xs[-1] + (carry * 0).astype(xs[-1].dtype))
            return out.astype(jnp.float32).ravel()[0], ()

        carry, _ = lax.scan(body, jnp.float32(0), None, length=SCAN_ITERS)
        return carry

    jit = jax.jit(scan_k)
    dev_args = jax.device_put(args)
    float(jax.device_get(jit(*dev_args)))  # compile
    times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        float(jax.device_get(jit(*dev_args)))
        times.append(time.perf_counter() - t0)
    wall = sorted(times)[len(times) // 2]
    return max(wall - rtt, 1e-9) / SCAN_ITERS


def main() -> None:
    import jax
    import jax.numpy as jnp

    from device_bench import measure_rtt
    from mlmicroservicetemplate_tpu.models import bert as bert_mod
    from mlmicroservicetemplate_tpu.models import t5 as t5_mod

    rtt = measure_rtt()
    out: dict = {"rtt_ms": round(rtt * 1000, 1), "scan_iters": SCAN_ITERS}

    # -- BERT-base, B=32, S=512 (the verdict's shape) -------------------
    b, s = 32, 512
    cfg = bert_mod.BertConfig()
    params = bert_mod.init_params(jax.random.PRNGKey(0), cfg=cfg)
    params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
    ids = np.ones((b, s), np.int32)
    mask_np = np.ones((b, s), np.int32)
    mask_np[:, s // 2 :] = 0  # realistic padding: half the keys masked
    mask = jnp.asarray(mask_np)

    for use_pallas, key in ((False, "bert_xla_ms"), (True, "bert_pallas_ms")):
        def fwd(p, m, i):
            return bert_mod.classify(p, cfg, i, m, dtype=jnp.bfloat16,
                                     use_pallas=use_pallas)

        dt = _timed_scan(fwd, (params, mask, jnp.asarray(ids)), rtt)
        out[key] = round(dt * 1000, 3)

    out["bert_speedup"] = round(out["bert_xla_ms"] / out["bert_pallas_ms"], 3)

    # -- T5-small encoder, B=8, S=512 (rel-pos bias path) ---------------
    b = 8
    tcfg = t5_mod.T5Config()
    tparams = t5_mod.init_params(jax.random.PRNGKey(1), tcfg)
    tparams = jax.tree.map(lambda x: x.astype(jnp.bfloat16), tparams)
    t_mask = jnp.asarray(np.ones((b, s), np.int32))
    t_ids = jnp.asarray(np.ones((b, s), np.int32))

    for use_pallas, key in ((False, "t5_enc_xla_ms"), (True, "t5_enc_pallas_ms")):
        def enc(p, m, i):
            return t5_mod.encode(p, tcfg, i, m, dtype=jnp.bfloat16,
                                 use_pallas=use_pallas)

        dt = _timed_scan(enc, (tparams, t_mask, t_ids), rtt)
        out[key] = round(dt * 1000, 3)

    out["t5_enc_speedup"] = round(out["t5_enc_xla_ms"] / out["t5_enc_pallas_ms"], 3)
    print(json.dumps(out))


if __name__ == "__main__":
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    main()
