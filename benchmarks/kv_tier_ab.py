"""Tiered-KV A/B: host-RAM swap vs recompute under memory pressure.

The judged claim (ISSUE 9): when the paged pool runs dry and streams
checkpoint, a host tier (``KV_HOST_BUDGET_MB``) that swaps the resume
KV out and prefetches it back beats re-prefilling it from scratch —
fewer prefill dispatches, lower resume latency (the longest
inter-chunk gap a checkpointed stream's client observes), and better
goodput at the same device budget.

Two arms over the same gpt2 service (random-init weights — the swap
economics depend on shapes and schedule, not weights), both at a
deliberately tight ``KV_BUDGET_MB`` so decode growth forces dry-pool
checkpoints:

- **recompute**: ``KV_HOST_BUDGET_MB=0`` — today's checkpoint path
  (free the blocks, later re-prefill prompt+delivered).
- **swap**: ``KV_HOST_BUDGET_MB=64`` — blocks copy out to host RAM and
  prefetch back, zero re-prefill.

Reported per arm: total wall, aggregate delivered tokens/s (goodput),
TTFT p50, the p50/max of each stream's LONGEST inter-chunk gap (the
resume-latency proxy — an uninterrupted stream's gaps are one chunk's
compute; a checkpointed one's longest gap spans its requeue + resume),
prefill dispatches, and the server's swap/stall counters.

    python benchmarks/kv_tier_ab.py              # current backend
    DEVICE=cpu python benchmarks/kv_tier_ab.py   # CPU sanity run

One JSON line per arm to stdout, a markdown table to stderr.
"""

from __future__ import annotations

import asyncio
import json
import os
import statistics
import sys
import time

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(_here))
from harness import ServiceUnderTest, pctile  # noqa: E402

N_STREAMS = int(os.environ.get("TIER_AB_N", "6"))
# ~11 gpt2 KV blocks at KV_BLOCK_SIZE=16: two 64-bucket streams admit
# (5 blocks each) but cannot BOTH grow through decode (6 each) — the
# dry-pool checkpoint fires continuously under the queue's churn.
BUDGET_MB = float(os.environ.get("TIER_AB_BUDGET_MB", "13"))
HOST_MB = float(os.environ.get("TIER_AB_HOST_MB", "64"))
PROMPT = "the quick brown fox jumps over the lazy dog and then some more"

BASE_ENV = {
    "MODEL_NAME": "gpt2",
    "BATCH_BUCKETS": "1,4",
    "SEQ_BUCKETS": "64",
    # 32-token budgets make a stream's worst case 6 blocks vs its
    # 5-block initial: two streams admit into the 11-block pool but
    # cannot both grow — decode growth finds it dry and checkpoints.
    "MAX_DECODE_LEN": "32",
    "MAX_STREAMS": "4",
    "MAX_STREAM_QUEUE": "16",
    "PAGED_KV": "1",
    "KV_BLOCK_SIZE": "16",
    "KV_BUDGET_MB": str(BUDGET_MB),
    "WARMUP": "1",
}


async def _counter(client, name: str) -> float:
    """Sum a counter family's samples off one /metrics scrape."""
    text = await (await client.get("/metrics")).text()
    total = 0.0
    for line in text.splitlines():
        if line.startswith(name) and not line.startswith("#"):
            try:
                total += float(line.rsplit(" ", 1)[1])
            except ValueError:
                pass
    return total


async def _one_stream(client, i: int):
    t0 = time.perf_counter()
    resp = await client.post(
        "/predict", json={"text": PROMPT, "stream": True}
    )
    assert resp.status == 200, await resp.text()
    ttft, gaps, t_prev, steps = None, [], None, 0
    async for line in resp.content:
        now = time.perf_counter()
        if ttft is None:
            ttft = now - t0
        if t_prev is not None:
            gaps.append(now - t_prev)
        t_prev = now
        msg = json.loads(line)
        if msg.get("done"):
            steps = int(msg.get("decode_steps", 0))
            break
    return {
        "ttft": ttft if ttft is not None else time.perf_counter() - t0,
        "max_gap": max(gaps) if gaps else 0.0,
        "wall": time.perf_counter() - t0,
        "steps": steps,
    }


async def _arm(name: str, host_mb: float) -> dict:
    dev = {"DEVICE": os.environ["DEVICE"]} if os.environ.get("DEVICE") else {}
    env = {**BASE_ENV, "KV_HOST_BUDGET_MB": str(host_mb), **dev}
    async with ServiceUnderTest(env) as s:
        t0 = time.perf_counter()
        rows = await asyncio.gather(
            *(_one_stream(s.client, i) for i in range(N_STREAMS))
        )
        wall = time.perf_counter() - t0
        status = await (await s.client.get("/status")).json()
        tier = status.get("kv_tier") or {}
        prefills = (
            status.get("decode", {})
            .get("dispatch_counts", {})
            .get("prefill", 0)
        )
        tokens = sum(r["steps"] for r in rows)
        max_gaps = [r["max_gap"] for r in rows]
        stalls = await _counter(s.client, "kv_growth_stalls_total")
        return {
            "growth_stalls": int(stalls),
            "arm": name,
            "streams": N_STREAMS,
            "pool_blocks": status.get("scheduler", {}).get(
                "kv_budget_bytes", 0
            ),
            "wall_s": round(wall, 2),
            "goodput_tok_s": round(tokens / wall, 2) if wall else 0.0,
            "ttft_p50_ms": round(
                statistics.median([r["ttft"] for r in rows]) * 1e3, 1
            ),
            "resume_gap_p50_ms": round(
                statistics.median(max_gaps) * 1e3, 1
            ),
            "resume_gap_max_ms": round(pctile(max_gaps, 1.0) * 1e3, 1),
            "prefill_dispatches": prefills,
            "swap_resumes": tier.get("swap_resumes", 0),
            "swap_fallbacks": tier.get("swap_fallbacks", 0),
            "swap_out_bytes": tier.get("swap_out_bytes", 0),
            "prefetch_overlap_ratio": tier.get("prefetch_overlap_ratio"),
        }


async def main() -> None:
    rows = [
        await _arm("recompute", 0.0),
        await _arm("swap", HOST_MB),
    ]
    print("\n| arm | metrics |", file=sys.stderr)
    print("|---|---|", file=sys.stderr)
    for row in rows:
        metrics = ", ".join(
            f"{k}={v}" for k, v in row.items() if k != "arm"
        )
        print(f"| {row['arm']} | {metrics} |", file=sys.stderr)
        print(json.dumps(row))


if __name__ == "__main__":
    asyncio.run(main())
