"""PROMPT_PREFIX A/B: prefill cost with a cached system prompt vs
re-encoding it in every request.

Measures the fused prefill+first-chunk dispatch (the TTFT dispatch)
for a short user suffix under three configurations:
  a) no prefix        — suffix-only baseline (the floor)
  b) cached prefix    — PROMPT_PREFIX path: prefill sees only the suffix
  c) concat prompt    — the prefix tokens prepended to every request
                        (what you pay without the cache)

(b) should sit at (a)'s cost regardless of prefix length; (c) grows
with it.  Device time via the two-scan-length method (timing.py).

    PREFIX_TOKENS=256 python benchmarks/prefix_ab.py     # TPU
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

PREFIX_TOKENS = int(os.environ.get("PREFIX_TOKENS", "256"))
SUFFIX_TOKENS = int(os.environ.get("SUFFIX_TOKENS", "16"))
CHUNK = 4
DECODE = 16


def main() -> None:
    device = os.environ.get("DEVICE", "tpu")
    from mlmicroservicetemplate_tpu.runtime.device import apply_device_env

    apply_device_env(device)

    import jax

    from timing import device_time_per_call

    model = os.environ.get("MODEL_NAME", "gpt2")
    if model == "llama":
        from mlmicroservicetemplate_tpu.models import llama as gpt_mod

        cfg = gpt_mod.LlamaConfig()
    else:
        from mlmicroservicetemplate_tpu.models import gpt as gpt_mod

        cfg = gpt_mod.GPTConfig()
    params = gpt_mod.init_params(jax.random.PRNGKey(0), cfg)
    params = jax.tree.map(
        lambda x: x.astype("bfloat16") if x.dtype.kind == "f" else x, params
    )
    rng = np.random.default_rng(0)
    prefix_ids = rng.integers(3, cfg.vocab_size, PREFIX_TOKENS).astype(np.int32)

    cached = dict(params)
    cached["__prefix__"] = jax.jit(
        lambda p, ids: gpt_mod.compute_prefix_kv(p, cfg, ids, dtype="bfloat16")
    )(params, prefix_ids)

    def start(p, ids, mask):
        state = gpt_mod.init_decode_state(p, cfg, ids, mask, DECODE, dtype="bfloat16")
        _, toks = gpt_mod.generate_chunk(p, cfg, state, CHUNK)
        return toks

    def prefill_ms(p, n_tokens: int) -> tuple[float, bool]:
        ids = rng.integers(3, cfg.vocab_size, (1, n_tokens)).astype(np.int32)
        mask = np.ones((1, n_tokens), np.int32)
        dt, noisy = device_time_per_call(start, (p, ids, mask), carry_idx=1,
                                         iters=int(os.environ.get("PREFIX_SCAN_ITERS", "24")))
        return round(dt * 1000, 3), noisy

    a, a_noisy = prefill_ms(params, SUFFIX_TOKENS)
    b, b_noisy = prefill_ms(cached, SUFFIX_TOKENS)
    c, c_noisy = prefill_ms(params, PREFIX_TOKENS + SUFFIX_TOKENS)
    print(json.dumps({
        "model": model, "prefix_tokens": PREFIX_TOKENS,
        "suffix_tokens": SUFFIX_TOKENS, "device": device,
        "no_prefix_ms": a, "cached_prefix_ms": b, "concat_prompt_ms": c,
        "cached_vs_concat_speedup": round(c / b, 2),
        "noisy": {"a": a_noisy, "b": b_noisy, "c": c_noisy},
    }))


if __name__ == "__main__":
    main()
