"""Replica-DP scaling curve on the 8-way virtual CPU mesh.

Round-1 verdict: BASELINE.md row 4 labeled a 1-device number as the
multi-replica config. This script produces the honest curve: the same
bert-base engine at replicas {1, 2, 4, 8} on a virtual CPU mesh,
fixed total batch, engine-level dispatch (no HTTP noise).

IMPORTANT caveat, printed with the result: the 8 virtual devices share
this box's ONE physical vCPU, so wall-clock cannot speed up with
replica count. What the curve demonstrates is (a) the sharded path is
correct at every width and (b) the sharding/collective overhead XLA
adds per width — the multi-chip speedup claim rides on real ICI
hardware, which this environment does not have (SURVEY.md §7.1).

    python benchmarks/replica_scaling.py
"""

from __future__ import annotations

import json
import os
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()

import numpy as np  # noqa: E402

TOTAL_BATCH = 32
REPS = 6


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from mlmicroservicetemplate_tpu.engine import InferenceEngine
    from mlmicroservicetemplate_tpu.parallel import ReplicaSet, make_mesh
    from mlmicroservicetemplate_tpu.utils.config import ServiceConfig

    from mlmicroservicetemplate_tpu.models.registry import build_model

    bundle = build_model(
        ServiceConfig(device="cpu", model_name="bert-base", warmup=False)
    )
    rows = []
    feats = [
        {"input_ids": np.ones(64, np.int32), "length": np.int32(64)}
        for _ in range(TOTAL_BATCH)
    ]
    for r in (1, 2, 4, 8):
        cfg = ServiceConfig(
            device="cpu", warmup=False, batch_buckets=(TOTAL_BATCH,),
            seq_buckets=(64,), replicas=r,
        )
        eng = InferenceEngine(bundle, cfg, ReplicaSet(make_mesh(r)))
        eng.run_batch(list(feats))  # compile
        t0 = time.perf_counter()
        for _ in range(REPS):
            eng.run_batch(list(feats))
        wall = time.perf_counter() - t0
        rows.append(
            {"replicas": r, "req_s": round(REPS * TOTAL_BATCH / wall, 1),
             "batch_ms": round(wall / REPS * 1000, 1)}
        )
    base = rows[0]["req_s"]
    for row in rows:
        row["rel_vs_1"] = round(row["req_s"] / base, 3)
    print(json.dumps({
        "note": ("8 virtual devices share 1 physical vCPU: rel_vs_1 measures "
                 "sharding overhead, not speedup; ICI speedup needs real chips"),
        "total_batch": TOTAL_BATCH,
        "rows": rows,
    }))
    # Fleet streaming goodput vs FLEET_REPLICAS (the ISSUE-8 satellite:
    # aggregate goodput vs R under the overload_ab traffic shape —
    # FLEET_RS override, FLEET_SCALING=0 skips).  Same caveat: one
    # physical vCPU, so the curve demonstrates routing/ledger
    # correctness and per-replica overhead, not speedup.
    if os.environ.get("FLEET_SCALING", "1").lower() not in ("0", "false", "no"):
        fleet_goodput()


def fleet_goodput() -> None:
    """Aggregate streaming goodput (tok/s over completed streams) for
    FLEET_REPLICAS in FLEET_RS, bursty interactive-heavy traffic
    (overload_ab's shape: a wave of short prompts, mixed budgets)."""
    import asyncio
    import sys as _sys

    _here = os.path.dirname(os.path.abspath(__file__))
    _sys.path.insert(0, _here)
    from harness import ServiceUnderTest  # noqa: E402

    rs = [int(x) for x in os.environ.get("FLEET_RS", "1,2,4").split(",")]
    n_streams = int(os.environ.get("FLEET_SCALING_N", "8"))

    async def one(client, i):
        t0 = time.perf_counter()
        resp = await client.post(
            "/predict",
            json={"text": f"stream {i} the quick brown fox", "stream": True,
                  "max_tokens": 16 if i % 2 == 0 else 8},
        )
        if resp.status != 200:
            await resp.read()
            return 0, None
        n_tok = 0
        async for line in resp.content:
            if not line.strip():
                continue
            row = json.loads(line)
            if row.get("done"):
                n_tok = int(row.get("tokens_generated", 0))
                break
            if "error" in row:
                return 0, None
        return n_tok, time.perf_counter() - t0

    async def arm(r):
        async with ServiceUnderTest({
            "MODEL_NAME": "gpt2", "BATCH_BUCKETS": "1,4",
            "SEQ_BUCKETS": "64", "MAX_DECODE_LEN": "16",
            "MAX_STREAMS": "4", "MAX_STREAM_QUEUE": "16",
            # Each fleet replica owns a single-device placement —
            # engines must not share a sharded mesh (collective
            # interleaving; gated at fleet construction).
            "REPLICAS": "1",
            "FLEET_REPLICAS": str(r), "WARMUP_SAMPLING": "0",
            **({"DEVICE": os.environ["DEVICE"]}
               if os.environ.get("DEVICE") else {}),
        }) as s:
            t0 = time.perf_counter()
            out = await asyncio.gather(
                *(one(s.client, i) for i in range(n_streams))
            )
            wall = time.perf_counter() - t0
            toks = sum(t for t, _ in out)
            return {
                "fleet_replicas": r,
                "streams": n_streams,
                "completed": sum(1 for t, _ in out if t > 0),
                "goodput_tok_s": round(toks / wall, 1),
                "wall_s": round(wall, 2),
            }

    frows = [asyncio.run(arm(r)) for r in rs]
    print(json.dumps({
        "note": ("fleet goodput vs R on ONE physical vCPU: flat-to-down "
                 "is expected locally (replicas contend for the same "
                 "core); the curve pins correctness + per-replica "
                 "overhead, the speedup claim needs real chips"),
        "rows": frows,
    }))


if __name__ == "__main__":
    main()
