"""Replica-DP scaling curve on the 8-way virtual CPU mesh.

Round-1 verdict: BASELINE.md row 4 labeled a 1-device number as the
multi-replica config. This script produces the honest curve: the same
bert-base engine at replicas {1, 2, 4, 8} on a virtual CPU mesh,
fixed total batch, engine-level dispatch (no HTTP noise).

IMPORTANT caveat, printed with the result: the 8 virtual devices share
this box's ONE physical vCPU, so wall-clock cannot speed up with
replica count. What the curve demonstrates is (a) the sharded path is
correct at every width and (b) the sharding/collective overhead XLA
adds per width — the multi-chip speedup claim rides on real ICI
hardware, which this environment does not have (SURVEY.md §7.1).

    python benchmarks/replica_scaling.py
"""

from __future__ import annotations

import json
import os
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()

import numpy as np  # noqa: E402

TOTAL_BATCH = 32
REPS = 6


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from mlmicroservicetemplate_tpu.engine import InferenceEngine
    from mlmicroservicetemplate_tpu.parallel import ReplicaSet, make_mesh
    from mlmicroservicetemplate_tpu.utils.config import ServiceConfig

    from mlmicroservicetemplate_tpu.models.registry import build_model

    bundle = build_model(
        ServiceConfig(device="cpu", model_name="bert-base", warmup=False)
    )
    rows = []
    feats = [
        {"input_ids": np.ones(64, np.int32), "length": np.int32(64)}
        for _ in range(TOTAL_BATCH)
    ]
    for r in (1, 2, 4, 8):
        cfg = ServiceConfig(
            device="cpu", warmup=False, batch_buckets=(TOTAL_BATCH,),
            seq_buckets=(64,), replicas=r,
        )
        eng = InferenceEngine(bundle, cfg, ReplicaSet(make_mesh(r)))
        eng.run_batch(list(feats))  # compile
        t0 = time.perf_counter()
        for _ in range(REPS):
            eng.run_batch(list(feats))
        wall = time.perf_counter() - t0
        rows.append(
            {"replicas": r, "req_s": round(REPS * TOTAL_BATCH / wall, 1),
             "batch_ms": round(wall / REPS * 1000, 1)}
        )
    base = rows[0]["req_s"]
    for row in rows:
        row["rel_vs_1"] = round(row["req_s"] / base, 3)
    print(json.dumps({
        "note": ("8 virtual devices share 1 physical vCPU: rel_vs_1 measures "
                 "sharding overhead, not speedup; ICI speedup needs real chips"),
        "total_batch": TOTAL_BATCH,
        "rows": rows,
    }))


if __name__ == "__main__":
    main()
