"""Replica-failover A/B: goodput + p99 TTFT through a replica kill and
recovery, fleet vs single replica.

The judged claim (ISSUE 8): with the SAME deterministic replica-kill
schedule (``r0:chunk:fatal@3`` and a spent restart budget — replica 0
dies on its third chunk dispatch, mid-decode), a FLEET_REPLICAS=2
deployment fails the dead replica's streams over to the survivor and
completes 100% of them token-identically, while the single-replica
deployment error-terminates every live stream — a replica crash costs
latency, not output.

Three arms over the same gpt2 service (random-init weights — failover
economics depend on dispatch structure, not weights):

- **single-clean**: FLEET_REPLICAS=1, no faults (the ceiling).
- **single-kill**:  FLEET_REPLICAS=1, the kill schedule (unscoped —
                    there is only one engine), SUPERVISE on but
                    ENGINE_RESTARTS_MAX=0: the whole listener's
                    streams die with the loop.
- **fleet-kill**:   FLEET_REPLICAS=2, the r0-scoped kill schedule,
                    ENGINE_RESTARTS_MAX=0: replica 0 dies the same
                    death; its streams resume on replica 1.

N streams arrive in two waves; each reports TTFT, tokens and whether
it terminated cleanly (a mid-stream in-band ``error`` line counts as
failed).  Goodput = tokens delivered by error-free streams / wall.

    python benchmarks/replica_failover_ab.py              # current backend
    DEVICE=cpu python benchmarks/replica_failover_ab.py   # CPU sanity run

One JSON line per arm to stdout, a markdown table to stderr.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(_here))
from harness import ServiceUnderTest, pctile  # noqa: E402

N_STREAMS = int(os.environ.get("FLEET_AB_N", "8"))
KILL_AT = os.environ.get("FLEET_AB_KILL_AT", "3")

PROMPTS = [
    "the quick brown fox jumps",
    "pack my box with five dozen",
    "a longer prompt that spans a few more tokens than the others do",
    "short one",
]


async def _one(client, i: int):
    text = PROMPTS[i % len(PROMPTS)]
    t0 = time.perf_counter()
    try:
        resp = await client.post(
            "/predict",
            json={"text": text, "stream": True,
                  "max_tokens": 16 if i % 2 == 0 else 8},
        )
        if resp.status != 200:
            await resp.read()
            return {"ok": False, "status": resp.status, "tokens": 0}
        ttft = None
        n_tok = 0
        failed = False
        async for line in resp.content:
            if not line.strip():
                continue
            if ttft is None:
                ttft = time.perf_counter() - t0
            row = json.loads(line)
            if "error" in row:
                failed = True
                break
            if row.get("done"):
                n_tok = int(row.get("tokens_generated", 0))
                break
        return {"ok": not failed and n_tok > 0, "status": 200,
                "tokens": 0 if failed else n_tok, "ttft": ttft}
    except Exception:
        return {"ok": False, "status": -1, "tokens": 0}


async def run_arm(name: str, extra: dict, dev: dict) -> dict:
    overrides = {
        "MODEL_NAME": "gpt2",
        "BATCH_BUCKETS": "1,4",
        "SEQ_BUCKETS": "64",
        "MAX_DECODE_LEN": "16",
        "MAX_STREAMS": "4",
        "MAX_STREAM_QUEUE": "16",
        "WARMUP_SAMPLING": "0",
        # Single-device placement on every arm: fleet replicas each
        # own their engine (sharing a sharded mesh is gated), and the
        # single-replica arms must be placement-comparable.
        "REPLICAS": "1",
        **extra,
        **dev,
    }
    async with ServiceUnderTest(overrides) as s:
        t0 = time.perf_counter()
        first = asyncio.gather(
            *(_one(s.client, i) for i in range(N_STREAMS // 2))
        )
        await asyncio.sleep(0.2)
        second = asyncio.gather(
            *(_one(s.client, i) for i in range(N_STREAMS // 2, N_STREAMS))
        )
        rows = (await first) + (await second)
        wall = time.perf_counter() - t0
        # Fleet introspection: how many replicas survived, failovers.
        status = await (await s.client.get("/status")).json()
        fleet = status.get("fleet") or {}
        readyz = await s.client.get("/readyz")
        ok = [r for r in rows if r["ok"]]
        ttfts = [r["ttft"] for r in rows if r.get("ttft") is not None]
        return {
            "arm": name,
            "offered": N_STREAMS,
            "completed": len(ok),
            "failed": N_STREAMS - len(ok),
            "wall_s": round(wall, 2),
            "goodput_tok_s": round(sum(r["tokens"] for r in ok) / wall, 1),
            "p99_ttft_ms": round(pctile(ttfts, 0.99) * 1000, 1) if ttfts else None,
            "replicas_healthy": fleet.get("healthy"),
            "failovers": fleet.get("failovers"),
            "readyz": readyz.status,
        }


async def main() -> None:
    dev = {"DEVICE": os.environ["DEVICE"]} if os.environ.get("DEVICE") else {}
    kill_single = {
        "FAULT_SPEC": f"chunk:fatal@{KILL_AT}",
        "ENGINE_RESTARTS_MAX": "0",
        "SUPERVISE": "1",
    }
    kill_fleet = {
        "FLEET_REPLICAS": "2",
        "FAULT_SPEC": f"r0:chunk:fatal@{KILL_AT}",
        "ENGINE_RESTARTS_MAX": "0",
        "SUPERVISE": "1",
    }
    rows = [
        await run_arm("single-clean", {}, dev),
        await run_arm("single-kill", kill_single, dev),
        await run_arm("fleet-kill", kill_fleet, dev),
    ]

    import jax

    backend = jax.default_backend()
    print("\n| arm | completed | goodput tok/s | p99 TTFT (ms) | readyz "
          "| wall (s) |", file=sys.stderr)
    print("|---|---|---|---|---|---|", file=sys.stderr)
    for r in rows:
        print(
            f"| {r['arm']} | {r['completed']}/{r['offered']} "
            f"| {r['goodput_tok_s']} | {r['p99_ttft_ms']} "
            f"| {r['readyz']} | {r['wall_s']} |",
            file=sys.stderr,
        )
        print(json.dumps({**r, "kill_at": KILL_AT, "backend": backend}))


if __name__ == "__main__":
    asyncio.run(main())
