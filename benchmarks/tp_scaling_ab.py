"""Tensor-parallel decode-scaling A/B (TP serving, round 23).

Decode-step time for TP∈{1,2} × KV∈{dense,int8} through the
PRODUCTION engine path: the registry builds the `('replica','tp')`
placement from the `TP` knob, params shard Megatron-style, the KV
cache shards its heads axis, and decode attention runs under
`shard_map`.  Two-scan differencing per config (relay RTT cancels).

HONEST-NEGATIVE NOTE (BASELINE.md round 23): on CPU the virtual host
devices share ONE core, so TP=2 pays the collective + dispatch
overhead with zero added FLOP throughput — it measures SLOWER than
TP=1 by construction.  The CPU run is a correctness/overhead probe;
the throughput/MFU claim belongs to the relay-TPU run (ROADMAP
item 3).

    MODEL_NAME=llama python benchmarks/tp_scaling_ab.py
    TP_AB=0 skips it in run_all.py.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# A TP=2 mesh needs ≥2 devices; on the host platform force the
# virtual-device split before the first jax import (no-op on TPU).
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )

import numpy as np  # noqa: E402

BATCH = int(os.environ.get("TP_BATCH", "4"))
CONTEXT = int(os.environ.get("TP_CONTEXT", "256"))
WIDTHS = tuple(
    int(x) for x in os.environ.get("TP_WIDTHS", "1,2").split(",")
)


def step_ms(tp: int, kv_quant: bool) -> tuple[float, bool]:
    import jax

    from timing import chunked_time_per_step

    from mlmicroservicetemplate_tpu.engine import InferenceEngine
    from mlmicroservicetemplate_tpu.models.registry import build_model
    from mlmicroservicetemplate_tpu.utils.config import ServiceConfig

    cfg = ServiceConfig(
        device=os.environ.get("DEVICE", "tpu"),
        model_name=os.environ.get("MODEL_NAME", "llama"),
        tp=tp,
        # Pin the replica axis so the A/B isolates TP width: without
        # this the TP=1 arm's REPLICAS=0 default data-parallels over
        # every visible device (8 here via the forced host split).
        replicas=1,
        quant_kv="int8" if kv_quant else None,
        warmup=False,
        batch_buckets=(BATCH,),
        seq_buckets=(CONTEXT,),
        max_decode_len=32,
        stream_chunk_tokens=16,
        continuous_batching=False,
    )
    bundle = build_model(cfg)
    # replicas=None: the registry's make_placement builds the TP mesh
    # (tp>1) or the plain single-device ReplicaSet (tp<=1) — the same
    # resolution order the server boot path uses.
    eng = InferenceEngine(bundle, cfg)
    rng = np.random.default_rng(0)
    feats = [
        {"input_ids": rng.integers(
            5, bundle.cfg.vocab_size, CONTEXT).astype(np.int32),
         "length": np.int32(CONTEXT)}
        for _ in range(BATCH)
    ]
    with eng._lock:
        ids, mask, _ = eng._collate_text(feats)
        sp, _ = eng._collate_sample(feats, ids.shape[0])
        ids, mask = eng.replicas.place_batch(ids, mask)
        state, _ = eng._start(
            eng.params, ids, mask, sp, eng.max_decode_len,
            eng.chunk_tokens, False,
        )
        jax.block_until_ready(state.done)
    per, noisy = chunked_time_per_step(
        eng._gen_chunk, eng.params, state,
        iters=int(os.environ.get("CHUNK_ITERS", "32")),
    )
    return per * 1e3, noisy


def main() -> None:
    from mlmicroservicetemplate_tpu.runtime.device import apply_device_env
    from mlmicroservicetemplate_tpu.utils.config import ServiceConfig

    apply_device_env(ServiceConfig(device=os.environ.get("DEVICE", "tpu")))
    rows = []
    for kv_quant in (False, True):
        base_ms = None
        for tp in WIDTHS:
            ms, noisy = step_ms(tp, kv_quant)
            if base_ms is None:
                base_ms = ms
            rows.append({
                "tp": tp,
                "kv": "int8" if kv_quant else "dense",
                "batch": BATCH,
                "context": CONTEXT,
                "step_ms": round(ms, 3),
                "vs_tp1": round(base_ms / max(ms, 1e-9), 3),
                "timing_noisy": bool(noisy),
            })
            print(json.dumps(rows[-1]), flush=True)
    print(json.dumps({
        "model": os.environ.get("MODEL_NAME", "llama"),
        "device": os.environ.get("DEVICE", "tpu"),
        "rows": rows,
    }))


if __name__ == "__main__":
    main()
