"""Tenant fairness A/B: weighted fair-share dequeue vs the plain
class-weighted EDF queue under a noisy-neighbor load shape.

Two arms, each its own service boot (gpt2 streaming causal-LM through
the continuous-batching loop, 2 slots, deep wait queue):

- **edf**: ``TENANTS`` unset — the seed's behavior.  Requests still
  carry ``X-Api-Key`` headers, but nothing classifies them: the heavy
  tenant's backlog and the light tenants' sparse arrivals share one
  FIFO-within-class EDF queue, so every light request waits behind the
  entire backlog ahead of it.
- **fair**: ``TENANTS=heavy,light1,light2,light3`` (equal weights) —
  the SFQ virtual-time dequeue round-robins across tenants with queued
  work, so a light arrival waits behind at most a few in-flight heavy
  streams, not the whole backlog.

Load shape per repeat: the heavy tenant dumps ``TENANT_AB_HEAVY``
streams at once (a queue-deep backlog), then each light tenant sends
``TENANT_AB_LIGHT`` spaced requests while the backlog drains.

Reported per arm: light-tenant TTFT p50/p99, heavy-tenant TTFT p99,
completions per class, sheds, makespan.  The judged claim (ISSUE 17):
with the heavy backlog saturating the queue, light-tenant p99 TTFT
under ``fair`` improves on ``edf`` — the cost being heavy-tenant TTFT,
NOT total throughput (the slot pool never idles in either arm).

    python benchmarks/tenant_fairness_ab.py               # current backend
    DEVICE=cpu python benchmarks/tenant_fairness_ab.py    # CPU sanity run

One JSON line per row to stdout, a markdown table to stderr.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(_here))
from harness import ServiceUnderTest, pctile  # noqa: E402

PROMPT = "the quick brown fox jumps over the lazy dog and"
N_HEAVY = int(os.environ.get("TENANT_AB_HEAVY", "8"))
N_LIGHT = int(os.environ.get("TENANT_AB_LIGHT", "2"))  # per light tenant
REPEATS = int(os.environ.get("TENANT_AB_REPEATS", "1"))
LIGHTS = ("light1", "light2", "light3")


async def _one(client, tenant: str):
    """One streamed request; returns (tenant, status, ttft_s, wall_s)."""
    t0 = time.perf_counter()
    try:
        resp = await client.post(
            "/predict", json={"text": PROMPT, "stream": True},
            headers={"X-Api-Key": tenant},
        )
        if resp.status != 200:
            await resp.read()
            return tenant, resp.status, None, None
        ttft = None
        async for line in resp.content:
            if ttft is None:
                ttft = time.perf_counter() - t0
            if json.loads(line).get("done"):
                break
        return tenant, 200, ttft, time.perf_counter() - t0
    except Exception:
        return tenant, -1, None, None


async def _run_load(s) -> list:
    """One repeat: the heavy backlog lands first, then spaced light
    arrivals ride on top while it drains."""
    tasks = [
        asyncio.create_task(_one(s.client, "heavy")) for _ in range(N_HEAVY)
    ]
    # Let the backlog reach the wait queue before the first light
    # arrival — the contrast under test is light-behind-backlog.
    await asyncio.sleep(0.2)
    for _ in range(N_LIGHT):
        for t in LIGHTS:
            tasks.append(asyncio.create_task(_one(s.client, t)))
        await asyncio.sleep(0.3)
    return await asyncio.gather(*tasks)


async def run_arm(arm: str, dev: dict) -> dict:
    overrides = {
        "MODEL_NAME": "gpt2",
        "BATCH_BUCKETS": "1,2",
        "SEQ_BUCKETS": "64",
        "MAX_DECODE_LEN": "8",
        # Narrow slot pool + a queue deep enough to hold the whole
        # backlog: waiting happens in the SCHEDULABLE queue, where
        # fair-share dequeue can reorder it — not in slots.
        "MAX_STREAMS": "2",
        "MAX_STREAM_QUEUE": "48",
        **dev,
    }
    if arm == "fair":
        overrides["TENANTS"] = ",".join(("heavy", *LIGHTS))
    t0 = time.perf_counter()
    light_ttfts, heavy_ttfts = [], []
    done = {"heavy": 0, "light": 0}
    sheds = 0
    async with ServiceUnderTest(overrides) as s:
        # One discarded probe: lazy first-dispatch costs stay out of
        # the measured cells.
        await _one(s.client, "heavy")
        for _ in range(REPEATS):
            for tenant, status, ttft, _wall in await _run_load(s):
                side = "heavy" if tenant == "heavy" else "light"
                if status == 200:
                    done[side] += 1
                    if ttft is not None:
                        (heavy_ttfts if side == "heavy"
                         else light_ttfts).append(ttft)
                else:
                    sheds += 1
            await asyncio.sleep(1.0)  # drain the slot pool between reps
    return {
        "arm": arm,
        "light_ttft_p50_ms": (
            round(pctile(light_ttfts, 0.5) * 1000, 1) if light_ttfts else None
        ),
        "light_ttft_p99_ms": (
            round(pctile(light_ttfts, 0.99) * 1000, 1) if light_ttfts else None
        ),
        "heavy_ttft_p99_ms": (
            round(pctile(heavy_ttfts, 0.99) * 1000, 1) if heavy_ttfts else None
        ),
        "light_done": done["light"],
        "heavy_done": done["heavy"],
        "sheds": sheds,
        "wall_s": round(time.perf_counter() - t0, 1),
    }


async def main() -> None:
    dev = {"DEVICE": os.environ["DEVICE"]} if os.environ.get("DEVICE") else {}
    rows = [await run_arm(arm, dev) for arm in ("edf", "fair")]

    import jax

    backend = jax.default_backend()
    cols = list(rows[0].keys())
    print("| " + " | ".join(cols) + " | backend |", file=sys.stderr)
    print("|" + "---|" * (len(cols) + 1), file=sys.stderr)
    for row in rows:
        print(
            "| " + " | ".join(str(row[c]) for c in cols)
            + f" | {backend} |",
            file=sys.stderr,
        )
        print(json.dumps({**row, "backend": backend}))


if __name__ == "__main__":
    asyncio.run(main())
