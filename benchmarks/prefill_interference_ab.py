"""Prefill-interference A/B: chunked prefill (PREFILL_CHUNK) vs the
monolithic seed under the head-of-line shape it exists for.

The shape (ISSUE 5): a few short interactive streams are decoding
through the continuous loop when one LONG prompt arrives.  Monolithic
prefill dispatches that prompt as one fused forward in front of the
next decode chunk, so every live stream's time-between-tokens (TBT)
spikes by the whole prefill; chunked prefill interleaves
PREFILL_CHUNK-token windows between decode chunks, bounding the spike
to one window's compute.

Two arms over the SAME service (gpt2 124M random-init, streaming):

- **mono**: ``PREFILL_CHUNK=0`` — the seed's monolithic prefill.
- **chunk<N>**: ``PREFILL_CHUNK=N`` for each N in ``PREFILL_AB_CHUNKS``
  (the sweep that picks the documented default).

Reported per (arm, repeat-aggregated): decode **TBT p99 and max** over
the short streams' inter-chunk gaps while the long prompt is in
flight (the judged stall), the long prompt's TTFT, and the short
streams' TTFT.  The acceptance claim: the chunked arm strictly lowers
short-stream TBT p99/max; the honest cost is the long prompt's own
TTFT (its windows yield to decode — that is the policy working).

Since round 11 the TBT cadence also comes from the SERVER's exported
``stream_tbt_seconds`` histogram (utils/metrics.py) — scraped from
``/metrics`` before/after each arm's measured section — so the
aggregate series every dashboard reads and this harness's
hand-computed client-side gaps must agree (``tbt_hist_*`` vs
``tbt_*`` columns).  The client-side slice stays authoritative for
the in-window stall (the histogram can't condition on the long
prompt being in flight); the histogram covers every gap.

    python benchmarks/prefill_interference_ab.py            # current backend
    DEVICE=cpu python benchmarks/prefill_interference_ab.py # CPU sanity run

One JSON line per row to stdout, a markdown table to stderr.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(_here))
from harness import (  # noqa: E402
    ServiceUnderTest,
    hist_delta,
    hist_pctile,
    pctile,
    scrape_histogram,
)

# The service byte-tokenizes gpt2 text, so prompt length == byte count.
SHORT_PROMPT = "the quick brown fox jumps over "  # 31 tokens < every chunk
LONG_LEN = int(os.environ.get("PREFILL_AB_LONG", "448"))
N_SHORT = 3
# Decode budget: keeps shorts live across the prefill (shrink via env
# for CPU smoke runs — a full-budget arm takes ~10 min on 1 vCPU).
SHORT_TOKENS = int(os.environ.get("PREFILL_AB_SHORT_TOKENS", "48"))
CHUNKS = tuple(
    int(c)
    for c in os.environ.get("PREFILL_AB_CHUNKS", "32,64,128").split(",")
    if c.strip()
)
REPEATS = int(os.environ.get("PREFILL_AB_REPEATS", "3"))


async def _short_stream(client, t_gate: asyncio.Event, out: dict):
    """One short interactive stream; records its TTFT and the
    timestamp of every chunk event so gaps can be sliced against the
    long prompt's in-flight window afterwards."""
    t0 = time.perf_counter()
    resp = await client.post(
        "/predict",
        json={"text": SHORT_PROMPT, "stream": True,
              "max_tokens": SHORT_TOKENS},
        headers={"X-Priority": "interactive"},
    )
    assert resp.status == 200, await resp.text()
    stamps = []
    async for line in resp.content:
        stamps.append(time.perf_counter())
        if not t_gate.is_set():
            t_gate.set()  # first token anywhere arms the long prompt
        if json.loads(line).get("done"):
            break
    out.setdefault("ttft", []).append(stamps[0] - t0)
    out.setdefault("stamps", []).append(stamps)


async def _long_stream(client, t_gate: asyncio.Event, out: dict):
    """The interfering long prompt: fires once a short stream is
    decoding, records TTFT and its own in-flight window."""
    await t_gate.wait()
    t0 = time.perf_counter()
    out["t_launch"] = t0
    resp = await client.post(
        "/predict",
        json={"text": "x" * LONG_LEN, "stream": True, "max_tokens": 8},
        headers={"X-Priority": "batch"},
    )
    assert resp.status == 200, await resp.text()
    first = None
    async for line in resp.content:
        if first is None:
            first = time.perf_counter()
        if json.loads(line).get("done"):
            break
    out["ttft"] = (first if first is not None else time.perf_counter()) - t0
    out["t_done"] = time.perf_counter()


async def run_arm(arm: str, prefill_chunk: int, dev: dict, rows: list):
    overrides = {
        "MODEL_NAME": "gpt2",
        "BATCH_BUCKETS": "1,4",
        # Max bucket covers the long prompt: BOTH arms admit it through
        # the continuous loop, so the A/B isolates the dispatch shape
        # (monolithic vs windowed), not the round-8 routing-bug class.
        "SEQ_BUCKETS": "64,512",
        "MAX_DECODE_LEN": str(SHORT_TOKENS),
        "MAX_STREAMS": "4",
        **({"PREFILL_CHUNK": str(prefill_chunk)} if prefill_chunk else {}),
        **dev,
    }
    tbt_gaps: list[float] = []
    tbt_all_gaps: list[float] = []
    short_ttfts: list[float] = []
    long_ttfts: list[float] = []
    async with ServiceUnderTest(overrides) as s:
        # Discard one warm probe (lazy one-time costs).
        gate0: asyncio.Event = asyncio.Event()
        await _short_stream(s.client, gate0, {})
        # Server-side cadence series: delta over the measured section
        # (the prometheus registry is process-global across arms).
        tbt_before = await scrape_histogram(s.client, "stream_tbt_seconds")
        for _ in range(REPEATS):
            gate: asyncio.Event = asyncio.Event()
            shorts: dict = {}
            longd: dict = {}
            await asyncio.gather(
                *(_short_stream(s.client, gate, shorts)
                  for _ in range(N_SHORT)),
                _long_stream(s.client, gate, longd),
            )
            short_ttfts.extend(shorts["ttft"])
            long_ttfts.append(longd["ttft"])
            # The judged stall: short-stream inter-chunk gaps that END
            # inside the long prompt's in-flight window (launch →
            # done).  A monolithic prefill parks the loop thread, so
            # one of these gaps swallows the whole prefill.
            for stamps in shorts["stamps"]:
                for a, b in zip(stamps, stamps[1:]):
                    gap = b - a
                    tbt_all_gaps.append(gap)
                    if longd["t_launch"] <= b <= longd["t_done"]:
                        tbt_gaps.append(gap)
            await asyncio.sleep(0.5)  # drain the slot pool between reps
        tbt_hist = hist_delta(
            await scrape_histogram(s.client, "stream_tbt_seconds"),
            tbt_before,
        )
    hist_p99 = hist_pctile(tbt_hist, 0.99)
    rows.append({
        "arm": arm,
        "tbt_p99_ms": round(pctile(tbt_gaps, 0.99) * 1e3, 1)
        if tbt_gaps else None,
        "tbt_max_ms": round(max(tbt_gaps) * 1e3, 1) if tbt_gaps else None,
        "tbt_all_p99_ms": round(pctile(tbt_all_gaps, 0.99) * 1e3, 1),
        # The exported stream_tbt_seconds view of the same section:
        # count must cover the client-observed gaps, p99 must agree
        # with tbt_all_p99_ms up to bucket resolution.
        "tbt_hist_p99_ms": round(hist_p99 * 1e3, 1)
        if hist_p99 is not None else None,
        "tbt_hist_n": int(tbt_hist["count"]),
        "tbt_hist_mean_ms": round(
            tbt_hist["sum"] / tbt_hist["count"] * 1e3, 1
        ) if tbt_hist["count"] else None,
        "gaps_in_window": len(tbt_gaps),
        "long_ttft_ms": round(
            sorted(long_ttfts)[len(long_ttfts) // 2] * 1e3, 1
        ),
        "short_ttft_p50_ms": round(
            sorted(short_ttfts)[len(short_ttfts) // 2] * 1e3, 1
        ),
        "long_len": LONG_LEN,
        "short_streams": N_SHORT,
    })


async def main() -> None:
    dev = {"DEVICE": os.environ["DEVICE"]} if os.environ.get("DEVICE") else {}
    rows: list = []
    await run_arm("mono", 0, dev, rows)
    for c in CHUNKS:
        await run_arm(f"chunk{c}", c, dev, rows)

    import jax

    backend = jax.default_backend()
    print("\n| arm | tbt p99 (ms) | tbt max (ms) | tbt hist p99 (ms) "
          "| hist n | long ttft (ms) | short ttft p50 (ms) | gaps |",
          file=sys.stderr)
    print("|---|---|---|---|---|---|---|---|", file=sys.stderr)
    for r in rows:
        print(
            f"| {r['arm']} | {r['tbt_p99_ms']} | {r['tbt_max_ms']} "
            f"| {r['tbt_hist_p99_ms']} | {r['tbt_hist_n']} "
            f"| {r['long_ttft_ms']} | {r['short_ttft_p50_ms']} "
            f"| {r['gaps_in_window']} |",
            file=sys.stderr,
        )
        print(json.dumps({**r, "backend": backend}))


if __name__ == "__main__":
    asyncio.run(main())
