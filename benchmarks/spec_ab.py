"""Speculative-decoding A/B at real model scale (VERDICT r3 item 1).

Decode at B=1 is HBM-bound (BASELINE.md: llama-1.1B 2.58 ms/step bf16 ≈
the v5e wire), so the win decomposes exactly into two measurables:

- ``r`` — verify-step cost ratio: device seconds per spec verify step
  (a K+1-token window forward) over seconds per normal decode step.
  Weight streaming dominates at 1.1B, so r ≈ 1 is the hypothesis: one
  window forward streams the weights once, same as one step.
- ``alpha`` — tokens emitted per verify step on given traffic
  (acceptance + the free bonus token; 1.0 = nothing accepted).

tokens/s speedup = alpha / r.  Both are measured here (two-scan
differencing for r — relay RTT cancels), plus a wall-clock
generate_stream A/B through the full engine path (fewer dispatches per
token also saves relay round-trips, which the ratio alone doesn't show).

Traffic cases for alpha:
- ``cyclic``  — natural greedy repetition: random-init decoders (like
  real LLMs) often lock into short cycles; once generation repeats,
  prompt-lookup drafts from the generated history and acceptance
  approaches K+1.  This is the summarization/extraction/code-edit
  regime where output reuses earlier spans.
- ``adversarial`` — prompts drawn uniformly at random: essentially no
  n-gram ever recurs, alpha ≈ 1, and the measured slowdown (r > 1
  share) is the honest worst case.

Usage: MODEL_NAME=llama|gpt2 [QUANTIZE=int8] [SPEC_K=8] python
benchmarks/spec_ab.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from timing import chunked_time_per_step  # noqa: E402


def make_engine(spec: bool):
    from mlmicroservicetemplate_tpu.engine import InferenceEngine
    from mlmicroservicetemplate_tpu.models.registry import build_model
    from mlmicroservicetemplate_tpu.parallel import ReplicaSet, make_mesh
    from mlmicroservicetemplate_tpu.runtime.device import apply_device_env
    from mlmicroservicetemplate_tpu.utils.config import ServiceConfig

    cfg = ServiceConfig(
        device=os.environ.get("DEVICE", "tpu"),
        model_name=os.environ.get("MODEL_NAME", "llama"),
        quantize=os.environ.get("QUANTIZE") or None,
        warmup=False,
        batch_buckets=(1,),
        seq_buckets=(64, 256),
        max_decode_len=int(os.environ.get("DECODE_LEN", "128")),
        stream_chunk_tokens=int(os.environ.get("CHUNK", "16")),
        spec_decode="ngram" if spec else None,
        spec_k=int(os.environ.get("SPEC_K", "8")),
        continuous_batching=False,
    )
    apply_device_env(cfg)
    bundle = build_model(cfg)
    return InferenceEngine(bundle, cfg, ReplicaSet(make_mesh(1))), cfg


def state_from_prompt(eng, ids_np):
    import jax

    feats = {"input_ids": ids_np, "length": np.int32(len(ids_np))}
    with eng._lock:
        ids, mask, _ = eng._collate_text([feats])
        sp, _ = eng._collate_sample([feats], ids.shape[0])
        ids, mask = eng.replicas.place_batch(ids, mask)
        state, _ = eng._start(
            eng.params, ids, mask, sp, eng.max_decode_len, eng.chunk_tokens, False
        )
        jax.block_until_ready(state.done)
    return feats, ids, mask, sp, state


def measure_alpha(eng, ids_np, budget) -> tuple[float, int]:
    """Drive the real spec stream; returns (tokens/verify-step, total)."""
    n_steps = 0
    total = 0
    feats = {"input_ids": ids_np, "length": np.int32(len(ids_np)),
             "max_tokens": budget}
    for chunk in eng.generate_stream(feats):
        total += int(chunk.size)
        n_steps += eng.chunk_tokens  # n_verify per dispatch
    return total / max(1, n_steps), total


def wall_tokens_s(eng, ids_np, budget, reps: int = 3, **extra) -> float:
    best = 0.0
    for _ in range(reps):
        feats = {"input_ids": ids_np, "length": np.int32(len(ids_np)),
                 "max_tokens": budget, **extra}
        t0 = time.perf_counter()
        n = sum(int(c.size) for c in eng.generate_stream(feats))
        dt = time.perf_counter() - t0
        best = max(best, n / dt)
    return best


def main() -> None:
    import jax

    spec_k = int(os.environ.get("SPEC_K", "8"))
    budget = int(os.environ.get("DECODE_LEN", "128"))
    rng = np.random.default_rng(0)

    eng_spec, cfg = make_engine(spec=True)
    eng_norm, _ = make_engine(spec=False)
    bundle = eng_spec.bundle
    vocab = bundle.cfg.vocab_size

    # Prompts: cyclic (short tiled n-gram cycle) and adversarial
    # (uniform random ids) at the same length.
    p_len = 48
    cycle = rng.integers(5, vocab, 4)
    ids_cyc = np.tile(cycle, p_len // 4 + 1)[:p_len].astype(np.int32)
    ids_adv = rng.integers(5, vocab, p_len).astype(np.int32)

    # -- r: per-step device cost, normal vs verify (differencing) -----
    _, _, _, _, state = state_from_prompt(eng_norm, ids_cyc)
    step_s, step_noisy = chunked_time_per_step(
        eng_norm._gen_chunk, eng_norm.params, state,
        iters=int(os.environ.get("CHUNK_ITERS", "48")),
    )

    feats, ids, mask, sp, state2 = state_from_prompt(eng_spec, ids_cyc)
    # Family-generic: the bundle's own init_spec_fn builds the history
    # (encoder-prefixed for T5, GPTState layout for decoder-only).
    ss = bundle.init_spec_fn(state2, ids, mask)
    spec_fn = jax.jit(
        lambda p, s, n: bundle.spec_chunk_fn(p, s, n, spec_k)[:2],
        static_argnums=2,
    )
    verify_s, verify_noisy = chunked_time_per_step(
        spec_fn, eng_spec.params, ss,
        iters=int(os.environ.get("CHUNK_ITERS", "48")),
    )
    r = verify_s / max(step_s, 1e-12)

    # -- alpha on both traffic shapes ---------------------------------
    alpha_cyc, total_cyc = measure_alpha(eng_spec, ids_cyc, budget)
    alpha_adv, total_adv = measure_alpha(eng_spec, ids_adv, budget)

    # -- end-to-end wall tokens/s through generate_stream -------------
    wall = {
        "spec_cyclic": wall_tokens_s(eng_spec, ids_cyc, budget),
        "norm_cyclic": wall_tokens_s(eng_norm, ids_cyc, budget),
        "spec_adversarial": wall_tokens_s(eng_spec, ids_adv, budget),
        "norm_adversarial": wall_tokens_s(eng_norm, ids_adv, budget),
    }
    # Sampled traffic (rejection-sampling acceptance, SPEC_SAMPLED):
    # same seeded request both sides; outputs differ in tokens (same
    # distribution), the wall ratio is the measurement.
    samp = dict(temperature=0.8, seed=7)
    wall["spec_sampled_cyclic"] = wall_tokens_s(
        eng_spec, ids_cyc, budget, **samp
    )
    wall["norm_sampled_cyclic"] = wall_tokens_s(
        eng_norm, ids_cyc, budget, **samp
    )

    out = {
        "model": bundle.name,
        "quantize": cfg.quantize,
        "spec_k": spec_k,
        "step_ms": round(step_s * 1e3, 4),
        "verify_step_ms": round(verify_s * 1e3, 4),
        "timing_noisy": bool(step_noisy or verify_noisy),
        "cost_ratio_r": round(r, 3),
        "alpha_cyclic": round(alpha_cyc, 3),
        "alpha_adversarial": round(alpha_adv, 3),
        "device_speedup_cyclic": round(alpha_cyc / r, 3),
        "device_speedup_adversarial": round(alpha_adv / r, 3),
        "wall_tokens_s": {k: round(v, 1) for k, v in wall.items()},
        "wall_speedup_cyclic": round(
            wall["spec_cyclic"] / max(wall["norm_cyclic"], 1e-9), 3
        ),
        "wall_speedup_adversarial": round(
            wall["spec_adversarial"] / max(wall["norm_adversarial"], 1e-9), 3
        ),
        "wall_speedup_sampled_cyclic": round(
            wall["spec_sampled_cyclic"]
            / max(wall["norm_sampled_cyclic"], 1e-9), 3
        ),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
