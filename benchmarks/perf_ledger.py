"""Perf-regression ledger: structural counters, not wall-clock.

Every BASELINE.md round since r12 carries the same caveat — CPU
wall-clock numbers on the contended 1-vCPU box are weather, not
signal.  What IS stable there is the *structure* of the work: host
syncs per generated token, XLA compiles paid during serving, staged
host-prep hit rate, swap fallbacks, dispatch counts per site.  Those
counters regress when a change breaks a lever (a fused window that
stops fusing, a cache that stops sharing, a prep stage that stops
hitting) and they are immune to box noise by construction.

Two consumers:

- ``benchmarks/run_all.py`` appends one JSONL row per measured config
  to ``PERF_LEDGER.jsonl`` (env ``PERF_LEDGER`` overrides the path,
  ``PERF_LEDGER=0`` disables) — the longitudinal record each
  BASELINE.md round can diff against the last;
- ``scripts/perf_smoke.py`` (the ``PERF_SMOKE`` stage in
  ``scripts/check.sh``) runs a deterministic tiny workload and FAILS
  on regression against the committed ``benchmarks/perf_baseline.json``.
"""

from __future__ import annotations

import json
import os
import time


def default_path() -> str | None:
    """The ledger file path, or None when disabled (PERF_LEDGER=0)."""
    v = os.environ.get("PERF_LEDGER", "")
    if v.lower() in ("0", "false", "no"):
        return None
    if v:
        return v
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(root, "PERF_LEDGER.jsonl")


def structural_counters(engine, cdl=None) -> dict:
    """The noise-immune counter set for one served workload."""
    attrs = engine.dispatch_attribution() if hasattr(
        engine, "dispatch_attribution"
    ) else {}
    counts = {site: a["count"] for site, a in attrs.items()}
    syncs = counts.get("chunk", 0) + counts.get("fetch", 0)
    tokens = getattr(cdl, "tokens_emitted", 0) if cdl is not None else 0
    out = {
        "dispatch_counts": counts,
        "host_syncs": syncs,
        "tokens": tokens,
        "host_syncs_per_token": round(syncs / tokens, 4) if tokens else None,
    }
    if cdl is not None:
        out.update(
            chunk_dispatches=cdl.chunk_dispatches,
            prefill_dispatches=cdl.prefill_dispatches,
            window_dispatches=getattr(cdl, "window_dispatches", 0),
            prep_staged=getattr(cdl, "prep_staged", 0),
            prep_hits=getattr(cdl, "prep_hits", 0),
            prep_misses=getattr(cdl, "prep_misses", 0),
            swap_fallbacks=getattr(cdl, "swap_fallbacks", 0),
            preemptions=getattr(cdl, "preemptions", 0),
        )
    try:
        from mlmicroservicetemplate_tpu.runtime.compile_cache import (
            cache_stats,
            compile_counters,
        )

        out["xla_compiles_total"] = compile_counters()["count"]
        out["executable_cache"] = cache_stats()
    except Exception:
        pass
    perf = getattr(engine, "perf", None)
    if perf is not None:
        snap = perf.snapshot()
        out["modeled_flops_total"] = snap.get("modeled_flops_total", 0.0)
        out["perf_pending_dispatches"] = snap.get("pending_dispatches", 0)
    try:
        from mlmicroservicetemplate_tpu.ops import autotune

        counts = autotune.stats()["counts"]
        if any(counts.values()):
            out["autotune_variants_swept"] = counts["timed"]
            out["autotune_installs"] = counts["installs"]
            out["autotune"] = counts
    except Exception:
        pass
    return out


def append_row(config: str, counters: dict, path: str | None = None,
               extra: dict | None = None) -> None:
    """Append one ledger row; never raises into the caller (a ledger
    write failure must not sink a benchmark run)."""
    path = path if path is not None else default_path()
    if path is None:
        return
    row = {
        "ts": round(time.time(), 3),
        "config": config,
        **(extra or {}),
        **counters,
    }
    try:
        with open(path, "a") as f:
            f.write(json.dumps(row, sort_keys=True) + "\n")
    except OSError as e:
        print(f"perf ledger append failed: {e}")
