"""Autoscaling A/B: goodput, shed rate and scale-event latency under a
burst→lull→burst arrival curve, static R=1 vs elastic [1..3].

The judged claim (ISSUE 12): a traffic spike against a FIXED fleet can
only queue or shed — the elastic fleet turns the same spike into a
scale-up (donor-param broadcast, no checkpoint reload) and turns the
lull into a drain-based scale-down, so capacity tracks the arrival
curve instead of the boot flag.  The cost is the scale-event latency
(engine build + warm compile + probe), which this benchmark measures
directly off ``/status.fleet.scaling``.

Two arms over the same tiny-dims llama service (random-init weights —
scaling economics depend on dispatch structure, not weights).  Since
r19 both arms boot with WARMUP=1 (sampling variants off): the boot
warm is UNTIMED and populates the process-level ExecutableCache
(docs/compilation.md), so the elastic arm's scale-up measures the
production spawn fast-path — donor broadcast + cache-hit warm + probe
— instead of a from-scratch compile of executables replica 0 never
built (the r17 arm ran WARMUP=0, which is why its spawn paid a 262 s
warm compile ON TOP of the serving core).  Same arrival curve:

- **static-r1**:     FLEET_REPLICAS=1, no elastic bounds (the seed
                     behavior: MAX_STREAMS slots + a bounded queue,
                     everything past them sheds).
- **elastic-1to3**:  FLEET_REPLICAS=1, FLEET_MAX_REPLICAS=3, an eager
                     governor (short period/cooldowns, sized for a
                     CPU-seconds benchmark; production values are the
                     knob table in docs/autoscaling.md).

Arrival curve per phase: burst (3 waves × WAVE streams back to back),
lull (LULL_S of one trickle stream), burst again.  Each stream
reports TTFT, tokens and its HTTP outcome; 503s count as sheds.

    python benchmarks/autoscale_ab.py              # current backend
    DEVICE=cpu python benchmarks/autoscale_ab.py   # CPU sanity run

One JSON line per arm to stdout, a markdown table to stderr.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(_here))
from harness import ServiceUnderTest, pctile  # noqa: E402

WAVE = int(os.environ.get("SCALE_AB_WAVE", "6"))
N_WAVES = int(os.environ.get("SCALE_AB_WAVES", "3"))
LULL_S = float(os.environ.get("SCALE_AB_LULL_S", "3.0"))

PROMPTS = [
    "the quick brown fox jumps over",
    "pack my box with five dozen jugs",
    "a somewhat longer prompt that spans a few more tokens",
    "short burst",
]


async def _one(client, i: int):
    text = PROMPTS[i % len(PROMPTS)]
    t0 = time.perf_counter()
    try:
        resp = await client.post(
            "/predict",
            json={"text": text, "stream": True, "max_tokens": 16},
        )
        if resp.status != 200:
            await resp.read()
            return {"ok": False, "shed": resp.status == 503,
                    "status": resp.status, "tokens": 0}
        ttft = None
        n_tok = 0
        failed = False
        async for line in resp.content:
            if not line.strip():
                continue
            if ttft is None:
                ttft = time.perf_counter() - t0
            row = json.loads(line)
            if "error" in row:
                failed = True
                break
            if row.get("done"):
                n_tok = int(row.get("tokens_generated", 0))
                break
        return {"ok": not failed and n_tok > 0, "shed": False,
                "status": 200, "tokens": 0 if failed else n_tok,
                "ttft": ttft}
    except Exception:
        return {"ok": False, "shed": False, "status": -1, "tokens": 0}


async def _burst(client, n_waves: int, base: int) -> list[dict]:
    rows: list[dict] = []
    for w in range(n_waves):
        wave = await asyncio.gather(
            *(_one(client, base + w * WAVE + i) for i in range(WAVE))
        )
        rows += list(wave)
    return rows


async def _fleet_scaling(client) -> dict:
    status = await (await client.get("/status")).json()
    fleet = status.get("fleet") or {}
    return fleet.get("scaling") or {}


async def run_arm(name: str, extra: dict, dev: dict) -> dict:
    overrides = {
        "MODEL_NAME": "llama",
        "BATCH_BUCKETS": "1,2,4",
        "SEQ_BUCKETS": "16,32",
        "MAX_DECODE_LEN": "16",
        "STREAM_CHUNK_TOKENS": "4",
        "MAX_STREAMS": "2",
        "MAX_STREAM_QUEUE": "4",
        "WARMUP": "1",
        "WARMUP_SAMPLING": "0",
        "REPLICAS": "1",
        **extra,
        **dev,
    }
    async with ServiceUnderTest(overrides) as s:
        # Untimed warm round: flushes any remaining request-path
        # first-touch cost so the curve under test measures
        # scheduling, not XLA (both arms identically; the boot warm
        # already compiled the grid into the ExecutableCache).
        await _one(s.client, 0)
        print(f"[{name}] warm round done", file=sys.stderr)
        t0 = time.perf_counter()
        rows = await _burst(s.client, N_WAVES, 0)     # burst A
        peak = await _fleet_scaling(s.client)
        print(f"[{name}] burst A done (live={peak.get('live')})",
              file=sys.stderr)
        lull_end = time.perf_counter() + LULL_S       # lull: a trickle
        while time.perf_counter() < lull_end:
            rows.append(await _one(s.client, len(rows)))
            await asyncio.sleep(0.3)
        rows += await _burst(s.client, N_WAVES, len(rows))  # burst B
        wall = time.perf_counter() - t0
        print(f"[{name}] burst B done", file=sys.stderr)
        scaling = await _fleet_scaling(s.client)
        ok = [r for r in rows if r["ok"]]
        sheds = sum(1 for r in rows if r["shed"])
        ttfts = [r["ttft"] for r in rows if r.get("ttft") is not None]
        recent = scaling.get("recent") or []
        up_events = [e for e in recent if e["dir"] == "up"]
        up_durs = [e["duration_s"] for e in up_events]
        # Scale-up latency breakdown per event (ISSUE 14): where the
        # spin-up wall went — engine build + donor broadcast, loop
        # warm, probe, rebalance — and the XLA compiles it paid.
        # With the fleet-shared executable cache the second spawn's
        # xla_compiles is 0 and warm_s collapses to dispatch time.
        breakdowns = [
            {"cause": e.get("cause"), "replica": e.get("replica"),
             "duration_s": e.get("duration_s"), **e.get("breakdown", {})}
            for e in up_events
        ]
        status_compile = None
        try:
            full_status = await (await s.client.get("/status")).json()
            status_compile = full_status.get("compile")
        except Exception:
            pass
        return {
            "arm": name,
            "offered": len(rows),
            "completed": len(ok),
            "shed": sheds,
            "shed_rate": round(sheds / len(rows), 3),
            "wall_s": round(wall, 2),
            "goodput_tok_s": round(
                sum(r["tokens"] for r in ok) / wall, 1
            ),
            "p99_ttft_ms": (
                round(pctile(ttfts, 0.99) * 1000, 1) if ttfts else None
            ),
            "peak_live": peak.get("live"),
            "final_live": scaling.get("live"),
            "scale_events": scaling.get("events"),
            "scale_up_latency_s": (
                round(max(up_durs), 3) if up_durs else None
            ),
            "scale_up_breakdown": breakdowns,
            "compile": status_compile,
        }


async def main() -> None:
    dev = {"DEVICE": os.environ["DEVICE"]} if os.environ.get("DEVICE") else {}
    elastic = {
        "FLEET_MAX_REPLICAS": "3",
        "SCALE_PERIOD_S": "0.1",
        "SCALE_UP_QUEUE": "1",
        "SCALE_UP_COOLDOWN_S": "0.5",
        "SCALE_DOWN_LOAD": "0.5",
        "SCALE_DOWN_COOLDOWN_S": "1.5",
        "DRAIN_GRACE_S": "10",
    }
    rows = [
        await run_arm("static-r1", {}, dev),
        await run_arm("elastic-1to3", elastic, dev),
    ]

    import jax

    backend = jax.default_backend()
    print("\n| arm | completed | shed rate | goodput tok/s | p99 TTFT "
          "(ms) | peak/final live | scale-up latency (s) |",
          file=sys.stderr)
    print("|---|---|---|---|---|---|---|", file=sys.stderr)
    for r in rows:
        print(
            f"| {r['arm']} | {r['completed']}/{r['offered']} "
            f"| {r['shed_rate']} | {r['goodput_tok_s']} "
            f"| {r['p99_ttft_ms']} "
            f"| {r['peak_live']}/{r['final_live']} "
            f"| {r['scale_up_latency_s']} |",
            file=sys.stderr,
        )
        for b in r.get("scale_up_breakdown") or []:
            print(
                f"    up:{b.get('cause')} r{b.get('replica')}: "
                f"total {b.get('duration_s')}s = build "
                f"{b.get('build_s')}s + warm {b.get('warm_s')}s + "
                f"probe {b.get('probe_s')}s + rebalance "
                f"{b.get('rebalance_s')}s "
                f"({b.get('xla_compiles')} XLA compiles, "
                f"{b.get('compile_s')}s compiling)",
                file=sys.stderr,
            )
        print(json.dumps({**r, "backend": backend,
                          "wave": WAVE, "lull_s": LULL_S}))


if __name__ == "__main__":
    asyncio.run(main())
