"""Decode-fusion A/B: host syncs per token, tokens/s and decode TBT
vs DECODE_WINDOW ∈ {1, 2, 4, 8}.

The judged claim (ISSUE 7): with W chunks fused into one dispatch
(``lax.while_loop`` + on-device EOS early exit), the host submits and
fetches once per window instead of per chunk — so the measured
``dispatch_host_seconds{site="chunk"|"fetch"}`` call count per
generated token must drop ≥ W/2× vs W=1, with output token-identical
and interactive decode TBT p99 no worse while the auto policy governs.

Three measurements per W arm, same gpt2 service (random-init weights —
dispatch counts and cadence depend on shapes, not weights):

- **batch lane** (the fusion target): N batch-class streams
  (``X-Priority: batch``) decode concurrently; reported tokens/s,
  client-side TBT p50/p99 (gaps between ndjson chunk lines after the
  first), and host syncs/token from the ``/status.decode`` chunk+fetch
  dispatch-count deltas.
- **interactive lane** (the SLA guard): the same prompts as
  interactive streams under the SAME ``DECODE_WINDOW`` cap with the
  auto policy on — the governor must hold W=1, so TBT p99 must match
  the W=1 arm (fused windows would multiply it by ~W).
- **token identity**: the batch lane's token streams are compared
  across arms (every W serves the same sequences).

CPU honest-negative expectation: dispatch submit→return is ~free on a
synchronous local backend, so tokens/s is flat-to-noise here — the
wins this harness PINS on CPU are the host-sync divisor and the
interactive TBT guard; the tokens/s claim is the relay-attached TPU's
to verify (BASELINE.md records both).

    DEVICE=cpu python benchmarks/decode_fusion_ab.py
    FUSION_AB_WINDOWS=1,4 python benchmarks/decode_fusion_ab.py

One JSON line per (arm, lane) to stdout, a markdown table to stderr.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(_here))
from harness import ServiceUnderTest, pctile  # noqa: E402

WINDOWS = [
    int(w)
    for w in os.environ.get("FUSION_AB_WINDOWS", "1,2,4,8").split(",")
    if w.strip()
]
N_STREAMS = int(os.environ.get("FUSION_AB_N", "4"))
# Enough chunks per stream (24 at chunk=4) that the deep arms can
# amortize the per-stream constants (admission fetch, terminal
# boundary): at 12 chunks a W=8 window can only ever fire twice and
# the divisor saturates near 2x regardless of W.
MAX_TOKENS = int(os.environ.get("FUSION_AB_TOKENS", "96"))
PROMPTS = [
    "the quick brown fox",
    "pack my box with five dozen",
    "a third prompt",
    "and one more stream to fill the batch",
]


async def _stream_one(client, text: str, klass: str):
    headers = {"X-Priority": klass}
    t0 = time.perf_counter()
    resp = await client.post(
        "/predict",
        json={"text": text, "stream": True, "max_tokens": MAX_TOKENS},
        headers=headers,
    )
    assert resp.status == 200, await resp.text()
    stamps, tokens, text = [], 0, ""
    async for line in resp.content:
        stamps.append(time.perf_counter())
        msg = json.loads(line)
        if msg.get("done"):
            tokens = int(msg.get("decode_steps", 0))
            text = msg.get("prediction", {}).get("text", "")
            break
    gaps = [b - a for a, b in zip(stamps[1:-1], stamps[2:])]
    return {
        "wall": time.perf_counter() - t0,
        "tokens": tokens,
        "gaps": gaps,
        "out": (text, int(msg.get("tokens_generated", 0))),
    }


async def _decode_status(client) -> dict:
    resp = await client.get("/status")
    return (await resp.json()).get("decode", {})


async def _lane(client, klass: str, n: int) -> dict:
    before = await _decode_status(client)
    t0 = time.perf_counter()
    rows = await asyncio.gather(
        *(_stream_one(client, PROMPTS[i % len(PROMPTS)], klass)
          for i in range(n))
    )
    wall = time.perf_counter() - t0
    after = await _decode_status(client)
    b_counts, a_counts = before.get("dispatch_counts", {}), after.get(
        "dispatch_counts", {}
    )
    syncs = sum(
        a_counts.get(site, 0) - b_counts.get(site, 0)
        for site in ("chunk", "fetch")
    )
    tokens = sum(r["tokens"] for r in rows)
    gaps = [g for r in rows for g in r["gaps"]]
    return {
        "lane": klass,
        "streams": n,
        "tokens": tokens,
        "tokens_per_s": round(tokens / wall, 1) if wall else 0.0,
        "chunk_fetch_syncs": syncs,
        "host_syncs_per_token": round(syncs / tokens, 4) if tokens else None,
        "tbt_p50_ms": round(
            sorted(gaps)[len(gaps) // 2] * 1e3, 2
        ) if gaps else None,
        "tbt_p99_ms": round(pctile(gaps, 0.99) * 1e3, 2) if gaps else None,
        "window_dispatches": after.get("window_dispatches", 0)
        - before.get("window_dispatches", 0),
        "window_early_exits": after.get("window_early_exits", 0)
        - before.get("window_early_exits", 0),
        "outs": [r["out"] for r in rows],
    }


async def run_arm(w: int, dev: dict) -> list[dict]:
    overrides = {
        "MODEL_NAME": "gpt2",
        # One batch bucket + one seq bucket: every prompt here fits 64,
        # and a small warm grid keeps the per-arm service start cheap
        # enough for the 4-arm sweep on CPU.
        "BATCH_BUCKETS": "1",
        "SEQ_BUCKETS": "64",
        "MAX_DECODE_LEN": str(MAX_TOKENS),
        "STREAM_CHUNK_TOKENS": "4",
        "MAX_STREAMS": str(N_STREAMS),
        "MAX_STREAM_QUEUE": "16",
        "DECODE_WINDOW": str(w),
        **dev,
    }
    async with ServiceUnderTest(overrides) as s:
        batch = await _lane(s.client, "batch", N_STREAMS)
        interactive = await _lane(s.client, "interactive", 2)
        out = []
        for lane in (batch, interactive):
            outs = lane.pop("outs")
            out.append({"window": w, **lane, "_outs": outs})
        return out


async def main() -> None:
    dev = {"DEVICE": os.environ["DEVICE"]} if os.environ.get("DEVICE") else {}
    arms = []
    for w in WINDOWS:
        arms.extend(await run_arm(w, dev))

    # Token identity across arms, per lane (same prompts, same greedy
    # model -> every W must serve identical sequences).
    identical = True
    for lane in ("batch", "interactive"):
        seqs = [a["_outs"] for a in arms if a["lane"] == lane]
        identical &= all(s == seqs[0] for s in seqs[1:])

    import jax

    backend = jax.default_backend()
    print(
        "\n| W | lane | tokens/s | syncs/token | TBT p50 (ms) "
        "| TBT p99 (ms) | windows | early exits |",
        file=sys.stderr,
    )
    print("|---|---|---|---|---|---|---|---|", file=sys.stderr)
    for a in arms:
        a.pop("_outs")
        print(
            f"| {a['window']} | {a['lane']} | {a['tokens_per_s']} "
            f"| {a['host_syncs_per_token']} | {a['tbt_p50_ms']} "
            f"| {a['tbt_p99_ms']} | {a['window_dispatches']} "
            f"| {a['window_early_exits']} |",
            file=sys.stderr,
        )
        print(json.dumps({**a, "backend": backend,
                          "token_identical_across_arms": identical}))
    base = next(
        (a for a in arms if a["window"] == 1 and a["lane"] == "batch"), None
    )
    if base and base["host_syncs_per_token"]:
        for a in arms:
            if a["lane"] == "batch" and a["window"] > 1 and (
                a["host_syncs_per_token"]
            ):
                ratio = base["host_syncs_per_token"] / a["host_syncs_per_token"]
                print(
                    f"W={a['window']}: host syncs/token divided by "
                    f"{ratio:.2f}x (acceptance floor {a['window'] / 2:.1f}x)",
                    file=sys.stderr,
                )
    print(f"token identity across arms: {identical}", file=sys.stderr)


if __name__ == "__main__":
    asyncio.run(main())
