"""QUANTIZE=int8 A/B: measured device-time effect of weight-only int8.

The round-2 verdict: the quant path shipped correctness-tested with an
HBM-bandwidth rationale and ZERO measured numbers.  This measures the
claim where it should show — small-batch autoregressive decode is
weight-streaming-bound, so halving weight bytes should cut per-step
time — and where it shouldn't (batch-32 encoder forward is
compute-bound; int8 adds dequant work).

Method: two-scan-length differencing (benchmarks/timing.py) for
forwards; chunk-length differencing for decode (the chunk IS the scan).
Both cancel the relay RTT exactly.

    python benchmarks/quant_ab.py            # TPU; one JSON line
    DEVICE=cpu python benchmarks/quant_ab.py # CPU sanity (slow)
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

PROMPT_LEN = int(os.environ.get("BENCH_PROMPT_LEN", "64"))
DECODE_BATCHES = (1, 8)


def _engine(model: str, device: str, quantize: str | None):
    from mlmicroservicetemplate_tpu.engine import InferenceEngine
    from mlmicroservicetemplate_tpu.models.registry import build_model
    from mlmicroservicetemplate_tpu.utils.config import ServiceConfig

    cfg = ServiceConfig(
        device=device, model_name=model, warmup=False, quantize=quantize,
        batch_buckets=(1, 8, 32), seq_buckets=(PROMPT_LEN,),
        max_decode_len=64,
    )
    return InferenceEngine(build_model(cfg), cfg)


def _decode_steps(engine, batch: int):
    import jax

    from timing import chunked_time_per_step

    feats = [{"input_ids": np.ones(PROMPT_LEN, np.int32),
              "length": np.int32(PROMPT_LEN)}] * batch
    ids, mask, _ = engine._collate_text(feats)
    sp, _ = engine._collate_sample(feats, ids.shape[0])
    ids, mask = engine.replicas.place_batch(ids, mask)
    state, toks = engine._start(
        engine.params, ids, mask, sp, engine.max_decode_len,
        engine.chunk_tokens, False,
    )
    jax.device_get(toks)
    chunk_fn = jax.jit(engine.bundle.generate_chunk_fn, static_argnums=(2, 3))

    def run_chunk(p, s, n):
        return chunk_fn(p, s, n, False)

    per_step, noisy = chunked_time_per_step(run_chunk, engine.params, state)
    return per_step, noisy


def main() -> None:
    device = os.environ.get("DEVICE", "tpu")
    from mlmicroservicetemplate_tpu.runtime.device import apply_device_env

    apply_device_env(device)

    from timing import device_time_per_call

    out: dict = {"device": device, "prompt_len": PROMPT_LEN,
                 "method": "two-scan-length / chunk-length differencing"}

    # -- gpt2 decode: the HBM-bound case int8 targets -------------------
    for mode in (None, "int8"):
        eng = _engine("gpt2", device, mode)
        key = "bf16" if mode is None else "int8"
        for b in DECODE_BATCHES:
            per_step, noisy = _decode_steps(eng, b)
            row = {
                "decode_step_ms": round(per_step * 1000, 3),
                "decode_tokens_s": round(b / per_step, 1),
            }
            if noisy:
                row["timing_noisy"] = True
            out[f"gpt2_{key}_b{b}"] = row
        del eng
    for b in DECODE_BATCHES:
        out[f"gpt2_int8_speedup_b{b}"] = round(
            out[f"gpt2_bf16_b{b}"]["decode_step_ms"]
            / out[f"gpt2_int8_b{b}"]["decode_step_ms"], 3,
        )

    # -- bert-base forward: compute-bound control ------------------------
    import jax.numpy as jnp

    for mode in (None, "int8"):
        eng = _engine("bert-base", device, mode)
        key = "bf16" if mode is None else "int8"
        b, s = 32, PROMPT_LEN
        ids = jnp.asarray(np.ones((b, s), np.int32))
        mask = jnp.asarray(np.ones((b, s), np.int32))
        dt, noisy = device_time_per_call(
            eng.bundle.forward, (eng.params, ids, mask), carry_idx=1
        )
        out[f"bert_{key}_batch32_ms"] = round(dt * 1000, 3)
        if noisy:
            out[f"bert_{key}_noisy"] = True
        del eng
    out["bert_int8_speedup"] = round(
        out["bert_bf16_batch32_ms"] / out["bert_int8_batch32_ms"], 3
    )

    # -- resnet-50 forward at B=32: the judged config-3 device path -----
    # (VERDICT r4 weak #1: conv HWIO kernels quantize but were never
    # A/B'd; if the stem/1x1 projections sit on the HBM roof as the
    # round-4 roofline note claims, halving weight bytes should move
    # the number; if it's XLA-compute-bound, this pins the claim.)
    if os.environ.get("BENCH_RESNET", "1").lower() not in ("0", "false", "no"):
        for mode in (None, "int8"):
            eng = _engine("resnet50", device, mode)
            key = "bf16" if mode is None else "int8"
            b = 32
            imgs = jnp.asarray(
                np.random.default_rng(0).integers(
                    0, 255, (b, 224, 224, 3), dtype=np.uint8
                )
            )
            dt, noisy = device_time_per_call(
                eng.bundle.forward, (eng.params, imgs), carry_idx=1
            )
            out[f"resnet_{key}_batch32_ms"] = round(dt * 1000, 3)
            out[f"resnet_{key}_img_s"] = round(b / dt, 1)
            if noisy:
                out[f"resnet_{key}_noisy"] = True
            del eng
        out["resnet_int8_speedup"] = round(
            out["resnet_bf16_batch32_ms"] / out["resnet_int8_batch32_ms"], 3
        )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
