"""Per-op-class roofline profile of the served ResNet-50 forward.

VERDICT r4 weak #1: encoder MFU sat at ~28-30% (conservative
convention) for three rounds with only prose attributing the gap to
the stem and 1x1 projections.  This produces the NUMBERS: device time
per network SEGMENT (stem / each bottleneck stage / head) by
cumulative-prefix differencing (two-scan method per prefix — relay RTT
cancels; segment time = prefix_k - prefix_{k-1}), plus analytic FLOPs
and minimum HBM bytes per segment, so each segment gets its own
MFU/roofline verdict instead of one blended number.

No profiler dependency: jax.profiler's xplane needs tensorboard's
profile plugin to parse, which this box doesn't ship; differencing
against the real served forward measures the same thing in-repo.

    python benchmarks/resnet_profile.py          # TPU, one JSON line
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

BATCH = int(os.environ.get("PROFILE_BATCH", "32"))
# v5e: 197 TFLOP/s bf16 MXU peak, ~819 GB/s HBM.
PEAK_FLOPS = float(os.environ.get("PEAK_TFLOPS", "197")) * 1e12
PEAK_HBM = float(os.environ.get("PEAK_HBM_GBS", "819")) * 1e9


def _prefix_forward(cfg, upto: int):
    """Forward through the first ``upto`` segments (0=stem only,
    1..4 = +stage_k, 5 = full incl. head); returns a jittable fn whose
    output is small (mean-reduced) so transfer cost stays flat."""
    import jax.numpy as jnp

    from mlmicroservicetemplate_tpu.models import resnet as resnet_mod
    from mlmicroservicetemplate_tpu.models.preprocess import normalize_imagenet

    def fn(p, images):
        x = normalize_imagenet(images).astype(jnp.bfloat16)
        x = resnet_mod.conv2d(
            p["embedder"]["conv"], x, stride=2, padding=((3, 3), (3, 3))
        )
        x = jnp.maximum(resnet_mod.batchnorm(p["embedder"]["bn"], x), 0)
        x = resnet_mod._max_pool_3x3_s2(x)
        for si, (blocks, stride) in enumerate(
            zip(p["stages"], resnet_mod._stage_strides(cfg))
        ):
            if si >= upto:
                break
            for bi, block in enumerate(blocks):
                x = resnet_mod._bottleneck_apply(
                    block, x, stride if bi == 0 else 1
                )
        if upto >= 5:
            pooled = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
            return resnet_mod.dense(p["classifier"], pooled).mean()
        return x.astype(jnp.float32).mean()

    return fn


def _conv_flops(h, w, cin, cout, k, stride):
    ho, wo = h // stride, w // stride
    return 2 * BATCH * ho * wo * cout * k * k * cin, (ho, wo)


def _segment_analytics():
    """FLOPs + min HBM bytes (weights bf16 + in/out activations bf16)
    per segment of ResNet-50 at 224x224."""
    segs = []
    # Stem: 7x7/2 conv 3->64 @112, pool -> 56.
    f, _ = _conv_flops(224, 224, 3, 64, 7, 2)
    w_bytes = 7 * 7 * 3 * 64 * 2
    act = BATCH * (224 * 224 * 3 * 4 + 112 * 112 * 64 * 2)
    segs.append(("stem", f, w_bytes + act))
    # Stages: (blocks, c_mid, c_out, h_in, stride)
    spec = [
        (3, 64, 256, 56, 1),
        (4, 128, 512, 56, 2),
        (6, 256, 1024, 28, 2),
        (3, 512, 2048, 14, 2),
    ]
    c_in = 256 // 4 * 4  # 64 after stem... keep explicit below
    c_in = 64
    for si, (nb, cm, co, h_in, stride) in enumerate(spec):
        f_total = 0
        w_total = 0
        h = h_in
        cin = c_in
        for bi in range(nb):
            s = stride if bi == 0 else 1
            # v1.5 bottleneck (resnet.py:_bottleneck_apply): conv1 1x1
            # runs stride 1 at the INPUT resolution; the 3x3 carries
            # the stride.
            f1, _ = _conv_flops(h, h, cin, cm, 1, 1)
            f2, _ = _conv_flops(h, h, cm, cm, 3, s)
            f3, _ = _conv_flops(h // s, h // s, cm, co, 1, 1)
            f_total += f1 + f2 + f3
            w_total += (cin * cm + 3 * 3 * cm * cm + cm * co) * 2
            if bi == 0:
                fd, _ = _conv_flops(h, h, cin, co, 1, s)
                f_total += fd
                w_total += cin * co * 2
            h = h // s
            cin = co
        act = BATCH * (h_in * h_in * c_in + h * h * co) * 2
        segs.append((f"stage{si + 1}", f_total, w_total + act))
        c_in = co
    # Head: global pool + 2048x1000 dense (tiny).
    segs.append(("head", 2 * BATCH * 2048 * 1000,
                 2048 * 1000 * 2 + BATCH * 2048 * 4))
    return segs


def main() -> None:
    import jax

    from timing import device_time_per_call

    from mlmicroservicetemplate_tpu.models import resnet as resnet_mod
    from mlmicroservicetemplate_tpu.runtime.device import apply_device_env
    from mlmicroservicetemplate_tpu.utils.config import ServiceConfig

    apply_device_env(ServiceConfig(device=os.environ.get("DEVICE", "tpu")))
    from mlmicroservicetemplate_tpu.models.common import cast_pytree
    import jax.numpy as jnp

    cfg = resnet_mod.ResNetConfig()
    params = cast_pytree(
        resnet_mod.init_params(jax.random.PRNGKey(0), cfg), jnp.bfloat16
    )
    imgs = np.random.default_rng(0).integers(
        0, 255, (BATCH, 224, 224, 3), dtype=np.uint8
    )

    prefix_ms = []
    for upto in range(6):
        fn = _prefix_forward(cfg, upto)
        dt, noisy = device_time_per_call(fn, (params, imgs), carry_idx=1)
        prefix_ms.append((dt * 1e3, noisy))

    names = ["stem", "stage1", "stage2", "stage3", "stage4", "head"]
    analytics = dict(
        (n, (f, b)) for n, f, b in _segment_analytics()
    )
    rows = []
    prev = 0.0
    total_flops = sum(f for f, _ in analytics.values())
    for name, (cum, noisy) in zip(names, prefix_ms):
        seg_ms = max(cum - prev, 0.0)
        prev = cum
        f, bts = analytics[name]
        seg_s = seg_ms / 1e3
        rows.append({
            "segment": name,
            "ms": round(seg_ms, 3),
            "gflops": round(f / 1e9, 2),
            "mfu_pct": round(100 * f / max(seg_s, 1e-9) / PEAK_FLOPS, 1),
            "min_hbm_mb": round(bts / 1e6, 1),
            "hbm_bound_floor_ms": round(bts / PEAK_HBM * 1e3, 3),
            "flops_bound_floor_ms": round(f / PEAK_FLOPS * 1e3, 3),
            "noisy": bool(noisy),
        })
    full_ms = prefix_ms[-1][0]
    early_ms = rows[0]["ms"] + rows[1]["ms"]
    early_f = analytics["stem"][0] + analytics["stage1"][0]
    late_ms = sum(r["ms"] for r in rows[2:5])
    late_f = sum(analytics[n][0] for n in ("stage2", "stage3", "stage4"))
    out = {
        "batch": BATCH,
        "device_ms_per_batch": round(full_ms, 3),
        "img_s": round(BATCH / (full_ms / 1e3), 1),
        "overall_mfu_pct": round(
            100 * total_flops / (full_ms / 1e3) / PEAK_FLOPS, 1
        ),
        # Coarse split — stable across runs where single segments
        # jitter: the sub-128-channel region (stem + stage1, 56x56
        # maps with <=64-wide contractions that under-tile the 128x128
        # MXU) vs the wide stages.
        "early_stem_stage1": {
            "ms": round(early_ms, 3),
            "share_pct": round(100 * early_ms / full_ms, 1),
            "mfu_pct": round(
                100 * early_f / max(early_ms / 1e3, 1e-9) / PEAK_FLOPS, 1
            ),
        },
        "late_stage2_4": {
            "ms": round(late_ms, 3),
            "share_pct": round(100 * late_ms / full_ms, 1),
            "mfu_pct": round(
                100 * late_f / max(late_ms / 1e3, 1e-9) / PEAK_FLOPS, 1
            ),
        },
        "segments": rows,
        "note": (
            "segment ms = cumulative-prefix differencing of the real "
            "served forward; floors = analytic bytes/FLOPs over v5e "
            "peaks.  CAVEAT: truncating the graph at a segment "
            "boundary changes XLA fusion, so SINGLE segment times "
            "jitter between runs (a >100% segment MFU = neighboring "
            "time mis-attributed to it); the early/late split, the "
            "overall MFU, and 'early runs far below late' are the "
            "stable findings"
        ),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
