"""Device-only benchmark: engine.run_batch with no HTTP, plus an
isolated-compute measurement and an MFU estimate.

Round-1 verdict: end-to-end req/s through the ~100 ms-RTT relay says
nothing about how busy the chip is.  This module produces the numbers
that do:

- ``device_batch_ms`` / ``device_img_s`` — pure device compute per
  batch, isolated from the relay by scanning K forwards inside ONE
  executable: wall = K x device_time + 1 round-trip, so
  device_time = (wall - rtt) / K.  The scan carries a scalar data
  dependency through every iteration so the loop cannot be collapsed.
- ``pipelined_img_s`` — engine.run_batch driven from pipeline_depth
  threads (the serving hot path minus HTTP): includes wire transfer,
  overlapped like production.
- ``mfu_pct`` — model FLOPs x achieved img/s / chip peak.  FLOPs come
  from XLA's own cost analysis when available (exact for the compiled
  module), else an analytic ResNet-50 estimate.  Peak defaults to a
  v5e's 197 bf16 TFLOP/s; override with PEAK_TFLOPS for other chips.
"""

from __future__ import annotations

import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SCAN_ITERS = int(os.environ.get("SCAN_ITERS", "16"))
PIPELINE_BATCHES = int(os.environ.get("PIPELINE_BATCHES", "24"))
# Forward FLOPs per 224x224 image.  The canonical "4.1 GFLOPs"
# ResNet-50 figure counts multiply-accumulates as ONE op; in the
# 2-ops-per-MAC convention every MFU definition uses (peak TFLOP/s
# counts multiplies AND adds), the forward is ~8.2e9.  Three
# independent sources agree: XLA cost analysis reports 7.9e9, a
# per-layer analytic count over the v1.5 graph gives 8.18e9
# (benchmarks/resnet_profile.py), and 2 x 4.09 GMACs = 8.18e9.
# Rounds 2-4 used 4.09e9 here (the MAC count mislabeled as FLOPs),
# halving every reported ResNet MFU — the "28%" plateau was an
# accounting artifact, not a hardware ceiling.
RESNET50_ANALYTIC_FLOPS = 8.18e9


def measure_rtt(reps: int = 5) -> float:
    """Median wall time of a minimal dispatch+fetch round-trip."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((), jnp.float32)
    float(jax.device_get(f(x)))  # compile
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(jax.device_get(f(x)))
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2]


def flops_per_image(forward, params, images) -> float:
    """XLA cost analysis of the compiled forward, per image; analytic
    ResNet-50 fallback when the backend doesn't report flops."""
    import jax

    try:
        compiled = jax.jit(forward).lower(params, images).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, list):  # some backends return [dict]
            analysis = analysis[0]
        flops = float(analysis["flops"])
        if flops > 0:
            return flops / images.shape[0]
    except Exception:
        pass
    return RESNET50_ANALYTIC_FLOPS


def bench_device(engine, batch: int = 32) -> dict:
    """All device-side numbers for an image-model engine."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    bundle = engine.bundle
    size = bundle.image_size
    rng = np.random.default_rng(0)
    images = rng.integers(0, 255, (batch, size, size, 3), dtype=np.uint8)
    feats = [{"image": images[i]} for i in range(batch)]

    # -- pipelined serving path (run_batch from N threads, like prod) --
    engine.run_batch(feats)  # compile + first transfer
    depth = engine._lock._value if hasattr(engine._lock, "_value") else 4
    pool = ThreadPoolExecutor(max_workers=max(1, depth))
    t0 = time.perf_counter()
    futs = [pool.submit(engine.run_batch, feats) for _ in range(PIPELINE_BATCHES)]
    for f in futs:
        f.result()
    pipelined_wall = time.perf_counter() - t0
    pool.shutdown()
    pipelined_img_s = PIPELINE_BATCHES * batch / pipelined_wall

    # -- isolated device compute: K forwards in ONE executable --------
    # Two scan lengths (K and 2K): device time = (wall_2K - wall_K) / K,
    # so the per-dispatch round-trip cancels exactly instead of being
    # subtracted from a separately-sampled (and ±10 ms jittery) RTT.
    params, forward = engine.params, bundle.forward

    def make_scan(n_iters: int):
        def scan_k(p, imgs):
            def body(carry, _):
                # carry perturbs the input by exactly 0 — a data
                # dependency XLA must honor, so iterations cannot be
                # collapsed, while values stay identical to forward().
                logits = forward(p, imgs + (carry * 0).astype(imgs.dtype))
                return logits.astype(jnp.float32).ravel()[0], ()

            carry, _ = lax.scan(body, jnp.float32(0), None, length=n_iters)
            return carry

        return jax.jit(scan_k)

    def median_wall(jit_fn, args, reps: int = 3) -> float:
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            float(jax.device_get(jit_fn(*args)))
            times.append(time.perf_counter() - t0)
        return sorted(times)[len(times) // 2]

    dev_images = jax.device_put(images)
    scan1, scan2 = make_scan(SCAN_ITERS), make_scan(2 * SCAN_ITERS)
    float(jax.device_get(scan1(params, dev_images)))  # compile
    float(jax.device_get(scan2(params, dev_images)))
    rtt = measure_rtt()
    w1 = median_wall(scan1, (params, dev_images))
    w2 = median_wall(scan2, (params, dev_images))
    noisy = w2 <= w1
    if noisy:  # relay jitter swamped the signal; fall back, flagged
        device_batch_s = max(w1 - rtt, 0.1 * w1) / SCAN_ITERS
    else:
        device_batch_s = (w2 - w1) / SCAN_ITERS
    device_img_s = batch / device_batch_s

    xla_flops = flops_per_image(forward, params, images)
    # Headline MFU uses the LOWER of XLA's cost analysis (7.9e9/img)
    # and the analytic 2-ops-per-MAC count (8.18e9) — both in the same
    # convention as the 197 TFLOP/s peak, so the ratio is honest.
    # (Rounds 2-4 divided by the 4.09e9 MAC count instead, reporting
    # half the real utilization; see RESNET50_ANALYTIC_FLOPS.)
    flops = (
        min(xla_flops, RESNET50_ANALYTIC_FLOPS)
        if bundle.name.startswith("resnet")
        else xla_flops
    )
    peak = float(os.environ.get("PEAK_TFLOPS", "197")) * 1e12
    return {
        "device_batch_ms": round(device_batch_s * 1000, 3),
        "device_img_s": round(device_img_s, 1),
        "pipelined_img_s": round(pipelined_img_s, 1),
        "rtt_ms": round(rtt * 1000, 1),
        "flops_per_img": round(flops),
        "flops_per_img_xla": round(xla_flops),
        "mfu_pct": round(100.0 * flops * device_img_s / peak, 2),
        "peak_tflops": peak / 1e12,
        "timing_noisy": noisy,
    }


def _peak_flops() -> float:
    return float(os.environ.get("PEAK_TFLOPS", "197")) * 1e12


def bench_text_device(engine, batch: int = 32, seq: int = 128) -> dict:
    """Device-isolated forward timing + tokens/s + MFU for a text
    classifier (bert-base / bert-long): the per-model numbers the
    round-2 verdict said only ResNet had."""
    import jax

    from timing import device_time_per_call

    bundle = engine.bundle
    params, forward = engine.params, bundle.forward
    ids = jnp.asarray(np.ones((batch, seq), np.int32))
    mask = jnp.asarray(np.ones((batch, seq), np.int32))

    per_call, noisy = device_time_per_call(
        forward, (params, ids, mask), carry_idx=1, iters=SCAN_ITERS
    )
    tokens_s = batch * seq / per_call

    # FLOPs from XLA's own cost analysis of the exact compiled module;
    # analytic 2*N*tokens fallback.  This is one extra compile per
    # bench run (the timing scans can't expose their cost analysis);
    # the persistent compile cache absorbs it on re-runs.
    from mlmicroservicetemplate_tpu.models.common import count_params

    n_params = count_params(params)
    try:
        analysis = jax.jit(forward).lower(params, ids, mask).compile().cost_analysis()
        if isinstance(analysis, list):
            analysis = analysis[0]
        flops_batch = float(analysis["flops"])
        assert flops_batch > 0
    except Exception:
        flops_batch = 2.0 * n_params * batch * seq
    peak = _peak_flops()
    return {
        "model": bundle.name, "batch": batch, "seq": seq,
        "device_batch_ms": round(per_call * 1000, 3),
        "device_tokens_s": round(tokens_s),
        "mfu_pct": round(100.0 * flops_batch / per_call / peak, 2),
        "flops_per_batch_xla": round(flops_batch),
        "n_params": n_params,
        "timing_noisy": noisy,
        "peak_tflops": peak / 1e12,
    }


def bench_generative_device(engine, prompt_len: int = 64,
                            batches=(1, 8)) -> dict:
    """Decode-side device numbers for seq2seq / causal-LM models:
    per-step ms, aggregate decode tokens/s, decode MFU (weight-streaming
    2*N FLOPs/token — the conservative convention), and the fused
    prefill+first-chunk wall (TTFT proxy; includes one RTT)."""
    import time as _time

    import jax

    from timing import chunked_time_per_step

    from mlmicroservicetemplate_tpu.models.common import count_params

    bundle = engine.bundle
    n_params = count_params(engine.params)
    peak = _peak_flops()
    # A fresh, non-donating jit: the timing helper re-decodes from the
    # same state, which donation would invalidate.
    chunk_fn = jax.jit(bundle.generate_chunk_fn, static_argnums=(2, 3))
    out: dict = {"model": bundle.name, "prompt_len": prompt_len,
                 "n_params": n_params, "peak_tflops": peak / 1e12}

    for b in batches:
        feats = [{"input_ids": np.ones(prompt_len, np.int32),
                  "length": np.int32(prompt_len)}] * b
        ids, mask, _ = engine._collate_text(feats)
        sp, _ = engine._collate_sample(feats, ids.shape[0])
        ids, mask = engine.replicas.place_batch(ids, mask)
        # Fused prefill+first-chunk (the TTFT dispatch). Wall includes
        # ONE round-trip — reported as-is, labeled.
        state, toks = engine._start(
            engine.params, ids, mask, sp,
            engine.max_decode_len, engine.chunk_tokens, False,
        )
        jax.device_get(toks)
        walls = []
        for _ in range(3):
            t0 = _time.perf_counter()
            state, toks = engine._start(
                engine.params, ids, mask, sp,
                engine.max_decode_len, engine.chunk_tokens, False,
            )
            jax.device_get(toks)
            walls.append(_time.perf_counter() - t0)
        prefill_wall = sorted(walls)[len(walls) // 2]

        def run_chunk(p, s, n, _fn=chunk_fn):
            return _fn(p, s, n, False)

        per_step, noisy = chunked_time_per_step(
            run_chunk, engine.params, state, iters=16
        )
        bsz = ids.shape[0]
        out[f"b{b}"] = {
            "decode_step_ms": round(per_step * 1000, 3),
            "decode_tokens_s": round(bsz / per_step, 1),
            "decode_mfu_pct": round(
                100.0 * 2.0 * n_params * bsz / per_step / peak, 2
            ),
            "prefill_first_chunk_wall_ms": round(prefill_wall * 1000, 1),
            "timing_noisy": noisy,
        }
    return out


def main() -> None:
    import json

    from mlmicroservicetemplate_tpu.engine import InferenceEngine
    from mlmicroservicetemplate_tpu.models.registry import (
        KIND_IMAGE,
        KIND_TEXT,
        build_model,
    )
    from mlmicroservicetemplate_tpu.runtime.device import apply_device_env
    from mlmicroservicetemplate_tpu.utils.config import ServiceConfig

    model = os.environ.get("MODEL_NAME", "resnet50")
    seq = int(os.environ.get("BENCH_SEQ", "128"))
    overrides = {"model_name": model, "warmup": False,
                 "batch_buckets": (1, 8, 32), "seq_buckets": (seq,),
                 "max_decode_len": int(os.environ.get("BENCH_DECODE_LEN", "64"))}
    if os.environ.get("DEVICE"):
        overrides["device"] = os.environ["DEVICE"]
    if os.environ.get("QUANTIZE"):
        overrides["quantize"] = os.environ["QUANTIZE"]
    cfg = ServiceConfig(**overrides)
    apply_device_env(cfg.device)
    bundle = build_model(cfg)
    engine = InferenceEngine(bundle, cfg)
    if bundle.kind == KIND_IMAGE:
        print(json.dumps(bench_device(engine)))
    elif bundle.kind == KIND_TEXT:
        print(json.dumps(bench_text_device(engine, seq=seq)))
    else:
        print(json.dumps(bench_generative_device(
            engine, prompt_len=min(seq, 64))))


if __name__ == "__main__":
    main()
