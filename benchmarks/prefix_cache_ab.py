"""Per-request prefix cache A/B (PREFIX_CACHE, VERDICT r3 item 4).

Measures the TTFT dispatch (fused prefill+first-chunk) device time for
a prompt whose first P tokens are cached vs the same prompt prefilled
in full — the per-request generalization of round 3's PROMPT_PREFIX
table (which measured 1.52× at llama-1.1B with a 768-token prefix).
Two-scan-length differencing (timing.py): relay RTT cancels exactly.

    MODEL_NAME=llama PREFIX_TOKENS=512 python benchmarks/prefix_cache_ab.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

PREFIX_TOKENS = int(os.environ.get("PREFIX_TOKENS", "512"))
SUFFIX_TOKENS = int(os.environ.get("SUFFIX_TOKENS", "16"))


def main() -> None:
    from mlmicroservicetemplate_tpu.engine import InferenceEngine
    from mlmicroservicetemplate_tpu.models.registry import build_model
    from mlmicroservicetemplate_tpu.parallel import ReplicaSet, make_mesh
    from mlmicroservicetemplate_tpu.runtime.device import apply_device_env
    from mlmicroservicetemplate_tpu.utils.config import ServiceConfig

    import jax

    from timing import device_time_per_call

    cfg = ServiceConfig(
        device=os.environ.get("DEVICE", "tpu"),
        model_name=os.environ.get("MODEL_NAME", "llama"),
        quantize=os.environ.get("QUANTIZE") or None,
        warmup=False,
        batch_buckets=(1,),
        seq_buckets=(32, PREFIX_TOKENS, PREFIX_TOKENS + 32),
        max_decode_len=16,
        stream_chunk_tokens=4,
        prefix_cache=True,
        continuous_batching=False,
    )
    apply_device_env(cfg)
    bundle = build_model(cfg)
    eng = InferenceEngine(bundle, cfg, ReplicaSet(make_mesh(1)))
    rng = np.random.default_rng(0)
    vocab = bundle.cfg.vocab_size
    ids = rng.integers(5, vocab, PREFIX_TOKENS + SUFFIX_TOKENS).astype(np.int32)
    feats = {"input_ids": ids, "length": np.int32(len(ids))}

    # Request 1: miss — donates tokens[:PREFIX_TOKENS] to the cache.
    for _ in eng.generate_stream(dict(feats)):
        pass
    m = eng.prefix_cache.match(ids, len(ids))
    assert m is not None and m[0] == PREFIX_TOKENS, eng.prefix_cache.stats()
    p_len, pkv = m

    # Collated shapes for both paths.
    sfeats = dict(feats, input_ids=ids[p_len:], length=np.int32(len(ids) - p_len))
    s_ids, s_mask, _ = eng._collate_text([sfeats])
    sp, _ = eng._collate_sample([sfeats], s_ids.shape[0])
    s_ids, s_mask = eng.replicas.place_batch(s_ids, s_mask)
    f_ids, f_mask, _ = eng._collate_text([feats])
    fsp, _ = eng._collate_sample([feats], f_ids.shape[0])
    f_ids, f_mask = eng.replicas.place_batch(f_ids, f_mask)

    def hit_fn(p, pk, i, mk):
        _, toks = eng.bundle.generate_chunk_fn(
            p, eng.bundle.init_state_fn(
                dict(p, __prefix__=pk), eng.bundle.encode_fn(
                    dict(p, __prefix__=pk), i, mk
                ), mk, eng.max_decode_len, sample=sp,
            ), eng.chunk_tokens, False,
        )
        return toks

    def miss_fn(p, i, mk):
        _, toks = eng.bundle.generate_chunk_fn(
            p, eng.bundle.init_state_fn(
                p, eng.bundle.encode_fn(p, i, mk), mk,
                eng.max_decode_len, sample=fsp,
            ), eng.chunk_tokens, False,
        )
        return toks

    iters = int(os.environ.get("SCAN_ITERS", "8"))
    hit_s, hit_noisy = device_time_per_call(
        hit_fn, (eng.params, pkv, s_ids, s_mask), carry_idx=2, iters=iters
    )
    miss_s, miss_noisy = device_time_per_call(
        miss_fn, (eng.params, f_ids, f_mask), carry_idx=1, iters=iters
    )
    print(json.dumps({
        "model": bundle.name,
        "quantize": cfg.quantize,
        "prefix_tokens": PREFIX_TOKENS,
        "suffix_tokens": SUFFIX_TOKENS,
        "ttft_dispatch_full_prefill_ms": round(miss_s * 1e3, 3),
        "ttft_dispatch_cached_prefix_ms": round(hit_s * 1e3, 3),
        "timing_noisy": bool(hit_noisy or miss_noisy),
        "speedup": round(miss_s / max(hit_s, 1e-12), 3),
        "cache": eng.prefix_cache.stats(),
    }))


if __name__ == "__main__":
    main()
