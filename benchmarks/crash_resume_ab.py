"""Crash-resume A/B: journal-on vs journal-off recovery goodput, plus
the fsync-policy overhead of the write-ahead journal.

The judged claims (ISSUE 10):

1. **Recovery**: a server with ``JOURNAL_DIR`` that is SIGKILLed
   mid-traffic loses ZERO streams — every in-flight request finishes
   token-identically through the restart + reconnect path — where the
   journal-off server loses everything in flight (clients must
   resubmit from scratch).  Reported: streams recovered/lost, recovery
   goodput (delivered tokens / wall including the restart), and the
   wall itself.
2. **Overhead**: the journal's steady-state cost by fsync policy
   (``always`` pays one fsync per delivery chunk, ``interval``
   amortizes to ≤20/s, ``off`` is page-cache-only) vs no journal at
   all.  Reported: aggregate tokens/s per policy.

Both phases run a REAL server subprocess (tiny-dims llama via
``LLAMA_CONFIG`` so the arms measure journal mechanics, not model
compute) on the current backend.

    python benchmarks/crash_resume_ab.py              # current backend
    DEVICE=cpu python benchmarks/crash_resume_ab.py   # CPU sanity run

One JSON line per arm to stdout, a markdown table to stderr.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

N_STREAMS = int(os.environ.get("CRASH_AB_N", "4"))
DECODE_LEN = int(os.environ.get("CRASH_AB_DECODE", "24"))
OVERHEAD_ROUNDS = int(os.environ.get("CRASH_AB_ROUNDS", "3"))

LLAMA_CFG = json.dumps({
    "vocab_size": 300, "d_model": 32, "num_heads": 4, "num_kv_heads": 2,
    "num_layers": 2, "d_ff": 64, "max_position": 256,
})

PROMPT = "the quick brown fox jumps over the lazy dog"


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def server_env(port: int, jdir: str | None, fsync: str = "always") -> dict:
    env = dict(os.environ)
    env.update({
        "DEVICE": os.environ.get("DEVICE", "cpu"),
        "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
        "WARMUP": "0", "MODEL_NAME": "llama", "LLAMA_CONFIG": LLAMA_CFG,
        "HOST": "127.0.0.1", "PORT": str(port),
        "SEQ_BUCKETS": "16,32", "BATCH_BUCKETS": "1,2,4",
        "MAX_DECODE_LEN": str(DECODE_LEN), "STREAM_CHUNK_TOKENS": "4",
        "MAX_STREAMS": "8", "MAX_STREAM_QUEUE": "8",
        # Chunked prefill keeps prompts past the largest bucket on the
        # continuous loop (the legacy per-stream path does not
        # journal); REPLICAS=1 because a driving pytest/harness env may
        # carry a multi-device XLA_FLAGS.
        "PREFILL_CHUNK": "16", "KV_BLOCK_SIZE": "8", "PAGED_KV": "1",
        "REPLICAS": "1",
        "LOG_LEVEL": "WARNING", "JOURNAL_FSYNC": fsync,
    })
    env.pop("XLA_FLAGS", None)
    env.pop("JOURNAL_DIR", None)
    if jdir:
        env["JOURNAL_DIR"] = jdir
    return env


def start(port: int, jdir: str | None, fsync: str = "always"):
    return subprocess.Popen(
        [sys.executable, "-m", "mlmicroservicetemplate_tpu.serve"],
        env=server_env(port, jdir, fsync),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def wait_ready(port: int, timeout: float = 180.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/readyz", timeout=2
            ) as r:
                if r.status == 200:
                    return
        except Exception:
            pass
        time.sleep(0.25)
    raise RuntimeError("server never became ready")


def stream_once(port: int, rid: str, stop_after: int | None = None):
    """POST /predict stream=true; returns (delta_lines, final|None)."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict",
        data=json.dumps({"text": PROMPT + f" {rid}", "stream": True}).encode(),
        headers={"Content-Type": "application/json", "X-Request-Id": rid},
    )
    deltas, final = [], None
    with urllib.request.urlopen(req, timeout=300) as r:
        for raw in r:
            ev = json.loads(raw.decode())
            if ev.get("done"):
                final = ev
                break
            deltas.append(ev.get("delta", ""))
            if stop_after is not None and len(deltas) >= stop_after:
                break
    return deltas, final


def reconnect(port: int, rid: str, timeout: float = 60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/streams/{rid}", timeout=300
            ) as r:
                return [json.loads(x.decode()) for x in r]
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise
            time.sleep(0.5)
    return None


def recovery_arm(journal: bool) -> dict:
    """SIGKILL mid-traffic; count completions across the restart."""
    jdir = tempfile.mkdtemp(prefix="crash_ab_") if journal else None
    port = free_port()
    p = start(port, jdir)
    t0 = time.monotonic()
    try:
        wait_ready(port)
        # The victims: read 2 chunks each, then kill.  (Token identity
        # itself is the chaos test's assertion — tests/test_durability
        # ::test_crash_smoke; this arm measures the recovery ledger.)
        partials: dict[str, str] = {}
        for i in range(N_STREAMS):
            rid = f"s{i}"
            try:
                deltas, _ = stream_once(port, rid, stop_after=2)
                partials[rid] = "".join(deltas)
            except Exception:
                partials[rid] = ""
        os.kill(p.pid, signal.SIGKILL)
        p.wait(timeout=60)
        t_kill = time.monotonic()
        recovered = lost = 0
        chars = 0
        if journal:
            port2 = free_port()
            p2 = start(port2, jdir)
            try:
                wait_ready(port2)
                for i in range(N_STREAMS):
                    rid = f"s{i}"
                    lines = reconnect(port2, rid)
                    if not lines or not lines[-1].get("done"):
                        lost += 1
                        continue
                    text = "".join(
                        ev.get("delta", "") for ev in lines[:-1]
                    )
                    if text.startswith(partials[rid]):
                        recovered += 1
                        chars += len(text)
                    else:
                        lost += 1
            finally:
                p2.terminate()
                p2.wait(timeout=30)
        else:
            # No journal: everything in flight at the kill is gone.
            lost = N_STREAMS
        wall = time.monotonic() - t_kill
        return {
            "arm": "journal" if journal else "no_journal",
            "streams": N_STREAMS,
            "recovered": recovered,
            "lost": lost,
            "recovery_wall_s": round(wall, 2),
            "recovered_chars_per_s": round(chars / max(wall, 1e-9), 2),
            "total_wall_s": round(time.monotonic() - t0, 2),
        }
    finally:
        if p.poll() is None:
            p.terminate()
            p.wait(timeout=30)


def overhead_arm(policy: str | None) -> dict:
    """Steady-state serving throughput under one fsync policy (None =
    journal off entirely)."""
    jdir = (
        tempfile.mkdtemp(prefix="crash_ab_ov_") if policy is not None
        else None
    )
    port = free_port()
    p = start(port, jdir, fsync=policy or "always")
    try:
        wait_ready(port)
        stream_once(port, "warm")  # absorb first-request compiles
        t0 = time.monotonic()
        toks = 0
        for r in range(OVERHEAD_ROUNDS):
            for i in range(N_STREAMS):
                _, fin = stream_once(port, f"ov-{policy}-{r}-{i}")
                toks += int(fin["tokens_generated"]) or DECODE_LEN
        wall = time.monotonic() - t0
        return {
            "arm": f"fsync={policy}" if policy else "journal_off",
            "streams": OVERHEAD_ROUNDS * N_STREAMS,
            "tokens": toks,
            "tokens_per_s": round(toks / max(wall, 1e-9), 2),
            "wall_s": round(wall, 2),
        }
    finally:
        p.terminate()
        p.wait(timeout=30)


def main() -> None:
    rows = []
    print("== recovery: SIGKILL mid-traffic ==", file=sys.stderr)
    for journal in (True, False):
        r = recovery_arm(journal)
        rows.append(r)
        print(json.dumps(r))
    print("== overhead: fsync policy ==", file=sys.stderr)
    for policy in (None, "off", "interval", "always"):
        r = overhead_arm(policy)
        rows.append(r)
        print(json.dumps(r))
    print("\n| arm | recovered | lost | rec wall s | tok/s |", file=sys.stderr)
    print("|---|---|---|---|---|", file=sys.stderr)
    for r in rows:
        print(
            f"| {r['arm']} | {r.get('recovered', '-')} "
            f"| {r.get('lost', '-')} | {r.get('recovery_wall_s', '-')} "
            f"| {r.get('tokens_per_s', '-')} |",
            file=sys.stderr,
        )


if __name__ == "__main__":
    main()
