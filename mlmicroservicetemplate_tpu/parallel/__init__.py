"""Device-mesh data-parallel serving — the TPU-native answer to the
reference's NCCL-broadcast ``torch.nn.DataParallel`` (BASELINE.json:5).

Instead of a driver GPU broadcasting replicated weights and scattering
sub-batches over NCCL, we build a ``jax.sharding.Mesh`` over the visible
TPU cores, place params once with a fully-replicated ``NamedSharding``,
and shard the batch axis across the ``replica`` mesh axis.  XLA compiles
the scatter/gather into the executable as ICI collectives — there is no
hand-written communication layer (SURVEY.md §5 "Distributed
communication backend").
"""

from .mesh import (  # noqa: F401
    ReplicaSet,
    SeqParallelSet,
    TensorParallelSet,
    make_mesh,
    make_replica_sp_mesh,
    make_replica_tp_mesh,
    make_sp_mesh,
)
