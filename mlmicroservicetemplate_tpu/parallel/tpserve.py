"""Serving-side tensor-parallel helpers (ROADMAP item 1).

``parallel/tp.py`` owns the Megatron layout rules (column-parallel
q/k/v + mlp-up, row-parallel attn-out + mlp-down) as PartitionSpec
pytrees; ``parallel/mesh.py`` owns the placement objects.  This module
is the small trace-time surface the REST of the serving stack needs:

- ``serving_tp_mesh(tp)`` — the cached ``('replica','tp')`` mesh an
  ops-level ``shard_map`` wrapper reconstructs at trace time from the
  STATIC tp width in the model config (model fns are pure; they cannot
  reach the engine's placement object, but the mesh over the first
  ``tp`` visible devices is deterministic and identical to the one
  ``make_replica_tp_mesh(tp, 1)`` built for the engine).  Multi-chip
  fleets place TP groups on NON-prefix device sets (replica 1 on
  devices (2,3), …): the fleet's executables run under
  ``use_trace_group`` (runtime/compile_cache.py wraps every shared
  executable), and ``serving_tp_mesh`` consults that thread-local so a
  trace on replica 1's thread reconstructs the mesh over replica 1's
  OWN devices.  The default (prefix) group normalizes to the original
  cache key, so single-group serving stays byte-identical.
- ``device_group(placement)`` — a placement's global device-id tuple
  (None for single-device and default-prefix placements), the value
  the executable proxies feed ``use_trace_group``.
- ``kv_head_spec(paged)`` — the one KV-cache layout rule: every cache
  leaf (contiguous ``[B, S, H, D]`` slab, pool ``[NB, BS, H, D]``
  block, or int8 scale ``[..., H]``) shards its HEADS axis (axis 2)
  over 'tp'.  Block ids, tables, free-lists and refcounts never see a
  device axis — the pool stays one logical pool with one ledger.
- ``placement_fingerprint(placement)`` — a short stable string naming
  the mesh topology + param layout, mixed into the executable-cache
  and autotuner keys so TP executables can never alias single-device
  (or differently-laid-out) ones.

TP=1 (the default) calls NONE of this: no mesh object is built
anywhere, pinned by ``tests/test_tp_serving.py``.
"""

from __future__ import annotations

import threading

_MESH_CACHE: dict = {}
_LOCK = threading.Lock()

# Thread-local device group for trace-time mesh reconstruction.  The
# fleet's executable proxies (runtime/compile_cache._CostedExecutable)
# set this around every call/lower so model-fn shard_maps traced on a
# non-prefix replica rebuild the mesh over THAT replica's devices.
# Thread-local (not a plain global) because the watchdog runs dispatches
# on fresh daemon threads and two replicas may trace concurrently.
_TRACE_GROUP = threading.local()


def current_trace_group():
    """The device-id tuple the current thread is tracing for, or None
    (default prefix placement)."""
    return getattr(_TRACE_GROUP, "group", None)


class use_trace_group:
    """Context manager pinning ``current_trace_group()`` for this
    thread.  ``use_trace_group(None)`` is a no-op (keeps the hot
    single-group path free of save/restore churn)."""

    __slots__ = ("_group", "_prev")

    def __init__(self, group):
        self._group = tuple(group) if group else None
        self._prev = None

    def __enter__(self):
        if self._group is not None:
            self._prev = getattr(_TRACE_GROUP, "group", None)
            _TRACE_GROUP.group = self._group
        return self

    def __exit__(self, *exc):
        if self._group is not None:
            _TRACE_GROUP.group = self._prev
        return False


def _normalize_group(group, need: int):
    """Collapse the default-prefix group to None so prefix placements
    keep the original (tp, replicas) cache key and mesh object."""
    if group is None:
        return None
    group = tuple(int(g) for g in group)
    if group == tuple(range(need)):
        return None
    return group


def serving_tp_mesh(tp: int, replicas: int = 1, group=None):
    """Cached ``('replica','tp')`` mesh over ``replicas*tp`` devices —
    bit-identical (compares/hashes equal) to the engine placement's
    mesh, so a ``shard_map`` traced against it composes with operands
    committed by ``TensorParallelSet``.

    ``group`` names the global device ids to build over (defaults to
    the current thread's trace group, else the visible-device prefix).
    The prefix group normalizes away so single-group serving reuses the
    exact pre-multichip mesh objects and cache keys."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    need = int(tp) * int(replicas)
    if group is None:
        group = current_trace_group()
    group = _normalize_group(group, need)
    key = (int(tp), int(replicas)) if group is None else (
        int(tp), int(replicas), group)
    with _LOCK:
        mesh = _MESH_CACHE.get(key)
        if mesh is None:
            devs = jax.devices()
            if group is not None and len(group) != need:
                raise ValueError(
                    f"device group {group} has {len(group)} devices, "
                    f"TP={tp} x replicas={replicas} needs {need}"
                )
            if need > len(devs) or (
                group is not None and max(group) >= len(devs)
            ):
                raise ValueError(
                    f"TP={tp} x replicas={replicas} needs {need} devices, "
                    f"only {len(devs)} visible"
                )
            picked = devs[:need] if group is None else [
                devs[i] for i in group]
            mesh = Mesh(
                np.array(picked).reshape(int(replicas), int(tp)),
                ("replica", "tp"),
            )
            _MESH_CACHE[key] = mesh
    return mesh


def device_group(placement):
    """Global device-id tuple of a TP placement, for trace-group
    pinning.  None for single-device placements, for plain DP meshes
    (no ``param_spec`` — they never reconstruct a serving mesh), and
    for the default prefix group (normalized so pre-multichip cache
    keys stay byte-identical)."""
    try:
        mesh = getattr(placement, "mesh", None)
        if mesh is None or getattr(placement, "param_spec", None) is None:
            return None
        ids = tuple(int(d.id) for d in mesh.devices.flat)
    except Exception:
        return None
    if len(ids) <= 1:
        return None
    return _normalize_group(ids, len(ids))


def kv_head_spec(paged: bool, ndim: int = 4):
    """PartitionSpec for one KV-cache leaf: heads axis (2) over 'tp'.

    Contiguous slabs additionally shard their batch axis (0) over
    'replica'; pool leaves must NOT (axis 0 is the block id space —
    device-agnostic by contract, and PAGED_KV pins REPLICAS=1)."""
    from jax.sharding import PartitionSpec as P

    lead = None if paged else "replica"
    tail = [None] * max(0, ndim - 3)
    return P(lead, None, "tp", *tail)


def placement_fingerprint(placement) -> str:
    """Stable short name of a placement's mesh topology + param layout
    for cache keying.  "" for plain single-mesh replica placements
    (keeps every pre-TP cache/autotune key byte-identical)."""
    mesh = getattr(placement, "mesh", None)
    if mesh is None:
        return ""
    try:
        axes = ",".join(f"{a}{int(n)}" for a, n in mesh.shape.items())
    except Exception:
        return ""
    spec = getattr(placement, "param_spec", None)
    if spec is None and axes in ("replica1", ""):
        return ""  # degenerate 1-device DP mesh == no placement axis
    tag = type(placement).__name__
    if spec is not None:
        import hashlib

        import jax
        from jax.sharding import PartitionSpec

        leaves = jax.tree.leaves(
            spec, is_leaf=lambda x: isinstance(x, PartitionSpec)
        )
        digest = hashlib.sha1(
            "|".join(str(s) for s in leaves).encode()
        ).hexdigest()[:10]
        return f"{tag}({axes})#{digest}"
    return f"{tag}({axes})"


def collective_probe(mesh, d_model: int, dtype="float32") -> dict:
    """Measured ICI collective latency over the serving mesh, per op —
    feeds ``tp_collective_seconds{op}`` at warm time (the serve path
    cannot separate collective from compute inside one executable, so
    the series reports a calibrated per-op probe, re-measured at every
    warm; docs/tensor-parallel.md documents the semantics)."""
    import time

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    tp = int(mesh.shape.get("tp", 1))
    if tp <= 1:
        return {}
    x = jnp.ones((max(1, d_model // tp), max(8, d_model)), dtype)
    xs = jax.device_put(x, NamedSharding(mesh, P("tp", None)))

    from jax.experimental.shard_map import shard_map

    # check_rep=False: the static replication checker cannot infer
    # out-replication over 'tp' for these one-op bodies on a 2-D mesh;
    # the probe is a timing harness, not a correctness surface.
    psum = jax.jit(shard_map(
        lambda v: jax.lax.psum(v, "tp"), mesh=mesh,
        in_specs=P("tp", None), out_specs=P(None, None),
        check_rep=False,
    ))
    gather = jax.jit(shard_map(
        lambda v: jax.lax.all_gather(v, "tp", axis=0, tiled=True),
        mesh=mesh, in_specs=P("tp", None), out_specs=P(None, None),
        check_rep=False,
    ))
    out = {}
    for op, fn in (("all_reduce", psum), ("all_gather", gather)):
        jax.block_until_ready(fn(xs))  # compile + warm outside the clock
        t0 = time.perf_counter()
        for _ in range(3):
            jax.block_until_ready(fn(xs))
        out[op] = (time.perf_counter() - t0) / 3.0
    return out
