"""Serving-side tensor-parallel helpers (ROADMAP item 1).

``parallel/tp.py`` owns the Megatron layout rules (column-parallel
q/k/v + mlp-up, row-parallel attn-out + mlp-down) as PartitionSpec
pytrees; ``parallel/mesh.py`` owns the placement objects.  This module
is the small trace-time surface the REST of the serving stack needs:

- ``serving_tp_mesh(tp)`` — the cached ``('replica','tp')`` mesh an
  ops-level ``shard_map`` wrapper reconstructs at trace time from the
  STATIC tp width in the model config (model fns are pure; they cannot
  reach the engine's placement object, but the mesh over the first
  ``tp`` visible devices is deterministic and identical to the one
  ``make_replica_tp_mesh(tp, 1)`` built for the engine).
- ``kv_head_spec(paged)`` — the one KV-cache layout rule: every cache
  leaf (contiguous ``[B, S, H, D]`` slab, pool ``[NB, BS, H, D]``
  block, or int8 scale ``[..., H]``) shards its HEADS axis (axis 2)
  over 'tp'.  Block ids, tables, free-lists and refcounts never see a
  device axis — the pool stays one logical pool with one ledger.
- ``placement_fingerprint(placement)`` — a short stable string naming
  the mesh topology + param layout, mixed into the executable-cache
  and autotuner keys so TP executables can never alias single-device
  (or differently-laid-out) ones.

TP=1 (the default) calls NONE of this: no mesh object is built
anywhere, pinned by ``tests/test_tp_serving.py``.
"""

from __future__ import annotations

import threading

_MESH_CACHE: dict = {}
_LOCK = threading.Lock()


def serving_tp_mesh(tp: int, replicas: int = 1):
    """Cached ``('replica','tp')`` mesh over the first ``replicas*tp``
    visible devices — bit-identical (compares/hashes equal) to the
    engine placement's mesh, so a ``shard_map`` traced against it
    composes with operands committed by ``TensorParallelSet``."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    key = (int(tp), int(replicas))
    with _LOCK:
        mesh = _MESH_CACHE.get(key)
        if mesh is None:
            need = key[0] * key[1]
            devs = jax.devices()
            if need > len(devs):
                raise ValueError(
                    f"TP={tp} x replicas={replicas} needs {need} devices, "
                    f"only {len(devs)} visible"
                )
            mesh = Mesh(
                np.array(devs[:need]).reshape(key[1], key[0]),
                ("replica", "tp"),
            )
            _MESH_CACHE[key] = mesh
    return mesh


def kv_head_spec(paged: bool, ndim: int = 4):
    """PartitionSpec for one KV-cache leaf: heads axis (2) over 'tp'.

    Contiguous slabs additionally shard their batch axis (0) over
    'replica'; pool leaves must NOT (axis 0 is the block id space —
    device-agnostic by contract, and PAGED_KV pins REPLICAS=1)."""
    from jax.sharding import PartitionSpec as P

    lead = None if paged else "replica"
    tail = [None] * max(0, ndim - 3)
    return P(lead, None, "tp", *tail)


def placement_fingerprint(placement) -> str:
    """Stable short name of a placement's mesh topology + param layout
    for cache keying.  "" for plain single-mesh replica placements
    (keeps every pre-TP cache/autotune key byte-identical)."""
    mesh = getattr(placement, "mesh", None)
    if mesh is None:
        return ""
    try:
        axes = ",".join(f"{a}{int(n)}" for a, n in mesh.shape.items())
    except Exception:
        return ""
    spec = getattr(placement, "param_spec", None)
    if spec is None and axes in ("replica1", ""):
        return ""  # degenerate 1-device DP mesh == no placement axis
    tag = type(placement).__name__
    if spec is not None:
        import hashlib

        import jax
        from jax.sharding import PartitionSpec

        leaves = jax.tree.leaves(
            spec, is_leaf=lambda x: isinstance(x, PartitionSpec)
        )
        digest = hashlib.sha1(
            "|".join(str(s) for s in leaves).encode()
        ).hexdigest()[:10]
        return f"{tag}({axes})#{digest}"
    return f"{tag}({axes})"


def collective_probe(mesh, d_model: int, dtype="float32") -> dict:
    """Measured ICI collective latency over the serving mesh, per op —
    feeds ``tp_collective_seconds{op}`` at warm time (the serve path
    cannot separate collective from compute inside one executable, so
    the series reports a calibrated per-op probe, re-measured at every
    warm; docs/tensor-parallel.md documents the semantics)."""
    import time

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    tp = int(mesh.shape.get("tp", 1))
    if tp <= 1:
        return {}
    x = jnp.ones((max(1, d_model // tp), max(8, d_model)), dtype)
    xs = jax.device_put(x, NamedSharding(mesh, P("tp", None)))

    from jax.experimental.shard_map import shard_map

    # check_rep=False: the static replication checker cannot infer
    # out-replication over 'tp' for these one-op bodies on a 2-D mesh;
    # the probe is a timing harness, not a correctness surface.
    psum = jax.jit(shard_map(
        lambda v: jax.lax.psum(v, "tp"), mesh=mesh,
        in_specs=P("tp", None), out_specs=P(None, None),
        check_rep=False,
    ))
    gather = jax.jit(shard_map(
        lambda v: jax.lax.all_gather(v, "tp", axis=0, tiled=True),
        mesh=mesh, in_specs=P("tp", None), out_specs=P(None, None),
        check_rep=False,
    ))
    out = {}
    for op, fn in (("all_reduce", psum), ("all_gather", gather)):
        jax.block_until_ready(fn(xs))  # compile + warm outside the clock
        t0 = time.perf_counter()
        for _ in range(3):
            jax.block_until_ready(fn(xs))
        out[op] = (time.perf_counter() - t0) / 3.0
    return out
