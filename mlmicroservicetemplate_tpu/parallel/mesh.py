"""Mesh construction + replica sharding for data-parallel serving.

Capability parity: the reference serves multi-accelerator by wrapping
the model in ``torch.nn.DataParallel`` — weights replicated per GPU via
NCCL broadcast, inputs scattered, outputs gathered (SURVEY.md §3.4).
Here the same contract is expressed as shardings on a 1-D device mesh:

- params:  ``NamedSharding(mesh, P())``        — replicated on every core
- batch:   ``NamedSharding(mesh, P("replica"))`` — leading axis split

A jitted forward whose inputs carry these shardings compiles to one SPMD
executable per shape bucket; XLA inserts the ICI collectives.  The
degenerate 1-core mesh works identically (SURVEY.md §7.2 L0), so the
single-chip and multi-chip serving paths are the same code.
"""

from __future__ import annotations

import logging

import numpy as np

log = logging.getLogger(__name__)


def _make_1d_mesh(axis: str, n_devices: int, devices, knob: str):
    """1-D mesh over the first ``n_devices`` visible devices (0 = all)."""
    import jax
    from jax.sharding import Mesh

    devs = list(devices if devices is not None else jax.devices())
    if n_devices:
        if n_devices > len(devs):
            raise ValueError(
                f"{knob}={n_devices} but only {len(devs)} devices visible"
            )
        devs = devs[:n_devices]
    log.info("%s mesh over %d device(s): %s", axis, len(devs), devs)
    return Mesh(np.array(devs), (axis,))


def make_mesh(n_replicas: int = 0, devices=None):
    """``('replica',)`` mesh for data-parallel serving."""
    return _make_1d_mesh("replica", n_replicas, devices, "REPLICAS")


class ReplicaSet:
    """Owns the mesh and the two shardings of DP serving.

    The engine asks it to (a) place params replicated, (b) place batch
    arrays sharded on the leading axis, and (c) report the padding
    multiple (batch sizes must divide evenly across replicas).
    """

    def __init__(self, mesh):
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.mesh = mesh
        self.param_sharding = NamedSharding(mesh, P())
        self.batch_sharding = NamedSharding(mesh, self._batch_spec())

    def _batch_spec(self):
        from jax.sharding import PartitionSpec as P

        return P("replica")

    @property
    def n_replicas(self) -> int:
        """Batch data-parallel width (what batch sizes must divide by)."""
        return self.mesh.devices.size

    @property
    def n_devices(self) -> int:
        """Total devices in the serving mesh (all axes)."""
        return self.mesh.devices.size

    def place_params(self, params):
        """Replicate a param pytree onto every core (the NCCL-broadcast
        equivalent; a single host→HBM transfer per core, done once)."""
        import jax

        return jax.device_put(params, self.param_sharding)

    def place_batch(self, *arrays):
        """Commit batch arrays with the leading axis sharded over
        replicas.  jit then propagates these shardings through the
        computation — no explicit in_shardings needed."""
        import jax

        if jax.process_count() > 1:
            # Host-local numpy cannot device_put onto non-addressable
            # devices; multi-host SERVING additionally needs every
            # process to enter the SPMD computation in lockstep (a
            # driver pattern this single-controller HTTP path does not
            # implement).  The multi-host bootstrap currently serves
            # the training/collective machinery — fail loudly here.
            raise NotImplementedError(
                "multi-process serving data-path is not implemented: the "
                "HTTP batcher is single-controller; run one serving "
                "process per host (REPLICAS over local devices) or use "
                "the train-step path for cross-host meshes"
            )
        placed = tuple(jax.device_put(a, self.batch_sharding) for a in arrays)
        return placed if len(placed) != 1 else placed[0]

    def pad_multiple(self) -> int:
        return self.n_replicas

    def seq_multiple(self) -> int:
        """Divisibility the SEQ bucket must honor (1 = unconstrained).
        Part of the placement contract the engine collates against."""
        return 1

    def place_decode_state(self, state, paged: bool = False):
        """Commit a host-built decode slot state (contiguous or paged)
        with this placement's shardings.  DP placements shard only the
        slot axis; TP placements additionally shard every KV-cache
        leaf's heads axis over 'tp' (override below)."""
        import jax

        return jax.device_put(state, self.batch_sharding)


def make_sp_mesh(n_devices: int = 0, devices=None):
    """``('sp',)`` mesh for sequence-parallel (ring attention) serving."""
    return _make_1d_mesh("sp", n_devices, devices, "SP")


def _make_2d_mesh(second_axis: str, width: int, replicas: int = 0, devices=None):
    """``('replica', <axis>)`` mesh: batch over rows, width over columns.

    replicas=0 = every remaining visible device (len(devices) // width).
    """
    import jax
    from jax.sharding import Mesh

    if width < 1:
        raise ValueError(f"{second_axis} width must be >= 1, got {width}")
    devs = list(devices if devices is not None else jax.devices())
    if replicas == 0:
        replicas = max(1, len(devs) // width)
    need = replicas * width
    if need > len(devs):
        raise ValueError(
            f"replicas={replicas} x {second_axis}={width} needs {need} "
            f"devices, only {len(devs)} visible"
        )
    grid = np.array(devs[:need]).reshape(replicas, width)
    log.info(
        "('replica', '%s') mesh %dx%d over %d device(s)",
        second_axis, replicas, width, need,
    )
    return Mesh(grid, ("replica", second_axis))


def make_replica_tp_mesh(tp: int, replicas: int = 0, devices=None):
    """``('replica', 'tp')`` serving mesh: Megatron-sharded params over
    'tp', batch data-parallel over 'replica'."""
    return _make_2d_mesh("tp", tp, replicas, devices)


def make_replica_sp_mesh(sp: int, replicas: int = 0, devices=None):
    """``('replica', 'sp')`` mesh: long-context ring attention over 'sp'
    WITH the batch axis data-parallel over 'replica' (round-2 verdict:
    a 1-D sp mesh left the batch axis idle on every device)."""
    return _make_2d_mesh("sp", sp, replicas, devices)


class TensorParallelSet(ReplicaSet):
    """Engine placement for tensor-parallel serving.

    Params are sharded per a Megatron-style PartitionSpec pytree
    (``parallel/tp.py``: column-parallel q/k/v + mlp-up, row-parallel
    attn-out + mlp-down, vocab-sharded embeddings) over the mesh's
    'tp' axis; batch arrays shard their leading axis over 'replica'.
    jit propagates both, and XLA inserts the ICI collectives
    (all-reduce after row-parallel matmuls) — serving-side Megatron
    with the compiler owning the comm.
    """

    def __init__(self, mesh, param_spec):
        self.param_spec = param_spec
        super().__init__(mesh)

    def _batch_spec(self):
        from jax.sharding import PartitionSpec as P

        return P("replica")

    @property
    def n_replicas(self) -> int:
        return int(self.mesh.shape["replica"])

    @property
    def tp_width(self) -> int:
        return int(self.mesh.shape["tp"])

    def place_params(self, params):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        # Top-level subtrees the spec doesn't describe (e.g. a cached
        # prompt-prefix KV attached after the spec was built) replicate
        # — always correct, just not tp-sharded.  ``may_alias``: a leaf
        # already resident with a compatible layout (a fleet spawn
        # re-placing the donor's sharded params, a supervised rebuild
        # re-placing its own) reuses the buffer instead of copying —
        # placement cost scales with what MOVED, not with model size.
        spec = dict(self.param_spec)
        for key in params:
            if key not in spec:
                spec[key] = jax.tree.map(lambda _: P(), params[key])
        return jax.tree.map(
            lambda p, s: jax.device_put(
                p, NamedSharding(self.mesh, s), may_alias=True
            ),
            params, spec,
        )

    def place_decode_state(self, state, paged: bool = False):
        """KV-cache leaves shard their heads axis over 'tp' (pool
        blocks ``[NB, BS, H, D]`` and contiguous slabs ``[B, S, H, D]``
        alike — parallel/tpserve.kv_head_spec); every other field
        keeps the DP slot sharding.  Spec slot states shard their
        ``base`` the same way (the drafting history has no head axis).
        One logical pool, per-shard buffers: block ids, tables and the
        free-list/refcount ledger never see the mesh."""
        import jax
        from jax.sharding import NamedSharding

        from .tpserve import kv_head_spec

        def kv_shard(x):
            # Heads axis must split evenly (registry validates real TP
            # configs; duck-typed test states just replicate).
            if (getattr(x, "ndim", 0) >= 3
                    and x.shape[2] % self.tp_width == 0):
                return NamedSharding(
                    self.mesh, kv_head_spec(paged, x.ndim)
                )
            return self.batch_sharding

        def shardings(st):
            tree = jax.tree.map(lambda _: self.batch_sharding, st)
            if hasattr(st, "base"):  # SpecState wrapper
                return tree._replace(base=shardings(st.base))
            if hasattr(st, "cache_k"):
                tree = tree._replace(
                    cache_k=jax.tree.map(kv_shard, st.cache_k),
                    cache_v=jax.tree.map(kv_shard, st.cache_v),
                )
            return tree

        return jax.device_put(state, shardings(state))

    def pad_multiple(self) -> int:
        return self.n_replicas


class SeqParallelSet(ReplicaSet):
    """Engine placement for sequence-parallel (long-context) serving.

    Same contract as ``ReplicaSet`` but the SEQUENCE axis (axis 1 of
    [B, S] batch arrays) is sharded over the mesh's 'sp' axis — the
    layout ring attention consumes (``parallel/ring.py``): each device
    holds its local Q and K/V blocks; K/V blocks rotate over ICI via
    ppermute.

    Works on a 1-D ``('sp',)`` mesh (batch replicated) or a 2-D
    ``('replica', 'sp')`` mesh (batch data-parallel over 'replica' so
    the batch axis no longer idles — ``make_replica_sp_mesh``).
    """

    @property
    def _has_replica(self) -> bool:
        return "replica" in self.mesh.axis_names

    def _batch_spec(self):
        from jax.sharding import PartitionSpec as P

        return P("replica" if self._has_replica else None, "sp")

    @property
    def n_replicas(self) -> int:
        return int(self.mesh.shape["replica"]) if self._has_replica else 1

    def pad_multiple(self) -> int:
        # Batch divisibility comes from the replica axis (1 on a pure
        # sp mesh); the SEQ bucket must divide by the sp width.
        return self.n_replicas

    def seq_multiple(self) -> int:
        return int(self.mesh.shape["sp"])
