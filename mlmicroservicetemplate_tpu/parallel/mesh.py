"""Mesh construction + replica sharding for data-parallel serving.

Capability parity: the reference serves multi-accelerator by wrapping
the model in ``torch.nn.DataParallel`` — weights replicated per GPU via
NCCL broadcast, inputs scattered, outputs gathered (SURVEY.md §3.4).
Here the same contract is expressed as shardings on a 1-D device mesh:

- params:  ``NamedSharding(mesh, P())``        — replicated on every core
- batch:   ``NamedSharding(mesh, P("replica"))`` — leading axis split

A jitted forward whose inputs carry these shardings compiles to one SPMD
executable per shape bucket; XLA inserts the ICI collectives.  The
degenerate 1-core mesh works identically (SURVEY.md §7.2 L0), so the
single-chip and multi-chip serving paths are the same code.
"""

from __future__ import annotations

import logging

import numpy as np

log = logging.getLogger(__name__)


def _make_1d_mesh(axis: str, n_devices: int, devices, knob: str):
    """1-D mesh over the first ``n_devices`` visible devices (0 = all)."""
    import jax
    from jax.sharding import Mesh

    devs = list(devices if devices is not None else jax.devices())
    if n_devices:
        if n_devices > len(devs):
            raise ValueError(
                f"{knob}={n_devices} but only {len(devs)} devices visible"
            )
        devs = devs[:n_devices]
    log.info("%s mesh over %d device(s): %s", axis, len(devs), devs)
    return Mesh(np.array(devs), (axis,))


def make_mesh(n_replicas: int = 0, devices=None):
    """``('replica',)`` mesh for data-parallel serving."""
    return _make_1d_mesh("replica", n_replicas, devices, "REPLICAS")


class ReplicaSet:
    """Owns the mesh and the two shardings of DP serving.

    The engine asks it to (a) place params replicated, (b) place batch
    arrays sharded on the leading axis, and (c) report the padding
    multiple (batch sizes must divide evenly across replicas).
    """

    def __init__(self, mesh):
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.mesh = mesh
        self.param_sharding = NamedSharding(mesh, P())
        self.batch_sharding = NamedSharding(mesh, self._batch_spec())

    def _batch_spec(self):
        from jax.sharding import PartitionSpec as P

        return P("replica")

    @property
    def n_replicas(self) -> int:
        return self.mesh.devices.size

    def place_params(self, params):
        """Replicate a param pytree onto every core (the NCCL-broadcast
        equivalent; a single host→HBM transfer per core, done once)."""
        import jax

        return jax.device_put(params, self.param_sharding)

    def place_batch(self, *arrays):
        """Commit batch arrays with the leading axis sharded over
        replicas.  jit then propagates these shardings through the
        computation — no explicit in_shardings needed."""
        import jax

        placed = tuple(jax.device_put(a, self.batch_sharding) for a in arrays)
        return placed if len(placed) != 1 else placed[0]

    def pad_multiple(self) -> int:
        return self.n_replicas

    def seq_multiple(self) -> int:
        """Divisibility the SEQ bucket must honor (1 = unconstrained).
        Part of the placement contract the engine collates against."""
        return 1


def make_sp_mesh(n_devices: int = 0, devices=None):
    """``('sp',)`` mesh for sequence-parallel (ring attention) serving."""
    return _make_1d_mesh("sp", n_devices, devices, "SP")


class SeqParallelSet(ReplicaSet):
    """Engine placement for sequence-parallel (long-context) serving.

    Same contract as ``ReplicaSet`` but the SEQUENCE axis (axis 1 of
    [B, S] batch arrays) is sharded over ``('sp',)`` while the batch
    axis stays whole on every device — the layout ring attention
    consumes (``parallel/ring.py``): each device holds its local Q and
    K/V blocks; K/V blocks rotate over ICI via ppermute.
    """

    def _batch_spec(self):
        from jax.sharding import PartitionSpec as P

        return P(None, "sp")

    def pad_multiple(self) -> int:
        # Batch sizes need no divisibility; the SEQ bucket must divide
        # by the mesh width instead.
        return 1

    def seq_multiple(self) -> int:
        return self.n_replicas
