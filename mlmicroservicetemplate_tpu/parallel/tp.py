"""Tensor-parallel sharding rules + a sharded train step (dp × tp).

The reference needs only replica data-parallelism (SURVEY.md §2
"Parallelism strategies"), but the framework's sharding layer is built
the general TPU way: params carry ``NamedSharding``s over a
``('dp', 'tp')`` mesh and XLA's sharding propagation inserts the ICI
collectives (all-reduce after row-parallel matmuls, all-gather where
layouts demand).  Megatron-style layout for the transformer blocks:

- column-parallel (shard d_out over 'tp'):  attn q/k/v, mlp up
- row-parallel   (shard d_in  over 'tp'):  attn out,   mlp down
- embeddings: vocab axis over 'tp'; norms/biases-of-row-parallel
  replicated.

``train_step`` exists so multi-chip sharding is exercised end-to-end
(forward + backward + optimizer update, donated state) even though the
serving path itself is inference-only.
"""

from __future__ import annotations

import numpy as np


def make_dp_tp_mesh(n_devices: int, tp: int | None = None, devices=None):
    """2-D ``('dp','tp')`` mesh.  tp defaults to 2 when it divides the
    device count (so both axes are real), else 1."""
    import jax
    from jax.sharding import Mesh

    devs = list(devices if devices is not None else jax.devices())[:n_devices]
    if len(devs) < n_devices:
        raise ValueError(f"need {n_devices} devices, have {len(devs)}")
    if tp is None:
        tp = 2 if n_devices % 2 == 0 and n_devices > 1 else 1
    dp = n_devices // tp
    if dp * tp != n_devices:
        raise ValueError(f"tp={tp} does not divide n_devices={n_devices}")
    return Mesh(np.array(devs).reshape(dp, tp), ("dp", "tp"))


def _bert_layer_spec():
    from jax.sharding import PartitionSpec as P

    col = {"kernel": P(None, "tp"), "bias": P("tp")}
    row = {"kernel": P("tp", None), "bias": P()}
    ln = {"scale": P(), "bias": P()}
    return {
        "attn": {"q": col, "k": col, "v": col, "out": row, "ln": ln},
        "mlp": {"up": col, "down": row, "ln": ln},
    }


def bert_param_spec(cfg):
    """PartitionSpec pytree matching ``bert.init_params`` exactly."""
    from jax.sharding import PartitionSpec as P

    ln = {"scale": P(), "bias": P()}
    return {
        "embeddings": {
            # Model-axis sharding: BERT's 30522 vocab rows don't divide
            # by common tp widths (30522 % 4 != 0); hidden_size does.
            "word": {"embedding": P(None, "tp")},
            "position": {"embedding": P()},
            "token_type": {"embedding": P()},
            "ln": ln,
        },
        "layers": [_bert_layer_spec() for _ in range(cfg.num_layers)],
        "pooler": {"kernel": P(), "bias": P()},
        "classifier": {"kernel": P(), "bias": P()},
    }


def gpt_param_spec(cfg):
    """PartitionSpec pytree matching ``gpt.init_params`` exactly.

    Serving-side Megatron for the decoder: fused qkv + mlp-up are
    column-parallel, attn-out + mlp-down row-parallel, wpe + norms
    replicated.  wte shards on the MODEL axis, not the vocab axis —
    GPT-2's 50257 rows divide by nothing useful, while d_model does;
    the tied LM head's logits matmul then contracts over the sharded
    model dim (an all-reduce XLA inserts).  XLA's sharding propagation
    keeps semantics exact regardless of the head-boundary slicing of
    the fused qkv — correctness comes from the logical program, the
    spec only steers layout.
    """
    from jax.sharding import PartitionSpec as P

    col = {"kernel": P(None, "tp"), "bias": P("tp")}
    row = {"kernel": P("tp", None), "bias": P()}
    ln = {"scale": P(), "bias": P()}
    return {
        "wte": {"embedding": P(None, "tp")},
        "wpe": {"embedding": P()},
        "layers": [
            {
                "ln1": ln,
                "attn": {"qkv": col, "out": row},
                "ln2": ln,
                "mlp": {"up": col, "down": row},
            }
            for _ in range(cfg.num_layers)
        ],
        "final_ln": ln,
    }


def llama_param_spec(cfg):
    """PartitionSpec pytree matching ``llama.init_params``: q/k/v +
    gate/up column-parallel, o/down row-parallel, embeddings + lm_head
    model/column-sharded, RMSNorm scales replicated.  k/v out dims are
    num_kv_heads*head_dim, so tp must divide the KV width (4 heads on
    TinyLlama ⇒ tp ≤ 4 there)."""
    from jax.sharding import PartitionSpec as P

    col = {"kernel": P(None, "tp")}
    row = {"kernel": P("tp", None)}
    ln = {"scale": P()}
    return {
        "embed": {"embedding": P(None, "tp")},
        "layers": [
            {
                "attn_ln": ln,
                "attn": {"q": col, "k": col, "v": col, "o": row},
                "mlp_ln": ln,
                "mlp": {"gate": col, "up": col, "down": row},
            }
            for _ in range(cfg.num_layers)
        ],
        "final_ln": ln,
        "lm_head": {"kernel": P(None, "tp")},
    }


PARAM_SPECS = {
    # model-name prefix -> spec builder(cfg); used by the registry to
    # turn TP=<n> into a servable TensorParallelSet placement.
    "bert": bert_param_spec,
    "gpt": gpt_param_spec,
    "llama": llama_param_spec,
}


def shard_params(params, spec, mesh):
    import jax
    from jax.sharding import NamedSharding

    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, spec,
        is_leaf=lambda x: x is None,
    )


def make_train_step(cfg, mesh, learning_rate: float = 1e-4):
    """Jitted full training step for the BERT classifier over the mesh:
    data-parallel batch, tensor-parallel params, AdamW update, donated
    (params, opt_state)."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..models import bert as bert_mod

    tx = optax.adamw(learning_rate)
    batch_sharding = NamedSharding(mesh, P("dp", None))
    label_sharding = NamedSharding(mesh, P("dp"))

    def loss_fn(params, ids, mask, labels):
        logits = bert_mod.classify(params, cfg, ids, mask, dtype=jnp.float32)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
        return nll.mean()

    def train_step(params, opt_state, ids, mask, labels):
        loss, grads = jax.value_and_grad(loss_fn)(params, ids, mask, labels)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    jitted = jax.jit(train_step, donate_argnums=(0, 1))

    def init_and_place(key):
        spec = bert_param_spec(cfg)
        params = bert_mod.init_params(key, cfg=cfg)
        params = shard_params(params, spec, mesh)
        opt_state = tx.init(params)  # inherits param shardings leafwise
        return params, opt_state

    return jitted, init_and_place, (batch_sharding, label_sharding)
