"""Ring attention: sequence-parallel attention over the device mesh.

Long-context capability (the reference has none — SURVEY.md §2 lists
every parallelism strategy as absent except replica-DP — but
long-sequence serving shapes the core design, so it is first-class
here): the sequence axis is sharded across a ``('sp',)`` mesh axis;
each device keeps its local Q block resident and the K/V (+ key mask)
blocks rotate around the ring via ``lax.ppermute`` over ICI, with
online-softmax accumulators merging each hop's partial attention.

Peak memory per device is O(S/n · S/n) for scores instead of O(S²),
and the ppermute of the next K/V block overlaps with compute of the
current one under XLA's async collectives — the standard TPU recipe
for million-token attention, here at serving scale.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def _hop_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, m_ref, l_ref,
                o_out, m_out, l_out, *, scale: float):
    """One ring hop's online-softmax update for one (batch, head) cell:
    the [S_loc, S_loc] score tile, mask, exp and the rescaled
    accumulator updates all stay VMEM-resident — the unfused path
    writes+reads the f32 score tensor through HBM on EVERY hop, n-1
    times per layer."""
    q = q_ref[0, 0].astype(jnp.float32)  # [Sq, D]
    k = k_ref[0, 0].astype(jnp.float32)  # [Sk, D]
    s = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    s = jnp.where(mask_ref[0][0][None, :] != 0, s, jnp.float32(-1e9))
    # m/l ride as [B, H, 1, S] (TPU block tiling wants the trailing two
    # dims to equal the array's); index the singleton away here.
    m_prev = m_ref[0, 0, 0]
    m_new = jnp.maximum(m_prev, s.max(axis=-1))
    p = jnp.exp(s - m_new[:, None])
    corr = jnp.exp(m_prev - m_new)
    l_out[0, 0, 0] = l_ref[0, 0, 0] * corr + p.sum(axis=-1)
    pv = jax.lax.dot_general(
        p, v_ref[0, 0].astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_out[0, 0] = o_ref[0, 0] * corr[:, None] + pv
    m_out[0, 0, 0] = m_new


def _hop_pallas(qf, kc, vc, mc, o, m, l, *, scale: float, interpret: bool):
    """Pallas dispatch of one hop: grid (B, H); accumulators in f32.

    Shapes: qf/kc/vc [B, S, H, D] (q pre-transposed NOT needed — blocks
    index [b, :, h, :] views via transpose outside), o [B,H,Sq,D],
    m/l [B,H,Sq]."""
    import functools

    from jax.experimental import pallas as pl

    b, s, h, d = qf.shape
    qt = jnp.transpose(qf, (0, 2, 1, 3))
    kt = jnp.transpose(kc, (0, 2, 1, 3))
    vt = jnp.transpose(vc, (0, 2, 1, 3))
    bhsd = pl.BlockSpec((1, 1, s, d), lambda i, j: (i, j, 0, 0))
    bh1s = pl.BlockSpec((1, 1, 1, s), lambda i, j: (i, j, 0, 0))
    mask_spec = pl.BlockSpec((1, 1, s), lambda i, j: (i, 0, 0))
    o2, m2, l2 = pl.pallas_call(
        functools.partial(_hop_kernel, scale=scale),
        grid=(b, h),
        in_specs=[bhsd, bhsd, bhsd, mask_spec, bhsd, bh1s, bh1s],
        out_specs=[bhsd, bh1s, bh1s],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, 1, s), jnp.float32),
            jax.ShapeDtypeStruct((b, h, 1, s), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt, mc.astype(jnp.int32)[:, None, :],
      o, m[:, :, None, :], l[:, :, None, :])
    return o2, m2[:, :, 0, :], l2[:, :, 0, :]


def _ring_attn_local(q, k, v, key_mask, *, axis_name: str, scale: float,
                     use_pallas: bool = False, interpret: bool = False):
    """Per-device body under shard_map.

    q, k, v: [B, S_loc, H, D] (local shard); key_mask: [B, S_loc].
    Returns [B, S_loc, H, D].
    """
    n = lax.psum(1, axis_name)
    qf = q.astype(jnp.float32)
    b, s_loc, h, d = q.shape

    def step(i, carry):
        o, m, l, kc, vc, mc = carry
        if use_pallas:
            o, m, l = _hop_pallas(
                qf, kc, vc, mc, o, m, l, scale=scale, interpret=interpret
            )
        else:
            s = jnp.einsum("bqhd,bkhd->bhqk", qf, kc.astype(jnp.float32)) * scale
            s = jnp.where(mc[:, None, None, :] != 0, s, jnp.float32(-1e9))
            m_new = jnp.maximum(m, s.max(axis=-1))
            p = jnp.exp(s - m_new[..., None])
            corr = jnp.exp(m - m_new)
            l = l * corr + p.sum(axis=-1)
            o = o * corr[..., None] + jnp.einsum(
                "bhqk,bkhd->bhqd", p, vc.astype(jnp.float32)
            )
            m = m_new
        # The final iteration's rotation would only be discarded — skip
        # it so each call pays n-1 K/V-block hops, not n.  (i is uniform
        # across the mesh, so every device takes the same branch and the
        # collectives stay collective.)
        def rotate(ops):
            perm = [(j, (j + 1) % n) for j in range(n)]
            return tuple(lax.ppermute(x, axis_name, perm) for x in ops)

        kc, vc, mc = lax.cond(i < n - 1, rotate, lambda ops: ops, (kc, vc, mc))
        return (o, m, l, kc, vc, mc)

    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    m0 = jnp.full((b, h, s_loc), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    o, m, l, *_ = lax.fori_loop(0, n, step, (o0, m0, l0, k, v, key_mask))
    o = o / jnp.maximum(l, 1e-20)[..., None]  # fully-masked rows stay finite
    return jnp.transpose(o, (0, 2, 1, 3)).astype(q.dtype)


def make_ring_attention(mesh, axis: str = "sp"):
    """Build a sequence-sharded attention fn over ``mesh[axis]``.

    Returns ``fn(q, k, v, key_mask) -> ctx`` with q/k/v [B, S, H, D] and
    key_mask [B, S]; S must divide evenly by the axis size.  Call it
    inside jit with inputs sharded seq-over-``axis`` (it is a
    shard_map, so it composes with the surrounding program).

    On a 2-D ``('replica', 'sp')`` mesh the batch axis additionally
    shards over 'replica'; the ppermute ring stays within each replica
    row (axis_name scopes the collective), so data-parallel groups run
    independent rings — batch DP × sequence SP composed.
    """
    batch_axis = "replica" if "replica" in mesh.axis_names else None

    def fn(q, k, v, key_mask, *, use_pallas: bool = False,
           interpret: bool = False):
        scale = 1.0 / math.sqrt(q.shape[-1])
        body = functools.partial(
            _ring_attn_local, axis_name=axis, scale=scale,
            use_pallas=use_pallas, interpret=interpret,
        )
        seq_sharded = P(batch_axis, axis, None, None)
        in_specs = (seq_sharded, seq_sharded, seq_sharded, P(batch_axis, axis))
        if hasattr(jax, "shard_map"):
            return jax.shard_map(
                body,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=seq_sharded,
                check_vma=False,
            )(q, k, v, key_mask)
        # jax < 0.5: shard_map lives in experimental and the replication
        # check is spelled check_rep.
        from jax.experimental.shard_map import shard_map as _sm

        return _sm(
            body, mesh=mesh, in_specs=in_specs, out_specs=seq_sharded,
            check_rep=False,
        )(q, k, v, key_mask)

    return fn
