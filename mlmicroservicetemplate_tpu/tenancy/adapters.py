"""Adapter pool: N LoRA adapters paged through S stacked device slots.

The kv_blocks.py discipline applied to adapter weights: host copies
(loaded once from ``ADAPTER_DIR``) are the source of truth, a fixed
number of device-resident slots serve live traffic, and cold slots
demote by simple overwrite (the host copy never leaves RAM, so
"demotion" costs nothing and "promotion" is one device install).

- **Loading** — ``ADAPTER_DIR/*.npz`` (and ``*.safetensors`` when the
  library is importable; gated, never a hard dependency), one file per
  adapter, id = file stem.  Key convention:
  ``layers.{li}.{proj}.lora_a`` ``[d_in, r]`` and ``.lora_b``
  ``[r, d_out]`` per layer/projection, optional scalar ``alpha``
  (scale ``alpha/r`` is folded into B at load — serving never
  multiplies by it).  Ranks may differ per adapter; stacks are
  zero-padded to the max rank (exact: padded rank columns contribute
  nothing).
- **Slots** — ``ADAPTER_SLOTS`` device slots plus the built-in all-zero
  slot 0 (``adapter_id=None`` rows).  ``acquire`` refcounts a resident
  slot or installs into a free/coldest-idle one; every slot busy =
  :class:`AdapterBusy` (shed, retryable).  Installs go through ONE
  jitted dynamic-slice updater with a TRACED slot index, so serving a
  new adapter never compiles anything after warm
  (CompileWindow-pinned).
- **Overlay** — ``overlay(params, rows)`` attaches the stacks + the
  per-row slot vector as ``params["__adapters__"]``
  (``models/lora.py`` consumes it inside the jitted steps).
"""

from __future__ import annotations

import os
import threading
from typing import Any

import numpy as np

from ..utils import metrics


class AdapterBusy(Exception):
    """Every adapter slot is refcounted by a live stream; shed the
    request (503, retryable) instead of blocking the decode loop."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


def _load_file(path: str) -> dict[str, np.ndarray]:
    """Flat name→array dict from one adapter checkpoint file."""
    if path.endswith(".npz"):
        with np.load(path) as z:
            return {k: np.asarray(z[k]) for k in z.files}
    if path.endswith(".safetensors"):
        try:
            from safetensors.numpy import load_file
        except Exception:
            raise ValueError(
                f"{path}: safetensors not importable in this runtime; "
                "convert the adapter to .npz"
            )
        return dict(load_file(path))
    raise ValueError(f"{path}: unsupported adapter format")


def _parse_adapter(name: str, raw: dict[str, np.ndarray]) -> dict:
    """``{proj: (A [L, d_in, r], B [L, r, d_out])}`` (scale folded into
    B) from the flat key convention; strict — a malformed adapter file
    fails the BOOT, not a request."""
    alpha = float(raw.get("alpha", 0.0)) if "alpha" in raw else 0.0
    layers: dict[str, dict[int, tuple]] = {}
    n_layers = -1
    for key, arr in raw.items():
        if key in ("alpha", "r"):
            continue
        parts = key.split(".")
        if (len(parts) != 4 or parts[0] != "layers"
                or parts[3] not in ("lora_a", "lora_b")):
            raise ValueError(
                f"adapter {name!r}: unexpected key {key!r} (want "
                "layers.<li>.<proj>.lora_a|lora_b)"
            )
        li, proj = int(parts[1]), parts[2]
        slot = layers.setdefault(proj, {}).setdefault(li, [None, None])
        slot[0 if parts[3] == "lora_a" else 1] = np.asarray(arr, np.float32)
        n_layers = max(n_layers, li + 1)
    if not layers:
        raise ValueError(f"adapter {name!r}: no layers.* keys")
    out = {}
    for proj, per_layer in layers.items():
        a_rows, b_rows = [], []
        for li in range(n_layers):
            ent = per_layer.get(li)
            if ent is None or ent[0] is None or ent[1] is None:
                raise ValueError(
                    f"adapter {name!r}: projection {proj!r} missing "
                    f"lora_a/lora_b at layer {li}"
                )
            a, b = ent
            if a.ndim != 2 or b.ndim != 2 or a.shape[1] != b.shape[0]:
                raise ValueError(
                    f"adapter {name!r}: {proj!r} layer {li} rank "
                    f"mismatch ({a.shape} vs {b.shape})"
                )
            r = a.shape[1]
            scale = (alpha / r) if alpha else 1.0
            a_rows.append(a)
            b_rows.append(b * np.float32(scale))
        out[proj] = (np.stack(a_rows), np.stack(b_rows))
    return out


def load_adapter_dir(path: str) -> dict[str, dict]:
    """All adapters under ``path`` (sorted order → deterministic ids);
    empty/missing directory raises — a configured ADAPTER_DIR with
    nothing to serve is a deployment mistake."""
    if not os.path.isdir(path):
        raise ValueError(f"ADAPTER_DIR {path!r} is not a directory")
    names = sorted(
        f for f in os.listdir(path)
        if f.endswith((".npz", ".safetensors"))
    )
    if not names:
        raise ValueError(f"ADAPTER_DIR {path!r} holds no .npz/.safetensors")
    out = {}
    for fname in names:
        aid = fname.rsplit(".", 1)[0]
        out[aid] = _parse_adapter(aid, _load_file(os.path.join(path, fname)))
    return out


class AdapterPool:
    """Refcounted device-slot pool over host-resident LoRA adapters.

    One pool per engine (fleet replicas each hold their own device
    stacks; the host dict is shared read-only).  Thread-safe: the
    decode loop acquires at admission and releases at stream teardown.
    """

    def __init__(self, host: dict[str, dict], slots: int = 8,
                 model: str = ""):
        if not host:
            raise ValueError("AdapterPool needs at least one adapter")
        self.model = model
        self.host = dict(host)
        self.n_slots = max(1, int(slots))
        first = next(iter(host.values()))
        self.projections = tuple(sorted(first))
        self.num_layers = first[self.projections[0]][0].shape[0]
        self.rank = 0
        for ad in host.values():
            if tuple(sorted(ad)) != self.projections:
                raise ValueError(
                    "adapters disagree on projection set "
                    f"({tuple(sorted(ad))} vs {self.projections})"
                )
            for proj, (a, b) in ad.items():
                if a.shape[0] != self.num_layers:
                    raise ValueError(
                        f"adapters disagree on layer count for {proj!r}"
                    )
                self.rank = max(self.rank, a.shape[2])
        self._lock = threading.Lock()
        # slot index (1-based; 0 is the permanent zero adapter) →
        # adapter id, refcount, lru tick.
        self._slot_of: dict[str, int] = {}
        self._aid_at: dict[int, str] = {}
        self._refs: dict[int, int] = {}
        self._tick = 0
        self._lru: dict[int, int] = {}
        self.installs = 0
        self.demotions = 0
        self._stacks: dict[str, dict[str, Any]] = {}
        self._install_fn = None
        self._rows_cache: dict[int, Any] = {}
        self._build_stacks()
        self._note_gauges()

    # -- construction ---------------------------------------------------

    @classmethod
    def from_cfg(cls, cfg, model: str = ""):
        """Pool from ``ADAPTER_DIR``/``ADAPTER_SLOTS``, or None when
        the knob is unset (bit-identical default, pinned)."""
        path = getattr(cfg, "adapter_dir", None)
        if not path:
            return None
        return cls(
            load_adapter_dir(path),
            slots=int(getattr(cfg, "adapter_slots", 8) or 8),
            model=model,
        )

    def _build_stacks(self) -> None:
        import jax.numpy as jnp

        s = self.n_slots + 1
        ref = next(iter(self.host.values()))
        for proj in self.projections:
            a, b = ref[proj]
            d_in, d_out = a.shape[1], b.shape[2]
            self._stacks[proj] = {
                "a": jnp.zeros((s, self.num_layers, d_in, self.rank),
                               jnp.float32),
                "b": jnp.zeros((s, self.num_layers, self.rank, d_out),
                               jnp.float32),
            }

    def _padded(self, arr: np.ndarray, axis: int) -> np.ndarray:
        """Zero-pad the rank axis to the pool's max rank (exact: the
        padded factor columns multiply to nothing)."""
        if arr.shape[axis] == self.rank:
            return arr
        pad = [(0, 0)] * arr.ndim
        pad[axis] = (0, self.rank - arr.shape[axis])
        return np.pad(arr, pad)

    def _installer(self):
        """ONE jitted updater with a TRACED slot index, shared by every
        install — adapter loads after warm never compile (pinned)."""
        if self._install_fn is None:
            import jax
            from jax import lax

            self._install_fn = jax.jit(
                lambda stack, arr, slot: lax.dynamic_update_slice_in_dim(
                    stack, arr[None], slot, axis=0
                )
            )
        return self._install_fn

    def _install_locked(self, aid: str, slot: int) -> None:
        import jax.numpy as jnp

        ins = self._installer()
        old = self._aid_at.pop(slot, None)
        if old is not None:
            self._slot_of.pop(old, None)
            self.demotions += 1
        for proj, (a, b) in self.host[aid].items():
            st = self._stacks[proj]
            st["a"] = ins(st["a"], jnp.asarray(self._padded(a, 2)),
                          jnp.int32(slot))
            st["b"] = ins(st["b"], jnp.asarray(self._padded(b, 1)),
                          jnp.int32(slot))
        self._slot_of[aid] = slot
        self._aid_at[slot] = aid
        self.installs += 1

    def warm(self) -> None:
        """Trace the installer for every stack shape by re-writing slot
        0's zero delta (a semantic no-op), so serve-time installs are
        dispatch-only."""
        import jax.numpy as jnp

        ins = self._installer()
        with self._lock:
            for st in self._stacks.values():
                st["a"] = ins(st["a"],
                              jnp.zeros(st["a"].shape[1:], jnp.float32),
                              jnp.int32(0))
                st["b"] = ins(st["b"],
                              jnp.zeros(st["b"].shape[1:], jnp.float32),
                              jnp.int32(0))

    # -- serving --------------------------------------------------------

    def known(self, aid: str) -> bool:
        return aid in self.host

    def ids(self) -> list[str]:
        return sorted(self.host)

    def acquire(self, aid: str) -> int:
        """Slot serving ``aid`` with one reference taken; installs into
        a free or coldest-idle slot when not resident."""
        if aid not in self.host:
            raise KeyError(f"unknown adapter {aid!r}")
        with self._lock:
            self._tick += 1
            slot = self._slot_of.get(aid)
            if slot is None:
                slot = self._find_slot_locked()
                if slot is None:
                    raise AdapterBusy(
                        f"all {self.n_slots} adapter slots are serving "
                        "live streams"
                    )
                self._install_locked(aid, slot)
            self._refs[slot] = self._refs.get(slot, 0) + 1
            self._lru[slot] = self._tick
        self._note_gauges()
        return slot

    def _find_slot_locked(self) -> int | None:
        for slot in range(1, self.n_slots + 1):
            if slot not in self._aid_at:
                return slot
        idle = [s for s in range(1, self.n_slots + 1)
                if not self._refs.get(s)]
        if not idle:
            return None
        return min(idle, key=lambda s: self._lru.get(s, 0))

    def release(self, slot: int) -> None:
        """Drop one reference on ``slot`` (slot 0 / non-positive = the
        zero adapter, never refcounted)."""
        if slot <= 0:
            return
        with self._lock:
            self._refs[slot] = max(0, self._refs.get(slot, 0) - 1)
        self._note_gauges()

    def overlay(self, params: dict, rows) -> dict:
        """``params`` plus the ``__adapters__`` overlay for one
        dispatch whose row ``i`` runs adapter slot ``rows[i]``."""
        import jax.numpy as jnp

        with self._lock:
            ad: dict[str, Any] = {
                proj: dict(st) for proj, st in self._stacks.items()
            }
        rows = np.asarray(rows, np.int32)
        if rows.size and not rows.any():
            # All-base dispatches (warm, empty-state builds) reuse one
            # cached device zeros vector per batch size.
            cached = self._rows_cache.get(rows.size)
            if cached is None:
                cached = jnp.zeros((rows.size,), jnp.int32)
                self._rows_cache[rows.size] = cached
            ad["rows"] = cached
        else:
            ad["rows"] = jnp.asarray(rows)
        p = dict(params)
        p["__adapters__"] = ad
        return p

    # -- observability --------------------------------------------------

    def _note_gauges(self) -> None:
        with self._lock:
            resident = len(self._aid_at)
            active = sum(1 for s, r in self._refs.items() if r > 0)
            free = self.n_slots - resident
        g = metrics.ADAPTER_SLOTS.labels
        g(self.model, "resident").set(resident)
        g(self.model, "active").set(active)
        g(self.model, "free").set(free)
        g(self.model, "host").set(len(self.host))

    def status(self) -> dict:
        """/status.tenancy.adapters: residency + lifetime counters."""
        with self._lock:
            residents = {
                str(slot): {
                    "adapter": aid,
                    "refs": self._refs.get(slot, 0),
                }
                for slot, aid in sorted(self._aid_at.items())
            }
            return {
                "slots": self.n_slots,
                "host_adapters": len(self.host),
                "resident": residents,
                "installs": self.installs,
                "demotions": self.demotions,
                "live_refs": sum(r for r in self._refs.values() if r > 0),
            }

    def validate_against(self, params: dict) -> None:
        """Boot-time shape check against the served model's params —
        a wrong-architecture ADAPTER_DIR must fail startup, not the
        first adapted request."""
        layers = params.get("layers") if isinstance(params, dict) else None
        if not layers:
            raise ValueError("adapter validation: model has no layers")
        attn = layers[0].get("attn", {})
        for proj in self.projections:
            tgt = attn.get(proj)
            kernel = tgt.get("kernel") if isinstance(tgt, dict) else None
            if kernel is None:
                raise ValueError(
                    f"adapters target projection {proj!r} but the model's "
                    f"attention block has {sorted(attn)}"
                )
            st = self._stacks[proj]
            d_in, d_out = st["a"].shape[2], st["b"].shape[3]
            if tuple(kernel.shape) != (d_in, d_out):
                raise ValueError(
                    f"adapter projection {proj!r} is [{d_in}, {d_out}] "
                    f"but the model kernel is {tuple(kernel.shape)}"
                )
        if len(layers) != self.num_layers:
            raise ValueError(
                f"adapters cover {self.num_layers} layers but the model "
                f"has {len(layers)}"
            )
