"""Tenant accounting: classification, quotas, usage, per-tenant SLO.

The registry is the single source of truth for "who is this request
and what may they consume":

- **Classification** — ``X-Api-Key`` → :class:`TenantSpec` via the
  key table built from ``TENANTS`` (inline ``name=weight`` pairs; the
  tenant name doubles as its API key) or ``TENANTS_FILE`` (full JSON
  specs: keys, quotas, default adapter).  Unknown/missing keys map to
  the anonymous tenant (``""``) with default weight and no quotas —
  multi-tenancy hardens the platform without breaking keyless callers.
- **Quota ledger** — clock-injected, thread-safe: per-tenant live
  concurrency, committed KV bytes, and a sliding-window token ledger
  (one deque per tenant, pruned to ``window_s``).  ``admit`` either
  charges all three and returns an idempotent lease, or raises
  :class:`QuotaExceeded` carrying a per-tenant ``retry_after_s``
  (time until enough of the token window drains).  Conservation —
  every admit matched by exactly one effective release, ledgers back
  to zero — is pinned by tests/test_tenancy.py.
- **Per-tenant SLO burn** — rides the r20
  ``scheduler.policy.SLOTracker`` machinery unchanged; only the export
  target differs (``tenant_slo_ttft_burn_rate{tenant,window}``, the
  worst objective per window, bounded tenant labels).

Metric label cardinality is bounded: the first ``topk`` configured
tenants (declaration order) keep their names, everything else exports
as ``other`` and anonymous traffic as ``anon`` (≤ topk+2 label
values regardless of key-table size).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import deque

from ..utils import metrics


class QuotaExceeded(Exception):
    """A per-tenant quota (concurrency / token window / KV bytes) is
    exhausted; the admission controller translates this into a
    ``QueueFullError(reason="quota")`` → HTTP 429 + Retry-After."""

    def __init__(self, msg: str, tenant: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.tenant = tenant
        self.retry_after_s = float(retry_after_s)


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's identity, weight and quota envelope (0 = no cap)."""

    name: str
    weight: float = 1.0
    api_keys: tuple[str, ...] = ()
    max_concurrency: int = 0
    tokens_per_window: int = 0
    kv_budget_mb: float = 0.0
    adapter: str = ""

    @property
    def kv_budget_bytes(self) -> int:
        return int(self.kv_budget_mb * 1024 * 1024)


def parse_tenants(inline: str | None, path: str | None) -> list[TenantSpec]:
    """Tenant specs from the knobs (boot-validated — garbage raises
    ValueError at config load, not as request-time surprises).

    ``TENANTS`` is the compact form: comma-separated ``name=weight``
    (or bare ``name``, weight 1); each tenant's name is its API key.
    ``TENANTS_FILE`` is the full form: a JSON list (or ``{"tenants":
    [...]}`` object) of spec objects with optional ``weight``,
    ``api_keys``, ``max_concurrency``, ``tokens_per_window``,
    ``kv_mb`` and ``adapter`` fields.  Both set = file wins for
    duplicate names.
    """
    specs: dict[str, TenantSpec] = {}
    if inline:
        for part in str(inline).split(","):
            part = part.strip()
            if not part:
                continue
            name, _, w = part.partition("=")
            name = name.strip()
            if not name:
                raise ValueError(f"TENANTS entry {part!r} has an empty name")
            try:
                weight = float(w) if w else 1.0
            except ValueError:
                raise ValueError(f"TENANTS weight in {part!r} is not a number")
            if not weight > 0:
                raise ValueError(f"TENANTS weight for {name!r} must be > 0")
            specs[name] = TenantSpec(name=name, weight=weight,
                                     api_keys=(name,))
    if path:
        if not os.path.isfile(path):
            raise ValueError(f"TENANTS_FILE {path!r} does not exist")
        with open(path) as f:
            try:
                doc = json.load(f)
            except json.JSONDecodeError as e:
                raise ValueError(f"TENANTS_FILE {path!r}: invalid JSON ({e})")
        entries = doc.get("tenants") if isinstance(doc, dict) else doc
        if not isinstance(entries, list):
            raise ValueError(
                f"TENANTS_FILE {path!r} must be a JSON list or "
                '{"tenants": [...]}'
            )
        for ent in entries:
            if not isinstance(ent, dict) or not ent.get("name"):
                raise ValueError(
                    f"TENANTS_FILE entry {ent!r} needs a non-empty name"
                )
            name = str(ent["name"])
            try:
                spec = TenantSpec(
                    name=name,
                    weight=float(ent.get("weight", 1.0)),
                    api_keys=tuple(
                        str(k) for k in (ent.get("api_keys") or (name,))
                    ),
                    max_concurrency=int(ent.get("max_concurrency", 0)),
                    tokens_per_window=int(ent.get("tokens_per_window", 0)),
                    kv_budget_mb=float(ent.get("kv_mb", 0.0)),
                    adapter=str(ent.get("adapter", "")),
                )
            except (TypeError, ValueError) as e:
                raise ValueError(f"TENANTS_FILE entry {name!r}: {e}")
            if not spec.weight > 0:
                raise ValueError(
                    f"TENANTS_FILE tenant {name!r} weight must be > 0"
                )
            if (spec.max_concurrency < 0 or spec.tokens_per_window < 0
                    or spec.kv_budget_mb < 0):
                raise ValueError(
                    f"TENANTS_FILE tenant {name!r} quotas must be >= 0"
                )
            specs[name] = spec
    return list(specs.values())


#: Metric label for anonymous (keyless/unknown-key) traffic.
ANON = "anon"
#: Metric label for configured tenants past the top-K cap.
OTHER = "other"


class TenantRegistry:
    """Classification + quota ledger + per-tenant SLO for all tenants.

    One registry per Batcher, SHARED across fleet replicas (quotas are
    a platform-level contract, not a per-replica one).  Thread-safe;
    clock-injected so tests drive the token window without sleeping.
    """

    def __init__(self, specs: list[TenantSpec], model: str = "",
                 default_weight: float = 1.0, window_s: float = 60.0,
                 topk: int = 8, clock=None):
        self.model = model
        self.window_s = max(1e-3, float(window_s))
        self.default_weight = float(default_weight)
        self._clock = clock if clock is not None else time.monotonic
        self._specs = {s.name: s for s in specs}
        self._by_key = {k: s for s in specs for k in s.api_keys}
        self._anon = TenantSpec(name="", weight=self.default_weight)
        # Bounded metric labels: declaration order, first topk keep
        # their names.
        self._labels = {
            s.name: (s.name if i < int(topk) else OTHER)
            for i, s in enumerate(specs)
        }
        self._lock = threading.Lock()
        self._active: dict[str, int] = {}
        self._kv: dict[str, int] = {}
        self._window: dict[str, deque] = {}
        self._window_tokens: dict[str, int] = {}
        self._sheds: dict[str, int] = {}
        self._slo: dict[str, object] = {}
        self._slo_cfg = None

    # -- construction ---------------------------------------------------

    @classmethod
    def from_cfg(cls, cfg, model: str = "", clock=None):
        """Registry from the service knobs, or None when both
        ``TENANTS`` and ``TENANTS_FILE`` are unset — the
        bit-identical-default gate (pinned)."""
        inline = getattr(cfg, "tenants", None)
        path = getattr(cfg, "tenants_file", None)
        if not inline and not path:
            return None
        reg = cls(
            parse_tenants(inline, path), model=model,
            default_weight=float(
                getattr(cfg, "tenant_default_weight", 1.0) or 1.0
            ),
            window_s=float(getattr(cfg, "tenant_window_s", 60.0) or 60.0),
            topk=int(getattr(cfg, "tenant_metrics_topk", 8) or 8),
            clock=clock,
        )
        reg._slo_cfg = cfg
        return reg

    # -- classification -------------------------------------------------

    def classify(self, api_key: str | None) -> TenantSpec:
        """The tenant a request belongs to; unknown/missing keys are
        the anonymous tenant (default weight, no quotas)."""
        if api_key:
            spec = self._by_key.get(str(api_key))
            if spec is not None:
                return spec
        return self._anon

    def spec(self, name: str) -> TenantSpec | None:
        return self._specs.get(name)

    def weights(self) -> dict[str, float]:
        return {s.name: s.weight for s in self._specs.values()}

    def label(self, name: str) -> str:
        """Bounded metric label for a tenant name (≤ topk+2 values)."""
        if not name:
            return ANON
        return self._labels.get(name, OTHER)

    # -- quota ledger ---------------------------------------------------

    def _prune_locked(self, name: str, now: float) -> None:
        q = self._window.get(name)
        if not q:
            return
        horizon = now - self.window_s
        while q and q[0][0] < horizon:
            _, n = q.popleft()
            self._window_tokens[name] -= n

    def admit(self, spec: TenantSpec, tokens: int, kv_bytes: int) -> dict:
        """Charge one request against ``spec``'s quotas, returning an
        idempotent lease, or raise :class:`QuotaExceeded`.

        Window tokens are RATE accounting: they age out of the sliding
        window rather than being refunded at release.  Concurrency and
        KV bytes are OCCUPANCY accounting: ``release`` returns them.
        """
        name = spec.name
        tokens = max(0, int(tokens))
        kv_bytes = max(0, int(kv_bytes))
        now = self._clock()
        with self._lock:
            self._prune_locked(name, now)
            if spec.max_concurrency and (
                self._active.get(name, 0) >= spec.max_concurrency
            ):
                self._sheds[name] = self._sheds.get(name, 0) + 1
                raise QuotaExceeded(
                    f"tenant {name!r} at max_concurrency="
                    f"{spec.max_concurrency}", name, retry_after_s=1.0,
                )
            used = self._window_tokens.get(name, 0)
            if spec.tokens_per_window and used + tokens > spec.tokens_per_window:
                q = self._window.get(name)
                retry = self.window_s
                if q:
                    # Time until the OLDEST window entry ages out —
                    # the earliest instant any budget returns.
                    retry = max(0.0, self.window_s - (now - q[0][0]))
                self._sheds[name] = self._sheds.get(name, 0) + 1
                raise QuotaExceeded(
                    f"tenant {name!r} over tokens_per_window="
                    f"{spec.tokens_per_window} (used {used}, "
                    f"wanted {tokens})", name,
                    retry_after_s=max(1.0, retry),
                )
            if spec.kv_budget_mb and (
                self._kv.get(name, 0) + kv_bytes > spec.kv_budget_bytes
            ):
                self._sheds[name] = self._sheds.get(name, 0) + 1
                raise QuotaExceeded(
                    f"tenant {name!r} over kv_mb={spec.kv_budget_mb:g}",
                    name, retry_after_s=1.0,
                )
            self._active[name] = self._active.get(name, 0) + 1
            self._kv[name] = self._kv.get(name, 0) + kv_bytes
            if tokens:
                self._window.setdefault(name, deque()).append((now, tokens))
                self._window_tokens[name] = used + tokens
            kv_now = self._kv[name]
        label = self.label(name)
        if tokens:
            metrics.TENANT_TOKENS.labels(self.model, label).inc(tokens)
        metrics.TENANT_KV.labels(self.model, label).set(kv_now)
        return {"tenant": name, "tokens": tokens, "kv": kv_bytes,
                "released": False}

    def readmit(self, name: str, kv_bytes: int) -> dict:
        """Occupancy re-charge for a stream RE-ENTERING service — a
        preemption resume, a failover adoption, a journal replay.
        Concurrency and KV re-enter the ledger unconditionally (an
        already-started stream must never convert into a quota error),
        and window tokens are NOT re-charged — they were spent at the
        original admission and age out on their own."""
        kv_bytes = max(0, int(kv_bytes))
        with self._lock:
            self._active[name] = self._active.get(name, 0) + 1
            self._kv[name] = self._kv.get(name, 0) + kv_bytes
            kv_now = self._kv[name]
        metrics.TENANT_KV.labels(self.model, self.label(name)).set(kv_now)
        return {"tenant": name, "tokens": 0, "kv": kv_bytes,
                "released": False}

    def release(self, lease: dict | None) -> None:
        """Return a lease's occupancy charges (idempotent — double
        release is a no-op, conservation pinned)."""
        if not lease or lease.get("released"):
            return
        name = lease["tenant"]
        with self._lock:
            if lease.get("released"):
                return
            lease["released"] = True
            self._active[name] = max(0, self._active.get(name, 0) - 1)
            self._kv[name] = max(0, self._kv.get(name, 0) - lease["kv"])
            kv_now = self._kv[name]
        metrics.TENANT_KV.labels(self.model, self.label(name)).set(kv_now)

    def note_shed(self, name: str, reason: str) -> None:
        """Count a shed against a tenant (quota sheds count themselves
        inside ``admit``; this is the metric export point)."""
        metrics.TENANT_SHED.labels(self.model, self.label(name), reason).inc()

    # -- per-tenant SLO (r20 SLOTracker machinery) ----------------------

    def note_latency(self, name: str, kind: str, klass: str,
                     value_s: float) -> None:
        """Score one TTFT/TBT delivery against the tenant's SLO burn
        tracker (built lazily per bounded label; no SLO knobs set =
        no trackers, zero overhead)."""
        if self._slo_cfg is None:
            return
        label = self.label(name)
        tracker = self._slo.get(label)
        if tracker is None:
            with self._lock:
                tracker = self._slo.get(label)
                if tracker is None:
                    tracker = _TenantSLOTracker.from_cfg(
                        self.model, self._slo_cfg, clock=self._clock
                    )
                    self._slo[label] = tracker if tracker else False
        if tracker:
            tracker.tenant_label = label
            tracker.note(kind, klass, value_s)

    # -- observability --------------------------------------------------

    def usage(self) -> dict:
        """/status.tenancy: per-tenant live usage + quota envelope."""
        now = self._clock()
        with self._lock:
            names = sorted(
                set(self._specs) | set(self._active) | set(self._window)
            )
            out = {}
            for name in names:
                self._prune_locked(name, now)
                spec = self._specs.get(name, self._anon)
                out[name or ANON] = {
                    "weight": spec.weight,
                    "active": self._active.get(name, 0),
                    "window_tokens": self._window_tokens.get(name, 0),
                    "kv_bytes": self._kv.get(name, 0),
                    "sheds": self._sheds.get(name, 0),
                    "quota": {
                        "max_concurrency": spec.max_concurrency,
                        "tokens_per_window": spec.tokens_per_window,
                        "kv_mb": spec.kv_budget_mb,
                    },
                }
            return out

    def totals(self) -> dict:
        """Ledger totals (the drain-to-zero smoke assertion reads
        this): live concurrency and committed KV across all tenants."""
        with self._lock:
            return {
                "active": sum(self._active.values()),
                "kv_bytes": sum(self._kv.values()),
            }


class _TenantSLOTracker:
    """Per-tenant wrapper over ``scheduler.policy.SLOTracker``: same
    objectives, same windows, same burn arithmetic — only the export
    target differs (``tenant_slo_ttft_burn_rate{tenant,window}``,
    worst TTFT objective per window)."""

    def __new__(cls, *a, **k):  # pragma: no cover - built via from_cfg
        raise TypeError("use _TenantSLOTracker.from_cfg")

    @staticmethod
    def from_cfg(model: str, cfg, clock=None):
        from ..scheduler.policy import SLOTracker

        class _Export(SLOTracker):
            tenant_label = ANON

            def export_gauges(self, now=None):
                now = self._clock() if now is None else now
                for win_name, win in zip(self.WINDOW_NAMES, self.windows_s):
                    burn = max(
                        (
                            self.burn_rate(kind, klass, win, now=now)
                            for kind, klass in self.objectives
                            if kind == "ttft"
                        ),
                        default=0.0,
                    )
                    metrics.TENANT_SLO_BURN.labels(
                        self.model, self.tenant_label, win_name
                    ).set(burn)

        return _Export.from_cfg(model, cfg, clock=clock)
