"""Weighted fair share across tenants (docs/multi-tenancy.md).

Virtual-time fair queueing (the start-time fair queueing family,
SFQ/WF²Q): each tenant carries a virtual finish time ``v[t]``; serving
one unit of work advances it by ``cost / weight(t)``, so a tenant with
weight 3 accrues virtual time a third as fast and is picked three times
as often under sustained contention.  ``pick`` chooses the ELIGIBLE
tenant with the smallest ``max(v[t], vnow)`` — the ``max`` with the
global virtual clock is the re-activation floor: a tenant that idled
for an hour re-enters at *now*, not at its stale (tiny) virtual time,
so idleness banks no credit and cannot be weaponized into a burst that
starves everyone else.

Pure policy, no clocks, no metrics: ``DeadlineQueue`` calls
``pick``/``charge`` under its own condition lock, and the weighted
3:1 / starvation behavior is pinned by tests/test_tenancy.py.
"""

from __future__ import annotations

import threading
from typing import Iterable


class WeightedFairShare:
    """Virtual-time weighted fair queueing over tenant names.

    Unknown tenants (including the anonymous ``""`` tenant) get
    ``default_weight``.  Thread-safe; state is O(tenants-ever-seen)
    floats.
    """

    def __init__(self, weights: dict[str, float] | None = None,
                 default_weight: float = 1.0):
        self._weights = {
            str(k): float(v) for k, v in (weights or {}).items() if v and v > 0
        }
        self._default = max(1e-9, float(default_weight))
        self._v: dict[str, float] = {}
        self._vnow = 0.0
        self._served: dict[str, int] = {}
        self._lock = threading.Lock()

    def weight(self, tenant: str) -> float:
        return self._weights.get(str(tenant), self._default)

    def pick(self, eligible: Iterable[str]) -> str | None:
        """The eligible tenant that should be served next (None when
        ``eligible`` is empty).  Ties break by name for determinism."""
        with self._lock:
            best = None
            best_key = None
            for t in eligible:
                t = str(t)
                key = (max(self._v.get(t, 0.0), self._vnow), t)
                if best_key is None or key < best_key:
                    best, best_key = t, key
            return best

    def charge(self, tenant: str, cost: float = 1.0) -> None:
        """Account one served unit of work against ``tenant``."""
        t = str(tenant)
        with self._lock:
            start = max(self._v.get(t, 0.0), self._vnow)
            self._v[t] = start + float(cost) / self.weight(t)
            # The global virtual clock tracks the LAST service start so
            # re-activating tenants join at the present.
            self._vnow = start
            self._served[t] = self._served.get(t, 0) + 1

    def snapshot(self) -> dict:
        """/status.tenancy view: per-tenant weight / virtual time /
        served count."""
        with self._lock:
            return {
                t: {
                    "weight": self.weight(t),
                    "vtime": round(self._v.get(t, 0.0), 6),
                    "served": self._served.get(t, 0),
                }
                for t in sorted(set(self._v) | set(self._weights))
            }
