"""Multi-tenant serving (ROADMAP item 5; docs/multi-tenancy.md).

Three composable pieces, all built only when the ``TENANTS`` /
``TENANTS_FILE`` / ``ADAPTER_DIR`` knobs are set (unset = none of this
is constructed and serving is bit-identical to the single-tenant
server, pinned by tests/test_tenancy.py):

- ``accounts``  — API-key → tenant classification, per-tenant quota
  ledger (concurrency / sliding-window tokens / KV bytes), per-tenant
  SLO burn riding the r20 SLOTracker.
- ``fairshare`` — weighted virtual-time fair queueing across tenants
  inside one priority class of ``scheduler.policy.DeadlineQueue``.
- ``adapters``  — N LoRA deltas over one shared base model, paged
  through a refcounted device-slot pool and served as ONE batched
  decode dispatch via a per-row adapter-index vector
  (``models/lora.py``).
"""

from .accounts import QuotaExceeded, TenantRegistry, TenantSpec
from .adapters import AdapterBusy, AdapterPool
from .fairshare import WeightedFairShare

__all__ = [
    "AdapterBusy",
    "AdapterPool",
    "QuotaExceeded",
    "TenantRegistry",
    "TenantSpec",
    "WeightedFairShare",
]
