"""Weight-only int8 quantization for serving (QUANTIZE=int8).

TPU-native rationale: single-request and small-batch decode is
HBM-bandwidth-bound — every step streams the full weight set through
VMEM while the MXU idles.  Storing weights as int8 with per-output-
channel f32 scales halves (vs bf16) the bytes per step; the dequant
multiply fuses into the matmul's operand load, so there is no
materialized full-precision copy.  Accuracy: symmetric per-channel
rounding keeps classifier top-1 and greedy decode argmax stable (see
tests/test_quant.py); this is weight-only — activations stay bf16/f32,
so no calibration data is needed.

What gets quantized: float arrays of rank >= 2 above a size threshold —
dense kernels [in, out] (scale per out-column), conv kernels HWIO
(scale per O), embedding tables [V, D] (scale per row, so gathers
dequantize only the rows they touch).  Rank-0/1 params (norms, biases)
stay as they are.

A quantized leaf is the dict {"q8": int8 array, "scale": f32 array};
``models/common``'s primitives dequantize transparently via
``maybe_dequant``.
"""

from __future__ import annotations

import logging

import numpy as np

log = logging.getLogger(__name__)

MIN_QUANT_SIZE = 4096  # below this, int8 saves nothing worth the hop

VALID_MODES = (None, "int8")


def symmetric_int8(x, axis) -> tuple:
    """THE symmetric-int8 formula (one home for it): q = round(x/s),
    s = amax/127 reduced over ``axis`` (keepdims), zero-guarded.
    Shared by the weight path below and the KV-cache path
    (common.kv_quantize)."""
    import jax.numpy as jnp

    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axis, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q8 = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q8, scale


def _quantize_array(w, per_row: bool):
    """Symmetric int8 weights: per-row scales for embeddings (gathers
    stay cheap), per-output-channel otherwise."""
    import jax.numpy as jnp

    axis = tuple(range(1, w.ndim)) if per_row else tuple(range(w.ndim - 1))
    q, scale = symmetric_int8(w, axis)
    return {"q8": q, "scale": scale.astype(jnp.float32)}


def quantize_pytree(params, mode: str | None):
    """Return a copy of ``params`` with large float weights quantized.

    Embedding tables (leaf key ``embedding``) get per-row scales; all
    other rank>=2 weights get per-output-channel scales.
    """
    import jax.numpy as jnp

    if mode is None:
        return params
    if mode not in VALID_MODES:
        raise ValueError(f"QUANTIZE must be one of {VALID_MODES}, got {mode!r}")
    n_q = 0
    total = 0

    def walk(node):
        nonlocal n_q, total
        if isinstance(node, dict):
            out = {}
            for key, val in node.items():
                if (
                    hasattr(val, "ndim")
                    and val.ndim >= 2
                    and jnp.issubdtype(val.dtype, jnp.floating)
                    and val.size >= MIN_QUANT_SIZE
                ):
                    out[key] = _quantize_array(val, per_row=(key == "embedding"))
                    n_q += 1
                    total += int(val.size)
                else:
                    out[key] = walk(val)
            return out
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v) for v in node)
        return node

    quantized = walk(params)
    log.info(
        "int8-quantized %d weight tensors (%.1fM params); norms/biases kept",
        n_q, total / 1e6,
    )
    return quantized


def quant_error_stats(w, q: dict) -> dict:
    """Max/mean abs reconstruction error (test/diagnostic helper)."""
    rec = np.asarray(q["q8"], np.float32) * np.asarray(q["scale"], np.float32)
    err = np.abs(np.asarray(w, np.float32) - rec)
    return {"max": float(err.max()), "mean": float(err.mean())}
