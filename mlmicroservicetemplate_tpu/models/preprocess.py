"""Host-side pre/post-processing as pure functions (numpy in/out).

Capability parity: the reference's ``ModelWrapper`` owns PIL decode +
ImageNet normalization for ResNet and label mapping for outputs
(SURVEY.md §2). Kept lean — this box serves from 1 vCPU shared with the
event loop (SURVEY.md §7.4.3), so decode/resize happen in a thread-pool
offload (see ``scheduler``), and everything here is allocation-light.
"""

from __future__ import annotations

import io

import numpy as np

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def decode_image_u8(data: bytes, image_size: int = 224) -> np.ndarray:
    """JPEG/PNG bytes → [H, W, 3] uint8 (resize-shortest + center crop).

    Normalization deliberately does NOT happen here: uint8 crosses the
    host→device boundary at 1/4 the bytes of f32, and the mean/std
    affine runs on-device inside the jitted forward (fused into the
    first conv by XLA).  On a relay-attached TPU the wire bytes are the
    serving bottleneck, so this is a 4× cut on the dominant term.
    """
    from PIL import Image

    img = Image.open(io.BytesIO(data)).convert("RGB")
    w, h = img.size
    short = int(round(image_size * 256 / 224))
    if w < h:
        nw, nh = short, max(1, int(round(h * short / w)))
    else:
        nw, nh = max(1, int(round(w * short / h))), short
    img = img.resize((nw, nh), Image.BILINEAR)
    left = (nw - image_size) // 2
    top = (nh - image_size) // 2
    img = img.crop((left, top, left + image_size, top + image_size))
    return np.asarray(img, np.uint8)


def normalize_imagenet(x):
    """Device-side ImageNet normalization: uint8 [.., 3] → f32.

    Lives next to the host decode so the two halves of the reference's
    preprocessing (SURVEY.md §2 ModelWrapper) stay in one place.
    """
    import jax.numpy as jnp

    mean = jnp.asarray(IMAGENET_MEAN)
    std = jnp.asarray(IMAGENET_STD)
    return (x.astype(jnp.float32) / 255.0 - mean) / std


def softmax_np(logits: np.ndarray) -> np.ndarray:
    z = logits - logits.max(axis=-1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=-1, keepdims=True)


def topk_np(logits: np.ndarray, k: int = 5) -> tuple[np.ndarray, np.ndarray]:
    """Per-row top-k (indices, probabilities), sorted descending."""
    probs = softmax_np(logits.astype(np.float32))
    idx = np.argpartition(-probs, kth=min(k, probs.shape[-1] - 1), axis=-1)[..., :k]
    vals = np.take_along_axis(probs, idx, axis=-1)
    order = np.argsort(-vals, axis=-1)
    return np.take_along_axis(idx, order, axis=-1), np.take_along_axis(vals, order, axis=-1)


def load_labels(path: str | None) -> list[str] | None:
    """Optional label file: one class name per line (LABELS_PATH)."""
    if not path:
        return None
    with open(path, encoding="utf-8") as f:
        return [line.rstrip("\n") for line in f]
