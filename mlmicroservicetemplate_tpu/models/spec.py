"""Self-drafting speculative decoding (prompt-lookup / n-gram).

At batch=1 a decoder's step time is pinned to the HBM ceiling: every
token streams the full weight set once (measured in BASELINE.md —
llama-1.1B at 2.58 ms/step bf16 ≈ 853 GB/s, the v5e wire).  No tuning
beats that wall except not paying one weight pass PER token: draft
several candidate tokens cheaply, then verify them all in ONE forward
whose weight traffic is the same as a single step.  With m drafts
accepted, one weight pass yields m+1 tokens.

This module is the drafter-free variant (no second checkpoint exists in
this offline environment): drafts come from *prompt lookup* — the last
``ngram_n`` generated tokens are matched against the prompt + generation
history, and the ``spec_k`` tokens that followed the most recent match
become the draft.  Free to compute (a masked compare over an int32
buffer already on device), highly effective whenever output re-uses
input spans (summarization, extraction, code edits, chat quoting), and
harmless when it misses: a rejected draft costs only MXU idle lanes in
the verify forward, which is HBM-bound at these shapes anyway.

Correctness contract (greedy only): every emitted token equals the
verify forward's own greedy argmax at its position, so the output
token sequence is EXACTLY what non-speculative greedy decoding would
produce under the same numerics (tested token-identical in
tests/test_spec.py).  Acceptance never depends on where a draft came
from — a garbage draft that happens to match argmax is a correct
emission by construction.

All control flow is static-shape: each verify step processes a fixed
``spec_k + 1`` token window and returns a fixed-width output row plus a
per-row valid count; the host slices counts off the fetched buffer.
Works on any decoder family exposing a ``multi_step`` window forward
(gpt.py, llama.py — the GPTState contract) AND on encoder-decoders
(t5.py): the history buffer may be WIDER than the KV cache by a
constant prefix that holds the encoder input ids — cache position p
maps to history position p + (hist_width - cache_width).  For T5 that
prefix is the document being summarized, exactly where summaries quote
from, so prompt-lookup drafts land at their highest-acceptance
workload.  Decoder-only families have equal widths and a zero offset.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp


class SpecState(NamedTuple):
    """Decode state + token history for drafting.

    ``base`` is the family's GPTState (per-row caches/write_idx/done —
    models/gpt.py); ``history`` is an int32 [B, total] buffer where
    position p holds the token id EMBEDDED at cache position p (-1
    where no real token lives: bucket padding, unwritten future, the
    startup-cached PROMPT_PREFIX region whose ids were never seen
    here).  Invariant: history[b, write_idx[b]] == last_token[b]."""

    base: Any
    history: jax.Array


def init_history(
    state, input_ids, attention_mask, p_len: int, prefix_ids=None
) -> SpecState:
    """Build the drafting history from the (right-padded) prompt.

    ``p_len`` is the cached-prefix length.  When the caller KNOWS the
    prefix token ids (per-request prefix caching: the prefix is the
    request's own leading tokens), pass them as ``prefix_ids`` [1, P]
    so the n-gram lookup drafts from the full prompt; a startup-global
    PROMPT_PREFIX's ids are unknown at this layer and that region
    stays -1 (no matches land there)."""
    b, s = input_ids.shape
    total = state.key_valid.shape[1]
    hist = jnp.full((b, total), -1, jnp.int32)
    ids = jnp.where(attention_mask != 0, input_ids, -1).astype(jnp.int32)
    hist = hist.at[:, p_len : p_len + s].set(ids)
    if prefix_ids is not None:
        pref = jnp.broadcast_to(
            jnp.asarray(prefix_ids, jnp.int32).reshape(1, -1), (b, p_len)
        )
        hist = hist.at[:, :p_len].set(pref)
    return SpecState(base=state, history=hist)


def make_init_spec_fn(p_len: int = 0):
    """THE ``init_spec_fn`` implementation for DECODER-ONLY families
    (the GPTState layout): ``(state, input_ids, attention_mask,
    prefix_ids=None) -> SpecState``.  ``prefix_ids`` arrives on
    per-request prefix-cache hits (its length wins over the builder's
    global ``p_len``); decoder-only builders and custom families should
    use this instead of hand-rolling the closure.  Encoder-decoders
    have a different history layout (the encoder ids prepend the
    buffer) — see ``t5.init_spec_state``."""

    def init_spec_fn(state, input_ids, attention_mask, prefix_ids=None):
        pl = prefix_ids.shape[-1] if prefix_ids is not None else p_len
        return init_history(state, input_ids, attention_mask, pl, prefix_ids)

    return init_spec_fn


def draft_ngram(
    history: jax.Array,  # [B, total] int32, -1 invalid
    write_idx: jax.Array,  # [B]
    spec_k: int,
    ngram_n: int,
) -> jax.Array:
    """Prompt-lookup draft: [B, spec_k] continuation of the most recent
    earlier occurrence of the trailing n-gram, matched LARGEST n first
    (``ngram_n`` down to 1): longer patterns give higher-precision
    continuations, and rows they miss fall back to shorter ones —
    a fallback match that verification rejects costs nothing in the
    HBM-bound regime (the verify window runs either way), while a
    fallback match that holds is pure extra acceptance.  -1 rows where
    no n matches (-1 never equals an argmax → rejected for free).

    One incremental pass: the depth-d candidate mask refines the
    depth-(d-1) mask, and each depth's most-recent match position is
    recorded along the way — every n in one sweep, no recomputation."""
    b, total = history.shape
    posv = jnp.arange(total)[None]  # [1, total]
    t = write_idx[:, None]  # [B, 1]
    cand = posv < t  # strictly before the current position
    j_by_n = []  # most-recent match position per pattern length 1..N
    for d in range(ngram_n):
        tgt = jnp.take_along_axis(
            history, jnp.clip(t - d, 0, total - 1), axis=1
        )  # [B, 1] token at position t-d (the pattern's d-th-last)
        if d == 0:
            hd = history
        else:
            hd = jnp.pad(
                history[:, :-d], ((0, 0), (d, 0)), constant_values=-1
            )
        cand = cand & (hd == tgt) & (tgt >= 0) & (posv >= d)
        j_by_n.append(jnp.where(cand, posv, -1).max(axis=1).astype(jnp.int32))
    # Largest n wins; rows it missed fall back toward n=1.
    j = jnp.full((b,), -1, jnp.int32)
    for j_n in reversed(j_by_n):
        j = jnp.where(j >= 0, j, j_n)
    gather = jnp.clip(
        j[:, None] + 1 + jnp.arange(spec_k)[None], 0, total - 1
    )
    draft = jnp.take_along_axis(history, gather, axis=1)  # [B, spec_k]
    return jnp.where(j[:, None] >= 0, draft, jnp.int32(-1))


def _sampled_emission(logits, draft, sp, spec_k: int):
    """Rejection-sampling acceptance for deterministic (point-mass)
    drafts — the standard speculative-sampling result specialized to
    prompt-lookup: the draft proposal q is a point mass at draft_i, so

    - accept draft_i with prob p_{i-1}(draft_i), where p is the row's
      temperature/top-k/top-p-FILTERED distribution (must be the same
      transform the sequential sampler applies — sampling.filtered_logits);
    - on first rejection, resample from the residual norm(max(0, p - q))
      = p with the rejected token's mass removed, renormalized;
    - if all K accepted, the bonus token samples from p_K directly.

    Marginally each emitted position is distributed EXACTLY as
    sequential ancestral sampling (the accepted-mass + residual-mass
    split reconstructs p), so the output distribution is identical —
    only the randomness CONSUMPTION differs, which is why seeded
    sequences differ across the spec/non-spec paths while each path
    stays deterministic per seed (tested in test_spec_sampled.py).

    A -1 draft slot (no n-gram match) never had a proposal: acceptance
    is forced false and the "residual" keeps full p (nothing to remove).
    Returns (cand [B, K+1] emission candidates, m [B] accepted counts,
    next_rng [B, 2])."""
    from .sampling import filtered_logits, row_split

    b, width, v = logits.shape
    rep = lambda a: jnp.repeat(a, width, axis=0)
    z = filtered_logits(
        logits.reshape(b * width, v),
        rep(sp.temperature), rep(sp.top_k), rep(sp.top_p),
    ).reshape(b, width, v)
    probs = jax.nn.softmax(z, axis=-1)  # [B, W, V] f32
    clip_d = jnp.clip(draft, 0, v - 1)
    p_draft = jnp.take_along_axis(
        probs[:, :spec_k, :], clip_d[:, :, None], axis=-1
    )[..., 0]  # [B, K]

    next_rng, step_keys = jax.vmap(row_split)(sp.rng)
    u = jax.vmap(
        lambda k: jax.random.uniform(jax.random.fold_in(k, 0), (spec_k,))
    )(step_keys)  # [B, K]
    accept = (u < p_draft) & (draft >= 0)
    m = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)  # [B]

    # Final token: residual at the rejection slot, or bonus at slot K.
    probs_m = jnp.take_along_axis(probs, m[:, None, None], axis=1)[:, 0]  # [B, V]
    rej_slot = jnp.minimum(m, spec_k - 1)[:, None]
    rej_tok = jnp.take_along_axis(clip_d, rej_slot, axis=1)[:, 0]  # [B]
    rej_valid = (m < spec_k) & (
        jnp.take_along_axis(draft, rej_slot, axis=1)[:, 0] >= 0
    )
    final_p = jnp.where(
        (jnp.arange(v)[None] == rej_tok[:, None]) & rej_valid[:, None],
        0.0, probs_m,
    )
    final_logits = jnp.log(jnp.maximum(final_p, jnp.float32(1e-38)))
    e = jax.vmap(
        lambda k, lg: jax.random.categorical(jax.random.fold_in(k, 1), lg)
    )(step_keys, final_logits).astype(jnp.int32)

    # Candidate emissions: the m accepted drafts, then the sampled token.
    offs = jnp.arange(width)[None]
    draft_pad = jnp.concatenate([draft, draft[:, :1]], axis=1)  # [B, W]
    cand = jnp.where(offs == m[:, None], e[:, None], draft_pad)
    return cand, m, next_rng.astype(jnp.uint32)


def verify_step(
    params,
    spec_state: SpecState,
    spec_k: int,
    ngram_n: int,
    multi_fn: Callable,  # (params, base_state, tokens [B,D]) -> (k, v, logits [B,D,V])
    eos_id: int,
    pad_id: int,
    sample: bool = False,
):
    """One draft→verify→accept round.  Returns (state', out [B, K+1],
    n_emit [B]): ``out[:, :n_emit]`` are the emitted tokens (padded with
    pad_id past the count).

    Window semantics: input x_0 = last_token (recomputed at its own
    position, identical to the single-step path's uniform-step trick),
    x_1..x_K = draft.  g_i = argmax of the logits after x_i.  g_0 is
    unconditionally correct (it is THE next greedy token); draft_i is
    accepted iff it equals g_i's predecessor chain — the longest prefix
    where draft == g[:, :K] — because only then was x_{i+1} the token
    greedy would have fed next.  m accepted drafts ⇒ m+1 emitted tokens
    (the bonus token g_m comes free from the verify logits).

    ``sample`` (static) additionally runs rejection-sampling acceptance
    for rows with temperature>0 (``_sampled_emission``): accepted
    drafts ARE the emissions there, and the (m+1)-th token is sampled
    from the residual/bonus distribution — distribution-identical to
    sequential sampling.  Greedy rows in the same batch keep the argmax
    rule; cache discipline is unchanged either way because the window
    K/V at position t+1+j always came from draft_{j+1}, which is
    exactly the token emitted at offset j on both rules.

    Cache/state discipline: K/V for ALL window positions are written
    before acceptance is known; only accepted positions get key_valid
    set, so rejected-position K/V is invisible and gets overwritten by
    later (sequential) writes before its position is ever marked valid.
    Rows already done emit nothing and freeze (their writes re-write
    position t with identical values)."""
    st = spec_state.base
    hist = spec_state.history
    b = st.last_token.shape[0]
    width = spec_k + 1
    rows = jnp.arange(b)[:, None]  # [B, 1]
    offs = jnp.arange(width)[None]  # [1, width]

    # Cache→history index offset: encoder-decoder families prepend the
    # encoder input ids to the history buffer (t5.init_spec_state), so
    # cache position p lives at history position p + hoff.  Both widths
    # are static, so this is a trace-time constant (0 for decoder-only).
    hoff = hist.shape[1] - st.key_valid.shape[1]

    draft = draft_ngram(hist, st.write_idx + hoff, spec_k, ngram_n)
    tokens = jnp.concatenate([st.last_token[:, None], draft], axis=1)
    # Draft slots may hold -1 (no match): embedding lookups need a real
    # id — feed pad instead; acceptance still compares the RAW draft,
    # so these can never be accepted.
    feed = jnp.where(tokens >= 0, tokens, jnp.int32(pad_id))
    new_k, new_v, logits = multi_fn(params, st, feed)
    g = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, width]

    match = draft == g[:, :spec_k]
    # Longest accepted prefix: count of leading True.
    m = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)  # [B]
    cand = g
    sp = st.sample
    if sample:
        cand_s, m_s, next_rng = _sampled_emission(logits, draft, sp, spec_k)
        is_samp = sp.temperature > 0.0
        cand = jnp.where(is_samp[:, None], cand_s, g)
        m = jnp.where(is_samp, m_s, m)
        sp = sp._replace(rng=next_rng)
    emit_raw = offs <= m[:, None]  # candidates cand_0..cand_m
    is_eos = (cand == jnp.int32(eos_id)) & emit_raw
    has_eos = is_eos.any(axis=1)
    eos_idx = jnp.where(has_eos, jnp.argmax(is_eos, axis=1), width)
    # Emit through the first EOS inclusive, like the sequential path.
    n_emit = jnp.minimum(m + 1, eos_idx + 1)
    n_emit = jnp.where(st.done, 0, n_emit).astype(jnp.int32)
    emit = offs < n_emit[:, None]  # [B, width]
    out = jnp.where(emit, cand, jnp.int32(pad_id))

    total = st.key_valid.shape[1]
    sentinel_tok = st.tokens.shape[1]  # OOB ⇒ mode="drop"
    tokens_buf = st.tokens.at[
        rows, jnp.where(emit, st.pos[:, None] + offs, sentinel_tok)
    ].set(out, mode="drop")
    posv = jnp.arange(total)[None]
    newly_valid = (posv >= st.write_idx[:, None]) & (
        posv < (st.write_idx + n_emit)[:, None]
    )
    key_valid = jnp.where(newly_valid, 1, st.key_valid)
    # Token g_i will be embedded at cache position t+1+i — history
    # position hoff+t+1+i (history invariant); sentinel = hist width.
    hist = hist.at[
        rows,
        jnp.where(emit, st.write_idx[:, None] + hoff + 1 + offs, hist.shape[1]),
    ].set(out, mode="drop")
    last = jnp.where(
        n_emit > 0,
        jnp.take_along_axis(cand, jnp.maximum(n_emit - 1, 0)[:, None], axis=1)[:, 0],
        st.last_token,
    )
    base = st._replace(
        cache_k=new_k,
        cache_v=new_v,
        key_valid=key_valid,
        write_idx=st.write_idx + n_emit,
        pos=st.pos + n_emit,
        last_token=last,
        done=st.done | has_eos,
        tokens=tokens_buf,
        sample=sp,
    )
    return SpecState(base=base, history=hist), out, n_emit


def spec_chunk(
    params,
    spec_state: SpecState,
    n_verify: int,
    spec_k: int,
    ngram_n: int,
    multi_fn: Callable,
    eos_id: int,
    pad_id: int,
    sample: bool = False,
):
    """``n_verify`` verify rounds in one compiled scan — the spec-path
    chunk contract.  Returns (state', out [B, n_verify, K+1], n_emit
    [B, n_verify]): each round emits between 1 and K+1 tokens per live
    row (0 once done), so one dispatch yields ≥ n_verify tokens and up
    to n_verify·(K+1).  ``sample`` is STATIC: True compiles the
    rejection-sampling acceptance path for temperature>0 rows."""

    def step(s, _):
        s2, out, n = verify_step(
            params, s, spec_k, ngram_n, multi_fn, eos_id, pad_id, sample
        )
        return s2, (out, n)

    spec_state, (outs, ns) = jax.lax.scan(
        step, spec_state, None, length=n_verify
    )
    return spec_state, jnp.transpose(outs, (1, 0, 2)), jnp.transpose(ns)


def flatten_emitted(out_np, n_np, row: int = 0):
    """Host-side: ordered emitted tokens for one row from a fetched
    (out [B, n_verify, K+1], n_emit [B, n_verify]) pair."""
    import numpy as np

    parts = [
        out_np[row, v, : int(n_np[row, v])] for v in range(out_np.shape[1])
    ]
    return np.concatenate(parts) if parts else np.zeros((0,), np.int32)
