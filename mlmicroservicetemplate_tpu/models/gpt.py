"""Decoder-only causal LM (GPT-2 family), pure-JAX, KV-cached decode.

Model-family breadth beyond the reference's three configs (SURVEY.md §2
serves ResNet/BERT/T5): the template contract is "bring a model, get
the serving stack" — this is the decoder-only member, servable as
``MODEL_NAME=gpt2`` with streaming generation through the SAME engine
machinery as T5 (encode/init/generate_chunk trio, single-dispatch
chunked scans, early EOS exit).

Architecture (GPT-2): learned positions, pre-LN blocks, GELU MLP,
causal attention, tied LM head, final LN.

TPU-first decode design: the prompt is prefilled in ONE forward (K/V
for all prompt positions written into static [B, S+max_decode, H, D]
caches), then generation runs as ``lax.scan`` chunks with per-row write
indices — right-padded prompts of different lengths decode correctly in
one batch because each row embeds/attends at its own position, with a
key-validity mask instead of a shared causal frontier.

The first decode step recomputes the last prompt position (its cache
write is bit-identical to prefill's), which buys a uniform step
function with no special first-token path.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from . import lora
from .common import (
    Params,
    dense,
    dense_init,
    embed,
    layernorm,
    layernorm_init,
    merge_heads,
    mha_attention,
    normal_init,
    split_heads,
)


def gelu_new(x: jax.Array) -> jax.Array:
    # GPT-2 uses the tanh-approximated GELU ("gelu_new" in HF), not the
    # erf form BERT uses — checkpoint fidelity depends on matching it.
    return jax.nn.gelu(x, approximate=True)


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    d_model: int = 768
    num_heads: int = 12
    num_layers: int = 12
    d_ff: int = 3072
    max_position: int = 1024
    ln_eps: float = 1e-5
    eos_id: int = 50256
    pad_id: int = 50256  # GPT-2 has no pad token; eos doubles as pad
    # Fused Pallas decode over the paged pool (ops/paged_attention):
    # one grid program per (row, block-group) DMAs exactly the row's
    # live blocks — no gather_pages materialization.  GPT is MHA
    # (kvh == num_heads, n_rep == 1), so this is the no-GQA corner of
    # the same kernel llama serves; token-identical to the gather path
    # (tests/test_pallas_autotune.py).  Serving-only, no VJP.
    pallas_decode: bool = False
    # Variant pin / interpret-mode toggle — same contract as
    # LlamaConfig (docs/kernel_tuning.md); "" resolves through the
    # autotuner tuning table at trace time.
    pallas_variant: str = ""
    pallas_interpret: bool = False
    # Tensor-parallel width of the serving placement (registry sets it
    # from the TP knob; 1 = default, builds no mesh anywhere).  Static
    # so kernel call sites decide shard_map wrapping at trace time and
    # the autotuner keys TP entries apart (parallel/tpserve.py).
    tp: int = 1

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads


# ---------------------------------------------------------------------------
# init


def init_params(key, cfg: GPTConfig = GPTConfig()) -> Params:
    keys = jax.random.split(key, cfg.num_layers + 2)
    d = cfg.d_model
    params: Params = {
        "wte": {"embedding": normal_init(keys[0], (cfg.vocab_size, d), std=0.02)},
        "wpe": {"embedding": normal_init(keys[1], (cfg.max_position, d), std=0.01)},
        "layers": [],
        "final_ln": layernorm_init(d),
    }
    for i in range(cfg.num_layers):
        k = jax.random.split(keys[2 + i], 4)
        params["layers"].append(
            {
                "ln1": layernorm_init(d),
                "attn": {
                    "qkv": dense_init(k[0], d, 3 * d, std=0.02),
                    "out": dense_init(k[1], d, d, std=0.02),
                },
                "ln2": layernorm_init(d),
                "mlp": {
                    "up": dense_init(k[2], d, cfg.d_ff, std=0.02),
                    "down": dense_init(k[3], cfg.d_ff, d, std=0.02),
                },
            }
        )
    return params


def _qkv(p, cfg: GPTConfig, x, ad=None, li=0):
    qkv = lora.apply(ad, "qkv", li, x, dense(p["qkv"], x))
    q, k, v = jnp.split(qkv, 3, axis=-1)
    return (split_heads(t, cfg.num_heads) for t in (q, k, v))


def _attn_out(p, x, ad=None, li=0):
    """Attention output projection (+ per-row LoRA delta when serving
    a ``__adapters__`` overlay; models/lora.py)."""
    return lora.apply(ad, "out", li, x, dense(p["out"], x))


def _logits(params: Params, cfg: GPTConfig, x) -> jax.Array:
    """Tied LM head; logits in f32 for exact argmax.  Quantized tables
    go through the scale-factored matmul (``common.lm_head_logits``) so
    no full-precision copy of wte is ever materialized in the scan."""
    from .common import lm_head_logits

    return lm_head_logits(x, params["wte"]["embedding"], transposed=True)


# ---------------------------------------------------------------------------
# prefill (full prompt forward)


def forward_hidden(
    params: Params,
    cfg: GPTConfig,
    input_ids: jax.Array,  # [B, S]
    attention_mask: jax.Array,  # [B, S]
    dtype=jnp.float32,
    collect_kv: bool = False,
    prefix_kv=None,  # optional list[(k,v)] of [1, P, H, D] cached prefix
):
    """Hidden states [B, S, D] (+ per-layer prompt K/V when collecting).

    With ``prefix_kv`` the batch is the SUFFIX of a shared cached
    prompt prefix (prompt-prefix caching): tokens embed at positions
    P.., every query attends to the whole prefix plus its causal
    suffix context, and only suffix K/V is computed — prefill cost is
    O(S), not O(P+S).
    """
    b, s = input_ids.shape
    p_len = 0 if prefix_kv is None else prefix_kv[0][0].shape[1]
    x = embed(params["wte"], input_ids, dtype)
    pos = jnp.arange(p_len, p_len + s, dtype=jnp.int32)
    x = x + embed(params["wpe"], pos, dtype)[None]
    causal = jnp.tril(jnp.ones((s, s), bool))
    mask = causal[None, None] & (attention_mask[:, None, None, :] != 0)
    if p_len:
        pre = jnp.ones((1, 1, s, p_len), bool)  # prefix fully visible
        mask = jnp.concatenate([jnp.broadcast_to(pre, (b, 1, s, p_len)), mask], axis=-1)
    ad = lora.adapter_tables(params)
    kv = []
    for li, layer in enumerate(params["layers"]):
        h = layernorm(layer["ln1"], x, eps=cfg.ln_eps)
        q, k, v = _qkv(layer["attn"], cfg, h, ad, li)
        if collect_kv:
            kv.append((k, v))
        if p_len:
            pk, pv = prefix_kv[li]
            k = jnp.concatenate([jnp.broadcast_to(pk.astype(k.dtype), (b,) + pk.shape[1:]), k], axis=1)
            v = jnp.concatenate([jnp.broadcast_to(pv.astype(v.dtype), (b,) + pv.shape[1:]), v], axis=1)
        ctx = mha_attention(q, k, v, mask=mask)
        x = x + _attn_out(layer["attn"], merge_heads(ctx), ad, li)
        h = layernorm(layer["ln2"], x, eps=cfg.ln_eps)
        x = x + dense(layer["mlp"]["down"], gelu_new(dense(layer["mlp"]["up"], h)))
    x = layernorm(params["final_ln"], x, eps=cfg.ln_eps)
    return (x, kv) if collect_kv else x


def compute_prefix_kv(params: Params, cfg: GPTConfig, prefix_ids, dtype=jnp.float32):
    """Per-layer K/V of a shared prompt prefix ([1, P] ids) — computed
    ONCE at startup and carried in the params pytree under
    ``__prefix__`` so placement/sharding/jit treat it like weights."""
    ids = jnp.asarray(prefix_ids, jnp.int32).reshape(1, -1)
    _, kv = forward_hidden(
        params, cfg, ids, jnp.ones_like(ids), dtype, collect_kv=True
    )
    return {"k": [k for k, _ in kv], "v": [v for _, v in kv]}


def lm_logits(
    params: Params, cfg: GPTConfig, input_ids, attention_mask, dtype=jnp.float32
) -> jax.Array:
    """[B, S, V] next-token logits (the non-generative forward)."""
    return _logits(params, cfg, forward_hidden(params, cfg, input_ids, attention_mask, dtype))


# ---------------------------------------------------------------------------
# incremental decode


class GPTState(NamedTuple):
    """Static-shape decode state; caches span prompt + decode budget.

    EVERY field is per-row (leading dim B): rows decode independently,
    which is what lets a continuous-batching loop insert a freshly
    prefilled request into slot i while other rows are mid-generation
    (``engine/streams.py``).
    """

    cache_k: Any  # per layer [B, S+Tmax, H, D]
    cache_v: Any
    key_valid: jax.Array  # [B, S+Tmax] int32 — 1 where cache rows are real
    write_idx: jax.Array  # [B] int32 — position the NEXT step processes
    pos: jax.Array  # [B] int32 — decode steps taken per row
    last_token: jax.Array  # [B] int32 — token the next step embeds
    done: jax.Array  # [B] bool
    tokens: jax.Array  # [B, Tmax] generated tokens (pad-filled)
    sample: Any  # sampling.SampleParams, all [B]-shaped


def init_decode_state(
    params: Params,
    cfg: GPTConfig,
    input_ids: jax.Array,  # [B, S] right-padded
    attention_mask: jax.Array,  # [B, S]
    max_len: int,
    dtype=jnp.float32,
    sample=None,  # SampleParams [B] or None (greedy)
) -> GPTState:
    from .sampling import greedy_params

    b, s = input_ids.shape
    pre = params.get("__prefix__") if isinstance(params, dict) else None
    p_len = pre["k"][0].shape[1] if pre is not None else 0
    prefix_kv = list(zip(pre["k"], pre["v"])) if pre is not None else None
    total = p_len + s + max_len
    _, kv = forward_hidden(
        params, cfg, input_ids, attention_mask, dtype,
        collect_kv=True, prefix_kv=prefix_kv,
    )
    cache_k, cache_v = [], []
    for li, (k, v) in enumerate(kv):
        ck = jnp.zeros((b, total, cfg.num_heads, cfg.head_dim), k.dtype)
        cv = ck
        if p_len:
            pk, pv = prefix_kv[li]
            ck = ck.at[:, :p_len].set(pk.astype(ck.dtype))
            cv = cv.at[:, :p_len].set(pv.astype(cv.dtype))
        cache_k.append(ck.at[:, p_len : p_len + s].set(k))
        cache_v.append(cv.at[:, p_len : p_len + s].set(v))
    lengths = attention_mask.sum(axis=-1).astype(jnp.int32)  # [B]
    key_valid = jnp.zeros((b, total), jnp.int32)
    if p_len:
        key_valid = key_valid.at[:, :p_len].set(1)
    key_valid = key_valid.at[:, p_len : p_len + s].set(
        attention_mask.astype(jnp.int32)
    )
    rows = jnp.arange(b)
    # The first step re-processes the last prompt token at its own
    # position (identical K/V overwrite), producing the first generated
    # token's logits — one uniform step fn, no prefill/decode seam.
    last_tok = input_ids[rows, jnp.maximum(lengths - 1, 0)]
    return GPTState(
        cache_k=cache_k,
        cache_v=cache_v,
        key_valid=key_valid,
        write_idx=p_len + jnp.maximum(lengths - 1, 0),
        pos=jnp.zeros((b,), jnp.int32),
        last_token=last_tok.astype(jnp.int32),
        done=lengths == 0,  # fully-pad rows never generate
        tokens=jnp.full((b, max_len), cfg.pad_id, jnp.int32),
        sample=sample if sample is not None else greedy_params(b),
    )


def _decode_step(params: Params, cfg: GPTConfig, state: GPTState, sample: bool = False):
    dtype = state.cache_k[0].dtype
    b = state.last_token.shape[0]
    rows = jnp.arange(b)
    t = state.write_idx  # [B] per-row position
    x = embed(params["wte"], state.last_token[:, None], dtype)  # [B,1,D]
    # Long-dead rows (continuous batching: slot freed, not yet reused)
    # keep stepping; clamp their position lookup and DROP their writes
    # so they never corrupt in-range cache entries.
    x = x + embed(params["wpe"], jnp.minimum(t, cfg.max_position - 1), dtype)[:, None]
    key_valid = state.key_valid.at[rows, t].set(1, mode="drop")
    attn_mask = (key_valid != 0)[:, None, None, :]  # [B,1,1,total]

    ad = lora.adapter_tables(params)
    new_k, new_v = [], []
    for li, layer in enumerate(params["layers"]):
        h = layernorm(layer["ln1"], x, eps=cfg.ln_eps)
        q, k1, v1 = _qkv(layer["attn"], cfg, h, ad, li)  # [B,1,H,D]
        ck = state.cache_k[li].at[rows, t].set(k1[:, 0], mode="drop")
        cv = state.cache_v[li].at[rows, t].set(v1[:, 0], mode="drop")
        new_k.append(ck)
        new_v.append(cv)
        ctx = mha_attention(q, ck, cv, mask=attn_mask)
        x = x + _attn_out(layer["attn"], merge_heads(ctx), ad, li)
        h = layernorm(layer["ln2"], x, eps=cfg.ln_eps)
        x = x + dense(layer["mlp"]["down"], gelu_new(dense(layer["mlp"]["up"], h)))
    x = layernorm(params["final_ln"], x, eps=cfg.ln_eps)
    logits = _logits(params, cfg, x[:, 0])  # [B, V]

    if sample:
        from .sampling import select_token

        next_tok, sp = select_token(logits, state.sample)
    else:
        next_tok, sp = jnp.argmax(logits, axis=-1).astype(jnp.int32), state.sample
    next_tok = jnp.where(state.done, jnp.int32(cfg.pad_id), next_tok)
    done = state.done | (next_tok == cfg.eos_id)
    tokens = state.tokens.at[rows, state.pos].set(next_tok, mode="drop")
    new_state = GPTState(
        cache_k=new_k,
        cache_v=new_v,
        key_valid=key_valid,
        write_idx=t + 1,
        pos=state.pos + 1,
        last_token=next_tok,
        done=done,
        tokens=tokens,
        sample=sp,
    )
    return new_state, next_tok


def multi_step(
    params: Params, cfg: GPTConfig, state: GPTState, tokens: jax.Array
) -> tuple[list, list, jax.Array]:
    """Window forward for speculative verification (models/spec.py):
    process D tokens per row at positions write_idx..write_idx+D-1 in
    ONE pass.  Writes K/V for every window position (cache rows beyond
    the buffer drop), attends each query to the valid cache PLUS its
    causal in-window prefix, and returns (new_k, new_v, logits
    [B, D, V]).  key_valid is NOT updated here — acceptance decides
    which window positions become real (spec.verify_step)."""
    dtype = state.cache_k[0].dtype
    b, d_w = tokens.shape
    rows = jnp.arange(b)[:, None]  # [B, 1]
    t = state.write_idx  # [B]
    pos_w = t[:, None] + jnp.arange(d_w)[None]  # [B, D]
    x = embed(params["wte"], tokens, dtype)  # [B, D, Dm]
    x = x + embed(params["wpe"], jnp.minimum(pos_w, cfg.max_position - 1), dtype)
    total = state.key_valid.shape[1]
    pos_k = jnp.arange(total)[None, None]  # [1, 1, total]
    base_valid = (state.key_valid != 0)[:, None, :]  # [B, 1, total]
    in_window = (pos_k >= t[:, None, None]) & (pos_k <= pos_w[:, :, None])
    mask = (base_valid | in_window)[:, None]  # [B, 1, D, total]

    ad = lora.adapter_tables(params)
    new_k, new_v = [], []
    for li, layer in enumerate(params["layers"]):
        h = layernorm(layer["ln1"], x, eps=cfg.ln_eps)
        q, k1, v1 = _qkv(layer["attn"], cfg, h, ad, li)  # [B, D, H, Dh]
        ck = state.cache_k[li].at[rows, pos_w].set(k1, mode="drop")
        cv = state.cache_v[li].at[rows, pos_w].set(v1, mode="drop")
        new_k.append(ck)
        new_v.append(cv)
        ctx = mha_attention(q, ck, cv, mask=mask)
        x = x + _attn_out(layer["attn"], merge_heads(ctx), ad, li)
        h = layernorm(layer["ln2"], x, eps=cfg.ln_eps)
        x = x + dense(layer["mlp"]["down"], gelu_new(dense(layer["mlp"]["up"], h)))
    x = layernorm(params["final_ln"], x, eps=cfg.ln_eps)
    return new_k, new_v, _logits(params, cfg, x)  # [B, D, V]


def generate_chunk(
    params: Params, cfg: GPTConfig, state: GPTState, n_steps: int, sample: bool = False
) -> tuple[GPTState, jax.Array]:
    """``n_steps`` decode steps in one compiled scan; returns
    (state, [B, n_steps] tokens) — the engine's chunk contract.
    ``sample`` is STATIC: False compiles the argmax fast path (no
    [B, V] sort per step), True the per-row sampling path."""

    def step(s, _):
        return _decode_step(params, cfg, s, sample)

    state, toks = jax.lax.scan(step, state, None, length=n_steps)
    return state, jnp.transpose(toks)


def generate_window(
    params: Params, cfg: GPTConfig, state: GPTState, n_steps: int,
    max_chunks: int, sample: bool = False,
):
    """Up to ``max_chunks`` chunk scans fused into ONE dispatch with
    on-device EOS early exit (models/window.py) — the engine's fused
    decode-window contract (DECODE_WINDOW).  Body == ``generate_chunk``
    verbatim, so the window is token-identical to dispatching the same
    chunks one by one."""
    from .window import decode_window

    return decode_window(
        lambda s: generate_chunk(params, cfg, s, n_steps, sample),
        state, n_steps, max_chunks, cfg.pad_id,
    )


def greedy_generate(
    params: Params,
    cfg: GPTConfig,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    max_len: int,
    dtype=jnp.float32,
) -> jax.Array:
    """Prefill + full decode scan, single dispatch → [B, max_len]."""
    state = init_decode_state(params, cfg, input_ids, attention_mask, max_len, dtype)
    state, _ = generate_chunk(params, cfg, state, max_len)
    return state.tokens


# ---------------------------------------------------------------------------
# block-paged decode (PAGED_KV=1; engine/kv_blocks.py owns the tables)


class PagedState(NamedTuple):
    """Decode state over a block-paged KV pool (``PAGED_KV=1``).

    Identical to ``GPTState`` except the caches: instead of per-row
    contiguous ``[B, W, H, D]`` slabs, K/V live in pools of
    ``block_size``-token blocks ``[NB, BS, H, D]`` shared by every
    row, and logical position ``p`` of row ``b`` resolves through a
    host-owned block table (``table[b, p // BS]``) that rides into
    each dispatch as a traced argument — NOT part of this state, so
    the host can grow/free blocks between dispatches without touching
    device buffers.  All non-cache fields keep their per-row GPTState
    semantics, which is what keeps paged decode token-identical to the
    contiguous layout: positions, masks and sampling never change,
    only where a KV row physically lives."""

    cache_k: Any  # per layer [NB, BS, H, D] pool ((int8, scale) under QUANT_KV)
    cache_v: Any
    key_valid: jax.Array  # [B, W] int32 over LOGICAL positions (W = T*BS)
    write_idx: jax.Array  # [B]
    pos: jax.Array  # [B]
    last_token: jax.Array  # [B]
    done: jax.Array  # [B]
    tokens: jax.Array  # [B, Tmax]
    sample: Any


def _paged_dest(table: jax.Array, t: jax.Array, bs: int, nb: int) -> jax.Array:
    """Flat pool index of logical position ``t`` per row; out-of-table
    positions (long-dead rows) and sentinel table entries both resolve
    out of range so ``.at[].set(mode="drop")`` drops them."""
    bidx = t // bs
    blk = jnp.take_along_axis(
        table, jnp.minimum(bidx, table.shape[1] - 1)[:, None], axis=1
    )[:, 0]
    blk = jnp.where(bidx < table.shape[1], blk, nb)
    return blk * bs + t % bs


def paged_write_token(pool, table, t, val, bs: int):
    """Scatter one new K (or V) row per batch row into a dense pool."""
    nb = pool.shape[0]
    flat = pool.reshape((nb * bs,) + pool.shape[2:])
    dest = _paged_dest(table, t, bs, nb)
    flat = flat.at[dest].set(val.astype(pool.dtype), mode="drop")
    return flat.reshape(pool.shape)


def _paged_decode_step(
    params: Params, cfg: GPTConfig, state: PagedState, table: jax.Array,
    sample: bool = False,
):
    """One decode step reading/writing K/V through the block table;
    everything else is ``_decode_step`` verbatim — same positions,
    same mask semantics, same logits — so greedy outputs are
    token-identical to the contiguous path."""
    from ..ops.paged_attention import gather_pages

    dtype = state.cache_k[0].dtype
    bs = state.cache_k[0].shape[1]
    b = state.last_token.shape[0]
    rows = jnp.arange(b)
    t = state.write_idx
    x = embed(params["wte"], state.last_token[:, None], dtype)
    x = x + embed(params["wpe"], jnp.minimum(t, cfg.max_position - 1), dtype)[:, None]
    key_valid = state.key_valid.at[rows, t].set(1, mode="drop")
    attn_mask = (key_valid != 0)[:, None, None, :]

    ad = lora.adapter_tables(params)
    new_k, new_v = [], []
    for li, layer in enumerate(params["layers"]):
        h = layernorm(layer["ln1"], x, eps=cfg.ln_eps)
        q, k1, v1 = _qkv(layer["attn"], cfg, h, ad, li)
        ck = paged_write_token(state.cache_k[li], table, t, k1[:, 0], bs)
        cv = paged_write_token(state.cache_v[li], table, t, v1[:, 0], bs)
        new_k.append(ck)
        new_v.append(cv)
        if cfg.pallas_decode:
            from ..ops import autotune
            from ..ops.paged_attention import paged_decode_attention

            vkey = cfg.pallas_variant or autotune.lookup(
                "paged_decode", b=b, kvh=ck.shape[2], n_rep=1,
                d=q.shape[3], block_size=bs, t=table.shape[1],
                dtype=str(q.dtype), quant=False, tp=cfg.tp,
            )
            ctx = paged_decode_attention(
                q[:, 0], ck, cv, table, key_valid, bs,
                interpret=cfg.pallas_interpret, variant=vkey, tp=cfg.tp,
            )[:, None]
        else:
            kd = gather_pages(ck, table, bs)
            vd = gather_pages(cv, table, bs)
            ctx = mha_attention(q, kd, vd, mask=attn_mask)
        x = x + _attn_out(layer["attn"], merge_heads(ctx), ad, li)
        h = layernorm(layer["ln2"], x, eps=cfg.ln_eps)
        x = x + dense(layer["mlp"]["down"], gelu_new(dense(layer["mlp"]["up"], h)))
    x = layernorm(params["final_ln"], x, eps=cfg.ln_eps)
    logits = _logits(params, cfg, x[:, 0])

    if sample:
        from .sampling import select_token

        next_tok, sp = select_token(logits, state.sample)
    else:
        next_tok, sp = jnp.argmax(logits, axis=-1).astype(jnp.int32), state.sample
    next_tok = jnp.where(state.done, jnp.int32(cfg.pad_id), next_tok)
    done = state.done | (next_tok == cfg.eos_id)
    tokens = state.tokens.at[rows, state.pos].set(next_tok, mode="drop")
    return (
        PagedState(
            cache_k=new_k, cache_v=new_v, key_valid=key_valid,
            write_idx=t + 1, pos=state.pos + 1, last_token=next_tok,
            done=done, tokens=tokens, sample=sp,
        ),
        next_tok,
    )


def generate_chunk_paged(
    params: Params, cfg: GPTConfig, state: PagedState, table: jax.Array,
    n_steps: int, sample: bool = False,
) -> tuple[PagedState, jax.Array]:
    """``n_steps`` paged decode steps in one compiled scan (the
    engine's chunk contract, plus the traced block table)."""

    def step(s, _):
        return _paged_decode_step(params, cfg, s, table, sample)

    state, toks = jax.lax.scan(step, state, None, length=n_steps)
    return state, jnp.transpose(toks)


def generate_window_paged(
    params: Params, cfg: GPTConfig, state: PagedState, table: jax.Array,
    n_steps: int, max_chunks: int, sample: bool = False,
):
    """Paged fused decode window: up to ``max_chunks`` paged chunk
    scans in one dispatch, EOS early exit on device.  The block table
    is constant across the window — the engine pre-provisions blocks
    for all ``max_chunks`` chunks up front and reconciles the ledger
    at the window boundary."""
    from .window import decode_window

    return decode_window(
        lambda s: generate_chunk_paged(params, cfg, s, table, n_steps, sample),
        state, n_steps, max_chunks, cfg.pad_id,
    )


# ---------------------------------------------------------------------------
# chunked prefill (PREFILL_CHUNK; engine/streams.py drives the windows)


def empty_decode_state(
    params: Params,
    cfg: GPTConfig,
    batch: int,
    s_total: int,
    max_len: int,
    dtype=jnp.float32,
) -> GPTState:
    """All-zero decode state sized for a chunked prefill: caches span
    ``s_total`` prompt positions plus the decode budget, every row
    born done.  ``prefill_chunk`` fills the prompt region window by
    window; the continuous loop flips the row live (write_idx /
    last_token / done / sample) once the prompt is exhausted, at which
    point the state is positionally what ``init_decode_state`` would
    have produced for the same prompt."""
    from .sampling import greedy_params

    total = s_total + max_len
    cache = [
        jnp.zeros((batch, total, cfg.num_heads, cfg.head_dim), dtype)
        for _ in params["layers"]
    ]
    return GPTState(
        cache_k=cache,
        cache_v=list(cache),
        key_valid=jnp.zeros((batch, total), jnp.int32),
        write_idx=jnp.zeros((batch,), jnp.int32),
        pos=jnp.zeros((batch,), jnp.int32),
        last_token=jnp.zeros((batch,), jnp.int32),
        done=jnp.ones((batch,), bool),
        tokens=jnp.full((batch, max_len), cfg.pad_id, jnp.int32),
        sample=greedy_params(batch),
    )


def _window_mask(base_valid: jax.Array, chunk_mask: jax.Array, start):
    """[B, 1, C, total] attention mask for one prefill window: every
    already-valid cache position (``base_valid`` [B, total] bool —
    previous windows, or an adopted/seeded prefix) plus the causal,
    pad-gated in-window prefix.  ``start`` is traced, so one
    executable serves every window of a prompt."""
    b, c = chunk_mask.shape
    total = base_valid.shape[1]
    pos_k = jnp.arange(total)[None, :]  # [1, total]
    off = pos_k - start  # key offset into the window
    in_win = (off >= 0) & (off < c)
    wvalid = jnp.take_along_axis(
        chunk_mask.astype(jnp.int32),
        jnp.clip(jnp.broadcast_to(off, (b, total)), 0, c - 1),
        axis=1,
    )
    win_keys = in_win & (wvalid != 0)  # [B, total]
    causal = off[:, None, :] <= jnp.arange(c)[None, :, None]  # [1, C, total]
    return (base_valid[:, None, :] | (win_keys[:, None, :] & causal))[:, None]


def prefill_chunk(
    params: Params,
    cfg: GPTConfig,
    state: GPTState,
    chunk_ids: jax.Array,  # [B, C] window of the prompt, right-padded
    chunk_mask: jax.Array,  # [B, C]
    start,  # traced scalar: absolute position of chunk_ids[:, 0]
    dtype=jnp.float32,
) -> GPTState:
    """Consume one prompt window [start, start+C) into the decode
    state: K/V written at absolute positions, ``key_valid`` extended,
    each window query attending to the whole already-prefilled prefix
    plus its causal in-window context — token-identical to the
    monolithic prompt forward, one bounded dispatch at a time.  The
    last window's pad tail writes junk K/V past the prompt (exactly
    like monolithic prefill's bucket padding): ``key_valid`` never
    marks it, and decode overwrites each position in the same step
    that validates it."""
    b, c = chunk_ids.shape
    rows = jnp.arange(b)[:, None]
    pos_w = jnp.broadcast_to(start + jnp.arange(c)[None, :], (b, c))
    x = embed(params["wte"], chunk_ids, dtype)
    x = x + embed(params["wpe"], jnp.minimum(pos_w, cfg.max_position - 1), dtype)
    mask = _window_mask(state.key_valid != 0, chunk_mask, start)

    ad = lora.adapter_tables(params)
    new_k, new_v = [], []
    for li, layer in enumerate(params["layers"]):
        h = layernorm(layer["ln1"], x, eps=cfg.ln_eps)
        q, k1, v1 = _qkv(layer["attn"], cfg, h, ad, li)  # [B, C, H, D]
        ck = state.cache_k[li].at[rows, pos_w].set(k1, mode="drop")
        cv = state.cache_v[li].at[rows, pos_w].set(v1, mode="drop")
        new_k.append(ck)
        new_v.append(cv)
        ctx = mha_attention(q, ck, cv, mask=mask)
        x = x + _attn_out(layer["attn"], merge_heads(ctx), ad, li)
        h = layernorm(layer["ln2"], x, eps=cfg.ln_eps)
        x = x + dense(layer["mlp"]["down"], gelu_new(dense(layer["mlp"]["up"], h)))
    key_valid = state.key_valid.at[rows, pos_w].set(
        chunk_mask.astype(jnp.int32), mode="drop"
    )
    return state._replace(cache_k=new_k, cache_v=new_v, key_valid=key_valid)


def paged_prefill_chunk(
    params: Params,
    cfg: GPTConfig,
    state: PagedState,
    table_row: jax.Array,  # [T] this stream's block table (sentinel-padded)
    chunk_ids: jax.Array,  # [1, C]
    chunk_mask: jax.Array,  # [1, C]
    start,
    dtype=jnp.float32,
) -> PagedState:
    """One prompt window written straight into the stream's pool
    blocks (PREFILL_CHUNK × PAGED_KV): K/V scatter through the block
    table at absolute positions; attention reads back through a dense
    gather of the stream's own blocks (adopted CoW prefix blocks
    included, so a prefix-cache hit suffix-prefills in chunks with no
    KV copy).  Only the pool leaves change — the slot rows' logical
    fields belong to OTHER streams and are untouched; this stream's
    row fields land at handoff (engine/streams.py).  Valid keys are
    exactly the positions below ``start``: the prompt is contiguous
    from 0, so no per-row key_valid is needed mid-prefill."""
    from ..ops.paged_attention import gather_pages, scatter_pages

    b, c = chunk_ids.shape  # b == 1: prefill windows are per-stream
    bs = state.cache_k[0].shape[1]
    pos_w = jnp.broadcast_to(start + jnp.arange(c)[None, :], (b, c))
    x = embed(params["wte"], chunk_ids, dtype)
    x = x + embed(params["wpe"], jnp.minimum(pos_w, cfg.max_position - 1), dtype)
    total = table_row.shape[0] * bs
    base_valid = jnp.broadcast_to(jnp.arange(total)[None, :] < start, (b, total))
    mask = _window_mask(base_valid, chunk_mask, start)

    ad = lora.adapter_tables(params)
    new_k, new_v = [], []
    for li, layer in enumerate(params["layers"]):
        h = layernorm(layer["ln1"], x, eps=cfg.ln_eps)
        q, k1, v1 = _qkv(layer["attn"], cfg, h, ad, li)
        ck = scatter_pages(state.cache_k[li], table_row, k1[0], bs, start=start)
        cv = scatter_pages(state.cache_v[li], table_row, v1[0], bs, start=start)
        new_k.append(ck)
        new_v.append(cv)
        kd = gather_pages(ck, table_row[None], bs)
        vd = gather_pages(cv, table_row[None], bs)
        ctx = mha_attention(q, kd, vd, mask=mask)
        x = x + _attn_out(layer["attn"], merge_heads(ctx), ad, li)
        h = layernorm(layer["ln2"], x, eps=cfg.ln_eps)
        x = x + dense(layer["mlp"]["down"], gelu_new(dense(layer["mlp"]["up"], h)))
    return state._replace(cache_k=new_k, cache_v=new_v)


def init_paged_state(
    params: Params,
    cfg: GPTConfig,
    input_ids: jax.Array,  # [B, S] right-padded
    attention_mask: jax.Array,
    max_len: int,
    table: jax.Array,  # [B, T] block ids covering S (+ growth later)
    num_blocks: int,
    block_size: int,
    dtype=jnp.float32,
    sample=None,
) -> PagedState:
    """Prefill straight into pool blocks: the prompt forward's K/V
    scatter through the table instead of filling a contiguous slab."""
    from ..ops.paged_attention import scatter_pages
    from .sampling import greedy_params

    b, s = input_ids.shape
    t_w = table.shape[1]
    _, kv = forward_hidden(
        params, cfg, input_ids, attention_mask, dtype, collect_kv=True
    )
    cache_k, cache_v = [], []
    for k, v in kv:
        shape = (num_blocks, block_size, cfg.num_heads, cfg.head_dim)
        ck = jnp.zeros(shape, k.dtype)
        cv = jnp.zeros(shape, v.dtype)
        for row in range(b):
            ck = scatter_pages(ck, table[row], k[row], block_size)
            cv = scatter_pages(cv, table[row], v[row], block_size)
        cache_k.append(ck)
        cache_v.append(cv)
    lengths = attention_mask.sum(axis=-1).astype(jnp.int32)
    key_valid = jnp.zeros((b, t_w * block_size), jnp.int32)
    key_valid = key_valid.at[:, :s].set(attention_mask.astype(jnp.int32))
    rows = jnp.arange(b)
    last_tok = input_ids[rows, jnp.maximum(lengths - 1, 0)]
    return PagedState(
        cache_k=cache_k,
        cache_v=cache_v,
        key_valid=key_valid,
        write_idx=jnp.maximum(lengths - 1, 0),
        pos=jnp.zeros((b,), jnp.int32),
        last_token=last_tok.astype(jnp.int32),
        done=lengths == 0,
        tokens=jnp.full((b, max_len), cfg.pad_id, jnp.int32),
        sample=sample if sample is not None else greedy_params(b),
    )
