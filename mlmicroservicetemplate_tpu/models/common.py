"""Shared pure-function building blocks for the JAX model zoo.

Design: every model is (init_params, apply) over a plain nested-dict
pytree — no module framework. Pure functions keep the whole forward pass
inside one jit trace (single XLA executable per shape bucket), make
params trivially shardable with ``jax.sharding`` (any leaf can carry a
NamedSharding), and keep checkpoint conversion a dumb dict mapping.

Layout conventions (TPU-first):
- images NHWC, conv kernels HWIO (XLA's native TPU layouts; the
  reference's NCHW/OIHW torch layouts are converted at checkpoint load).
- attention activations [B, S, H, D]; matmuls via einsum so XLA fuses
  and tiles them onto the MXU.
- params stored in ``param_dtype`` (bf16 on TPU), compute in
  ``compute_dtype``, logits returned in f32.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

Params = dict[str, Any]


# ---------------------------------------------------------------------------
# initializers


def _fan_in_out(shape: tuple[int, ...]) -> tuple[int, int]:
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv HWIO: receptive * in, receptive * out
    receptive = math.prod(shape[:-2])
    return shape[-2] * receptive, shape[-1] * receptive


def kaiming_normal(key, shape, dtype=jnp.float32):
    fan_in, _ = _fan_in_out(shape)
    std = math.sqrt(2.0 / fan_in)
    return jax.random.normal(key, shape, dtype) * std


def xavier_uniform(key, shape, dtype=jnp.float32):
    fan_in, fan_out = _fan_in_out(shape)
    a = math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, dtype, -a, a)


def normal_init(key, shape, dtype=jnp.float32, std=0.02):
    return jax.random.normal(key, shape, dtype) * std


# ---------------------------------------------------------------------------
# primitive layers


def dense_init(key, d_in: int, d_out: int, bias: bool = True, std: float | None = None):
    kw, _ = jax.random.split(key)
    if std is None:
        w = xavier_uniform(kw, (d_in, d_out))
    else:
        w = normal_init(kw, (d_in, d_out), std=std)
    p: Params = {"kernel": w}
    if bias:
        p["bias"] = jnp.zeros((d_out,))
    return p


def maybe_dequant(w, dtype) -> jax.Array:
    """Transparent int8 weight-only dequant (see ``models/quant.py``):
    a quantized leaf is {"q8", "scale"}; the convert+multiply fuses
    into the consuming matmul's operand load under XLA."""
    if isinstance(w, dict) and "q8" in w:
        return w["q8"].astype(dtype) * w["scale"].astype(dtype)
    return w.astype(dtype)


def lm_head_logits(x: jax.Array, w, transposed: bool = False) -> jax.Array:
    """f32 logits for a (possibly int8-quantized) LM head.

    For a quantized weight the per-output-channel scale factors out of
    the matmul *exactly* — logits = (x @ q8) * scale — so the int8
    table is never dequantized in full.  The naive
    ``x @ maybe_dequant(w).T`` materializes a full-precision copy of
    the largest tensor on the decode path (e.g. GPT-2's [50257, 768]
    wte), which XLA hoists out of the decode scan as loop-invariant,
    negating the int8 HBM saving; this form keeps only the int8 bytes
    resident.

    ``transposed=True`` means ``w`` is an embedding table [V, D] (tied
    head, per-ROW scales); otherwise a kernel [D, V] (per-column).
    """
    if isinstance(w, dict) and "q8" in w:
        q8 = w["q8"]
        scale = w["scale"].astype(jnp.float32)
        xf = x.astype(jnp.float32)
        if transposed:  # [V, D] table, scale [V, 1] -> one scale per logit
            return (xf @ q8.T.astype(jnp.float32)) * scale[:, 0][None, :]
        return (xf @ q8.astype(jnp.float32)) * scale  # scale [1, V]
    wf = w.astype(jnp.float32)
    if transposed:
        wf = wf.T
    return x.astype(jnp.float32) @ wf


def dense(p: Params, x: jax.Array) -> jax.Array:
    y = x @ maybe_dequant(p["kernel"], x.dtype)
    if "bias" in p:
        y = y + p["bias"].astype(x.dtype)
    return y


def conv_init(key, kh: int, kw: int, c_in: int, c_out: int):
    return {"kernel": kaiming_normal(key, (kh, kw, c_in, c_out))}


def conv2d(p: Params, x: jax.Array, stride: int = 1, padding="SAME") -> jax.Array:
    """NHWC conv with HWIO kernel — the MXU-friendly layout."""
    return lax.conv_general_dilated(
        x,
        maybe_dequant(p["kernel"], x.dtype),
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def batchnorm_init(c: int):
    """Inference-mode BN state (running stats + affine)."""
    return {
        "scale": jnp.ones((c,)),
        "bias": jnp.zeros((c,)),
        "mean": jnp.zeros((c,)),
        "var": jnp.ones((c,)),
    }


def batchnorm(p: Params, x: jax.Array, eps: float = 1e-5) -> jax.Array:
    """Inference BN as a single fused affine: y = x * g + b.

    The rescale is precomputed in f32 (rsqrt of running var) then cast,
    so bf16 activations see one multiply-add — XLA fuses this into the
    preceding conv's epilogue.
    """
    g = (p["scale"] * lax.rsqrt(p["var"] + eps)).astype(x.dtype)
    b = (p["bias"] - p["mean"] * p["scale"] * lax.rsqrt(p["var"] + eps)).astype(x.dtype)
    return x * g + b


def layernorm_init(d: int):
    return {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))}


def layernorm(p: Params, x: jax.Array, eps: float = 1e-12) -> jax.Array:
    # Normalize in f32 for numerical stability, cast back for the MXU.
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    y = (xf - mu) * lax.rsqrt(var + eps)
    y = y * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)
    return y.astype(x.dtype)


def rmsnorm_init(d: int):
    return {"scale": jnp.ones((d,))}


def rmsnorm(p: Params, x: jax.Array, eps: float = 1e-6) -> jax.Array:
    """T5-style LayerNorm: no mean subtraction, no bias."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * lax.rsqrt(var + eps) * p["scale"].astype(jnp.float32)
    return y.astype(x.dtype)


def embedding_init(key, vocab: int, d: int, std: float = 0.02):
    return {"embedding": normal_init(key, (vocab, d), std=std)}


def embed(p: Params, ids: jax.Array, dtype=None) -> jax.Array:
    t = p["embedding"]
    if isinstance(t, dict) and "q8" in t:
        # Per-ROW scales: gather rows + their scales, dequant only what
        # the lookup touches (never the whole table).
        rows = jnp.take(t["q8"], ids, axis=0)
        scales = jnp.take(t["scale"], ids, axis=0)
        out_dtype = dtype if dtype is not None else jnp.float32
        return rows.astype(out_dtype) * scales.astype(out_dtype)
    if dtype is not None:
        t = t.astype(dtype)
    return jnp.take(t, ids, axis=0)


def gelu(x: jax.Array) -> jax.Array:
    # erf-based gelu (matches torch nn.GELU default / BERT "gelu").
    return jax.nn.gelu(x, approximate=False)


# ---------------------------------------------------------------------------
# attention


def mha_attention(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Sk, H, D]
    v: jax.Array,  # [B, Sk, H, D]
    mask: jax.Array | None = None,  # broadcastable to [B, H, Sq, Sk]
    bias: jax.Array | None = None,  # additive, broadcastable to [B, H, Sq, Sk]
    scale: float | None = None,
) -> jax.Array:
    """Batched multi-head attention core; returns [B, Sq, H, D].

    Softmax runs in f32 regardless of activation dtype. The two einsums
    are the MXU work; XLA fuses mask/bias/softmax between them.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.float32(-1e9))
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def kv_quantize(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-token-per-head symmetric int8 for K/V cache storage:
    [..., H, D] → (int8 same shape, f32 scale [..., H, 1]).

    The KV cache is the second HBM-bandwidth term of batched long-
    context decode (after weights); int8 halves its bytes and the
    scale factors out of both attention matmuls EXACTLY — see
    ``mha_attention_kv8`` — so no dense dequantized copy ever
    materializes (same discipline as ``lm_head_logits``)."""
    from .quant import symmetric_int8

    return symmetric_int8(x, axis=-1)


def mha_attention_kv8(
    q: jax.Array,  # [B, Sq, H, D]
    k8: jax.Array,  # [B, Sk, H, D] int8
    k_scale: jax.Array,  # [B, Sk, H, 1] f32
    v8: jax.Array,  # [B, Sk, H, D] int8
    v_scale: jax.Array,  # [B, Sk, H, 1] f32
    mask: jax.Array | None = None,
    scale: float | None = None,
) -> jax.Array:
    """mha_attention over an int8-quantized KV cache.

    Scale factoring keeps the HBM reads at int8 width: the key scale
    multiplies the logit COLUMN it belongs to (logits[...,k] ∝ q·k8[k]
    · ks[k]), and the value scale folds into the softmax weights
    before the second matmul (Σ_k w[k]·vs[k]·v8[k] = (w·vs) @ v8) —
    both matmuls consume the int8 tensors directly (cast in-register),
    never a dense dequantized cache."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    # [B, Sk, H, 1] -> [B, H, 1, Sk] to line up with bhqk logits.
    ks = jnp.transpose(k_scale[..., 0], (0, 2, 1))[:, :, None, :]
    vs = jnp.transpose(v_scale[..., 0], (0, 2, 1))[:, :, None, :]
    logits = (
        jnp.einsum("bqhd,bkhd->bhqk", q, k8.astype(q.dtype)).astype(jnp.float32)
        * scale
        * ks
    )
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.float32(-1e9))
    probs = jax.nn.softmax(logits, axis=-1)
    weighted = (probs * vs).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", weighted, v8.astype(q.dtype))


def split_heads(x: jax.Array, n_heads: int) -> jax.Array:
    b, s, d = x.shape
    return x.reshape(b, s, n_heads, d // n_heads)


def merge_heads(x: jax.Array) -> jax.Array:
    b, s, h, d = x.shape
    return x.reshape(b, s, h * d)


def count_params(params: Params) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(params))


def cast_pytree(params: Params, dtype) -> Params:
    """Cast all floating leaves to ``dtype`` (int leaves untouched)."""
    def _cast(p):
        if jnp.issubdtype(p.dtype, jnp.floating):
            return p.astype(dtype)
        return p

    return jax.tree.map(_cast, params)
