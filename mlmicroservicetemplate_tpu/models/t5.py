"""T5-small encoder-decoder, pure-JAX, with KV-cached incremental decode.

Capability parity: the reference streams seq2seq generations (T5-small
summarization) through ``/predict`` (BASELINE.json:12). This is a
ground-up JAX implementation of the T5 architecture: pre-LN blocks with
RMSNorm, relative-position-bucket attention bias (shared from layer 0),
unscaled dot-product attention, ReLU feed-forward, tied lm_head with
d_model**-0.5 output scaling.

TPU-first decode design (SURVEY.md §7.4.2): generation runs as a
``lax.scan`` over decode steps inside ONE jit — static-shape KV caches
sized to ``max_decode_len``, no per-token Python dispatch. Streaming is
chunked: the engine calls ``generate_chunk`` (one dispatch per K tokens)
and forwards tokens to the HTTP layer between chunks.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from .common import (
    Params,
    dense,
    dense_init,
    embed,
    merge_heads,
    mha_attention,
    normal_init,
    rmsnorm,
    rmsnorm_init,
    split_heads,
)


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64
    num_heads: int = 8
    d_ff: int = 2048
    num_layers: int = 6
    rel_buckets: int = 32
    rel_max_distance: int = 128
    pad_id: int = 0
    eos_id: int = 1
    decoder_start_id: int = 0

    @property
    def inner_dim(self) -> int:
        return self.num_heads * self.d_kv


# ---------------------------------------------------------------------------
# init


def _attn_init(key, cfg: T5Config, with_rel_bias: bool) -> Params:
    keys = jax.random.split(key, 5)
    d, inner = cfg.d_model, cfg.inner_dim
    p: Params = {
        "q": dense_init(keys[0], d, inner, bias=False, std=(d * cfg.d_kv) ** -0.5),
        "k": dense_init(keys[1], d, inner, bias=False, std=d**-0.5),
        "v": dense_init(keys[2], d, inner, bias=False, std=d**-0.5),
        "out": dense_init(keys[3], inner, d, bias=False, std=inner**-0.5),
    }
    if with_rel_bias:
        p["rel_bias"] = {
            "embedding": normal_init(keys[4], (cfg.rel_buckets, cfg.num_heads), std=d**-0.5)
        }
    return p


def _mlp_init(key, cfg: T5Config) -> Params:
    k1, k2 = jax.random.split(key)
    return {
        "wi": dense_init(k1, cfg.d_model, cfg.d_ff, bias=False, std=cfg.d_model**-0.5),
        "wo": dense_init(k2, cfg.d_ff, cfg.d_model, bias=False, std=cfg.d_ff**-0.5),
    }


def init_params(key, cfg: T5Config = T5Config()) -> Params:
    keys = jax.random.split(key, 2 * cfg.num_layers + 2)
    params: Params = {
        "shared": {"embedding": normal_init(keys[0], (cfg.vocab_size, cfg.d_model), std=1.0)},
        "encoder": {"layers": [], "final_ln": rmsnorm_init(cfg.d_model)},
        "decoder": {"layers": [], "final_ln": rmsnorm_init(cfg.d_model)},
    }
    for i in range(cfg.num_layers):
        k = jax.random.split(keys[1 + i], 2)
        params["encoder"]["layers"].append(
            {
                "attn": _attn_init(k[0], cfg, with_rel_bias=(i == 0)),
                "attn_ln": rmsnorm_init(cfg.d_model),
                "mlp": _mlp_init(k[1], cfg),
                "mlp_ln": rmsnorm_init(cfg.d_model),
            }
        )
    for i in range(cfg.num_layers):
        k = jax.random.split(keys[1 + cfg.num_layers + i], 3)
        params["decoder"]["layers"].append(
            {
                "self_attn": _attn_init(k[0], cfg, with_rel_bias=(i == 0)),
                "self_attn_ln": rmsnorm_init(cfg.d_model),
                "cross_attn": _attn_init(k[1], cfg, with_rel_bias=False),
                "cross_attn_ln": rmsnorm_init(cfg.d_model),
                "mlp": _mlp_init(k[2], cfg),
                "mlp_ln": rmsnorm_init(cfg.d_model),
            }
        )
    return params


# ---------------------------------------------------------------------------
# relative position bias


def _relative_bucket(rel: jax.Array, bidirectional: bool, num_buckets: int, max_dist: int):
    ret = jnp.zeros_like(rel)
    n = num_buckets
    if bidirectional:
        n //= 2
        ret = ret + (rel > 0).astype(rel.dtype) * n
        rel = jnp.abs(rel)
    else:
        rel = -jnp.minimum(rel, 0)
    max_exact = n // 2
    is_small = rel < max_exact
    rel_f = jnp.maximum(rel.astype(jnp.float32), 1.0)
    val_if_large = max_exact + (
        jnp.log(rel_f / max_exact)
        / jnp.log(max_dist / max_exact)
        * (n - max_exact)
    ).astype(rel.dtype)
    val_if_large = jnp.minimum(val_if_large, n - 1)
    return ret + jnp.where(is_small, rel, val_if_large)


def _position_bias(
    rel_bias: Params,
    cfg: T5Config,
    q_pos: jax.Array,  # [Sq] int32
    k_pos: jax.Array,  # [Sk] int32
    bidirectional: bool,
) -> jax.Array:
    """[1, H, Sq, Sk] additive attention bias from bucketed relative positions."""
    rel = k_pos[None, :] - q_pos[:, None]  # [Sq, Sk]
    buckets = _relative_bucket(rel, bidirectional, cfg.rel_buckets, cfg.rel_max_distance)
    bias = embed(rel_bias, buckets)  # [Sq, Sk, H]
    return jnp.transpose(bias, (2, 0, 1))[None]


def _position_bias_rows(
    rel_bias: Params,
    cfg: T5Config,
    t: jax.Array,  # [B] int32 — per-row decode position
    k_pos: jax.Array,  # [Sk] int32
) -> jax.Array:
    """[B, H, 1, Sk] causal decode bias where every row sits at its OWN
    position (continuous batching serves rows at different depths)."""
    rel = k_pos[None, :] - t[:, None]  # [B, Sk]
    buckets = _relative_bucket(rel, False, cfg.rel_buckets, cfg.rel_max_distance)
    bias = embed(rel_bias, buckets)  # [B, Sk, H]
    return jnp.transpose(bias, (0, 2, 1))[:, :, None, :]


# ---------------------------------------------------------------------------
# blocks


def _self_attention(p, cfg, x, mask, bias, key_mask=None):
    q = split_heads(dense(p["q"], x), cfg.num_heads)
    k = split_heads(dense(p["k"], x), cfg.num_heads)
    v = split_heads(dense(p["v"], x), cfg.num_heads)
    if key_mask is not None:
        # Pallas fused path (opt-in, serving-only — no VJP/sharding):
        # scores + rel-pos bias + softmax stay VMEM-resident.
        from ..ops.attention import fused_attention

        ctx = fused_attention(q, k, v, key_mask, bias=bias, scale=1.0)
    else:
        # T5 folds the 1/sqrt(d) into init: scale=1.
        ctx = mha_attention(q, k, v, mask=mask, bias=bias, scale=1.0)
    return dense(p["out"], merge_heads(ctx))


def encode(
    params: Params,
    cfg: T5Config,
    input_ids: jax.Array,  # [B, S]
    attention_mask: jax.Array,  # [B, S]
    dtype=jnp.float32,
    use_pallas: bool = False,
) -> jax.Array:
    s = input_ids.shape[1]
    x = embed(params["shared"], input_ids, dtype)
    mask = attention_mask[:, None, None, :].astype(bool)
    pos = jnp.arange(s, dtype=jnp.int32)
    bias = _position_bias(
        params["encoder"]["layers"][0]["attn"]["rel_bias"], cfg, pos, pos, bidirectional=True
    )
    # use_pallas is the CALLER's decision (serving wrapper only): the
    # fused kernel has no VJP, so training consumers stay on jnp.
    key_mask = attention_mask if use_pallas else None
    for layer in params["encoder"]["layers"]:
        h = rmsnorm(layer["attn_ln"], x)
        x = x + _self_attention(layer["attn"], cfg, h, mask, bias, key_mask=key_mask)
        h = rmsnorm(layer["mlp_ln"], x)
        h = dense(layer["mlp"]["wo"], jax.nn.relu(dense(layer["mlp"]["wi"], h)))
        x = x + h
    return rmsnorm(params["encoder"]["final_ln"], x)


class DecodeState(NamedTuple):
    """Static-shape incremental decode state (everything lives on device).

    EVERY field is per-row (leading dim B) — rows decode independently
    at their own positions, which is what lets the continuous-batching
    loop (``engine/streams.py``) insert a freshly prefilled request
    into one slot while other rows are mid-generation.
    """

    cache_k: Any  # list of [B, Tmax, H, D] per decoder layer
    cache_v: Any
    cross_k: Any  # list of [B, Senc, H, D] — precomputed once
    cross_v: Any
    enc_mask: jax.Array  # [B, Senc]
    pos: jax.Array  # [B] int32 — next position to write, per row
    last_token: jax.Array  # [B] int32
    done: jax.Array  # [B] bool
    tokens: jax.Array  # [B, Tmax] int32 — generated so far (pad-filled)
    sample: Any  # sampling.SampleParams, all [B]-shaped


def init_decode_state(
    params: Params,
    cfg: T5Config,
    enc_out: jax.Array,  # [B, Senc, D]
    enc_mask: jax.Array,  # [B, Senc]
    max_len: int,
    sample=None,  # SampleParams [B] or None (greedy)
) -> DecodeState:
    from .sampling import greedy_params

    b = enc_out.shape[0]
    dtype = enc_out.dtype
    cache_k, cache_v, cross_k, cross_v = [], [], [], []
    for layer in params["decoder"]["layers"]:
        cache_k.append(jnp.zeros((b, max_len, cfg.num_heads, cfg.d_kv), dtype))
        cache_v.append(jnp.zeros((b, max_len, cfg.num_heads, cfg.d_kv), dtype))
        ca = layer["cross_attn"]
        cross_k.append(split_heads(dense(ca["k"], enc_out), cfg.num_heads))
        cross_v.append(split_heads(dense(ca["v"], enc_out), cfg.num_heads))
    return DecodeState(
        cache_k=cache_k,
        cache_v=cache_v,
        cross_k=cross_k,
        cross_v=cross_v,
        enc_mask=enc_mask,
        pos=jnp.zeros((b,), jnp.int32),
        last_token=jnp.full((b,), cfg.decoder_start_id, jnp.int32),
        done=jnp.zeros((b,), bool),
        tokens=jnp.full((b, max_len), cfg.pad_id, jnp.int32),
        sample=sample if sample is not None else greedy_params(b),
    )


def _lm_logits(params: Params, cfg: T5Config, x: jax.Array) -> jax.Array:
    """Tied/untied lm_head with T5's d_model**-0.5 output scale; f32
    logits.  Quantized heads use the scale-factored matmul (no full-
    precision copy of the table inside the decode scan —
    common.lm_head_logits).  One home for the head dispatch: greedy and
    speculative paths MUST share it or their argmaxes can diverge."""
    from .common import lm_head_logits

    x = x * (cfg.d_model**-0.5)
    lm = params.get("lm_head", params["shared"])
    if "kernel" in lm:
        return lm_head_logits(x, lm["kernel"], transposed=False)
    return lm_head_logits(x, lm["embedding"], transposed=True)


def _decode_step(
    params: Params, cfg: T5Config, state: DecodeState, sample: bool = False
) -> tuple[DecodeState, jax.Array]:
    """One decode step (argmax or per-row sampling); returns
    (new_state, emitted token [B]).  All position logic is per-row."""
    dtype = state.cross_k[0].dtype
    max_len = state.tokens.shape[1]
    b = state.last_token.shape[0]
    rows = jnp.arange(b)
    x = embed(params["shared"], state.last_token[:, None], dtype)  # [B,1,D]
    t = state.pos  # [B]
    k_pos = jnp.arange(max_len, dtype=jnp.int32)
    # Causal-with-cache mask: each row attends to positions <= its t.
    self_mask = (k_pos[None, :] <= t[:, None])[:, None, None, :]  # [B,1,1,T]
    rel = params["decoder"]["layers"][0]["self_attn"]["rel_bias"]
    self_bias = _position_bias_rows(rel, cfg, t, k_pos)  # [B,H,1,T]
    cross_mask = state.enc_mask[:, None, None, :].astype(bool)

    new_k, new_v = [], []
    for li, layer in enumerate(params["decoder"]["layers"]):
        sa = layer["self_attn"]
        h = rmsnorm(layer["self_attn_ln"], x)
        q = split_heads(dense(sa["q"], h), cfg.num_heads)  # [B,1,H,D]
        k1 = split_heads(dense(sa["k"], h), cfg.num_heads)
        v1 = split_heads(dense(sa["v"], h), cfg.num_heads)
        # Per-row scatter; DROP out-of-range writes (a freed slot in the
        # continuous loop keeps stepping past the budget harmlessly).
        ck = state.cache_k[li].at[rows, t].set(k1[:, 0], mode="drop")
        cv = state.cache_v[li].at[rows, t].set(v1[:, 0], mode="drop")
        new_k.append(ck)
        new_v.append(cv)
        ctx = mha_attention(q, ck, cv, mask=self_mask, bias=self_bias, scale=1.0)
        x = x + dense(sa["out"], merge_heads(ctx))

        ca = layer["cross_attn"]
        h = rmsnorm(layer["cross_attn_ln"], x)
        qc = split_heads(dense(ca["q"], h), cfg.num_heads)
        ctx = mha_attention(qc, state.cross_k[li], state.cross_v[li], mask=cross_mask, scale=1.0)
        x = x + dense(ca["out"], merge_heads(ctx))

        h = rmsnorm(layer["mlp_ln"], x)
        h = dense(layer["mlp"]["wo"], jax.nn.relu(dense(layer["mlp"]["wi"], h)))
        x = x + h

    x = rmsnorm(params["decoder"]["final_ln"], x)
    logits = _lm_logits(params, cfg, x[:, 0])

    if sample:
        from .sampling import select_token

        next_tok, sp = select_token(logits, state.sample)
    else:
        next_tok, sp = jnp.argmax(logits, axis=-1).astype(jnp.int32), state.sample
    next_tok = jnp.where(state.done, jnp.int32(cfg.pad_id), next_tok)
    done = state.done | (next_tok == cfg.eos_id)
    tokens = state.tokens.at[rows, t].set(next_tok, mode="drop")
    new_state = DecodeState(
        cache_k=new_k,
        cache_v=new_v,
        cross_k=state.cross_k,
        cross_v=state.cross_v,
        enc_mask=state.enc_mask,
        pos=t + 1,
        last_token=next_tok,
        done=done,
        tokens=tokens,
        sample=sp,
    )
    return new_state, next_tok


def generate_chunk(
    params: Params, cfg: T5Config, state: DecodeState, n_steps: int, sample: bool = False
) -> tuple[DecodeState, jax.Array]:
    """Run ``n_steps`` decode steps in ONE compiled scan.

    Returns (state, chunk_tokens [B, n_steps]). The engine jits this per
    chunk size; streaming granularity = n_steps tokens per dispatch.
    ``sample`` is STATIC: False = argmax fast path, True = per-row
    temperature/top-k/top-p sampling (models/sampling.py).
    """

    def step(s, _):
        s, tok = _decode_step(params, cfg, s, sample)
        return s, tok

    state, toks = lax.scan(step, state, None, length=n_steps)
    return state, jnp.transpose(toks)  # [B, n_steps]


def greedy_generate(
    params: Params,
    cfg: T5Config,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    max_len: int,
    dtype=jnp.float32,
) -> jax.Array:
    """Non-streaming generate: encode + full scan, single dispatch. [B, max_len]."""
    enc = encode(params, cfg, input_ids, attention_mask, dtype)
    state = init_decode_state(params, cfg, enc, attention_mask, max_len)
    state, _ = generate_chunk(params, cfg, state, max_len)
    return state.tokens


# ---------------------------------------------------------------------------
# speculative decoding (models/spec.py contract)


class SpecDecodeState(NamedTuple):
    """DecodeState recast to the spec contract (models/spec.py): the
    generic ``verify_step`` drives any base exposing cache_k/cache_v/
    key_valid/write_idx/pos/last_token/done/tokens via ``_replace`` —
    the T5-only fields (cross-KV, encoder mask) ride along untouched.

    T5's decoder positions are contiguous from 0 (no prompt prefill in
    the decoder), so ``write_idx == pos`` always, and ``key_valid`` is
    equivalent to ``position < write_idx`` — materialized as a buffer
    because acceptance-driven validity is the spec contract's currency.
    """

    cache_k: Any  # list of [B, Tmax, H, D] self-attn caches
    cache_v: Any
    cross_k: Any
    cross_v: Any
    enc_mask: jax.Array
    key_valid: jax.Array  # [B, Tmax] int32
    write_idx: jax.Array  # [B] int32 (== pos)
    pos: jax.Array  # [B] int32
    last_token: jax.Array  # [B] int32
    done: jax.Array  # [B] bool
    tokens: jax.Array  # [B, Tmax] int32
    sample: Any


def init_spec_state(state: DecodeState, input_ids, attention_mask):
    """Fresh DecodeState → spec.SpecState whose history buffer holds
    [encoder input ids | decoder tokens]: the history is WIDER than the
    decoder cache by S_enc, which the generic verify_step reads off the
    shapes as the cache→history offset.  Drafting therefore matches
    n-grams against the DOCUMENT — summaries quote their input, which
    is where prompt-lookup acceptance comes from on seq2seq traffic.

    Invariant (spec.py): history[b, hoff + write_idx[b]] == the token
    embedded at cache position write_idx — at init, decoder_start at
    history position S_enc."""
    from .spec import SpecState

    b, s_enc = input_ids.shape
    t_max = state.tokens.shape[1]
    base = SpecDecodeState(
        cache_k=state.cache_k,
        cache_v=state.cache_v,
        cross_k=state.cross_k,
        cross_v=state.cross_v,
        enc_mask=state.enc_mask,
        key_valid=(
            jnp.arange(t_max)[None] < state.pos[:, None]
        ).astype(jnp.int32),
        write_idx=state.pos,
        pos=state.pos,
        last_token=state.last_token,
        done=state.done,
        tokens=state.tokens,
        sample=state.sample,
    )
    hist = jnp.full((b, s_enc + t_max), -1, jnp.int32)
    ids = jnp.where(attention_mask != 0, input_ids, -1).astype(jnp.int32)
    hist = hist.at[:, :s_enc].set(ids)
    hist = hist.at[jnp.arange(b), s_enc + state.pos].set(state.last_token)
    return SpecState(base=base, history=hist)


def multi_step(
    params: Params, cfg: T5Config, state: SpecDecodeState, tokens: jax.Array
) -> tuple[list, list, jax.Array]:
    """Window forward for speculative verification: D decoder tokens per
    row at positions write_idx..write_idx+D-1 in ONE pass (self-attn
    over the valid cache + causal in-window prefix, cross-attn to the
    cached encoder).  Returns (new_k, new_v, logits [B, D, V]);
    key_valid is NOT updated — acceptance decides validity
    (spec.verify_step), so rejected-position K/V stays invisible."""
    dtype = state.cross_k[0].dtype
    b, d_w = tokens.shape
    rows = jnp.arange(b)[:, None]  # [B, 1]
    t = state.write_idx  # [B]
    pos_w = t[:, None] + jnp.arange(d_w)[None]  # [B, D]
    max_len = state.tokens.shape[1]
    x = embed(params["shared"], tokens, dtype)  # [B, D, Dm]
    k_pos = jnp.arange(max_len, dtype=jnp.int32)
    base_valid = (state.key_valid != 0)[:, None, :]  # [B, 1, T]
    in_window = (k_pos[None, None, :] >= t[:, None, None]) & (
        k_pos[None, None, :] <= pos_w[:, :, None]
    )  # [B, D, T]
    mask = (base_valid | in_window)[:, None]  # [B, 1, D, T]
    rel = params["decoder"]["layers"][0]["self_attn"]["rel_bias"]
    buckets = _relative_bucket(
        k_pos[None, None, :] - pos_w[:, :, None],  # [B, D, T]
        False, cfg.rel_buckets, cfg.rel_max_distance,
    )
    bias = jnp.transpose(embed(rel, buckets), (0, 3, 1, 2))  # [B, H, D, T]
    cross_mask = state.enc_mask[:, None, None, :].astype(bool)

    new_k, new_v = [], []
    for li, layer in enumerate(params["decoder"]["layers"]):
        sa = layer["self_attn"]
        h = rmsnorm(layer["self_attn_ln"], x)
        q = split_heads(dense(sa["q"], h), cfg.num_heads)  # [B, D, H, Dh]
        k1 = split_heads(dense(sa["k"], h), cfg.num_heads)
        v1 = split_heads(dense(sa["v"], h), cfg.num_heads)
        ck = state.cache_k[li].at[rows, pos_w].set(k1, mode="drop")
        cv = state.cache_v[li].at[rows, pos_w].set(v1, mode="drop")
        new_k.append(ck)
        new_v.append(cv)
        ctx = mha_attention(q, ck, cv, mask=mask, bias=bias, scale=1.0)
        x = x + dense(sa["out"], merge_heads(ctx))

        ca = layer["cross_attn"]
        h = rmsnorm(layer["cross_attn_ln"], x)
        qc = split_heads(dense(ca["q"], h), cfg.num_heads)
        ctx = mha_attention(
            qc, state.cross_k[li], state.cross_v[li], mask=cross_mask, scale=1.0
        )
        x = x + dense(ca["out"], merge_heads(ctx))

        h = rmsnorm(layer["mlp_ln"], x)
        h = dense(layer["mlp"]["wo"], jax.nn.relu(dense(layer["mlp"]["wi"], h)))
        x = x + h

    x = rmsnorm(params["decoder"]["final_ln"], x)
    return new_k, new_v, _lm_logits(params, cfg, x)  # [B, D, V]
