from . import bert, resnet, t5  # noqa: F401
from .registry import MODEL_REGISTRY, ModelBundle, build_model  # noqa: F401
