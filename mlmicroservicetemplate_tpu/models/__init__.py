from . import bert, resnet, t5  # noqa: F401
from .registry import (  # noqa: F401
    MODEL_REGISTRY,
    ModelBundle,
    RawItem,
    build_model,
    register_model,
)
