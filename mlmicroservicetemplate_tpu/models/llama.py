"""Llama-family decoder (RoPE + GQA + SwiGLU), pure-JAX, KV-cached.

Model-family breadth beyond the reference's zoo (SURVEY.md §2 serves
ResNet/BERT/T5; round 2 added GPT-2): this is the modern-decoder
member — the architecture family (Llama/Mistral/TinyLlama/Qwen-style)
a 2026 user actually brings to a serving template.  Servable as
``MODEL_NAME=llama`` through the SAME machinery as GPT-2: the
encode/init/generate_chunk trio, fused prefill+first-chunk dispatch,
continuous batching, per-request sampling, TP sharding.

Architecture: pre-norm RMSNorm blocks, rotary position embeddings
(HF rotate-half convention), grouped-query attention (num_kv_heads <
num_heads; K/V cached at KV width and broadcast to query heads at
attention time), SwiGLU MLP (down(silu(gate)·up)), no biases anywhere,
untied LM head.

Decode reuses ``gpt.GPTState`` verbatim — the per-row
(write_idx/key_valid/pos/rng) state contract is what the continuous
batching loop and the engine already speak.  RoPE is applied BEFORE
caching K (the standard layout), so cached keys never need re-rotation;
each row rotates its new K/Q at its OWN position.

Checkpoint mapping: ``convert/hf_maps.llama_state_to_pytree`` (HF
``model.layers.i.self_attn.{q,k,v,o}_proj`` etc., nn.Linear [out,in]
weights transposed to [in,out]).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from . import lora
from .common import (
    Params,
    dense,
    dense_init,
    embed,
    kv_quantize,
    lm_head_logits,
    merge_heads,
    mha_attention,
    mha_attention_kv8,
    normal_init,
    rmsnorm,
    rmsnorm_init,
)
from .gpt import GPTState


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    # Defaults = TinyLlama-1.1B (the smallest real Llama-family
    # checkpoint people serve); tests use tiny overrides.
    vocab_size: int = 32000
    d_model: int = 2048
    num_heads: int = 32
    num_kv_heads: int = 4
    num_layers: int = 22
    d_ff: int = 5632
    max_position: int = 2048
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    bos_id: int = 1
    eos_id: int = 2
    pad_id: int = 0
    # int8 KV cache (QUANT_KV=int8): K/V stored as per-token-per-head
    # int8 + f32 scales, dequantized by scale factoring inside the
    # attention matmuls (common.mha_attention_kv8) — halves the KV
    # HBM term of batched long-context decode.  Generation is NOT
    # bit-identical to the bf16 cache (quantization is lossy); the
    # knob ships measured (BASELINE.md) and default-off.
    kv_quant: bool = False
    # Pallas decode attention (USE_PALLAS_DECODE=1): the single-token
    # decode step's cache attention runs as one kernel gridded over
    # (batch, KV head) — the cache crosses HBM once per KV HEAD
    # instead of once per query head (no materialized GQA repeat), and
    # under kv_quant the payload crosses at int8 width with in-kernel
    # dequant (ops/attention.decode_attention).  Numerics: f32 scores/
    # softmax like the jnp path (verified equal in tests/test_ops.py);
    # serving-only, no VJP.
    pallas_decode: bool = False
    # Tensor-parallel width of the serving placement (registry sets it
    # from the TP knob; 1 = default, builds no mesh anywhere).  Static
    # so kernel call sites decide shard_map wrapping at trace time and
    # the autotuner keys TP entries apart (parallel/tpserve.py).
    tp: int = 1
    # Kernel-variant pin (ops/paged_attention.Variant grammar, e.g.
    # "b4-hb"): "" = resolve through the autotuner's tuning table at
    # trace time (ops/autotune.lookup — the measured winner for this
    # decode shape, or the default kernel when nothing is tuned).
    # Registry plumbs PALLAS_VARIANT here; docs/kernel_tuning.md.
    pallas_variant: str = ""
    # Run Pallas kernels in interpret mode (CPU serving/CI; TPU runs
    # compiled Mosaic).  Registry plumbs PALLAS_INTERPRET.
    pallas_interpret: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    @property
    def n_rep(self) -> int:
        return self.num_heads // self.num_kv_heads


# ---------------------------------------------------------------------------
# init


def init_params(key, cfg: LlamaConfig = LlamaConfig()) -> Params:
    keys = jax.random.split(key, cfg.num_layers + 2)
    d, kv_dim = cfg.d_model, cfg.num_kv_heads * cfg.head_dim
    params: Params = {
        "embed": {"embedding": normal_init(keys[0], (cfg.vocab_size, d), std=0.02)},
        "layers": [],
        "final_ln": rmsnorm_init(d),
        "lm_head": {"kernel": normal_init(keys[1], (d, cfg.vocab_size), std=0.02)},
    }
    for i in range(cfg.num_layers):
        k = jax.random.split(keys[2 + i], 7)
        params["layers"].append(
            {
                "attn_ln": rmsnorm_init(d),
                "attn": {
                    "q": dense_init(k[0], d, d, bias=False, std=0.02),
                    "k": dense_init(k[1], d, kv_dim, bias=False, std=0.02),
                    "v": dense_init(k[2], d, kv_dim, bias=False, std=0.02),
                    "o": dense_init(k[3], d, d, bias=False, std=0.02),
                },
                "mlp_ln": rmsnorm_init(d),
                "mlp": {
                    "gate": dense_init(k[4], d, cfg.d_ff, bias=False, std=0.02),
                    "up": dense_init(k[5], d, cfg.d_ff, bias=False, std=0.02),
                    "down": dense_init(k[6], cfg.d_ff, d, bias=False, std=0.02),
                },
            }
        )
    return params


# ---------------------------------------------------------------------------
# rotary embeddings (HF rotate-half convention)


def _rope_tables(cfg: LlamaConfig, positions: jax.Array, dtype):
    """cos/sin [..., head_dim] for integer positions [...]."""
    half = cfg.head_dim // 2
    inv_freq = 1.0 / (
        cfg.rope_theta
        ** (jnp.arange(0, half, dtype=jnp.float32) * 2.0 / cfg.head_dim)
    )
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., half]
    emb = jnp.concatenate([angles, angles], axis=-1)  # [..., head_dim]
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def _rotate_half(x: jax.Array) -> jax.Array:
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def _apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x [B, S, H, D]; cos/sin broadcastable to [B, S, 1, D]."""
    return x * cos + _rotate_half(x) * sin


def _repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """[B, S, KVH, D] -> [B, S, KVH*n_rep, D] (GQA broadcast)."""
    if n_rep == 1:
        return x
    b, s, h, d = x.shape
    return jnp.broadcast_to(
        x[:, :, :, None, :], (b, s, h, n_rep, d)
    ).reshape(b, s, h * n_rep, d)


def _split(x: jax.Array, n_heads: int) -> jax.Array:
    b, s, d = x.shape
    return x.reshape(b, s, n_heads, d // n_heads)


def _aproj(a, ad, name: str, li: int, x):
    """One attention projection (+ per-row LoRA delta when serving a
    ``__adapters__`` overlay; models/lora.py)."""
    return lora.apply(ad, name, li, x, dense(a[name], x))


# ---------------------------------------------------------------------------
# prefill


def _prefix_entry_len(entry) -> int:
    """Token count of one prefix K/V entry — dense [1, P, KVH, D] or
    quantized (int8 payload, scale) tuple."""
    return entry[0].shape[1] if isinstance(entry, tuple) else entry.shape[1]


def _dequant_prefix(entry, dtype):
    """Dense view of a prefix K/V entry for the prefill-side concat.
    Quantized entries pay an int8→dtype multiply over P tokens ONCE per
    prefill — the cache-resident copy stays int8."""
    if isinstance(entry, tuple):
        q8, sc = entry
        return q8.astype(dtype) * sc.astype(dtype)
    return entry.astype(dtype)


def _quant_prefix_entry(entry, dtype):
    """(int8, scale-in-``dtype``) form of a prefix K/V entry for the
    quantized cache: already-quantized entries pass through EXACTLY
    (no requantization loss — capture under kv_quant slices the int8
    cache rows themselves); dense entries quantize with the cache's own
    per-token-per-head scheme."""
    if isinstance(entry, tuple):
        q8, sc = entry
        return q8, sc.astype(dtype)
    q8, sc = kv_quantize(entry)
    return q8, sc.astype(dtype)


def quantize_prefix_kv(pkv: dict) -> dict:
    """Quantize a dense ``compute_prefix_kv`` pytree to the (int8,
    scale) entry form the kv_quant cache absorbs — used by the registry
    to store a global PROMPT_PREFIX at cache width (per-request capture
    under kv_quant produces this form natively)."""
    return {
        "k": [tuple(kv_quantize(k)) for k in pkv["k"]],
        "v": [tuple(kv_quantize(v)) for v in pkv["v"]],
    }


def forward_hidden(
    params: Params,
    cfg: LlamaConfig,
    input_ids: jax.Array,  # [B, S]
    attention_mask: jax.Array,  # [B, S]
    dtype=jnp.float32,
    collect_kv: bool = False,
    prefix_kv=None,  # optional list[(k,v)] of [1, P, KVH, D] cached prefix
):
    """Hidden states [B, S, D] (+ per-layer ROTATED prompt K / V).

    With ``prefix_kv`` the batch is the SUFFIX of a shared cached
    prompt prefix: tokens take rotary positions P.., queries attend to
    the (already rotated) prefix K/V plus the causal suffix — prefill
    cost is O(S), not O(P+S)."""
    b, s = input_ids.shape
    p_len = 0 if prefix_kv is None else _prefix_entry_len(prefix_kv[0][0])
    x = embed(params["embed"], input_ids, dtype)
    pos = jnp.arange(p_len, p_len + s, dtype=jnp.int32)
    cos, sin = _rope_tables(cfg, pos, dtype)  # [S, D_h]
    cos, sin = cos[None, :, None, :], sin[None, :, None, :]
    causal = jnp.tril(jnp.ones((s, s), bool))
    mask = causal[None, None] & (attention_mask[:, None, None, :] != 0)
    if p_len:
        pre = jnp.ones((1, 1, s, p_len), bool)  # prefix fully visible
        mask = jnp.concatenate(
            [jnp.broadcast_to(pre, (b, 1, s, p_len)), mask], axis=-1
        )
    ad = lora.adapter_tables(params)
    kv = []
    for li, layer in enumerate(params["layers"]):
        h = rmsnorm(layer["attn_ln"], x, eps=cfg.rms_eps)
        a = layer["attn"]
        q = _apply_rope(_split(_aproj(a, ad, "q", li, h), cfg.num_heads), cos, sin)
        k = _apply_rope(_split(_aproj(a, ad, "k", li, h), cfg.num_kv_heads), cos, sin)
        v = _split(_aproj(a, ad, "v", li, h), cfg.num_kv_heads)
        if collect_kv:
            kv.append((k, v))
        if p_len:
            pk = _dequant_prefix(prefix_kv[li][0], k.dtype)
            pv = _dequant_prefix(prefix_kv[li][1], v.dtype)
            k = jnp.concatenate(
                [jnp.broadcast_to(pk, (b,) + pk.shape[1:]), k], axis=1
            )
            v = jnp.concatenate(
                [jnp.broadcast_to(pv, (b,) + pv.shape[1:]), v], axis=1
            )
        ctx = mha_attention(
            q, _repeat_kv(k, cfg.n_rep), _repeat_kv(v, cfg.n_rep), mask=mask
        )
        x = x + _aproj(a, ad, "o", li, merge_heads(ctx))
        h = rmsnorm(layer["mlp_ln"], x, eps=cfg.rms_eps)
        m = layer["mlp"]
        x = x + dense(m["down"], jax.nn.silu(dense(m["gate"], h)) * dense(m["up"], h))
    x = rmsnorm(params["final_ln"], x, eps=cfg.rms_eps)
    return (x, kv) if collect_kv else x


def compute_prefix_kv(params: Params, cfg: LlamaConfig, prefix_ids, dtype=jnp.float32):
    """Per-layer ROTATED K/V of a shared prompt prefix — computed once
    at startup, carried in params under ``__prefix__`` (see gpt.py)."""
    ids = jnp.asarray(prefix_ids, jnp.int32).reshape(1, -1)
    _, kv = forward_hidden(
        params, cfg, ids, jnp.ones_like(ids), dtype, collect_kv=True
    )
    return {"k": [k for k, _ in kv], "v": [v for _, v in kv]}


def lm_logits(
    params: Params, cfg: LlamaConfig, input_ids, attention_mask, dtype=jnp.float32
) -> jax.Array:
    """[B, S, V] next-token logits (the non-generative forward)."""
    x = forward_hidden(params, cfg, input_ids, attention_mask, dtype)
    return lm_head_logits(x, params["lm_head"]["kernel"], transposed=False)


# ---------------------------------------------------------------------------
# incremental decode (state layout shared with gpt.GPTState)


def init_decode_state(
    params: Params,
    cfg: LlamaConfig,
    input_ids: jax.Array,  # [B, S] right-padded
    attention_mask: jax.Array,  # [B, S]
    max_len: int,
    dtype=jnp.float32,
    sample=None,
) -> GPTState:
    from .sampling import greedy_params

    b, s = input_ids.shape
    pre = params.get("__prefix__") if isinstance(params, dict) else None
    p_len = _prefix_entry_len(pre["k"][0]) if pre is not None else 0
    prefix_kv = list(zip(pre["k"], pre["v"])) if pre is not None else None
    total = p_len + s + max_len
    _, kv = forward_hidden(
        params, cfg, input_ids, attention_mask, dtype,
        collect_kv=True, prefix_kv=prefix_kv,
    )
    cache_k, cache_v = [], []
    for li, (k, v) in enumerate(kv):
        if cfg.kv_quant:
            # Scales stored in the COMPUTE dtype: the decode step
            # recovers its working dtype from the state (the int8
            # payload can't carry it), and mha_attention_kv8 upcasts
            # scales into the f32 logits anyway.  Prefix rows (global
            # PROMPT_PREFIX or a per-request cache hit) land as int8 +
            # scale too — already-quantized entries copy bit-exact,
            # dense ones quantize with the cache's own scheme — so the
            # whole slab stays uniform for the fused decode kernel.
            shape = (b, total, cfg.num_kv_heads, cfg.head_dim)
            k8, ks = kv_quantize(k)
            v8, vs = kv_quantize(v)
            ck8 = jnp.zeros(shape, jnp.int8)
            cks = jnp.ones(shape[:3] + (1,), dtype)
            cv8 = jnp.zeros(shape, jnp.int8)
            cvs = jnp.ones(shape[:3] + (1,), dtype)
            if p_len:
                pk8, pks = _quant_prefix_entry(prefix_kv[li][0], dtype)
                pv8, pvs = _quant_prefix_entry(prefix_kv[li][1], dtype)
                ck8 = ck8.at[:, :p_len].set(pk8)
                cks = cks.at[:, :p_len].set(pks)
                cv8 = cv8.at[:, :p_len].set(pv8)
                cvs = cvs.at[:, :p_len].set(pvs)
            ck8 = ck8.at[:, p_len : p_len + s].set(k8)
            cks = cks.at[:, p_len : p_len + s].set(ks.astype(dtype))
            cv8 = cv8.at[:, p_len : p_len + s].set(v8)
            cvs = cvs.at[:, p_len : p_len + s].set(vs.astype(dtype))
            cache_k.append((ck8, cks))
            cache_v.append((cv8, cvs))
            continue
        ck = jnp.zeros((b, total, cfg.num_kv_heads, cfg.head_dim), k.dtype)
        cv = ck
        if p_len:
            pk, pv = prefix_kv[li]
            ck = ck.at[:, :p_len].set(pk.astype(ck.dtype))
            cv = cv.at[:, :p_len].set(pv.astype(cv.dtype))
        cache_k.append(ck.at[:, p_len : p_len + s].set(k))
        cache_v.append(cv.at[:, p_len : p_len + s].set(v))
    lengths = attention_mask.sum(axis=-1).astype(jnp.int32)
    key_valid = jnp.zeros((b, total), jnp.int32)
    if p_len:
        key_valid = key_valid.at[:, :p_len].set(1)
    key_valid = key_valid.at[:, p_len : p_len + s].set(
        attention_mask.astype(jnp.int32)
    )
    rows = jnp.arange(b)
    last_tok = input_ids[rows, jnp.maximum(lengths - 1, 0)]
    return GPTState(
        cache_k=cache_k,
        cache_v=cache_v,
        key_valid=key_valid,
        write_idx=p_len + jnp.maximum(lengths - 1, 0),
        pos=jnp.zeros((b,), jnp.int32),
        last_token=last_tok.astype(jnp.int32),
        done=lengths == 0,
        tokens=jnp.full((b, max_len), cfg.pad_id, jnp.int32),
        sample=sample if sample is not None else greedy_params(b),
    )


def _cache_dtype(state: GPTState):
    entry = state.cache_k[0]
    return entry[1].dtype if isinstance(entry, tuple) else entry.dtype


def _write_kv(cache, rows_idx, pos_idx, k_new, dtype):
    """Scatter new K (or V) into a dense or (int8, scale) cache entry."""
    if isinstance(cache, tuple):
        q8, sc = kv_quantize(k_new)
        return (
            cache[0].at[rows_idx, pos_idx].set(q8, mode="drop"),
            cache[1].at[rows_idx, pos_idx].set(sc.astype(dtype), mode="drop"),
        )
    return cache.at[rows_idx, pos_idx].set(k_new, mode="drop")


def _cache_attention(cfg: LlamaConfig, q, ck, cv, mask):
    """Attention over a dense or int8-quantized KV cache (GQA repeat
    applies to payloads and scales alike).  With ``cfg.pallas_decode``
    the single-query step runs the fused decode kernel instead: no
    materialized GQA repeat, int8 payloads dequantized in-kernel."""
    if cfg.pallas_decode and q.shape[1] == 1:
        from ..ops import autotune
        from ..ops.attention import decode_attention

        m2 = mask[:, 0, 0, :]  # [B, 1, 1, T] -> [B, T]
        quant = isinstance(ck, tuple)
        kslab = ck[0] if quant else ck
        vkey = cfg.pallas_variant or autotune.lookup(
            "decode", b=q.shape[0], kvh=kslab.shape[2],
            n_rep=q.shape[2] // kslab.shape[2], d=q.shape[3],
            block_size=0, t=kslab.shape[1], dtype=str(q.dtype), quant=quant,
            tp=cfg.tp,
        )
        if quant:
            ctx = decode_attention(
                q[:, 0], ck[0], cv[0], m2, k_scale=ck[1], v_scale=cv[1],
                interpret=cfg.pallas_interpret, variant=vkey, tp=cfg.tp,
            )
        else:
            ctx = decode_attention(q[:, 0], ck, cv, m2,
                                   interpret=cfg.pallas_interpret,
                                   variant=vkey, tp=cfg.tp)
        return ctx[:, None]  # [B, 1, H, D]
    if isinstance(ck, tuple):
        return mha_attention_kv8(
            q,
            _repeat_kv(ck[0], cfg.n_rep), _repeat_kv(ck[1], cfg.n_rep),
            _repeat_kv(cv[0], cfg.n_rep), _repeat_kv(cv[1], cfg.n_rep),
            mask=mask,
        )
    return mha_attention(
        q, _repeat_kv(ck, cfg.n_rep), _repeat_kv(cv, cfg.n_rep), mask=mask
    )


def _decode_step(params: Params, cfg: LlamaConfig, state: GPTState, sample: bool = False):
    dtype = _cache_dtype(state)
    b = state.last_token.shape[0]
    rows = jnp.arange(b)
    t = state.write_idx  # [B] per-row position
    x = embed(params["embed"], state.last_token[:, None], dtype)  # [B,1,D]
    # Per-row rotary tables at each row's own position (clamped for
    # long-dead continuous-batching rows whose writes drop anyway).
    cos, sin = _rope_tables(cfg, jnp.minimum(t, cfg.max_position - 1), dtype)
    cos, sin = cos[:, None, None, :], sin[:, None, None, :]  # [B,1,1,D_h]
    key_valid = state.key_valid.at[rows, t].set(1, mode="drop")
    attn_mask = (key_valid != 0)[:, None, None, :]

    ad = lora.adapter_tables(params)
    new_k, new_v = [], []
    for li, layer in enumerate(params["layers"]):
        h = rmsnorm(layer["attn_ln"], x, eps=cfg.rms_eps)
        a = layer["attn"]
        q = _apply_rope(_split(_aproj(a, ad, "q", li, h), cfg.num_heads), cos, sin)
        k1 = _apply_rope(_split(_aproj(a, ad, "k", li, h), cfg.num_kv_heads), cos, sin)
        v1 = _split(_aproj(a, ad, "v", li, h), cfg.num_kv_heads)
        ck = _write_kv(state.cache_k[li], rows, t, k1[:, 0], dtype)
        cv = _write_kv(state.cache_v[li], rows, t, v1[:, 0], dtype)
        new_k.append(ck)
        new_v.append(cv)
        ctx = _cache_attention(cfg, q, ck, cv, attn_mask)
        x = x + _aproj(a, ad, "o", li, merge_heads(ctx))
        h = rmsnorm(layer["mlp_ln"], x, eps=cfg.rms_eps)
        m = layer["mlp"]
        x = x + dense(m["down"], jax.nn.silu(dense(m["gate"], h)) * dense(m["up"], h))
    x = rmsnorm(params["final_ln"], x, eps=cfg.rms_eps)
    logits = lm_head_logits(x[:, 0], params["lm_head"]["kernel"], transposed=False)

    if sample:
        from .sampling import select_token

        next_tok, sp = select_token(logits, state.sample)
    else:
        next_tok, sp = jnp.argmax(logits, axis=-1).astype(jnp.int32), state.sample
    next_tok = jnp.where(state.done, jnp.int32(cfg.pad_id), next_tok)
    done = state.done | (next_tok == cfg.eos_id)
    tokens = state.tokens.at[rows, state.pos].set(next_tok, mode="drop")
    return (
        GPTState(
            cache_k=new_k,
            cache_v=new_v,
            key_valid=key_valid,
            write_idx=t + 1,
            pos=state.pos + 1,
            last_token=next_tok,
            done=done,
            tokens=tokens,
            sample=sp,
        ),
        next_tok,
    )


def multi_step(
    params: Params, cfg: LlamaConfig, state: GPTState, tokens: jax.Array
) -> tuple[list, list, jax.Array]:
    """Window forward for speculative verification (models/spec.py):
    D tokens per row at positions write_idx.., one pass — the llama
    variant of ``gpt.multi_step`` (per-row rotary tables at each
    window position, GQA-width cache writes).  key_valid updates are
    acceptance's job (spec.verify_step)."""
    dtype = _cache_dtype(state)
    b, d_w = tokens.shape
    rows = jnp.arange(b)[:, None]  # [B, 1]
    t = state.write_idx  # [B]
    pos_w = t[:, None] + jnp.arange(d_w)[None]  # [B, D]
    x = embed(params["embed"], tokens, dtype)  # [B, D, Dm]
    cos, sin = _rope_tables(
        cfg, jnp.minimum(pos_w, cfg.max_position - 1), dtype
    )  # [B, D, Dh]
    cos, sin = cos[:, :, None, :], sin[:, :, None, :]
    total = state.key_valid.shape[1]
    pos_k = jnp.arange(total)[None, None]
    base_valid = (state.key_valid != 0)[:, None, :]
    in_window = (pos_k >= t[:, None, None]) & (pos_k <= pos_w[:, :, None])
    mask = (base_valid | in_window)[:, None]  # [B, 1, D, total]

    ad = lora.adapter_tables(params)
    new_k, new_v = [], []
    for li, layer in enumerate(params["layers"]):
        h = rmsnorm(layer["attn_ln"], x, eps=cfg.rms_eps)
        a = layer["attn"]
        q = _apply_rope(_split(_aproj(a, ad, "q", li, h), cfg.num_heads), cos, sin)
        k1 = _apply_rope(_split(_aproj(a, ad, "k", li, h), cfg.num_kv_heads), cos, sin)
        v1 = _split(_aproj(a, ad, "v", li, h), cfg.num_kv_heads)
        ck = _write_kv(state.cache_k[li], rows, pos_w, k1, dtype)
        cv = _write_kv(state.cache_v[li], rows, pos_w, v1, dtype)
        new_k.append(ck)
        new_v.append(cv)
        ctx = _cache_attention(cfg, q, ck, cv, mask)
        x = x + _aproj(a, ad, "o", li, merge_heads(ctx))
        h = rmsnorm(layer["mlp_ln"], x, eps=cfg.rms_eps)
        m = layer["mlp"]
        x = x + dense(m["down"], jax.nn.silu(dense(m["gate"], h)) * dense(m["up"], h))
    x = rmsnorm(params["final_ln"], x, eps=cfg.rms_eps)
    logits = lm_head_logits(x, params["lm_head"]["kernel"], transposed=False)
    return new_k, new_v, logits  # [B, D, V]


def generate_chunk(
    params: Params, cfg: LlamaConfig, state: GPTState, n_steps: int, sample: bool = False
) -> tuple[GPTState, jax.Array]:
    """``n_steps`` decode steps in one compiled scan — the engine's
    chunk contract (static ``sample`` picks argmax vs sampling path)."""

    def step(s, _):
        return _decode_step(params, cfg, s, sample)

    state, toks = jax.lax.scan(step, state, None, length=n_steps)
    return state, jnp.transpose(toks)


def generate_window(
    params: Params, cfg: LlamaConfig, state: GPTState, n_steps: int,
    max_chunks: int, sample: bool = False,
):
    """Fused decode window (DECODE_WINDOW): up to ``max_chunks`` chunk
    scans in ONE dispatch with on-device EOS early exit — the llama
    twin of ``gpt.generate_window`` (int8 KV cache entries ride the
    while_loop carry as (payload, scale) tuples unchanged)."""
    from .window import decode_window

    return decode_window(
        lambda s: generate_chunk(params, cfg, s, n_steps, sample),
        state, n_steps, max_chunks, cfg.pad_id,
    )


def greedy_generate(
    params: Params,
    cfg: LlamaConfig,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    max_len: int,
    dtype=jnp.float32,
) -> jax.Array:
    """Prefill + full decode scan, single dispatch → [B, max_len]."""
    state = init_decode_state(params, cfg, input_ids, attention_mask, max_len, dtype)
    state, _ = generate_chunk(params, cfg, state, max_len)
    return state.tokens


# ---------------------------------------------------------------------------
# block-paged decode (PAGED_KV=1) — gpt.PagedState layout at GQA width,
# composed with the int8 KV cache ((payload, scale) pool pairs).


def _paged_write_kv(cache, table, t, val, bs: int, dtype):
    """Scatter one new K (or V) row per batch row through the block
    table, into a dense pool or an (int8 payload, scale) pool pair —
    the paged mirror of ``_write_kv`` (same quantization, so paged
    int8 decode stays bit-identical to the contiguous int8 cache)."""
    from .gpt import paged_write_token

    if isinstance(cache, tuple):
        q8, sc = kv_quantize(val)
        return (
            paged_write_token(cache[0], table, t, q8, bs),
            paged_write_token(cache[1], table, t, sc.astype(dtype), bs),
        )
    return paged_write_token(cache, table, t, val, bs)


def _paged_cache_attention(cfg: LlamaConfig, q, ck, cv, table, key_valid,
                           bs: int):
    """Attention over the paged pool.  With ``cfg.pallas_decode`` the
    single-query step runs the fused paged kernel — each program DMAs
    exactly the row's live blocks, int8 payloads dequantize in VMEM.
    Otherwise the row's blocks gather to a dense view and run the
    contiguous path's exact math (token identity by construction)."""
    if cfg.pallas_decode and q.shape[1] == 1:
        from ..ops import autotune
        from ..ops.paged_attention import paged_decode_attention

        quant = isinstance(ck, tuple)
        kpool = ck[0] if quant else ck
        vkey = cfg.pallas_variant or autotune.lookup(
            "paged_decode", b=q.shape[0], kvh=kpool.shape[2],
            n_rep=q.shape[2] // kpool.shape[2], d=q.shape[3],
            block_size=bs, t=table.shape[1], dtype=str(q.dtype), quant=quant,
            tp=cfg.tp,
        )
        if quant:
            ctx = paged_decode_attention(
                q[:, 0], ck[0], cv[0], table, key_valid, bs,
                k_scale=ck[1], v_scale=cv[1],
                interpret=cfg.pallas_interpret, variant=vkey, tp=cfg.tp,
            )
        else:
            ctx = paged_decode_attention(q[:, 0], ck, cv, table, key_valid,
                                         bs, interpret=cfg.pallas_interpret,
                                         variant=vkey, tp=cfg.tp)
        return ctx[:, None]
    from ..ops.paged_attention import gather_pages

    mask = (key_valid != 0)[:, None, None, :]
    if isinstance(ck, tuple):
        return mha_attention_kv8(
            q,
            _repeat_kv(gather_pages(ck[0], table, bs), cfg.n_rep),
            _repeat_kv(gather_pages(ck[1], table, bs), cfg.n_rep),
            _repeat_kv(gather_pages(cv[0], table, bs), cfg.n_rep),
            _repeat_kv(gather_pages(cv[1], table, bs), cfg.n_rep),
            mask=mask,
        )
    return mha_attention(
        q,
        _repeat_kv(gather_pages(ck, table, bs), cfg.n_rep),
        _repeat_kv(gather_pages(cv, table, bs), cfg.n_rep),
        mask=mask,
    )


def _paged_decode_step(params: Params, cfg: LlamaConfig, state, table,
                       sample: bool = False):
    """One paged decode step: ``_decode_step`` with cache reads/writes
    resolved through the block table (RoPE, GQA, sampling and EOS
    logic unchanged — physical layout is the only difference)."""
    from .gpt import PagedState

    entry = state.cache_k[0]
    dtype = entry[1].dtype if isinstance(entry, tuple) else entry.dtype
    bs = entry[0].shape[1] if isinstance(entry, tuple) else entry.shape[1]
    b = state.last_token.shape[0]
    rows = jnp.arange(b)
    t = state.write_idx
    x = embed(params["embed"], state.last_token[:, None], dtype)
    cos, sin = _rope_tables(cfg, jnp.minimum(t, cfg.max_position - 1), dtype)
    cos, sin = cos[:, None, None, :], sin[:, None, None, :]
    key_valid = state.key_valid.at[rows, t].set(1, mode="drop")

    ad = lora.adapter_tables(params)
    new_k, new_v = [], []
    for li, layer in enumerate(params["layers"]):
        h = rmsnorm(layer["attn_ln"], x, eps=cfg.rms_eps)
        a = layer["attn"]
        q = _apply_rope(_split(_aproj(a, ad, "q", li, h), cfg.num_heads), cos, sin)
        k1 = _apply_rope(_split(_aproj(a, ad, "k", li, h), cfg.num_kv_heads), cos, sin)
        v1 = _split(_aproj(a, ad, "v", li, h), cfg.num_kv_heads)
        ck = _paged_write_kv(state.cache_k[li], table, t, k1[:, 0], bs, dtype)
        cv = _paged_write_kv(state.cache_v[li], table, t, v1[:, 0], bs, dtype)
        new_k.append(ck)
        new_v.append(cv)
        ctx = _paged_cache_attention(cfg, q, ck, cv, table, key_valid, bs)
        x = x + _aproj(a, ad, "o", li, merge_heads(ctx))
        h = rmsnorm(layer["mlp_ln"], x, eps=cfg.rms_eps)
        m = layer["mlp"]
        x = x + dense(m["down"], jax.nn.silu(dense(m["gate"], h)) * dense(m["up"], h))
    x = rmsnorm(params["final_ln"], x, eps=cfg.rms_eps)
    logits = lm_head_logits(x[:, 0], params["lm_head"]["kernel"], transposed=False)

    if sample:
        from .sampling import select_token

        next_tok, sp = select_token(logits, state.sample)
    else:
        next_tok, sp = jnp.argmax(logits, axis=-1).astype(jnp.int32), state.sample
    next_tok = jnp.where(state.done, jnp.int32(cfg.pad_id), next_tok)
    done = state.done | (next_tok == cfg.eos_id)
    tokens = state.tokens.at[rows, state.pos].set(next_tok, mode="drop")
    return (
        PagedState(
            cache_k=new_k, cache_v=new_v, key_valid=key_valid,
            write_idx=t + 1, pos=state.pos + 1, last_token=next_tok,
            done=done, tokens=tokens, sample=sp,
        ),
        next_tok,
    )


def generate_chunk_paged(params: Params, cfg: LlamaConfig, state, table,
                         n_steps: int, sample: bool = False):
    """``n_steps`` paged decode steps in one compiled scan."""

    def step(s, _):
        return _paged_decode_step(params, cfg, s, table, sample)

    state, toks = jax.lax.scan(step, state, None, length=n_steps)
    return state, jnp.transpose(toks)


def generate_window_paged(params: Params, cfg: LlamaConfig, state, table,
                          n_steps: int, max_chunks: int,
                          sample: bool = False):
    """Paged fused decode window over a constant block table (blocks
    for all ``max_chunks`` chunks are pre-provisioned by the engine;
    the ledger reconciles at the window boundary)."""
    from .window import decode_window

    return decode_window(
        lambda s: generate_chunk_paged(params, cfg, s, table, n_steps, sample),
        state, n_steps, max_chunks, cfg.pad_id,
    )


# ---------------------------------------------------------------------------
# chunked prefill (PREFILL_CHUNK) — gpt.py's window contract at GQA
# width, composed with the int8 KV cache.


def empty_decode_state(
    params: Params,
    cfg: LlamaConfig,
    batch: int,
    s_total: int,
    max_len: int,
    dtype=jnp.float32,
) -> GPTState:
    """All-zero decode state for chunked prefill (see
    ``gpt.empty_decode_state``); under ``kv_quant`` the cache entries
    are (int8 payload, scale) pairs mirroring ``init_decode_state``'s
    zero/ones init, so per-window quantized writes land in the exact
    slab layout monolithic prefill would have produced."""
    from .sampling import greedy_params

    total = s_total + max_len
    shape = (batch, total, cfg.num_kv_heads, cfg.head_dim)
    if cfg.kv_quant:
        cache_k = [
            (jnp.zeros(shape, jnp.int8), jnp.ones(shape[:3] + (1,), dtype))
            for _ in params["layers"]
        ]
        cache_v = [
            (jnp.zeros(shape, jnp.int8), jnp.ones(shape[:3] + (1,), dtype))
            for _ in params["layers"]
        ]
    else:
        cache_k = [jnp.zeros(shape, dtype) for _ in params["layers"]]
        cache_v = list(cache_k)
    return GPTState(
        cache_k=cache_k,
        cache_v=cache_v,
        key_valid=jnp.zeros((batch, total), jnp.int32),
        write_idx=jnp.zeros((batch,), jnp.int32),
        pos=jnp.zeros((batch,), jnp.int32),
        last_token=jnp.zeros((batch,), jnp.int32),
        done=jnp.ones((batch,), bool),
        tokens=jnp.full((batch, max_len), cfg.pad_id, jnp.int32),
        sample=greedy_params(batch),
    )


def prefill_chunk(
    params: Params,
    cfg: LlamaConfig,
    state: GPTState,
    chunk_ids: jax.Array,  # [B, C]
    chunk_mask: jax.Array,  # [B, C]
    start,
    dtype=jnp.float32,
) -> GPTState:
    """One prompt window into the contiguous cache (see
    ``gpt.prefill_chunk``): RoPE at each absolute window position, GQA
    cache writes (quantized per token-head under ``kv_quant`` — the
    same per-token scheme as monolithic prefill, so window grouping
    never changes the stored bytes)."""
    from .gpt import _window_mask

    b, c = chunk_ids.shape
    rows = jnp.arange(b)[:, None]
    pos_w = jnp.broadcast_to(start + jnp.arange(c)[None, :], (b, c))
    x = embed(params["embed"], chunk_ids, dtype)
    cos, sin = _rope_tables(
        cfg, jnp.minimum(pos_w, cfg.max_position - 1), dtype
    )  # [B, C, Dh]
    cos, sin = cos[:, :, None, :], sin[:, :, None, :]
    mask = _window_mask(state.key_valid != 0, chunk_mask, start)

    ad = lora.adapter_tables(params)
    new_k, new_v = [], []
    for li, layer in enumerate(params["layers"]):
        h = rmsnorm(layer["attn_ln"], x, eps=cfg.rms_eps)
        a = layer["attn"]
        q = _apply_rope(_split(_aproj(a, ad, "q", li, h), cfg.num_heads), cos, sin)
        k1 = _apply_rope(_split(_aproj(a, ad, "k", li, h), cfg.num_kv_heads), cos, sin)
        v1 = _split(_aproj(a, ad, "v", li, h), cfg.num_kv_heads)
        ck = _write_kv(state.cache_k[li], rows, pos_w, k1, dtype)
        cv = _write_kv(state.cache_v[li], rows, pos_w, v1, dtype)
        new_k.append(ck)
        new_v.append(cv)
        ctx = _cache_attention(cfg, q, ck, cv, mask)
        x = x + _aproj(a, ad, "o", li, merge_heads(ctx))
        h = rmsnorm(layer["mlp_ln"], x, eps=cfg.rms_eps)
        m = layer["mlp"]
        x = x + dense(m["down"], jax.nn.silu(dense(m["gate"], h)) * dense(m["up"], h))
    key_valid = state.key_valid.at[rows, pos_w].set(
        chunk_mask.astype(jnp.int32), mode="drop"
    )
    return state._replace(cache_k=new_k, cache_v=new_v, key_valid=key_valid)


def _paged_scatter_entry(cache, table_row, vals, bs: int, start, dtype):
    """Scatter one window's K (or V) rows [C, KVH, D] through the
    table into a dense pool or an (int8, scale) pool pair."""
    from ..ops.paged_attention import scatter_pages

    if isinstance(cache, tuple):
        q8, sc = kv_quantize(vals)
        return (
            scatter_pages(cache[0], table_row, q8, bs, start=start),
            scatter_pages(cache[1], table_row, sc.astype(dtype), bs, start=start),
        )
    return scatter_pages(cache, table_row, vals, bs, start=start)


def paged_prefill_chunk(
    params: Params,
    cfg: LlamaConfig,
    state,  # gpt.PagedState
    table_row: jax.Array,
    chunk_ids: jax.Array,  # [1, C]
    chunk_mask: jax.Array,
    start,
    dtype=jnp.float32,
):
    """One prompt window straight into pool blocks (see
    ``gpt.paged_prefill_chunk``), at GQA width and composed with the
    int8 pool pairs."""
    from ..ops.paged_attention import gather_pages

    from .gpt import _window_mask

    b, c = chunk_ids.shape  # b == 1
    entry = state.cache_k[0]
    bs = entry[0].shape[1] if isinstance(entry, tuple) else entry.shape[1]
    pos_w = jnp.broadcast_to(start + jnp.arange(c)[None, :], (b, c))
    x = embed(params["embed"], chunk_ids, dtype)
    cos, sin = _rope_tables(cfg, jnp.minimum(pos_w, cfg.max_position - 1), dtype)
    cos, sin = cos[:, :, None, :], sin[:, :, None, :]
    total = table_row.shape[0] * bs
    base_valid = jnp.broadcast_to(jnp.arange(total)[None, :] < start, (b, total))
    mask = _window_mask(base_valid, chunk_mask, start)

    ad = lora.adapter_tables(params)
    new_k, new_v = [], []
    for li, layer in enumerate(params["layers"]):
        h = rmsnorm(layer["attn_ln"], x, eps=cfg.rms_eps)
        a = layer["attn"]
        q = _apply_rope(_split(_aproj(a, ad, "q", li, h), cfg.num_heads), cos, sin)
        k1 = _apply_rope(_split(_aproj(a, ad, "k", li, h), cfg.num_kv_heads), cos, sin)
        v1 = _split(_aproj(a, ad, "v", li, h), cfg.num_kv_heads)
        ck = _paged_scatter_entry(state.cache_k[li], table_row, k1[0], bs, start, dtype)
        cv = _paged_scatter_entry(state.cache_v[li], table_row, v1[0], bs, start, dtype)
        new_k.append(ck)
        new_v.append(cv)
        if isinstance(ck, tuple):
            ctx = mha_attention_kv8(
                q,
                _repeat_kv(gather_pages(ck[0], table_row[None], bs), cfg.n_rep),
                _repeat_kv(gather_pages(ck[1], table_row[None], bs), cfg.n_rep),
                _repeat_kv(gather_pages(cv[0], table_row[None], bs), cfg.n_rep),
                _repeat_kv(gather_pages(cv[1], table_row[None], bs), cfg.n_rep),
                mask=mask,
            )
        else:
            ctx = mha_attention(
                q,
                _repeat_kv(gather_pages(ck, table_row[None], bs), cfg.n_rep),
                _repeat_kv(gather_pages(cv, table_row[None], bs), cfg.n_rep),
                mask=mask,
            )
        x = x + _aproj(a, ad, "o", li, merge_heads(ctx))
        h = rmsnorm(layer["mlp_ln"], x, eps=cfg.rms_eps)
        m = layer["mlp"]
        x = x + dense(m["down"], jax.nn.silu(dense(m["gate"], h)) * dense(m["up"], h))
    return state._replace(cache_k=new_k, cache_v=new_v)


def init_paged_state(
    params: Params,
    cfg: LlamaConfig,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    max_len: int,
    table: jax.Array,  # [B, T] block ids covering the prompt width
    num_blocks: int,
    block_size: int,
    dtype=jnp.float32,
    sample=None,
):
    """Prefill straight into pool blocks (int8 pools under kv_quant,
    same per-token scales as the contiguous cache).  Paged mode has no
    global ``__prefix__`` overlay (build_model rejects the combo) —
    per-request prefixes share BLOCKS instead."""
    from ..ops.paged_attention import scatter_pages
    from .gpt import PagedState
    from .sampling import greedy_params

    b, s = input_ids.shape
    t_w = table.shape[1]
    _, kv = forward_hidden(
        params, cfg, input_ids, attention_mask, dtype, collect_kv=True
    )
    cache_k, cache_v = [], []
    shape = (num_blocks, block_size, cfg.num_kv_heads, cfg.head_dim)
    for k, v in kv:
        if cfg.kv_quant:
            k8, ks = kv_quantize(k)
            v8, vs = kv_quantize(v)
            ck8 = jnp.zeros(shape, jnp.int8)
            cks = jnp.ones(shape[:3] + (1,), dtype)
            cv8 = jnp.zeros(shape, jnp.int8)
            cvs = jnp.ones(shape[:3] + (1,), dtype)
            for row in range(b):
                ck8 = scatter_pages(ck8, table[row], k8[row], block_size)
                cks = scatter_pages(cks, table[row], ks[row].astype(dtype), block_size)
                cv8 = scatter_pages(cv8, table[row], v8[row], block_size)
                cvs = scatter_pages(cvs, table[row], vs[row].astype(dtype), block_size)
            cache_k.append((ck8, cks))
            cache_v.append((cv8, cvs))
            continue
        ck = jnp.zeros(shape, k.dtype)
        cv = jnp.zeros(shape, v.dtype)
        for row in range(b):
            ck = scatter_pages(ck, table[row], k[row], block_size)
            cv = scatter_pages(cv, table[row], v[row], block_size)
        cache_k.append(ck)
        cache_v.append(cv)
    lengths = attention_mask.sum(axis=-1).astype(jnp.int32)
    key_valid = jnp.zeros((b, t_w * block_size), jnp.int32)
    key_valid = key_valid.at[:, :s].set(attention_mask.astype(jnp.int32))
    rows = jnp.arange(b)
    last_tok = input_ids[rows, jnp.maximum(lengths - 1, 0)]
    return PagedState(
        cache_k=cache_k,
        cache_v=cache_v,
        key_valid=key_valid,
        write_idx=jnp.maximum(lengths - 1, 0),
        pos=jnp.zeros((b,), jnp.int32),
        last_token=last_tok.astype(jnp.int32),
        done=lengths == 0,
        tokens=jnp.full((b, max_len), cfg.pad_id, jnp.int32),
        sample=sample if sample is not None else greedy_params(b),
    )
