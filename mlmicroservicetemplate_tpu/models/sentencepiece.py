"""Pure-Python SentencePiece **unigram** tokenizer (T5-compatible).

Capability parity: the reference serves HF T5 with its real
SentencePiece tokenizer inside ``ModelWrapper`` (SURVEY.md §2); without
this, a converted real T5 checkpoint (``MODEL_PATH``) cannot round-trip
real text through ``/predict``.  This environment has no network and no
``sentencepiece`` wheel (SURVEY.md §7.1), so the loader and the unigram
algorithm are implemented here from scratch:

- ``load_spiece_model`` — minimal protobuf wire-format reader for the
  standard ``spiece.model`` file (ModelProto: repeated SentencePiece
  ``pieces`` = field 1, each with ``piece``/``score``/``type``).  No
  protobuf dependency; unknown fields are skipped, so real exported
  models load.
- ``SentencePieceTokenizer`` — unigram encoding as a Viterbi search for
  the max-score segmentation (the same objective the C++ library
  optimizes), with byte-fallback for out-of-vocab characters when the
  model carries ``<0xXX>`` byte pieces, else ``<unk>``.
- ``write_spiece_model`` — the inverse of the loader: serialize a piece
  table to a valid ``spiece.model``.  Used by tests to build fixtures
  and by the convert CLI to materialize tokenizers from piece tables.

Normalization approximates the library's default ``nmt_nfkc`` rules:
NFKC + whitespace collapse + dummy-prefix space, with " " mapped to the
U+2581 meta symbol.  Exact charsmap replication is out of scope; for
the ASCII/latin text of the serving workloads the two agree.

Interface matches ``models/tokenizer.py``: ``encode(text, max_len) ->
(ids, mask)`` / ``decode(ids) -> str`` plus pad/eos/unk ids.
"""

from __future__ import annotations

import struct
import unicodedata

import numpy as np

# SentencePiece ModelProto piece types.
TYPE_NORMAL = 1
TYPE_UNKNOWN = 2
TYPE_CONTROL = 3
TYPE_USER_DEFINED = 4
TYPE_UNUSED = 5
TYPE_BYTE = 6

_META = "▁"  # ▁ — the SentencePiece whitespace meta symbol


# ---------------------------------------------------------------------------
# protobuf wire format (read + write), just enough for ModelProto


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long — not a protobuf file")


def _iter_fields(buf: bytes):
    """Yield (field_number, wire_type, value) over one message body."""
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:  # varint
            val, pos = _read_varint(buf, pos)
        elif wire == 1:  # 64-bit
            val = buf[pos : pos + 8]
            pos += 8
        elif wire == 2:  # length-delimited
            ln, pos = _read_varint(buf, pos)
            val = buf[pos : pos + ln]
            pos += ln
        elif wire == 5:  # 32-bit
            val = buf[pos : pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wire}")
        yield field, wire, val


# TrainerSpec.model_type enum values (sentencepiece.proto).
MODEL_UNIGRAM = 1
MODEL_BPE = 2


def load_spiece_model_ex(path: str) -> tuple[list[tuple[str, float, int]], int]:
    """Parse a ``spiece.model`` → ([(piece, score, type)] in id order,
    trainer model_type).  model_type defaults to unigram when the file
    carries no trainer_spec (e.g. fixtures written by
    ``write_spiece_model`` without one)."""
    with open(path, "rb") as f:
        buf = f.read()
    pieces: list[tuple[str, float, int]] = []
    model_type = MODEL_UNIGRAM
    for field, wire, val in _iter_fields(buf):
        if field == 2 and wire == 2:  # ModelProto.trainer_spec
            for sfield, swire, sval in _iter_fields(val):
                if sfield == 3 and swire == 0:  # TrainerSpec.model_type
                    model_type = int(sval)
            continue
        if field != 1 or wire != 2:  # ModelProto.pieces
            continue
        piece, score, ptype = "", 0.0, TYPE_NORMAL
        for sfield, swire, sval in _iter_fields(val):
            if sfield == 1 and swire == 2:  # SentencePiece.piece
                piece = sval.decode("utf-8")
            elif sfield == 2 and swire == 5:  # SentencePiece.score (float)
                score = struct.unpack("<f", sval)[0]
            elif sfield == 3 and swire == 0:  # SentencePiece.type
                ptype = int(sval)
        pieces.append((piece, score, ptype))
    if not pieces:
        raise ValueError(f"{path}: no sentencepiece pieces found (wrong file?)")
    return pieces, model_type


def load_spiece_model(path: str) -> list[tuple[str, float, int]]:
    """Back-compat wrapper: pieces only."""
    return load_spiece_model_ex(path)[0]


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def write_spiece_model(path: str, pieces: list[tuple[str, float, int]],
                       model_type: int | None = None) -> None:
    """Serialize [(piece, score, type)] to a valid ``spiece.model``
    (optionally with a trainer_spec carrying ``model_type``)."""
    body = bytearray()
    for piece, score, ptype in pieces:
        sub = bytearray()
        pb = piece.encode("utf-8")
        sub += _varint((1 << 3) | 2) + _varint(len(pb)) + pb
        sub += _varint((2 << 3) | 5) + struct.pack("<f", score)
        sub += _varint((3 << 3) | 0) + _varint(ptype)
        body += _varint((1 << 3) | 2) + _varint(len(sub)) + bytes(sub)
    if model_type is not None:
        spec = _varint((3 << 3) | 0) + _varint(model_type)
        body += _varint((2 << 3) | 2) + _varint(len(spec)) + spec
    with open(path, "wb") as f:
        f.write(bytes(body))


def load_piece_tsv(path: str) -> list[tuple[str, float, int]]:
    """``piece<TAB>score`` per line (the exportable text form); types are
    inferred for the conventional specials."""
    pieces: list[tuple[str, float, int]] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.rstrip("\n")
            if not line:
                continue
            piece, _, score_s = line.partition("\t")
            score = float(score_s) if score_s else 0.0
            if piece == "<unk>":
                ptype = TYPE_UNKNOWN
            elif piece in ("<pad>", "</s>", "<s>"):
                ptype = TYPE_CONTROL
            elif piece.startswith("<0x") and piece.endswith(">") and len(piece) == 6:
                ptype = TYPE_BYTE
            else:
                ptype = TYPE_NORMAL
            pieces.append((piece, score, ptype))
    if not pieces:
        raise ValueError(f"{path}: empty piece table")
    return pieces


# ---------------------------------------------------------------------------
# unigram tokenizer


class SentencePieceTokenizer:
    """Unigram LM tokenizer over a loaded piece table.

    Viterbi max-score segmentation, byte-fallback OOV handling, T5-style
    trailing ``</s>`` on encode.
    """

    def __init__(self, pieces: list[tuple[str, float, int]], add_eos: bool = True,
                 add_bos: bool = False, algorithm: str = "unigram"):
        if algorithm not in ("unigram", "bpe"):
            raise ValueError(f"algorithm must be unigram|bpe, got {algorithm!r}")
        self.pieces = pieces
        self.add_eos = add_eos
        # Llama-family convention: prompts start with <s> and do NOT end
        # in </s> (the exact inverse of T5's add_eos).
        self.add_bos = add_bos
        # Segmentation algorithm, from the file's TrainerSpec: unigram
        # (T5 family, Viterbi max-score) or BPE (Llama family, greedy
        # best-scoring merges — scores encode merge order, -rank).
        self.algorithm = algorithm
        self.vocab: dict[str, int] = {}
        self.byte_pieces: dict[int, int] = {}
        self.scores = np.full((len(pieces),), -1e9, np.float32)
        self.pad_id, self.eos_id, self.unk_id, self.bos_id = 0, 1, 2, None
        min_score = 0.0
        for i, (piece, score, ptype) in enumerate(pieces):
            self.scores[i] = score
            if ptype in (TYPE_NORMAL, TYPE_USER_DEFINED):
                # Matchable in segmentation.  First writer wins on dupes
                # (id order = priority order, like the library).
                self.vocab.setdefault(piece, i)
                min_score = min(min_score, score)
            elif ptype == TYPE_BYTE:
                self.byte_pieces[int(piece[1:-1], 16)] = i
            elif ptype == TYPE_UNKNOWN:
                self.unk_id = i
            elif ptype == TYPE_CONTROL:
                if piece == "<pad>":
                    self.pad_id = i
                elif piece == "</s>":
                    self.eos_id = i
                elif piece == "<s>":
                    self.bos_id = i
        self.max_piece_len = max((len(p) for p in self.vocab), default=1)
        # OOV edge weight: below every real piece so known segmentations
        # always win (the library applies the same kind of unk penalty).
        self._unk_score = min_score - 10.0

    @property
    def vocab_size(self) -> int:
        return len(self.pieces)

    # -- normalization ------------------------------------------------------

    def _normalize(self, text: str) -> str:
        text = unicodedata.normalize("NFKC", text)
        text = " ".join(text.split())  # collapse whitespace runs, strip
        if not text:
            return ""
        return _META + text.replace(" ", _META)  # dummy prefix + meta spaces

    # -- encode -------------------------------------------------------------

    def _segment(self, s: str) -> list[int]:
        """Viterbi: max-score segmentation of the normalized string."""
        n = len(s)
        NEG = -1e18
        best = [NEG] * (n + 1)
        best[0] = 0.0
        # back[i] = (start_j, ids_for_span_j_i)
        back: list[tuple[int, tuple[int, ...]]] = [(0, ())] * (n + 1)
        for i in range(1, n + 1):
            lo = max(0, i - self.max_piece_len)
            for j in range(lo, i):
                if best[j] <= NEG:
                    continue
                pid = self.vocab.get(s[j:i])
                if pid is None:
                    continue
                sc = best[j] + float(self.scores[pid])
                if sc > best[i]:
                    best[i] = sc
                    back[i] = (j, (pid,))
            if best[i] <= NEG:
                # OOV character s[i-1]: byte-fallback, else <unk>
                # (shared with the BPE path — _ids_for_symbol).
                j = i - 1
                best[i] = best[j] + self._unk_score
                back[i] = (j, self._ids_for_symbol(s[j]))
        out: list[int] = []
        i = n
        while i > 0:
            j, ids = back[i]
            out.extend(reversed(ids))
            i = j
        out.reverse()
        return out

    def _ids_for_symbol(self, sym: str) -> tuple[int, ...]:
        """Vocab id for a surviving symbol, byte-fallback, else <unk>."""
        pid = self.vocab.get(sym)
        if pid is not None:
            return (pid,)
        byte_ids = tuple(self.byte_pieces.get(b) for b in sym.encode("utf-8"))
        if byte_ids and None not in byte_ids:
            return byte_ids
        return (self.unk_id,)

    def _segment_bpe(self, s: str) -> list[int]:
        """SentencePiece BPE: repeatedly merge the adjacent symbol pair
        whose MERGED piece has the best score (scores are -merge-rank in
        BPE models), leftmost on ties — bpe_model.cc's agenda order,
        implemented the same way: a heap keyed (score desc, position
        asc) over a doubly-linked symbol list, O(n log n) per word
        instead of rescanning every pair after each merge.  Merges
        never cross whitespace: each ▁-prefixed word segments
        independently (split_by_whitespace, the library default)."""
        import heapq

        out: list[int] = []

        def flush(word: list[str]) -> None:
            n = len(word)
            if n == 0:
                return
            syms = list(word)
            nxt = list(range(1, n)) + [-1]
            prv = [-1] + list(range(0, n - 1))
            alive = [True] * n
            heap: list[tuple[float, int, str, str]] = []

            def consider(i: int) -> None:
                j = nxt[i]
                if j == -1:
                    return
                pid = self.vocab.get(syms[i] + syms[j])
                if pid is not None:
                    heapq.heappush(
                        heap, (-float(self.scores[pid]), i, syms[i], syms[j])
                    )

            for i in range(n - 1):
                consider(i)
            while heap:
                _, i, ls, rs = heapq.heappop(heap)
                j = nxt[i] if alive[i] else -1
                # Stale agenda entries (either side already merged away)
                # are detected by symbol mismatch and skipped.
                if j == -1 or not alive[i] or syms[i] != ls or syms[j] != rs:
                    continue
                syms[i] = ls + rs
                alive[j] = False
                nxt[i] = nxt[j]
                if nxt[j] != -1:
                    prv[nxt[j]] = i
                consider(i)
                if prv[i] != -1:
                    consider(prv[i])
            k = 0  # merges only ever remove the RIGHT symbol; 0 survives
            while k != -1:
                out.extend(self._ids_for_symbol(syms[k]))
                k = nxt[k]

        word: list[str] = []
        for ch in s:
            if ch == _META and word:
                flush(word)
                word = []
            word.append(ch)
        flush(word)
        return out

    def encode(self, text: str, max_len: int) -> tuple[np.ndarray, np.ndarray]:
        seg = self._segment_bpe if self.algorithm == "bpe" else self._segment
        s = self._normalize(text)
        # Every output token covers >= 1 input char, so chars past
        # max_len * max_piece_len cannot reach the truncated output —
        # bound segmentation work on pathological (huge, space-free)
        # request bodies.
        s = s[: max_len * max(self.max_piece_len, 4)]
        ids = seg(s)
        if self.add_bos and self.bos_id is not None:
            ids = [self.bos_id] + ids
        if self.add_eos:
            ids = ids[: max_len - 1] + [self.eos_id]
        else:
            ids = ids[:max_len]
        n = len(ids)
        out = np.full((max_len,), self.pad_id, np.int32)
        out[:n] = ids
        mask = np.zeros((max_len,), np.int32)
        mask[:n] = 1
        return out, mask

    # -- decode -------------------------------------------------------------

    def decode(self, ids) -> str:
        parts: list[str] = []
        pending: bytearray = bytearray()
        control = {self.pad_id, self.eos_id}
        if self.bos_id is not None:
            control.add(self.bos_id)
        for i in ids:
            i = int(i)
            if i == self.eos_id:
                break
            if not 0 <= i < len(self.pieces):
                continue
            piece, _, ptype = self.pieces[i]
            if ptype == TYPE_BYTE:
                pending.append(int(piece[1:-1], 16))
                continue
            if pending:
                parts.append(pending.decode("utf-8", errors="replace"))
                pending = bytearray()
            if i in control or ptype in (TYPE_CONTROL, TYPE_UNUSED):
                continue
            if ptype == TYPE_UNKNOWN:
                parts.append(" ⁇ ")  # the library's default unk surface
                continue
            parts.append(piece)
        if pending:
            parts.append(pending.decode("utf-8", errors="replace"))
        text = "".join(parts).replace(_META, " ")
        return text[1:] if text.startswith(" ") else text


def load_sentencepiece(path: str, add_eos: bool = True,
                       add_bos: bool = False) -> SentencePieceTokenizer:
    """Build from a binary ``spiece.model`` or a ``piece\\tscore`` tsv.
    The segmentation algorithm follows the file's TrainerSpec
    (unigram = T5 family, BPE = Llama family)."""
    if path.endswith((".tsv", ".vocab")):
        pieces, model_type = load_piece_tsv(path), MODEL_UNIGRAM
    else:
        pieces, model_type = load_spiece_model_ex(path)
    return SentencePieceTokenizer(
        pieces, add_eos=add_eos, add_bos=add_bos,
        algorithm="bpe" if model_type == MODEL_BPE else "unigram",
    )
