"""Per-row token sampling for generative decode (temperature/top-k/top-p).

Serving contract: every request carries its own sampling knobs, so a
single batched decode dispatch mixes greedy and sampled rows freely —
essential for continuous batching, where one `generate_chunk` serves
many concurrent streams.  All controls are therefore PER-ROW arrays
([B]-shaped) living inside the decode state:

- ``temperature`` (f32): 0 = greedy argmax (the default); >0 scales
  logits before sampling.
- ``top_k`` (i32): keep only the k highest logits (0 = off).
- ``top_p`` (f32): nucleus sampling — keep the smallest set of tokens
  whose cumulative probability reaches p (>= 1.0 = off).
- ``rng`` ([B, 2] u32): per-row threefry key.  Keys derive from the
  request's ``seed`` only, and each step's key is split from the row's
  own chain — so a seeded request reproduces its tokens exactly
  regardless of which other rows share the batch (batched == solo).

Determinism note: greedy rows never touch the rng, and a seeded
sampled row's trajectory is a pure function of (seed, step, logits).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

# Python float, NOT jnp.float32: this module can be first imported
# from inside a jit trace (model fns import it lazily), and a
# module-level jnp constant created under an active trace would be a
# tracer — leaking into every later executable that reads it.  A weak
# float promotes to the logits' f32 in jnp.where identically.
_NEG_INF = -1e9


class SampleParams(NamedTuple):
    """Per-row sampling state carried inside GPT/T5 decode states."""

    rng: jax.Array  # [B, 2] uint32 threefry keys
    temperature: jax.Array  # [B] f32, 0 = greedy
    top_k: jax.Array  # [B] i32, 0 = off
    top_p: jax.Array  # [B] f32, >= 1 = off


def greedy_params(batch: int) -> SampleParams:
    """All-greedy defaults (what init_decode_state uses when the caller
    passes no sampling request)."""
    return SampleParams(
        rng=jnp.zeros((batch, 2), jnp.uint32),
        temperature=jnp.zeros((batch,), jnp.float32),
        top_k=jnp.zeros((batch,), jnp.int32),
        top_p=jnp.ones((batch,), jnp.float32),
    )


def make_params(seed, temperature, top_k, top_p) -> SampleParams:
    """Build per-row params from [B] request arrays.

    Pure numpy on purpose: this runs on the request path, where every
    eager jax op would cost a device dispatch (a full RTT through the
    relay).  The key layout matches threefry2x32's PRNGKey(seed) —
    [hi32, lo32] — which ``select_token`` wraps explicitly.
    """
    import numpy as np

    seed64 = np.asarray(seed, np.uint64)
    rng = np.stack(
        [(seed64 >> np.uint64(32)).astype(np.uint32),
         (seed64 & np.uint64(0xFFFFFFFF)).astype(np.uint32)],
        axis=-1,
    )
    return SampleParams(
        rng=rng,
        temperature=np.asarray(temperature, np.float32),
        top_k=np.asarray(top_k, np.int32),
        top_p=np.asarray(top_p, np.float32),
    )


def _filter_top_k(logits: jax.Array, top_k: jax.Array, sorted_desc: jax.Array) -> jax.Array:
    """Mask logits below each row's k-th largest (top_k == 0 keeps all)."""
    v = sorted_desc.shape[-1]
    k_idx = jnp.clip(top_k - 1, 0, v - 1)  # [B]
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)  # [B, 1]
    keep = (logits >= kth) | (top_k <= 0)[:, None]
    return jnp.where(keep, logits, _NEG_INF)


def _filter_top_p(logits: jax.Array, top_p: jax.Array, sorted_desc: jax.Array) -> jax.Array:
    """Nucleus filter: keep the smallest prefix of the sorted
    distribution whose cumulative probability reaches top_p (the
    first token is always kept).  top_p >= 1 keeps all."""
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # A sorted position is kept while the mass BEFORE it is < p.
    keep_sorted = (cum - probs) < top_p[:, None]  # [B, V] monotone prefix
    # Cutoff = smallest kept logit value in sorted order.
    cutoff = jnp.min(
        jnp.where(keep_sorted, sorted_desc, jnp.float32(jnp.inf)), axis=-1
    )  # [B]
    keep = (logits >= cutoff[:, None]) | (top_p >= 1.0)[:, None]
    return jnp.where(keep, logits, _NEG_INF)


def filtered_logits(
    logits: jax.Array,  # [B, V]
    temperature: jax.Array,  # [B]
    top_k: jax.Array,  # [B]
    top_p: jax.Array,  # [B]
) -> jax.Array:
    """The temperature/top-k/top-p transform as f32 logits (filtered
    entries at -inf): softmax of the result IS the distribution a
    sampled row draws from.  One home for the filter order (HF:
    temperature, then top-k, then top-p) — the sequential sampler and
    the speculative rejection sampler (spec.py) must agree exactly or
    spec stops being distribution-identical."""
    # Temperature first, guarded against div-by-zero for greedy rows
    # whose sampled value is discarded anyway.
    z = logits.astype(jnp.float32) / jnp.maximum(temperature, 1e-6)[:, None]
    v = z.shape[-1]
    sorted_desc = -jnp.sort(-z, axis=-1)  # descending — the ONE sort
    z = _filter_top_k(z, top_k, sorted_desc)
    # The sorted view of the top-k-filtered dist is derivable from the
    # first sort by masking its tail — no second O(V log V) sort on the
    # per-token hot path.
    eff_k = jnp.where(top_k > 0, top_k, v)[:, None]
    sorted_desc2 = jnp.where(
        jnp.arange(v)[None, :] < eff_k, sorted_desc, _NEG_INF
    )
    return _filter_top_p(z, top_p, sorted_desc2)


def row_split(k):
    """Per-row key chain: split -> (next chain, this step's key), so a
    row's randomness is independent of batch composition.  ``k`` is a
    [2] u32 raw key; returns ([2] u32 next chain, typed step key)."""
    nk, sk = jax.random.split(jax.random.wrap_key_data(k, impl="threefry2x32"))
    return jax.random.key_data(nk), sk


def select_token(logits: jax.Array, sp: SampleParams) -> tuple[jax.Array, SampleParams]:
    """Pick the next token per row: argmax where temperature <= 0,
    filtered categorical sample elsewhere.  Returns (tokens [B] i32,
    params with advanced rng chains).

    The full [B, V] sort this costs per step is why the engine keeps a
    separate greedy executable (static ``sample=False``) for the
    no-sampling fast path.
    """
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    z = filtered_logits(logits, sp.temperature, sp.top_k, sp.top_p)
    next_rng, step_keys = jax.vmap(row_split)(sp.rng)
    sampled = jax.vmap(jax.random.categorical)(step_keys, z).astype(jnp.int32)
    tok = jnp.where(sp.temperature > 0.0, sampled, greedy_tok)
    return tok, sp._replace(rng=next_rng.astype(jnp.uint32))
