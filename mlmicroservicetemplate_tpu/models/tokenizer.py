"""Tokenizers for the text models — pure Python, zero external assets.

Capability parity: the reference's ``ModelWrapper`` owns tokenization via
HF AutoTokenizer (SURVEY.md §2). This environment has no network and no
HF cache (SURVEY.md §7.1), so the framework ships:

- ``WordPieceTokenizer`` — full WordPiece (BERT-style: basic tokenize →
  greedy longest-match subwords), loading a standard ``vocab.txt`` when
  the operator provides one (``TOKENIZER_PATH``).
- ``ByteTokenizer`` — deterministic byte-level fallback needing no
  assets; ids = byte + offset, with pad/unk/cls/sep/eos specials laid
  out to fit inside the BERT (30522) and T5 (32128) vocab spaces.

Both expose the same interface: ``encode(text, max_len) -> (ids, mask)``
and ``decode(ids) -> text``.
"""

from __future__ import annotations

import functools as _functools
import unicodedata

import numpy as np


class ByteTokenizer:
    """Byte-level tokenizer: token = byte value + offset. No assets.

    Layout (T5-compatible specials): pad=0, eos=1, unk=2, cls=3, sep=4,
    bytes at 5..260.
    """

    pad_id = 0
    eos_id = 1
    unk_id = 2
    cls_id = 3
    sep_id = 4
    _byte_offset = 5

    def __init__(self, add_cls_sep: bool = False, add_eos: bool = False):
        self.add_cls_sep = add_cls_sep
        self.add_eos = add_eos

    @property
    def vocab_size(self) -> int:
        return self._byte_offset + 256

    def encode(self, text: str, max_len: int) -> tuple[np.ndarray, np.ndarray]:
        raw = list(text.encode("utf-8"))
        specials = (2 if self.add_cls_sep else 0) + (1 if self.add_eos else 0)
        raw = raw[: max_len - specials]
        ids = [b + self._byte_offset for b in raw]
        if self.add_cls_sep:
            ids = [self.cls_id] + ids + [self.sep_id]
        if self.add_eos:
            ids = ids + [self.eos_id]
        n = len(ids)
        out = np.full((max_len,), self.pad_id, np.int32)
        out[:n] = ids
        mask = np.zeros((max_len,), np.int32)
        mask[:n] = 1
        return out, mask

    def decode(self, ids) -> str:
        bs = bytearray()
        for i in ids:
            i = int(i)
            if i == self.eos_id:
                break
            # Ids past the byte range (a model's vocab may exceed the
            # tokenizer's) decode to nothing rather than crashing.
            if self._byte_offset <= i < self._byte_offset + 256:
                bs.append(i - self._byte_offset)
        return bs.decode("utf-8", errors="replace")


def _is_punct(ch: str) -> bool:
    cp = ord(ch)
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or (123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


class WordPieceTokenizer:
    """BERT-style WordPiece over a standard ``vocab.txt`` file."""

    def __init__(self, vocab_path: str, lowercase: bool = True, max_chars_per_word: int = 100):
        with open(vocab_path, encoding="utf-8") as f:
            tokens = [line.rstrip("\n") for line in f]
        self.vocab = {t: i for i, t in enumerate(tokens)}
        self.inv_vocab = tokens
        self.lowercase = lowercase
        self.max_chars_per_word = max_chars_per_word
        self.pad_id = self.vocab.get("[PAD]", 0)
        self.unk_id = self.vocab.get("[UNK]", 100)
        self.cls_id = self.vocab.get("[CLS]", 101)
        self.sep_id = self.vocab.get("[SEP]", 102)
        self.eos_id = self.sep_id

    @property
    def vocab_size(self) -> int:
        return len(self.inv_vocab)

    def _basic_tokenize(self, text: str) -> list[str]:
        text = unicodedata.normalize("NFC", text)
        if self.lowercase:
            text = text.lower()
            text = "".join(
                c for c in unicodedata.normalize("NFD", text)
                if unicodedata.category(c) != "Mn"
            )
        out: list[str] = []
        word = []
        for ch in text:
            if ch.isspace():
                if word:
                    out.append("".join(word))
                    word = []
            elif _is_punct(ch):
                if word:
                    out.append("".join(word))
                    word = []
                out.append(ch)
            else:
                word.append(ch)
        if word:
            out.append("".join(word))
        return out

    def _wordpiece(self, word: str) -> list[int]:
        if len(word) > self.max_chars_per_word:
            return [self.unk_id]
        ids: list[int] = []
        start = 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    cur = self.vocab[sub]
                    break
                end -= 1
            if cur is None:
                return [self.unk_id]
            ids.append(cur)
            start = end
        return ids

    def encode(self, text: str, max_len: int) -> tuple[np.ndarray, np.ndarray]:
        ids: list[int] = [self.cls_id]
        for w in self._basic_tokenize(text):
            ids.extend(self._wordpiece(w))
            if len(ids) >= max_len - 1:
                break
        ids = ids[: max_len - 1] + [self.sep_id]
        n = len(ids)
        out = np.full((max_len,), self.pad_id, np.int32)
        out[:n] = ids
        mask = np.zeros((max_len,), np.int32)
        mask[:n] = 1
        return out, mask

    # Spacing heuristics for detokenization (WordPiece has no offsets,
    # so original whitespace is unrecoverable; these render natural
    # text instead of "don ' t"-style surfaces).
    _GLUE_BOTH = set("'’-/")  # joins to neighbors on both sides
    _NO_SPACE_BEFORE = set(".,!?;:%)]}\"") | _GLUE_BOTH
    _NO_SPACE_AFTER = set("([{$#'’")

    def decode(self, ids) -> str:
        toks = []
        for i in ids:
            i = int(i)
            if i in (self.pad_id, self.cls_id):
                continue
            if i == self.sep_id:
                break
            t = self.inv_vocab[i] if 0 <= i < len(self.inv_vocab) else "[UNK]"
            if t.startswith("##") and toks:
                toks[-1] += t[2:]
            else:
                toks.append(t)
        text = ""
        glue = True  # no leading space
        for t in toks:
            if glue or (len(t) == 1 and t in self._NO_SPACE_BEFORE):
                text += t
            else:
                text += " " + t
            glue = len(t) == 1 and (t in self._GLUE_BOTH or t in self._NO_SPACE_AFTER)
        return text


@_functools.lru_cache(maxsize=1)
def _bytes_to_unicode() -> dict[int, str]:
    """GPT-2's reversible byte→printable-unicode table (the standard
    construction: printable latin bytes map to themselves, the rest to
    256+n), so BPE operates on visible characters."""
    bs = list(range(33, 127)) + list(range(161, 173)) + list(range(174, 256))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, (chr(c) for c in cs)))


class ByteLevelBPETokenizer:
    """GPT-2 style byte-level BPE over ``vocab.json`` + ``merges.txt``.

    Pure Python (no ``tokenizers`` wheel in this environment); uses the
    exact GPT-2 split pattern via the installed ``regex`` module.
    """

    def __init__(self, vocab_path: str, merges_path: str | None = None):
        import json
        import os

        import regex

        if merges_path is None:
            merges_path = os.path.join(os.path.dirname(vocab_path), "merges.txt")
        with open(vocab_path, encoding="utf-8") as f:
            self.vocab: dict[str, int] = json.load(f)
        self.inv_vocab = {i: t for t, i in self.vocab.items()}
        with open(merges_path, encoding="utf-8") as f:
            lines = [l.rstrip("\n") for l in f]
        # Only the FIRST line is a header ("#version: ..."); real merges
        # can legitimately start with '#' (e.g. the "# #" merge that
        # builds the "##" token) and must not be filtered.
        if lines and lines[0].startswith("#version"):
            lines = lines[1:]
        merges = [tuple(l.split()) for l in lines if l]
        self.ranks = {pair: i for i, pair in enumerate(m for m in merges if len(m) == 2)}
        self.byte_enc = _bytes_to_unicode()
        self.byte_dec = {c: b for b, c in self.byte_enc.items()}
        self.pat = regex.compile(
            r"'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+"
        )
        self.eos_id = self.vocab.get("<|endoftext|>", len(self.vocab) - 1)
        self.pad_id = self.eos_id  # GPT-2 has no pad token
        self._cache: dict[str, tuple[str, ...]] = {}

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    @property
    def max_token_id(self) -> int:
        """Largest id this tokenizer can emit — what embedding-table
        bounds checks must compare against (a sparse/edited vocab.json
        can have ids far past len(vocab))."""
        return max(self.vocab.values()) if self.vocab else 0

    def _bpe(self, token: str) -> tuple[str, ...]:
        cached = self._cache.get(token)
        if cached is not None:
            return cached
        # Bound the cache: high-cardinality traffic (UUIDs, hashes) in a
        # long-lived server must not grow RSS without limit.
        if len(self._cache) >= 65536:
            self._cache.clear()
        word = tuple(token)
        while len(word) > 1:
            pairs = {(word[i], word[i + 1]) for i in range(len(word) - 1)}
            best = min(pairs, key=lambda p: self.ranks.get(p, 1 << 60))
            if best not in self.ranks:
                break
            a, b = best
            merged: list[str] = []
            i = 0
            while i < len(word):
                if i < len(word) - 1 and word[i] == a and word[i + 1] == b:
                    merged.append(a + b)
                    i += 2
                else:
                    merged.append(word[i])
                    i += 1
            word = tuple(merged)
        self._cache[token] = word
        return word

    def encode(self, text: str, max_len: int) -> tuple[np.ndarray, np.ndarray]:
        ids: list[int] = []
        for tok in self.pat.findall(text):
            mapped = "".join(self.byte_enc[b] for b in tok.encode("utf-8"))
            for piece in self._bpe(mapped):
                piece_id = self.vocab.get(piece)
                if piece_id is None:
                    # A full vocab.json covers every single byte, so
                    # this only fires on truncated vocabs.  Emitting
                    # eos here (GPT-2 has no unk) would semantically
                    # truncate the prompt mid-text — skip instead.
                    continue
                ids.append(piece_id)
                if len(ids) >= max_len:
                    break
            if len(ids) >= max_len:
                break
        n = len(ids)
        out = np.full((max_len,), self.pad_id, np.int32)
        out[:n] = ids
        mask = np.zeros((max_len,), np.int32)
        mask[:n] = 1
        return out, mask

    def decode(self, ids) -> str:
        chars: list[str] = []
        for i in ids:
            i = int(i)
            if i == self.eos_id:
                break
            tok = self.inv_vocab.get(i)
            if tok is not None:
                chars.append(tok)
        data = bytes(self.byte_dec.get(c, 32) for c in "".join(chars))
        return data.decode("utf-8", errors="replace")


def build_tokenizer(tokenizer_path: str | None, for_t5: bool = False):
    """Tokenizer factory honoring TOKENIZER_PATH with byte-level fallback.

    File-format routing: ``spiece.model`` / ``*.tsv`` / ``*.vocab`` →
    SentencePiece unigram (the T5 family's real tokenizer);
    ``vocab.json`` (+ sibling ``merges.txt``) → GPT-2 byte-level BPE;
    anything else → WordPiece ``vocab.txt`` (BERT family).  ``for_t5``
    only shapes the no-asset byte fallback and SP eos behavior.
    """
    if tokenizer_path:
        if tokenizer_path.endswith((".model", ".tsv", ".vocab")):
            from .sentencepiece import load_sentencepiece

            return load_sentencepiece(tokenizer_path, add_eos=for_t5)
        if tokenizer_path.endswith(".json"):
            return ByteLevelBPETokenizer(tokenizer_path)
        return WordPieceTokenizer(tokenizer_path)
    return ByteTokenizer(add_cls_sep=not for_t5, add_eos=for_t5)
