"""ResNet-50 image classifier, pure-JAX, NHWC/HWIO (TPU-native layouts).

Capability parity: the reference serves a torchvision/HF ResNet-50
ImageNet classifier behind ``/predict`` (BASELINE.json:8). This is a
ground-up JAX implementation of the same architecture (ResNet v1.5:
stride on the 3x3 bottleneck conv, matching torchvision and HF
``ResNetForImageClassification`` with default config), structured so HF
checkpoints map 1:1 onto the param pytree via ``convert/``.

Inference-only: BatchNorm applies running stats as a fused affine
(``common.batchnorm``), which XLA folds into the conv epilogue.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from .common import (
    Params,
    batchnorm,
    batchnorm_init,
    conv2d,
    conv_init,
    dense,
    dense_init,
)


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    embedding_size: int = 64
    hidden_sizes: tuple[int, ...] = (256, 512, 1024, 2048)
    depths: tuple[int, ...] = (3, 4, 6, 3)
    num_labels: int = 1000
    downsample_in_first_stage: bool = False
    image_size: int = 224
    reduction: int = 4


def _bottleneck_init(key, c_in: int, c_out: int, stride: int, reduction: int) -> Params:
    c_mid = c_out // reduction
    keys = jax.random.split(key, 4)
    p: Params = {
        "conv1": conv_init(keys[0], 1, 1, c_in, c_mid),
        "bn1": batchnorm_init(c_mid),
        "conv2": conv_init(keys[1], 3, 3, c_mid, c_mid),
        "bn2": batchnorm_init(c_mid),
        "conv3": conv_init(keys[2], 1, 1, c_mid, c_out),
        "bn3": batchnorm_init(c_out),
    }
    if c_in != c_out or stride != 1:
        p["shortcut"] = {
            "conv": conv_init(keys[3], 1, 1, c_in, c_out),
            "bn": batchnorm_init(c_out),
        }
    return p


def _bottleneck_apply(p: Params, x: jax.Array, stride: int) -> jax.Array:
    residual = x
    if "shortcut" in p:
        residual = conv2d(p["shortcut"]["conv"], x, stride=stride, padding="VALID")
        residual = batchnorm(p["shortcut"]["bn"], residual)
    y = conv2d(p["conv1"], x, stride=1, padding="VALID")
    y = jax.nn.relu(batchnorm(p["bn1"], y))
    # v1.5: the spatial downsample lives on the 3x3 conv.
    y = conv2d(p["conv2"], y, stride=stride, padding=((1, 1), (1, 1)))
    y = jax.nn.relu(batchnorm(p["bn2"], y))
    y = conv2d(p["conv3"], y, stride=1, padding="VALID")
    y = batchnorm(p["bn3"], y)
    return jax.nn.relu(y + residual)


def _stage_strides(cfg: ResNetConfig) -> list[int]:
    first = 2 if cfg.downsample_in_first_stage else 1
    return [first] + [2] * (len(cfg.depths) - 1)


def init_params(key, cfg: ResNetConfig = ResNetConfig()) -> Params:
    k_embed, k_stages, k_cls = jax.random.split(key, 3)
    params: Params = {
        "embedder": {
            "conv": conv_init(k_embed, 7, 7, 3, cfg.embedding_size),
            "bn": batchnorm_init(cfg.embedding_size),
        }
    }
    stages = []
    c_in = cfg.embedding_size
    stage_keys = jax.random.split(k_stages, len(cfg.depths))
    for si, (depth, c_out, stride) in enumerate(
        zip(cfg.depths, cfg.hidden_sizes, _stage_strides(cfg))
    ):
        blocks = []
        block_keys = jax.random.split(stage_keys[si], depth)
        for bi in range(depth):
            s = stride if bi == 0 else 1
            blocks.append(_bottleneck_init(block_keys[bi], c_in, c_out, s, cfg.reduction))
            c_in = c_out
        stages.append(blocks)
    params["stages"] = stages
    params["classifier"] = dense_init(k_cls, cfg.hidden_sizes[-1], cfg.num_labels)
    return params


def _max_pool_3x3_s2(x: jax.Array) -> jax.Array:
    # torch MaxPool2d(kernel=3, stride=2, padding=1) equivalent.
    return lax.reduce_window(
        x,
        -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
        lax.max,
        window_dimensions=(1, 3, 3, 1),
        window_strides=(1, 2, 2, 1),
        padding=((0, 0), (1, 1), (1, 1), (0, 0)),
    )


def apply(params: Params, cfg: ResNetConfig, images: jax.Array) -> jax.Array:
    """images: [B, H, W, 3] float (already normalized) → logits [B, labels] f32."""
    x = conv2d(params["embedder"]["conv"], images, stride=2, padding=((3, 3), (3, 3)))
    x = jax.nn.relu(batchnorm(params["embedder"]["bn"], x))
    x = _max_pool_3x3_s2(x)
    for blocks, stride in zip(params["stages"], _stage_strides(cfg)):
        for bi, block in enumerate(blocks):
            x = _bottleneck_apply(block, x, stride if bi == 0 else 1)
    # Global average pool → classifier; logits in f32 for exact argmax.
    pooled = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    return dense(params["classifier"], pooled)
