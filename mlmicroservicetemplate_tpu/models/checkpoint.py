"""Checkpoint IO: load converted pytrees / convert HF state dicts on the fly.

Parity with ``ModelWrapper.load()`` (BASELINE.json:5). Formats:

- directory       → orbax checkpoint of an already-converted pytree (the
                    warm-start cache: conversion runs once, restores are
                    straight bytes→HBM).
- ``*.safetensors`` → HF state dict, converted via the model's map
                    (no torch involved).
- ``*.npz``        → HF state dict as numpy archive, converted likewise.
- ``*.bin``/``*.pt`` → torch state dict; torch imported HERE only, lazily
                    (keeps torch off the serving import path).
"""

from __future__ import annotations

import os
from typing import Callable

import numpy as np


def load_state_dict(path: str) -> dict[str, np.ndarray]:
    if path.endswith(".safetensors"):
        from safetensors.numpy import load_file

        return load_file(path)
    if path.endswith(".npz"):
        with np.load(path) as z:
            return {k: z[k] for k in z.files}
    if path.endswith((".bin", ".pt", ".pth")):
        import torch  # offline conversion only — never on the serving path

        sd = torch.load(path, map_location="cpu", weights_only=True)
        return {k: v.numpy() for k, v in sd.items()}
    raise ValueError(f"unrecognized checkpoint format: {path}")


def load_pytree(path: str, converter: Callable[[dict], dict]):
    """Path → param pytree (device arrays committed by the caller/runtime)."""
    if os.path.isdir(path):
        import orbax.checkpoint as ocp

        with ocp.StandardCheckpointer() as ckptr:
            return ckptr.restore(os.path.abspath(path))
    state = load_state_dict(path)
    return converter(state)


def save_pytree(path: str, pytree) -> None:
    """Cache a converted pytree with orbax for fast warm starts."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), pytree, force=True)
