"""Model registry: name → loaded, servable ModelBundle.

This is the TPU-native answer to the reference's ``ModelWrapper.load()``
(BASELINE.json:5): selecting a model materializes its params as a JAX
pytree (from a converted checkpoint when ``MODEL_PATH`` is set, else
deterministic random init — no network/HF hub here, SURVEY.md §7.1),
binds host-side pre/post-processing, and exposes jittable device
functions for the engine to compile per shape bucket.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
from typing import Any, Callable

import numpy as np

from ..runtime.device import DtypePolicy
from . import bert as bert_mod
from . import resnet as resnet_mod
from . import t5 as t5_mod
from .preprocess import decode_image_u8, load_labels, normalize_imagenet, softmax_np, topk_np
from .tokenizer import build_tokenizer

log = logging.getLogger(__name__)

KIND_IMAGE = "image_classification"
KIND_TEXT = "text_classification"
KIND_SEQ2SEQ = "seq2seq"


@dataclasses.dataclass
class ModelBundle:
    """Everything the engine/scheduler/API need to serve one model."""

    name: str
    kind: str
    cfg: Any
    params: Any  # device pytree
    policy: DtypePolicy
    tokenizer: Any | None
    labels: list[str] | None
    # Jittable: (params, *batch arrays) -> outputs. Engine owns jit+buckets.
    forward: Callable | None
    # seq2seq trio (jittable): encode, init_decode_state, generate_chunk.
    encode_fn: Callable | None = None
    init_state_fn: Callable | None = None
    generate_chunk_fn: Callable | None = None
    image_size: int = 224
    # Optional engine-placement override: () -> ReplicaSet-like. Lets a
    # model pick a non-default sharding (bert-long uses SeqParallelSet:
    # sequence axis over ('sp',) for ring attention).
    make_placement: Callable | None = None
    # Hard cap on tokenized prompt length (decoder-only models must
    # leave position-table room for generation — jnp.take would clamp
    # out-of-range positions silently otherwise).
    max_prompt_len: int | None = None
    # Whether this family consumed cfg.prompt_prefix (cached system-
    # prompt KV); build_model rejects the knob when unsupported.
    supports_prefix: bool = False
    # Speculative decoding (generative families; models/spec.py):
    # init_spec_fn(state, ids, mask, prefix_ids=None) -> SpecState
    # builds the drafting history (``prefix_ids`` arrives on
    # per-request prefix-cache hits).  Decoder-only families use
    # spec.make_init_spec_fn (the contract's one implementation for
    # the GPTState layout); encoder-decoders need their own history
    # layout — t5.init_spec_state prepends the ENCODER ids so lookup
    # drafts from the document.  spec_chunk_fn(params, spec_state,
    # n_verify, spec_k, sample=False) -> (SpecState, out [B,nv,K+1],
    # n_emit [B,nv]) runs n_verify draft→verify rounds in one dispatch;
    # ``sample`` (static) turns on rejection-sampling acceptance for
    # temperature>0 rows.  None = family does not support SPEC_DECODE.
    init_spec_fn: Callable | None = None
    spec_chunk_fn: Callable | None = None
    # Block-paged KV decode (PAGED_KV=1, decoder-only families):
    # paged_chunk_fn(params, paged_state, table, n_steps, sample=False)
    # -> (paged_state, tokens) runs n_steps decode steps reading and
    # writing K/V through the traced block table (models/gpt.PagedState
    # layout; engine/kv_blocks.py owns the host-side tables).  None =
    # family does not support PAGED_KV.
    paged_chunk_fn: Callable | None = None
    # Chunked prefill (PREFILL_CHUNK, decoder-only families;
    # docs/chunked-prefill.md).  empty_state_fn(params, batch, s_total,
    # max_len) -> all-dead decode state sized for a chunked prefill;
    # prefill_chunk_fn(params, state, ids, mask, start) consumes one
    # [B, C] prompt window at absolute position ``start`` (traced);
    # paged_prefill_chunk_fn(params, paged_state, table_row, ids,
    # mask, start) is the PAGED_KV variant writing straight into the
    # stream's pool blocks.  None = family does not support
    # PREFILL_CHUNK (encoder-decoders prefill the decoder from a start
    # token — there is no prompt to chunk).
    empty_state_fn: Callable | None = None
    prefill_chunk_fn: Callable | None = None
    paged_prefill_chunk_fn: Callable | None = None
    # Fused decode windows (DECODE_WINDOW; models/window.py).
    # window_fn(params, state, n_steps, max_chunks, sample=False) ->
    # (state, tokens [B, max_chunks*n_steps], done_hist [max_chunks, B],
    # n_chunks) runs up to ``max_chunks`` chunk scans in ONE dispatch
    # with on-device EOS early exit; paged_window_fn adds the traced
    # block table after ``state``.  None = family decodes one chunk
    # per dispatch only (DECODE_WINDOW>1 rejects at build).
    window_fn: Callable | None = None
    paged_window_fn: Callable | None = None

    # -- host-side single-item pre/post ------------------------------------
    def preprocess(self, item: "RawItem") -> dict[str, np.ndarray]:
        if self.kind == KIND_IMAGE:
            if item.image is None:
                raise ValueError("this model expects an image payload")
            # uint8 on the wire; normalization happens in-jit on device.
            return {"image": decode_image_u8(item.image, self.image_size)}
        if item.text is None:
            raise ValueError("this model expects a text payload")
        if self.max_prompt_len is not None:
            max_len = self.max_prompt_len
        else:
            max_len = self.cfg.max_position if hasattr(self.cfg, "max_position") else 512
        ids, mask = self.tokenizer.encode(item.text, max_len)
        n = int(mask.sum())
        feats = {"input_ids": ids[:n], "length": np.int32(n)}
        if self.kind == KIND_SEQ2SEQ:
            if item.temperature > 0.0:
                feats["temperature"] = float(item.temperature)
                feats["top_k"] = int(item.top_k)
                feats["top_p"] = float(item.top_p)
                if item.seed is not None:
                    feats["seed"] = int(item.seed)
            if item.max_tokens is not None:
                # Scheduler-visible budget: the decode loop stops
                # spending chunks on a row once it is reached.
                feats["max_tokens"] = int(item.max_tokens)
        return feats

    def postprocess(self, row: np.ndarray) -> dict:
        if self.kind == KIND_IMAGE:
            idx, probs = topk_np(row[None], k=5)
            top = [
                {
                    "class_id": int(i),
                    "score": round(float(p), 6),
                    **({"label": self.labels[int(i)]} if self.labels else {}),
                }
                for i, p in zip(idx[0], probs[0])
            ]
            return {"prediction": top[0], "topk": top}
        if self.kind == KIND_TEXT:
            probs = softmax_np(row)
            label_id = int(np.argmax(probs))
            return {
                "prediction": {
                    "label_id": label_id,
                    **({"label": self.labels[label_id]} if self.labels else {}),
                    "score": round(float(probs[label_id]), 6),
                },
                "probs": [round(float(p), 6) for p in probs],
            }
        # seq2seq: row is a token id vector.
        return {"prediction": {"text": self.tokenizer.decode(row)}}


@dataclasses.dataclass
class RawItem:
    """One unparsed /predict payload.

    Sampling knobs apply to generative (seq2seq/causal-LM) models only;
    temperature 0 = greedy (the default).  Unseeded sampled requests
    draw a fresh seed per request."""

    text: str | None = None
    image: bytes | None = None
    stream: bool = False
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int | None = None
    # Generation stops after this many tokens (None = the server's
    # MAX_DECODE_LEN budget) or when any stop string appears.
    max_tokens: int | None = None
    stop: tuple[str, ...] = ()


# ---------------------------------------------------------------------------
# builders


def _load_or_init(name: str, model_path: str | None, init_fn, converter):
    """Load converted checkpoint if given, else deterministic random init."""
    import jax

    if model_path:
        from .checkpoint import load_pytree

        log.info("loading %s checkpoint from %s", name, model_path)
        return load_pytree(model_path, converter)
    log.info("no MODEL_PATH for %s — deterministic random init", name)
    return init_fn(jax.random.PRNGKey(0))



def _maybe_quantize(params, svc_cfg):
    """Apply QUANTIZE=int8 weight-only quantization after dtype cast
    (scales stay f32; see models/quant.py)."""
    mode = getattr(svc_cfg, "quantize", None)
    if not mode:
        return params
    from .quant import quantize_pytree

    return quantize_pytree(params, mode)


def _attach_prompt_prefix(params, tokenizer, svc_cfg, compute_fn,
                          max_positions: int) -> int:
    """Cache a shared system-prompt prefix's KV into the params pytree
    (``__prefix__``) — computed once here (one jitted dispatch), then
    placed/sharded/traced like weights.  Returns the prefix token count
    (0 = no prefix configured)."""
    prefix = getattr(svc_cfg, "prompt_prefix", None)
    if not prefix:
        return 0
    # TP composes: TensorParallelSet replicates spec-unknown subtrees
    # (the prefix KV) across the mesh — correct, just unsharded.
    import jax

    ids, mask = tokenizer.encode(prefix, max_positions)
    n = int(mask.sum())
    # The request tokenizer may append terminal specials (byte/SP
    # fallbacks add eos; WordPiece adds [SEP]).  Baked into the MIDDLE
    # of every served context, an EOS acts as a document separator and
    # severs the prefix from the prompt — strip terminal specials, keep
    # any leading BOS.
    terminal = {
        int(t) for t in (
            getattr(tokenizer, "eos_id", None), getattr(tokenizer, "sep_id", None)
        ) if t is not None
    }
    while n > 0 and int(ids[n - 1]) in terminal:
        n -= 1
    if n == 0:
        raise ValueError("PROMPT_PREFIX tokenized to zero (non-special) tokens")
    params["__prefix__"] = jax.jit(compute_fn)(params, ids[:n])
    log.info("cached prompt prefix: %d tokens", n)
    return n


def _decode_position_budget(svc_cfg, max_position: int, p_len: int,
                            family: str) -> int:
    """Shared decoder-position arithmetic: prefix + prompt + decode must
    fit inside ``max_position`` (jnp.take would silently clamp past it).
    Returns the max prompt length; raises when the budget is impossible
    or a configured seq bucket exceeds it."""
    import math as _math

    chunk = max(1, int(getattr(svc_cfg, "stream_chunk_tokens", 4)))
    decode_budget = int(_math.ceil(svc_cfg.max_decode_len / chunk) * chunk)
    if decode_budget + p_len >= max_position:
        raise ValueError(
            f"MAX_DECODE_LEN(+chunk rounding)={decode_budget} plus prefix "
            f"{p_len} leaves no room for a prompt within {family}'s "
            f"{max_position} positions"
        )
    max_prompt = max_position - decode_budget - p_len
    bad = [s for s in svc_cfg.seq_buckets if s > max_prompt]
    if bad:
        raise ValueError(
            f"SEQ_BUCKETS {bad} exceed {family}'s position budget: max "
            f"prompt = {max_position} - {decode_budget} decode - {p_len} "
            f"prefix = {max_prompt}"
        )
    return max_prompt


def _pallas_knobs(svc_cfg) -> dict:
    """Kernel-selection knobs every decoder-only family plumbs into its
    (frozen) model config at build time (docs/kernel_tuning.md):
    ``PALLAS_VARIANT`` pins one autotuner variant (validated here — a
    typo'd pin must fail at boot, not at first trace) and
    ``PALLAS_INTERPRET`` runs the kernels in interpret mode, which also
    lifts the TPU backend gate so CPU CI/serving can exercise the real
    kernel path end-to-end."""
    out: dict = {}
    interp = bool(getattr(svc_cfg, "pallas_interpret", False))
    if interp:
        out["pallas_interpret"] = True
    pin = getattr(svc_cfg, "pallas_variant", None)
    if pin:
        from ..ops.paged_attention import parse_variant

        parse_variant(pin)
        out["pallas_variant"] = pin
    # TP width rides in the frozen model config too: kernel call sites
    # are pure functions that decide shard_map wrapping at trace time,
    # and the autotuner keys TP entries apart.  TP<=1 sets nothing —
    # the config (and every executable keyed on it) stays bit-identical
    # to pre-TP builds.
    tp = int(getattr(svc_cfg, "tp", 0) or 0)
    if tp > 1:
        out["tp"] = tp
    return out


def _pallas_backend_ok(svc_cfg) -> bool:
    """The fused decode kernels lower on TPU only; interpret mode is
    the explicit escape hatch (CPU CI, the pallas_ab bench)."""
    if getattr(svc_cfg, "pallas_interpret", False):
        return True
    try:
        import jax as _jax

        return _jax.default_backend() == "tpu"
    except Exception:
        return False


def _tp_placement(svc_cfg, model_cfg, family: str, devices=None):
    """TP=<n> → a TensorParallelSet factory over a ('replica','tp')
    mesh with the family's Megatron param spec; None when TP is off.

    ``devices`` (global device ids) places the group on a specific
    carve instead of the visible-device prefix — the multi-chip fleet's
    per-replica placement path (engine/fleet.py).

    Mutually exclusive with QUANTIZE: int8 leaves are {"q8","scale"}
    dicts the per-leaf PartitionSpec tree cannot describe.
    """
    tp = int(getattr(svc_cfg, "tp", 0) or 0)
    if tp <= 1:
        return None
    if getattr(svc_cfg, "quantize", None):
        raise ValueError(
            "TP and QUANTIZE cannot combine (quantized leaves are "
            "{'q8','scale'} subtrees the TP param spec cannot shard); "
            "pick one"
        )
    heads = int(getattr(model_cfg, "num_heads", 0) or 0)
    kvh = int(getattr(model_cfg, "num_kv_heads", heads) or heads)
    if heads and (heads % tp or kvh % tp):
        raise ValueError(
            f"TP={tp} must divide attention heads evenly "
            f"(num_heads={heads}, kv_heads={kvh}): q/k/v shards and the "
            "KV cache's heads axis split over the 'tp' mesh axis"
        )
    from ..parallel import TensorParallelSet
    from ..parallel.tp import PARAM_SPECS
    from ..parallel.tpserve import serving_tp_mesh

    spec = PARAM_SPECS[family](model_cfg)
    # REPLICAS=0 (unset) pins the mesh replica axis to 1: TP=<n> claims
    # exactly n devices.  The 2-D auto-fill (every leftover device into
    # the replica axis) would silently turn TP=2 on an 8-device host
    # into a 4x2 DP x TP grid — which the paged block pool rejects
    # (no batch axis to shard) and which the fleet layer already covers
    # with separate engines.  An explicit REPLICAS>1 still composes for
    # contiguous-KV serving.  The mesh comes from the serving-mesh
    # cache (same structural mesh make_replica_tp_mesh built), so the
    # engine placement and every trace-time shard_map reconstruction
    # share ONE object per (tp, replicas, devices) — multi-chip fleet
    # groups pass their carved device ids through ``devices``.
    mesh = serving_tp_mesh(
        tp, int(getattr(svc_cfg, "replicas", 0) or 1), group=devices
    )
    return lambda: TensorParallelSet(mesh, spec)


def _build_resnet(svc_cfg, policy: DtypePolicy) -> ModelBundle:
    from ..convert import resnet_state_to_pytree
    from .common import cast_pytree

    cfg = resnet_mod.ResNetConfig()
    params = _load_or_init("resnet50", svc_cfg.model_path,
                           functools.partial(resnet_mod.init_params, cfg=cfg),
                           resnet_state_to_pytree)
    params = cast_pytree(params, policy.param_jnp)
    params = _maybe_quantize(params, svc_cfg)

    def forward(p, images):
        # images arrive uint8; normalize on device, then cast for the MXU.
        x = normalize_imagenet(images)
        return resnet_mod.apply(p, cfg, x.astype(policy.compute_jnp))

    return ModelBundle(
        name="resnet50",
        kind=KIND_IMAGE,
        cfg=cfg,
        params=params,
        policy=policy,
        tokenizer=None,
        labels=load_labels(getattr(svc_cfg, "labels_path", None)),
        forward=forward,
        image_size=cfg.image_size,
    )


def _build_bert(svc_cfg, policy: DtypePolicy) -> ModelBundle:
    from ..convert import bert_state_to_pytree
    from .common import cast_pytree

    cfg = bert_mod.BertConfig()
    params = _load_or_init("bert-base", svc_cfg.model_path,
                           functools.partial(bert_mod.init_params, cfg=cfg),
                           bert_state_to_pytree)
    params = cast_pytree(params, policy.param_jnp)
    params = _maybe_quantize(params, svc_cfg)

    # TP=<n>: Megatron-shard the params over a ('replica','tp') mesh.
    make_placement = _tp_placement(svc_cfg, cfg, "bert")

    # Decide the Pallas fused-attention path once, at serving-build
    # time: inference-only call site, so the kernel's lack of VJP and
    # sharding rules never leaks into training/tp consumers.  The max
    # seq bucket gates the default (single-block VMEM regime); TP
    # forces the jnp path (the kernel has no sharding rules).
    from ..ops.attention import use_pallas_attention

    use_pallas = make_placement is None and use_pallas_attention(
        max_seq=max(svc_cfg.seq_buckets)
    )

    def forward(p, input_ids, attention_mask):
        return bert_mod.classify(
            p, cfg, input_ids, attention_mask,
            dtype=policy.compute_jnp, use_pallas=use_pallas,
        )

    return ModelBundle(
        name="bert-base",
        kind=KIND_TEXT,
        cfg=cfg,
        params=params,
        policy=policy,
        tokenizer=build_tokenizer(svc_cfg.tokenizer_path, for_t5=False),
        labels=load_labels(getattr(svc_cfg, "labels_path", None)),
        forward=forward,
        make_placement=make_placement,
    )


def _build_bert_long(svc_cfg, policy: DtypePolicy) -> ModelBundle:
    """Long-context BERT classifier served with ring attention.

    The sequence axis shards over an ``('sp',)`` mesh
    (``parallel.SeqParallelSet``); every encoder layer's attention runs
    as a ppermute ring (``parallel/ring.py``), so per-device score
    memory is O((S/n)²) and S scales with the mesh instead of a single
    chip's VMEM/HBM.  Capability beyond the reference (SURVEY.md §2
    lists no long-context machinery); the serving stack — buckets,
    batcher, API — is unchanged.  SP=<width> picks the mesh size
    (0 = all visible devices); every seq bucket must divide by it.
    """
    from ..convert import bert_state_to_pytree
    from ..parallel import SeqParallelSet, make_sp_mesh
    from ..parallel.ring import make_ring_attention
    from .common import cast_pytree

    max_pos = max(max(svc_cfg.seq_buckets), 512)
    cfg = bert_mod.BertConfig(max_position=max_pos)
    params = _load_or_init("bert-long", svc_cfg.model_path,
                           functools.partial(bert_mod.init_params, cfg=cfg),
                           bert_state_to_pytree)
    # A loaded checkpoint's position table must actually cover the long
    # buckets: jnp.take CLAMPS out-of-range indices, so an undersized
    # table would silently reuse its last row for every position past
    # it — confidently wrong logits, no error. Fail at startup instead.
    pos_rows = params["embeddings"]["position"]["embedding"].shape[0]
    if pos_rows < max_pos:
        raise ValueError(
            f"bert-long needs a position-embedding table with >= {max_pos} "
            f"rows for SEQ_BUCKETS={svc_cfg.seq_buckets}, but the loaded "
            f"checkpoint has {pos_rows}; extend the table (e.g. interpolate) "
            "or lower the buckets"
        )
    params = cast_pytree(params, policy.param_jnp)
    params = _maybe_quantize(params, svc_cfg)

    # bert-long scales with SP (+ REPLICAS), never TP — fail loudly so
    # a TP knob is not silently swallowed by the SP placement below
    # (build_model's generic guard can't see past make_placement).
    if int(getattr(svc_cfg, "tp", 0) or 0) > 1:
        raise ValueError(
            "TP is not supported for bert-long; scale long-context via "
            "SP=<width> and REPLICAS=<n> (a ('replica','sp') mesh)"
        )

    # REPLICAS>=2 composes batch DP on top of sequence parallelism:
    # a ('replica','sp') mesh whose rows are independent ppermute
    # rings (round-2 verdict: the 1-D sp mesh idled the batch axis).
    from ..parallel import make_replica_sp_mesh

    replicas = int(getattr(svc_cfg, "replicas", 0) or 0)
    if replicas > 1:
        import jax

        sp_width = getattr(svc_cfg, "sp", 0) or max(
            1, len(jax.devices()) // replicas
        )
        mesh = make_replica_sp_mesh(sp_width, replicas)
    else:
        mesh = make_sp_mesh(getattr(svc_cfg, "sp", 0))
    width = int(mesh.shape["sp"])
    bad = [s for s in svc_cfg.seq_buckets if s % width]
    if bad:
        raise ValueError(
            f"SEQ_BUCKETS {bad} not divisible by sp mesh width {width}"
        )
    raw_ring = make_ring_attention(mesh)
    # Pallas hop kernel (VMEM-resident per-hop scores): single-block
    # regime is per-DEVICE, so gate on the largest LOCAL block.
    from ..ops.attention import use_pallas_attention

    use_pallas_ring = use_pallas_attention(
        max_seq=max(svc_cfg.seq_buckets) // width
    )

    def ring(q, k, v, key_mask):
        return raw_ring(q, k, v, key_mask, use_pallas=use_pallas_ring)

    def forward(p, input_ids, attention_mask):
        return bert_mod.classify(
            p, cfg, input_ids, attention_mask,
            dtype=policy.compute_jnp, attn_fn=ring,
        )

    return ModelBundle(
        name="bert-long",
        kind=KIND_TEXT,
        cfg=cfg,
        params=params,
        policy=policy,
        tokenizer=build_tokenizer(svc_cfg.tokenizer_path, for_t5=False),
        labels=load_labels(getattr(svc_cfg, "labels_path", None)),
        forward=forward,
        make_placement=lambda: SeqParallelSet(mesh),
    )


def _build_t5(svc_cfg, policy: DtypePolicy) -> ModelBundle:
    from ..convert import t5_state_to_pytree
    from .common import cast_pytree

    cfg = t5_mod.T5Config()
    params = _load_or_init("t5-small", svc_cfg.model_path,
                           functools.partial(t5_mod.init_params, cfg=cfg),
                           t5_state_to_pytree)
    params = cast_pytree(params, policy.param_jnp)
    params = _maybe_quantize(params, svc_cfg)

    # Same serving-only Pallas opt-in as BERT (the kernel has no VJP;
    # the rel-pos bias rides into the fused kernel as a [1,H,S,S] block).
    from ..ops.attention import use_pallas_attention

    use_pallas = use_pallas_attention(max_seq=max(svc_cfg.seq_buckets))

    def encode_fn(p, input_ids, attention_mask):
        return t5_mod.encode(
            p, cfg, input_ids, attention_mask,
            dtype=policy.compute_jnp, use_pallas=use_pallas,
        )

    def init_state_fn(p, enc_out, enc_mask, max_len: int, sample=None):
        return t5_mod.init_decode_state(p, cfg, enc_out, enc_mask, max_len, sample=sample)

    def generate_chunk_fn(p, state, n_steps: int, sample: bool = False):
        return t5_mod.generate_chunk(p, cfg, state, n_steps, sample)

    # Speculative decoding: summarization quotes its input, so the
    # drafting history is [encoder ids | decoder tokens] and prompt-
    # lookup matches land in the document itself (t5.init_spec_state).
    from . import spec as spec_mod

    def init_spec_fn(state, input_ids, attention_mask, prefix_ids=None):
        return t5_mod.init_spec_state(state, input_ids, attention_mask)

    def spec_chunk_fn(p, spec_state, n_verify: int, spec_k: int,
                      sample: bool = False):
        return spec_mod.spec_chunk(
            p, spec_state, n_verify, spec_k, int(svc_cfg.spec_ngram),
            lambda pp, st, toks: t5_mod.multi_step(pp, cfg, st, toks),
            cfg.eos_id, cfg.pad_id, sample,
        )

    return ModelBundle(
        name="t5-small",
        kind=KIND_SEQ2SEQ,
        cfg=cfg,
        params=params,
        policy=policy,
        tokenizer=build_tokenizer(svc_cfg.tokenizer_path, for_t5=True),
        labels=None,
        forward=None,
        encode_fn=encode_fn,
        init_state_fn=init_state_fn,
        generate_chunk_fn=generate_chunk_fn,
        init_spec_fn=init_spec_fn,
        spec_chunk_fn=spec_chunk_fn,
    )


def _build_gpt(svc_cfg, policy: DtypePolicy) -> ModelBundle:
    """Decoder-only causal LM (GPT-2), served through the seq2seq
    engine machinery: "encode" passes the prompt through, init prefills
    the KV caches in the same fused dispatch, chunks stream tokens.

    Tokenizer: a real GPT-2 ``vocab.json`` (+ merges.txt) via
    TOKENIZER_PATH; without one, the byte-level fallback is used and
    eos/pad are remapped to its ids so EOS detection stays coherent.
    """
    from ..convert import gpt2_state_to_pytree
    from . import gpt as gpt_mod
    from .common import cast_pytree

    tokenizer = build_tokenizer(svc_cfg.tokenizer_path, for_t5=True)
    # Fused paged-decode kernel (MHA corner of the llama kernel):
    # USE_PALLAS_DECODE opt-in, TPU-or-interpret gated.  The paged
    # kernel's VMEM footprint is per block-group, not per slab, so the
    # whole-slab fit gate doesn't apply — the autotuner's cost model
    # (ops/autotune.paged_vmem_bytes) bounds each variant instead.
    import os as _os

    gpt_pallas: dict = dict(_pallas_knobs(svc_cfg))
    env_pd = _os.environ.get("USE_PALLAS_DECODE", "").lower()
    if env_pd in ("1", "true", "yes"):
        if _pallas_backend_ok(svc_cfg):
            gpt_pallas["pallas_decode"] = True
        else:
            log.warning(
                "USE_PALLAS_DECODE requested but unavailable (backend!="
                "tpu and PALLAS_INTERPRET off); using gather_pages+mha"
            )
    cfg = gpt_mod.GPTConfig(
        eos_id=int(tokenizer.eos_id), pad_id=int(tokenizer.pad_id),
        **gpt_pallas,
    )
    # A tokenizer that can emit ids past the checkpoint's embedding
    # table would hit jnp.take's silent clamp (confidently wrong
    # logits, no error) — same failure class as bert-long's position
    # table.  Compare the MAX emittable id, not the vocab count: a
    # sparse/edited vocab.json can have ids far past len(vocab).
    max_id = int(getattr(tokenizer, "max_token_id",
                         getattr(tokenizer, "vocab_size", 1) - 1))
    if max_id >= cfg.vocab_size:
        raise ValueError(
            f"tokenizer at {svc_cfg.tokenizer_path!r} can emit id "
            f"{max_id} >= gpt2 embedding table rows {cfg.vocab_size}; "
            "out-of-range ids would be silently clamped"
        )
    if not (0 <= cfg.eos_id < cfg.vocab_size and 0 <= cfg.pad_id < cfg.vocab_size):
        raise ValueError(
            f"tokenizer eos_id={cfg.eos_id}/pad_id={cfg.pad_id} outside "
            f"gpt2 vocab of {cfg.vocab_size}"
        )
    params = _load_or_init("gpt2", svc_cfg.model_path,
                           functools.partial(gpt_mod.init_params, cfg=cfg),
                           gpt2_state_to_pytree)
    params = cast_pytree(params, policy.param_jnp)
    params = _maybe_quantize(params, svc_cfg)

    # Optional shared system prompt: cached KV in the params pytree.
    p_len = _attach_prompt_prefix(
        params, tokenizer, svc_cfg,
        lambda p, ids: gpt_mod.compute_prefix_kv(
            p, cfg, ids, dtype=policy.compute_jnp
        ),
        cfg.max_position,
    )

    max_prompt = _decode_position_budget(svc_cfg, cfg.max_position, p_len, "gpt2")

    def encode_fn(p, input_ids, attention_mask):
        # Prompt passes through; the prefill forward happens in
        # init_state_fn — both live inside the same fused jit dispatch.
        return input_ids

    def init_state_fn(p, input_ids, enc_mask, max_len: int, sample=None):
        return gpt_mod.init_decode_state(
            p, cfg, input_ids, enc_mask, max_len, dtype=policy.compute_jnp,
            sample=sample,
        )

    def generate_chunk_fn(p, state, n_steps: int, sample: bool = False):
        return gpt_mod.generate_chunk(p, cfg, state, n_steps, sample)

    def paged_chunk_fn(p, state, table, n_steps: int, sample: bool = False):
        return gpt_mod.generate_chunk_paged(p, cfg, state, table, n_steps, sample)

    def empty_state_fn(p, batch: int, s_total: int, max_len: int):
        return gpt_mod.empty_decode_state(
            p, cfg, batch, s_total, max_len, dtype=policy.compute_jnp
        )

    def prefill_chunk_fn(p, state, ids, mask, start):
        return gpt_mod.prefill_chunk(
            p, cfg, state, ids, mask, start, dtype=policy.compute_jnp
        )

    def paged_prefill_chunk_fn(p, state, table_row, ids, mask, start):
        return gpt_mod.paged_prefill_chunk(
            p, cfg, state, table_row, ids, mask, start, dtype=policy.compute_jnp
        )

    def window_fn(p, state, n_steps: int, max_chunks: int,
                  sample: bool = False):
        return gpt_mod.generate_window(
            p, cfg, state, n_steps, max_chunks, sample
        )

    def paged_window_fn(p, state, table, n_steps: int, max_chunks: int,
                        sample: bool = False):
        return gpt_mod.generate_window_paged(
            p, cfg, state, table, n_steps, max_chunks, sample
        )

    from . import spec as spec_mod

    init_spec_fn = spec_mod.make_init_spec_fn(p_len)

    def spec_chunk_fn(p, spec_state, n_verify: int, spec_k: int,
                      sample: bool = False):
        return spec_mod.spec_chunk(
            p, spec_state, n_verify, spec_k, int(svc_cfg.spec_ngram),
            lambda pp, st, toks: gpt_mod.multi_step(pp, cfg, st, toks),
            cfg.eos_id, cfg.pad_id, sample,
        )

    return ModelBundle(
        name="gpt2",
        kind=KIND_SEQ2SEQ,
        cfg=cfg,
        params=params,
        policy=policy,
        tokenizer=tokenizer,
        labels=None,
        forward=None,
        encode_fn=encode_fn,
        init_state_fn=init_state_fn,
        generate_chunk_fn=generate_chunk_fn,
        max_prompt_len=max_prompt,
        # TP=<n>: decoder Megatron sharding (parallel/tp.py gpt spec).
        make_placement=_tp_placement(svc_cfg, cfg, "gpt"),
        supports_prefix=True,
        init_spec_fn=init_spec_fn,
        spec_chunk_fn=spec_chunk_fn,
        paged_chunk_fn=paged_chunk_fn,
        empty_state_fn=empty_state_fn,
        prefill_chunk_fn=prefill_chunk_fn,
        paged_prefill_chunk_fn=paged_prefill_chunk_fn,
        window_fn=window_fn,
        paged_window_fn=paged_window_fn,
    )


def _build_llama(svc_cfg, policy: DtypePolicy) -> ModelBundle:
    """Llama-family decoder (RoPE/GQA/SwiGLU — models/llama.py), served
    through the same seq2seq machinery as GPT-2 (fused prefill, chunked
    decode, continuous batching, sampling, TP).

    Default dims = TinyLlama-1.1B; ``LLAMA_CONFIG`` env takes a JSON
    object of LlamaConfig overrides (e.g. '{"num_layers": 16}') so one
    builder serves the whole dims family without code changes.
    """
    import json as _json
    import os as _os

    from ..convert import llama_state_to_pytree
    from . import llama as llama_mod
    from .common import cast_pytree

    # Llama input convention is the INVERSE of T5's: prompts start with
    # <s> (BOS) and must NOT end in </s> — a trailing EOS conditions the
    # model on end-of-document and derails generation.  SentencePiece
    # assets get the convention natively; other paths use the for_t5
    # fallback (byte fallback/eos layouts, bos-less).
    tok_path = svc_cfg.tokenizer_path
    if tok_path and tok_path.endswith((".model", ".tsv", ".vocab")):
        from .sentencepiece import load_sentencepiece

        tokenizer = load_sentencepiece(tok_path, add_eos=False, add_bos=True)
    else:
        tokenizer = build_tokenizer(tok_path, for_t5=True)
    overrides = {}
    env_cfg = _os.environ.get("LLAMA_CONFIG")
    if env_cfg:
        overrides = _json.loads(env_cfg)
    # Model-side EOS/pad must be the TOKENIZER's ids (gpt2 precedent):
    # a mismatch would leave streams decoding the full budget while the
    # detokenizer silently truncates at its own eos.
    overrides.setdefault("eos_id", int(tokenizer.eos_id))
    overrides.setdefault("pad_id", int(tokenizer.pad_id))
    if getattr(svc_cfg, "quant_kv", None) == "int8":
        overrides["kv_quant"] = True
    # Pallas decode attention (ops/attention.decode_attention).
    # Measured policy (benchmarks/kv_quant_ab.py, v5e, llama-1.1B
    # int8 weights, B=8): int8-KV through the fused kernel beats the
    # dense XLA path 1.32-1.58x across contexts 512-1792 — in-kernel
    # dequant is what flips round-4's 0.89-0.90x XLA kv-quant loss —
    # while the DENSE kernel variant loses slightly (0.86-0.96x).  So
    # the default follows the measurement: ON exactly when the int8 KV
    # cache is on.  USE_PALLAS_DECODE=1 forces it for dense too,
    # =0 disables.  TPU-gated like use_pallas_attention — the kernel
    # has no CPU lowering, so a CPU run must fall back, not crash.
    env_pd = _os.environ.get("USE_PALLAS_DECODE", "").lower()
    want_pd = (
        env_pd in ("1", "true", "yes")
        or (env_pd not in ("0", "false", "no") and overrides.get("kv_quant"))
    )
    if want_pd:
        import math as _math

        from ..ops.attention import decode_kernel_fits

        # Worst-case cache width this deployment can reach.  The
        # per-request prefix cache never widens it (its admission guard
        # keeps p_len + suffix bucket <= the max seq bucket), but a
        # global PROMPT_PREFIX prepends its own tokens — estimate them
        # with the request tokenizer (upper bound: terminal specials
        # not yet stripped) so the VMEM-fit gate sees the real slab.
        probe = llama_mod.LlamaConfig(
            **{k: v for k, v in overrides.items() if k != "pallas_decode"}
        )
        p_est = 0
        if getattr(svc_cfg, "prompt_prefix", None):
            _, _pmask = tokenizer.encode(
                svc_cfg.prompt_prefix, probe.max_position
            )
            p_est = int(_pmask.sum())
        chunk = max(1, int(getattr(svc_cfg, "stream_chunk_tokens", 4)))
        t_est = p_est + max(svc_cfg.seq_buckets) + int(
            _math.ceil(svc_cfg.max_decode_len / chunk) * chunk
        )
        if _pallas_backend_ok(svc_cfg) and decode_kernel_fits(
            t_est, probe.num_kv_heads, probe.head_dim
        ):
            overrides["pallas_decode"] = True
        elif env_pd in ("1", "true", "yes"):
            log.warning(
                "USE_PALLAS_DECODE requested but unavailable "
                "(backend!=tpu or slab exceeds VMEM at T=%d); using the "
                "jnp cache-attention path", t_est,
            )
    overrides.update(_pallas_knobs(svc_cfg))
    cfg = llama_mod.LlamaConfig(**overrides)

    max_id = int(getattr(tokenizer, "max_token_id",
                         getattr(tokenizer, "vocab_size", 1) - 1))
    if max_id >= cfg.vocab_size:
        raise ValueError(
            f"tokenizer at {svc_cfg.tokenizer_path!r} can emit id {max_id} "
            f">= llama embedding table rows {cfg.vocab_size}"
        )
    if not (0 <= cfg.eos_id < cfg.vocab_size and 0 <= cfg.pad_id < cfg.vocab_size):
        raise ValueError(
            f"eos_id={cfg.eos_id}/pad_id={cfg.pad_id} outside llama vocab "
            f"of {cfg.vocab_size}"
        )
    params = _load_or_init("llama", svc_cfg.model_path,
                           functools.partial(llama_mod.init_params, cfg=cfg),
                           llama_state_to_pytree)
    params = cast_pytree(params, policy.param_jnp)
    params = _maybe_quantize(params, svc_cfg)

    # Optional shared system prompt (cached KV).  The prefix carries
    # the BOS; request suffixes must then NOT get their own.
    p_len = _attach_prompt_prefix(
        params, tokenizer, svc_cfg,
        lambda p, ids: llama_mod.compute_prefix_kv(
            p, cfg, ids, dtype=policy.compute_jnp
        ),
        cfg.max_position,
    )
    if p_len and cfg.kv_quant:
        # The quantized cache stores every row as int8 + per-token
        # scale, the global prefix included: quantize it ONCE here
        # (startup), so init_decode_state writes prefix rows at int8
        # width and the fused Pallas decode kernel reads one uniform
        # int8 slab.  The prefill-side attention over the prefix
        # dequantizes these few rows per request (llama.forward_hidden).
        params["__prefix__"] = llama_mod.quantize_prefix_kv(
            params["__prefix__"]
        )
    if p_len and getattr(tokenizer, "add_bos", False):
        tokenizer.add_bos = False

    max_prompt = _decode_position_budget(svc_cfg, cfg.max_position, p_len, "llama")

    def encode_fn(p, input_ids, attention_mask):
        return input_ids

    def init_state_fn(p, input_ids, enc_mask, max_len: int, sample=None):
        return llama_mod.init_decode_state(
            p, cfg, input_ids, enc_mask, max_len, dtype=policy.compute_jnp,
            sample=sample,
        )

    def generate_chunk_fn(p, state, n_steps: int, sample: bool = False):
        return llama_mod.generate_chunk(p, cfg, state, n_steps, sample)

    def paged_chunk_fn(p, state, table, n_steps: int, sample: bool = False):
        return llama_mod.generate_chunk_paged(
            p, cfg, state, table, n_steps, sample
        )

    def empty_state_fn(p, batch: int, s_total: int, max_len: int):
        return llama_mod.empty_decode_state(
            p, cfg, batch, s_total, max_len, dtype=policy.compute_jnp
        )

    def prefill_chunk_fn(p, state, ids, mask, start):
        return llama_mod.prefill_chunk(
            p, cfg, state, ids, mask, start, dtype=policy.compute_jnp
        )

    def paged_prefill_chunk_fn(p, state, table_row, ids, mask, start):
        return llama_mod.paged_prefill_chunk(
            p, cfg, state, table_row, ids, mask, start, dtype=policy.compute_jnp
        )

    def window_fn(p, state, n_steps: int, max_chunks: int,
                  sample: bool = False):
        return llama_mod.generate_window(
            p, cfg, state, n_steps, max_chunks, sample
        )

    def paged_window_fn(p, state, table, n_steps: int, max_chunks: int,
                        sample: bool = False):
        return llama_mod.generate_window_paged(
            p, cfg, state, table, n_steps, max_chunks, sample
        )

    from . import spec as spec_mod

    init_spec_fn = spec_mod.make_init_spec_fn(p_len)

    def spec_chunk_fn(p, spec_state, n_verify: int, spec_k: int,
                      sample: bool = False):
        return spec_mod.spec_chunk(
            p, spec_state, n_verify, spec_k, int(svc_cfg.spec_ngram),
            lambda pp, st, toks: llama_mod.multi_step(pp, cfg, st, toks),
            cfg.eos_id, cfg.pad_id, sample,
        )

    return ModelBundle(
        name="llama",
        kind=KIND_SEQ2SEQ,
        cfg=cfg,
        params=params,
        policy=policy,
        tokenizer=tokenizer,
        labels=None,
        forward=None,
        encode_fn=encode_fn,
        init_state_fn=init_state_fn,
        generate_chunk_fn=generate_chunk_fn,
        max_prompt_len=max_prompt,
        make_placement=_tp_placement(svc_cfg, cfg, "llama"),
        supports_prefix=True,
        init_spec_fn=init_spec_fn,
        spec_chunk_fn=spec_chunk_fn,
        paged_chunk_fn=paged_chunk_fn,
        empty_state_fn=empty_state_fn,
        prefill_chunk_fn=prefill_chunk_fn,
        paged_prefill_chunk_fn=paged_prefill_chunk_fn,
        window_fn=window_fn,
        paged_window_fn=paged_window_fn,
    )


MODEL_REGISTRY: dict[str, Callable] = {
    "resnet50": _build_resnet,
    "bert-base": _build_bert,
    "bert-long": _build_bert_long,
    "t5-small": _build_t5,
    "gpt2": _build_gpt,
    "llama": _build_llama,
}
MODEL_REGISTRY["tinyllama"] = _build_llama
# Aliases for HF-style names the reference's configs use.
MODEL_REGISTRY["resnet-50"] = _build_resnet
MODEL_REGISTRY["bert-base-uncased"] = _build_bert
MODEL_REGISTRY["t5small"] = _build_t5


def register_model(name: str, builder: Callable) -> None:
    """The template's extension point: plug YOUR model into the stack.

    The reference repo is a *template* — its README tells users to
    implement their model behind ``ModelWrapper`` hooks and get the
    HTTP service, batching and deployment for free (SURVEY.md §1–2).
    Same contract here: register ``builder(svc_cfg, policy) ->
    ModelBundle`` under a name, set ``MODEL_NAME=<name>``, and the
    engine/scheduler/API serve it with bucketed jit, dynamic batching
    and replica sharding unchanged.  See
    ``docs/custom_models.md`` for a worked example.
    """
    if not callable(builder):
        raise TypeError("builder must be callable(svc_cfg, policy) -> ModelBundle")
    if name in MODEL_REGISTRY:
        log.warning("register_model: overriding existing model %r", name)
    MODEL_REGISTRY[name] = builder


def build_model(svc_cfg, policy: DtypePolicy | None = None) -> ModelBundle:
    if policy is None:
        from ..runtime.device import default_policy

        policy = default_policy(svc_cfg.device)
    try:
        builder = MODEL_REGISTRY[svc_cfg.model_name]
    except KeyError:
        raise ValueError(
            f"unknown model {svc_cfg.model_name!r}; available: {sorted(MODEL_REGISTRY)}"
        ) from None
    bundle = builder(svc_cfg, policy)
    # TP must never be silently ignored: a model deployed BECAUSE
    # sharding makes it fit would otherwise OOM per-device with no
    # warning.  (bert-long composes SP, not TP, by design.)
    if int(getattr(svc_cfg, "tp", 0) or 0) > 1 and bundle.make_placement is None:
        raise ValueError(
            f"TP={svc_cfg.tp} is not supported for {svc_cfg.model_name!r} "
            "(tensor-parallel serving covers bert-base, gpt2 and llama; "
            "bert-long scales via SP/REPLICAS instead)"
        )
    # A configured PROMPT_PREFIX that a model silently drops would serve
    # un-prefixed generations with no warning — reject instead.
    if getattr(svc_cfg, "prompt_prefix", None) and not bundle.supports_prefix:
        raise ValueError(
            f"PROMPT_PREFIX is not supported for {svc_cfg.model_name!r} "
            "(cached-prefix serving covers the decoder families: gpt2, llama)"
        )
    # Same rule for SPEC_DECODE: an operator who turned it on must not
    # silently serve without it (zero speedup, no metric, no error).
    if getattr(svc_cfg, "spec_decode", None) and bundle.spec_chunk_fn is None:
        raise ValueError(
            f"SPEC_DECODE is not supported for {svc_cfg.model_name!r} "
            "(speculative decoding covers the generative families: "
            "gpt2, llama, t5-small)"
        )
    if getattr(svc_cfg, "quant_kv", None):
        # QUANT_KV now COMPOSES with both prefix knobs (round-6): prefix
        # KV is captured/attached as int8+per-row-scale entries the
        # quantized cache absorbs directly (llama._quant_prefix_entry),
        # so the only retained restriction is the family one.
        if bundle.name != "llama":
            raise ValueError(
                f"QUANT_KV is not supported for {svc_cfg.model_name!r} "
                "(int8 KV cache covers the llama family)"
            )
    if getattr(svc_cfg, "spec_continuous", False):
        # PREFIX_CACHE no longer excluded (round-6): hit-group batched
        # wave states recast through init_spec_fn at slot-insert time
        # (engine/streams.py), so prefix-hit streams join the spec slot
        # batch like any other admission.
        if not getattr(svc_cfg, "spec_decode", None):
            raise ValueError(
                "SPEC_CONTINUOUS requires SPEC_DECODE=ngram (it is the "
                "continuous-loop extension of speculative decoding)"
            )
    if getattr(svc_cfg, "paged_kv", False):
        # PAGED_KV v1 scope (docs/kv-paging.md): block-paged decode in
        # the continuous loop, decoder-only families.  Every unsupported
        # combination rejects loudly — a silently-contiguous deployment
        # would report paged occupancy wins it isn't getting.
        if bundle.paged_chunk_fn is None:
            raise ValueError(
                f"PAGED_KV is not supported for {svc_cfg.model_name!r} "
                "(block-paged KV covers the decoder families: gpt2, llama)"
            )
        if getattr(svc_cfg, "prompt_prefix", None):
            raise ValueError(
                "PAGED_KV and PROMPT_PREFIX are mutually exclusive: the "
                "global prefix overlay predates the block pool — use "
                "PREFIX_CACHE=1, whose hits SHARE prompt blocks by "
                "refcount"
            )
        if getattr(svc_cfg, "spec_continuous", False):
            raise ValueError(
                "PAGED_KV does not yet compose with SPEC_CONTINUOUS "
                "(speculative verify windows write multi-token spans "
                "through the table; planned follow-up)"
            )
        # Bucket alignment is no longer a rejection: ServiceConfig
        # block-aligns the seq bucket grid at parse time (rounding up,
        # deduped — utils/config._align_paged_seq_buckets).  Guard the
        # invariant here for duck-typed configs that bypassed pydantic.
        bs = int(getattr(svc_cfg, "kv_block_size", 16))
        bad = [b for b in svc_cfg.seq_buckets if b % bs]
        if bad:
            raise ValueError(
                f"KV_BLOCK_SIZE={bs} must divide every seq bucket; "
                f"ServiceConfig aligns the grid at parse time, but this "
                f"config bypassed it (offending buckets: {bad})"
            )
        if int(getattr(svc_cfg, "replicas", 0) or 0) > 1:
            raise ValueError(
                "PAGED_KV requires REPLICAS=1: the block pool has no "
                "batch axis to shard over the replica mesh"
            )
    if int(getattr(svc_cfg, "prefill_chunk", 0) or 0) > 0:
        # Chunked prefill (docs/chunked-prefill.md) changes the loop's
        # dispatch unit; every unsupported combination rejects loudly —
        # a silently-monolithic deployment would report interference
        # wins it isn't getting.
        if bundle.prefill_chunk_fn is None:
            raise ValueError(
                f"PREFILL_CHUNK is not supported for {svc_cfg.model_name!r} "
                "(chunked prefill covers the decoder families gpt2/llama; "
                "encoder-decoders like t5 prefill the DECODER from a start "
                "token — the encoder pass has no incremental KV to chunk)"
            )
        if getattr(svc_cfg, "prompt_prefix", None):
            raise ValueError(
                "PREFILL_CHUNK and PROMPT_PREFIX are mutually exclusive: "
                "the global prefix overlay seeds positions 0..P inside "
                "init_decode_state, which chunked prefill bypasses — use "
                "PREFIX_CACHE=1, whose hits suffix-prefill in chunks"
            )
        if getattr(svc_cfg, "spec_continuous", False):
            raise ValueError(
                "PREFILL_CHUNK does not compose with SPEC_CONTINUOUS "
                "(the spec slot insert rebuilds the drafting history from "
                "a monolithic collated prompt; planned follow-up)"
            )
        if getattr(svc_cfg, "paged_kv", False):
            bs = int(getattr(svc_cfg, "kv_block_size", 16))
            if int(svc_cfg.prefill_chunk) % bs:
                raise ValueError(
                    f"PREFILL_CHUNK={svc_cfg.prefill_chunk} must be a "
                    f"multiple of KV_BLOCK_SIZE={bs} so every window "
                    "boundary is block-aligned (per-chunk block growth "
                    "stays exact)"
                )
    if getattr(svc_cfg, "prefix_cache", False):
        if not bundle.supports_prefix:
            raise ValueError(
                f"PREFIX_CACHE is not supported for {svc_cfg.model_name!r} "
                "(per-request prefix caching covers the decoder "
                "families: gpt2, llama)"
            )
        if getattr(svc_cfg, "prompt_prefix", None):
            raise ValueError(
                "PREFIX_CACHE and PROMPT_PREFIX are mutually exclusive: "
                "the global prefix occupies positions 0..P that "
                "per-request prefixes need (the cache generalizes the "
                "global knob — drop PROMPT_PREFIX)"
            )
    return bundle
