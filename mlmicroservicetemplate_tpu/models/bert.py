"""BERT-base encoder + sequence-classification head, pure-JAX.

Capability parity: the reference serves an HF BERT-base text classifier
behind ``/predict`` (BASELINE.json:9). Ground-up JAX implementation of
the BERT architecture (post-LN transformer encoder, learned positions,
token-type embeddings, erf-GELU, LN eps 1e-12), HF-checkpoint-mappable
via ``convert.bert_state_to_pytree``.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from .common import (
    Params,
    dense,
    dense_init,
    embed,
    embedding_init,
    gelu,
    layernorm,
    layernorm_init,
    merge_heads,
    mha_attention,
    split_heads,
)


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    num_labels: int = 2
    ln_eps: float = 1e-12


def init_params(key, cfg: BertConfig = BertConfig()) -> Params:
    keys = jax.random.split(key, cfg.num_layers + 4)
    d = cfg.hidden_size
    params: Params = {
        "embeddings": {
            "word": embedding_init(keys[0], cfg.vocab_size, d),
            "position": embedding_init(keys[1], cfg.max_position, d),
            "token_type": embedding_init(keys[2], cfg.type_vocab_size, d),
            "ln": layernorm_init(d),
        },
        "layers": [],
    }
    for i in range(cfg.num_layers):
        k = jax.random.split(keys[3 + i], 6)
        params["layers"].append(
            {
                "attn": {
                    "q": dense_init(k[0], d, d, std=0.02),
                    "k": dense_init(k[1], d, d, std=0.02),
                    "v": dense_init(k[2], d, d, std=0.02),
                    "out": dense_init(k[3], d, d, std=0.02),
                    "ln": layernorm_init(d),
                },
                "mlp": {
                    "up": dense_init(k[4], d, cfg.intermediate_size, std=0.02),
                    "down": dense_init(k[5], cfg.intermediate_size, d, std=0.02),
                    "ln": layernorm_init(d),
                },
            }
        )
    k_pool, k_cls = jax.random.split(keys[-1])
    params["pooler"] = dense_init(k_pool, d, d, std=0.02)
    params["classifier"] = dense_init(k_cls, d, cfg.num_labels, std=0.02)
    return params


def _layer(
    p: Params,
    cfg: BertConfig,
    x: jax.Array,
    mask: jax.Array,
    key_mask: jax.Array | None = None,
    attn_fn=None,
) -> jax.Array:
    a = p["attn"]
    q = split_heads(dense(a["q"], x), cfg.num_heads)
    k = split_heads(dense(a["k"], x), cfg.num_heads)
    v = split_heads(dense(a["v"], x), cfg.num_heads)
    if attn_fn is not None:
        # Pluggable attention core (q, k, v, key_mask[B,S]) -> ctx —
        # the long-context path injects ring attention here.
        ctx = merge_heads(attn_fn(q, k, v, key_mask))
    elif key_mask is not None:
        # Pallas fused path: scores/softmax stay VMEM-resident
        # (default on TPU serving, see ops/attention.py).
        from ..ops.attention import fused_attention

        ctx = merge_heads(fused_attention(q, k, v, key_mask))
    else:
        ctx = merge_heads(mha_attention(q, k, v, mask=mask))
    x = layernorm(a["ln"], x + dense(a["out"], ctx), eps=cfg.ln_eps)
    m = p["mlp"]
    h = dense(m["down"], gelu(dense(m["up"], x)))
    return layernorm(m["ln"], x + h, eps=cfg.ln_eps)


def encode(
    params: Params,
    cfg: BertConfig,
    input_ids: jax.Array,  # [B, S] int32
    attention_mask: jax.Array,  # [B, S] 1=keep
    token_type_ids: jax.Array | None = None,
    dtype=jnp.float32,
    use_pallas: bool = False,
    attn_fn=None,
) -> jax.Array:
    """Returns the final hidden states [B, S, D]."""
    b, s = input_ids.shape
    e = params["embeddings"]
    x = embed(e["word"], input_ids, dtype)
    x = x + embed(e["position"], jnp.arange(s, dtype=jnp.int32), dtype)[None]
    tt = token_type_ids if token_type_ids is not None else jnp.zeros_like(input_ids)
    x = x + embed(e["token_type"], tt, dtype)
    x = layernorm(e["ln"], x, eps=cfg.ln_eps)
    mask = attention_mask[:, None, None, :].astype(bool)  # [B,1,1,S]
    # use_pallas must be decided by the CALLER (the serving wrapper):
    # the kernel has no VJP and no sharding awareness, so training and
    # tp-sharded consumers of encode() stay on the jnp path.
    key_mask = attention_mask if (use_pallas or attn_fn is not None) else None
    for layer in params["layers"]:
        x = _layer(layer, cfg, x, mask, key_mask=key_mask, attn_fn=attn_fn)
    return x


def classify(
    params: Params,
    cfg: BertConfig,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    token_type_ids: jax.Array | None = None,
    dtype=jnp.float32,
    use_pallas: bool = False,
    attn_fn=None,
) -> jax.Array:
    """Sequence classification logits [B, num_labels] in f32 (the serving path)."""
    hidden = encode(
        params, cfg, input_ids, attention_mask, token_type_ids, dtype, use_pallas,
        attn_fn=attn_fn,
    )
    pooled = jnp.tanh(dense(params["pooler"], hidden[:, 0]).astype(jnp.float32))
    return dense(params["classifier"], pooled.astype(jnp.float32))
