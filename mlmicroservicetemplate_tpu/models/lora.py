"""Batched multi-adapter (LoRA) delta math (docs/multi-tenancy.md).

The serving loop attaches stacked adapter weights to the params dict
under ``"__adapters__"`` — the same overlay precedent as
``"__prefix__"`` (gpt.py): absent key = the traced graph is IDENTICAL
to the base model (the bit-identical-default pin), present key = every
projection gains a per-row low-rank delta gathered by a per-row slot
index, so ONE dispatch serves rows running different adapters.

Overlay layout (built by ``tenancy.adapters.AdapterPool.overlay``)::

    {
      "rows": int32 [B]          # per-row adapter slot (0 = zero delta)
      "<proj>": {                # e.g. "qkv"/"out" (gpt), "q".."o" (llama)
        "a": f32 [S, L, d_in, r],   # slot-stacked LoRA A (slot 0 = zeros)
        "b": f32 [S, L, r, d_out],  # slot-stacked LoRA B (scale folded in)
      },
    }

``S`` (slot count) and ``r`` (max rank, zero-padded) are FIXED at pool
build, so loading/evicting adapters swaps array CONTENTS (same shapes)
and the serving executables never recompile (CompileWindow-pinned).
Slot 0 is all-zero: ``adapter_id=None`` rows ride the same batched
dispatch and produce base-model tokens (pinned).
"""

from __future__ import annotations

import jax.numpy as jnp


def adapter_tables(params):
    """The ``__adapters__`` overlay when the caller attached one."""
    return params.get("__adapters__") if isinstance(params, dict) else None


def delta(ad, name: str, li: int, x):
    """Per-row LoRA delta for projection ``name`` at layer ``li``, or
    None when no overlay / the projection isn't adapted.

    ``x`` is the projection INPUT ``[B, T, d_in]``; the result is the
    ``[B, T, d_out]`` term to add to the dense output.  Row ``i`` uses
    adapter slot ``rows[i]`` — two batched einsums through the row's
    gathered ``[d_in, r]`` / ``[r, d_out]`` factors (rank ``r`` ≪ d,
    so the extra FLOPs are a rounding error next to the base matmul).
    """
    ent = None if ad is None else ad.get(name)
    if ent is None:
        return None
    rows = ad["rows"]
    a = jnp.take(ent["a"][:, li], rows, axis=0)  # [B, d_in, r]
    b = jnp.take(ent["b"][:, li], rows, axis=0)  # [B, r, d_out]
    h = jnp.einsum("btd,bdr->btr", x.astype(a.dtype), a)
    return jnp.einsum("btr,bro->bto", h, b).astype(x.dtype)


def apply(ad, name: str, li: int, x, y):
    """``y + delta`` when adapted, else ``y`` UNTOUCHED (same traced
    graph as the base model when no overlay is present)."""
    d = delta(ad, name, li, x)
    return y if d is None else y + d
