"""Fused decode windows: W chunk scans in ONE dispatch, with
on-device EOS early exit.

The continuous loop's dispatch unit so far was one chunk
(``generate_chunk``: a ``lax.scan`` of ``chunk_tokens`` decode steps).
Through a relay-attached device every dispatch boundary costs a host
round-trip, and the round-11 attribution measured host_share ≈ 1.0 at
the chunk/fetch sites — the boundaries, not the compute, are the
serving ceiling (BENCH_r02–r05).  A fused window lifts the unit to W
chunks: a ``lax.while_loop`` whose body is one whole chunk scan, so
the host submits once, fetches once and reconciles once per W chunks
instead of per chunk.

Why a while_loop and not one W·chunk scan: the loop carries the chunk
STRUCTURE into the fused dispatch — the condition re-checks
``state.done`` at every chunk boundary and stops the moment every row
is finished (on-device EOS early exit), so a window is never charged
for chunks past the batch's last EOS.  The host learns how many chunks
actually ran from the returned counter and routes exactly those.

Token identity is by construction: the body calls the SAME chunk
function the per-chunk path dispatches, on the same state, in the same
order — fusing changes where the host/device boundary sits, never the
math.  The per-chunk ``done`` history rides out with the tokens so the
host can replay its per-chunk routing (budget cursor, EOS at chunk
granularity) bit-for-bit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def decode_window(chunk_fn, state, n_steps: int, max_chunks: int, pad_id: int):
    """Run up to ``max_chunks`` invocations of ``chunk_fn`` (one chunk
    scan each: ``state -> (state, [B, n_steps] tokens)``) inside a
    single ``lax.while_loop``, stopping early once every row is done.

    Returns ``(state, tokens [B, max_chunks*n_steps], done_hist
    [max_chunks, B], n_chunks)``:

    - ``tokens``: chunk c's tokens at columns [c·n_steps, (c+1)·n_steps);
      unexecuted chunks stay ``pad_id``.
    - ``done_hist[c]``: ``state.done`` AFTER chunk c — what the
      per-chunk path's fetch would have seen at that boundary;
      unexecuted rows read all-done.
    - ``n_chunks``: chunks actually executed (< max_chunks on early
      exit; 0 when every row was already done at entry).
    """
    b = state.done.shape[0]
    buf = jnp.full((b, max_chunks * n_steps), pad_id, jnp.int32)
    hist = jnp.ones((max_chunks, b), bool)

    def cond(carry):
        s, _, _, i = carry
        return (i < max_chunks) & jnp.logical_not(jnp.all(s.done))

    def body(carry):
        s, buf, hist, i = carry
        s, toks = chunk_fn(s)
        buf = jax.lax.dynamic_update_slice(
            buf, toks.astype(jnp.int32), (0, i * n_steps)
        )
        hist = jax.lax.dynamic_update_slice(hist, s.done[None], (i, 0))
        return s, buf, hist, i + 1

    state, buf, hist, n = jax.lax.while_loop(
        cond, body, (state, buf, hist, jnp.int32(0))
    )
    return state, buf, hist, n
