from .device import DtypePolicy, apply_device_env, default_policy, get_devices

__all__ = ["DtypePolicy", "apply_device_env", "default_policy", "get_devices"]
