"""Process-level executable cache: compile once, serve from every replica.

Why this layer exists (ISSUE 14 / ROADMAP item 4): ``jax.jit`` caches
traces and compiled executables PER WRAPPER OBJECT.  Every
``ContinuousDecodeLoop`` and ``InferenceEngine`` used to construct its
own private wrappers (``jax.jit(bundle.generate_chunk_fn)``, the insert
scatters, the window/handoff/swap executables, …), so a second fleet
replica — identical bundle, identical shapes, identical placement —
re-traced and re-compiled every one of them from scratch.  On CPU that
warm compile measured 262 s per ``_spawn_replica`` (BASELINE.md r17,
the honest negative that made elastic scaling LOSE its A/B); through
the TPU relay it is the 52–487 s warmup table.

``ExecutableCache`` is the fix: ONE process-level table of jitted
wrappers keyed by

    (bundle fingerprint, executable kind, static descriptor, placement)

shared across the fleet exactly like the r14 host KV tier and the r15
journal.  A spawned replica's ``warm()`` then finds every wrapper
already built — its warm dispatches hit jit's C++ fast path (same
shapes, same shardings) and perform ZERO XLA compiles, which
``tests/test_compile_cache.py`` pins by counting backend compiles via
``jax.monitoring``.  Supervised restarts (``reset_device_state``) and
journal-replay re-admissions reuse the same wrappers for the same
reason.

Key discipline (the no-aliasing contract, also pinned):

- the **bundle fingerprint** is a fresh unique token minted per bundle
  OBJECT and stored on it — two distinct bundles can never collide,
  even with identical names/dims, and a fleet (which shares one bundle
  object) shares one fingerprint;
- the **kind** names the executable's code path ("gen_chunk",
  "paged_insert", …);
- the **static descriptor** carries everything the builder closes over
  besides the bundle (static argnums are implied by the kind; closure
  constants like a prefix length or block size must be spelled out);
- the **placement** is the device set the engine dispatches onto
  (engines over different meshes never share).

Layering (docs/compilation.md): jit's per-wrapper cache (shapes ×
shardings) sits below this table; the persistent XLA disk cache
(``COMPILE_CACHE_DIR``, runtime/device.py) sits below BOTH and is what
carries compiles across process restarts.

This module is import-light (no jax at import time) and thread-safe:
fleet replicas warm concurrently and jitted callables are themselves
thread-safe.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict
from typing import Any, Callable

from ..utils import metrics, perfobs

_LOCK = threading.RLock()
_CACHE: OrderedDict[tuple, Any] = OrderedDict()
_COUNTS = {"hit": 0, "miss": 0, "insert": 0}
#: Soft entry cap — an LRU bound, not a correctness surface (an evicted
#: wrapper simply recompiles on next use).  Generous: a real deployment
#: has a few dozen kinds × one bundle.
MAX_ENTRIES = 1024

_fp_counter = itertools.count()

# -- warm-phase accounting (engine_warm_seconds{phase}) ----------------
_WARM_LOCK = threading.Lock()
_WARM_PHASES: dict[str, float] = {}

# -- XLA compile accounting (jax.monitoring) ---------------------------
_MON_LOCK = threading.Lock()
_MON_INSTALLED = False
_COMPILES = {"count": 0, "seconds": 0.0}
_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def _install_monitor() -> None:
    """Register ONE process-wide jax.monitoring listener that counts
    backend (XLA) compiles and their wall seconds.  Idempotent; the
    listener cannot be unregistered, so it accumulates for the process
    lifetime and consumers read deltas (``CompileWindow``)."""
    global _MON_INSTALLED
    with _MON_LOCK:
        if _MON_INSTALLED:
            return
        import jax

        def on_duration(name: str, dur: float, **kw) -> None:
            if name != _BACKEND_COMPILE_EVENT:
                return
            with _MON_LOCK:
                _COMPILES["count"] += 1
                _COMPILES["seconds"] += float(dur)

        jax.monitoring.register_event_duration_secs_listener(on_duration)
        _MON_INSTALLED = True


def compile_counters() -> dict:
    """Process-lifetime XLA compile totals ``{count, seconds}`` (zeros
    until the first shared executable installs the monitor)."""
    with _MON_LOCK:
        return dict(_COMPILES)


class CompileWindow:
    """Delta view over the compile counters::

        with CompileWindow() as w:
            replica.cdl.warm()
        assert w.compiles == 0          # the zero-compile spawn pin
        breakdown["compile_s"] = w.seconds
    """

    def __init__(self):
        self.compiles = 0
        self.seconds = 0.0
        self._base: dict | None = None

    def __enter__(self) -> "CompileWindow":
        _install_monitor()
        self._base = compile_counters()
        return self

    def __exit__(self, *exc) -> None:
        now = compile_counters()
        self.compiles = now["count"] - self._base["count"]
        self.seconds = now["seconds"] - self._base["seconds"]


def bundle_fingerprint(bundle: Any) -> str:
    """The bundle's cache identity: a unique token minted on first use
    and stored on the bundle object.  Distinct bundle objects ALWAYS
    get distinct tokens (no aliasing, ever — not even after one is
    garbage-collected); everything sharing the object (a whole fleet)
    shares the token."""
    fp = getattr(bundle, "_exec_fingerprint", None)
    if fp is None:
        with _LOCK:
            fp = getattr(bundle, "_exec_fingerprint", None)
            if fp is None:
                fp = (
                    f"{getattr(bundle, 'name', '?')}"
                    f"#{next(_fp_counter)}"
                )
                try:
                    bundle._exec_fingerprint = fp
                except Exception:
                    # Unwritable bundle (slots/frozen): fall back to the
                    # object id with the bundle PINNED by the cache
                    # entry, so the id can never be recycled while a
                    # wrapper is live under it.
                    fp = f"id:{id(bundle)}"
    return fp


def placement_key(replicas: Any) -> tuple:
    """Hashable descriptor of the device set an engine dispatches onto
    PLUS its sharding layout.  Engines sharing one ReplicaSet (every
    fleet replica today) get the same key; distinct meshes/device sets
    never share — and neither do distinct LAYOUTS over the same
    devices: a TP=2 ``('replica','tp')`` mesh and a REPLICAS=2 DP mesh
    cover the same two chips but compile different SPMD programs, so
    the key carries a mesh-topology + PartitionSpec fingerprint
    (parallel/tpserve.py).  Single-device placements fingerprint to ""
    — every pre-TP key stays byte-identical."""
    mesh = getattr(replicas, "mesh", None)
    devs = getattr(mesh, "devices", None)
    if devs is not None:
        try:
            from ..parallel.tpserve import placement_fingerprint

            return (placement_fingerprint(replicas),) + tuple(
                str(d) for d in devs.flat
            )
        except Exception:
            pass
    return ("replicas", id(replicas))


# -- modeled-cost accounting (r20 perf observatory) --------------------
#
# Every shared executable is wrapped in a thin proxy that, on the
# first call per argument signature, runs ``Lowered.cost_analysis()``
# — a trace + lower with ZERO backend compiles (the zero-compile spawn
# pins stay intact) and zero dispatches — and from then on accrues the
# memoized modeled FLOPs/bytes into the perfobs book on every call.
# "Analyzed once per executable": the proxy lives in the process-level
# cache, so every engine/replica sharing the wrapper shares the memo;
# a (wrapper, signature) pair IS one XLA executable.  PERF_OBS=0 skips
# everything past one boolean check per call.

#: Distinct call signatures analyzed per wrapper before the proxy
#: stops analyzing new ones (a signature that never memoizes — e.g. a
#: pathological pytree — must not re-pay a trace+lower per dispatch).
MAX_SIGS = 16


def _sig_item(a: Any) -> Any:
    """Cheap hashable shape signature for one call argument: scalars by
    value, arrays by (shape, dtype), containers recursively (lists of
    per-layer cache entries stay cheap), opaque pytrees by identity
    (``params`` is a stable dict on the engine)."""
    if a is None or isinstance(a, (bool, int, float, str)):
        return a
    shp = getattr(a, "shape", None)
    dt = getattr(a, "dtype", None)
    if shp is not None and dt is not None:
        return (tuple(shp), str(dt))
    if isinstance(a, dict):
        return ("dict", id(a))
    if isinstance(a, (tuple, list)) and len(a) <= 64:
        return (type(a).__name__,) + tuple(_sig_item(x) for x in a)
    if hasattr(a, "_fields"):  # NamedTuple decode states
        return ("nt",) + tuple(_sig_item(getattr(a, f)) for f in a._fields)
    return ("obj", id(a))


class _CostedExecutable:
    """Call-transparent proxy accruing modeled FLOPs per dispatch.

    Also the trace-group pin: an executable built for a non-prefix
    device group (multi-chip fleet replica) re-enters its group's
    thread-local around every call/lower, so a model-fn ``shard_map``
    traced from ANY thread (continuous loop, watchdog daemon, warmers)
    reconstructs ``serving_tp_mesh`` over the replica's own devices —
    parallel/tpserve.py.  ``_group is None`` (every single-group
    serving stack) costs one attribute check per call."""

    __slots__ = ("_fn", "_kind", "_model", "_costs", "_costs_lock",
                 "_group")

    def __init__(self, fn: Any, kind: str, model: str, group=None):
        self._fn = fn
        self._kind = kind
        self._model = model
        self._costs: dict = {}
        self._costs_lock = threading.Lock()
        self._group = group

    def __call__(self, *args, **kwargs):
        if self._group is not None:
            from ..parallel.tpserve import use_trace_group

            with use_trace_group(self._group):
                out = self._fn(*args, **kwargs)
        else:
            out = self._fn(*args, **kwargs)
        if perfobs.enabled():
            sig = tuple(_sig_item(a) for a in args)
            c = self._costs.get(sig)
            if c is None:
                c = self._analyze(sig, args, kwargs)
            if c[0] or c[1]:
                perfobs.note_cost(self._model, self._kind, c[0], c[1])
        return out

    def _analyze(self, sig, args, kwargs) -> tuple[float, float]:
        with self._costs_lock:
            if sig in self._costs:
                return self._costs[sig]
            if len(self._costs) >= MAX_SIGS:
                return (0.0, 0.0)  # saturated: stop analyzing new sigs
            try:
                if self._group is not None:
                    from ..parallel.tpserve import use_trace_group

                    with use_trace_group(self._group):
                        ca = self._fn.lower(
                            *args, **kwargs).cost_analysis()
                else:
                    ca = self._fn.lower(*args, **kwargs).cost_analysis()
                if isinstance(ca, (list, tuple)):
                    ca = ca[0] if ca else {}
                cost = (
                    float(ca.get("flops", 0.0) or 0.0),
                    float(ca.get("bytes accessed", 0.0) or 0.0),
                )
            except Exception:
                # Backends without HLO cost analysis (or un-lowerable
                # duck-typed test fns): this executable just accrues
                # nothing — the estimator degrades, serving does not.
                cost = (0.0, 0.0)
            self._costs[sig] = cost
            return cost

    def __getattr__(self, name: str):
        # Transparent for .lower()/.trace()/attribute probes.
        return getattr(self._fn, name)


def cost_stats() -> dict:
    """Analyzed-signature counts per cached wrapper kind (/status +
    tests): {kind: n_signatures}."""
    out: dict[str, int] = {}
    with _LOCK:
        entries = list(_CACHE.items())
    for key, fn in entries:
        if isinstance(fn, _CostedExecutable):
            out[key[1]] = out.get(key[1], 0) + len(fn._costs)
    return out


def shared_executable(kind: str, bundle: Any, replicas: Any,
                      build: Callable[[], Any], statics: tuple = ()) -> Any:
    """The one lookup every jit-wrapper construction site routes
    through: return the cached wrapper for this (bundle, kind, statics,
    placement) or build-and-insert it.  ``build`` must construct the
    wrapper from state fully described by the key (the bundle's fns +
    the spelled-out statics) — that is the no-aliasing contract."""
    key = (
        bundle_fingerprint(bundle), kind, tuple(statics),
        placement_key(replicas),
    )
    model = getattr(bundle, "name", "?")
    with _LOCK:
        fn = _CACHE.get(key)
        if fn is not None:
            _CACHE.move_to_end(key)
            _COUNTS["hit"] += 1
            metrics.EXEC_CACHE_EVENTS.labels("hit").inc()
            return fn
        _COUNTS["miss"] += 1
    metrics.EXEC_CACHE_EVENTS.labels("miss").inc()
    _install_monitor()  # first build turns on compile accounting
    try:
        from ..parallel.tpserve import device_group, use_trace_group

        grp = device_group(replicas)
    except Exception:
        grp = None
    if grp is not None:
        # Build (and later call/lower) under the placement's device
        # group so any eager trace lands on the right mesh.
        with use_trace_group(grp):
            fn = _CostedExecutable(build(), kind, model, group=grp)
    else:
        fn = _CostedExecutable(build(), kind, model)
    with _LOCK:
        # A racing builder may have inserted meanwhile: last wins is
        # fine (both wrappers are correct; one just goes unshared), but
        # prefer the first so concurrent warmers converge on one.
        existing = _CACHE.get(key)
        if existing is not None:
            return existing
        _CACHE[key] = fn
        # The id:-fingerprint fallback pins the bundle (see
        # bundle_fingerprint); normal tokens don't need it.
        _COUNTS["insert"] += 1
        while len(_CACHE) > MAX_ENTRIES:
            _CACHE.popitem(last=False)
    metrics.EXEC_CACHE_EVENTS.labels("insert").inc()
    _ = model  # model kept out of the series: ≤1 label, bounded
    return fn


def cache_stats() -> dict:
    """{entries, hit, miss, insert} — /status.compile + BENCH json."""
    with _LOCK:
        return {"entries": len(_CACHE), **_COUNTS}


def cache_kinds() -> dict:
    """Entry count per ``kind`` — lets /status and the autotuner tests
    see e.g. how many ``paged_decode_kernel`` variants are installed
    without exposing the raw keys (which embed bundle fingerprints)."""
    with _LOCK:
        out: dict = {}
        for key in _CACHE:
            out[key[1]] = out.get(key[1], 0) + 1
        return out


def clear() -> None:
    """Test hook: drop every cached wrapper and zero the event counts
    (compile totals are process-lifetime and stay)."""
    with _LOCK:
        _CACHE.clear()
        for k in _COUNTS:
            _COUNTS[k] = 0


def note_warm_phase(model: str, phase: str, seconds: float) -> None:
    """Record one warm phase's wall seconds: feeds
    ``engine_warm_seconds{phase}`` and the process totals bench.py's
    ``warmup`` block reads."""
    metrics.WARM_SECONDS.labels(model, phase).observe(seconds)
    with _WARM_LOCK:
        _WARM_PHASES[phase] = _WARM_PHASES.get(phase, 0.0) + seconds


class warm_phase:
    """``with warm_phase(model, "loop"): cdl.warm()`` timing helper."""

    def __init__(self, model: str, phase: str):
        self.model = model
        self.phase = phase
        self.seconds = 0.0

    def __enter__(self) -> "warm_phase":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.seconds = time.perf_counter() - self._t0
        note_warm_phase(self.model, self.phase, self.seconds)


def warm_stats() -> dict:
    """Accumulated per-phase warm seconds for /status + BENCH."""
    with _WARM_LOCK:
        return {k: round(v, 4) for k, v in sorted(_WARM_PHASES.items())}
