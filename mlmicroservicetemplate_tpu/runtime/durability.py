"""Durable serving: crash-safe stream journal + disk KV tier + reconnects.

The fault-tolerance ladder (docs/fault-tolerance.md) ends at the
process boundary: dispatch retries, supervised engine rebuilds, replica
failover and host-RAM KV swap all assume the Python process survives.
A SIGKILL/OOM loses every in-flight stream and the entire prefix/KV
investment.  This module is the next rung — state that OUTLIVES the
process:

- **StreamJournal** (``JOURNAL_DIR``): a write-ahead, append-only log
  of every stream's admission record and delivered-token cursor.  Each
  record is one JSON object framed by a ``<u32 length><u32 crc32>``
  header, so a torn tail (the write the kill interrupted) is detected
  and truncated at replay instead of poisoning the log.  Records are
  written BEFORE tokens are emitted to the consumer (write-ahead), so
  the journal cursor always covers everything a client may have seen.
  On startup the server replays the journal and re-admits every
  incomplete stream through the existing recast/replay resume paths —
  token-identical completions after ``kill -9``.

- **KVDiskTier** (``KV_DISK_BUDGET_MB``): a disk block tier BELOW the
  host-RAM tier (``engine/kv_blocks.KVHostTier``).  Cold host blocks
  (LRU-evicted swap entries and demoted prefixes) spill here instead
  of dying, and stream checkpoints write through so their resume KV
  can outlive the process: a post-restart resume prefetches
  disk→host→device instead of re-prefilling.  Block payloads live in
  per-leaf memmap files; entry metadata rides a framed index log with
  the same torn-tail discipline as the journal.

- **StreamRegistry**: the reconnect surface.  Resumed streams run
  headless (their original connection died with the process); clients
  reconnect via ``GET /v1/streams/{request_id}`` and drain the
  journaled tokens plus the live continuation — exactly once each.

``JOURNAL_DIR`` unset (the default) builds none of this: every hook in
the serving path is a ``None`` check, bit-identical to the pre-journal
code (pinned by test).

Durability model: appends hit the OS page cache at ``write()`` time,
which survives a *process* kill (the chaos contract here) regardless
of fsync.  ``JOURNAL_FSYNC`` governs survival of a *kernel/power*
crash: ``always`` fsyncs per record, ``interval`` at most every 50 ms,
``off`` never.
"""

from __future__ import annotations

import json
import logging
import os
import struct
import threading
import time
import zlib

import numpy as np

from ..utils import metrics

log = logging.getLogger(__name__)

_HDR = struct.Struct("<II")  # payload length, crc32(payload)
_FSYNC_INTERVAL_S = 0.05
# Compaction bounds: completed-stream history and unary results kept
# across restarts (reconnect idempotency) without unbounded growth.
_KEEP_DONE = 256
_KEEP_RESULTS = 1024
# Completed-then-compacted rids kept as TOMBSTONES (rid + terminal
# outcome only): the reconnect endpoint answers 410 "already finished,
# history gone" for these, vs 404 for ids this journal never saw.
_KEEP_TOMBS = 4096

# The admission-record feats whitelist: everything a token-identical
# resume needs, nothing engine-internal.  Deadlines are deliberately
# dropped — a stream that survived a process crash must not 504 on
# replay because its original deadline lapsed while the server was down.
_FEAT_KEYS = (
    "length", "temperature", "top_k", "top_p", "seed", "max_tokens",
    "priority", "request_id",
)


def append_frame(f, payload: bytes) -> None:
    """One framed record: header + payload (payload ends with ``\\n``
    so the log stays greppable)."""
    f.write(_HDR.pack(len(payload), zlib.crc32(payload)) + payload)


def read_frames(path: str) -> tuple[list[bytes], int]:
    """Every intact record plus the byte offset of the first torn/bad
    frame (== file size when the log is clean).  A short header, short
    payload or CRC mismatch ends the scan — everything after a torn
    write is unreachable by construction (frames are self-delimiting),
    so the caller truncates there."""
    out: list[bytes] = []
    good = 0
    try:
        data = open(path, "rb").read()
    except FileNotFoundError:
        return out, 0
    n = len(data)
    while good + _HDR.size <= n:
        length, crc = _HDR.unpack_from(data, good)
        end = good + _HDR.size + length
        if end > n:
            break
        payload = data[good + _HDR.size : end]
        if zlib.crc32(payload) != crc:
            break
        out.append(payload)
        good = end
    return out, good


class RecoveredStream:
    """One stream's replayed state: the admission record plus the
    cumulative delivered-token cursor."""

    __slots__ = (
        "rid", "feats", "klass", "budget", "tokens", "done", "outcome",
        "stop",
    )

    def __init__(self, rid: str, feats: dict, klass: str, budget: int,
                 stop=()):
        self.rid = rid
        self.feats = feats  # JSON-serializable form
        self.klass = klass
        self.budget = int(budget)
        self.tokens: list[int] = []
        self.done = False
        self.outcome: str | None = None
        self.stop = tuple(stop or ())

    def np_feats(self) -> dict:
        """The feats dict the engine consumes (arrays restored)."""
        f = dict(self.feats)
        ids = np.asarray(f.get("input_ids", []), np.int32)
        f["input_ids"] = ids
        f["length"] = np.int32(int(f.get("length", ids.size)))
        return f


class StreamJournal:
    """Write-ahead journal for one serving process (see module doc).

    Thread-safe: the decode loop thread appends token cursors while
    the event loop appends admissions.  One process owns a journal dir
    at a time (advisory ``flock`` on ``.lock``) — two servers sharing
    a journal would interleave frames and corrupt each other's replay.
    """

    def __init__(self, dir: str, fsync: str = "always", model: str = ""):
        self.dir = dir
        self.fsync = str(fsync or "always").lower()
        self.model = model or "unknown"
        self._lock = threading.RLock()
        self._last_fsync = 0.0
        self.records_written = 0
        self.torn_bytes = 0
        os.makedirs(dir, exist_ok=True)
        self._lockfile = open(os.path.join(dir, ".lock"), "a+")
        try:
            import fcntl

            fcntl.flock(self._lockfile, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except ImportError:  # pragma: no cover - non-unix
            pass
        except OSError:
            self._lockfile.close()
            raise RuntimeError(
                f"journal dir {dir!r} is locked by another process "
                "(one server per JOURNAL_DIR)"
            )
        # Replay every segment in order, then compact the live state
        # into a fresh segment and delete the old ones — replay cost
        # and on-disk size stay proportional to LIVE state, not to
        # all-time history.
        self.streams: dict[str, RecoveredStream] = {}
        self.results: dict[str, list[int]] = {}
        self.tombs: dict[str, str] = {}
        segs = self._segments()
        for _, path in segs:
            frames, good = read_frames(path)
            sz = os.path.getsize(path)
            if good < sz:
                self.torn_bytes += sz - good
                log.warning(
                    "journal %s: torn tail (%d bytes) truncated at replay",
                    path, sz - good,
                )
            for payload in frames:
                try:
                    self._apply(json.loads(payload))
                except Exception:
                    log.exception("journal: unreadable record skipped")
        nxt = (segs[-1][0] + 1) if segs else 1
        self._path = os.path.join(dir, f"wal-{nxt:06d}.log")
        self._f = open(self._path, "ab")
        self._compact_into_open_segment()
        for _, path in segs:
            try:
                os.unlink(path)
            except OSError:
                pass

    # -- replay --------------------------------------------------------

    def _segments(self) -> list[tuple[int, str]]:
        out = []
        for name in os.listdir(self.dir):
            if name.startswith("wal-") and name.endswith(".log"):
                try:
                    out.append(
                        (int(name[4:-4]), os.path.join(self.dir, name))
                    )
                except ValueError:
                    pass
        return sorted(out)

    def _apply(self, rec: dict) -> None:
        k = rec.get("k")
        rid = str(rec.get("rid", ""))
        if k == "admit":
            rs = RecoveredStream(
                rid, rec.get("feats", {}), rec.get("klass", "interactive"),
                rec.get("budget", 0), stop=rec.get("stop", ()),
            )
            # A compacted admit carries its cumulative cursor; replay
            # RESETS the rid to it, so a crash mid-compaction (old and
            # new segments both present) can never double-count deltas.
            rs.tokens = [int(t) for t in rec.get("delivered", [])]
            self.streams[rid] = rs
            self.tombs.pop(rid, None)  # the rid lives again
        elif k == "tokens":
            rs = self.streams.get(rid)
            if rs is not None:
                rs.tokens.extend(int(t) for t in rec.get("t", []))
        elif k == "done":
            rs = self.streams.get(rid)
            if rs is not None:
                rs.done = True
                rs.outcome = rec.get("outcome", "end")
        elif k == "result":
            self.results[rid] = [int(t) for t in rec.get("row", [])]
        elif k == "tomb":
            self.tombs[rid] = str(rec.get("outcome", "end"))

    def _compact_into_open_segment(self) -> None:
        done = [rs for rs in self.streams.values() if rs.done]
        for rs in done[: max(0, len(done) - _KEEP_DONE)]:
            self.streams.pop(rs.rid, None)
            # The token history dies here; the terminal outcome
            # survives as a tombstone so reconnects get 410, not 404.
            self.tombs[rs.rid] = rs.outcome or "end"
        for rid in list(self.tombs)[: max(0, len(self.tombs) - _KEEP_TOMBS)]:
            self.tombs.pop(rid)
        for rid in list(self.results)[: max(0, len(self.results) - _KEEP_RESULTS)]:
            self.results.pop(rid, None)
        with self._lock:
            for rs in self.streams.values():
                append_frame(self._f, (json.dumps({
                    "k": "admit", "rid": rs.rid, "feats": rs.feats,
                    "klass": rs.klass, "budget": rs.budget,
                    "stop": list(rs.stop), "delivered": rs.tokens,
                }) + "\n").encode())
                if rs.done:
                    append_frame(self._f, (json.dumps({
                        "k": "done", "rid": rs.rid,
                        "outcome": rs.outcome or "end",
                    }) + "\n").encode())
            for rid, row in self.results.items():
                append_frame(self._f, (json.dumps({
                    "k": "result", "rid": rid, "row": row,
                }) + "\n").encode())
            for rid, outcome in self.tombs.items():
                append_frame(self._f, (json.dumps({
                    "k": "tomb", "rid": rid, "outcome": outcome,
                }) + "\n").encode())
            self._f.flush()
            os.fsync(self._f.fileno())

    def incomplete(self) -> list[RecoveredStream]:
        return [rs for rs in self.streams.values() if not rs.done]

    def lookup_result(self, rid: str) -> list[int] | None:
        with self._lock:
            row = self.results.get(rid)
            return list(row) if row is not None else None

    def terminal_status(self, rid: str) -> str | None:
        """The journaled terminal outcome for a COMPLETED stream —
        live (still tracked) or compacted down to a tombstone.  None =
        this journal never saw the rid finish (the reconnect endpoint's
        404), else the outcome string behind its 410."""
        with self._lock:
            rs = self.streams.get(rid)
            if rs is not None and rs.done:
                return rs.outcome or "end"
            return self.tombs.get(rid)

    # -- appends (write-ahead) -----------------------------------------

    def _append(self, kind: str, rec: dict) -> None:
        payload = (json.dumps(rec, separators=(",", ":")) + "\n").encode()
        with self._lock:
            if self._f.closed:
                return
            append_frame(self._f, payload)
            self._f.flush()
            self.records_written += 1
            now = time.monotonic()
            if self.fsync == "always" or (
                self.fsync == "interval"
                and now - self._last_fsync >= _FSYNC_INTERVAL_S
            ):
                t0 = time.perf_counter()
                os.fsync(self._f.fileno())
                metrics.JOURNAL_FSYNC.labels(self.model).observe(
                    time.perf_counter() - t0
                )
                self._last_fsync = now
        metrics.JOURNAL_RECORDS.labels(self.model, kind).inc()

    def admit(self, rid: str, feats: dict, klass: str, budget: int,
              stop=()) -> None:
        ids = np.asarray(feats.get("input_ids", []), np.int32)
        ser: dict = {"input_ids": [int(t) for t in ids.tolist()]}
        for key in _FEAT_KEYS:
            v = feats.get(key)
            if v is not None:
                ser[key] = (
                    float(v) if key in ("temperature", "top_p")
                    else str(v) if key in ("priority", "request_id")
                    else int(v)
                )
        stop = tuple(feats.get("stop_strs") or stop or ())
        with self._lock:
            self.streams[rid] = rs = RecoveredStream(
                rid, ser, klass, budget, stop=stop
            )
            rs.done = False
            self.tombs.pop(rid, None)  # the rid lives again
        self._append("admit", {
            "k": "admit", "rid": rid, "feats": ser, "klass": klass,
            "budget": int(budget), "stop": list(stop),
        })

    def tokens(self, rid: str, toks) -> None:
        lst = [int(t) for t in np.asarray(toks).reshape(-1).tolist()]
        if not lst:
            return
        with self._lock:
            rs = self.streams.get(rid)
            if rs is not None:
                rs.tokens.extend(lst)
        self._append("tokens", {"k": "tokens", "rid": rid, "t": lst})

    def checkpoint(self, rid: str) -> None:
        """Checkpoint-site marker (preemption, dry-pool reclaim,
        supervised recovery, evacuation): records the journal's own
        cumulative delivered-token cursor — the continuation point the
        resume will honor.  Informational at replay (the per-emission
        ``tokens`` records already carry the cursor), but it makes the
        journal a readable account of every resume."""
        with self._lock:
            rs = self.streams.get(rid)
            cursor = len(rs.tokens) if rs is not None else 0
        self._append(
            "checkpoint",
            {"k": "checkpoint", "rid": rid, "cursor": cursor},
        )

    def done(self, rid: str, outcome: str = "end") -> None:
        with self._lock:
            rs = self.streams.get(rid)
            if rs is None or rs.done:
                return
            rs.done = True
            rs.outcome = outcome
        self._append("done", {"k": "done", "rid": rid, "outcome": outcome})

    def result(self, rid: str, row) -> None:
        lst = [int(t) for t in np.asarray(row).reshape(-1).tolist()]
        with self._lock:
            self.results[rid] = lst
        self._append("result", {"k": "result", "rid": rid, "row": lst})

    def stats(self) -> dict:
        with self._lock:
            inc = sum(1 for r in self.streams.values() if not r.done)
            return {
                "dir": self.dir,
                "fsync": self.fsync,
                "records_written": self.records_written,
                "streams_tracked": len(self.streams),
                "streams_incomplete": inc,
                "results_kept": len(self.results),
                "tombstones": len(self.tombs),
                "torn_bytes_truncated": self.torn_bytes,
            }

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                try:
                    os.fsync(self._f.fileno())
                except OSError:
                    pass
                self._f.close()
            try:
                import fcntl

                fcntl.flock(self._lockfile, fcntl.LOCK_UN)
            except Exception:
                pass
            try:
                self._lockfile.close()
            except Exception:
                pass


# ---------------------------------------------------------------------------
# disk block tier (KV_DISK_BUDGET_MB) — the rung below host RAM


class DiskBlockPool:
    """Block storage on disk: the ``HostBlockPool`` layout (one buffer
    per KV pool leaf, ``jax.tree.leaves`` order) backed by memmap files
    under the journal dir instead of RAM.  Allocation bookkeeping rides
    the shared ``BlockPool`` free-list/refcount discipline; payloads
    attach lazily once the leaf shapes are known (the device pools must
    exist first)."""

    def __init__(self, num_blocks: int, block_bytes: int, dir: str):
        from ..engine.kv_blocks import BlockPool

        self.book = BlockPool(num_blocks, block_bytes)
        self.num_blocks = int(num_blocks)
        self.block_bytes = int(block_bytes)
        self.dir = dir
        self.leaves: list | None = None
        self.leaf_specs: list | None = None

    # BlockPool surface the SwapLedger drives (delegation, not
    # inheritance: attach-time wipes need to swap the book out).
    def alloc(self, n):
        return self.book.alloc(n)

    def free(self, ids):
        self.book.free(ids)

    def take(self, ids):
        self.book.take(ids)

    @property
    def free_blocks(self):
        return self.book.free_blocks

    @property
    def used_blocks(self):
        return self.book.used_blocks

    def attach(self, leaf_specs) -> None:
        os.makedirs(self.dir, exist_ok=True)
        leaves = []
        for i, (shape, dtype) in enumerate(leaf_specs):
            path = os.path.join(self.dir, f"leaf-{i}.dat")
            full = (self.num_blocks,) + tuple(int(s) for s in shape)
            nbytes = int(np.prod(full)) * np.dtype(dtype).itemsize
            mode = (
                "r+" if os.path.exists(path)
                and os.path.getsize(path) == nbytes else "w+"
            )
            leaves.append(np.memmap(path, dtype=dtype, mode=mode, shape=full))
        self.leaves = leaves
        self.leaf_specs = [
            (tuple(int(s) for s in shape), np.dtype(dtype).str)
            for shape, dtype in leaf_specs
        ]

    def write(self, ids: list[int], leaf_vals) -> None:
        idx = np.asarray(ids, np.int64)
        for buf, vals in zip(self.leaves, leaf_vals):
            buf[idx] = vals

    def read(self, ids: list[int]):
        idx = np.asarray(ids, np.int64)
        return [np.asarray(buf[idx]) for buf in self.leaves]

    def flush(self) -> None:
        if self.leaves:
            for buf in self.leaves:
                buf.flush()


def _json_key(key) -> list:
    """Disk-index serialization of an entry key: ``("stream", rid)`` or
    the prefix cache's ``(p_len, blake2b-bytes)``."""
    if isinstance(key, tuple) and len(key) == 2 and isinstance(key[1], bytes):
        return ["p", int(key[0]), key[1].hex()]
    return ["s", str(key[1]) if isinstance(key, tuple) else str(key)]


def _from_json_key(j):
    if not isinstance(j, list) or not j:
        return None
    if j[0] == "p" and len(j) == 3:
        return (int(j[1]), bytes.fromhex(j[2]))
    if j[0] == "s" and len(j) == 2:
        return ("stream", j[1])
    return None


# One tier object per directory per process: a second engine built
# over the same JOURNAL_DIR (fleet replica rebuilds, probe engines)
# must SHARE the tier, not open a second index handle — two writers
# compacting one index would orphan each other's appends.
_DISK_TIERS: dict[str, "KVDiskTier"] = {}
_DISK_TIERS_LOCK = threading.Lock()


def get_disk_tier(budget_mb: float, block_bytes: int,
                  dir: str) -> "KVDiskTier":
    """Process-level KVDiskTier registry: the first open of a dir
    constructs (and index-replays) the tier; later opens return the
    same object.  ``close()`` evicts, so a genuinely-new tier (tests'
    simulated restarts) rebuilds from disk."""
    key = os.path.realpath(dir)
    with _DISK_TIERS_LOCK:
        tier = _DISK_TIERS.get(key)
        if tier is not None:
            return tier
        tier = KVDiskTier(budget_mb, block_bytes, dir)
        tier._registry_key = key
        _DISK_TIERS[key] = tier
        return tier


class KVDiskTier:
    """The disk rung of the KV offload hierarchy (ChunkFlow's last
    tier): entries the host-RAM ledger evicts demote here, and stream
    checkpoints write through so a resume can outlive the process.
    Every lookup is keyed — ``("stream", rid)`` for checkpoint KV,
    the prefix cache's content-hash key for demoted prefixes — and the
    index log replays across restarts with the journal's torn-tail
    discipline.  Payload correctness across restarts is guarded by the
    persisted leaf-spec meta: a config change that alters the block
    layout wipes the tier instead of scattering garbage KV."""

    def __init__(self, budget_mb: float, block_bytes: int, dir: str):
        from ..engine.kv_blocks import SwapLedger

        self.budget_bytes = int(float(budget_mb) * 1e6)
        self.block_bytes = int(block_bytes)
        self.num_blocks = self.budget_bytes // max(1, self.block_bytes)
        self.dir = dir
        self.pool = DiskBlockPool(self.num_blocks, self.block_bytes, dir)
        self.ledger = SwapLedger(self.pool)
        self.ledger.on_release = self._index_del
        self._index_path = os.path.join(dir, "index.log")
        self._meta_specs = None
        self._lock = threading.RLock()
        self._index_f = None
        self.spills = 0
        self.promotes = 0
        os.makedirs(dir, exist_ok=True)
        self._load_index()

    @property
    def enabled(self) -> bool:
        return self.num_blocks > 0

    # -- index ---------------------------------------------------------

    def _load_index(self) -> None:
        frames, good = read_frames(self._index_path)
        sz = (
            os.path.getsize(self._index_path)
            if os.path.exists(self._index_path) else 0
        )
        if good < sz:
            log.warning("disk-tier index: torn tail truncated at replay")
        live: dict = {}
        meta_ok = True
        for payload in frames:
            try:
                rec = json.loads(payload)
            except Exception:
                continue
            op = rec.get("op")
            if op == "meta":
                if (
                    int(rec.get("block_bytes", -1)) != self.block_bytes
                    or int(rec.get("num_blocks", -1)) > self.num_blocks
                ):
                    meta_ok = False
                    break
                self._meta_specs = rec.get("leaf_specs")
            elif op == "put":
                key = _from_json_key(rec.get("key"))
                ids = [int(i) for i in rec.get("ids", [])]
                if key is None or any(i >= self.num_blocks for i in ids):
                    continue
                live[_tuple_key(key)] = (
                    key, ids, int(rec.get("tokens", 0)),
                    str(rec.get("kind", "stream")),
                )
            elif op == "del":
                key = _from_json_key(rec.get("key"))
                if key is not None:
                    live.pop(_tuple_key(key), None)
        if not meta_ok:
            self.wipe()
            live = {}
        # Rebuild the ledger from the net state, then compact-rewrite
        # the index so it never grows unbounded across restarts.
        self.ledger.on_release = None
        for key, ids, tokens, kind in live.values():
            try:
                self.ledger.restore(ids, tokens, kind, key)
            except Exception:
                log.exception("disk-tier index: unrestorable entry dropped")
        self.ledger.on_release = self._index_del
        self._index_f = open(self._index_path + ".new", "wb")
        self._index_meta()
        for key, ids, tokens, kind in live.values():
            append_frame(self._index_f, (json.dumps({
                "op": "put", "key": _json_key(key), "ids": ids,
                "tokens": tokens, "kind": kind,
            }) + "\n").encode())
        self._index_f.flush()
        os.fsync(self._index_f.fileno())
        self._index_f.close()
        os.replace(self._index_path + ".new", self._index_path)
        self._index_f = open(self._index_path, "ab")

    def _index_meta(self) -> None:
        append_frame(self._index_f, (json.dumps({
            "op": "meta", "block_bytes": self.block_bytes,
            "num_blocks": self.num_blocks, "leaf_specs": self._meta_specs,
        }) + "\n").encode())

    def _index_append(self, rec: dict) -> None:
        with self._lock:
            if self._index_f is None or self._index_f.closed:
                return
            append_frame(
                self._index_f, (json.dumps(rec) + "\n").encode()
            )
            self._index_f.flush()

    def _index_del(self, entry) -> None:
        if entry.key is not None:
            self._index_append({"op": "del", "key": _json_key(entry.key)})

    # -- storage -------------------------------------------------------

    def attach(self, leaf_specs) -> bool:
        """Open (or validate) the memmap payload files against the
        live pool leaf layout.  A layout mismatch against persisted
        entries wipes the tier — stale-config KV must never scatter
        into the device pools."""
        if not self.enabled:
            return False
        with self._lock:
            want = [
                (tuple(int(s) for s in shape), np.dtype(dtype).str)
                for shape, dtype in leaf_specs
            ]
            if self.pool.leaves is not None:
                return self.pool.leaf_specs == want
            if self._meta_specs is not None and [
                (tuple(s), d) for s, d in
                (tuple(e) for e in self._meta_specs)
            ] != want:
                log.warning(
                    "disk KV tier: leaf layout changed; wiping stale tier"
                )
                self.wipe()
            self.pool.attach(leaf_specs)
            if self._meta_specs is None:
                self._meta_specs = [
                    [list(shape), dtype] for shape, dtype in
                    self.pool.leaf_specs
                ]
                self._index_meta()
                self._index_f.flush()
            return True

    def wipe(self) -> None:
        from ..engine.kv_blocks import SwapLedger

        with self._lock:
            for name in list(os.listdir(self.dir)):
                if name.startswith("leaf-") or name.startswith("index.log"):
                    try:
                        os.unlink(os.path.join(self.dir, name))
                    except OSError:
                        pass
            self.pool = DiskBlockPool(
                self.num_blocks, self.block_bytes, self.dir
            )
            self.ledger = SwapLedger(self.pool)
            self.ledger.on_release = self._index_del
            self._meta_specs = None
            if self._index_f is not None and not self._index_f.closed:
                self._index_f.close()
            self._index_f = open(self._index_path, "ab")

    # -- entries -------------------------------------------------------

    def put(self, key, tokens: int, kind: str, leaf_vals):
        """Store one entry's blocks (superseding any older entry at the
        same key); None when the tier cannot hold it even after LRU
        eviction.  ``leaf_vals[i]`` is ``[n_blocks, block, ...]`` in
        pool-leaf order — exactly what ``HostBlockPool.read`` returns,
        so host→disk demotion is one call."""
        if self.pool.leaves is None:
            return None
        n = int(leaf_vals[0].shape[0]) if leaf_vals else 0
        with self._lock:
            old = self.ledger.get(key)
            if old is not None:
                self.ledger.release(old)
            entry = self.ledger.reserve(n, tokens, kind, key=key)
            if entry is None:
                return None
            try:
                self.pool.write(entry.ids, leaf_vals)
            except Exception:
                log.exception("disk KV tier: write failed")
                self.ledger.release(entry)
                return None
            entry.ready = True
            self._index_append({
                "op": "put", "key": _json_key(key), "ids": entry.ids,
                "tokens": int(tokens), "kind": kind,
            })
            self.spills += 1
        self._note_gauges()
        return entry

    def get(self, key):
        return self.ledger.get(key)

    def prefix_get(self, key):
        """Duck-typed ``KVHostTier.prefix_get`` so the prefix cache's
        ``host_lookup`` can probe the disk rung with the same call —
        but only once the payload files are attached (a metadata-only
        hit would promise KV this process cannot read yet)."""
        if self.pool.leaves is None:
            return None
        return self.ledger.get(key)

    def release(self, entry) -> None:
        self.ledger.release(entry)
        self._note_gauges()

    def release_key(self, key) -> None:
        e = self.ledger.get(key)
        if e is not None:
            self.ledger.release(e)
            self._note_gauges()

    def _note_gauges(self, model: str | None = None) -> None:
        m = model or getattr(self, "model", None) or "unknown"
        metrics.KV_DISK_POOL_BLOCKS.labels(m, "used").set(
            self.pool.used_blocks
        )
        metrics.KV_DISK_POOL_BLOCKS.labels(m, "free").set(
            self.pool.free_blocks
        )

    def stats(self) -> dict:
        base = {
            "budget_bytes": self.budget_bytes,
            "block_bytes": self.block_bytes,
            "num_blocks": self.num_blocks,
            "spills": self.spills,
            "promotes": self.promotes,
            "attached": self.pool.leaves is not None,
        }
        base.update(self.ledger.stats())
        return base

    def close(self) -> None:
        with self._lock:
            self.pool.flush()
            if self._index_f is not None and not self._index_f.closed:
                self._index_f.flush()
                self._index_f.close()
        key = getattr(self, "_registry_key", None)
        if key is not None:
            with _DISK_TIERS_LOCK:
                if _DISK_TIERS.get(key) is self:
                    del _DISK_TIERS[key]


def _tuple_key(key):
    return key if isinstance(key, tuple) else ("stream", str(key))


# ---------------------------------------------------------------------------
# reconnect registry (GET /v1/streams/{request_id})


class StreamRecord:
    """One resumed stream's reconnect state: journaled tokens + the
    live continuation, on the server's event loop."""

    def __init__(self, rid: str, tokens: list[int], max_tokens=None,
                 stop=()):
        self.rid = rid
        self.tokens = list(tokens)
        self.max_tokens = max_tokens
        self.stop = tuple(stop or ())
        self.done = False
        self.error: str | None = None
        self._waiters: list = []

    def _wake(self) -> None:
        for fut in self._waiters:
            if not fut.done():
                fut.set_result(None)
        self._waiters = []

    def extend(self, toks) -> None:
        self.tokens.extend(int(t) for t in np.asarray(toks).reshape(-1))
        self._wake()

    def complete(self) -> None:
        self.done = True
        self._wake()

    def fail(self, msg: str) -> None:
        self.error = msg
        self.done = True
        self._wake()

    async def wait_past(self, n: int) -> None:
        """Block until more than ``n`` tokens exist or the stream ends."""
        import asyncio

        while len(self.tokens) <= n and not self.done:
            fut = asyncio.get_running_loop().create_future()
            self._waiters.append(fut)
            if len(self.tokens) > n or self.done:
                fut.cancel()
                return
            await fut


class StreamRegistry:
    """rid → StreamRecord for every journal-resumed stream."""

    def __init__(self):
        self._records: dict[str, StreamRecord] = {}

    def add(self, rec: StreamRecord) -> StreamRecord:
        self._records[rec.rid] = rec
        return rec

    def get(self, rid: str) -> StreamRecord | None:
        return self._records.get(rid)

    def stats(self) -> dict:
        live = sum(1 for r in self._records.values() if not r.done)
        return {"streams": len(self._records), "live": live}
