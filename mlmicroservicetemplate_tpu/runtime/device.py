"""L0 device runtime: platform selection, device discovery, dtype policy.

Replaces the reference's ``torch.device`` / ``.to(device)`` layer
(SURVEY.md §1 L0): here device placement is owned by XLA — params are
materialized directly into device memory (HBM on TPU) with an explicit
sharding, and the ``DEVICE`` env contract (BASELINE.json:5) maps onto
``JAX_PLATFORMS``.

``apply_device_env`` MUST run before the first ``import jax`` anywhere in
the process; jax latches the platform at import time.
"""

from __future__ import annotations

import dataclasses
import os


def enable_compilation_cache(device: str,
                             cache_dir: str | None = None) -> str | None:
    """Persistent XLA compilation cache: restarts reuse compiled
    executables instead of re-paying warmup (52–487 s per model through
    the remote-compile relay, BASELINE.md warmup table).  This is the
    bottom rung of the compile-cache hierarchy (docs/compilation.md):
    jit's per-wrapper cache and the process-level ExecutableCache
    (runtime/compile_cache.py) sit above it and cover in-process reuse;
    this disk cache is what carries compiles ACROSS processes.

    Default ON for DEVICE=tpu at ``~/.cache/mlmst-xla-cache``;
    ``cache_dir`` (the ``COMPILE_CACHE_DIR`` ServiceConfig knob —
    utils/config.py, validated and README-documented under the
    knob-drift rule) overrides, ``"0"``/``"off"``/empty disables.
    ``cache_dir=None`` falls back to the raw ``COMPILE_CACHE_DIR`` env
    var for pre-config callers (benchmarks).  Returns the active dir
    (None = disabled).  CPU compiles are fast and golden tests want
    cold compiles, so CPU stays off unless a dir is given explicitly.
    """
    env = cache_dir if cache_dir is not None \
        else os.environ.get("COMPILE_CACHE_DIR")
    if env is not None and env.strip().lower() in ("", "0", "false", "no", "off"):
        return None
    if env:
        cache_dir = env
    elif device == "tpu":
        cache_dir = os.path.expanduser("~/.cache/mlmst-xla-cache")
    else:
        return None
    import jax

    os.makedirs(cache_dir, exist_ok=True)
    # jax latches the no-dir decision at its FIRST compile; a process
    # that already compiled something (benchmark harnesses, tests)
    # would silently ignore the dir without this reset.
    try:  # internal seam; absence just means nothing was latched
        from jax._src import compilation_cache as _jax_cc

        _jax_cc.reset_cache()
    except Exception:
        pass
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # Cache everything the warmup compiles, not just slow ones: through
    # the relay even "fast" compiles cost seconds of round-trips.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return cache_dir


def tune_table_default(cache_dir: str | None) -> str | None:
    """Default location for the Pallas kernel tuning table
    (ops/autotune.py): alongside the persistent XLA disk cache when the
    operator configured one, so the tuned-variant choices and the
    executables they select survive restarts TOGETHER — a table entry
    whose executable is also disk-cached costs a restart zero compiles
    (docs/kernel_tuning.md).  No cache dir -> no persistence (None):
    the sweep re-runs per process, which is the correct default for
    tests and CPU golden runs that want cold, hermetic state.
    """
    if not cache_dir or cache_dir.strip().lower() in (
            "", "0", "false", "no", "off"):
        return None
    return os.path.join(cache_dir, "pallas_tune.json")


def apply_device_env(device: str, compile_cache_dir: str | None = None
                     ) -> None:
    """Map DEVICE=tpu|cpu onto JAX_PLATFORMS before jax is imported.

    tpu: leave platform selection to the environment (PJRT TPU plugin
    auto-registers; a broken TPU init should raise, not silently fall
    back to CPU). cpu: force the CPU backend.

    Also enables the persistent compilation cache (see
    ``enable_compilation_cache``; ``compile_cache_dir`` is the
    ServiceConfig knob, None = env-var fallback).
    """
    enable_compilation_cache(device, compile_cache_dir)
    if device != "cpu":
        return
    os.environ["JAX_PLATFORMS"] = "cpu"
    # jax is typically pre-imported by the environment's sitecustomize
    # with JAX_PLATFORMS=tpu/axon, so the env var alone is too late —
    # flip the config too.  The backend initializes lazily, so this
    # works any time before the first device use; afterwards we can only
    # verify.
    import jax

    jax.config.update("jax_platforms", "cpu")
    # XLA CPU's default conv/matmul precision is reduced; CPU serving
    # is a correctness path, so buy back real f32 math.
    jax.config.update("jax_default_matmul_precision", "highest")
    plat = jax.default_backend()
    if plat != "cpu":
        raise RuntimeError(
            f"DEVICE=cpu requested but jax already initialized on {plat!r}; "
            "set JAX_PLATFORMS=cpu before starting the process"
        )


def get_devices():
    """All accelerator devices visible to this process, in stable order."""
    import jax

    return jax.devices()


@dataclasses.dataclass(frozen=True)
class DtypePolicy:
    """Mixed-precision policy tuned for the TPU MXU.

    bf16 params + bf16 compute keeps matmuls/convs on the MXU fast path
    and halves HBM traffic; logits/softmax come back in f32 so
    postprocessing (argmax, label probabilities, sampling) is exact.
    """

    param_dtype: str = "bfloat16"
    compute_dtype: str = "bfloat16"
    output_dtype: str = "float32"

    @property
    def param_jnp(self):
        import jax.numpy as jnp

        return jnp.dtype(self.param_dtype)

    @property
    def compute_jnp(self):
        import jax.numpy as jnp

        return jnp.dtype(self.compute_dtype)

    @property
    def output_jnp(self):
        import jax.numpy as jnp

        return jnp.dtype(self.output_dtype)


def default_policy(device: str = "tpu") -> DtypePolicy:
    """bf16 on TPU; f32 on CPU (CPU bf16 is slow and golden tests want
    bit-comparable f32 math)."""
    if device == "cpu":
        return DtypePolicy("float32", "float32", "float32")
    return DtypePolicy()
