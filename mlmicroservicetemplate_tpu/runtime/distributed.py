"""Multi-host runtime initialization (the DCN story).

Single-host serving needs nothing from this module: a v5e-8's eight
chips share one host and ICI, and the mesh code (``parallel/``) already
spans them.  On MULTI-host topologies (v5e-16+, pods), JAX processes
must rendezvous before any device use so the global device list covers
every host and XLA can emit DCN collectives between ICI islands — the
TPU-native answer to the reference stack's multi-node NCCL/MPI
bootstrap (SURVEY.md §5 "Distributed communication backend"), with the
same division of labor: this module only BOOTSTRAPS; the collectives
themselves are compiled by XLA, never hand-written.

Env contract (standard jax.distributed args, all-or-nothing):
  JAX_COORDINATOR      host:port of process 0 (e.g. "10.0.0.2:8476")
  JAX_NUM_PROCESSES    total process count
  JAX_PROCESS_ID       this process's index [0, NUM_PROCESSES)

Unset ⇒ single-host, no-op.  ``serve.build_service`` calls this before
the platform probe; meshes built afterwards see ``jax.devices()``
spanning all hosts, and ``parallel/``'s NamedShardings lay axes out so
collectives ride ICI within a host and DCN only across (device order
groups by process).

Scope: this bootstraps the RUNTIME (cross-host meshes for the
train-step/collective machinery).  The HTTP serving data path stays
single-controller — ``ReplicaSet.place_batch`` refuses multi-process
placement loudly — so pods serve as one process per host with
``REPLICAS`` over the local chips.
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger(__name__)

_ENV = ("JAX_COORDINATOR", "JAX_NUM_PROCESSES", "JAX_PROCESS_ID")


def broadcast_params(donor_params, replicas):
    """λScale-style scale-up param placement (arXiv 2502.09922): place
    a NEW replica's params from a live donor engine's already-placed
    device arrays instead of re-uploading the checkpoint pytree from
    host memory.  Returns ``(placed_params, moved_bytes)``.

    ``donor_params`` leaves are committed jax.Arrays (immutable), so:

    - same devices / same sharding (single-device fleet replicas
      sharing one placement): ``device_put`` aliases — the spawn pays
      ZERO param bytes, host or wire, and ``moved_bytes`` is 0 (the
      engine reports ``params_source="donor-alias"``);
    - different devices (per-replica device assignment — multi-chip
      fleets): ``device_put`` of a device-resident array moves it
      device→device over ICI, compiled by the runtime — never back
      through the host, never through a checkpoint read.
      ``moved_bytes`` counts the destination bytes of every leaf whose
      device set actually changed (``params_source="donor-ici"``,
      ``fleet_param_broadcast_bytes_total``).

    The byte count compares SOURCE vs DESTINATION device sets per leaf
    rather than trusting object identity: ``device_put`` may return a
    fresh Array object even when it aliased the donor's buffers, so
    identity would over-report.  Leaves without a ``devices()`` (host
    arrays in duck-typed tests) count as not-moved — honest negative.

    This is the seam the multi-host story extends (one broadcast
    collective over DCN instead of per-host checkpoint reads).
    Routing through ``replicas.place_params`` keeps every placement
    flavor (replicated, tensor-parallel spec trees) correct without
    duplicating the sharding logic here.
    """
    placed = replicas.place_params(donor_params)
    moved = 0
    try:
        import jax

        for src, dst in zip(
            jax.tree.leaves(donor_params), jax.tree.leaves(placed)
        ):
            try:
                if src.devices() != dst.devices():
                    moved += int(dst.nbytes)
            except Exception:
                continue
    except Exception:
        pass
    return placed, moved


def maybe_init_distributed(env: dict | None = None) -> bool:
    """Rendezvous this process into a multi-host JAX runtime when the
    JAX_COORDINATOR/… env trio is set; no-op (False) otherwise.

    MUST run before the first device use (same latch as platform
    selection — runtime.device.apply_device_env).  Raises on a partial
    env (a half-configured pod must not silently serve single-host).
    """
    e = env if env is not None else os.environ
    present = [k for k in _ENV if e.get(k)]
    if not present:
        return False
    missing = [k for k in _ENV if not e.get(k)]
    if missing:
        raise ValueError(
            f"multi-host init needs all of {_ENV}; set {present} but not "
            f"{missing} — a partially configured pod must fail loudly, not "
            "serve single-host"
        )
    coordinator = e["JAX_COORDINATOR"]
    num = int(e["JAX_NUM_PROCESSES"])
    pid = int(e["JAX_PROCESS_ID"])
    if not (0 <= pid < num):
        raise ValueError(f"JAX_PROCESS_ID={pid} outside [0, {num})")
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator, num_processes=num, process_id=pid
    )
    log.info(
        "multi-host runtime up: process %d/%d via %s (%d global devices)",
        pid, num, coordinator, len(jax.devices()),
    )
    return True
