"""Offline checkpoint-conversion CLI: HF/torch weights → servable pytree.

The operator-facing half of the reference's ``ModelWrapper.load()``
contract (BASELINE.json:5): run once offline, point the service at the
output with ``MODEL_PATH``, and the server materializes params straight
into device memory with no torch anywhere on its import path.

    python -m mlmicroservicetemplate_tpu.convert \
        --model bert-base --input pytorch_model.bin --output /ckpt/bert

Input formats: .safetensors / .npz (no torch needed), .bin/.pt/.pth
(torch, CPU only).  Output: an orbax checkpoint directory, which
``load_pytree`` restores directly (warm starts skip conversion).
"""

from __future__ import annotations

import argparse
import sys

CONVERTERS = {
    "resnet50": "resnet_state_to_pytree",
    "bert-base": "bert_state_to_pytree",
    "t5-small": "t5_state_to_pytree",
    "gpt2": "gpt2_state_to_pytree",
    "llama": "llama_state_to_pytree",
}


def main(argv: list[str] | None = None) -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--model", required=True, choices=sorted(CONVERTERS))
    p.add_argument("--input", required=True, help="state-dict file (.safetensors/.npz/.bin/.pt)")
    p.add_argument("--output", required=True, help="orbax checkpoint directory")
    p.add_argument(
        "--num-layers", type=int, default=None,
        help="override transformer layer count (default: the model's standard depth)",
    )
    args = p.parse_args(argv)

    from ..models.checkpoint import load_state_dict, save_pytree
    from . import hf_maps

    convert = getattr(hf_maps, CONVERTERS[args.model])
    state = load_state_dict(args.input)
    kwargs = {}
    if args.num_layers is not None:
        if args.model == "resnet50":
            p.error("--num-layers applies to transformer models, not resnet50")
        kwargs["n_layers"] = args.num_layers
    pytree = convert(state, **kwargs)

    save_pytree(args.output, pytree)
    print(f"converted {args.input} -> {args.output}", file=sys.stderr)


if __name__ == "__main__":
    main()
