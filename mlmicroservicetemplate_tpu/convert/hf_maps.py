"""Name/layout maps from HuggingFace state dicts to our param pytrees.

Input is always ``{name: numpy.ndarray}`` (call ``.numpy()`` on torch
tensors before passing, or load a safetensors file directly), output is
a nested-dict pytree matching ``models/{resnet,bert,t5}.init_params``.

Layout conversions performed here (SURVEY.md §7.4.5 — the classic
torch↔JAX pitfalls):
- conv kernels OIHW → HWIO (transpose 2,3,1,0)
- linear weights [out, in] → [in, out] (transpose)
- embeddings and norm vectors pass through unchanged
"""

from __future__ import annotations

import numpy as np

Array = np.ndarray
State = dict[str, Array]


def _conv(w: Array) -> Array:
    return np.ascontiguousarray(np.transpose(w, (2, 3, 1, 0)))


def _lin(w: Array) -> Array:
    return np.ascontiguousarray(np.transpose(w, (1, 0)))


def _bn(state: State, prefix: str) -> dict:
    return {
        "scale": state[f"{prefix}.weight"],
        "bias": state[f"{prefix}.bias"],
        "mean": state[f"{prefix}.running_mean"],
        "var": state[f"{prefix}.running_var"],
    }


# ---------------------------------------------------------------------------
# ResNet (HF ResNetForImageClassification)


def resnet_state_to_pytree(state: State, depths=(3, 4, 6, 3)) -> dict:
    p: dict = {
        "embedder": {
            "conv": {"kernel": _conv(state["resnet.embedder.embedder.convolution.weight"])},
            "bn": _bn(state, "resnet.embedder.embedder.normalization"),
        }
    }
    stages = []
    for si, depth in enumerate(depths):
        blocks = []
        for bi in range(depth):
            base = f"resnet.encoder.stages.{si}.layers.{bi}"
            block: dict = {}
            if f"{base}.shortcut.convolution.weight" in state:
                block["shortcut"] = {
                    "conv": {"kernel": _conv(state[f"{base}.shortcut.convolution.weight"])},
                    "bn": _bn(state, f"{base}.shortcut.normalization"),
                }
            for li, (cname, bname) in enumerate(
                [("conv1", "bn1"), ("conv2", "bn2"), ("conv3", "bn3")]
            ):
                block[cname] = {"kernel": _conv(state[f"{base}.layer.{li}.convolution.weight"])}
                block[bname] = _bn(state, f"{base}.layer.{li}.normalization")
            blocks.append(block)
        stages.append(blocks)
    p["stages"] = stages
    p["classifier"] = {
        "kernel": _lin(state["classifier.1.weight"]),
        "bias": state["classifier.1.bias"],
    }
    return p


# ---------------------------------------------------------------------------
# BERT (HF BertForSequenceClassification)


def bert_state_to_pytree(state: State, n_layers: int = 12) -> dict:
    def ln(prefix: str) -> dict:
        return {"scale": state[f"{prefix}.weight"], "bias": state[f"{prefix}.bias"]}

    def lin(prefix: str) -> dict:
        return {"kernel": _lin(state[f"{prefix}.weight"]), "bias": state[f"{prefix}.bias"]}

    p: dict = {
        "embeddings": {
            "word": {"embedding": state["bert.embeddings.word_embeddings.weight"]},
            "position": {"embedding": state["bert.embeddings.position_embeddings.weight"]},
            "token_type": {"embedding": state["bert.embeddings.token_type_embeddings.weight"]},
            "ln": ln("bert.embeddings.LayerNorm"),
        },
        "layers": [],
    }
    for i in range(n_layers):
        base = f"bert.encoder.layer.{i}"
        p["layers"].append(
            {
                "attn": {
                    "q": lin(f"{base}.attention.self.query"),
                    "k": lin(f"{base}.attention.self.key"),
                    "v": lin(f"{base}.attention.self.value"),
                    "out": lin(f"{base}.attention.output.dense"),
                    "ln": ln(f"{base}.attention.output.LayerNorm"),
                },
                "mlp": {
                    "up": lin(f"{base}.intermediate.dense"),
                    "down": lin(f"{base}.output.dense"),
                    "ln": ln(f"{base}.output.LayerNorm"),
                },
            }
        )
    if "bert.pooler.dense.weight" in state:
        p["pooler"] = lin("bert.pooler.dense")
    if "classifier.weight" in state:
        p["classifier"] = lin("classifier")
    return p


# ---------------------------------------------------------------------------
# T5 (HF T5ForConditionalGeneration)


def t5_state_to_pytree(state: State, n_layers: int = 6) -> dict:
    def rms(prefix: str) -> dict:
        return {"scale": state[f"{prefix}.weight"]}

    def lin(prefix: str) -> dict:
        # T5 linears have no bias.
        return {"kernel": _lin(state[f"{prefix}.weight"])}

    def attn(base: str, cross: bool = False) -> dict:
        d = {
            "q": lin(f"{base}.q"),
            "k": lin(f"{base}.k"),
            "v": lin(f"{base}.v"),
            "out": lin(f"{base}.o"),
        }
        rp = f"{base}.relative_attention_bias.weight"
        if rp in state:
            d["rel_bias"] = {"embedding": state[rp]}
        return d

    p: dict = {
        "shared": {"embedding": state["shared.weight"]},
        "encoder": {"layers": [], "final_ln": rms("encoder.final_layer_norm")},
        "decoder": {"layers": [], "final_ln": rms("decoder.final_layer_norm")},
    }
    for i in range(n_layers):
        b = f"encoder.block.{i}.layer"
        p["encoder"]["layers"].append(
            {
                "attn": attn(f"{b}.0.SelfAttention"),
                "attn_ln": rms(f"{b}.0.layer_norm"),
                "mlp": {
                    "wi": lin(f"{b}.1.DenseReluDense.wi"),
                    "wo": lin(f"{b}.1.DenseReluDense.wo"),
                },
                "mlp_ln": rms(f"{b}.1.layer_norm"),
            }
        )
    for i in range(n_layers):
        b = f"decoder.block.{i}.layer"
        p["decoder"]["layers"].append(
            {
                "self_attn": attn(f"{b}.0.SelfAttention"),
                "self_attn_ln": rms(f"{b}.0.layer_norm"),
                "cross_attn": attn(f"{b}.1.EncDecAttention", cross=True),
                "cross_attn_ln": rms(f"{b}.1.layer_norm"),
                "mlp": {
                    "wi": lin(f"{b}.2.DenseReluDense.wi"),
                    "wo": lin(f"{b}.2.DenseReluDense.wo"),
                },
                "mlp_ln": rms(f"{b}.2.layer_norm"),
            }
        )
    if "lm_head.weight" in state:
        p["lm_head"] = {"kernel": _lin(state["lm_head.weight"])}
    return p


# ---------------------------------------------------------------------------
# GPT-2 (HF GPT2LMHeadModel)


def gpt2_state_to_pytree(state: State, n_layers: int = 12) -> dict:
    """HF ``transformer.*`` names → ``models/gpt.init_params`` layout.

    GPT-2's linear layers are HF ``Conv1D`` modules whose weights are
    already stored [in, out] — the one transformer family where NO
    transpose is needed (unlike nn.Linear's [out, in]).
    """

    def ln(prefix: str) -> dict:
        return {"scale": state[f"{prefix}.weight"], "bias": state[f"{prefix}.bias"]}

    def conv1d(prefix: str) -> dict:
        return {"kernel": state[f"{prefix}.weight"], "bias": state[f"{prefix}.bias"]}

    p: dict = {
        "wte": {"embedding": state["transformer.wte.weight"]},
        "wpe": {"embedding": state["transformer.wpe.weight"]},
        "layers": [],
        "final_ln": ln("transformer.ln_f"),
    }
    for i in range(n_layers):
        b = f"transformer.h.{i}"
        p["layers"].append(
            {
                "ln1": ln(f"{b}.ln_1"),
                "attn": {
                    "qkv": conv1d(f"{b}.attn.c_attn"),
                    "out": conv1d(f"{b}.attn.c_proj"),
                },
                "ln2": ln(f"{b}.ln_2"),
                "mlp": {
                    "up": conv1d(f"{b}.mlp.c_fc"),
                    "down": conv1d(f"{b}.mlp.c_proj"),
                },
            }
        )
    return p


def llama_state_to_pytree(state: State, n_layers: int | None = None) -> dict:
    """HF Llama-family names → ``models/llama.init_params`` layout.

    All projections are ``nn.Linear`` ([out, in] → transpose); norms are
    RMSNorm weight vectors; ``lm_head.weight`` [V, D] transposes to the
    untied [D, V] kernel.  Tied-embedding checkpoints (no ``lm_head``
    key) fall back to the embedding table transposed.
    """
    if n_layers is None:
        n_layers = 1 + max(
            int(k.split(".")[2])
            for k in state
            if k.startswith("model.layers.")
        )

    def lin(prefix: str) -> dict:
        return {"kernel": _lin(state[f"{prefix}.weight"])}

    embed_w = state["model.embed_tokens.weight"]
    head = state.get("lm_head.weight", embed_w)
    p: dict = {
        "embed": {"embedding": embed_w},
        "layers": [],
        "final_ln": {"scale": state["model.norm.weight"]},
        "lm_head": {"kernel": _lin(head)},
    }
    for i in range(n_layers):
        b = f"model.layers.{i}"
        p["layers"].append(
            {
                "attn_ln": {"scale": state[f"{b}.input_layernorm.weight"]},
                "attn": {
                    "q": lin(f"{b}.self_attn.q_proj"),
                    "k": lin(f"{b}.self_attn.k_proj"),
                    "v": lin(f"{b}.self_attn.v_proj"),
                    "o": lin(f"{b}.self_attn.o_proj"),
                },
                "mlp_ln": {"scale": state[f"{b}.post_attention_layernorm.weight"]},
                "mlp": {
                    "gate": lin(f"{b}.mlp.gate_proj"),
                    "up": lin(f"{b}.mlp.up_proj"),
                    "down": lin(f"{b}.mlp.down_proj"),
                },
            }
        )
    return p
