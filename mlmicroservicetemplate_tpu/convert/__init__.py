"""Offline checkpoint conversion: HF/torch state dicts → JAX pytrees.

The ONLY place in the framework allowed to touch torch (and even here it
is optional: the mapping functions operate on ``{name: numpy array}``
dicts, so safetensors files convert with no torch at all).
Parity target: ``ModelWrapper.load()`` materializing pretrained
checkpoints onto the device (BASELINE.json:5) — here the pytree is
materialized straight into HBM by the runtime with a chosen sharding.
"""

from .hf_maps import (
    bert_state_to_pytree,
    gpt2_state_to_pytree,
    llama_state_to_pytree,
    resnet_state_to_pytree,
    t5_state_to_pytree,
)

__all__ = [
    "bert_state_to_pytree",
    "gpt2_state_to_pytree",
    "llama_state_to_pytree",
    "resnet_state_to_pytree",
    "t5_state_to_pytree",
]
