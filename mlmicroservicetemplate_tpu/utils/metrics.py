"""Prometheus observability (SURVEY.md §5 "Metrics / logging").

The reference template's only introspection is its ``/status`` endpoint
and access logs; this module is the deliberate upgrade: request
count/latency histograms, batch-size distribution (the lever behind
req/s/chip), queue depth, and generated-token throughput, all exported
at ``GET /metrics``.

Kept import-safe without prometheus_client (stub fallback) so the core
serving path never gains a hard dependency.
"""

from __future__ import annotations

import os

try:
    from prometheus_client import (
        CONTENT_TYPE_LATEST,
        Counter,
        Gauge,
        Histogram,
        generate_latest,
    )

    HAVE_PROM = True
except Exception:  # pragma: no cover - prometheus_client is installed here
    HAVE_PROM = False
    CONTENT_TYPE_LATEST = "text/plain"

    class _Noop:
        def labels(self, *a, **k):
            return self

        def inc(self, *a, **k):
            pass

        def observe(self, *a, **k):
            pass

        def set(self, *a, **k):
            pass

    def Counter(*a, **k):  # noqa: N802
        return _Noop()

    Gauge = Histogram = Counter

    def generate_latest():
        return b"# prometheus_client not installed\n"


# Latency histogram buckets (r20, the r11 honest negative closed):
# the defaults extend past 10 s — on the 1-vCPU CI box,
# stream_ttft/tbt p99 saturated the old 10 s top bucket and
# hist_pctile could only report "≥ 10 s".  The LATENCY_BUCKETS env
# knob overrides the whole set (comma-separated ascending seconds,
# validated strictly in ServiceConfig; parsed leniently here because
# metrics imports before config validation and a bad env var must
# not break `import metrics` for a test process).
_DEFAULT_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    5.0, 10.0, 30.0, 60.0, 120.0,
)


def parse_buckets(spec: str | None) -> tuple[float, ...] | None:
    """Comma-separated ascending positive bucket edges, or None when
    unset/invalid (callers fall back to the defaults; ServiceConfig's
    validator is the strict gate that rejects garbage at boot)."""
    if not spec:
        return None
    try:
        vals = tuple(float(x) for x in spec.split(",") if x.strip())
    except ValueError:
        return None
    if not vals or any(v <= 0 for v in vals) or list(vals) != sorted(
        set(vals)
    ):
        return None
    return vals


_LATENCY_BUCKETS = (
    parse_buckets(os.environ.get("LATENCY_BUCKETS"))
    or _DEFAULT_LATENCY_BUCKETS
)

REQUESTS = Counter(
    "predict_requests_total", "Completed /predict requests", ["model", "status"]
)
LATENCY = Histogram(
    "predict_latency_seconds", "End-to-end /predict latency", ["model"],
    buckets=_LATENCY_BUCKETS,
)
QUEUE_WAIT = Histogram(
    "batch_queue_wait_seconds", "Time a request waits in the batching queue",
    ["model"], buckets=_LATENCY_BUCKETS,
)
DEVICE_TIME = Histogram(
    "device_batch_seconds", "Device time per dispatched batch", ["model"],
    buckets=_LATENCY_BUCKETS,
)
BATCH_SIZE = Histogram(
    "batch_size", "Items per dispatched batch", ["model"],
    buckets=(1, 2, 4, 8, 16, 32, 64),
)
QUEUE_DEPTH = Gauge("batch_queue_depth", "Requests currently queued", ["model"])
TOKENS = Counter("generated_tokens_total", "Seq2seq tokens generated", ["model"])
STREAM_BATCH = Histogram(
    "stream_batch_size",
    "Live streams served per continuous-batching chunk dispatch",
    ["model"], buckets=(1, 2, 4, 8, 16, 32),
)
DECODE_STEPS = Histogram(
    "seq2seq_decode_steps",
    "Decode steps executed per non-streaming seq2seq dispatch "
    "(< max_decode_len when the whole batch hit EOS early)",
    ["model"], buckets=(4, 8, 16, 32, 64, 128, 256),
)
SPEC_EMITTED = Histogram(
    "spec_tokens_per_verify_step",
    "Speculative decoding: tokens emitted per verify step (1.0 = no "
    "draft accepted; the acceptance-rate observability surface)",
    ["model"], buckets=(1.0, 1.5, 2.0, 3.0, 4.0, 6.0, 9.0),
)
SHED = Counter(
    "requests_shed_total",
    "Load-shed requests by reason "
    "(queue_full | deadline | kv_budget | drain | degraded | "
    "fleet_down | quota | adapter_pool)",
    ["model", "reason"],
)
TTFT = Histogram(
    "stream_ttft_seconds",
    "Streaming time-to-first-token-chunk (submit to first event), by "
    "admission mode (chunked = PREFILL_CHUNK windows, monolithic = "
    "one fused prefill dispatch)",
    ["model", "mode"], buckets=_LATENCY_BUCKETS,
)
PREFILL_CHUNKS = Counter(
    "prefill_chunks_total",
    "Prompt windows dispatched by chunked prefill (PREFILL_CHUNK)",
    ["model"],
)
PREFILL_STALL = Counter(
    "prefill_stall_seconds",
    "Host time spent dispatching prefill windows while decode streams "
    "were live — the decode-cadence delay chunked prefill bounds to "
    "one window (device-side serialization rides behind the decode "
    "dispatch, so this is the interleaving overhead, not a full stall)",
    ["model"],
)
PREFILL_BACKLOG = Gauge(
    "prefill_backlog_tokens",
    "Prompt tokens admitted but not yet prefilled (chunked backlog)",
    ["model"],
)
CLASS_QUEUE_DEPTH = Gauge(
    "sched_class_queue_depth",
    "Requests waiting in the deadline queue, by queue and priority class",
    ["model", "queue", "klass"],
)
PREEMPTIONS = Counter(
    "stream_preemptions_total",
    "Batch-class streams checkpointed and re-queued to admit "
    "interactive work",
    ["model"],
)
KV_COMMITTED = Gauge(
    "kv_committed_bytes",
    "KV-cache bytes currently committed against the admission budget, "
    "per fleet replica (replica 0 = the single-engine path)",
    ["model", "replica"],
)
KV_POOL_BLOCKS = Gauge(
    "kv_pool_blocks",
    "Paged-KV pool blocks by state (used includes prefix-cache pins), "
    "per fleet replica",
    ["model", "replica", "state"],
)
ENGINE_RESTARTS = Counter(
    "engine_restarts_total",
    "Supervised engine rebuilds after a fatal dispatch fault or decode "
    "loop death (streams checkpoint and resume token-identically)",
    ["model"],
)
DISPATCH_RETRIES = Counter(
    "dispatch_retries_total",
    "Transient dispatch failures retried under the watchdog, by "
    "exception type",
    ["model", "reason"],
)
DISPATCH_TIMEOUTS = Counter(
    "dispatch_timeouts_total",
    "Dispatches cut off by the DISPATCH_TIMEOUT_S watchdog deadline",
    ["model"],
)
STREAMS_RECOVERED = Counter(
    "streams_recovered_total",
    "Live streams checkpointed and resumed token-identically, by "
    "replica and cause (restart = same-engine rebuild, failover = "
    "re-routed to a healthy fleet replica)",
    ["model", "replica", "cause"],
)
STREAMS_LOST = Counter(
    "streams_lost_total",
    "Live streams error-terminated by an unrecoverable engine fault, "
    "by replica and cause (fault = no supervisor or budget spent, "
    "no_replica = every fleet replica was dead at failover)",
    ["model", "replica", "cause"],
)
FLEET_FAILOVERS = Counter(
    "fleet_failovers_total",
    "Replica evacuations: a replica died (restart budget spent, loop "
    "death, or breaker open past FLEET_EVICT_S) and its streams were "
    "re-routed for token-identical resume",
    ["model", "replica", "cause"],
)
FLEET_REPLICAS = Gauge(
    "fleet_replicas",
    "Fleet members by state: live (healthy-or-breaker-open, routable "
    "pool), draining (scale-down in progress — finishing or evacuating "
    "its streams), evicted (dead, awaiting rejoin), spawning (being "
    "built/warmed/probed; not yet admitted to routing)",
    ["model", "state"],
)
FLEET_SCALE_EVENTS = Counter(
    "fleet_scale_events_total",
    "Completed fleet scale events by direction and cause (up: queue | "
    "kv | ttft | slo | min | rejoin | manual, spawn_failed when the "
    "warm probe died, no_devices when no free device group could seat "
    "the spawn; down: idle | manual)",
    ["model", "dir", "cause"],
)
FLEET_SCALE_DURATION = Histogram(
    "fleet_scale_duration_seconds",
    "Wall time one scale event took (up: engine build + donor param "
    "broadcast + warm compile + probe dispatch; down: drain-or-"
    "evacuate + retire)",
    ["model", "dir"],
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0),
)
FLEET_BREAKER = Gauge(
    "fleet_breaker_state",
    "Per-replica circuit breaker state: 0=closed (healthy), "
    "1=half-open (probing), 2=open (routing avoids it), 3=dead "
    "(evicted; streams failed over)",
    ["model", "replica"],
)
FLEET_PARAM_BROADCAST = Counter(
    "fleet_param_broadcast_bytes_total",
    "Real param bytes moved device-to-device by donor broadcasts at "
    "spawn (params_source=donor-ici). Same-placement spawns alias the "
    "donor's arrays and add ZERO here — the honest-transport ledger "
    "for multi-chip scale-up (docs/autoscaling.md)",
    ["model"],
)
FLEET_REPLICA_DEVICES = Gauge(
    "fleet_replica_devices",
    "Devices owned by each fleet replica's placement (TP group width; "
    "1 for single-device replicas; 0 once the replica is dead and its "
    "devices are released or retired)",
    ["model", "replica"],
)
CHAIN_DEPTH = Gauge(
    "stream_chain_depth",
    "Chunk-chain pipelining depth the continuous decode loop runs at "
    "(STREAM_PIPELINE; auto-tuned at warmup from measured dispatch RTT "
    "vs chunk compute when 0)",
    ["model"],
)
DECODE_WINDOW_CHUNKS = Histogram(
    "decode_window_chunks",
    "Decode chunks fused per window dispatch (DECODE_WINDOW; 1 = the "
    "unfused per-chunk path) — host syncs per token scale inversely "
    "with this",
    ["model"], buckets=(1, 2, 4, 8, 16, 32, 64),
)
WINDOW_EARLY_EXITS = Counter(
    "decode_window_early_exits_total",
    "Fused decode windows that exited on-device before their chunk cap "
    "because every live row hit EOS",
    ["model"],
)
KV_HOST_POOL_BLOCKS = Gauge(
    "kv_host_pool_blocks",
    "Host-RAM KV tier blocks by state (KV_HOST_BUDGET_MB; used = "
    "swapped-out stream checkpoints + demoted prefix entries)",
    ["model", "state"],
)
KV_SWAP_BYTES = Counter(
    "kv_swap_bytes_total",
    "KV bytes moved across the device/host tier boundary, by direction "
    "(out = checkpoint swap-out + prefix demotion, in = resume "
    "prefetch + prefix promotion)",
    ["model", "dir"],
)
KV_SWAP_RESUMES = Counter(
    "kv_swap_resumes_total",
    "Checkpointed-stream resumes by outcome: swapped = KV prefetched "
    "from the host tier (zero re-prefill), fallback = host copy "
    "missing/evicted/foreign so the stream re-prefilled (recast or "
    "replay)",
    ["model", "outcome"],
)
KV_HOST_PREFIX_HITS = Counter(
    "kv_host_prefix_hits_total",
    "Prefix-cache matches served from the host tier: the entry was "
    "demoted under device-budget pressure and promoted back on match",
    ["model"],
)
JOURNAL_RECORDS = Counter(
    "journal_records_total",
    "Write-ahead stream-journal records appended, by kind (admit = "
    "stream admission, tokens = delivered-token cursor delta, done = "
    "terminal, result = unary /predict completion for X-Request-Id "
    "dedup)",
    ["model", "kind"],
)
JOURNAL_REPLAY = Counter(
    "journal_replay_streams_total",
    "Journaled streams processed at startup replay, by outcome "
    "(resumed = re-admitted for token-identical continuation, "
    "complete = already finished before the crash, failed = could not "
    "re-admit)",
    ["model", "outcome"],
)
JOBS_ACTIVE = Gauge(
    "jobs_active",
    "Bulk /v1/batches jobs with a live executor task (JOBS_ENABLED; "
    "their lines backfill idle compute as batch-class streams)",
    ["model"],
)
JOB_LINES = Counter(
    "job_lines_total",
    "Bulk job lines reaching a terminal state (completed = result "
    "journaled write-ahead to JOURNAL_DIR/jobs, failed = the error "
    "became the recorded result, cancelled = unfinished at job cancel)",
    ["model", "state"],
)
JOB_REPLAYS = Counter(
    "job_replays_total",
    "Jobs processed at startup replay, by outcome (resumed = "
    "re-admitted from the last completed line, complete = every line "
    "finished before the kill, failed = could not re-admit)",
    ["model", "outcome"],
)
KV_DISK_POOL_BLOCKS = Gauge(
    "kv_disk_pool_blocks",
    "Disk KV tier blocks by state (KV_DISK_BUDGET_MB; used = spilled "
    "stream checkpoints + demoted prefix entries persisted under "
    "JOURNAL_DIR/kv_disk)",
    ["model", "state"],
)
KV_GROWTH_STALLS = Counter(
    "kv_growth_stalls_total",
    "Paged-KV decode growth found the pool dry: the stream was "
    "checkpointed and re-queued (resumes when blocks free up)",
    ["model"],
)
# Sub-millisecond buckets: dispatch submit→return and inter-token
# cadence both sit well under 1 ms on direct-attached chips — the
# whole point of these two series is separating that regime from the
# ~100 ms relay RTT regime.
# The fine set keeps its sub-ms resolution but no longer tops out at
# 10 s (the r11 honest negative: stream_tbt_seconds p99 saturated the
# top bucket on the 1-vCPU box and the scrape-side percentile could
# only answer "≥ 10 s").
_FINE_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 10.0, 30.0, 120.0,
)
DISPATCH_HOST = Histogram(
    "dispatch_host_seconds",
    "Host time one guarded device dispatch spent from submit to "
    "return, by dispatch site (prefill | prefill_chunk | chunk | "
    "fetch | batch | handoff | swap | prep) — the host-side half of "
    "the host-vs-device attribution split (TRACE=1 spans carry the "
    "device half); prep is the double-buffered host prep staged while "
    "the previous chunk is in flight",
    ["model", "site"], buckets=_FINE_BUCKETS,
)
JOURNAL_FSYNC = Histogram(
    "journal_fsync_seconds",
    "Wall time per journal fsync (JOURNAL_FSYNC=always pays one per "
    "record on the delivery path; interval amortizes; off never "
    "observes here)",
    ["model"], buckets=_FINE_BUCKETS,
)
WARM_SECONDS = Histogram(
    "engine_warm_seconds",
    "Wall seconds one warm phase took (engine = engine.warmup bucket "
    "grid, loop = ContinuousDecodeLoop.warm, spawn_build / spawn_warm "
    "/ spawn_probe = the fleet scale-up breakdown) — with the "
    "fleet-shared executable cache a second replica's loop/spawn "
    "phases collapse to dispatch time, zero XLA compiles",
    ["model", "phase"],
    buckets=(0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0),
)
EXEC_CACHE_EVENTS = Counter(
    "executable_cache_events_total",
    "Process-level ExecutableCache lookups by event (hit = an existing "
    "jitted wrapper was shared — the zero-compile spawn/restart path; "
    "miss = no wrapper under the key; insert = a freshly built wrapper "
    "was cached) — runtime/compile_cache.py, docs/compilation.md",
    ["event"],
)
PALLAS_AUTOTUNE_EVENTS = Counter(
    "pallas_autotune_events_total",
    "Decode-kernel autotuner decisions by event (sweep = a measured "
    "variant search ran; hit = the tuning table answered without one; "
    "pin = PALLAS_VARIANT honored; install = a winner entered the "
    "ExecutableCache; reject_vmem/reject_verify/reject_error = "
    "candidates dropped by the cost model / reference check / build "
    "failure) — ops/autotune.py, docs/kernel_tuning.md",
    ["event"],
)
TBT = Histogram(
    "stream_tbt_seconds",
    "Streaming inter-chunk delivery gap (time between consecutive "
    "token-chunk deliveries to one stream after its first chunk) — "
    "the decode-cadence series the chunked-prefill A/B judges",
    ["model"], buckets=_FINE_BUCKETS,
)
# -- perf observatory (r20; utils/perfobs.py, docs/observability.md) --
DEVICE_BUSY = Counter(
    "device_busy_seconds",
    "Estimated device-busy seconds by dispatch site, derived from "
    "submit timestamps + the loop's existing fetch seams (zero extra "
    "syncs, always on — the production replacement for the TRACE=1 "
    "block_until_ready attribution mode)",
    ["model", "site"],
)
DEVICE_BUBBLE = Counter(
    "device_bubble_seconds",
    "Estimated device idle gaps between attributed busy intervals "
    "(time the chip sat waiting on host dispatch/prep — the quantity "
    "the host-side levers shrink)",
    ["model"],
)
MODELED_FLOPS = Counter(
    "modeled_flops_total",
    "Modeled FLOPs accrued per dispatched executable kind "
    "(XLA cost_analysis, analyzed once per executable at the shared "
    "compile cache — runtime/compile_cache.py)",
    ["model", "kind"],
)
MFU = Gauge(
    "mfu_estimate",
    "Rolling model-FLOPs-utilization estimate: modeled FLOP rate over "
    "peak chip FLOPs (PEAK_TFLOPS knob or device-kind table; 0 when "
    "the peak is unknown — /debug/perf carries the raw components)",
    ["model"],
)
SLO_TTFT_BURN = Gauge(
    "slo_ttft_burn_rate",
    "Per-priority-class TTFT SLO burn rate by window (fast/slow): "
    "fraction of the error budget (1 - SLO_TARGET) being consumed; "
    "1.0 = burning exactly at budget, >1 = violating "
    "(scheduler/policy.SLOTracker; SLO_TTFT_MS knobs)",
    ["model", "klass", "window"],
)
# -- multi-tenancy (tenancy/; docs/multi-tenancy.md).  The tenant
# label is BOUNDED: the first TENANT_METRICS_TOPK configured tenants
# export by name, everything else folds into "other" and anonymous
# traffic into "anon" (TenantRegistry.label) — cardinality is
# topk+2 regardless of how many API keys exist.
TENANT_SHED = Counter(
    "tenant_requests_shed_total",
    "Per-tenant load sheds by reason (quota = the tenant exhausted its "
    "own concurrency/token-window/KV envelope → HTTP 429; other "
    "reasons mirror requests_shed_total, attributed to the caller)",
    ["model", "tenant", "reason"],
)
TENANT_KV = Gauge(
    "tenant_kv_committed_bytes",
    "KV-cache bytes currently leased against each tenant's quota "
    "(tenancy/accounts.py occupancy ledger; drains to zero at idle)",
    ["model", "tenant"],
)
TENANT_TOKENS = Counter(
    "tenant_tokens_total",
    "Offered tokens charged to each tenant's sliding window (prompt "
    "length + clamped decode budget, charged at admission — metered "
    "work, not realized luck)",
    ["model", "tenant"],
)
TENANT_SLO_BURN = Gauge(
    "tenant_slo_ttft_burn_rate",
    "Per-tenant TTFT SLO burn rate by window (fast/slow), same budget "
    "arithmetic as slo_ttft_burn_rate — the noisy-neighbor blast-"
    "radius gauge fair share is supposed to keep flat",
    ["model", "tenant", "window"],
)
ADAPTER_SLOTS = Gauge(
    "adapter_pool_slots",
    "LoRA adapter device-slot pool by state (resident = installed "
    "adapters, active = slots refcounted by live streams, free = "
    "installable without eviction, host = adapters loaded host-side) "
    "— tenancy/adapters.py",
    ["model", "state"],
)
SLO_TBT_BURN = Gauge(
    "slo_tbt_burn_rate",
    "Per-priority-class TBT (inter-chunk cadence) SLO burn rate by "
    "window (fast/slow), same budget arithmetic as slo_ttft_burn_rate "
    "(SLO_TBT_MS knobs)",
    ["model", "klass", "window"],
)
TP_COLLECTIVE_SECONDS = Gauge(
    "tp_collective_seconds",
    "Measured wall time of one d_model-sized collective over the "
    "('replica','tp') serving mesh, by op (all_reduce = the row-"
    "parallel psum every decode layer pays, all_gather = the logits "
    "gather) — probed once at engine warm (parallel/tpserve.py); a "
    "step change flags ICI vs host-hop placement drift",
    ["model", "op"],
)
KV_POOL_SHARD_BLOCKS = Gauge(
    "kv_pool_shard_blocks",
    "Paged-KV blocks resident per TP shard (TP>1: every block splits "
    "its heads axis across shards, so the shards MUST stay equal — "
    "one logical pool, device-agnostic block ids; divergence means a "
    "sharding bug).  TP=1 emits shard 0 only",
    ["model", "shard"],
)


def render() -> tuple[bytes, str]:
    return generate_latest(), CONTENT_TYPE_LATEST
