"""Always-on device-time & MFU attribution (the perf observatory core).

Round 11 left the repo with a blind spot this module closes: per-site
HOST time is always measured (``dispatch_host_seconds{site}``), but the
DEVICE half was only visible under ``TRACE=1`` attribution mode, whose
``block_until_ready`` serializes the dispatch pipeline (8–15%
overhead, BASELINE.md r11) — so no production run and no headline
BENCH pass has carried device-side numbers since r05.  The estimator
here derives device occupancy from timestamps the serving loop
**already touches**, in the spirit of the benchmark-methodology
guidance of arXiv 2210.04323 (measure the steady pipeline, don't
serialize it to observe it):

- every guarded dispatch is **stamped at submit** (``on_guard`` — two
  clock reads that ``dispatch_guard`` was already paying);
- **completion is sampled at the fetch seams the loop already has**
  (``note_complete`` from ``_deliver_ready``/``_deliver_oldest``/
  ``_deliver_all``/``_admit_complete`` in ``engine/streams.py`` and
  the per-stream fetches in ``engine/engine.py``): a ``device_get``
  returns exactly when the producing dispatch finished, so the fetch
  return IS a device-completion timestamp — no extra sync, no extra
  dispatch, dispatch/fetch counts pinned unchanged
  (``tests/test_perf_obs.py``).

Because one device executes its stream in submission order, a
completion sample at sequence ``s`` also closes every older pending
submit (the linearity rule) — chunked-prefill windows, swap scatters
and handoffs, which have no fetch of their own, are closed by the next
decode-chunk completion.

**Accounting model** (estimator, documented as such): each completion
sample at time ``T`` closing pending submits ``P`` contributes one
busy interval ``[max(prev_busy_end, min_submit(P)), T]``; the gap
before it is device **bubble**.  The interval is attributed across the
closed sites (equal split — per-dispatch FLOP pairing would require
cross-thread plumbing the hot path doesn't need).  Only the
precisely-paired sites accrue busy time (``chunk``, ``prefill``,
``prefill_chunk``; ``batch`` is synchronous and self-closing); rare
un-paired sites (``swap``/``handoff`` tails) conservatively land in
bubble.  ``prep`` host intervals that overlap in-flight device work
accrue ``prep_overlap_s`` — the overlap-with-prep series the r19
double-buffering claims are judged by.

**MFU**: ``runtime/compile_cache.py`` analyzes every shared executable
once per call signature (``Lowered.cost_analysis()`` — a trace+lower,
zero XLA compiles, zero dispatches) and accrues modeled FLOPs/bytes
per (model, kind) into the process-level book here on every dispatch.
``mfu_estimate`` = rolling modeled-FLOP rate / peak chip FLOPs
(``PEAK_TFLOPS`` knob, else the device-kind table, else unknown →
gauge stays 0 and /debug/perf says why).

``PERF_OBS=0`` disables the whole layer: ``on_guard``/``note_*``
return before touching any state (no timestamps kept — pinned), and
shared executables skip cost analysis.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from . import metrics

# ---------------------------------------------------------------------------
# process-level switch (set from ServiceConfig at engine construction;
# read by compile_cache's cost-analysis wrapper and the occupancy
# estimators; default on — the whole point is always-on attribution).

_ENABLED = os.environ.get("PERF_OBS", "1").lower() not in ("0", "false", "no")


def configure(enabled: bool) -> None:
    """Flip the process-level switch (engine construction calls this
    with ``cfg.perf_obs``; last engine wins, which only matters to
    tests that build engines with differing knobs)."""
    global _ENABLED
    _ENABLED = bool(enabled)


def enabled() -> bool:
    return _ENABLED


# ---------------------------------------------------------------------------
# modeled-FLOP book: per-(model, kind) accruals fed by compile_cache.

_BOOK_LOCK = threading.Lock()
_BOOK: dict[str, dict] = {}  # model -> {"flops", "bytes", "by_kind": {}}


def note_cost(model: str, kind: str, flops: float, bytes_: float) -> None:
    """One dispatch of an analyzed executable: accrue its modeled cost
    (called by the compile-cache wrapper on every call; any thread)."""
    if flops:
        metrics.MODELED_FLOPS.labels(model, kind).inc(flops)
    with _BOOK_LOCK:
        b = _BOOK.setdefault(
            model, {"flops": 0.0, "bytes": 0.0, "by_kind": {}}
        )
        b["flops"] += flops
        b["bytes"] += bytes_
        b["by_kind"][kind] = b["by_kind"].get(kind, 0.0) + flops


def book_totals(model: str) -> dict:
    """{"flops", "bytes", "by_kind"} accrued for one model so far."""
    with _BOOK_LOCK:
        b = _BOOK.get(model)
        if b is None:
            return {"flops": 0.0, "bytes": 0.0, "by_kind": {}}
        return {
            "flops": b["flops"], "bytes": b["bytes"],
            "by_kind": dict(b["by_kind"]),
        }


def reset_book() -> None:
    """Test hook: zero the modeled-cost accruals."""
    with _BOOK_LOCK:
        _BOOK.clear()


# ---------------------------------------------------------------------------
# peak-FLOP resolution (the MFU denominator).

#: Dense peak FLOP/s by TPU device kind (bf16 MXU numbers from public
#: spec sheets; the PEAK_TFLOPS knob overrides).  CPU backends have no
#: meaningful entry — MFU stays 0/unknown unless the knob says
#: otherwise.
_PEAK_BY_KIND = (
    ("v6e", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_flops(cfg=None) -> float:
    """Peak FLOP/s for the MFU denominator: the PEAK_TFLOPS knob when
    set, else a device-kind lookup, else 0.0 (unknown)."""
    knob = float(getattr(cfg, "peak_tflops", 0.0) or 0.0) if cfg is not None \
        else 0.0
    if not knob:
        try:
            knob = float(os.environ.get("PEAK_TFLOPS", "0") or 0.0)
        except ValueError:
            knob = 0.0
    if knob:
        return knob * 1e12
    try:
        import jax

        kind = str(jax.devices()[0].device_kind).lower()
    except Exception:
        return 0.0
    for frag, peak in _PEAK_BY_KIND:
        if frag in kind:
            return peak
    return 0.0


# ---------------------------------------------------------------------------
# the per-engine occupancy estimator.


class DeviceOccupancy:
    """Zero-extra-sync device busy/bubble estimator for one engine
    (module docstring has the accounting model).  Thread-safe: submits
    arrive from the decode-loop and stream-executor threads,
    completions from whichever thread ran the fetch."""

    #: Sites whose submits are precisely paired with a fetch seam.
    TRACKED_SITES = frozenset({"chunk", "prefill", "prefill_chunk"})
    #: Synchronous sites: the guarded callable contains its own fetch,
    #: so the guard return IS the completion (the unary batch path).
    SYNC_SITES = frozenset({"batch"})
    #: Host-side prep (r19 double-buffering): overlap accounting only.
    HOST_SITES = frozenset({"prep"})
    #: Pending-submit bound: a path that never completes (legacy
    #: engines driven without fetch seams) must not grow memory.
    MAX_PENDING = 4096

    def __init__(self, model: str, enabled: bool = True,
                 peak_flops: float = 0.0, clock=time.perf_counter,
                 window_s: float = 60.0):
        self.model = model
        self.enabled = bool(enabled)
        self.peak_flops = float(peak_flops)
        self.window_s = float(window_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._seq = 0
        self._pending: dict[str, deque] = {}  # site -> deque[(seq, ts)]
        self._pending_total = 0
        self._epoch = clock()
        self._busy_end: float | None = None
        self.busy_s: dict[str, float] = {}
        self.bubble_s = 0.0
        self.prep_overlap_s = 0.0
        self.prep_host_s = 0.0
        self.samples = 0
        self.dropped_submits = 0
        # Rolling MFU ring: (ts, cumulative modeled flops) appended at
        # completion samples; bounded.
        self._flops_ring: deque = deque(maxlen=2048)
        self._last_gauge = 0.0

    # -- capture seams (graftlint: perf-capture — these ride the
    # dispatch_guard boundary / the loop's fetch seams only) ----------

    def on_guard(self, site: str, t0: float, t1: float) -> None:
        """One guarded dispatch returned: stamp it.  Called by
        ``InferenceEngine.dispatch_guard`` with the two clock reads it
        already paid — the layer adds no clock reads of its own on the
        dispatch path."""
        if not self.enabled:
            return
        if site in self.HOST_SITES:
            with self._lock:
                self.prep_host_s += t1 - t0
                if self._pending_total:
                    # Host prep that ran while device work was in
                    # flight: the overlap the r19 double-buffer buys.
                    self.prep_overlap_s += t1 - t0
            return
        if site in self.SYNC_SITES:
            with self._lock:
                self._account_locked([site], t0, t1)
            return
        if site not in self.TRACKED_SITES:
            return
        with self._lock:
            q = self._pending.setdefault(site, deque())
            if self._pending_total >= self.MAX_PENDING:
                # Unpaired path: drop the oldest rather than grow.
                for qq in self._pending.values():
                    if qq:
                        qq.popleft()
                        self._pending_total -= 1
                        self.dropped_submits += 1
                        break
            self._seq += 1
            q.append((self._seq, t0))
            self._pending_total += 1

    def note_complete(self, site: str, n: int = 1) -> None:
        """A fetch seam observed ``n`` dispatches of ``site`` landed:
        close them (and, by device-order linearity, every older pending
        submit of any site) and account the busy interval."""
        if not self.enabled:
            return
        now = self._clock()
        with self._lock:
            q = self._pending.get(site)
            if not q:
                return
            closed: list[tuple[int, float, str]] = []
            for _ in range(min(n, len(q))):
                seq, ts = q.popleft()
                self._pending_total -= 1
                closed.append((seq, ts, site))
            max_seq = closed[-1][0]
            # Linearity: anything submitted before the newest closed
            # dispatch finished before it did.
            for other, qq in self._pending.items():
                while qq and qq[0][0] < max_seq:
                    seq, ts = qq.popleft()
                    self._pending_total -= 1
                    closed.append((seq, ts, other))
            t0 = min(ts for _, ts, _ in closed)
            self._account_locked([s for _, _, s in closed], t0, now)

    # -- accounting ----------------------------------------------------

    def _account_locked(self, sites: list[str], t0: float,
                        t1: float) -> None:
        start = t0 if self._busy_end is None else max(self._busy_end, t0)
        if self._busy_end is not None and start > self._busy_end:
            gap = start - self._busy_end
            self.bubble_s += gap
            metrics.DEVICE_BUBBLE.labels(self.model).inc(gap)
        busy = max(0.0, t1 - start)
        self._busy_end = max(t1, self._busy_end or t1)
        self.samples += 1
        share = busy / len(sites)
        for s in sites:
            self.busy_s[s] = self.busy_s.get(s, 0.0) + share
            if share:
                metrics.DEVICE_BUSY.labels(self.model, s).inc(share)
        self._flops_ring.append((t1, book_totals(self.model)["flops"]))
        if t1 - self._last_gauge >= 1.0:
            self._last_gauge = t1
            metrics.MFU.labels(self.model).set(self._mfu_locked(t1))

    def _mfu_locked(self, now: float) -> float:
        if not self.peak_flops or not self._flops_ring:
            return 0.0
        newest_ts, newest = self._flops_ring[-1]
        oldest_ts, oldest = self._flops_ring[0]
        for ts, cum in self._flops_ring:
            if ts >= now - self.window_s:
                oldest_ts, oldest = ts, cum
                break
        span = newest_ts - oldest_ts
        if span <= 0:
            # One sample in the window: fall back to the epoch rate.
            span = max(now - self._epoch, 1e-9)
            oldest = 0.0
        return (newest - oldest) / span / self.peak_flops

    # -- read side -----------------------------------------------------

    def snapshot(self) -> dict:
        """/debug/perf + /status.perf + the BENCH ``perf`` block."""
        now = self._clock()
        with self._lock:
            busy_total = sum(self.busy_s.values())
            elapsed = max(now - self._epoch, 1e-9)
            book = book_totals(self.model)
            peak = self.peak_flops
            out = {
                "enabled": self.enabled,
                "model": self.model,
                "elapsed_s": round(elapsed, 4),
                "device_busy_s": {
                    k: round(v, 4) for k, v in sorted(self.busy_s.items())
                },
                "device_busy_total_s": round(busy_total, 4),
                "device_bubble_s": round(self.bubble_s, 4),
                "busy_ratio": round(
                    busy_total / (busy_total + self.bubble_s), 4
                ) if busy_total + self.bubble_s > 0 else None,
                "prep_host_s": round(self.prep_host_s, 4),
                "prep_overlap_s": round(self.prep_overlap_s, 4),
                "completion_samples": self.samples,
                "pending_dispatches": self._pending_total,
                "dropped_submits": self.dropped_submits,
                "modeled_flops_total": book["flops"],
                "modeled_bytes_total": book["bytes"],
                "modeled_flops_by_kind": {
                    k: v for k, v in sorted(book["by_kind"].items())
                },
                "peak_flops": peak,
                "mfu_estimate": round(self._mfu_locked(now), 6)
                if peak else None,
                # Roofline-ish companions: modeled flops over the busy
                # union (what the chip sustained while it ran) and over
                # the whole epoch (what the deployment extracted).
                "mfu_busy": round(
                    book["flops"] / busy_total / peak, 6
                ) if peak and busy_total > 0 else None,
                "mfu_epoch": round(
                    book["flops"] / elapsed / peak, 6
                ) if peak else None,
            }
        return out


def merge_snapshots(snaps: list[dict]) -> dict:
    """Fleet-wide rollup: sum the additive fields across per-replica
    occupancy snapshots (ratios recomputed from the sums)."""
    out: dict = {
        "replicas": len(snaps),
        "device_busy_total_s": 0.0,
        "device_bubble_s": 0.0,
        "prep_overlap_s": 0.0,
        "modeled_flops_total": 0.0,
        "completion_samples": 0,
        "device_busy_s": {},
    }
    for s in snaps:
        out["device_busy_total_s"] += s.get("device_busy_total_s", 0.0)
        out["device_bubble_s"] += s.get("device_bubble_s", 0.0)
        out["prep_overlap_s"] += s.get("prep_overlap_s", 0.0)
        out["completion_samples"] += s.get("completion_samples", 0)
        for k, v in (s.get("device_busy_s") or {}).items():
            out["device_busy_s"][k] = out["device_busy_s"].get(k, 0.0) + v
    busy, bubble = out["device_busy_total_s"], out["device_bubble_s"]
    out["busy_ratio"] = (
        round(busy / (busy + bubble), 4) if busy + bubble > 0 else None
    )
    # The modeled-FLOP book is per model (fleet replicas share one
    # model), so take it from the first snapshot rather than summing
    # the same book R times.
    if snaps:
        out["modeled_flops_total"] = snaps[0].get("modeled_flops_total", 0.0)
        out["mfu_estimate"] = snaps[0].get("mfu_estimate")
    return out
