"""Env-var driven service configuration (12-factor), typed via pydantic.

Capability parity: the reference template configures itself entirely from
environment variables read at startup — device selection (the north-star
``DEVICE=tpu`` mode, BASELINE.json:5), model selection, ports, batching
knobs (``max_batch=32``, BASELINE.json:10), and the parent orchestration
server URL its registration client announces itself to (SURVEY.md §2).

This module must stay import-light: no jax, no torch.  Device selection
has to happen *before* jax is imported (see ``runtime.device``), so the
config object is plain data.
"""

from __future__ import annotations

import os

from pydantic import BaseModel, Field, field_validator, model_validator

_VALID_DEVICES = ("tpu", "cpu")


class ServiceConfig(BaseModel):
    """All knobs for one model-serving process."""

    # Device runtime (L0). "tpu" routes through the PJRT TPU plugin,
    # "cpu" forces JAX_PLATFORMS=cpu (useful for CI and local dev).
    device: str = Field(default="tpu")
    # Model zoo selection (L1).
    model_name: str = Field(default="resnet50")
    # Optional path to a converted checkpoint (orbax dir or .npz). When
    # unset, models run from deterministic random init (no network, no
    # HF hub in this environment — SURVEY.md §7.1).
    model_path: str | None = None
    # Optional tokenizer asset (vocab.txt for WordPiece / spm vocab). When
    # unset, text models fall back to the built-in byte-level tokenizer.
    tokenizer_path: str | None = None

    # Persistent XLA compilation cache directory (runtime/device.py,
    # docs/compilation.md): restarts and fleet spawns reuse compiled
    # executables from disk instead of re-paying warmup.  Unset =
    # device default (ON for DEVICE=tpu at ~/.cache/mlmst-xla-cache;
    # OFF on cpu — CPU compiles are fast and golden tests want cold
    # compiles).  A path enables it anywhere; "0"/"off" disables even
    # on tpu.  The same setting is also read from the
    # COMPILE_CACHE_DIR env var for pre-config callers (benchmarks).
    compile_cache_dir: str | None = None

    # HTTP surface (L4).
    host: str = "0.0.0.0"
    port: int = 8000

    # Dynamic batching (L3). max_batch mirrors the reference's knob
    # (BASELINE.json:10); batch_timeout_ms is the max-wait policy.
    max_batch: int = 32
    batch_timeout_ms: float = 3.0
    # Upper bound on queued requests before the server sheds load (503).
    max_queue: int = 1024
    # Batches allowed in flight on the device concurrently. Dispatch and
    # result-fetch round-trips overlap (XLA queues the work), so >1
    # hides host<->device transfer latency behind compute. Measured on a
    # relay-attached v5e: 4 -> 66.8 req/s, 8 -> 83.0, 12 -> regression
    # (thread thrash). CPU-backend hosts may prefer a lower value.
    pipeline_depth: int = 8

    # Static-shape buckets (L2). XLA compiles one executable per shape;
    # requests are padded up to the nearest bucket (SURVEY.md §7.4.1).
    batch_buckets: tuple[int, ...] = (1, 2, 4, 8, 16, 32)
    seq_buckets: tuple[int, ...] = (32, 64, 128, 256, 512)
    # Warm (AOT-compile) every bucket at startup so compilation never
    # lands on the request path. Disable for fast test startup.
    warmup: bool = True

    # Replica data-parallel serving (the NCCL-DataParallel equivalent).
    # 0 = use every visible device.
    replicas: int = 0
    # Sequence-parallel width for long-context models (bert-long): the
    # sequence axis shards over an ('sp',) mesh and attention runs as a
    # ppermute ring (parallel/ring.py). 0 = every visible device.
    # Combine with REPLICAS>=2 for a ('replica','sp') 2-D mesh (batch
    # data-parallel on top of sequence parallelism).
    sp: int = 0
    # Tensor-parallel width (bert-base / gpt2): params Megatron-sharded
    # over the 'tp' axis of a ('replica','tp') mesh (parallel/tp.py
    # specs), batch over 'replica'. 0 = off (pure replica DP).
    tp: int = 0

    # Seq2seq decoding (T5).
    max_decode_len: int = 64
    stream_chunk_tokens: int = 4
    # Concurrent streaming generations admitted before 503 shedding.
    max_streams: int = 8
    # Continuous batching: live streams share one batched decode
    # dispatch, new streams admitted at chunk boundaries
    # (engine/streams.py).  Off = round-2 per-stream workers.
    continuous_batching: bool = True
    # Chunk-chain pipelining depth for the continuous loop: how many
    # batched chunk dispatches ride in flight before the oldest is
    # fetched.  The state chain is pure device-side, so depth D cuts
    # the steady-state inter-chunk cadence to ~max(RTT/D, chunk
    # compute).  0 = auto: measured at warmup from dispatch RTT vs
    # per-chunk device time (the relay regime picks ~RTT/compute,
    # a directly-attached chip picks 1).
    stream_pipeline: int = 0

    # Parent orchestration-server registration (template parity:
    # the public template self-registers with a Photo Analysis Server on
    # startup, retrying until acked — SURVEY.md §1).
    server_url: str | None = None
    register_retry_s: float = 2.0
    register_max_tries: int = 30
    # Re-register every N seconds so a restarted parent re-learns this
    # service; 0 disables (register-once, template-parity behavior).
    register_heartbeat_s: float = 0.0

    # Weight-only quantization for serving: None (full precision) or
    # "int8" (per-channel symmetric; halves weight bytes per decode
    # step — the lever for HBM-bound small-batch generation).
    quantize: str | None = None
    # KV-cache quantization (llama family): "int8" stores K/V as
    # per-token-per-head int8 + scales, halving the SECOND bandwidth
    # term of batched long-context decode (weights being the first).
    # Lossy (not bit-identical to bf16-cache generation); measured in
    # BASELINE.md.  Composes with both prefix knobs (round 6): cached
    # prefix rows are captured/attached as int8 + scale entries the
    # quantized cache absorbs directly.
    quant_kv: str | None = None

    # Speculative decoding for generative families (gpt2/llama/t5):
    # "ngram" drafts the next SPEC_K tokens by prompt-lookup (the last
    # SPEC_NGRAM generated tokens are matched against the prompt +
    # generation history — for T5, against the ENCODER input, where
    # summaries quote from) and verifies all of them in ONE forward —
    # the only lever past the HBM ceiling at batch=1, where each step
    # otherwise streams the full weights for one token.  Greedy output
    # is exactly the verify-forward's argmax at every position, so
    # output == non-speculative greedy.
    spec_decode: str | None = None
    # Draft length per verify step (tokens checked per forward).
    spec_k: int = 8
    # Match-pattern length for the n-gram lookup.
    spec_ngram: int = 2
    # Load gate: greedy streams route to the speculative per-stream
    # path only while FEWER than this many streams are active; beyond
    # it they join the shared continuous-batching loop instead (one
    # batched dispatch for all streams beats per-stream speculation
    # under concurrency — speculation is the B=1 latency lever).
    spec_max_streams: int = 1
    # Speculation inside the continuous-batching loop: the shared slot
    # state carries a per-row drafting history and the shared chunk
    # runs draft→verify rounds, so EVERY live stream keeps the
    # accepted-token multiplier instead of losing drafting beyond
    # spec_max_streams.  Costs a (spec_k+1)-wide window per row per
    # round — wins on quoting/repetitive traffic, can lose on
    # low-acceptance traffic at high width (measure before enabling:
    # benchmarks/streams_scaling.py prints the spec_continuous column
    # by default; BENCH_SPEC=0 skips it).  Stacks with PREFIX_CACHE
    # (round 6): hit admissions recast through init_spec_fn at
    # slot-insert time, so prefix-hit streams join the spec slot
    # batch (benchmarks/compose_ab.py measures the stack).  With
    # SPEC_SAMPLED=0, sampled streams bypass the loop to the
    # per-stream chunked path so the strict seed contract holds.
    spec_continuous: bool = False
    # Rejection-sampling acceptance for temperature>0 requests (accept
    # draft_i with prob p(draft_i) under the filtered distribution;
    # resample the residual on reject): DISTRIBUTION-identical to
    # sequential sampling, but consumes randomness differently, so a
    # seeded request's exact tokens depend on which path served it
    # (each path is itself deterministic per seed).  SPEC_SAMPLED=0
    # restores strict cross-path seed reproducibility by routing all
    # sampled traffic to the normal chunked path.
    spec_sampled: bool = True

    # Shared prompt prefix (system prompt) for decoder models
    # (gpt2/llama): its KV is computed ONCE at startup and cached, so
    # every request's prefill pays only its own suffix (O(S) instead
    # of O(P+S)) and the prefix never counts against wire bytes.
    prompt_prefix: str | None = None
    # PER-REQUEST prefix caching (decoder families; the vLLM-class
    # generalization of PROMPT_PREFIX): KV of recurring token prefixes
    # — per-conversation system prompt + history — is captured from
    # each prefill and reused by any later request sharing it, matched
    # at request time by content hash at seq-bucket lengths.  Opt-in:
    # it compiles a (prefix-bucket × suffix-bucket) executable grid at
    # warmup, so restrict SEQ_BUCKETS for these deployments.
    # Mutually exclusive with PROMPT_PREFIX.
    prefix_cache: bool = False
    prefix_cache_mb: float = 256.0

    # SLA-aware request scheduling (scheduler/admission.py + policy.py).
    # Priority class for requests without an X-Priority header.
    priority_default: str = "interactive"
    # Default deadline for requests without X-Deadline-Ms, in ms; a
    # request still WAITING past its deadline sheds as a fast 504
    # before any device work.  0 = no default deadline.
    deadline_ms: float = 0.0
    # Weighted dequeue: interactive pops per batch pop while both
    # classes wait (batch never starves, interactive never waits more
    # than 1/weight extra).
    class_weight: int = 4
    # KV-footprint admission budget in MB: the cache bytes the admitted
    # working set may commit (estimated per request from prompt bucket,
    # decode budget, model dims and the QUANT_KV dtype).  Requests that
    # can never fit shed 503; transient overcommit down-classes
    # interactive work to batch.  0 disables the gate.
    kv_budget_mb: float = 0.0
    # Streams allowed to WAIT (deadline-queued) beyond max_streams
    # active; 0 restores the historical instant 503 past max_streams.
    max_stream_queue: int = 0
    # Block-paged KV cache (decoder families, continuous batching):
    # the shared decode loop's KV lives in a pool of KV_BLOCK_SIZE-token
    # blocks with per-slot block tables instead of per-slot contiguous
    # slabs.  Admission then charges a stream only its prompt blocks
    # plus the first chunk's block, grows block-by-block at chunk
    # boundaries, frees every block the moment the stream ends (early
    # EOS, cancel, preemption), and prefix-cache hits SHARE the donor's
    # prompt blocks by refcount instead of copying — which is what
    # turns KV_BUDGET_MB from a worst-case gate into live-token
    # occupancy (docs/kv-paging.md).  Default off = the seed layout.
    paged_kv: bool = False
    # Tokens per KV block in paged mode.  Unaligned seq buckets are
    # rounded UP to this grid at parse (_align_paged_seq_buckets) —
    # prefix sharing relies on bucket-aligned block boundaries.
    kv_block_size: int = 16
    # -- Pallas decode-kernel selection (docs/kernel_tuning.md) --------
    # Measured kernel-variant sweep at warmup (ops/autotune.py): every
    # feasible variant is verified against the jnp reference and timed
    # at the real serving shapes; the winner installs into the shared
    # ExecutableCache and persists in the tuning table, so replica
    # spawns/rebuilds inherit it with zero extra compiles.  Off =
    # default kernel everywhere (the seed behavior).
    pallas_autotune: bool = False
    # Pin one kernel variant fleet-wide (Variant grammar, e.g.
    # "b4-hb"); validated at boot.  None = autotuned-or-default.
    pallas_variant: str | None = None
    # Run Pallas kernels in interpret mode and lift the TPU backend
    # gate — CPU CI and the pallas_ab bench exercise the real kernel
    # path; never set this on a TPU deployment.
    pallas_interpret: bool = False
    # Contiguous-slab Pallas attention cutover: prompts at or under
    # this length run the single-block fused kernel (ops/attention.
    # use_pallas_attention); longer prompts take the XLA path.  Env is
    # read by ops/attention directly (config-less callers: benchmarks,
    # unit tests); this field validates it at boot.
    pallas_single_block_max_seq: int = 512
    # VMEM budget (MB) the decode-kernel fit gate AND the autotuner's
    # variant cost model filter against (ops/attention.
    # decode_kernel_fits, ops/autotune.paged_vmem_bytes).  ~16 MB/core
    # physical on v4/v5e; default leaves headroom for double-buffering.
    decode_kernel_vmem_budget_mb: int = 10
    # Host-RAM KV tier (docs/kv-tiering.md; requires PAGED_KV=1): MB of
    # host memory backing swapped-out KV.  Checkpointed streams
    # (preemption, dry-pool reclaim, supervised crash recovery, fleet
    # evacuation) copy the blocks behind their resume prompt
    # device→host instead of freeing-and-recomputing them, and resume
    # by prefetching the copies back — zero re-prefill chunks; evicted
    # prefix-cache entries demote here and promote back on a match, so
    # CoW prefix hits survive device-budget pressure.  0 (default) =
    # tier off: every checkpoint recomputes exactly as before
    # (bit-identical paths).
    kv_host_budget_mb: float = 0.0
    # Swap-in pacing: host→device block copies per loop iteration while
    # decode streams are live (idle backfill is unbounded) — the
    # communication-aware prefetch budget that keeps a resume from
    # stalling live decode (ChunkFlow, arXiv 2605.11335).
    kv_prefetch_blocks: int = 4
    # Durable serving (runtime/durability.py; docs/durability.md).
    # Directory for the crash-safe write-ahead stream journal: every
    # stream's admission record and delivered-token cursor append here
    # (length/CRC-framed JSONL) BEFORE tokens reach the client, and on
    # startup the server replays the journal and re-admits every
    # incomplete stream for token-identical resume after kill -9.
    # Clients reconnect via GET /v1/streams/{request_id}; unary
    # /predict retries dedup by X-Request-Id against journaled
    # results.  Unset (default) = no journal, every path bit-identical
    # to the pre-durability code.
    journal_dir: str | None = None
    # Journal fsync policy: "always" (fsync per record — survives
    # kernel/power crashes), "interval" (fsync at most every 50 ms),
    # "off" (OS page cache only — still survives a PROCESS kill, which
    # is the kill -9 contract; not a host crash).
    journal_fsync: str = "always"
    # Disk KV tier below the host-RAM tier (requires PAGED_KV=1,
    # KV_HOST_BUDGET_MB>0 and JOURNAL_DIR): cold host blocks (LRU-
    # evicted swaps, demoted prefixes) spill to memmap files under
    # JOURNAL_DIR/kv_disk instead of dying, and stream checkpoints
    # write through so their resume KV outlives the process.  0
    # (default) = no disk tier.
    kv_disk_budget_mb: float = 0.0
    # Bulk inference lane (jobs/; docs/bulk-inference.md): the
    # /v1/batches job API — thousands of JSONL prompt lines submitted
    # as ONE durable job whose manifest, per-line state and results
    # persist through the write-ahead journal machinery under
    # JOURNAL_DIR/jobs, so a kill -9 mid-job resumes from the last
    # completed line with exactly-once per-line results.  Lines run as
    # batch-class streams behind the deadline queue and pacer — pure
    # idle-compute backfill that interactive arrivals preempt at chunk
    # boundaries.  Requires JOURNAL_DIR and a generative model.  Off
    # (default) = no job code runs, serving paths bit-identical.
    jobs_enabled: bool = False
    # Per-job cap on lines in flight concurrently; the backfill
    # governor throttles below it while interactive work is live or
    # waiting (scheduler/policy.py).
    job_max_concurrent_lines: int = 4
    # Seconds a completed/cancelled job's results stay fetchable
    # before the store purges them; 0 = keep forever.
    job_result_ttl_s: float = 3600.0
    # Multi-tenant serving (tenancy/; docs/multi-tenancy.md).  Inline
    # tenant table: comma-separated "name=weight" (or bare "name",
    # weight 1) — each tenant's name doubles as its X-Api-Key.  Unset
    # AND TENANTS_FILE/ADAPTER_DIR unset (default) = no tenancy object
    # is constructed anywhere and every serving path is bit-identical
    # to the single-tenant server (pinned by tests/test_tenancy.py).
    tenants: str | None = None
    # Full tenant table: JSON file of spec objects with optional
    # "weight", "api_keys", "max_concurrency", "tokens_per_window",
    # "kv_mb" and "adapter" fields.  Both set = file wins for
    # duplicate names.  Garbage fails at boot, not request time.
    tenants_file: str | None = None
    # Fair-share weight for tenants without an explicit weight (and
    # for anonymous/keyless traffic).
    tenant_default_weight: float = 1.0
    # Sliding window in seconds for the per-tenant token-rate ledger
    # (tokens_per_window quotas count tokens admitted in the trailing
    # window; Retry-After = time until enough of the window drains).
    tenant_window_s: float = 60.0
    # Metric-label cardinality bound: the first K configured tenants
    # (declaration order) keep their names in the `tenant` label,
    # everything else exports as "other", keyless traffic as "anon" —
    # <= K+2 label values regardless of tenant-table size.
    tenant_metrics_topk: int = 8
    # LoRA adapter library directory: each <name>.npz under it (keys
    # "layers.{i}.{proj}.lora_a|lora_b", optional scalar "alpha")
    # becomes an adapter servable via the X-Adapter header — N tenants'
    # adapters decode as ONE batched dispatch over the shared base
    # weights (models/lora.py), routed through the SAME executables as
    # the base model (adapter install/evict never recompiles; pinned).
    # Unset (default) = no adapter code runs.  Rejected with
    # SPEC_DECODE/SPEC_CONTINUOUS (spec scoreboards assume base-model
    # logits).
    adapter_dir: str | None = None
    # Device-resident adapter slots (slot 0 is the pinned zero delta
    # serving base-model rows).  Adapters page host<->device through a
    # refcounted pool of this many slots; acquisition beyond capacity
    # sheds with reason="adapter_pool".
    adapter_slots: int = 8
    # Chunked prefill with prefill–decode interleaving
    # (docs/chunked-prefill.md): prompts longer than PREFILL_CHUNK
    # tokens prefill in PREFILL_CHUNK-token windows interleaved with
    # the continuous loop's decode chunks, so one long prompt never
    # stalls every live stream for its whole prefill.  Also lifts the
    # loop's prompt ceiling past the largest seq bucket (up to the
    # model's position budget) — oversized prompts chunk instead of
    # falling to the legacy per-stream path.  0 = off (the seed's
    # monolithic prefill).  Under PAGED_KV must be a multiple of
    # KV_BLOCK_SIZE; rejected for t5 / PROMPT_PREFIX / SPEC_CONTINUOUS.
    prefill_chunk: int = 0
    # Max prefill tokens interleaved per loop iteration while decode
    # streams are live (idle compute backfills unbounded).  0 = one
    # chunk (PREFILL_CHUNK) per iteration — decode cadence never waits
    # behind more than one window's compute.
    prefill_budget: int = 0
    # Prompt-length ceiling for chunked admission; 0 = auto (the
    # model's position budget: max_position - decode budget).  Bounds
    # the continuous loop's slot width (contiguous mode) / block-table
    # width (paged), so cap it when HBM is tight.
    prefill_max_prompt: int = 0
    # Fused decode windows (docs/decode-fusion.md): cap on how many
    # decode chunks fuse into ONE device dispatch (a lax.while_loop
    # over whole chunk scans with on-device EOS early exit), so the
    # host submits/fetches once per window instead of per chunk — the
    # knob that attacks the host-round-trip ceiling the round-11
    # attribution measured (host_share ≈ 1.0 at the chunk/fetch
    # sites).  1 = off (the seed's one-chunk dispatches, exactly).
    # Requires a window-capable family (gpt2/llama); rejected with
    # SPEC_CONTINUOUS (spec rounds have their own fused shape).
    decode_window: int = 1
    # Auto window policy: drop to W=1 whenever interactive streams are
    # live or waiting (their TBT/admission cadence binds at chunk
    # granularity), fuse up to DECODE_WINDOW for batch-class and idle
    # backfill.  0 = always fuse to the cap (throughput lanes with no
    # interactive SLA).
    decode_window_auto: bool = True
    # Double-buffered host dispatch prep (engine/streams.py,
    # docs/compilation.md): while chunk N is in flight, the loop
    # stages iteration N+1's host-side prep — the paged block-growth
    # pass, table assembly and the table's host→device upload — so it
    # overlaps N's device compute instead of serializing between
    # dispatches.  Token-identical by construction (a stale staged
    # plan rolls back and re-preps inline); measured at
    # dispatch_host_seconds{site="prep"}.  Off = the serial prep
    # order, exactly.
    host_prep_double: bool = True
    # Interactive arrivals may preempt batch-class streams (checkpoint
    # the cursor, free the slot, re-queue for token-identical resume)
    # when every slot is busy.  Only reachable with MAX_STREAM_QUEUE>0.
    preempt: bool = True
    # Seconds the SIGTERM drain waits for in-flight work before exit.
    drain_grace_s: float = 30.0

    # Replica fleet (engine/fleet.py + scheduler/router.py): run this
    # many INDEPENDENT continuous decode loops — each with its own
    # engine, supervisor, watchdog, KV pool and prefix cache — behind
    # a health-gated router.  A dead replica's streams checkpoint at
    # their delivered-token cursor and resume token-identically on a
    # healthy replica.  1 (default) = the single-engine path, exactly.
    fleet_replicas: int = 1
    # Routing policy: "least" = health → least-loaded (committed KV
    # bytes + queue depth) → prefix affinity; "rr" = health-gated
    # round-robin (the A/B baseline).
    fleet_route: str = "least"
    # Consecutive dispatch faults that open a replica's circuit
    # breaker (routing avoids it; a half-open probe re-admits).
    fleet_breaker_n: int = 3
    # Seconds a breaker may sit open before the replica is evicted:
    # its streams failover to a healthy replica.  Half-open probes
    # start at half this interval.  Under elastic scaling this is ALSO
    # the rejoin delay: an evicted replica is rebuilt through the
    # scale-up path once it has been dead this long.
    fleet_evict_s: float = 10.0
    # Multi-chip fleet placement (docs/tensor-parallel.md +
    # docs/autoscaling.md): comma-separated per-replica TP widths, e.g.
    # "2,2,1" = two TP=2 groups plus one single-device spare, carved
    # DISJOINT from the visible device list (replica 0 keeps the base
    # engine's devices, so the first width must equal TP).  Unset
    # (default) with TP>1 carves one TP-wide group per replica; unset
    # with TP=1 keeps the shared single-device placement bit-identical
    # to the pre-multichip fleet.
    fleet_tp_groups: str | None = None

    # Elastic fleet (docs/autoscaling.md): live autoscaling bounds.
    # FLEET_REPLICAS becomes the INITIAL size; the ScalingGovernor
    # (scheduler/policy.py) moves the live count within
    # [FLEET_MIN_REPLICAS, FLEET_MAX_REPLICAS] off the router's own
    # load signals.  0 = same as FLEET_REPLICAS, and when BOTH bounds
    # collapse onto FLEET_REPLICAS the fleet is STATIC — no governor
    # thread, bit-identical to the pre-elastic code.
    fleet_min_replicas: int = 0
    fleet_max_replicas: int = 0
    # Scale-UP triggers (evaluated per governor tick, live < max):
    # waiting streams per live replica...
    scale_up_queue: float = 2.0
    # ...or committed-KV bytes as a fraction of the live fleet budget...
    scale_up_kv_frac: float = 0.85
    # ...or the decode loops' TTFT EWMA in ms (0 = signal off).
    scale_up_ttft_ms: float = 0.0
    # Minimum seconds between scale-up events (spin-up is cheap under
    # donor broadcast but each event still recompiles executables).
    scale_up_cooldown_s: float = 3.0
    # Scale-DOWN trigger: total load (active + queued streams) would
    # fit inside this fraction of the SURVIVORS' slots...
    scale_down_load: float = 0.25
    # ...sustained for this many seconds (the lull filter).
    scale_down_cooldown_s: float = 10.0
    # Governor tick period in seconds.
    scale_period_s: float = 0.5

    # Fault tolerance (engine/faults.py + engine/supervisor.py).
    # Deterministic fault-injection schedule wrapped around the
    # device-dispatch boundaries; off (None) = zero overhead.  Grammar
    # in engine/faults.py, e.g. "chunk:fatal@5;*:transient~0.05".
    fault_spec: str | None = None
    # Seed for rate-based (~) fault rules, so a chaos run replays.
    fault_seed: int = 0
    # Watchdog deadline per device dispatch in seconds; an overrun
    # raises DispatchTimeoutError (classified fatal → supervisor
    # rebuild) instead of stalling the decode loop forever.  0 = off
    # (the seed behavior; supervised deployments should set e.g. 60).
    dispatch_timeout_s: float = 0.0
    # Transient dispatch failures retried with capped exponential
    # backoff before the error escalates.
    dispatch_retries: int = 2
    dispatch_backoff_s: float = 0.05
    # Engine rebuilds the supervisor may spend (fatal fault / loop
    # death → checkpoint streams, rebuild device state, resume) before
    # /readyz goes permanently unready.
    engine_restarts_max: int = 3
    # Sliding restart window in seconds: the budget above counts only
    # restarts within the trailing window, so a long-lived engine is
    # not condemned by faults from hours ago.  0 (default) = the
    # historical lifetime cap.
    engine_restart_window_s: float = 0.0
    # Supervised crash recovery for the continuous decode loop; off
    # restores the seed's error-every-stream behavior on a fault.
    supervise: bool = True

    # Perf observatory (r20; utils/perfobs.py, docs/observability.md).
    # Always-on device-time attribution: every guarded dispatch is
    # stamped at submit and completion is sampled at the loop's
    # existing fetch seams — device busy/bubble, prep overlap and a
    # rolling MFU estimate with ZERO extra device syncs (the TRACE=1
    # block_until_ready attribution mode stays the high-resolution
    # debugging tool).  0 = the layer keeps no timestamps at all and
    # the compile cache skips cost analysis (pinned).
    perf_obs: bool = True
    # Peak chip TFLOP/s for the MFU denominator; 0 = auto (TPU
    # device-kind table; unknown on CPU, so mfu_estimate stays 0 and
    # /debug/perf carries the raw FLOP components instead).
    peak_tflops: float = 0.0
    # Latency histogram bucket edges (comma-separated ascending
    # seconds) for the request/TTFT latency families in
    # utils/metrics.py; unset = the built-in defaults, which since r20
    # extend past 10 s (the r11 honest negative: stream TTFT/TBT p99
    # saturated the old 10 s top bucket on the 1-vCPU box).
    latency_buckets: str | None = None
    # SLO objectives per priority class, in ms; 0 disables that
    # objective.  Interactive-class time-to-first-token / inter-chunk
    # cadence...
    slo_ttft_ms: float = 0.0
    slo_tbt_ms: float = 0.0
    # ...and the batch-class pair (bulk/background traffic usually
    # carries a much looser objective, not none).
    slo_batch_ttft_ms: float = 0.0
    slo_batch_tbt_ms: float = 0.0
    # SLO attainment target: the burn-rate denominator is the error
    # budget (1 - SLO_TARGET); burn 1.0 = consuming it exactly at the
    # sustainable rate.
    slo_target: float = 0.99
    # Burn-rate windows in seconds, "fast,slow" (multi-window
    # alerting: fast reacts, slow filters blips).
    slo_windows_s: str = "60,600"
    # SLO-burn scale-up signal for the ScalingGovernor: scale up when
    # the worst fast-window burn rate reaches this threshold.  0
    # (default) = off — governor decisions bit-identical to pre-SLO
    # behavior (pinned).
    scale_up_slo_burn: float = 0.0

    # Observability.
    log_level: str = "INFO"
    # Log line shape: "text" (the classic formatter) or "json" (one
    # structured object per line, request_id-correlated with spans and
    # HTTP error bodies — utils/tracing.JsonLogFormatter).
    log_format: str = "text"
    # Request-level span tracing (utils/tracing.py): spans at the
    # request / admission / queue-wait / prefill-window / decode-chunk
    # / dispatch-site seams, exported as Chrome trace-event JSON at
    # GET /debug/trace.  Off = zero overhead (no span objects on the
    # hot path).  ON additionally block_until_ready's each dispatch to
    # split host vs device time — an attribution mode that serializes
    # the chunk pipeline; see docs/observability.md.
    trace: bool = False
    # Completed spans kept in the trace ring.
    trace_ring: int = 4096
    # Engine flight recorder ring: loop iterations + scheduling/fault
    # events kept for GET /debug/engine and the automatic dump on
    # fatal faults.  0 disables recording (dump still answers, empty).
    flight_ring: int = 256
    # Directory for on-demand jax.profiler device traces
    # (POST /debug/profile); None = $PROFILE_DIR or /tmp/jax-trace.
    profile_dir: str | None = None

    # ------------------------------------------------------------------
    # r18 (graftlint knob-drift): every knob fails fast on garbage at
    # boot instead of surfacing as a serving-path error hours later.

    @field_validator("model_name")
    @classmethod
    def _check_model_name(cls, v: str) -> str:
        if not v.strip():
            raise ValueError("MODEL_NAME must be non-empty")
        return v

    @field_validator("host")
    @classmethod
    def _check_host(cls, v: str) -> str:
        if not v.strip():
            raise ValueError("HOST must be non-empty")
        return v

    @field_validator("port")
    @classmethod
    def _check_port(cls, v: int) -> int:
        if not (1 <= v <= 65535):
            raise ValueError("PORT must be in [1, 65535]")
        return v

    @field_validator("max_queue", "pipeline_depth", "max_decode_len",
                     "stream_chunk_tokens", "max_streams",
                     "register_max_tries")
    @classmethod
    def _check_pos_int(cls, v: int) -> int:
        if v < 1:
            raise ValueError(
                "MAX_QUEUE/PIPELINE_DEPTH/MAX_DECODE_LEN/"
                "STREAM_CHUNK_TOKENS/MAX_STREAMS/REGISTER_MAX_TRIES "
                "must be >= 1"
            )
        return v

    @field_validator("replicas", "sp", "tp", "stream_pipeline",
                     "max_stream_queue", "fault_seed", "spec_max_streams")
    @classmethod
    def _check_nonneg_knob_int(cls, v: int) -> int:
        if v < 0:
            raise ValueError(
                "REPLICAS/SP/TP/STREAM_PIPELINE/MAX_STREAM_QUEUE/"
                "FAULT_SEED/SPEC_MAX_STREAMS must be >= 0 (0 = auto/off)"
            )
        return v

    @field_validator("batch_timeout_ms", "register_retry_s",
                     "register_heartbeat_s", "prefix_cache_mb",
                     "deadline_ms", "kv_budget_mb", "drain_grace_s")
    @classmethod
    def _check_nonneg_knob_float(cls, v: float) -> float:
        if v < 0:
            raise ValueError(
                "BATCH_TIMEOUT_MS/REGISTER_RETRY_S/REGISTER_HEARTBEAT_S/"
                "PREFIX_CACHE_MB/DEADLINE_MS/KV_BUDGET_MB/DRAIN_GRACE_S "
                "must be >= 0"
            )
        return v

    @field_validator("batch_buckets", "seq_buckets")
    @classmethod
    def _check_buckets(cls, v: tuple[int, ...]) -> tuple[int, ...]:
        if not v:
            raise ValueError("BATCH_BUCKETS/SEQ_BUCKETS must be non-empty")
        if any(b < 1 for b in v):
            raise ValueError("bucket sizes must be >= 1")
        if list(v) != sorted(set(v)):
            raise ValueError(
                "BATCH_BUCKETS/SEQ_BUCKETS must be strictly ascending "
                f"(got {v})"
            )
        return v

    @field_validator("log_level")
    @classmethod
    def _check_log_level(cls, v: str) -> str:
        if v.upper() not in ("DEBUG", "INFO", "WARNING", "ERROR",
                             "CRITICAL"):
            raise ValueError(
                f"LOG_LEVEL must be a standard logging level, got {v!r}"
            )
        return v

    @field_validator("quantize")
    @classmethod
    def _check_quantize(cls, v: str | None) -> str | None:
        if v is not None:
            v = v.lower()
            if v in ("", "none", "0", "false"):
                return None
            if v != "int8":
                raise ValueError(f"QUANTIZE must be 'int8' or unset, got {v!r}")
        return v

    @field_validator("quant_kv")
    @classmethod
    def _check_quant_kv(cls, v: str | None) -> str | None:
        if v is not None:
            v = v.lower()
            if v in ("", "none", "0", "false"):
                return None
            if v != "int8":
                raise ValueError(f"QUANT_KV must be 'int8' or unset, got {v!r}")
        return v

    @field_validator("spec_decode")
    @classmethod
    def _check_spec(cls, v: str | None) -> str | None:
        if v is not None:
            v = v.lower()
            if v in ("", "none", "off", "0", "false"):
                return None
            if v != "ngram":
                raise ValueError(
                    f"SPEC_DECODE must be 'ngram' or unset, got {v!r}"
                )
        return v

    @field_validator("spec_k")
    @classmethod
    def _check_spec_k(cls, v: int) -> int:
        if not (1 <= v <= 64):
            raise ValueError("SPEC_K must be in [1, 64]")
        return v

    @field_validator("spec_ngram")
    @classmethod
    def _check_spec_ngram(cls, v: int) -> int:
        if not (1 <= v <= 8):
            raise ValueError("SPEC_NGRAM must be in [1, 8]")
        return v

    @field_validator("device")
    @classmethod
    def _check_device(cls, v: str) -> str:
        v = v.lower()
        if v not in _VALID_DEVICES:
            raise ValueError(f"DEVICE must be one of {_VALID_DEVICES}, got {v!r}")
        return v

    @field_validator("max_batch")
    @classmethod
    def _check_max_batch(cls, v: int) -> int:
        if v < 1:
            raise ValueError("MAX_BATCH must be >= 1")
        return v

    @field_validator("priority_default")
    @classmethod
    def _check_priority_default(cls, v: str) -> str:
        v = v.lower()
        if v not in ("interactive", "batch"):
            raise ValueError(
                f"PRIORITY_DEFAULT must be 'interactive' or 'batch', got {v!r}"
            )
        return v

    @field_validator("class_weight")
    @classmethod
    def _check_class_weight(cls, v: int) -> int:
        if v < 1:
            raise ValueError("CLASS_WEIGHT must be >= 1")
        return v

    @field_validator("kv_block_size")
    @classmethod
    def _check_kv_block_size(cls, v: int) -> int:
        if not (1 <= v <= 1024):
            raise ValueError("KV_BLOCK_SIZE must be in [1, 1024]")
        return v

    @field_validator("pallas_variant")
    @classmethod
    def _check_pallas_variant(cls, v: str | None) -> str | None:
        if v:
            from ..ops.paged_attention import parse_variant

            parse_variant(v)  # ValueError with the grammar on junk
        return v

    @field_validator("pallas_single_block_max_seq")
    @classmethod
    def _check_pallas_single_block(cls, v: int) -> int:
        if not (64 <= v <= 8192):
            raise ValueError(
                "PALLAS_SINGLE_BLOCK_MAX_SEQ must be in [64, 8192] "
                "(whole-slab kernel: one grid block per sequence)"
            )
        return v

    @field_validator("decode_kernel_vmem_budget_mb")
    @classmethod
    def _check_decode_vmem_budget(cls, v: int) -> int:
        if not (1 <= v <= 256):
            raise ValueError(
                "DECODE_KERNEL_VMEM_BUDGET_MB must be in [1, 256] MB"
            )
        return v

    @field_validator("prefill_chunk", "prefill_budget", "prefill_max_prompt")
    @classmethod
    def _check_prefill(cls, v: int) -> int:
        if v < 0:
            raise ValueError(
                "PREFILL_CHUNK/PREFILL_BUDGET/PREFILL_MAX_PROMPT must be >= 0"
            )
        return v

    @field_validator("kv_host_budget_mb")
    @classmethod
    def _check_kv_host_budget(cls, v: float) -> float:
        if v < 0:
            raise ValueError("KV_HOST_BUDGET_MB must be >= 0")
        return v

    @field_validator("kv_disk_budget_mb")
    @classmethod
    def _check_kv_disk_budget(cls, v: float) -> float:
        if v < 0:
            raise ValueError("KV_DISK_BUDGET_MB must be >= 0")
        return v

    @field_validator("journal_fsync")
    @classmethod
    def _check_journal_fsync(cls, v: str) -> str:
        v = v.lower()
        if v not in ("always", "interval", "off"):
            raise ValueError(
                f"JOURNAL_FSYNC must be 'always', 'interval' or 'off', "
                f"got {v!r}"
            )
        return v

    @field_validator("job_max_concurrent_lines")
    @classmethod
    def _check_job_lines(cls, v: int) -> int:
        if not (1 <= v <= 256):
            raise ValueError("JOB_MAX_CONCURRENT_LINES must be in [1, 256]")
        return v

    @field_validator("job_result_ttl_s")
    @classmethod
    def _check_job_ttl(cls, v: float) -> float:
        if v < 0:
            raise ValueError("JOB_RESULT_TTL_S must be >= 0")
        return v

    @field_validator("tenant_default_weight", "tenant_window_s")
    @classmethod
    def _check_tenant_pos_float(cls, v: float) -> float:
        if v <= 0:
            raise ValueError(
                "TENANT_DEFAULT_WEIGHT/TENANT_WINDOW_S must be > 0"
            )
        return v

    @field_validator("tenant_metrics_topk")
    @classmethod
    def _check_tenant_topk(cls, v: int) -> int:
        if not (1 <= v <= 64):
            raise ValueError("TENANT_METRICS_TOPK must be in [1, 64]")
        return v

    @field_validator("adapter_slots")
    @classmethod
    def _check_adapter_slots(cls, v: int) -> int:
        if not (1 <= v <= 256):
            raise ValueError("ADAPTER_SLOTS must be in [1, 256]")
        return v

    @model_validator(mode="after")
    def _check_tenant_table(self):
        # Boot-validate the tenant table so garbage TENANTS /
        # TENANTS_FILE fails here, not as request-time surprises.
        # Lazy import: tenancy is jax-free but pulls numpy/metrics.
        if self.tenants or self.tenants_file:
            from ..tenancy.accounts import parse_tenants

            parse_tenants(self.tenants, self.tenants_file)
        return self

    @field_validator("kv_prefetch_blocks")
    @classmethod
    def _check_kv_prefetch(cls, v: int) -> int:
        if not (1 <= v <= 4096):
            raise ValueError("KV_PREFETCH_BLOCKS must be in [1, 4096]")
        return v

    @field_validator("decode_window")
    @classmethod
    def _check_decode_window(cls, v: int) -> int:
        if not (1 <= v <= 64):
            raise ValueError("DECODE_WINDOW must be in [1, 64]")
        return v

    @field_validator("fleet_replicas")
    @classmethod
    def _check_fleet_replicas(cls, v: int) -> int:
        if not (1 <= v <= 64):
            raise ValueError("FLEET_REPLICAS must be in [1, 64]")
        return v

    @field_validator("fleet_route")
    @classmethod
    def _check_fleet_route(cls, v: str) -> str:
        v = v.lower()
        if v not in ("least", "rr"):
            raise ValueError(f"FLEET_ROUTE must be 'least' or 'rr', got {v!r}")
        return v

    @field_validator("fleet_breaker_n")
    @classmethod
    def _check_fleet_breaker_n(cls, v: int) -> int:
        if v < 1:
            raise ValueError("FLEET_BREAKER_N must be >= 1")
        return v

    @field_validator("fleet_evict_s", "engine_restart_window_s")
    @classmethod
    def _check_fleet_nonneg(cls, v: float) -> float:
        if v < 0:
            raise ValueError(
                "FLEET_EVICT_S/ENGINE_RESTART_WINDOW_S must be >= 0"
            )
        return v

    @field_validator("fleet_tp_groups")
    @classmethod
    def _check_fleet_tp_groups(cls, v: str | None) -> str | None:
        if v is None or not str(v).strip():
            return None
        try:
            widths = [int(w) for w in str(v).split(",")]
        except ValueError:
            raise ValueError(
                f"FLEET_TP_GROUPS must be comma-separated integer TP "
                f"widths (e.g. '2,2,1'), got {v!r}"
            ) from None
        if not widths or any(not (1 <= w <= 64) for w in widths):
            raise ValueError(
                "FLEET_TP_GROUPS widths must each be in [1, 64]"
            )
        return ",".join(str(w) for w in widths)

    @field_validator("fleet_min_replicas", "fleet_max_replicas")
    @classmethod
    def _check_fleet_bounds_range(cls, v: int) -> int:
        if not (0 <= v <= 64):
            raise ValueError(
                "FLEET_MIN/MAX_REPLICAS must be in [0, 64] (0 = "
                "FLEET_REPLICAS)"
            )
        return v

    @field_validator("scale_up_queue", "scale_up_cooldown_s",
                     "scale_down_cooldown_s", "scale_up_ttft_ms")
    @classmethod
    def _check_scale_nonneg(cls, v: float) -> float:
        if v < 0:
            raise ValueError("SCALE_UP/DOWN_* thresholds must be >= 0")
        return v

    @field_validator("scale_up_kv_frac", "scale_down_load")
    @classmethod
    def _check_scale_frac(cls, v: float) -> float:
        if not (0.0 <= v <= 1.0):
            raise ValueError(
                "SCALE_UP_KV_FRAC/SCALE_DOWN_LOAD must be in [0, 1]"
            )
        return v

    @field_validator("scale_period_s")
    @classmethod
    def _check_scale_period(cls, v: float) -> float:
        if v <= 0:
            raise ValueError("SCALE_PERIOD_S must be > 0")
        return v

    @model_validator(mode="after")
    def _check_fleet_elastic_bounds(self):
        n = self.fleet_replicas
        mn = self.fleet_min_replicas or n
        mx = self.fleet_max_replicas or n
        if not (mn <= n <= mx):
            raise ValueError(
                f"elastic fleet bounds must satisfy FLEET_MIN_REPLICAS "
                f"<= FLEET_REPLICAS <= FLEET_MAX_REPLICAS, got "
                f"{mn} <= {n} <= {mx}"
            )
        return self

    @model_validator(mode="after")
    def _check_tp_knob(self):
        # Tensor-parallel serving (TP>1; docs/tensor-parallel.md).
        # Composition limits fail at config parse, not first trace:
        # QUANTIZE's {'q8','scale'} weight subtrees have no TP layout
        # (same contract the registry enforces — "TP and QUANTIZE"),
        # and SP/TP compose via a 3-D mesh this engine doesn't build.
        if self.tp > 1:
            if self.quantize:
                raise ValueError(
                    "TP and QUANTIZE cannot combine (quantized leaves "
                    "are {'q8','scale'} subtrees the TP param spec "
                    "cannot shard); pick one"
                )
            if self.sp > 1:
                raise ValueError(
                    "TP and SP cannot combine (a ('replica','sp','tp') "
                    "mesh is not built); pick one parallelism axis"
                )
        return self

    @model_validator(mode="after")
    def _align_paged_seq_buckets(self):
        # PAGED_KV: block-align the bucket grid at BUILD time instead
        # of rejecting unaligned grids (prefix sharing and table-span
        # writes need block-aligned bucket boundaries).  Rounding UP
        # never shrinks an admissible prompt; collapsing duplicates
        # keeps the grid strictly ascending.  Aligned grids (the
        # default 16-multiples) pass through byte-identical.
        if self.paged_kv and self.kv_block_size > 1:
            bs = self.kv_block_size
            aligned = tuple(sorted({-(-b // bs) * bs
                                    for b in self.seq_buckets}))
            if aligned != self.seq_buckets:
                self.seq_buckets = aligned
        return self

    @field_validator("fault_spec")
    @classmethod
    def _check_fault_spec(cls, v: str | None) -> str | None:
        # Grammar validation happens at engine construction (still
        # startup, before readiness) — engine/faults.py cannot be
        # imported here because this module must stay jax-free.
        if v is not None and v.strip().lower() in ("", "none", "off", "0"):
            return None
        return v

    @field_validator("dispatch_timeout_s", "dispatch_backoff_s")
    @classmethod
    def _check_nonneg_float(cls, v: float) -> float:
        if v < 0:
            raise ValueError("dispatch timeout/backoff must be >= 0")
        return v

    @field_validator("dispatch_retries", "engine_restarts_max")
    @classmethod
    def _check_nonneg_int(cls, v: int) -> int:
        if v < 0:
            raise ValueError("DISPATCH_RETRIES/ENGINE_RESTARTS_MAX must be >= 0")
        return v

    @field_validator("log_format")
    @classmethod
    def _check_log_format(cls, v: str) -> str:
        v = v.lower()
        if v not in ("text", "json"):
            raise ValueError(f"LOG_FORMAT must be 'text' or 'json', got {v!r}")
        return v

    @field_validator("trace_ring", "flight_ring")
    @classmethod
    def _check_ring(cls, v: int) -> int:
        if v < 0:
            raise ValueError("TRACE_RING/FLIGHT_RING must be >= 0")
        return v

    @field_validator("peak_tflops", "slo_ttft_ms", "slo_tbt_ms",
                     "slo_batch_ttft_ms", "slo_batch_tbt_ms",
                     "scale_up_slo_burn")
    @classmethod
    def _check_perf_nonneg(cls, v: float) -> float:
        if v < 0:
            raise ValueError(
                "PEAK_TFLOPS/SLO_TTFT_MS/SLO_TBT_MS/SLO_BATCH_TTFT_MS/"
                "SLO_BATCH_TBT_MS/SCALE_UP_SLO_BURN must be >= 0 "
                "(0 = off/auto)"
            )
        return v

    @field_validator("slo_target")
    @classmethod
    def _check_slo_target(cls, v: float) -> float:
        if not (0.0 < v < 1.0):
            raise ValueError(
                "SLO_TARGET must be in (0, 1) — the error budget is "
                "1 - SLO_TARGET"
            )
        return v

    @field_validator("slo_windows_s")
    @classmethod
    def _check_slo_windows(cls, v: str) -> str:
        try:
            parts = [float(x) for x in v.split(",") if x.strip()]
        except ValueError:
            raise ValueError(
                f"SLO_WINDOWS_S must be 'fast,slow' seconds, got {v!r}"
            )
        if len(parts) != 2 or parts[0] <= 0 or parts[0] >= parts[1]:
            raise ValueError(
                "SLO_WINDOWS_S must be two ascending positive durations "
                f"'fast,slow', got {v!r}"
            )
        return v

    @field_validator("latency_buckets")
    @classmethod
    def _check_latency_buckets(cls, v: str | None) -> str | None:
        if v is None or not v.strip():
            return None
        from . import metrics as _metrics

        if _metrics.parse_buckets(v) is None:
            raise ValueError(
                "LATENCY_BUCKETS must be comma-separated strictly "
                f"ascending positive seconds, got {v!r}"
            )
        return v


def _env(name: str, default: str | None = None) -> str | None:
    v = os.environ.get(name)
    return v if v not in (None, "") else default


def load_config(env: dict[str, str] | None = None) -> ServiceConfig:
    """Build a ServiceConfig from environment variables.

    Recognized variables (reference-parity names first):
      DEVICE, MODEL_NAME, MODEL_PATH, TOKENIZER_PATH, HOST, PORT,
      MAX_BATCH, BATCH_TIMEOUT_MS, MAX_QUEUE, REPLICAS, SP, TP,
      MAX_DECODE_LEN, SERVER_URL, WARMUP, LOG_LEVEL, PIPELINE_DEPTH,
      MAX_STREAMS, BATCH_BUCKETS, SEQ_BUCKETS, QUANTIZE,
      REGISTER_HEARTBEAT_S, CONTINUOUS_BATCHING, PROMPT_PREFIX,
      SPEC_DECODE, SPEC_K, SPEC_NGRAM, PRIORITY_DEFAULT, DEADLINE_MS,
      CLASS_WEIGHT, KV_BUDGET_MB, MAX_STREAM_QUEUE, PREEMPT,
      DRAIN_GRACE_S, PAGED_KV, KV_BLOCK_SIZE, KV_HOST_BUDGET_MB,
      KV_DISK_BUDGET_MB, JOURNAL_DIR, JOURNAL_FSYNC,
      KV_PREFETCH_BLOCKS, JOBS_ENABLED, JOB_MAX_CONCURRENT_LINES,
      JOB_RESULT_TTL_S, TENANTS, TENANTS_FILE, TENANT_DEFAULT_WEIGHT,
      TENANT_WINDOW_S, TENANT_METRICS_TOPK, ADAPTER_DIR,
      ADAPTER_SLOTS, PREFILL_CHUNK,
      PREFILL_BUDGET, PREFILL_MAX_PROMPT, DECODE_WINDOW,
      DECODE_WINDOW_AUTO, FAULT_SPEC, FAULT_SEED,
      DISPATCH_TIMEOUT_S, DISPATCH_RETRIES, DISPATCH_BACKOFF_S,
      ENGINE_RESTARTS_MAX, ENGINE_RESTART_WINDOW_S, SUPERVISE,
      FLEET_REPLICAS, FLEET_ROUTE, FLEET_BREAKER_N, FLEET_EVICT_S,
      FLEET_TP_GROUPS,
      FLEET_MIN_REPLICAS, FLEET_MAX_REPLICAS, SCALE_UP_QUEUE,
      SCALE_UP_KV_FRAC, SCALE_UP_TTFT_MS, SCALE_UP_COOLDOWN_S,
      SCALE_DOWN_LOAD, SCALE_DOWN_COOLDOWN_S, SCALE_PERIOD_S,
      TRACE, TRACE_RING, FLIGHT_RING, PROFILE_DIR, LOG_FORMAT,
      COMPILE_CACHE_DIR, HOST_PREP_DOUBLE, PERF_OBS, PEAK_TFLOPS,
      LATENCY_BUCKETS, SLO_TTFT_MS, SLO_TBT_MS, SLO_BATCH_TTFT_MS,
      SLO_BATCH_TBT_MS, SLO_TARGET, SLO_WINDOWS_S, SCALE_UP_SLO_BURN.
    """
    e = dict(os.environ)
    if env:
        e.update(env)

    def get(name: str, default: str | None = None) -> str | None:
        v = e.get(name)
        return v if v not in (None, "") else default

    kwargs: dict = {}
    mapping = {
        "device": "DEVICE",
        "model_name": "MODEL_NAME",
        "model_path": "MODEL_PATH",
        "tokenizer_path": "TOKENIZER_PATH",
        "host": "HOST",
        "server_url": "SERVER_URL",
        "log_level": "LOG_LEVEL",
        "quantize": "QUANTIZE",
        "quant_kv": "QUANT_KV",
        "prompt_prefix": "PROMPT_PREFIX",
        "spec_decode": "SPEC_DECODE",
        "priority_default": "PRIORITY_DEFAULT",
        "fleet_route": "FLEET_ROUTE",
        "fleet_tp_groups": "FLEET_TP_GROUPS",
        "fault_spec": "FAULT_SPEC",
        "log_format": "LOG_FORMAT",
        "profile_dir": "PROFILE_DIR",
        "journal_dir": "JOURNAL_DIR",
        "journal_fsync": "JOURNAL_FSYNC",
        "tenants": "TENANTS",
        "tenants_file": "TENANTS_FILE",
        "adapter_dir": "ADAPTER_DIR",
        "compile_cache_dir": "COMPILE_CACHE_DIR",
        "latency_buckets": "LATENCY_BUCKETS",
        "slo_windows_s": "SLO_WINDOWS_S",
        "pallas_variant": "PALLAS_VARIANT",
    }
    for field, var in mapping.items():
        v = get(var)
        if v is not None:
            kwargs[field] = v
    int_mapping = {
        "port": "PORT",
        "max_batch": "MAX_BATCH",
        "max_queue": "MAX_QUEUE",
        "replicas": "REPLICAS",
        "sp": "SP",
        "tp": "TP",
        "max_decode_len": "MAX_DECODE_LEN",
        "pipeline_depth": "PIPELINE_DEPTH",
        "max_streams": "MAX_STREAMS",
        "spec_k": "SPEC_K",
        "spec_ngram": "SPEC_NGRAM",
        "spec_max_streams": "SPEC_MAX_STREAMS",
        "stream_pipeline": "STREAM_PIPELINE",
        "class_weight": "CLASS_WEIGHT",
        "max_stream_queue": "MAX_STREAM_QUEUE",
        "kv_block_size": "KV_BLOCK_SIZE",
        "kv_prefetch_blocks": "KV_PREFETCH_BLOCKS",
        "job_max_concurrent_lines": "JOB_MAX_CONCURRENT_LINES",
        "tenant_metrics_topk": "TENANT_METRICS_TOPK",
        "adapter_slots": "ADAPTER_SLOTS",
        "prefill_chunk": "PREFILL_CHUNK",
        "prefill_budget": "PREFILL_BUDGET",
        "prefill_max_prompt": "PREFILL_MAX_PROMPT",
        "decode_window": "DECODE_WINDOW",
        "fleet_min_replicas": "FLEET_MIN_REPLICAS",
        "fleet_max_replicas": "FLEET_MAX_REPLICAS",
        "fault_seed": "FAULT_SEED",
        "dispatch_retries": "DISPATCH_RETRIES",
        "engine_restarts_max": "ENGINE_RESTARTS_MAX",
        "fleet_replicas": "FLEET_REPLICAS",
        "fleet_breaker_n": "FLEET_BREAKER_N",
        "trace_ring": "TRACE_RING",
        "flight_ring": "FLIGHT_RING",
        "pallas_single_block_max_seq": "PALLAS_SINGLE_BLOCK_MAX_SEQ",
        "decode_kernel_vmem_budget_mb": "DECODE_KERNEL_VMEM_BUDGET_MB",
    }
    for field, var in int_mapping.items():
        v = get(var)
        if v is not None:
            kwargs[field] = int(v)
    v = get("BATCH_TIMEOUT_MS")
    if v is not None:
        kwargs["batch_timeout_ms"] = float(v)
    v = get("REGISTER_HEARTBEAT_S")
    if v is not None:
        kwargs["register_heartbeat_s"] = float(v)
    for field, var in (
        ("deadline_ms", "DEADLINE_MS"),
        ("kv_budget_mb", "KV_BUDGET_MB"),
        ("kv_host_budget_mb", "KV_HOST_BUDGET_MB"),
        ("kv_disk_budget_mb", "KV_DISK_BUDGET_MB"),
        ("job_result_ttl_s", "JOB_RESULT_TTL_S"),
        ("tenant_default_weight", "TENANT_DEFAULT_WEIGHT"),
        ("tenant_window_s", "TENANT_WINDOW_S"),
        ("drain_grace_s", "DRAIN_GRACE_S"),
        ("dispatch_timeout_s", "DISPATCH_TIMEOUT_S"),
        ("dispatch_backoff_s", "DISPATCH_BACKOFF_S"),
        ("fleet_evict_s", "FLEET_EVICT_S"),
        ("scale_up_queue", "SCALE_UP_QUEUE"),
        ("scale_up_kv_frac", "SCALE_UP_KV_FRAC"),
        ("scale_up_ttft_ms", "SCALE_UP_TTFT_MS"),
        ("scale_up_cooldown_s", "SCALE_UP_COOLDOWN_S"),
        ("scale_down_load", "SCALE_DOWN_LOAD"),
        ("scale_down_cooldown_s", "SCALE_DOWN_COOLDOWN_S"),
        ("scale_period_s", "SCALE_PERIOD_S"),
        ("engine_restart_window_s", "ENGINE_RESTART_WINDOW_S"),
        ("peak_tflops", "PEAK_TFLOPS"),
        ("slo_ttft_ms", "SLO_TTFT_MS"),
        ("slo_tbt_ms", "SLO_TBT_MS"),
        ("slo_batch_ttft_ms", "SLO_BATCH_TTFT_MS"),
        ("slo_batch_tbt_ms", "SLO_BATCH_TBT_MS"),
        ("slo_target", "SLO_TARGET"),
        ("scale_up_slo_burn", "SCALE_UP_SLO_BURN"),
    ):
        v = get(var)
        if v is not None:
            kwargs[field] = float(v)
    v = get("PERF_OBS")
    if v is not None:
        kwargs["perf_obs"] = v.lower() not in ("0", "false", "no")
    v = get("PREEMPT")
    if v is not None:
        kwargs["preempt"] = v.lower() not in ("0", "false", "no")
    v = get("HOST_PREP_DOUBLE")
    if v is not None:
        kwargs["host_prep_double"] = v.lower() not in ("0", "false", "no")
    v = get("DECODE_WINDOW_AUTO")
    if v is not None:
        kwargs["decode_window_auto"] = v.lower() not in ("0", "false", "no")
    v = get("PAGED_KV")
    if v is not None:
        kwargs["paged_kv"] = v.lower() not in ("0", "false", "no")
    v = get("PALLAS_AUTOTUNE")
    if v is not None:
        kwargs["pallas_autotune"] = v.lower() not in ("0", "false", "no")
    v = get("PALLAS_INTERPRET")
    if v is not None:
        kwargs["pallas_interpret"] = v.lower() not in ("0", "false", "no")
    v = get("JOBS_ENABLED")
    if v is not None:
        kwargs["jobs_enabled"] = v.lower() not in ("0", "false", "no")
    v = get("SUPERVISE")
    if v is not None:
        kwargs["supervise"] = v.lower() not in ("0", "false", "no")
    v = get("TRACE")
    if v is not None:
        kwargs["trace"] = v.lower() not in ("0", "false", "no")
    # Comma-separated bucket overrides, e.g. BATCH_BUCKETS=1,8,32 — used
    # to bound warmup compile time when only some shapes will be served.
    for field, var in (("batch_buckets", "BATCH_BUCKETS"), ("seq_buckets", "SEQ_BUCKETS")):
        v = get(var)
        if v is not None:
            buckets = tuple(int(x) for x in v.split(",") if x.strip())
            if not buckets:
                raise ValueError(f"{var}={v!r} parsed to no buckets")
            kwargs[field] = buckets
    v = get("WARMUP")
    if v is not None:
        kwargs["warmup"] = v.lower() not in ("0", "false", "no")
    v = get("CONTINUOUS_BATCHING")
    if v is not None:
        kwargs["continuous_batching"] = v.lower() not in ("0", "false", "no")
    v = get("PREFIX_CACHE")
    if v is not None:
        kwargs["prefix_cache"] = v.lower() not in ("0", "false", "no")
    v = get("SPEC_SAMPLED")
    if v is not None:
        kwargs["spec_sampled"] = v.lower() not in ("0", "false", "no")
    v = get("SPEC_CONTINUOUS")
    if v is not None:
        kwargs["spec_continuous"] = v.lower() not in ("0", "false", "no")
    v = get("PREFIX_CACHE_MB")
    if v is not None:
        kwargs["prefix_cache_mb"] = float(v)
    return ServiceConfig(**kwargs)
