from .config import ServiceConfig, load_config

__all__ = ["ServiceConfig", "load_config"]
