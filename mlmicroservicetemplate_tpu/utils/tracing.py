"""Request-level span tracing + the engine flight recorder.

Two instruments, both import-safe and OFF by default (mirroring the
``metrics.py`` stub pattern — no OpenTelemetry or any other hard
dependency):

- **Span tracer** (``TRACE=1``): lightweight wall-clock spans opened at
  the serving layers' seams — the HTTP request (keyed by
  ``X-Request-Id``), admission/classify, queue wait, each prefill
  window, each decode chunk, and every ``dispatch_guard`` site (with
  the host submit→return vs device ``block_until_ready`` split) — kept
  in a bounded ring (``TRACE_RING``) and exported as Chrome
  trace-event JSON from ``GET /debug/trace`` (loadable in Perfetto or
  ``chrome://tracing``).  When off, the module-level tracer is ``None``
  and every call site takes a no-allocation fast path: ``span()``
  returns one shared no-op context manager, so the decode hot loop
  never constructs a span object (pinned by test).

  When ON, dispatch spans additionally ``block_until_ready`` the
  dispatch result to attribute device time — which serializes the
  chunk-chain pipeline.  TRACE=1 is an attribution mode, not a
  production default; the A/B cost is recorded in BASELINE.md.

- **Flight recorder** (``FLIGHT_RING``, default on): a bounded ring of
  the engine loop's last N iterations (batch composition, slot
  occupancy, KV pool state) plus scheduling/fault events (admission
  sheds, pacer holds, preemptions, dispatch retries/timeouts, engine
  restarts).  It dumps automatically on fatal faults — the supervisor
  snapshots the ring the moment it grants (or refuses) a restart, so
  the post-mortem shows the iterations that LED to the fault — and on
  demand via ``GET /debug/engine``.

Timestamps use ``time.monotonic()`` throughout (the same base the
scheduler stamps ``t_in`` with), anchored to wall-clock once at
configure time so trace events correlate with log lines.
"""

from __future__ import annotations

import collections
import json
import logging
import threading
import time

log = logging.getLogger(__name__)

_now = time.monotonic


# ---------------------------------------------------------------------------
# span tracer


class _NoopSpan:
    """Shared do-nothing span: the TRACE=0 hot path enters/exits this
    singleton instead of allocating anything."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **kw):
        return self


NOOP = _NoopSpan()


class Span:
    """One timed interval.  Use as a context manager (records itself on
    exit) or via ``Tracer.add`` for after-the-fact intervals (queue
    wait, whose start predates the pop that observes it)."""

    __slots__ = (
        "name", "cat", "rid", "t0", "dur", "tid", "sid", "parent", "args",
        "_tracer",
    )

    def __init__(self, tracer, name: str, cat: str, rid: str, args: dict):
        self.name = name
        self.cat = cat
        self.rid = rid
        self.args = args
        self.t0 = _now()
        self.dur = 0.0
        self.tid = threading.get_ident()
        self.sid = tracer._next_sid()
        self.parent = 0
        self._tracer = tracer

    def set(self, **kw) -> "Span":
        self.args.update(kw)
        return self

    def __enter__(self) -> "Span":
        stack = self._tracer._stack()
        if stack:
            self.parent = stack[-1].sid
        stack.append(self)
        return self

    def __exit__(self, etype, exc, tb):
        self.dur = _now() - self.t0
        stack = self._tracer._stack()
        if stack and stack[-1] is self:
            stack.pop()
        if etype is not None:
            self.args.setdefault("error", f"{etype.__name__}: {exc}")
        self._tracer._record(self)
        return False


class Tracer:
    """Bounded ring of completed spans.  Thread-safe appends; parenting
    is per-thread (a span opened inside another on the same thread gets
    its ``parent`` sid), cross-thread correlation rides the request id."""

    def __init__(self, ring: int = 4096):
        self.ring = max(16, int(ring))
        self._spans: collections.deque = collections.deque(maxlen=self.ring)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._sid = 0
        self.spans_created = 0
        self.t_anchor = _now()
        self.wall_anchor = time.time()

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _next_sid(self) -> int:
        with self._lock:
            self._sid += 1
            self.spans_created += 1
            return self._sid

    def _record(self, sp: Span) -> None:
        with self._lock:
            self._spans.append(sp)

    # -- producer API ---------------------------------------------------

    def span(self, name: str, cat: str = "app", rid: str = "", **args) -> Span:
        return Span(self, name, cat, rid, args)

    def add(self, name: str, cat: str = "app", rid: str = "",
            t0: float | None = None, dur: float | None = None,
            **args) -> None:
        """Record a completed interval: ``[t0, t0+dur]`` (dur defaults
        to now−t0).  No parenting — these are after-the-fact spans."""
        sp = Span(self, name, cat, rid, args)
        if t0 is not None:
            sp.t0 = t0
        sp.dur = dur if dur is not None else max(0.0, _now() - sp.t0)
        self._record(sp)

    def instant(self, name: str, cat: str = "app", rid: str = "",
                **args) -> None:
        """Zero-duration marker event."""
        self.add(name, cat, rid, dur=0.0, **args)

    # -- consumer API ---------------------------------------------------

    def snapshot(self, last: int | None = None) -> list[Span]:
        with self._lock:
            spans = list(self._spans)
        return spans[-last:] if last else spans

    def chrome_trace(self, last: int | None = None) -> dict:
        """Chrome trace-event JSON (Perfetto / chrome://tracing).  Spans
        become ``ph:"X"`` complete events; zero-duration spans become
        ``ph:"i"`` instants.  ``ts`` is µs since the tracer anchor."""
        spans = self.snapshot(last)
        tids: dict[int, int] = {}
        events: list[dict] = []
        for sp in spans:
            tid = tids.setdefault(sp.tid, len(tids) + 1)
            args = dict(sp.args)
            if sp.rid:
                args["request_id"] = sp.rid
            if sp.parent:
                args["parent_sid"] = sp.parent
            args["sid"] = sp.sid
            ev = {
                "name": sp.name,
                "cat": sp.cat,
                "pid": 1,
                "tid": tid,
                "ts": round((sp.t0 - self.t_anchor) * 1e6, 3),
                "args": args,
            }
            if sp.dur > 0.0:
                ev["ph"] = "X"
                ev["dur"] = round(sp.dur * 1e6, 3)
            else:
                ev["ph"] = "i"
                ev["s"] = "t"
            events.append(ev)
        meta = [
            {"name": "process_name", "ph": "M", "pid": 1,
             "args": {"name": "mlmicroservicetemplate-tpu"}},
        ]
        for raw, tid in tids.items():
            meta.append({
                "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
                "args": {"name": f"thread-{raw}"},
            })
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {
                "wall_anchor": self.wall_anchor,
                "spans_created": self.spans_created,
                "ring": self.ring,
            },
        }


_TRACER: Tracer | None = None


def tracer() -> Tracer | None:
    """The process tracer, or None when TRACE=0 (the zero-overhead
    check every hot path makes first)."""
    return _TRACER


def configure(enabled: bool, ring: int = 4096) -> Tracer | None:
    """Install (or remove) the process tracer.  Serving calls this at
    startup from the TRACE/TRACE_RING knobs; tests call it directly.
    Enabling replaces any existing tracer (fresh ring)."""
    global _TRACER
    _TRACER = Tracer(ring) if enabled else None
    return _TRACER


def span(name: str, cat: str = "app", rid: str = "", **args):
    """Convenience: a context-manager span, or the shared no-op when
    tracing is off.  NOTE: kwargs are evaluated by the caller either
    way — hot paths that build expensive args should check ``tracer()``
    themselves."""
    tr = _TRACER
    if tr is None:
        return NOOP
    return tr.span(name, cat, rid, **args)


# ---------------------------------------------------------------------------
# flight recorder


class FlightRecorder:
    """Bounded ring of engine-loop iteration snapshots + discrete
    events, dumped on fatal faults and served at ``GET /debug/engine``.

    ``size=0`` disables recording (``record_iteration``/``event``
    return immediately); ``dump`` still works (empty rings)."""

    def __init__(self, size: int = 256):
        self.size = max(0, int(size))
        cap = self.size or 1
        self._iters: collections.deque = collections.deque(maxlen=cap)
        self._events: collections.deque = collections.deque(maxlen=cap)
        self._lock = threading.Lock()
        self.last_dump: dict | None = None
        self.dumps = 0

    def record_iteration(self, **fields) -> None:
        if not self.size:
            return
        fields["t"] = round(_now(), 4)
        with self._lock:
            self._iters.append(fields)

    def event(self, kind: str, **fields) -> None:
        if not self.size:
            return
        fields["event"] = kind
        fields["t"] = round(_now(), 4)
        with self._lock:
            self._events.append(fields)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "size": self.size,
                "iterations": list(self._iters),
                "events": list(self._events),
                "dumps": self.dumps,
                "last_dump": self.last_dump,
            }

    def dump(self, reason: str) -> dict:
        """Snapshot the rings into ``last_dump`` and log it as ONE
        structured JSON line — the post-mortem a fatal fault leaves
        behind even if nobody ever curls /debug/engine."""
        with self._lock:
            snap = {
                "reason": reason,
                "t": round(_now(), 4),
                "wall": time.time(),
                "iterations": list(self._iters),
                "events": list(self._events),
            }
            self.last_dump = snap
            self.dumps += 1
        try:
            log.error(
                "engine flight recorder dump: %s",
                json.dumps(snap, default=str),
            )
        except Exception:  # a dump must never raise into recovery
            log.exception("flight recorder dump serialization failed")
        return snap


# ---------------------------------------------------------------------------
# structured JSON logs


class JsonLogFormatter(logging.Formatter):
    """One JSON object per log line (``LOG_FORMAT=json``): timestamp,
    level, logger, message, and — when the record carries one (via
    ``extra={"request_id": ...}``) — the request id, so log lines
    join against spans and the HTTP error bodies on the same key."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 4),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        rid = getattr(record, "request_id", None)
        if rid:
            out["request_id"] = rid
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)
