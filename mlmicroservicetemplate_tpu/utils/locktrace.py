"""Opt-in runtime lock-order detector (``LOCKTRACE=1``).

The serving stack is heavily threaded — decode-loop threads, the
batcher's executor, watchdog dispatch threads, the scaling governor,
failover callbacks — and its lock discipline is enforced by review
only.  This module makes it enforceable at runtime: with
``LOCKTRACE=1`` every lock created through ``threading.Lock`` /
``threading.RLock`` (and therefore ``threading.Condition``'s default)
is wrapped to record the per-thread acquisition graph, and two
violation classes are flagged:

- **lock-order inversion**: thread A acquired L2 while holding L1,
  and (now) some thread acquires L1 while holding L2 — the classic
  deadlock potential, caught on the *edge*, long before a real
  interleaving wedges the fleet;
- **lock held across a dispatch boundary**: a lock is held while
  ``dispatch_guard`` submits device work.  A relay RTT (or a watchdog
  deadline) under a lock stalls every thread that needs it; only
  explicitly allowed locks (the engine's own dispatch-serialization
  lock, registered via ``allow_across_dispatch``) may do this.

Violations are RECORDED, not raised: raising inside ``acquire`` would
corrupt the very invariants being watched.  The chaos stages assert
``violations() == []`` after each test (tests/conftest.py), and
``scripts/check.sh`` runs the fleet/scale smokes under ``LOCKTRACE=1``.

Zero overhead when off: nothing is patched, ``tracer()`` is None, and
the single ``is_active()`` check in ``dispatch_guard`` is a module
attribute read.

Usage::

    LOCKTRACE=1 python -m pytest tests/ -m chaos ...

    from mlmicroservicetemplate_tpu.utils import locktrace
    locktrace.install()          # or LOCKTRACE=1 + auto_install()
    ...
    assert not locktrace.violations()
"""

from __future__ import annotations

import _thread
import itertools
import os
import sys
import threading

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

_tracer: "LockTracer | None" = None


def _creation_site() -> str:
    """First stack frame outside this module — the lock's identity in
    reports (``engine/engine.py:85``)."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if "locktrace" not in fn and "threading" not in fn:
            short = fn
            for marker in ("mlmicroservicetemplate_tpu", "tests",
                           "benchmarks", "tools"):
                idx = fn.find(marker)
                if idx >= 0:
                    short = fn[idx:]
                    break
            return f"{short}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


class LockTracer:
    """Acquisition-graph recorder shared by every traced lock."""

    def __init__(self):
        # Raw (untraced) lock for the tracer's own state — the wrapper
        # classes must never recurse into themselves.
        self._raw = _thread.allocate_lock()
        self._uid = itertools.count(1)
        self._names: dict[int, str] = {}
        # held[tid] = [uid, ...] in acquisition order (RLock levels
        # push/pop like distinct holds; self-edges are skipped).
        self._held: dict[int, list[int]] = {}
        # edges[a] = {b, ...}: some thread acquired b while holding a.
        self._edges: dict[int, set[int]] = {}
        self._seen_pairs: set[tuple[int, int]] = set()
        self._seen_dispatch: set[tuple[int, str]] = set()
        self._allowed_across: set[int] = set()
        self.violation_list: list[dict] = []

    # -- wrapper callbacks --------------------------------------------

    def register(self, lock) -> int:
        uid = next(self._uid)
        with self._raw:
            self._names[uid] = lock._lt_name
        return uid

    def note_acquire(self, lock) -> None:
        tid = _thread.get_ident()
        uid = lock._lt_uid
        with self._raw:
            held = self._held.setdefault(tid, [])
            for h in held:
                if h == uid:
                    continue  # RLock re-entry: no self-edge
                self._check_edge_locked(h, uid)
            held.append(uid)

    def note_release(self, lock) -> None:
        tid = _thread.get_ident()
        uid = lock._lt_uid
        with self._raw:
            held = self._held.get(tid)
            if held:
                # Remove the LAST occurrence (LIFO is the common case,
                # but out-of-order releases are legal for Locks).
                for i in range(len(held) - 1, -1, -1):
                    if held[i] == uid:
                        del held[i]
                        break

    def note_dispatch(self, site: str) -> None:
        """Called at dispatch_guard entry on the dispatching thread:
        flags locks held across the device-dispatch boundary."""
        tid = _thread.get_ident()
        with self._raw:
            held = self._held.get(tid, [])
            for uid in held:
                if uid in self._allowed_across:
                    continue
                key = (uid, site)
                if key in self._seen_dispatch:
                    continue
                self._seen_dispatch.add(key)
                self.violation_list.append({
                    "kind": "held_across_dispatch",
                    "lock": self._names.get(uid, "?"),
                    "site": site,
                    "detail": (
                        f"lock {self._names.get(uid, '?')} held across "
                        f"dispatch_guard({site!r}) — a relay RTT under "
                        f"this lock stalls every thread that needs it "
                        f"(allow_across_dispatch() if deliberate)"
                    ),
                })

    def allow_across_dispatch(self, lock) -> None:
        uid = getattr(lock, "_lt_uid", None)
        if uid is None:
            return  # untraced (created before install, or LOCKTRACE=0)
        with self._raw:
            self._allowed_across.add(uid)

    # -- graph --------------------------------------------------------

    def _check_edge_locked(self, a: int, b: int) -> None:
        """Record edge a→b; flag an inversion if b→…→a already exists."""
        succ = self._edges.setdefault(a, set())
        if b in succ:
            return
        if self._reachable_locked(b, a):
            pair = (min(a, b), max(a, b))
            if pair not in self._seen_pairs:
                self._seen_pairs.add(pair)
                self.violation_list.append({
                    "kind": "lock_order_inversion",
                    "locks": [self._names.get(a, "?"),
                              self._names.get(b, "?")],
                    "detail": (
                        f"acquiring {self._names.get(b, '?')} while "
                        f"holding {self._names.get(a, '?')}, but the "
                        f"opposite order was also observed — deadlock "
                        f"potential"
                    ),
                })
        succ.add(b)

    def _reachable_locked(self, src: int, dst: int) -> bool:
        seen = set()
        stack = [src]
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(self._edges.get(n, ()))
        return False


class _TracedLock:
    """threading.Lock wrapper feeding the tracer."""

    _lt_rlock = False

    def __init__(self):
        self._inner = _REAL_LOCK()
        self._lt_name = _creation_site()
        self._lt_uid = _tracer.register(self) if _tracer else 0

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok and _tracer is not None:
            _tracer.note_acquire(self)
        return ok

    def release(self):
        if _tracer is not None:
            _tracer.note_release(self)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def __getattr__(self, name):
        # Delegate everything else (e.g. _at_fork_reinit, which
        # concurrent.futures registers with os.register_at_fork) to
        # the real lock.  Only reached when normal lookup fails, so
        # the tracked acquire/release above always win.
        return getattr(self._inner, name)

    def __repr__(self):
        return f"<TracedLock {self._lt_name}>"


class _TracedRLock(_TracedLock):
    """threading.RLock wrapper; forwards the Condition protocol so
    ``Condition(RLock())`` waits release/re-acquire through the
    tracer's bookkeeping."""

    _lt_rlock = True

    def __init__(self):
        self._inner = _REAL_RLOCK()
        self._lt_name = _creation_site()
        self._lt_uid = _tracer.register(self) if _tracer else 0

    def locked(self):  # RLock has no .locked() pre-3.12
        locked = getattr(self._inner, "locked", None)
        return locked() if locked else False

    # Condition protocol (threading.Condition probes these).
    def _release_save(self):
        if _tracer is not None:
            _tracer.note_release(self)
        return self._inner._release_save()

    def _acquire_restore(self, state):
        self._inner._acquire_restore(state)
        if _tracer is not None:
            _tracer.note_acquire(self)

    def _is_owned(self):
        return self._inner._is_owned()


def install() -> None:
    """Patch ``threading.Lock``/``RLock`` so every lock created from
    now on is traced.  Locks created earlier stay raw (and silent)."""
    global _tracer
    if _tracer is not None:
        return
    _tracer = LockTracer()
    threading.Lock = _TracedLock
    threading.RLock = _TracedRLock


def uninstall() -> None:
    """Restore the real factories.  Existing traced locks keep working
    (their inner locks are real); they just stop reporting."""
    global _tracer
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    _tracer = None


def auto_install() -> bool:
    """Install iff LOCKTRACE=1 in the environment (serve.py/conftest)."""
    if os.environ.get("LOCKTRACE", "0").lower() not in ("0", "false", ""):
        install()
        return True
    return False


def tracer() -> LockTracer | None:
    return _tracer


def is_active() -> bool:
    return _tracer is not None


def note_dispatch(site: str) -> None:
    """Engine hook: called at every dispatch_guard entry (no-op off)."""
    if _tracer is not None:
        _tracer.note_dispatch(site)


def allow_across_dispatch(lock) -> None:
    """Mark one lock as legitimately held across dispatch boundaries
    (the engine's dispatch-serialization lock)."""
    if _tracer is not None:
        _tracer.allow_across_dispatch(lock)


def violations() -> list[dict]:
    return list(_tracer.violation_list) if _tracer is not None else []


def reset() -> None:
    if _tracer is not None:
        _tracer.violation_list.clear()
        _tracer._seen_pairs.clear()
        _tracer._seen_dispatch.clear()
        _tracer._edges.clear()
