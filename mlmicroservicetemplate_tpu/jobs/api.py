"""The ``/v1/batches`` HTTP surface (docs/bulk-inference.md).

Four endpoints over the durable job subsystem:

- ``POST /v1/batches``            — submit a job: a JSON body with a
  ``lines`` array, or a raw JSONL body (``application/x-ndjson`` /
  ``text/plain``) with one /predict-shaped object per line.  Each line
  is validated by the SAME parser interactive requests go through, and
  sampled lines get their seed pinned here so crash re-runs are
  deterministic.  An ``Idempotency-Key`` header (or body field) dedups
  retried submissions onto the first job.
- ``GET  /v1/batches``            — list jobs.
- ``GET  /v1/batches/{id}``       — job status + line counts.
- ``GET  /v1/batches/{id}/results`` — completed lines as ndjson (one
  ``{"line", "text", "tokens", "finish_reason"}`` object per line, in
  index order; partial while the job runs).
- ``POST /v1/batches/{id}/cancel`` — stop at the next chunk boundary.

Routes register only when the Batcher built a JobManager
(``JOBS_ENABLED=1``); with the knob unset this module is never
imported and the HTTP surface is bit-identical to pre-jobs serving.
"""

from __future__ import annotations

import json
import logging
import random

from aiohttp import web

log = logging.getLogger(__name__)

K_JOBS = web.AppKey("jobs", object)


def add_job_routes(app: web.Application, manager) -> None:
    app[K_JOBS] = manager
    app.router.add_post("/v1/batches", handle_submit)
    app.router.add_get("/v1/batches", handle_list)
    app.router.add_get("/v1/batches/{jid}", handle_get)
    app.router.add_get("/v1/batches/{jid}/results", handle_results)
    app.router.add_post("/v1/batches/{jid}/cancel", handle_cancel)


def _parse_line(obj, idx: int) -> dict:
    """One JSONL line → the validated, seed-pinned manifest entry.
    Reuses the /predict JSON validator so a job line accepts exactly
    the fields an interactive request would."""
    from ..api.app import _parse_json_item

    if isinstance(obj, str):
        obj = {"text": obj}
    if not isinstance(obj, dict):
        raise web.HTTPBadRequest(
            reason=f"line {idx}: each line must be a JSON object or string"
        )
    try:
        item = _parse_json_item(dict(obj))
    except web.HTTPBadRequest as e:
        raise web.HTTPBadRequest(reason=f"line {idx}: {e.reason}")
    seed = item.seed
    if item.temperature > 0.0 and seed is None:
        # Pin the sampling seed at SUBMIT, not at execution: a line
        # re-run after a crash must reproduce the exact result the
        # first attempt would have journaled.
        seed = random.getrandbits(32)
    return {
        "text": item.text,
        "temperature": item.temperature,
        "top_k": item.top_k,
        "top_p": item.top_p,
        "seed": seed,
        "max_tokens": item.max_tokens,
        "stop": list(item.stop),
    }


async def _parse_lines(request: web.Request) -> tuple[list[dict], str | None]:
    """(validated lines, idempotency key) from either body shape."""
    key = request.headers.get("Idempotency-Key")
    ctype = request.content_type
    if ctype == "application/json":
        try:
            body = await request.json()
        except json.JSONDecodeError:
            raise web.HTTPBadRequest(reason="invalid JSON body")
        if not isinstance(body, dict) or not isinstance(
            body.get("lines"), list
        ):
            raise web.HTTPBadRequest(
                reason='JSON body needs a "lines" array '
                       "(or POST raw JSONL)"
            )
        key = key or body.get("idempotency_key")
        raw = body["lines"]
    else:
        text = (await request.read()).decode("utf-8", "replace")
        raw = []
        for ln in text.splitlines():
            ln = ln.strip()
            if not ln:
                continue
            try:
                raw.append(json.loads(ln))
            except json.JSONDecodeError:
                raise web.HTTPBadRequest(
                    reason=f"line {len(raw)}: invalid JSON"
                )
    if not raw:
        raise web.HTTPBadRequest(reason="job has no lines")
    lines = [_parse_line(obj, i) for i, obj in enumerate(raw)]
    return lines, (str(key) if key else None)


async def handle_submit(request: web.Request) -> web.Response:
    from ..api.app import K_BATCHER, _shed_response
    from ..scheduler.policy import QueueFullError

    manager = request.app[K_JOBS]
    batcher = request.app[K_BATCHER]
    if batcher.draining:
        # Jobs are claimed work, not queued HTTP: a draining server
        # must not accept a manifest it will never run.
        raise _shed_response(QueueFullError(
            "server is draining", reason="drain", retry_after_s=5.0
        ))
    lines, key = await _parse_lines(request)
    try:
        job, created = manager.submit(lines, key=key)
    except ValueError as e:
        raise web.HTTPBadRequest(reason=str(e))
    return web.json_response(job.to_json(), status=200 if not created else 201)


async def handle_list(request: web.Request) -> web.Response:
    manager = request.app[K_JOBS]
    manager.store.sweep()
    return web.json_response({
        "object": "list",
        "data": [j.to_json() for j in manager.store.list()],
    })


def _job_or_404(request: web.Request):
    manager = request.app[K_JOBS]
    jid = request.match_info["jid"]
    job = manager.store.get(jid)
    if job is None:
        raise web.HTTPNotFound(reason=f"unknown job {jid!r}")
    return manager, job


async def handle_get(request: web.Request) -> web.Response:
    _manager, job = _job_or_404(request)
    return web.json_response(job.to_json())


async def handle_results(request: web.Request) -> web.StreamResponse:
    _manager, job = _job_or_404(request)
    resp = web.StreamResponse(
        status=200,
        headers={"Content-Type": "application/x-ndjson",
                 "X-Job-Status": job.state},
    )
    resp.enable_chunked_encoding()
    await resp.prepare(request)
    try:
        for i in sorted(job.results):
            r = job.results[i]
            row = {
                "line": i, "text": r["text"], "tokens": r["tokens"],
                "finish_reason": r["finish"],
            }
            if r.get("error"):
                row["error"] = r["error"]
            await resp.write((json.dumps(row) + "\n").encode())
    except ConnectionError:
        pass  # client gone; results persist for the next fetch
    finally:
        try:
            await resp.write_eof()
        except ConnectionError:
            pass
    return resp


async def handle_cancel(request: web.Request) -> web.Response:
    manager, job = _job_or_404(request)
    job = manager.cancel(job.id) or job
    return web.json_response(job.to_json())
