"""Durable bulk-inference job state (the ``/v1/batches`` backbone).

BatchGen (arXiv 2606.21712) makes durable job state the backbone of
scalable batch inference: a bulk job is not a pile of HTTP requests but
a MANIFEST — thousands of prompt lines — whose per-line progress
outlives any single process.  This module is that backbone, built on
the same write-ahead machinery as the stream journal
(``runtime/durability.py``): every record is one JSON object framed by
a ``<u32 length><u32 crc32>`` header in append-only segments under
``JOURNAL_DIR/jobs``, torn tails truncate at replay, and open-time
compaction keeps replay cost proportional to LIVE state.

Record kinds:

- ``job``    — the manifest: id, idempotency key, created time, and
  every line's VALIDATED generation params (text, sampling fields
  with the seed pinned at submit so re-runs are deterministic).
  Written before the submit response goes out.
- ``line``   — one completed line's result (text, token count, finish
  reason, optional error).  Written BEFORE the in-memory state counts
  the line complete (write-ahead), so a ``kill -9`` can lose at most
  in-flight lines — which re-run to the same result — never recorded
  ones.  Exactly-once: a duplicate ``line_done`` is refused in memory
  and never appended.
- ``state``  — job status transitions (queued → running → completed |
  cancelled) with the terminal timestamp for TTL accounting.
- ``purge``  — TTL tombstone: the job's records are skipped at the
  next compaction.

The store is process-local state the ``JobManager`` (executor.py)
drives; one process owns the directory at a time — the parent
``StreamJournal``'s flock on ``JOURNAL_DIR`` already guarantees that
when the store lives in its standard location.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid

from ..runtime.durability import append_frame, read_frames
from ..utils import metrics

log = logging.getLogger(__name__)

QUEUED = "queued"
RUNNING = "running"
COMPLETED = "completed"
CANCELLED = "cancelled"
#: States a startup replay re-admits (anything non-terminal).
ACTIVE_STATES = (QUEUED, RUNNING)

_FSYNC_INTERVAL_S = 0.05
#: Hard cap on lines per job — bounds one manifest record's size.
MAX_LINES = 10_000


class Job:
    """One bulk job: the manifest plus per-line results."""

    __slots__ = ("id", "key", "created", "lines", "results", "state",
                 "done_at")

    def __init__(self, jid: str, key: str | None, created: float,
                 lines: list[dict]):
        self.id = jid
        self.key = key
        self.created = float(created)
        self.lines = lines
        #: line index -> {"text", "tokens", "finish", ("error")}
        self.results: dict[int, dict] = {}
        self.state = QUEUED
        self.done_at: float | None = None

    @property
    def total(self) -> int:
        return len(self.lines)

    @property
    def terminal(self) -> bool:
        return self.state in (COMPLETED, CANCELLED)

    def remaining(self) -> list[int]:
        """Line indices with no recorded result — the resume work-list."""
        return [i for i in range(self.total) if i not in self.results]

    def counts(self) -> dict:
        failed = sum(1 for r in self.results.values() if r.get("error"))
        return {
            "total": self.total,
            "completed": len(self.results) - failed,
            "failed": failed,
        }

    def to_json(self) -> dict:
        """The API object shape (GET /v1/batches/{id})."""
        body = {
            "id": self.id,
            "object": "batch",
            "status": self.state,
            "created_at": self.created,
            "line_counts": self.counts(),
        }
        if self.key:
            body["idempotency_key"] = self.key
        if self.done_at is not None:
            body["finished_at"] = self.done_at
        return body


class JobStore:
    """Crash-safe job/line/result store (see module docstring).

    Thread-safe like the stream journal: the executor appends line
    results from event-loop callbacks while HTTP handlers read job
    state; a lock keeps the in-memory maps and the append stream
    coherent.
    """

    def __init__(self, dir: str, fsync: str = "always", model: str = "",
                 ttl_s: float = 0.0):
        self.dir = dir
        self.fsync = str(fsync or "always").lower()
        self.model = model or "unknown"
        self.ttl_s = max(0.0, float(ttl_s or 0.0))
        self._lock = threading.RLock()
        self._last_fsync = 0.0
        self.records_written = 0
        self.torn_bytes = 0
        self.jobs: dict[str, Job] = {}
        self.by_key: dict[str, str] = {}
        os.makedirs(dir, exist_ok=True)
        segs = self._segments()
        purged: set[str] = set()
        for _, path in segs:
            frames, good = read_frames(path)
            sz = os.path.getsize(path)
            if good < sz:
                self.torn_bytes += sz - good
                log.warning(
                    "job store %s: torn tail (%d bytes) truncated at "
                    "replay", path, sz - good,
                )
            for payload in frames:
                try:
                    self._apply(json.loads(payload), purged)
                except Exception:
                    log.exception("job store: unreadable record skipped")
        # TTL expiry at open counts as a purge too.
        now = time.time()
        if self.ttl_s:
            for job in self.jobs.values():
                if job.terminal and job.done_at is not None and (
                    now - job.done_at >= self.ttl_s
                ):
                    purged.add(job.id)
        for jid in purged:
            job = self.jobs.pop(jid, None)
            if job is not None and job.key:
                self.by_key.pop(job.key, None)
        nxt = (segs[-1][0] + 1) if segs else 1
        self._path = os.path.join(dir, f"jobs-{nxt:06d}.log")
        self._f = open(self._path, "ab")
        self._compact_into_open_segment()
        for _, path in segs:
            try:
                os.unlink(path)
            except OSError:
                pass

    # -- replay --------------------------------------------------------

    def _segments(self) -> list[tuple[int, str]]:
        out = []
        for name in os.listdir(self.dir):
            if name.startswith("jobs-") and name.endswith(".log"):
                try:
                    out.append(
                        (int(name[5:-4]), os.path.join(self.dir, name))
                    )
                except ValueError:
                    pass
        return sorted(out)

    def _apply(self, rec: dict, purged: set[str]) -> None:
        k = rec.get("k")
        jid = str(rec.get("id", ""))
        if k == "job":
            job = Job(
                jid, rec.get("key") or None,
                float(rec.get("created", 0.0)),
                list(rec.get("lines", [])),
            )
            self.jobs[jid] = job
            if job.key:
                self.by_key[job.key] = jid
            purged.discard(jid)
        elif k == "line":
            job = self.jobs.get(jid)
            if job is not None:
                i = int(rec.get("i", -1))
                if 0 <= i < job.total:
                    row = {
                        "text": rec.get("text", ""),
                        "tokens": int(rec.get("tokens", 0)),
                        "finish": rec.get("finish", "stop"),
                    }
                    if rec.get("error"):
                        row["error"] = str(rec["error"])
                    # graftlint: write-ahead(replay reader — this record was already journaled on disk; _apply only materializes it)
                    job.results[i] = row
        elif k == "state":
            job = self.jobs.get(jid)
            if job is not None:
                job.state = str(rec.get("state", job.state))
                if "t" in rec:
                    job.done_at = float(rec["t"])
        elif k == "purge":
            purged.add(jid)

    def _compact_into_open_segment(self) -> None:
        with self._lock:
            for job in self.jobs.values():
                append_frame(self._f, (json.dumps({
                    "k": "job", "id": job.id, "key": job.key,
                    "created": job.created, "lines": job.lines,
                }) + "\n").encode())
                for i in sorted(job.results):
                    r = job.results[i]
                    append_frame(self._f, (json.dumps({
                        "k": "line", "id": job.id, "i": i, **r,
                    }) + "\n").encode())
                if job.state != QUEUED:
                    rec = {"k": "state", "id": job.id, "state": job.state}
                    if job.done_at is not None:
                        rec["t"] = job.done_at
                    append_frame(self._f, (json.dumps(rec) + "\n").encode())
            self._f.flush()
            os.fsync(self._f.fileno())

    # -- appends (write-ahead) -----------------------------------------

    def _append(self, rec: dict) -> None:
        payload = (json.dumps(rec, separators=(",", ":")) + "\n").encode()
        with self._lock:
            if self._f.closed:
                return
            append_frame(self._f, payload)
            self._f.flush()
            self.records_written += 1
            now = time.monotonic()
            if self.fsync == "always" or (
                self.fsync == "interval"
                and now - self._last_fsync >= _FSYNC_INTERVAL_S
            ):
                os.fsync(self._f.fileno())
                self._last_fsync = now

    # -- API -----------------------------------------------------------

    def create(self, lines: list[dict],
               key: str | None = None) -> tuple[Job, bool]:
        """Persist one job manifest; returns ``(job, created)``.

        ``created`` is False when ``key`` dedups onto an existing job —
        the idempotency contract: a retried POST (client timeout, LB
        replay) observes the FIRST submission instead of doubling the
        work, exactly like unary X-Request-Id dedup."""
        if not lines:
            raise ValueError("a job needs at least one line")
        if len(lines) > MAX_LINES:
            raise ValueError(
                f"{len(lines)} lines > MAX_LINES={MAX_LINES}"
            )
        with self._lock:
            if key:
                jid = self.by_key.get(key)
                if jid is not None and jid in self.jobs:
                    return self.jobs[jid], False
            jid = "job-" + uuid.uuid4().hex[:16]
            job = Job(jid, key, time.time(), lines)
            self.jobs[jid] = job
            if key:
                self.by_key[key] = jid
            self._append({
                "k": "job", "id": jid, "key": key,
                "created": job.created, "lines": lines,
            })
        return job, True

    def line_done(self, jid: str, i: int, text: str, tokens: int,
                  finish: str, error: str | None = None) -> bool:
        """Record one line's result exactly once (write-ahead: the
        append lands before the in-memory count moves).  False = the
        line already had a result (duplicate refused, nothing written)."""
        with self._lock:
            job = self.jobs.get(jid)
            if job is None or i in job.results:
                return False
            rec = {
                "k": "line", "id": jid, "i": int(i), "text": text,
                "tokens": int(tokens), "finish": finish,
            }
            if error:
                rec["error"] = error
            self._append(rec)
            row = {"text": text, "tokens": int(tokens), "finish": finish}
            if error:
                row["error"] = error
            job.results[int(i)] = row
        metrics.JOB_LINES.labels(
            self.model, "failed" if error else "completed"
        ).inc()
        return True

    def set_state(self, jid: str, state: str) -> None:
        with self._lock:
            job = self.jobs.get(jid)
            if job is None or job.state == state or job.terminal:
                return
            job.state = state
            rec = {"k": "state", "id": jid, "state": state}
            if state in (COMPLETED, CANCELLED):
                job.done_at = time.time()
                rec["t"] = job.done_at
            self._append(rec)

    def get(self, jid: str) -> Job | None:
        with self._lock:
            return self.jobs.get(jid)

    def list(self) -> list[Job]:
        with self._lock:
            return sorted(self.jobs.values(), key=lambda j: j.created)

    def sweep(self) -> int:
        """Purge completed/cancelled jobs older than ``ttl_s`` (0 =
        keep forever).  A ``purge`` tombstone makes the drop durable;
        the next open-time compaction reclaims the bytes."""
        if not self.ttl_s:
            return 0
        now = time.time()
        dropped = 0
        with self._lock:
            for jid in list(self.jobs):
                job = self.jobs[jid]
                if job.terminal and job.done_at is not None and (
                    now - job.done_at >= self.ttl_s
                ):
                    self._append({"k": "purge", "id": jid})
                    del self.jobs[jid]
                    if job.key:
                        self.by_key.pop(job.key, None)
                    dropped += 1
        return dropped

    def stats(self) -> dict:
        with self._lock:
            active = sum(
                1 for j in self.jobs.values() if j.state in ACTIVE_STATES
            )
            return {
                "dir": self.dir,
                "jobs_tracked": len(self.jobs),
                "jobs_active": active,
                "records_written": self.records_written,
                "torn_bytes_truncated": self.torn_bytes,
                "result_ttl_s": self.ttl_s,
            }

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                try:
                    os.fsync(self._f.fileno())
                except OSError:
                    pass
                self._f.close()
