"""Bulk-job executor: idle-compute backfill behind the SLA scheduler.

The ``JobManager`` turns durable job manifests (``store.py``) into
batch-class stream traffic through the EXISTING serving stack — each
claimed line is one headless stream submitted through
``Batcher.submit_stream`` with ``priority=batch`` and no deadline, so
every protection the interactive lane already has applies unchanged:

- the r7 deadline queue class-weights bulk lines behind interactive
  work and interactive arrivals PREEMPT bulk slot holders at chunk
  boundaries (checkpoint/resume, token-identical);
- the r10 pacer starves bulk prefill windows while interactive decode
  is live;
- admission charges each line against the shared KV ledger exactly
  like any other stream (paged mode: the exact block ledger).

On top of that ride the job-level policies: a per-job concurrency cap
(``JOB_MAX_CONCURRENT_LINES``) throttled further by the
``BackfillGovernor`` (scheduler/policy.py) whenever interactive work
is live or waiting, drain-aware claiming (a draining server finishes
in-flight lines but claims no new ones — the job resumes on the next
boot), shed-aware retry (a 503'd line backs off instead of burning
the shed counters in a loop), and cancellation at the next chunk
boundary.

Crash safety is the store's: a line's result is journaled write-ahead
before it counts as done, in-flight lines simply re-run after a
restart (their seeds were pinned at submit, so re-runs are
deterministic), and ``replay()`` — called from the app's startup hook
after warmup, exactly like the stream-journal replay — re-admits every
non-terminal job from its last completed line.  Job lines deliberately
carry NO request id: per-line durability lives in the job store, and a
stream-journal record would make the startup stream replay and the job
replay race to resume the same work.
"""

from __future__ import annotations

import asyncio
import logging

from ..models.registry import RawItem
from ..scheduler.policy import BackfillGovernor, QueueFullError
from ..utils import metrics
from .store import CANCELLED, COMPLETED, RUNNING, JobStore

log = logging.getLogger(__name__)

#: Backoff while the scheduler sheds bulk admissions (seconds).
_RETRY_MIN_S = 0.05
_RETRY_MAX_S = 2.0
#: Generic line failures retried before the error becomes the result.
_LINE_RETRIES = 2


class JobManager:
    """Owns the JobStore and the per-job executor tasks (event loop)."""

    def __init__(self, engine, batcher, cfg):
        import os

        jdir = getattr(cfg, "journal_dir", None)
        if not jdir:
            raise ValueError(
                "JOBS_ENABLED=1 requires JOURNAL_DIR (the job store "
                "rides the write-ahead journal machinery)"
            )
        if getattr(engine.bundle, "kind", None) != "seq2seq":
            raise ValueError(
                "JOBS_ENABLED=1 requires a generative (seq2seq) model"
            )
        self.engine = engine
        self.batcher = batcher
        self.bundle = engine.bundle
        self.model = engine.bundle.name
        self.store = JobStore(
            os.path.join(jdir, "jobs"),
            fsync=getattr(cfg, "journal_fsync", "always"),
            model=self.model,
            ttl_s=getattr(cfg, "job_result_ttl_s", 0.0),
        )
        self.max_lines = max(
            1, int(getattr(cfg, "job_max_concurrent_lines", 4) or 4)
        )
        self.governor = BackfillGovernor(self.max_lines)
        self._tasks: dict[str, asyncio.Task] = {}
        self._cancelled: set[str] = set()
        self.replayed: dict | None = None

    # -- lifecycle -----------------------------------------------------

    def submit(self, lines: list[dict], key: str | None = None):
        """Persist + launch one job (event loop).  Returns
        ``(job, created)`` — ``created`` False when the idempotency key
        dedup'd onto an existing job (no new work scheduled)."""
        job, created = self.store.create(lines, key=key)
        if created:
            self._launch(job)
        return job, created

    def cancel(self, jid: str):
        """Flip a job to ``cancelled`` (journaled) and stop its lines at
        the next chunk boundary.  Terminal jobs are left untouched."""
        job = self.store.get(jid)
        if job is None:
            return None
        if not job.terminal:
            unfinished = len(job.remaining())
            self._cancelled.add(jid)
            self.store.set_state(jid, CANCELLED)
            task = self._tasks.get(jid)
            if task is not None and not task.done():
                task.cancel()
            if unfinished:
                metrics.JOB_LINES.labels(self.model, "cancelled").inc(
                    unfinished
                )
        return job

    def replay(self) -> dict:
        """Startup re-admission (app startup hook, after warmup): every
        non-terminal job resumes from its last completed line; a job
        whose lines all finished before the kill is closed out here."""
        counts = {"resumed": 0, "complete": 0, "failed": 0}
        for job in self.store.list():
            if job.terminal:
                continue
            try:
                if not job.remaining():
                    self.store.set_state(job.id, COMPLETED)
                    counts["complete"] += 1
                else:
                    self._launch(job)
                    counts["resumed"] += 1
            except Exception:
                log.exception("job replay: could not resume %s", job.id)
                counts["failed"] += 1
        for outcome, n in counts.items():
            if n:
                metrics.JOB_REPLAYS.labels(self.model, outcome).inc(n)
        self.replayed = counts
        if counts["resumed"]:
            log.info(
                "job replay: %d incomplete job(s) re-admitted from "
                "their last completed line", counts["resumed"],
            )
        return counts

    async def stop(self) -> None:
        for task in list(self._tasks.values()):
            task.cancel()
        for task in list(self._tasks.values()):
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks.clear()
        self._note_active()
        self.store.close()

    def active_jobs(self) -> int:
        return sum(1 for t in self._tasks.values() if not t.done())

    def stats(self) -> dict:
        body = self.store.stats()
        body["executor_active"] = self.active_jobs()
        body["max_concurrent_lines"] = self.max_lines
        if self.replayed is not None:
            body["replay"] = self.replayed
        return body

    # -- executor ------------------------------------------------------

    def _note_active(self) -> None:
        metrics.JOBS_ACTIVE.labels(self.model).set(self.active_jobs())

    def _launch(self, job) -> None:
        task = asyncio.get_running_loop().create_task(self._run_job(job))
        self._tasks[job.id] = task
        task.add_done_callback(lambda _t: self._note_active())
        self._note_active()

    async def _run_job(self, job) -> None:
        self.store.set_state(job.id, RUNNING)
        pending = job.remaining()
        in_flight: set[asyncio.Task] = set()
        try:
            while pending or in_flight:
                if job.id in self._cancelled:
                    break
                # Drain-aware claiming: in-flight lines finish (the
                # batcher's drain gate waits for them), new claims stop
                # — the store resumes the remainder on the next boot.
                claiming = not self.batcher.draining
                target = self.governor.target(
                    *self.batcher.interactive_load()
                ) if claiming else 0
                while pending and len(in_flight) < target:
                    i = pending.pop(0)
                    in_flight.add(asyncio.get_running_loop().create_task(
                        self._run_line(job, i)
                    ))
                if not in_flight:
                    if not claiming:
                        return  # draining: leave the job resumable
                    # Interactive pressure left zero claim budget:
                    # wait it out without spinning.
                    await asyncio.sleep(_RETRY_MIN_S)
                    continue
                done, in_flight = await asyncio.wait(
                    in_flight, timeout=0.25,
                    return_when=asyncio.FIRST_COMPLETED,
                )
                for t in done:
                    exc = t.exception() if not t.cancelled() else None
                    if exc is not None:
                        raise exc
            if job.id not in self._cancelled and not job.remaining():
                self.store.set_state(job.id, COMPLETED)
                self.store.sweep()
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("job %s executor failed", job.id)
        finally:
            for t in in_flight:
                t.cancel()
            for t in in_flight:
                try:
                    await t
                except (asyncio.CancelledError, Exception):
                    pass

    def _line_item(self, line: dict) -> RawItem:
        return RawItem(
            text=str(line.get("text", "")), stream=True,
            temperature=float(line.get("temperature", 0.0) or 0.0),
            top_k=int(line.get("top_k", 0) or 0),
            top_p=float(line.get("top_p", 1.0) or 1.0),
            seed=(int(line["seed"]) if line.get("seed") is not None
                  else None),
            max_tokens=(int(line["max_tokens"])
                        if line.get("max_tokens") is not None else None),
            stop=tuple(line.get("stop") or ()),
        )

    async def _run_line(self, job, i: int) -> None:
        """One line, exactly once: preprocess → batch-class stream →
        result record.  Sheds retry with backoff (bulk has no deadline
        — it backfills whenever the scheduler has room); generation
        errors retry ``_LINE_RETRIES`` times, then the error IS the
        line's recorded result (the job still completes)."""
        # The delta machinery is the SAME one interactive streams use
        # (stop strings, max_tokens, finish_reason); lazy import keeps
        # scheduler → jobs → api acyclic at module load.
        from ..api.app import _delta_stream
        from ..engine.streams import StreamClosedError
        from ..scheduler.policy import DeadlineExceededError

        item = self._line_item(job.lines[i])
        loop = asyncio.get_running_loop()
        feats = await loop.run_in_executor(
            None, self.bundle.preprocess, item
        )
        feats["priority"] = "batch"
        feats["deadline_ms"] = 0.0  # bulk lines never 504
        if item.seed is not None:
            feats["seed"] = item.seed
        backoff = _RETRY_MIN_S
        failures = 0
        while True:
            if job.id in self._cancelled or self.batcher.draining:
                return
            adm = getattr(self.batcher, "admission", None)
            if adm is not None and not adm.backfill_ok():
                # Advisory headroom gate: defer the claim instead of
                # bouncing off admission as a metered shed.
                await asyncio.sleep(backoff)
                backoff = min(_RETRY_MAX_S, backoff * 2)
                continue
            try:
                gen = self.batcher.submit_stream(dict(feats))
            except QueueFullError as e:
                await asyncio.sleep(
                    min(_RETRY_MAX_S, e.retry_after_s or backoff)
                )
                backoff = min(_RETRY_MAX_S, backoff * 2)
                continue
            try:
                final = None
                async for ev in _delta_stream(self.bundle, gen, item):
                    if ev.get("done"):
                        final = ev
                if final is None:
                    raise RuntimeError("line stream produced no final event")
                self.store.line_done(
                    job.id, i, final["text"], final["tokens"],
                    final["finish_reason"],
                )
                return
            except (QueueFullError, DeadlineExceededError,
                    StreamClosedError) as e:
                # Shed mid-queue (eviction, drain race): retry later.
                await asyncio.sleep(
                    min(_RETRY_MAX_S,
                        getattr(e, "retry_after_s", None) or backoff)
                )
                backoff = min(_RETRY_MAX_S, backoff * 2)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                failures += 1
                if failures > _LINE_RETRIES:
                    log.exception(
                        "job %s line %d failed terminally", job.id, i
                    )
                    self.store.line_done(
                        job.id, i, "", 0, "error",
                        error=str(e) or type(e).__name__,
                    )
                    return
                await asyncio.sleep(backoff)
                backoff = min(_RETRY_MAX_S, backoff * 2)
            finally:
                try:
                    await gen.aclose()
                except Exception:
                    pass
