"""Bulk inference lane: durable ``/v1/batches`` jobs (JOBS_ENABLED).

``store.py`` persists job manifests and per-line results through the
write-ahead journal machinery (CRC-framed records under
``JOURNAL_DIR/jobs``), ``executor.py`` feeds job lines into the fleet
as batch-class idle backfill, ``api.py`` is the HTTP surface.  See
docs/bulk-inference.md.
"""

from .executor import JobManager
from .store import Job, JobStore

__all__ = ["Job", "JobManager", "JobStore"]
