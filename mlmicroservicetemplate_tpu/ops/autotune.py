"""Pallas decode-kernel autotuner: measured variant search at warmup.

The paged/whole-slab decode kernels (``ops/paged_attention.py``,
``ops/attention.py``) are parameterized by a :class:`Variant` — grid
block folding, head batching, native-MXU input width, int8 scale
folding (docs/kernel_tuning.md).  Which point wins depends on the
serving shape (B, KVH, n_rep, D, block size, table width) and dtype,
so instead of hardcoding one choice this module measures:

1. **Enumerate** the variant space for the shape, filtered by a VMEM
   cost model (``paged_vmem_bytes``, generalizing
   ``attention.decode_kernel_fits``) against the
   ``DECODE_KERNEL_VMEM_BUDGET_MB`` budget, and by block-table
   divisibility (``blocks_per_step`` must divide the table width — no
   pad-block path exists, by design).
2. **Verify** every candidate against the jnp reference on synthetic
   probe data at the REAL serving shapes — a variant that fails
   verification is rejected and counted, never timed.  Variants are
   token-identical to the reference by construction (same f32 masked
   online softmax, work only rearranged); this step enforces it at
   runtime against compiler surprises.
3. **Time** survivors with the two-scan-length method
   (``benchmarks/timing.py``: K vs 2K iterations inside one
   executable, differenced so the dispatch RTT cancels exactly), and
4. **Install** the winner into the fleet-shared
   ``runtime/compile_cache.ExecutableCache`` keyed by (shape key,
   variant) and journal it into a persistent tuning table, so replica
   spawns, supervised rebuilds and journal replays look the variant up
   and hit the SAME cached executable — zero extra compiles (the r19
   invariant; pinned by tests/test_pallas_autotune.py).

The sweep runs once per (model, kind, shape, dtype) key per process —
at warm time, before serving traffic — and ``PALLAS_VARIANT`` pins a
variant explicitly, skipping the sweep (validated, so a typo fails at
boot).  The lossy ``accbf16`` scratch axis is never enumerated; it is
reachable only through a pin.

Import-light (no jax at module import), thread-safe, and counters-
first: every decision (sweep/hit/pin/install/reject) increments a
process counter surfaced through ``stats()`` -> /status.decode, the
``pallas_autotune_events_total`` metric and the PERF_SMOKE structural
gate.
"""

from __future__ import annotations

import json
import os
import threading
import time

from .paged_attention import Variant, parse_variant

#: blocks-per-step folds the sweep considers (further filtered by
#: table-width divisibility and the VMEM model).
BLOCK_FOLDS = (1, 2, 4, 8)

#: scan lengths for the two-scan timing (small: the sweep times a
#: single fused kernel, not a serving chunk; interpret-mode CPU sweeps
#: stay affordable).  PALLAS_AUTOTUNE_ITERS overrides.
SWEEP_ITERS = 4
SWEEP_REPS = 3

_LOCK = threading.RLock()
_TABLE: dict[str, str] = {}
_RESULTS: dict[str, dict] = {}
_LOADED: set[str] = set()
_COUNTS = {
    "sweeps": 0,          # measured sweeps run (one per new key)
    "candidates": 0,      # variants enumerated across all sweeps
    "timed": 0,           # variants that survived to measurement
    "hits": 0,            # table lookups answered without a sweep
    "pins": 0,            # PALLAS_VARIANT pins honored
    "installs": 0,        # winners installed into the ExecutableCache
    "reject_vmem": 0,     # candidates over the VMEM budget
    "reject_verify": 0,   # candidates that mismatched the reference
    "reject_error": 0,    # candidates that failed to build/run
    "persist_errors": 0,  # tuning-table write/load failures (non-fatal)
}


def _event(name: str) -> None:
    try:
        from ..utils import metrics

        metrics.PALLAS_AUTOTUNE_EVENTS.labels(name).inc()
    except Exception:
        pass  # ops stays importable without the service metric surface


def tune_key(kind: str, *, b: int, kvh: int, n_rep: int,
             d: int, block_size: int, t: int, dtype: str,
             quant: bool, tp: int = 1) -> str:
    """Stable string key for one tuning problem.  Everything the
    kernel's cost surface depends on is spelled out, and NOTHING else:
    two models (or replicas) with identical decode shapes intentionally
    share an entry (λScale: tuning results are fleet artifacts keyed by
    workload, not by replica) — and because every field is derivable
    from the tensors at a kernel call site, the model code can
    reconstruct the key at trace time (:func:`lookup`) without any
    side-channel through its frozen config.  ``tp`` is the tensor-
    parallel width the kernel runs under: each shard's kernel sees
    kvh/tp local heads AND a different compute/VMEM surface (the
    shard_map body), so TP entries must never alias single-device ones.
    tp=1 appends nothing — every pre-TP persisted table stays valid."""
    q8 = "-q8" if quant else ""
    tps = f"-tp{tp}" if int(tp) > 1 else ""
    return (
        f"{kind}/B{b}-G{kvh}-R{n_rep}-D{d}"
        f"-bs{block_size}-T{t}-{dtype}{q8}{tps}"
    )


def lookup(kind: str, *, b: int, kvh: int, n_rep: int, d: int,
           block_size: int, t: int, dtype: str, quant: bool,
           tp: int = 1, default: str = "") -> str:
    """Trace-time variant resolution for kernel call sites: the winner
    ``ensure_tuned`` recorded for this shape, else ``default``.  The
    table only ever changes by gaining entries (warm-time sweeps/pins,
    before the shapes they describe are traced), so a serving-time
    RE-trace at a tuned shape resolves the same variant the warm trace
    did — variant choice is deterministic per (process, shape)."""
    key = tune_key(kind, b=b, kvh=kvh, n_rep=n_rep, d=d,
                   block_size=block_size, t=t, dtype=dtype, quant=quant,
                   tp=tp)
    with _LOCK:
        return _TABLE.get(key, default)


def paged_vmem_bytes(var: Variant, *, bs: int, kvh: int, d: int,
                     n_rep: int, payload_bytes: int, quant: bool) -> int:
    """Per-program VMEM for one paged grid step under ``var`` —
    generalizes ``attention.decode_kernel_fits`` to the tuned axes:
    K raw K/V blocks (+scales), the dequant/upcast f32 copies
    (``native_mxu`` skips them), q/out tiles, the online-softmax
    scratch at its configured width and the score/prob temporaries."""
    kb = var.blocks_per_step * bs
    payload = 2 * kb * kvh * d * payload_bytes
    scales = 2 * kb * kvh * 4 if quant else 0
    f32_copies = 0 if (var.native_mxu and not quant) else 2 * kb * kvh * d * 4
    q_out = 2 * kvh * n_rep * d * 4
    acc = 4 if var.acc_dtype == "f32" else 2
    scratch = (2 * kvh * n_rep + kvh * n_rep * d) * acc
    scores = 2 * kvh * n_rep * kb * 4  # s and p live together briefly
    return payload + scales + f32_copies + q_out + scratch + scores


def variant_fits(var: Variant, *, bs: int, kvh: int, d: int, n_rep: int,
                 payload_bytes: int, quant: bool,
                 budget: int | None = None) -> bool:
    from .attention import decode_vmem_budget_bytes

    if budget is None:
        budget = decode_vmem_budget_bytes()
    return paged_vmem_bytes(
        var, bs=bs, kvh=kvh, d=d, n_rep=n_rep,
        payload_bytes=payload_bytes, quant=quant,
    ) <= budget


def enumerate_variants(kind: str, *, t: int, bs: int, kvh: int, d: int,
                       n_rep: int, dtype: str, quant: bool,
                       budget: int | None = None) -> list[Variant]:
    """The feasible sweep set for one shape, default variant first.
    ``nat`` only exists for bf16 payloads, ``fs`` only for int8, the
    block fold only for the paged kernel (and only at divisors of the
    table width) — axes that would be no-ops are never enumerated, so
    every candidate the sweep times is a genuinely distinct kernel."""
    payload_bytes = 1 if quant else (2 if dtype == "bfloat16" else 4)
    folds = [1]
    if kind == "paged_decode":
        folds = [k for k in BLOCK_FOLDS if k <= max(t, 1) and t % k == 0]
        if not folds:
            folds = [1]
    nats = [False, True] if (dtype == "bfloat16" and not quant) else [False]
    fss = [False, True] if quant else [False]
    out: list[Variant] = []
    for k in folds:
        for hb in (False, True):
            for nat in nats:
                for fs in fss:
                    var = Variant(k, hb, nat, fs)
                    if variant_fits(
                        var, bs=bs, kvh=kvh, d=d, n_rep=n_rep,
                        payload_bytes=payload_bytes, quant=quant,
                        budget=budget,
                    ):
                        out.append(var)
                    else:
                        with _LOCK:
                            _COUNTS["reject_vmem"] += 1
                        _event("reject_vmem")
    return out


def _time_per_call(fn, args, iters: int, reps: int):
    """Two-scan-length device time (benchmarks/timing.py).  The
    benchmarks tree is not a package inside a deployed service, so
    fall back to an inline copy of the same method when the repo
    checkout is not importable."""
    try:
        from benchmarks.timing import device_time_per_call

        return device_time_per_call(fn, args, carry_idx=0, iters=iters,
                                    reps=reps)
    except ImportError:
        pass
    import jax
    import jax.numpy as jnp
    from jax import lax

    def make(n: int):
        def scan_k(*xs):
            def body(carry, _):
                xs2 = list(xs)
                xs2[0] = xs2[0] + (carry * 0).astype(xs2[0].dtype)
                out = fn(*xs2)
                return out.astype(jnp.float32).ravel()[0], ()

            carry, _ = lax.scan(body, jnp.float32(0), None, length=n)
            return carry

        return jax.jit(scan_k)

    s1, s2 = make(iters), make(2 * iters)
    dev = jax.device_put(tuple(args))
    float(jax.device_get(s1(*dev)))
    float(jax.device_get(s2(*dev)))

    def med(f) -> float:
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            float(jax.device_get(f(*dev)))
            ts.append(time.perf_counter() - t0)
        return sorted(ts)[len(ts) // 2]

    w1, w2 = med(s1), med(s2)
    noisy = w2 <= w1
    per = (max(w1, 1e-9) / iters) if noisy else (w2 - w1) / iters
    return per, noisy


def _probe(kind: str, *, b: int, kvh: int, n_rep: int, d: int, bs: int,
           t: int, dtype: str, quant: bool, seed: int = 0):
    """Synthetic probe tensors at the real serving shapes, plus the jnp
    reference output: (args_without_variant_call, ref).  Deterministic
    (fixed seed) so every replica's sweep measures the same problem."""
    import numpy as np

    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    h = kvh * n_rep
    jdt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    q = jnp.asarray(rng.normal(size=(b, h, d)).astype(np.float32), dtype=jdt)
    if kind == "paged_decode":
        nb_pool = t + 2  # a couple of free blocks, like a live pool
        kf = rng.normal(size=(nb_pool, bs, kvh, d)).astype(np.float32)
        vf = rng.normal(size=(nb_pool, bs, kvh, d)).astype(np.float32)
        table = np.stack(
            [rng.permutation(nb_pool)[:t] for _ in range(b)]
        ).astype(np.int32)
        valid = np.ones((b, t * bs), np.int32)
        valid[:, -max(bs // 2, 1):] = 0  # a part-filled tail block
        if quant:
            ks = (np.abs(kf).max(axis=3, keepdims=True) / 127.0 + 1e-6)
            vs = (np.abs(vf).max(axis=3, keepdims=True) / 127.0 + 1e-6)
            k8 = np.clip(np.round(kf / ks), -127, 127).astype(np.int8)
            v8 = np.clip(np.round(vf / vs), -127, 127).astype(np.int8)
            args = (q, jnp.asarray(k8), jnp.asarray(v8),
                    jnp.asarray(table), jnp.asarray(valid),
                    jnp.asarray(ks.astype(np.float32)),
                    jnp.asarray(vs.astype(np.float32)))
        else:
            args = (q, jnp.asarray(kf, dtype=jdt), jnp.asarray(vf, dtype=jdt),
                    jnp.asarray(table), jnp.asarray(valid), None, None)
        from .paged_attention import paged_attention_ref

        ref = paged_attention_ref(args[0], args[1], args[2], args[3],
                                  args[4], bs, k_scale=args[5],
                                  v_scale=args[6])
        return args, ref
    # whole-slab decode
    kf = rng.normal(size=(b, t, kvh, d)).astype(np.float32)
    vf = rng.normal(size=(b, t, kvh, d)).astype(np.float32)
    mask = np.ones((b, t), np.int32)
    mask[:, -max(t // 8, 1):] = 0
    if quant:
        ks = (np.abs(kf).max(axis=3, keepdims=True) / 127.0 + 1e-6)
        vs = (np.abs(vf).max(axis=3, keepdims=True) / 127.0 + 1e-6)
        k8 = np.clip(np.round(kf / ks), -127, 127).astype(np.int8)
        v8 = np.clip(np.round(vf / vs), -127, 127).astype(np.int8)
        args = (q, jnp.asarray(k8), jnp.asarray(v8), jnp.asarray(mask),
                jnp.asarray(ks.astype(np.float32)),
                jnp.asarray(vs.astype(np.float32)))
    else:
        args = (q, jnp.asarray(kf, dtype=jdt), jnp.asarray(vf, dtype=jdt),
                jnp.asarray(mask), None, None)
    ref = _slab_ref(*args)
    return args, ref


def _slab_ref(q, k, v, mask, ks, vs):
    """jnp reference for the whole-slab kernel (mirrors
    ``paged_attention_ref`` on the dense [B, T, KVH, D] layout)."""
    import math

    import jax
    import jax.numpy as jnp

    b, h, d = q.shape
    kvh = k.shape[2]
    n_rep = h // kvh
    kd = k.astype(jnp.float32)
    vd = v.astype(jnp.float32)
    if ks is not None:
        kd = kd * ks.astype(jnp.float32)
        vd = vd * vs.astype(jnp.float32)
    qg = q.reshape(b, kvh, n_rep, d).astype(jnp.float32)
    s = jnp.einsum("bgrd,btgd->bgrt", qg, kd) / math.sqrt(d)
    s = jnp.where(mask[:, None, None, :] != 0, s, jnp.float32(-1e9))
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bgrt,btgd->bgrd", p, vd)
    return o.reshape(b, h, d).astype(q.dtype)


def _make_call(kind: str, vkey: str, block_size: int, interpret: bool):
    """A positional-args callable running the kernel at one variant —
    the object the sweep times and the ExecutableCache installs."""
    if kind == "paged_decode":
        from .paged_attention import paged_decode_attention

        def call(q, kp, vp, tbl, valid, ks=None, vs=None):
            return paged_decode_attention(
                q, kp, vp, tbl, valid, block_size, k_scale=ks, v_scale=vs,
                interpret=interpret, variant=vkey,
            )

        return call
    from .attention import decode_attention

    def call(q, k, v, mask, ks=None, vs=None):
        return decode_attention(
            q, k, v, mask, k_scale=ks, v_scale=vs, interpret=interpret,
            variant=vkey,
        )

    return call


def _verify(out, ref, dtype: str) -> bool:
    import numpy as np

    a = np.asarray(out, dtype=np.float32)
    b = np.asarray(ref, dtype=np.float32)
    tol = 3e-2 if dtype == "bfloat16" else 1e-4
    return bool(np.allclose(a, b, rtol=tol, atol=tol))


def default_table_path() -> str | None:
    """PALLAS_TUNE_TABLE, else alongside the persistent XLA disk cache
    (COMPILE_CACHE_DIR) so both tuning artifacts survive restarts
    together; None = in-memory only."""
    p = os.environ.get("PALLAS_TUNE_TABLE")
    if p:
        return p
    from ..runtime.device import tune_table_default

    return tune_table_default(os.environ.get("COMPILE_CACHE_DIR"))


def _load_table(path: str | None) -> None:
    if not path:
        return
    with _LOCK:
        if path in _LOADED:
            return
        _LOADED.add(path)
    try:
        with open(path) as f:
            data = json.load(f)
        entries = data.get("table", {})
        if not isinstance(entries, dict):
            raise ValueError("tuning table is not an object")
        for key, vkey in entries.items():
            parse_variant(vkey)  # junk on disk must not reach a trace
            with _LOCK:
                _TABLE.setdefault(key, vkey)
    except FileNotFoundError:
        pass
    except Exception:
        with _LOCK:
            _COUNTS["persist_errors"] += 1
        _event("persist_error")


def _persist_table(path: str | None) -> None:
    if not path:
        return
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with _LOCK:
            body = {"version": 1, "table": dict(sorted(_TABLE.items()))}
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(body, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)  # atomic: concurrent readers see old or new
    except Exception:
        with _LOCK:
            _COUNTS["persist_errors"] += 1
        _event("persist_error")


def _install(kind: str, bundle, replicas, key: str, vkey: str,
             block_size: int, interpret: bool):
    """Winner -> fleet-shared ExecutableCache, keyed (shape key,
    variant).  Every replica resolving the same key gets the SAME
    wrapper object, so spawns/rebuilds/replays reuse its jit cache —
    the zero-extra-compile inheritance path."""
    import jax

    from ..runtime.compile_cache import shared_executable

    fn = shared_executable(
        f"{kind}_kernel", bundle, replicas,
        lambda: jax.jit(_make_call(kind, vkey, block_size, interpret)),
        statics=(key, vkey),
    )
    with _LOCK:
        _COUNTS["installs"] += 1
    _event("install")
    return fn


def _sweep(kind: str, key: str, *, b, kvh, n_rep, d, block_size, t,
           dtype, quant, interpret) -> str:
    iters = int(os.environ.get("PALLAS_AUTOTUNE_ITERS", str(SWEEP_ITERS)))
    cands = enumerate_variants(
        kind, t=t, bs=block_size or t, kvh=kvh, d=d, n_rep=n_rep,
        dtype=dtype, quant=quant,
    )
    with _LOCK:
        _COUNTS["sweeps"] += 1
        _COUNTS["candidates"] += len(cands)
    _event("sweep")
    args, ref = _probe(kind, b=b, kvh=kvh, n_rep=n_rep, d=d,
                       bs=block_size or t, t=t, dtype=dtype, quant=quant)
    call_args = tuple(a for a in args if a is not None)
    timings: dict[str, float] = {}
    any_noisy = False
    best_key, best_t = "b1", float("inf")
    for var in cands:
        vkey = var.key()
        fn = _make_call(kind, vkey, block_size, interpret)
        try:
            out = fn(*call_args)
            if not _verify(out, ref, dtype):
                with _LOCK:
                    _COUNTS["reject_verify"] += 1
                _event("reject_verify")
                continue
            per, noisy = _time_per_call(fn, call_args, iters, SWEEP_REPS)
        except Exception:
            with _LOCK:
                _COUNTS["reject_error"] += 1
            _event("reject_error")
            continue
        with _LOCK:
            _COUNTS["timed"] += 1
        any_noisy = any_noisy or noisy
        timings[vkey] = per
        if per < best_t:
            best_key, best_t = vkey, per
    with _LOCK:
        _RESULTS[key] = {
            "winner": best_key,
            "candidates": len(cands),
            "timed": len(timings),
            "noisy": any_noisy,
            "per_call_us": {
                k: round(v * 1e6, 2) for k, v in sorted(timings.items())
            },
        }
    return best_key


def ensure_tuned(kind: str, bundle, replicas, *, b: int, kvh: int,
                 n_rep: int, d: int, block_size: int = 0, t: int = 0,
                 dtype: str = "float32", quant: bool = False,
                 interpret: bool = False, pin: str | None = None,
                 table_path: str | None = "") -> str:
    """Resolve the tuned variant for one serving shape: honor a pin,
    answer from the (persisted) tuning table, or run a measured sweep —
    then install the winner into the ExecutableCache.  Returns the
    variant key the caller should thread into its serving executables'
    static descriptors.  ``table_path``: ``""`` = resolve the default
    (PALLAS_TUNE_TABLE / COMPILE_CACHE_DIR), None = no persistence."""
    # The placement's TP width keys the table entry (tp=1 placements
    # add nothing): sweeps under a TP mesh measure the SHARDED kernel,
    # and their winners must never be served to single-device traces.
    tp = int(getattr(replicas, "tp_width", 1) or 1)
    key = tune_key(kind, b=b, kvh=kvh, n_rep=n_rep, d=d,
                   block_size=block_size, t=t, dtype=dtype, quant=quant,
                   tp=tp)
    path = default_table_path() if table_path == "" else table_path
    if pin:
        var = parse_variant(pin)  # ValueError on junk: fail at boot
        if kind == "paged_decode" and t and t % var.blocks_per_step != 0:
            raise ValueError(
                f"PALLAS_VARIANT={pin!r}: blocks_per_step="
                f"{var.blocks_per_step} does not divide table width {t}"
            )
        vkey = var.key()
        with _LOCK:
            _TABLE[key] = vkey
            _COUNTS["pins"] += 1
        _event("pin")
        _install(kind, bundle, replicas, key, vkey, block_size, interpret)
        return vkey
    _load_table(path)
    with _LOCK:
        got = _TABLE.get(key)
    if got is not None:
        with _LOCK:
            _COUNTS["hits"] += 1
        _event("hit")
        _install(kind, bundle, replicas, key, got, block_size, interpret)
        return got
    winner = _sweep(kind, key, b=b, kvh=kvh, n_rep=n_rep, d=d,
                    block_size=block_size, t=t, dtype=dtype, quant=quant,
                    interpret=interpret)
    with _LOCK:
        _TABLE[key] = winner
    _persist_table(path)
    _install(kind, bundle, replicas, key, winner, block_size, interpret)
    return winner


def stats() -> dict:
    """Counters + table + last sweep details: /status.decode.autotune,
    the PERF_SMOKE gate and BENCH json all read this one snapshot."""
    with _LOCK:
        return {
            "counts": dict(_COUNTS),
            "table": dict(sorted(_TABLE.items())),
            "sweeps": {k: dict(v) for k, v in sorted(_RESULTS.items())},
        }


def clear() -> None:
    """Test hook: forget tables, results and counters (files on disk
    stay; pass a fresh table_path to isolate persistence tests)."""
    with _LOCK:
        _TABLE.clear()
        _RESULTS.clear()
        _LOADED.clear()
        for k in _COUNTS:
            _COUNTS[k] = 0
