"""Pallas TPU kernels for the hot ops.

The serving models' FLOPs live in attention + matmuls; XLA fuses most
elementwise work already, so kernels here target what XLA does NOT do
well: keeping the [S, S] attention score matrix VMEM-resident instead
of round-tripping it through HBM (``attention.fused_attention``).

Kernels are opt-in per call site and always have a pure-jnp reference
implementation next to them — CPU/CI runs use the reference (or
``interpret=True``), TPU serving can flip them on via
``USE_PALLAS_ATTENTION=1``.
"""

from .attention import fused_attention, use_pallas_attention  # noqa: F401
