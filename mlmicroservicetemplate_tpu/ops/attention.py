"""Fused multi-head attention as a Pallas TPU kernel.

One grid program per (batch, head): Q/K/V tiles stream HBM→VMEM once,
the [S, S] score matrix, mask, softmax, and the probs·V matmul all stay
in VMEM, and only the [S, D] context tile goes back to HBM.  The
un-fused XLA path materializes the f32 score tensor in HBM twice
(write after QK^T, read for softmax·V) — at S=512, H=12 that is
2·B·12·512·512·4B of HBM traffic this kernel never pays.

Encoder sizes here (S ≤ 512, D = 64) fit whole heads in VMEM
(512·512·4B scores + 3·512·64 tiles ≈ 1.3 MB of ~16 MB), so no online
softmax is needed; this is the single-block regime, not FlashAttention.

Serving-shape contract: optional additive bias [1, H, S, S] (T5's
relative-position bias, shared across batch), optional padding mask,
Sq == Sk.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp


# The kernel materializes full [S, S] f32 scores (plus [S, S] bias for
# T5) in VMEM per grid step — the single-block regime.  Past this
# sequence length the block no longer fits and compiles would fail at
# warmup, so default-on falls back to the jnp path instead.
PALLAS_SINGLE_BLOCK_MAX_SEQ = 512


def use_pallas_attention(max_seq: int | None = None) -> bool:
    """Default ON for TPU serving; USE_PALLAS_ATTENTION=0 disables.

    Measured wins (benchmarks/pallas_ab.py, v5e, device time isolated
    from the relay): BERT-base B=32 S=512 1.13x; T5-small encoder B=8
    S=512 2.10x.  The kernel is verified against the jnp path at every
    serving seq bucket (32..512) in bf16 on real hardware.  Serving
    call sites only — no VJP, so training/tp consumers stay on jnp.

    ``max_seq`` is the largest configured seq bucket: beyond
    ``PALLAS_SINGLE_BLOCK_MAX_SEQ`` (single-block VMEM regime) the
    default flips off so raising SEQ_BUCKETS never turns into a
    VMEM-overflow compile failure at warmup.  USE_PALLAS_ATTENTION=1
    forces the kernel on regardless (operator overrides the guard).
    """
    env = os.environ.get("USE_PALLAS_ATTENTION", "").lower()
    if env in ("0", "false", "no"):
        return False
    try:
        on_tpu = jax.default_backend() == "tpu"
    except Exception:
        return False
    if env in ("1", "true", "yes"):
        return on_tpu
    if max_seq is not None and max_seq > PALLAS_SINGLE_BLOCK_MAX_SEQ:
        return False
    return on_tpu


def _attn_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *, scale: float):
    # Block shapes: q/k/v [1, 1, S, D]; mask [1, 1, S]; o [1, 1, S, D].
    _attn_body(q_ref, k_ref, v_ref, mask_ref, None, o_ref, scale=scale)


def _attn_kernel_bias(q_ref, k_ref, v_ref, mask_ref, bias_ref, o_ref, *, scale: float):
    # As _attn_kernel plus an additive [1, 1, S, S] bias block (one head
    # of the shared rel-pos bias); bias also stays VMEM-resident.
    _attn_body(q_ref, k_ref, v_ref, mask_ref, bias_ref, o_ref, scale=scale)


def _attn_body(q_ref, k_ref, v_ref, mask_ref, bias_ref, o_ref, *, scale: float):
    q = q_ref[0, 0].astype(jnp.float32)  # [S, D]
    k = k_ref[0, 0].astype(jnp.float32)
    v = v_ref[0, 0]
    scores = jax.lax.dot_general(
        q, k,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [S, S]
    if bias_ref is not None:
        scores = scores + bias_ref[0, 0].astype(jnp.float32)
    mask = mask_ref[0]  # [1, S] int32, 1 = keep (key-side padding mask)
    scores = jnp.where(mask[0][None, :] != 0, scores, jnp.float32(-1e9))
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    ctx = jax.lax.dot_general(
        probs, v,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[0, 0] = ctx.astype(o_ref.dtype)


def _decode_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *, scale: float):
    # Blocks: q/o [1, 1, R, D] (R = GQA group width), k/v [1, T, 1, D],
    # mask [1, 1, T].  One program = one (batch row, kv head): the K/V
    # tile streams HBM→VMEM ONCE and serves all R query heads of its
    # group — the XLA path's _repeat_kv reads it R times.
    q = q_ref[0, 0].astype(jnp.float32)  # [R, D]
    k = k_ref[0, :, 0].astype(jnp.float32)  # [T, D]
    scores = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [R, T]
    mask = mask_ref[0]  # [1, T]
    scores = jnp.where(mask[0][None, :] != 0, scores, jnp.float32(-1e9))
    probs = jax.nn.softmax(scores, axis=-1)
    v = v_ref[0, :, 0]  # [T, D]
    ctx = jax.lax.dot_general(
        probs.astype(v.dtype), v,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[0, 0] = ctx.astype(o_ref.dtype)


def _decode_kernel_kv8(q_ref, k8_ref, ks_ref, v8_ref, vs_ref, mask_ref,
                       o_ref, *, scale: float):
    # int8-KV variant: payloads cross HBM at int8 width and dequantize
    # IN VMEM — the hypothesis test for the measured XLA kv-quant loss
    # (BASELINE.md r4: materialized int8→bf16 converts feeding the
    # cache einsums).  Scale factoring is exact: the key scale
    # multiplies its logit column, the value scale folds into the
    # softmax weights (common.mha_attention_kv8's math, fused here).
    q = q_ref[0, 0].astype(jnp.float32)  # [R, D]
    k8 = k8_ref[0, :, 0].astype(jnp.float32)  # [T, D]
    ks = ks_ref[0, :, 0, 0].astype(jnp.float32)  # [T]
    scores = jax.lax.dot_general(
        q, k8, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale * ks[None, :]  # [R, T]
    mask = mask_ref[0]
    scores = jnp.where(mask[0][None, :] != 0, scores, jnp.float32(-1e9))
    probs = jax.nn.softmax(scores, axis=-1)
    vs = vs_ref[0, :, 0, 0].astype(jnp.float32)  # [T]
    v8 = v8_ref[0, :, 0].astype(jnp.float32)
    ctx = jax.lax.dot_general(
        probs * vs[None, :], v8,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[0, 0] = ctx.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def decode_attention(
    q: jax.Array,  # [B, H, D] — one query per row (the decode step)
    k: jax.Array,  # [B, T, KVH, D] dense, or int8 payload
    v: jax.Array,  # [B, T, KVH, D]
    mask: jax.Array,  # [B, T] 1 = attend
    k_scale: jax.Array | None = None,  # [B, T, KVH, 1] → int8 path
    v_scale: jax.Array | None = None,
    scale: float | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Decode-side fused attention over the KV cache; returns [B, H, D].

    Grid (B, KVH): each program serves one kv head's whole GQA query
    group, so the cache crosses HBM once per kv head instead of once
    per query head (``_repeat_kv``), and with ``k_scale``/``v_scale``
    the payload crosses at int8 width with in-kernel dequant.  The
    [T, D] tile + f32 copies fit VMEM comfortably at serving contexts
    (T=2048, D=64 ≈ 0.5 MB f32)."""
    from jax.experimental import pallas as pl

    b, h, d = q.shape
    _, t, kvh, _ = k.shape
    n_rep = h // kvh
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    qg = q.reshape(b, kvh, n_rep, d)
    q_spec = pl.BlockSpec((1, 1, n_rep, d), lambda i, g: (i, g, 0, 0))
    kv_spec = pl.BlockSpec((1, t, 1, d), lambda i, g: (i, 0, g, 0))
    mask3 = mask.astype(jnp.int32)[:, None, :]
    mask_spec = pl.BlockSpec((1, 1, t), lambda i, g: (i, 0, 0))
    if k_scale is None:
        kernel = functools.partial(_decode_kernel, scale=scale)
        in_specs = [q_spec, kv_spec, kv_spec, mask_spec]
        args = (qg, k, v, mask3)
    else:
        sc_spec = pl.BlockSpec((1, t, 1, 1), lambda i, g: (i, 0, g, 0))
        kernel = functools.partial(_decode_kernel_kv8, scale=scale)
        in_specs = [q_spec, kv_spec, sc_spec, kv_spec, sc_spec, mask_spec]
        args = (qg, k, k_scale, v, v_scale, mask3)
    out = pl.pallas_call(
        kernel,
        grid=(b, kvh),
        in_specs=in_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, kvh, n_rep, d), q.dtype),
        interpret=interpret,
    )(*args)
    return out.reshape(b, h, d)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def fused_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, S, H, D]
    v: jax.Array,  # [B, S, H, D]
    mask: jax.Array,  # [B, S] 1 = keep
    bias: jax.Array | None = None,  # [1, H, S, S] additive (T5 rel-pos)
    scale: float | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Drop-in for ``common.mha_attention(q, k, v, mask=broadcast)`` on
    the encoder self-attention shapes; returns [B, S, H, D]."""
    from jax.experimental import pallas as pl

    b, s, h, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    # [B, S, H, D] -> [B, H, S, D]: per-(b,h) tiles are contiguous for
    # the grid; XLA fuses the transposes into neighbors.
    qt, kt, vt = (jnp.transpose(x, (0, 2, 1, 3)) for x in (q, k, v))
    bhsd = pl.BlockSpec((1, 1, s, d), lambda i, j: (i, j, 0, 0))
    # TPU tiling wants the mask block's trailing dims to equal the array
    # dims, so carry it as [B, 1, S] with a (1, 1, S) block.
    mask3 = mask.astype(jnp.int32)[:, None, :]
    mask_spec = pl.BlockSpec((1, 1, s), lambda i, j: (i, 0, 0))
    if bias is None:
        kernel = functools.partial(_attn_kernel, scale=scale)
        in_specs = [bhsd, bhsd, bhsd, mask_spec]
        args = (qt, kt, vt, mask3)
    else:
        # One [S, S] head-slice of the shared bias per grid step.
        kernel = functools.partial(_attn_kernel_bias, scale=scale)
        in_specs = [
            bhsd, bhsd, bhsd, mask_spec,
            pl.BlockSpec((1, 1, s, s), lambda i, j: (0, j, 0, 0)),
        ]
        args = (qt, kt, vt, mask3, bias)
    out = pl.pallas_call(
        kernel,
        grid=(b, h),
        in_specs=in_specs,
        out_specs=bhsd,
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        interpret=interpret,
    )(*args)
    return jnp.transpose(out, (0, 2, 1, 3))
