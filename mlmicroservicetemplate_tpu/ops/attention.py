"""Fused multi-head attention as a Pallas TPU kernel.

One grid program per (batch, head): Q/K/V tiles stream HBM→VMEM once,
the [S, S] score matrix, mask, softmax, and the probs·V matmul all stay
in VMEM, and only the [S, D] context tile goes back to HBM.  The
un-fused XLA path materializes the f32 score tensor in HBM twice
(write after QK^T, read for softmax·V) — at S=512, H=12 that is
2·B·12·512·512·4B of HBM traffic this kernel never pays.

Encoder sizes here (S ≤ 512, D = 64) fit whole heads in VMEM
(512·512·4B scores + 3·512·64 tiles ≈ 1.3 MB of ~16 MB), so no online
softmax is needed; this is the single-block regime, not FlashAttention.

Serving-shape contract: optional additive bias [1, H, S, S] (T5's
relative-position bias, shared across batch), optional padding mask,
Sq == Sk.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp


# The kernel materializes full [S, S] f32 scores (plus [S, S] bias for
# T5) in VMEM per grid step — the single-block regime.  Past this
# sequence length the block no longer fits and compiles would fail at
# warmup, so default-on falls back to the jnp path instead.  Default
# for the PALLAS_SINGLE_BLOCK_MAX_SEQ env knob (validated range in
# ``single_block_max_seq``; mirrored by ServiceConfig so a typo'd
# value fails at boot).
PALLAS_SINGLE_BLOCK_MAX_SEQ = 512


def single_block_max_seq() -> int:
    """The PALLAS_SINGLE_BLOCK_MAX_SEQ knob, range-checked.  Raises
    ``ValueError`` on junk — a silent fallback here would flip the
    kernel off (or VMEM-overflow warmup) with no operator signal."""
    raw = os.environ.get("PALLAS_SINGLE_BLOCK_MAX_SEQ")
    if raw in (None, ""):
        return PALLAS_SINGLE_BLOCK_MAX_SEQ
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(
            f"PALLAS_SINGLE_BLOCK_MAX_SEQ={raw!r} is not an integer"
        ) from None
    if not 64 <= v <= 8192:
        raise ValueError(
            f"PALLAS_SINGLE_BLOCK_MAX_SEQ={v} outside [64, 8192] — the "
            f"single-block VMEM regime cannot hold more"
        )
    return v


def use_pallas_attention(max_seq: int | None = None) -> bool:
    """Default ON for TPU serving; USE_PALLAS_ATTENTION=0 disables.

    Measured wins (benchmarks/pallas_ab.py, v5e, device time isolated
    from the relay): BERT-base B=32 S=512 1.13x; T5-small encoder B=8
    S=512 2.10x.  The kernel is verified against the jnp path at every
    serving seq bucket (32..512) in bf16 on real hardware.  Serving
    call sites only — no VJP, so training/tp consumers stay on jnp.

    ``max_seq`` is the largest configured seq bucket: beyond
    ``PALLAS_SINGLE_BLOCK_MAX_SEQ`` (single-block VMEM regime) the
    default flips off so raising SEQ_BUCKETS never turns into a
    VMEM-overflow compile failure at warmup.  USE_PALLAS_ATTENTION=1
    forces the kernel on regardless (operator overrides the guard).
    """
    env = os.environ.get("USE_PALLAS_ATTENTION", "").lower()
    if env in ("0", "false", "no"):
        return False
    try:
        on_tpu = jax.default_backend() == "tpu"
    except Exception:
        return False
    if env in ("1", "true", "yes"):
        return on_tpu
    if max_seq is not None and max_seq > single_block_max_seq():
        return False
    return on_tpu


def _attn_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *, scale: float):
    # Block shapes: q/k/v [1, 1, S, D]; mask [1, 1, S]; o [1, 1, S, D].
    _attn_body(q_ref, k_ref, v_ref, mask_ref, None, o_ref, scale=scale)


def _attn_kernel_bias(q_ref, k_ref, v_ref, mask_ref, bias_ref, o_ref, *, scale: float):
    # As _attn_kernel plus an additive [1, 1, S, S] bias block (one head
    # of the shared rel-pos bias); bias also stays VMEM-resident.
    _attn_body(q_ref, k_ref, v_ref, mask_ref, bias_ref, o_ref, scale=scale)


def _attn_body(q_ref, k_ref, v_ref, mask_ref, bias_ref, o_ref, *, scale: float):
    q = q_ref[0, 0].astype(jnp.float32)  # [S, D]
    k = k_ref[0, 0].astype(jnp.float32)
    v = v_ref[0, 0]
    scores = jax.lax.dot_general(
        q, k,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [S, S]
    if bias_ref is not None:
        scores = scores + bias_ref[0, 0].astype(jnp.float32)
    mask = mask_ref[0]  # [1, S] int32, 1 = keep (key-side padding mask)
    scores = jnp.where(mask[0][None, :] != 0, scores, jnp.float32(-1e9))
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    ctx = jax.lax.dot_general(
        probs, v,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[0, 0] = ctx.astype(o_ref.dtype)


def _decode_body(q_ref, k_ref, v_ref, ks_ref, vs_ref, mask_ref, o_ref, *,
                 scale: float, kvh: int):
    # Blocks: q/o [1, KVH, R, D] (R = GQA group width), k/v
    # [1, T, KVH, D], scales (int8 path) [1, T, KVH], mask [1, 1, T].
    # One program = one batch row: the whole row's cache slab streams
    # HBM->VMEM exactly ONCE and the (static) kv-head loop serves
    # every query group from it — the XLA path's _repeat_kv costs one
    # cache read per QUERY head.  (Blocking the KVH axis instead would
    # need a sublane-divisible block there, which Mosaic's
    # (8, 128)-or-whole-dim rule rejects for small head counts;
    # whole-slab blocks satisfy it trivially.)
    #
    # With scale refs the payloads are int8 and dequantize IN VMEM —
    # the hypothesis test for the measured XLA kv-quant loss
    # (BASELINE.md r4: materialized int8->bf16 converts feeding the
    # cache einsums).  Scales fold into the dequantized tiles
    # ((q·k8)·ks == q·(k8·ks) exactly in real arithmetic); everything
    # stays >=2-D — Mosaic's layout inference rejects 1-D vector
    # extractions like [1,T,1,1]->[T].
    mask = mask_ref[0]  # [1, T]
    ks_all = None if ks_ref is None else ks_ref[0].astype(jnp.float32)
    vs_all = None if vs_ref is None else vs_ref[0].astype(jnp.float32)
    for g in range(kvh):
        q = q_ref[0, g].astype(jnp.float32)  # [R, D]
        k = k_ref[0, :, g].astype(jnp.float32)  # [T, D]
        if ks_all is not None:
            k = k * ks_all[:, g:g + 1]
        scores = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [R, T]
        scores = jnp.where(mask[0][None, :] != 0, scores, jnp.float32(-1e9))
        probs = jax.nn.softmax(scores, axis=-1)
        v = v_ref[0, :, g]  # [T, D]
        if vs_all is not None:
            v = v.astype(jnp.float32) * vs_all[:, g:g + 1]
            probs_t = probs
        else:
            probs_t = probs.astype(v.dtype)
        ctx = jax.lax.dot_general(
            probs_t, v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        o_ref[0, g] = ctx.astype(o_ref.dtype)


def _decode_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *, scale: float,
                   kvh: int):
    _decode_body(q_ref, k_ref, v_ref, None, None, mask_ref, o_ref,
                 scale=scale, kvh=kvh)


def _decode_kernel_kv8(q_ref, k8_ref, ks_ref, v8_ref, vs_ref, mask_ref,
                       o_ref, *, scale: float, kvh: int):
    _decode_body(q_ref, k8_ref, v8_ref, ks_ref, vs_ref, mask_ref, o_ref,
                 scale=scale, kvh=kvh)


def _decode_body_v(q_ref, k_ref, v_ref, ks_ref, vs_ref, mask_ref, o_ref, *,
                   scale: float, kvh: int, var):
    """Variant-parameterized whole-slab body (docs/kernel_tuning.md):
    the same masked softmax as ``_decode_body`` with the autotuner's
    axes applied — ``head_batched`` serves every kv head from ONE
    kvh-batched dot pair, ``native_mxu`` feeds bf16 slabs to the MXU
    at storage width, ``fold_scales`` keeps int8 payloads unscaled
    through the dots and folds the scales into scores/probs.  The
    block axis (``blocks_per_step``) has no meaning here — there is no
    block table — so the sweep only enumerates these three."""
    f32 = jnp.float32
    quant = ks_ref is not None
    native = var.native_mxu and not quant and (
        q_ref.dtype == jnp.bfloat16 and k_ref.dtype == jnp.bfloat16
    )

    def up(x):
        return x if native else x.astype(f32)

    mask = mask_ref[0]  # [1, T]
    ks_all = None if ks_ref is None else ks_ref[0].astype(f32)  # [T, KVH]
    vs_all = None if vs_ref is None else vs_ref[0].astype(f32)
    k_raw = k_ref[0]  # [T, KVH, D]
    v_raw = v_ref[0]
    if quant and not var.fold_scales:
        k_raw = k_raw.astype(f32) * ks_all[:, :, None]
        v_raw = v_raw.astype(f32) * vs_all[:, :, None]
        quant = False
    elif quant:
        k_raw = k_raw.astype(f32)
        v_raw = v_raw.astype(f32)

    if var.head_batched:
        q = up(q_ref[0])  # [KVH, R, D]
        s = jax.lax.dot_general(
            q, up(k_raw),
            dimension_numbers=(((2,), (2,)), ((0,), (1,))),
            preferred_element_type=f32,
        )  # [KVH, R, T]
        if quant:
            s = s * jnp.transpose(ks_all)[:, None, :]
        s = s * scale
        s = jnp.where(mask[0][None, None, :] != 0, s, f32(-1e9))
        probs = jax.nn.softmax(s, axis=-1)
        if quant:
            probs = probs * jnp.transpose(vs_all)[:, None, :]
        ctx = jax.lax.dot_general(
            probs, up(v_raw),
            dimension_numbers=(((2,), (0,)), ((0,), (1,))),
            preferred_element_type=f32,
        )  # [KVH, R, D]
        o_ref[0] = ctx.astype(o_ref.dtype)
        return

    for g in range(kvh):
        q = up(q_ref[0, g])  # [R, D]
        k = up(k_raw[:, g])  # [T, D]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=f32,
        )
        if quant:
            s = s * ks_all[None, :, g]
        s = s * scale
        s = jnp.where(mask[0][None, :] != 0, s, f32(-1e9))
        probs = jax.nn.softmax(s, axis=-1)
        if quant:
            probs = probs * vs_all[None, :, g]
        ctx = jax.lax.dot_general(
            probs, up(v_raw[:, g]),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=f32,
        )
        o_ref[0, g] = ctx.astype(o_ref.dtype)


def _decode_kernel_v(q_ref, k_ref, v_ref, mask_ref, o_ref, *, scale: float,
                     kvh: int, var):
    _decode_body_v(q_ref, k_ref, v_ref, None, None, mask_ref, o_ref,
                   scale=scale, kvh=kvh, var=var)


def _decode_kernel_v_kv8(q_ref, k8_ref, ks_ref, v8_ref, vs_ref, mask_ref,
                         o_ref, *, scale: float, kvh: int, var):
    _decode_body_v(q_ref, k8_ref, v8_ref, ks_ref, vs_ref, mask_ref, o_ref,
                   scale=scale, kvh=kvh, var=var)


# Per-program VMEM for the whole-slab decode kernel: K+V f32 copies
# dominate (2·T·KVH·D·4B) on top of the raw blocks.  Guard the
# auto-enable against configs whose slabs cannot fit, mirroring
# use_pallas_attention's single-block guard.  Default for the
# DECODE_KERNEL_VMEM_BUDGET_MB env knob (``decode_vmem_budget_bytes``
# validates; ServiceConfig mirrors).
DECODE_KERNEL_VMEM_BUDGET = 10 * 1024 * 1024


def decode_vmem_budget_bytes() -> int:
    """The DECODE_KERNEL_VMEM_BUDGET_MB knob in bytes, range-checked.
    Also the budget ``ops/autotune.py`` filters kernel variants
    against, so one number bounds both auto-enable and the sweep."""
    raw = os.environ.get("DECODE_KERNEL_VMEM_BUDGET_MB")
    if raw in (None, ""):
        return DECODE_KERNEL_VMEM_BUDGET
    try:
        mb = int(raw)
    except ValueError:
        raise ValueError(
            f"DECODE_KERNEL_VMEM_BUDGET_MB={raw!r} is not an integer"
        ) from None
    if not 1 <= mb <= 256:
        raise ValueError(
            f"DECODE_KERNEL_VMEM_BUDGET_MB={mb} outside [1, 256] — VMEM "
            f"is ~16 MB/core; budgets past 256 MB are fiction"
        )
    return mb * 1024 * 1024


def decode_kernel_fits(t: int, kvh: int, d: int) -> bool:
    """True when the per-program slabs of ``decode_attention`` fit the
    VMEM budget at cache width ``t`` (f32 K+V copies + raw payloads)."""
    f32_copies = 2 * t * kvh * d * 4
    payloads = 2 * t * kvh * d * 4  # bf16/int8 blocks + scales, rounded up
    return f32_copies + payloads <= decode_vmem_budget_bytes()


@functools.partial(
    jax.jit, static_argnames=("scale", "interpret", "variant", "tp")
)
def decode_attention(
    q: jax.Array,  # [B, H, D] — one query per row (the decode step)
    k: jax.Array,  # [B, T, KVH, D] dense, or int8 payload
    v: jax.Array,  # [B, T, KVH, D]
    mask: jax.Array,  # [B, T] 1 = attend
    k_scale: jax.Array | None = None,  # [B, T, KVH, 1] -> int8 path
    v_scale: jax.Array | None = None,
    scale: float | None = None,
    interpret: bool = False,
    variant: str = "",
    tp: int = 1,
) -> jax.Array:
    """Decode-side fused attention over the KV cache; returns [B, H, D].

    Grid (B,): each program serves one batch row — its whole KV slab
    crosses HBM once (the XLA path's ``_repeat_kv`` costs one read per
    query head), and with ``k_scale``/``v_scale`` the payload crosses
    at int8 width with in-kernel dequant.  The kernel never cares where
    rows came from: cached prefixes (PREFIX_CACHE / PROMPT_PREFIX under
    QUANT_KV) are written into the slab as int8 + scale like prefill
    rows, so prefix hits ride through unchanged.  VMEM: the [T, KVH, D]
    slab + f32 copies ~= 4.6 MB at T=2048, KVH=4, D=64 — comfortable."""
    from jax.experimental import pallas as pl

    from .paged_attention import parse_variant

    if tp > 1:
        # Each shard runs this kernel over its local heads; the
        # row-parallel all-reduce lands after attn-out via sharding
        # propagation (ops/paged_attention.tp_shard_attention).
        from .paged_attention import tp_shard_attention

        opt = () if k_scale is None else (k_scale, v_scale)

        def local(q_l, kl, vl, m, *sc):
            ks, vs = sc if sc else (None, None)
            return decode_attention(
                q_l, kl, vl, m, ks, vs, scale=scale,
                interpret=interpret, variant=variant,
            )

        return tp_shard_attention(local, tp, q, (k, v), (mask,), opt)

    var = parse_variant(variant)
    b, h, d = q.shape
    _, t, kvh, _ = k.shape
    n_rep = h // kvh
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    qg = q.reshape(b, kvh, n_rep, d)
    q_spec = pl.BlockSpec((1, kvh, n_rep, d), lambda i: (i, 0, 0, 0))
    kv_spec = pl.BlockSpec((1, t, kvh, d), lambda i: (i, 0, 0, 0))
    mask3 = mask.astype(jnp.int32)[:, None, :]
    mask_spec = pl.BlockSpec((1, 1, t), lambda i: (i, 0, 0))
    default = not (var.head_batched or var.native_mxu or var.fold_scales)
    if k_scale is None:
        if default:  # the pre-autotuner kernel, bit-identical
            kernel = functools.partial(_decode_kernel, scale=scale, kvh=kvh)
        else:
            kernel = functools.partial(
                _decode_kernel_v, scale=scale, kvh=kvh, var=var
            )
        in_specs = [q_spec, kv_spec, kv_spec, mask_spec]
        args = (qg, k, v, mask3)
    else:
        sc_spec = pl.BlockSpec((1, t, kvh), lambda i: (i, 0, 0))
        if default:
            kernel = functools.partial(
                _decode_kernel_kv8, scale=scale, kvh=kvh
            )
        else:
            kernel = functools.partial(
                _decode_kernel_v_kv8, scale=scale, kvh=kvh, var=var
            )
        in_specs = [q_spec, kv_spec, sc_spec, kv_spec, sc_spec, mask_spec]
        args = (
            qg, k, k_scale[..., 0], v, v_scale[..., 0], mask3
        )
    out = pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=in_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, kvh, n_rep, d), q.dtype),
        interpret=interpret,
    )(*args)
    return out.reshape(b, h, d)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def fused_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, S, H, D]
    v: jax.Array,  # [B, S, H, D]
    mask: jax.Array,  # [B, S] 1 = keep
    bias: jax.Array | None = None,  # [1, H, S, S] additive (T5 rel-pos)
    scale: float | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Drop-in for ``common.mha_attention(q, k, v, mask=broadcast)`` on
    the encoder self-attention shapes; returns [B, S, H, D]."""
    from jax.experimental import pallas as pl

    b, s, h, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    # [B, S, H, D] -> [B, H, S, D]: per-(b,h) tiles are contiguous for
    # the grid; XLA fuses the transposes into neighbors.
    qt, kt, vt = (jnp.transpose(x, (0, 2, 1, 3)) for x in (q, k, v))
    bhsd = pl.BlockSpec((1, 1, s, d), lambda i, j: (i, j, 0, 0))
    # TPU tiling wants the mask block's trailing dims to equal the array
    # dims, so carry it as [B, 1, S] with a (1, 1, S) block.
    mask3 = mask.astype(jnp.int32)[:, None, :]
    mask_spec = pl.BlockSpec((1, 1, s), lambda i, j: (i, 0, 0))
    if bias is None:
        kernel = functools.partial(_attn_kernel, scale=scale)
        in_specs = [bhsd, bhsd, bhsd, mask_spec]
        args = (qt, kt, vt, mask3)
    else:
        # One [S, S] head-slice of the shared bias per grid step.
        kernel = functools.partial(_attn_kernel_bias, scale=scale)
        in_specs = [
            bhsd, bhsd, bhsd, mask_spec,
            pl.BlockSpec((1, 1, s, s), lambda i, j: (0, j, 0, 0)),
        ]
        args = (qt, kt, vt, mask3, bias)
    out = pl.pallas_call(
        kernel,
        grid=(b, h),
        in_specs=in_specs,
        out_specs=bhsd,
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        interpret=interpret,
    )(*args)
    return jnp.transpose(out, (0, 2, 1, 3))
