"""Paged-attention decode: fused attention over a block-paged KV pool.

Paged mode (``PAGED_KV=1``) stores the KV cache as a pool of
fixed-size token blocks ``[NB, BS, KVH, D]`` shared by every live
stream, with a per-row block table mapping logical position
``p -> pool[table[row, p // BS], p % BS]``.  This module is the
device-side half:

- ``gather_pages``: XLA fallback — materialize a row's dense
  ``[B, W, KVH, D]`` view through the table (one ``take``; XLA fuses
  it into the consumer).  The models' paged decode steps attend over
  this view with their EXISTING attention code, which is what makes
  paged decode token-identical to the contiguous layout by
  construction.
- ``paged_decode_attention``: Pallas kernel — grid ``(B, NB)`` with
  the block table as a scalar-prefetch operand, so each program DMAs
  exactly one of its row's blocks HBM->VMEM (the gather never
  materializes in HBM) and folds it into an online-softmax
  accumulator, FlashAttention-style.  Composes with ``QUANT_KV=int8``:
  payloads cross at int8 width with per-token-head f32 scales riding
  in their own paged pool, dequantized in VMEM like
  ``ops/attention.decode_attention``.  ``interpret=True`` runs the
  same kernel on CPU (the test/fallback path, same pattern as
  ``parallel/ring.py``).

Sentinel table entries (freed slots) must be clamped to a real block
id by the caller — out-of-range ids would index past the pool — and
masked via ``key_valid``; ``gather_pages`` clamps internally.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp


def gather_pages(pool: jax.Array, table: jax.Array, block_size: int) -> jax.Array:
    """Dense view of each row's blocks: ``[NB, BS, ...] x [B, T]`` ->
    ``[B, T*BS, ...]``.  Out-of-range table ids (the freed-slot
    sentinel) clamp to the last block; callers mask those positions
    with ``key_valid``, and clamped garbage is finite (pools are
    zero-initialized), so a masked softmax stays well-behaved."""
    nb = pool.shape[0]
    flat = pool.reshape((nb * block_size,) + pool.shape[2:])
    idx = (
        jnp.clip(table, 0, nb - 1)[:, :, None] * block_size
        + jnp.arange(block_size)[None, None, :]
    )  # [B, T, BS]
    b, t, _ = idx.shape
    return jnp.take(flat, idx.reshape(b, t * block_size), axis=0)


def scatter_pages(
    pool: jax.Array, table_row: jax.Array, values: jax.Array,
    block_size: int, start: int = 0,
) -> jax.Array:
    """Write ``values`` ``[W, ...]`` at logical positions
    ``start..start+W-1`` of ONE row's blocks.  Positions whose table
    entry is out of range (sentinel) drop — the paged insert relies on
    this for pad regions and freed slots."""
    nb = pool.shape[0]
    w = values.shape[0]
    flat = pool.reshape((nb * block_size,) + pool.shape[2:])
    p = start + jnp.arange(w)
    blk = jnp.take(table_row, p // block_size, mode="fill", fill_value=nb)
    dest = blk * block_size + p % block_size  # OOB where sentinel
    flat = flat.at[dest].set(values.astype(pool.dtype), mode="drop")
    return flat.reshape(pool.shape)


def _paged_body(tbl_ref, q_ref, k_ref, ks_ref, v_ref, vs_ref, valid_ref,
                o_ref, m_scr, l_scr, a_scr, *, scale: float, kvh: int):
    """One (row, block) grid step: fold block j of row b into the
    row's online-softmax accumulators; finalize on the last block.
    Blocks: q/o [1, KVH, R, D]; k/v [1, BS, KVH, D] (int8 payloads
    with ks/vs [1, BS, KVH] scales on the quantized path); valid
    [1, 1, BS].  Scratch (f32, VMEM): m/l [KVH, R], acc [KVH, R, D] —
    persistent across the sequential block axis, reset at j == 0."""
    from jax.experimental import pallas as pl

    j = pl.program_id(1)
    nb = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -1e30)
        l_scr[...] = jnp.zeros_like(l_scr)
        a_scr[...] = jnp.zeros_like(a_scr)

    valid = valid_ref[0, 0]  # [BS]
    ks_all = None if ks_ref is None else ks_ref[0].astype(jnp.float32)
    vs_all = None if vs_ref is None else vs_ref[0].astype(jnp.float32)
    for g in range(kvh):
        q = q_ref[0, g].astype(jnp.float32)  # [R, D]
        k = k_ref[0, :, g].astype(jnp.float32)  # [BS, D]
        if ks_all is not None:
            k = k * ks_all[:, g:g + 1]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [R, BS]
        s = jnp.where(valid[None, :] != 0, s, jnp.float32(-1e30))
        m_prev = m_scr[g]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        l_scr[g] = l_scr[g] * corr + p.sum(axis=-1)
        v = v_ref[0, :, g].astype(jnp.float32)
        if vs_all is not None:
            v = v * vs_all[:, g:g + 1]
        a_scr[g] = a_scr[g] * corr[:, None] + jax.lax.dot_general(
            p, v, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[g] = m_new

    @pl.when(j == nb - 1)
    def _finalize():
        o_ref[0] = (
            a_scr[...] / jnp.maximum(l_scr[...], 1e-20)[..., None]
        ).astype(o_ref.dtype)


def _paged_kernel(tbl_ref, q_ref, k_ref, v_ref, valid_ref, o_ref,
                  m_scr, l_scr, a_scr, *, scale: float, kvh: int):
    _paged_body(tbl_ref, q_ref, k_ref, None, v_ref, None, valid_ref,
                o_ref, m_scr, l_scr, a_scr, scale=scale, kvh=kvh)


def _paged_kernel_kv8(tbl_ref, q_ref, k_ref, ks_ref, v_ref, vs_ref,
                      valid_ref, o_ref, m_scr, l_scr, a_scr, *,
                      scale: float, kvh: int):
    _paged_body(tbl_ref, q_ref, k_ref, ks_ref, v_ref, vs_ref, valid_ref,
                o_ref, m_scr, l_scr, a_scr, scale=scale, kvh=kvh)


@functools.partial(jax.jit, static_argnames=("block_size", "scale", "interpret"))
def paged_decode_attention(
    q: jax.Array,  # [B, H, D] — one query per row
    k_pool: jax.Array,  # [NB, BS, KVH, D] dense, or int8 payload
    v_pool: jax.Array,
    table: jax.Array,  # [B, T] block ids (caller clamps sentinels)
    key_valid: jax.Array,  # [B, T*BS] 1 = attend
    block_size: int,
    k_scale: jax.Array | None = None,  # [NB, BS, KVH, 1] -> int8 path
    v_scale: jax.Array | None = None,
    scale: float | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Fused paged decode attention; returns ``[B, H, D]``.

    Grid (B, T): program (b, j) DMAs block ``table[b, j]`` of the pool
    into VMEM via the scalar-prefetched table — HBM traffic is exactly
    the row's live blocks, never a materialized dense gather — and
    accumulates FlashAttention-style (the block axis is sequential on
    TPU, so the VMEM scratch carries m/l/acc across it).  VMEM per
    program is one [BS, KVH, D] K+V block pair + [KVH, R, D] f32
    accumulators: ~50 KB at BS=16, KVH=4, D=64 — tiny, so pool size
    never hits a VMEM wall (the whole-slab decode kernel's limit)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, d = q.shape
    nb_pool, bs, kvh, _ = k_pool.shape
    t = table.shape[1]
    n_rep = h // kvh
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    qg = q.reshape(b, kvh, n_rep, d)
    tbl = jnp.clip(table, 0, nb_pool - 1).astype(jnp.int32)
    validb = key_valid.astype(jnp.int32).reshape(b, t, bs)

    q_spec = pl.BlockSpec((1, kvh, n_rep, d), lambda i, j, tb: (i, 0, 0, 0))
    kv_spec = pl.BlockSpec((1, bs, kvh, d), lambda i, j, tb: (tb[i, j], 0, 0, 0))
    valid_spec = pl.BlockSpec((1, 1, bs), lambda i, j, tb: (i, j, 0))
    scratch = [
        pltpu.VMEM((kvh, n_rep), jnp.float32),
        pltpu.VMEM((kvh, n_rep), jnp.float32),
        pltpu.VMEM((kvh, n_rep, d), jnp.float32),
    ]
    if k_scale is None:
        kernel = functools.partial(_paged_kernel, scale=scale, kvh=kvh)
        in_specs = [q_spec, kv_spec, kv_spec, valid_spec]
        args = (tbl, qg, k_pool, v_pool, validb)
    else:
        sc_spec = pl.BlockSpec((1, bs, kvh), lambda i, j, tb: (tb[i, j], 0, 0))
        kernel = functools.partial(_paged_kernel_kv8, scale=scale, kvh=kvh)
        in_specs = [q_spec, kv_spec, sc_spec, kv_spec, sc_spec, valid_spec]
        args = (tbl, qg, k_pool, k_scale[..., 0], v_pool, v_scale[..., 0], validb)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, t),
        in_specs=in_specs,
        out_specs=q_spec,
        scratch_shapes=scratch,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kvh, n_rep, d), q.dtype),
        interpret=interpret,
    )(*args)
    return out.reshape(b, h, d)


def paged_attention_ref(
    q: jax.Array, k_pool: jax.Array, v_pool: jax.Array, table: jax.Array,
    key_valid: jax.Array, block_size: int,
    k_scale: jax.Array | None = None, v_scale: jax.Array | None = None,
    scale: float | None = None,
) -> jax.Array:
    """jnp reference for the kernel: gather the dense view, dequantize,
    and run masked softmax attention in f32.  Also the XLA serving
    fallback shape the models reproduce inline."""
    b, h, d = q.shape
    kvh = k_pool.shape[2]
    n_rep = h // kvh
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    kd = gather_pages(k_pool, table, block_size).astype(jnp.float32)
    vd = gather_pages(v_pool, table, block_size).astype(jnp.float32)
    if k_scale is not None:
        kd = kd * gather_pages(k_scale, table, block_size).astype(jnp.float32)
        vd = vd * gather_pages(v_scale, table, block_size).astype(jnp.float32)
    qg = q.reshape(b, kvh, n_rep, d).astype(jnp.float32)
    s = jnp.einsum("bgrd,btgd->bgrt", qg, kd) * scale
    s = jnp.where(key_valid[:, None, None, :] != 0, s, jnp.float32(-1e30))
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bgrt,btgd->bgrd", p, vd)
    return o.reshape(b, h, d).astype(q.dtype)
