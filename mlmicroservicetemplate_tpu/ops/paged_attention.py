"""Paged-attention decode: fused attention over a block-paged KV pool.

Paged mode (``PAGED_KV=1``) stores the KV cache as a pool of
fixed-size token blocks ``[NB, BS, KVH, D]`` shared by every live
stream, with a per-row block table mapping logical position
``p -> pool[table[row, p // BS], p % BS]``.  This module is the
device-side half:

- ``gather_pages``: XLA fallback — materialize a row's dense
  ``[B, W, KVH, D]`` view through the table (one ``take``; XLA fuses
  it into the consumer).  The models' paged decode steps attend over
  this view with their EXISTING attention code, which is what makes
  paged decode token-identical to the contiguous layout by
  construction.
- ``paged_decode_attention``: Pallas kernel — grid ``(B, T/K)`` with
  the block table as a scalar-prefetch operand, so each program DMAs
  exactly K of its row's blocks HBM->VMEM (the gather never
  materializes in HBM) and folds them into an online-softmax
  accumulator, FlashAttention-style.  Composes with ``QUANT_KV=int8``:
  payloads cross at int8 width with per-token-head f32 scales riding
  in their own paged pool, dequantized in VMEM like
  ``ops/attention.decode_attention``.  ``interpret=True`` runs the
  same kernel on CPU (the test/fallback path, same pattern as
  ``parallel/ring.py``).

The kernel is parameterized by a :class:`Variant` (docs/
kernel_tuning.md): the axes ``ops/autotune.py`` sweeps at warmup.
Every variant computes the same masked online softmax in the same
f32 accumulators — variants rearrange WHERE work happens (grid
folding, head batching, dequant placement, MXU input width), never
WHAT is accumulated, which is what keeps each one token-identical to
``paged_attention_ref`` by construction.  The only lossy axis
(``accbf16`` scratch) is excluded from sweeps and reachable solely
through an explicit ``PALLAS_VARIANT`` pin.

Sentinel table entries (freed slots) must be clamped to a real block
id by the caller — out-of-range ids would index past the pool — and
masked via ``key_valid``; ``gather_pages`` clamps internally.
"""

from __future__ import annotations

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Variant:
    """One point in the paged/slab decode-kernel tuning space.

    - ``blocks_per_step``: K sequential pool blocks folded per grid
      step — the online-softmax fold then runs over ``K*BS`` keys at
      once (fewer, larger MXU issues; K must divide the table width so
      no pad-block path exists).  Paged kernel only; the whole-slab
      kernel has no block axis.
    - ``head_batched``: replace the static ``for g in range(kvh)``
      Python loop with ONE kvh-batched ``dot_general`` so every head's
      ``n_rep x D`` tile is in flight together (packs full 128-lane
      registers when a single group's R·D tile is narrow).
    - ``native_mxu``: feed bf16 payloads to the MXU at storage width
      (bf16 x bf16 -> f32 via ``preferred_element_type``) instead of
      upcasting to f32 copies in VMEM first.  Exact — f32 accumulation
      either way — and a no-op unless q and the pools are bf16.
    - ``fold_scales``: int8 path — keep payloads UNscaled through the
      QK/PV dots and fold the per-token-head scales into the score
      matrix / probability weights instead of dequantizing whole
      ``[KB, KVH, D]`` tiles ((q·k8)·ks == q·(k8·ks) in real
      arithmetic; the broadcast multiply shrinks from KB·D to R·KB
      elements per head).
    - ``acc_dtype``: online-softmax scratch width.  ``"f32"`` always;
      ``"bf16"`` is lossy, never enumerated by the sweep, and exists
      only for an explicit operator pin.
    """

    blocks_per_step: int = 1
    head_batched: bool = False
    native_mxu: bool = False
    fold_scales: bool = False
    acc_dtype: str = "f32"

    def key(self) -> str:
        parts = [f"b{self.blocks_per_step}"]
        if self.head_batched:
            parts.append("hb")
        if self.native_mxu:
            parts.append("nat")
        if self.fold_scales:
            parts.append("fs")
        if self.acc_dtype != "f32":
            parts.append(f"acc{self.acc_dtype}")
        return "-".join(parts)


DEFAULT_VARIANT = Variant()


def parse_variant(key: str | None) -> Variant:
    """``"b4-hb-fs"`` -> Variant; ``""``/None -> the default (the
    pre-autotuner kernel, exactly).  Raises ``ValueError`` on junk so
    a typo'd ``PALLAS_VARIANT`` pin fails at boot, not at trace."""
    if not key:
        return DEFAULT_VARIANT
    blocks, hb, nat, fs, acc = 1, False, False, False, "f32"
    for part in key.split("-"):
        if part.startswith("b") and part[1:].isdigit():
            blocks = int(part[1:])
            if blocks < 1:
                raise ValueError(f"variant {key!r}: blocks_per_step < 1")
        elif part == "hb":
            hb = True
        elif part == "nat":
            nat = True
        elif part == "fs":
            fs = True
        elif part.startswith("acc") and part[3:] in ("f32", "bf16"):
            acc = part[3:]
        else:
            raise ValueError(
                f"unknown variant token {part!r} in {key!r} (grammar: "
                f"b<K>[-hb][-nat][-fs][-accbf16])"
            )
    return Variant(blocks, hb, nat, fs, acc)


def gather_pages(pool: jax.Array, table: jax.Array, block_size: int) -> jax.Array:
    """Dense view of each row's blocks: ``[NB, BS, ...] x [B, T]`` ->
    ``[B, T*BS, ...]``.  Out-of-range table ids (the freed-slot
    sentinel) clamp to the last block; callers mask those positions
    with ``key_valid``, and clamped garbage is finite (pools are
    zero-initialized), so a masked softmax stays well-behaved."""
    nb = pool.shape[0]
    flat = pool.reshape((nb * block_size,) + pool.shape[2:])
    idx = (
        jnp.clip(table, 0, nb - 1)[:, :, None] * block_size
        + jnp.arange(block_size)[None, None, :]
    )  # [B, T, BS]
    b, t, _ = idx.shape
    return jnp.take(flat, idx.reshape(b, t * block_size), axis=0)


def scatter_pages(
    pool: jax.Array, table_row: jax.Array, values: jax.Array,
    block_size: int, start: int = 0,
) -> jax.Array:
    """Write ``values`` ``[W, ...]`` at logical positions
    ``start..start+W-1`` of ONE row's blocks.  Positions whose table
    entry is out of range (sentinel) drop — the paged insert relies on
    this for pad regions and freed slots."""
    nb = pool.shape[0]
    w = values.shape[0]
    flat = pool.reshape((nb * block_size,) + pool.shape[2:])
    p = start + jnp.arange(w)
    blk = jnp.take(table_row, p // block_size, mode="fill", fill_value=nb)
    dest = blk * block_size + p % block_size  # OOB where sentinel
    flat = flat.at[dest].set(values.astype(pool.dtype), mode="drop")
    return flat.reshape(pool.shape)


def _fold_block(q_ref, k_blk, ks_blk, v_blk, vs_blk, valid, m_scr, l_scr,
                a_scr, *, scale: float, kvh: int, var: Variant):
    """Fold one [KB, KVH, D] key/value block into the online-softmax
    accumulators.  ``k_blk``/``v_blk`` are raw payloads (f32/bf16, or
    int8 when ``ks_blk``/``vs_blk`` carry the [KB, KVH] f32 scales);
    ``valid`` is the block's [KB] mask.  Scratch m/l [KVH, R] and
    acc [KVH, R, D] read/write in ``var.acc_dtype``."""
    f32 = jnp.float32
    quant = ks_blk is not None
    native = var.native_mxu and not quant and (
        q_ref.dtype == jnp.bfloat16 and k_blk.dtype == jnp.bfloat16
    )

    def up(x):  # payload -> dot operand
        return x if native else x.astype(f32)

    if quant and not var.fold_scales:
        k_blk = k_blk.astype(f32) * ks_blk[:, :, None]
        v_blk = v_blk.astype(f32) * vs_blk[:, :, None]
        quant = False  # dequantized: downstream treats as dense
    elif quant:
        k_blk = k_blk.astype(f32)
        v_blk = v_blk.astype(f32)

    if var.head_batched:
        q = up(q_ref[0])  # [KVH, R, D]
        # Batched over KVH: q [KVH, R, D] x k [KB, KVH, D] -> [KVH, R, KB]
        s = jax.lax.dot_general(
            q, up(k_blk),
            dimension_numbers=(((2,), (2,)), ((0,), (1,))),
            preferred_element_type=f32,
        )
        if quant:  # fold_scales: ks [KB, KVH] -> [KVH, 1, KB]
            s = s * jnp.transpose(ks_blk)[:, None, :]
        s = s * scale
        s = jnp.where(valid[None, None, :] != 0, s, f32(-1e30))
        m_prev = m_scr[...].astype(f32)
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m_prev - m_new)
        l_scr[...] = (
            l_scr[...].astype(f32) * corr + p.sum(axis=-1)
        ).astype(l_scr.dtype)
        if quant:  # fold_scales: vs [KB, KVH] -> [KVH, 1, KB]
            p = p * jnp.transpose(vs_blk)[:, None, :]
        # p [KVH, R, KB] x v [KB, KVH, D] -> [KVH, R, D]
        pv = jax.lax.dot_general(
            p, up(v_blk),
            dimension_numbers=(((2,), (0,)), ((0,), (1,))),
            preferred_element_type=f32,
        )
        a_scr[...] = (
            a_scr[...].astype(f32) * corr[..., None] + pv
        ).astype(a_scr.dtype)
        m_scr[...] = m_new.astype(m_scr.dtype)
        return

    for g in range(kvh):
        q = up(q_ref[0, g])  # [R, D]
        k = up(k_blk[:, g])  # [KB, D]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=f32,
        )  # [R, KB]
        if quant:
            s = s * ks_blk[None, :, g]
        s = s * scale
        s = jnp.where(valid[None, :] != 0, s, f32(-1e30))
        m_prev = m_scr[g].astype(f32)
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        l_scr[g] = (l_scr[g].astype(f32) * corr + p.sum(axis=-1)).astype(
            l_scr.dtype
        )
        if quant:
            p = p * vs_blk[None, :, g]
        pv = jax.lax.dot_general(
            p, up(v_blk[:, g]),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=f32,
        )
        a_scr[g] = (a_scr[g].astype(f32) * corr[:, None] + pv).astype(
            a_scr.dtype
        )
        m_scr[g] = m_new.astype(m_scr.dtype)


def _paged_kernel_v(*refs, scale: float, kvh: int, bs: int, quant: bool,
                    var: Variant):
    """Grid step (b, j): fold blocks ``table[b, j*K .. j*K+K-1]`` into
    row b's accumulators; finalize on the last step.  Ref layout:
    tbl (prefetch), q [1, KVH, R, D], then K k-blocks [1, BS, KVH, D]
    (+K [1, BS, KVH] k-scales when quant), K v-blocks (+K v-scales),
    valid [1, 1, K*BS], output, then m/l/acc scratch."""
    from jax.experimental import pallas as pl

    K = var.blocks_per_step
    it = iter(refs)
    next(it)  # tbl_ref: consumed by the index maps, not the body
    q_ref = next(it)
    k_refs = [next(it) for _ in range(K)]
    ks_refs = [next(it) for _ in range(K)] if quant else [None] * K
    v_refs = [next(it) for _ in range(K)]
    vs_refs = [next(it) for _ in range(K)] if quant else [None] * K
    valid_ref = next(it)
    o_ref = next(it)
    m_scr, l_scr, a_scr = next(it), next(it), next(it)

    j = pl.program_id(1)
    nsteps = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -1e30)
        l_scr[...] = jnp.zeros_like(l_scr)
        a_scr[...] = jnp.zeros_like(a_scr)

    if K == 1:
        k_blk = k_refs[0][0]
        v_blk = v_refs[0][0]
        ks_blk = ks_refs[0][0].astype(jnp.float32) if quant else None
        vs_blk = vs_refs[0][0].astype(jnp.float32) if quant else None
    else:
        k_blk = jnp.concatenate([r[0] for r in k_refs], axis=0)
        v_blk = jnp.concatenate([r[0] for r in v_refs], axis=0)
        ks_blk = (
            jnp.concatenate([r[0] for r in ks_refs], axis=0).astype(
                jnp.float32
            ) if quant else None
        )
        vs_blk = (
            jnp.concatenate([r[0] for r in vs_refs], axis=0).astype(
                jnp.float32
            ) if quant else None
        )
    valid = valid_ref[0, 0]  # [K*BS]
    _fold_block(q_ref, k_blk, ks_blk, v_blk, vs_blk, valid, m_scr, l_scr,
                a_scr, scale=scale, kvh=kvh, var=var)

    @pl.when(j == nsteps - 1)
    def _finalize():
        acc = a_scr[...].astype(jnp.float32)
        l = l_scr[...].astype(jnp.float32)
        o_ref[0] = (acc / jnp.maximum(l, 1e-20)[..., None]).astype(o_ref.dtype)


def tp_shard_attention(
    fn, tp: int, q, kv_args: tuple, rep_args: tuple,
    scale_args: tuple = (),
):
    """Run a decode-attention kernel under ``shard_map`` over the
    serving TP mesh: each shard attends over its LOCAL heads (q axis 1,
    KV heads axis 2) — attention is embarrassingly parallel across
    heads, so the body carries no collective; the row-parallel
    all-reduce lands after the attn-out matmul, where XLA's sharding
    propagation puts it.  ``rep_args`` (tables, masks) replicate.

    The wrapper is only reachable at TP>1 — TP=1 call sites never
    build a mesh (the no-mesh pin in tests/test_tp_serving.py)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.tpserve import serving_tp_mesh

    h = q.shape[1]
    kvh = kv_args[0].shape[2]
    if h % tp or kvh % tp:
        raise ValueError(
            f"TP={tp} must divide query heads ({h}) and KV heads ({kvh})"
        )
    heads4 = P(None, None, "tp", None)
    args = (q,) + tuple(kv_args) + tuple(rep_args) + tuple(scale_args)
    in_specs = (
        [P(None, "tp", None)]
        + [heads4] * len(kv_args)
        + [P(*([None] * a.ndim)) for a in rep_args]
        + [heads4] * len(scale_args)
    )
    mesh = serving_tp_mesh(tp)
    return shard_map(
        fn, mesh=mesh, in_specs=tuple(in_specs),
        out_specs=P(None, "tp", None), check_rep=False,
    )(*args)


@functools.partial(
    jax.jit,
    static_argnames=("block_size", "scale", "interpret", "variant", "tp"),
)
def paged_decode_attention(
    q: jax.Array,  # [B, H, D] — one query per row
    k_pool: jax.Array,  # [NB, BS, KVH, D] dense, or int8 payload
    v_pool: jax.Array,
    table: jax.Array,  # [B, T] block ids (caller clamps sentinels)
    key_valid: jax.Array,  # [B, T*BS] 1 = attend
    block_size: int,
    k_scale: jax.Array | None = None,  # [NB, BS, KVH, 1] -> int8 path
    v_scale: jax.Array | None = None,
    scale: float | None = None,
    interpret: bool = False,
    variant: str = "",
    tp: int = 1,
) -> jax.Array:
    """Fused paged decode attention; returns ``[B, H, D]``.

    Grid (B, T/K): program (b, j) DMAs blocks ``table[b, j*K..]`` of
    the pool into VMEM via the scalar-prefetched table — HBM traffic
    is exactly the row's live blocks, never a materialized dense
    gather — and accumulates FlashAttention-style (the block axis is
    sequential on TPU, so the VMEM scratch carries m/l/acc across it).
    ``variant`` selects a tuning point (see :class:`Variant`); K must
    divide the table width T (``ops/autotune.py`` only enumerates
    divisors, so serving never needs a pad-block path).  VMEM per
    program is K [BS, KVH, D] K+V block pairs + [KVH, R, D] f32
    accumulators — ``autotune.paged_vmem_bytes`` is the budget model.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if tp > 1:
        opt = () if k_scale is None else (k_scale, v_scale)

        def local(q_l, kp, vp, tbl, valid, *sc):
            ks, vs = sc if sc else (None, None)
            return paged_decode_attention(
                q_l, kp, vp, tbl, valid, block_size, ks, vs,
                scale=scale, interpret=interpret, variant=variant,
            )

        return tp_shard_attention(
            local, tp, q, (k_pool, v_pool), (table, key_valid), opt
        )

    var = parse_variant(variant)
    K = var.blocks_per_step
    b, h, d = q.shape
    nb_pool, bs, kvh, _ = k_pool.shape
    t = table.shape[1]
    n_rep = h // kvh
    if t % K != 0:
        raise ValueError(
            f"variant {var.key()!r}: blocks_per_step={K} does not divide "
            f"table width {t}"
        )
    tsteps = t // K
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    quant = k_scale is not None
    acc_jnp = jnp.float32 if var.acc_dtype == "f32" else jnp.bfloat16
    qg = q.reshape(b, kvh, n_rep, d)
    tbl = jnp.clip(table, 0, nb_pool - 1).astype(jnp.int32)
    validb = key_valid.astype(jnp.int32).reshape(b, tsteps, K * bs)

    q_spec = pl.BlockSpec((1, kvh, n_rep, d), lambda i, j, tb: (i, 0, 0, 0))
    kv_specs = [
        pl.BlockSpec(
            (1, bs, kvh, d),
            functools.partial(
                lambda i, j, tb, _m: (tb[i, j * K + _m], 0, 0, 0), _m=m
            ),
        )
        for m in range(K)
    ]
    sc_specs = [
        pl.BlockSpec(
            (1, bs, kvh),
            functools.partial(
                lambda i, j, tb, _m: (tb[i, j * K + _m], 0, 0), _m=m
            ),
        )
        for m in range(K)
    ]
    valid_spec = pl.BlockSpec((1, 1, K * bs), lambda i, j, tb: (i, j, 0))
    scratch = [
        pltpu.VMEM((kvh, n_rep), acc_jnp),
        pltpu.VMEM((kvh, n_rep), acc_jnp),
        pltpu.VMEM((kvh, n_rep, d), acc_jnp),
    ]
    kernel = functools.partial(
        _paged_kernel_v, scale=scale, kvh=kvh, bs=bs, quant=quant, var=var
    )
    if not quant:
        in_specs = [q_spec, *kv_specs, *kv_specs, valid_spec]
        args = (tbl, qg, *([k_pool] * K), *([v_pool] * K), validb)
    else:
        in_specs = [q_spec, *kv_specs, *sc_specs, *kv_specs, *sc_specs,
                    valid_spec]
        args = (
            tbl, qg, *([k_pool] * K), *([k_scale[..., 0]] * K),
            *([v_pool] * K), *([v_scale[..., 0]] * K), validb,
        )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, tsteps),
        in_specs=in_specs,
        out_specs=q_spec,
        scratch_shapes=scratch,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kvh, n_rep, d), q.dtype),
        interpret=interpret,
    )(*args)
    return out.reshape(b, h, d)


def paged_attention_ref(
    q: jax.Array, k_pool: jax.Array, v_pool: jax.Array, table: jax.Array,
    key_valid: jax.Array, block_size: int,
    k_scale: jax.Array | None = None, v_scale: jax.Array | None = None,
    scale: float | None = None,
) -> jax.Array:
    """jnp reference for the kernel: gather the dense view, dequantize,
    and run masked softmax attention in f32.  Also the XLA serving
    fallback shape the models reproduce inline."""
    b, h, d = q.shape
    kvh = k_pool.shape[2]
    n_rep = h // kvh
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    kd = gather_pages(k_pool, table, block_size).astype(jnp.float32)
    vd = gather_pages(v_pool, table, block_size).astype(jnp.float32)
    if k_scale is not None:
        kd = kd * gather_pages(k_scale, table, block_size).astype(jnp.float32)
        vd = vd * gather_pages(v_scale, table, block_size).astype(jnp.float32)
    qg = q.reshape(b, kvh, n_rep, d).astype(jnp.float32)
    s = jnp.einsum("bgrd,btgd->bgrt", qg, kd) * scale
    s = jnp.where(key_valid[:, None, None, :] != 0, s, jnp.float32(-1e30))
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bgrt,btgd->bgrd", p, vd)
    return o.reshape(b, h, d).astype(q.dtype)
