"""Fleet routing policy: which replica serves this request.

Pure policy over a list of candidate replicas (engine/fleet.py owns
the replicas themselves), so the ordering rules are unit-testable
without engines.  The decision ladder, per the λScale-style
data-parallel serving design (arXiv 2502.09922):

1. **Health** — the fleet hands this router only replicas whose
   breaker admits traffic (closed, or half-open probing); dead and
   open-breaker replicas never appear.
2. **Prefix affinity** — a prompt whose cached prefix lives on some
   replica's prefix cache routes there: the hit saves the whole
   prefix prefill, worth far more than marginal load spread.  Probed
   with ``PrefixCache.peek`` (non-mutating — a probe must not skew
   hit stats or LRU recency on replicas the request never reaches).
   Ties (same longest prefix bucket) break by load.
3. **Least-loaded** — committed KV bytes (the pool-authoritative
   ledger) plus queue depth, normalized so neither term drowns the
   other.

``FLEET_ROUTE=rr`` replaces 2-3 with plain round-robin over the
healthy set — the A/B baseline that shows what affinity+load buy.
"""

from __future__ import annotations

import threading

import numpy as np

ROUTE_LEAST = "least"
ROUTE_RR = "rr"


def replica_load(replica) -> float:
    """Load score: committed KV bytes (normalized to blocks-ish scale)
    + waiting/active stream count.  Works on any object exposing
    ``cdl`` (queue + active) and an optional admission controller.

    Multi-chip fleets divide by the replica's TP width: a TP=2 group
    owns twice the compute and HBM of a single-device sibling, so the
    same absolute load leaves it comparatively less full.  Width 1
    (every pre-multichip replica) keeps the score bit-identical."""
    cdl = replica.cdl
    n = (
        len(cdl.active) + cdl.queue.qsize() + len(cdl._prefilling)
        + len(getattr(cdl, "_swapping", ()))
    )
    adm = getattr(cdl, "admission", None)
    kv = float(adm.committed_bytes) if adm is not None else 0.0
    # One stream-slot of load per MB committed: coarse, but keeps a
    # KV-heavy replica from looking idle on stream count alone.
    return (n + kv / 1e6) / max(1, int(getattr(replica, "width", 1) or 1))


class Router:
    """Stateless policy + the round-robin cursor."""

    def __init__(self, policy: str = ROUTE_LEAST):
        policy = (policy or ROUTE_LEAST).lower()
        if policy not in (ROUTE_LEAST, ROUTE_RR):
            raise ValueError(f"FLEET_ROUTE must be least|rr, got {policy!r}")
        self.policy = policy
        self._rr = 0
        self._lock = threading.Lock()

    def _affinity(self, replica, feats: dict) -> int:
        """Longest cached prefix bucket this replica holds for the
        prompt (0 = none / no cache / non-text request)."""
        eng = getattr(replica, "engine", None)
        cache = getattr(eng, "prefix_cache", None)
        if cache is None or "input_ids" not in feats:
            return 0
        L = int(feats.get("length", 0))
        if L <= 1:
            return 0
        ids = np.asarray(feats["input_ids"], np.int32)[:L]
        return int(cache.peek(ids, L))

    def order(self, healthy: list, feats: dict) -> list:
        """Candidate replicas, best first.  The fleet tries them in
        order (a shed on the first falls through to the next)."""
        if not healthy:
            return []
        if self.policy == ROUTE_RR:
            with self._lock:
                k = self._rr % len(healthy)
                self._rr += 1
            return healthy[k:] + healthy[:k]
        scored = [
            (-self._affinity(r, feats), replica_load(r), i, r)
            for i, r in enumerate(healthy)
        ]
        scored.sort(key=lambda t: t[:3])
        return [r for *_, r in scored]

    def pick_adopter(self, healthy: list):
        """Failover target for one checkpointed stream: round-robin
        over the healthy set so a dead replica's streams SPREAD
        instead of dog-piling one survivor."""
        if not healthy:
            return None
        with self._lock:
            k = self._rr % len(healthy)
            self._rr += 1
        return healthy[k]
