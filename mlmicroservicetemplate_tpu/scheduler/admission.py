"""Admission control: priority classes, deadlines, KV-footprint budget,
drain gate.

Sits between the HTTP layer and the wait queues (``policy.py``).  Three
decisions happen HERE, at submit time, instead of being discovered
deep in the serving path:

- **Classification**: ``X-Priority`` (interactive | batch, config
  default) and ``X-Deadline-Ms`` (config default; 0 = none) become the
  queue's scheduling fields.
- **KV budget**: each request's cache footprint is estimated up front
  (``InferenceEngine.kv_bytes_estimate`` — prompt bucket + decode
  budget + model dims + the active QUANT_KV dtype).  Work that could
  NEVER fit the budget sheds immediately (503 ``kv_budget``); work that
  would overcommit the CURRENTLY committed HBM is down-classed to
  ``batch`` and waits for capacity instead of failing at slot-insert.
  The budget then gates DEQUEUE: an item leaves the wait queue only
  when its reservation fits.
- **Drain**: once ``draining`` flips (SIGTERM), every new admission
  sheds with 503 ``drain`` while admitted work runs to completion.

The controller is shared by the batcher's request queue and the
continuous decode loop's stream queue, so the committed-bytes ledger
covers both.
"""

from __future__ import annotations

import threading
import time

from ..utils import metrics
from .policy import BATCH, CLASSES, INTERACTIVE, QueueFullError


class AdmissionController:
    """Shared admission policy + committed-KV ledger for one model."""

    def __init__(self, cfg, engine=None):
        self.engine = engine
        self.model = getattr(
            getattr(engine, "bundle", None), "name", "unknown"
        )
        default = str(
            getattr(cfg, "priority_default", INTERACTIVE) or INTERACTIVE
        ).lower()
        self.default_class = default if default in CLASSES else INTERACTIVE
        self.default_deadline_ms = float(
            getattr(cfg, "deadline_ms", 0.0) or 0.0
        )
        self.kv_budget_bytes = int(
            float(getattr(cfg, "kv_budget_mb", 0.0) or 0.0) * 1e6
        )
        self._committed = 0
        self._lock = threading.Lock()
        self.draining = False

    # -- classification ------------------------------------------------

    def classify(self, feats: dict) -> tuple[str, float | None]:
        """(klass, absolute monotonic deadline | None) from the request's
        scheduling fields (set by the API layer off the X-Priority /
        X-Deadline-Ms headers), with config defaults."""
        klass = str(feats.get("priority") or self.default_class).lower()
        if klass not in CLASSES:  # header syntax is 400-checked upstream
            klass = self.default_class
        dl_ms = feats.get("deadline_ms")
        dl_ms = float(dl_ms) if dl_ms is not None else self.default_deadline_ms
        deadline = time.monotonic() + dl_ms / 1e3 if dl_ms > 0 else None
        return klass, deadline

    # -- KV budget -----------------------------------------------------

    def kv_bytes(self, feats: dict) -> int:
        est = getattr(self.engine, "kv_bytes_estimate", None)
        return int(est(feats)) if est is not None else 0

    def admit(self, feats: dict, klass: str) -> tuple[str, int]:
        """Drain + KV-budget gate.  Returns (possibly down-classed
        klass, kv bytes); raises ``QueueFullError`` with reason
        ``drain`` or ``kv_budget``."""
        if self.draining:
            raise QueueFullError(
                "server is draining", reason="drain", retry_after_s=5.0
            )
        kv = self.kv_bytes(feats)
        if self.kv_budget_bytes:
            if kv > self.kv_budget_bytes:
                raise QueueFullError(
                    f"request KV footprint {kv}B exceeds the "
                    f"{self.kv_budget_bytes}B budget",
                    reason="kv_budget",
                )
            with self._lock:
                over = self._committed + kv > self.kv_budget_bytes
            if over and klass == INTERACTIVE:
                # Transient overcommit: wait out the pressure in the
                # lower class instead of failing at slot-insert.
                klass = BATCH
        return klass, kv

    def fits(self, item) -> bool:
        """Dequeue gate: may this waiter's KV reservation commit now?"""
        if not self.kv_budget_bytes:
            return True
        with self._lock:
            return self._committed + getattr(item, "kv", 0) \
                <= self.kv_budget_bytes

    def reserve(self, item) -> None:
        kv = getattr(item, "kv", 0)
        if kv and not item.kv_held:
            with self._lock:
                self._committed += kv
                metrics.KV_COMMITTED.labels(self.model).set(self._committed)
            item.kv_held = True

    def release(self, item) -> None:
        if getattr(item, "kv_held", False):
            with self._lock:
                self._committed -= item.kv
                metrics.KV_COMMITTED.labels(self.model).set(self._committed)
            item.kv_held = False

    @property
    def committed_bytes(self) -> int:
        with self._lock:
            return self._committed
