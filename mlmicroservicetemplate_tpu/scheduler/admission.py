"""Admission control: priority classes, deadlines, KV-footprint budget,
drain gate.

Sits between the HTTP layer and the wait queues (``policy.py``).  Three
decisions happen HERE, at submit time, instead of being discovered
deep in the serving path:

- **Classification**: ``X-Priority`` (interactive | batch, config
  default) and ``X-Deadline-Ms`` (config default; 0 = none) become the
  queue's scheduling fields.
- **KV budget**: each request's cache footprint is estimated up front
  (``InferenceEngine.kv_bytes_estimate`` — prompt bucket + decode
  budget + model dims + the active QUANT_KV dtype).  Work that could
  NEVER fit the budget sheds immediately (503 ``kv_budget``); work that
  would overcommit the CURRENTLY committed HBM is down-classed to
  ``batch`` and waits for capacity instead of failing at slot-insert.
  The budget then gates DEQUEUE: an item leaves the wait queue only
  when its reservation fits.
- **Drain**: once ``draining`` flips (SIGTERM), every new admission
  sheds with 503 ``drain`` while admitted work runs to completion.

The controller is shared by the batcher's request queue and the
continuous decode loop's stream queue, so the committed-bytes ledger
covers both.
"""

from __future__ import annotations

import threading
import time

from ..tenancy.accounts import QuotaExceeded
from ..utils import metrics, tracing
from .policy import BATCH, CLASSES, INTERACTIVE, QueueFullError


class AdmissionController:
    """Shared admission policy + committed-KV ledger for one model."""

    def __init__(self, cfg, engine=None):
        self.engine = engine
        self.model = getattr(
            getattr(engine, "bundle", None), "name", "unknown"
        )
        # Fleet replica label for the committed-KV gauges: each replica
        # runs its OWN controller over its OWN pool — per-replica
        # pool-authoritative ledgers under one fleet budget (the fleet
        # splits KV_BUDGET_MB across replicas; engine/fleet.py).
        self.replica = str(getattr(engine, "replica_id", 0))
        default = str(
            getattr(cfg, "priority_default", INTERACTIVE) or INTERACTIVE
        ).lower()
        self.default_class = default if default in CLASSES else INTERACTIVE
        self.default_deadline_ms = float(
            getattr(cfg, "deadline_ms", 0.0) or 0.0
        )
        self.kv_budget_bytes = int(
            float(getattr(cfg, "kv_budget_mb", 0.0) or 0.0) * 1e6
        )
        self._committed = 0
        self._lock = threading.Lock()
        self.draining = False
        # Paged mode (PAGED_KV=1): streams are accounted by the
        # engine's block pool — the EXACT ledger (allocated blocks ×
        # block bytes, growth and frees included) — instead of this
        # controller's ceiling ledger.  The byte ledger stays in place
        # for the non-stream batch path, gated against whatever the
        # pool hasn't claimed.
        self.paged = bool(getattr(engine, "paged_kv", False))
        self.pool = getattr(engine, "kv_pool", None)
        # TP width for the per-shard pool gauge: one logical pool whose
        # blocks split their heads axis across the 'tp' mesh — every
        # shard's residency is by construction identical.
        self.tp_width = int(getattr(
            getattr(engine, "replicas", None), "tp_width", 1
        ) or 1)
        # Elastic-fleet budget re-split (engine/fleet.py): a LEDGER cap
        # in blocks below the pool's physical size — the fleet re-sets
        # it on every scale/evict/rejoin event so the live replicas
        # together keep honoring ONE fleet budget even though each
        # pool's device buffers were sized at spawn time.  None
        # (default, and every static deployment) = the physical pool is
        # the ledger, bit-identical to the pre-elastic code.  The cap
        # binds ADMISSION; in-slot decode growth still runs against the
        # physical pool (a dry pool checkpoint-requeues, the existing
        # machinery), so it is a soft budget — docs/autoscaling.md.
        self.cap_blocks: int | None = None
        # Flight recorder (utils/tracing.py, engine-owned): admission's
        # down-class decisions land in the engine post-mortem ring.
        self.recorder = getattr(engine, "flight", None)
        # Per-tenant quota registry (tenancy/accounts.py; attached by
        # the batcher when TENANTS is configured).  None = no tenant
        # gate, bit-identical to pre-tenancy admission.
        self.tenants = None

    def set_tenants(self, registry) -> None:
        """Attach (or detach) the shared ``TenantRegistry``: every
        admission then charges the caller's tenant ledgers (concurrency
        occupancy, sliding-window tokens, committed KV) and sheds with
        reason ``quota`` → HTTP 429 + Retry-After when one is
        exhausted."""
        self.tenants = registry

    def _note_downclass(self, feats: dict, why: str) -> None:
        rid = str(feats.get("request_id") or "")
        tr = tracing.tracer()
        if tr is not None:
            tr.instant("downclass", cat="sched", rid=rid, why=why)
        if self.recorder is not None:
            self.recorder.event("downclass", rid=rid, why=why)

    def _pool_bytes(self) -> int:
        return self.pool.used_bytes if (self.paged and self.pool) else 0

    # -- elastic budget re-split (engine/fleet.py) ---------------------

    def ledger_blocks(self) -> int:
        """Blocks this replica's ledger may admit against: the physical
        pool, capped by the fleet's live budget share."""
        n = self.pool.num_blocks if self.pool is not None else 0
        if self.cap_blocks is not None:
            n = min(n, self.cap_blocks)
        return n

    def ledger_free_blocks(self) -> int:
        if self.pool is None:
            return 0
        return max(0, self.ledger_blocks() - self.pool.used_blocks)

    def set_budget(self, budget_bytes: int | None) -> None:
        """Re-point this replica's share of the fleet KV budget (called
        on every scale/evict/rejoin event).  Non-paged: the byte-ledger
        bound moves.  Paged: the block cap moves (never the physical
        pool — live streams hold its buffers).  None clears the split
        (single-replica semantics)."""
        if budget_bytes is None:
            self.cap_blocks = None
            return
        budget_bytes = int(budget_bytes)
        self.kv_budget_bytes = budget_bytes
        if self.paged and self.pool is not None:
            self.cap_blocks = max(
                1, budget_bytes // max(1, self.pool.block_bytes)
            )

    def note_pool(self) -> None:
        """Refresh the committed-bytes gauge off the pool (paged)."""
        if self.paged and self.pool:
            metrics.KV_COMMITTED.labels(self.model, self.replica).set(
                self._committed + self.pool.used_bytes
            )
            metrics.KV_POOL_BLOCKS.labels(
                self.model, self.replica, "used"
            ).set(self.pool.used_blocks)
            metrics.KV_POOL_BLOCKS.labels(
                self.model, self.replica, "free"
            ).set(self.pool.free_blocks)
            for shard in range(self.tp_width):
                metrics.KV_POOL_SHARD_BLOCKS.labels(
                    self.model, str(shard)
                ).set(self.pool.used_blocks)

    # -- classification ------------------------------------------------

    def classify(self, feats: dict) -> tuple[str, float | None]:
        """(klass, absolute monotonic deadline | None) from the request's
        scheduling fields (set by the API layer off the X-Priority /
        X-Deadline-Ms headers), with config defaults."""
        klass = str(feats.get("priority") or self.default_class).lower()
        if klass not in CLASSES:  # header syntax is 400-checked upstream
            klass = self.default_class
        dl_ms = feats.get("deadline_ms")
        dl_ms = float(dl_ms) if dl_ms is not None else self.default_deadline_ms
        deadline = time.monotonic() + dl_ms / 1e3 if dl_ms > 0 else None
        return klass, deadline

    # -- KV budget -----------------------------------------------------

    def kv_bytes(self, feats: dict) -> int:
        est = getattr(self.engine, "kv_bytes_estimate", None)
        return int(est(feats)) if est is not None else 0

    def kv_bytes_for_resume(self, feats: dict,
                            swap_tokens: int | None = None) -> int:
        """Footprint a checkpointed stream re-reserves at dequeue, off
        its CURRENT feats — the recast resume folds delivered tokens
        into the prompt, so the admission-time estimate can undershoot
        the new prompt bucket.  A stream checkpointed MID-PREFILL
        (chunked prefill: fatal fault, dry pool) holds zero blocks
        while it waits and re-reserves only its first prefill window —
        ``kv_blocks_estimate`` returns the chunked initial, never the
        whole-prompt estimate.

        ``swap_tokens`` (host KV tier, docs/kv-tiering.md): the resume
        is a host→device block prefetch covering exactly this many
        token positions, so the reservation is its TRUE cost — the
        prefetch blocks — not the first-window re-prefill estimate the
        recompute path would charge.  This covers every swap shape:
        full resume prompts, MID-PREFILL checkpoints (swap_tokens =
        the consumed prefix, which continues growing window-by-window
        after the prefetch), and journal-replay resumes whose KV
        promotes disk→host→device after a process restart
        (docs/durability.md) — the charge is always the blocks the
        prefetch will allocate up front."""
        if self.paged and self.pool is not None:
            if swap_tokens:
                from ..engine.kv_blocks import blocks_for

                need = blocks_for(
                    int(swap_tokens), int(self.engine.kv_block_size)
                )
                return need * self.pool.block_bytes
            initial, _ = self.engine.kv_blocks_estimate(feats)
            return initial * self.pool.block_bytes
        return self.kv_bytes(feats)

    def admit(self, feats: dict, klass: str) -> tuple[str, int]:
        """Drain + KV-budget gate.  Returns (possibly down-classed
        klass, kv bytes); raises ``QueueFullError`` with reason
        ``drain`` or ``kv_budget``.

        Paged mode swaps the ceiling math for the block ledger: a
        stream that could NEVER fit (its prompt bucket + its own
        decode budget in blocks exceeds the whole pool) sheds here;
        the returned kv is only the INITIAL commitment — prompt blocks
        plus the first chunk's block — and the decode loop grows it
        block-by-block against the pool."""
        if self.draining:
            raise QueueFullError(
                "server is draining", reason="drain", retry_after_s=5.0
            )
        klass, kv = self._admit_kv(feats, klass)
        self._quota_gate(feats, kv)
        return klass, kv

    def _admit_kv(self, feats: dict, klass: str) -> tuple[str, int]:
        if self.paged and self.pool is not None:
            initial, worst = self.engine.kv_blocks_estimate(feats)
            if worst > self.ledger_blocks():
                raise QueueFullError(
                    f"request needs {worst} KV blocks, ledger holds "
                    f"{self.ledger_blocks()}",
                    reason="kv_budget",
                )
            if self.ledger_free_blocks() < initial and klass == INTERACTIVE:
                # Transient pressure: wait it out in the lower class.
                klass = BATCH
                self._note_downclass(feats, "pool_pressure")
            return klass, initial * self.pool.block_bytes
        kv = self.kv_bytes(feats)
        if self.kv_budget_bytes:
            if kv > self.kv_budget_bytes:
                raise QueueFullError(
                    f"request KV footprint {kv}B exceeds the "
                    f"{self.kv_budget_bytes}B budget",
                    reason="kv_budget",
                )
            with self._lock:
                over = self._committed + kv > self.kv_budget_bytes
            if over and klass == INTERACTIVE:
                # Transient overcommit: wait out the pressure in the
                # lower class instead of failing at slot-insert.
                klass = BATCH
                self._note_downclass(feats, "kv_overcommit")
        return klass, kv

    # -- per-tenant quotas (tenancy/accounts.py) -----------------------

    def _quota_gate(self, feats: dict, kv: int) -> None:
        """Charge the caller's tenant ledgers and stash the lease in
        ``feats["_lease"]`` (released via ``release_lease``).  Runs
        LAST, after the service-wide gates: a request the service would
        shed anyway must not burn the tenant's window.  Token cost is
        the worst case — prompt length plus the clamped decode budget
        (``InferenceEngine.budget_for``) — so the window meters offered
        work, not realized luck."""
        reg = self.tenants
        if reg is None:
            return
        name = str(feats.get("tenant") or "")
        spec = reg.spec(name)
        if spec is None:
            return
        tokens = int(feats.get("length", 0) or 0)
        bf = getattr(self.engine, "budget_for", None)
        if bf is not None:
            tokens += int(bf(feats))
        try:
            feats["_lease"] = reg.admit(spec, tokens, int(kv))
        except QuotaExceeded as exc:
            reg.note_shed(name, "quota")
            raise QueueFullError(
                str(exc), reason="quota", retry_after_s=exc.retry_after_s
            ) from None

    def release_lease(self, feats) -> None:
        """Return a quota lease (idempotent; the lease pops off feats
        so double calls on shed/finish race-free no-op)."""
        lease = feats.pop("_lease", None) if isinstance(feats, dict) else None
        if lease is not None and self.tenants is not None:
            self.tenants.release(lease)

    def backfill_ok(self) -> bool:
        """Advisory pre-admission gate for bulk-job line claiming
        (jobs/executor.py): False while draining or while the KV
        ledger has no headroom at all, so the executor DEFERS the
        claim instead of bouncing off ``admit`` as a metered shed —
        backfill pressure must not pollute the shed counters the
        operator alerts on."""
        if self.draining:
            return False
        if self.paged and self.pool is not None:
            return self.ledger_free_blocks() > 0
        if self.kv_budget_bytes:
            with self._lock:
                return self._committed < self.kv_budget_bytes
        return True

    def fits(self, item) -> bool:
        """Dequeue gate: may this waiter's KV reservation commit now?

        Paged streams gate on FREE POOL BLOCKS for their initial
        commitment (the exact ledger); non-stream batch work keeps the
        byte ledger, measured against what the pool hasn't claimed."""
        if self.paged and self.pool is not None:
            if getattr(item, "is_stream", False):
                need = -(-getattr(item, "kv", 0) // self.pool.block_bytes)
                return self.ledger_free_blocks() >= need
            if not self.kv_budget_bytes:
                return True
            with self._lock:
                return (
                    self._committed + getattr(item, "kv", 0)
                    + self._pool_bytes() <= self.kv_budget_bytes
                )
        if not self.kv_budget_bytes:
            return True
        with self._lock:
            return self._committed + getattr(item, "kv", 0) \
                <= self.kv_budget_bytes

    def reserve(self, item) -> None:
        # A stream re-entering service (preemption resume, failover
        # adoption, journal replay) released its quota lease when it
        # checkpointed: re-charge OCCUPANCY (concurrency + KV, never
        # window tokens) unconditionally — started streams must not
        # convert into quota errors (tenancy/accounts.readmit).
        if self.tenants is not None:
            feats = getattr(item, "feats", None)
            if isinstance(feats, dict) and "_lease" not in feats:
                name = str(feats.get("tenant") or "")
                if self.tenants.spec(name) is not None:
                    feats["_lease"] = self.tenants.readmit(
                        name, int(getattr(item, "kv", 0))
                    )
        if self.paged and getattr(item, "is_stream", False):
            # The pool is the ledger: blocks commit at slot insert and
            # grow at chunk boundaries (engine/streams.py); nothing to
            # reserve here beyond refreshing the gauge.
            self.note_pool()
            return
        kv = getattr(item, "kv", 0)
        if kv and not item.kv_held:
            with self._lock:
                self._committed += kv
                metrics.KV_COMMITTED.labels(self.model, self.replica).set(
                    self._committed + self._pool_bytes()
                )
            item.kv_held = True

    def release(self, item) -> None:
        self.release_lease(getattr(item, "feats", None))
        if self.paged and getattr(item, "is_stream", False):
            self.note_pool()
            return
        if getattr(item, "kv_held", False):
            with self._lock:
                self._committed -= item.kv
                metrics.KV_COMMITTED.labels(self.model, self.replica).set(
                    self._committed + self._pool_bytes()
                )
            item.kv_held = False

    @property
    def committed_bytes(self) -> int:
        with self._lock:
            return self._committed + self._pool_bytes()
