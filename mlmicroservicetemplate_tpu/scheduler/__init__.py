"""L3 request scheduling: dynamic batching + SLA-aware admission.

The component the whole latency/throughput metric hinges on (SURVEY.md
§3.2): concurrent ``/predict`` requests accumulate into batches under a
max-batch-size (``max_batch=32``, BASELINE.json:10) + max-wait policy,
one jitted dispatch serves the whole batch, and per-item results are
routed back to each request's future.

Round 7 adds the request-lifecycle scheduler on top: priority classes
and deadlines (``policy.DeadlineQueue``), KV-footprint admission and
the drain gate (``admission.AdmissionController``), preemption of
batch-class streams for interactive arrivals (engine/streams.py), and
graceful SIGTERM drain (``Batcher.begin_drain``/``drained``).
"""

from .admission import AdmissionController  # noqa: F401
from .batcher import Batcher, QueueFullError  # noqa: F401
from .policy import (  # noqa: F401
    BATCH,
    CLASSES,
    INTERACTIVE,
    DeadlineExceededError,
    DeadlineQueue,
)
