"""L3 request scheduling: the dynamic-batching queue.

The component the whole latency/throughput metric hinges on (SURVEY.md
§3.2): concurrent ``/predict`` requests accumulate into batches under a
max-batch-size (``max_batch=32``, BASELINE.json:10) + max-wait policy,
one jitted dispatch serves the whole batch, and per-item results are
routed back to each request's future.
"""

from .batcher import Batcher, QueueFullError  # noqa: F401
