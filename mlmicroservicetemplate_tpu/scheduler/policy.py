"""Deadline-aware, class-weighted wait queue (the scheduler's policy
core).

The seed's front door was binary: a raw FIFO ``asyncio.Queue`` in the
batcher and an instant 503 past ``max_streams`` in the stream loop.
This module replaces both with one policy structure, following the
memory-aware / SLA-constrained batching literature (PAPERS.md): what
decides goodput under overload is WHICH request waits, for HOW long,
and which one is shed — not the kernels.

Policy, in one place:

- Two priority classes (``interactive`` > ``batch``), selected per
  request via the ``X-Priority`` header with a config default.
- Earliest-deadline-first ordering WITHIN a class; FIFO tie-break for
  deadline-less requests (so the default config degrades to exactly
  the seed's FIFO behavior).
- Class-weighted dequeue ACROSS classes: ``weight`` interactive pops
  per batch pop while both classes wait, so batch work cannot starve
  but never delays interactive work by more than 1/weight.
- Overload shed on ``put``: the victim is the lowest-class,
  latest-deadline waiter — and only if the newcomer outranks it;
  otherwise the newcomer itself is shed (503).
- Expiry: a request still waiting past its deadline is removed and
  failed FAST (504 before dispatch) instead of being served stale or
  timing out client-side after burning device time.

Thread-safe: the batcher puts/pops on the asyncio event loop while the
continuous decode loop pops from its owner thread.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from collections import deque

from ..utils import metrics

INTERACTIVE = "interactive"
BATCH = "batch"
#: Rank order: earlier = higher priority.
CLASSES = (INTERACTIVE, BATCH)


class QueueFullError(Exception):
    """Queue at capacity; shed load (HTTP 503).

    ``reason`` labels the shed counter (queue_full | kv_budget | drain |
    quota | adapter_pool); ``retry_after_s`` rides to the HTTP
    Retry-After header.  ``quota`` sheds (per-tenant admission,
    tenancy/accounts.py) map to HTTP 429 instead of 503 — the tenant is
    over ITS budget while the service has capacity to sell elsewhere.
    """

    def __init__(self, msg: str = "", reason: str = "queue_full",
                 retry_after_s: float | None = None):
        super().__init__(msg)
        self.reason = reason
        self.retry_after_s = retry_after_s


class DeadlineExceededError(Exception):
    """The request's deadline passed while it waited (HTTP 504)."""


def _dl(item) -> float:
    """Sort key: absolute monotonic deadline, None = no deadline = last."""
    return item.deadline if item.deadline is not None else float("inf")


class PrefillPacer:
    """Deadline-aware chunk budget for prefill–decode interleaving
    (PREFILL_CHUNK; engine/streams.py).

    Policy, mirroring the dequeue weights: interactive-class prefill
    always advances (it IS the latency-sensitive work — holding it
    back only moves its TTFT); batch-class prefill is starved while
    interactive-class decode is live, EXCEPT one window every
    ``weight`` boundaries so it cannot starve forever; with no
    interactive decode running, batch prefill backfills the idle
    compute freely."""

    def __init__(self, weight: int = 4):
        self.weight = max(1, int(weight))
        self._held = 0
        # Optional flight recorder (utils/tracing.FlightRecorder, wired
        # by the decode loop): every hold/grant decision on batch-class
        # prefill is an event in the engine post-mortem ring — "why
        # didn't my batch prompt advance" answers itself.
        self.recorder = None

    def allow(self, job_klass: str, interactive_active: bool) -> bool:
        """May a ``job_klass`` prefill window dispatch at this chunk
        boundary, given whether interactive decode is live?"""
        if job_klass == INTERACTIVE or not interactive_active:
            return True
        self._held += 1
        if self._held >= self.weight:
            self._held = 0
            if self.recorder is not None:
                self.recorder.event(
                    "pacer_grant", klass=job_klass, weight=self.weight
                )
            return True
        if self.recorder is not None:
            self.recorder.event(
                "pacer_hold", klass=job_klass, held=self._held,
                weight=self.weight,
            )
        return False


class BackfillGovernor:
    """How many bulk-job lines may ride in flight right now
    (JOB_MAX_CONCURRENT_LINES; jobs/executor.py).

    Bulk lines are batch-class streams, so the deadline queue's class
    weights and chunk-boundary preemption already protect interactive
    traffic once a line is ADMITTED — what this governor controls is
    how hard the executor pushes on admission in the first place
    (SLA-constrained batching, arXiv 2503.05248: the bulk lane rides
    the same scheduler, it must not flood it):

    - no interactive work anywhere → claim the full cap (pure
      idle-compute backfill);
    - interactive decode live → half the cap (lines in slots still
      yield via preemption, but fresh claims deepen the next
      preemption sweep);
    - interactive work WAITING (queued or mid-prefill) → one line,
      keeping the lane warm without competing for the very capacity
      the waiters need.
    """

    def __init__(self, cap: int):
        self.cap = max(1, int(cap))

    def target(self, interactive_live: bool,
               interactive_waiting: bool) -> int:
        if interactive_waiting:
            return 1
        if interactive_live:
            return max(1, self.cap // 2)
        return self.cap


class SLOTracker:
    """Per-priority-class latency-SLO burn-rate tracking (r20 perf
    observatory; docs/observability.md).

    Objectives come from the ``SLO_TTFT_MS``/``SLO_TBT_MS`` knobs
    (interactive class) and their ``SLO_BATCH_*`` siblings; a 0 knob
    disables that (kind, class) objective.  Each delivery the decode
    loop already measures (TTFT at the first chunk, TBT per inter-chunk
    gap — ``engine/streams.py::_emit_tokens``) is scored good/bad
    against its objective, and the classic SRE burn rate is derived
    over two windows::

        burn = (bad / total within window) / (1 - SLO_TARGET)

    1.0 = consuming the error budget exactly at the sustainable rate;
    >1 = the SLO is being violated; the FAST window reacts to incidents
    while the SLOW window filters blips.  Exported as
    ``slo_{ttft,tbt}_burn_rate{klass, window}`` gauges (rate-limited to
    ~1/s) and consumed by the ``ScalingGovernor`` when
    ``SCALE_UP_SLO_BURN`` is set (off by default — bit-identical
    scaling decisions when unset, pinned).

    Pure policy: clock-injected (tests drive burn windows without
    sleeping), bounded memory (one deque per objective, pruned to the
    slow window), thread-safe (the decode loop notes; the governor and
    /status read)."""

    KINDS = ("ttft", "tbt")
    WINDOW_NAMES = ("fast", "slow")

    def __init__(self, model: str, objectives: dict, target: float = 0.99,
                 windows_s: tuple = (60.0, 600.0), clock=None,
                 max_samples: int = 4096):
        self.model = model
        #: {(kind, klass): objective_seconds}, only enabled objectives.
        self.objectives = {
            k: float(v) for k, v in objectives.items() if v and v > 0
        }
        self.target = float(target)
        self.windows_s = (float(windows_s[0]), float(windows_s[1]))
        self._budget = max(1e-9, 1.0 - self.target)
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._max_samples = int(max_samples)
        self._samples: dict = {
            key: deque(maxlen=self._max_samples) for key in self.objectives
        }
        self._last_export = 0.0

    @classmethod
    def from_cfg(cls, model: str, cfg, clock=None):
        """Tracker from the service knobs, or None when every
        objective is 0 (the default) — the zero-overhead-off gate."""
        objectives = {
            ("ttft", INTERACTIVE): float(
                getattr(cfg, "slo_ttft_ms", 0.0) or 0.0
            ) / 1e3,
            ("tbt", INTERACTIVE): float(
                getattr(cfg, "slo_tbt_ms", 0.0) or 0.0
            ) / 1e3,
            ("ttft", BATCH): float(
                getattr(cfg, "slo_batch_ttft_ms", 0.0) or 0.0
            ) / 1e3,
            ("tbt", BATCH): float(
                getattr(cfg, "slo_batch_tbt_ms", 0.0) or 0.0
            ) / 1e3,
        }
        if not any(v > 0 for v in objectives.values()):
            return None
        windows = getattr(cfg, "slo_windows_s", None) or "60,600"
        try:
            parts = [float(x) for x in str(windows).split(",") if x.strip()]
        except ValueError:
            parts = [60.0, 600.0]
        if len(parts) != 2 or parts[0] <= 0 or parts[0] >= parts[1]:
            parts = [60.0, 600.0]
        return cls(
            model, objectives,
            target=float(getattr(cfg, "slo_target", 0.99) or 0.99),
            windows_s=(parts[0], parts[1]), clock=clock,
        )

    # -- write side (the decode loop's delivery path) ------------------

    def note(self, kind: str, klass: str, value_s: float) -> None:
        obj = self.objectives.get((kind, klass))
        if obj is None:
            return
        now = self._clock()
        with self._lock:
            q = self._samples[(kind, klass)]
            q.append((now, value_s <= obj))
            # Prune past the slow window so burn reads stay O(window).
            horizon = now - self.windows_s[1]
            while q and q[0][0] < horizon:
                q.popleft()
            export = now - self._last_export >= 1.0
            if export:
                self._last_export = now
        if export:
            self.export_gauges(now)

    # -- read side -----------------------------------------------------

    def burn_rate(self, kind: str, klass: str,
                  window_s: float | None = None,
                  now: float | None = None) -> float:
        """Burn rate over ``window_s`` (default: the fast window); 0.0
        with no samples (no traffic = no budget burned)."""
        if (kind, klass) not in self.objectives:
            return 0.0
        window = self.windows_s[0] if window_s is None else float(window_s)
        now = self._clock() if now is None else now
        horizon = now - window
        with self._lock:
            q = self._samples[(kind, klass)]
            total = bad = 0
            for ts, good in reversed(q):
                if ts < horizon:
                    break
                total += 1
                if not good:
                    bad += 1
        if not total:
            return 0.0
        return (bad / total) / self._budget

    def worst_burn(self) -> float:
        """Max fast-window burn across every enabled objective — the
        single scalar the ScalingGovernor consumes."""
        return max(
            (
                self.burn_rate(kind, klass)
                for kind, klass in self.objectives
            ),
            default=0.0,
        )

    def export_gauges(self, now: float | None = None) -> None:
        """Set the burn-rate gauges for every (objective, window)."""
        now = self._clock() if now is None else now
        for (kind, klass) in self.objectives:
            gauge = (
                metrics.SLO_TTFT_BURN if kind == "ttft"
                else metrics.SLO_TBT_BURN
            )
            for name, win in zip(self.WINDOW_NAMES, self.windows_s):
                gauge.labels(self.model, klass, name).set(
                    self.burn_rate(kind, klass, win, now=now)
                )

    def snapshot(self) -> dict:
        """/status.perf.slo + /debug/perf: objectives + burn rates."""
        now = self._clock()
        out: dict = {
            "target": self.target,
            "windows_s": list(self.windows_s),
            "objectives_ms": {
                f"{kind}:{klass}": round(obj * 1e3, 3)
                for (kind, klass), obj in sorted(self.objectives.items())
            },
            "burn": {},
        }
        for (kind, klass) in sorted(self.objectives):
            for name, win in zip(self.WINDOW_NAMES, self.windows_s):
                out["burn"][f"{kind}:{klass}:{name}"] = round(
                    self.burn_rate(kind, klass, win, now=now), 4
                )
        with self._lock:
            out["samples"] = {
                f"{kind}:{klass}": len(self._samples[(kind, klass)])
                for (kind, klass) in sorted(self.objectives)
            }
        return out


class ScalingGovernor:
    """Decide when the replica fleet should grow or shrink
    (engine/fleet.py drives ``ReplicaFleet`` off these decisions;
    docs/autoscaling.md).

    Pure policy over a load snapshot — no engines, no threads — so the
    thresholds are unit-testable with an injected clock.  The signals
    are the router's OWN load exports (λScale, arXiv 2502.09922: scale
    off serving signals, not external monitors):

    - **queue depth**: waiting streams per live replica ≥ ``up_queue``
      → scale up (the queue is where overload becomes visible first);
    - **committed KV**: the live fleet's committed-KV bytes at
      ``up_kv_frac`` of its budget → scale up (memory saturates before
      compute for long-context traffic);
    - **TTFT EWMA**: the decode loops' time-to-first-chunk EWMA past
      ``up_ttft_s`` → scale up (0 disables the signal — it needs a
      deployment-calibrated threshold);
    - **sustained lull**: total load (active + queued) would fit in
      ``down_load`` of the SURVIVORS' slots for ``down_cooldown_s``
      straight → scale down (the hysteresis that keeps a bursty
      workload from flapping).

    One step per decision (up OR down by 1): each event rebalances the
    fleet budget and re-snapshots, so multi-step corrections converge
    over a few ticks instead of overshooting on a stale signal.
    ``note_event`` stamps the cooldowns when the fleet actually acted
    (a failed spawn must not burn the cooldown silently).
    """

    def __init__(self, min_r: int, max_r: int, *, up_queue: float = 2.0,
                 up_kv_frac: float = 0.85, up_ttft_s: float = 0.0,
                 up_cooldown_s: float = 3.0, down_load: float = 0.25,
                 down_cooldown_s: float = 10.0, up_slo_burn: float = 0.0,
                 clock=None):
        self.min_r = max(1, int(min_r))
        self.max_r = max(self.min_r, int(max_r))
        self.up_queue = float(up_queue)
        self.up_kv_frac = float(up_kv_frac)
        self.up_ttft_s = float(up_ttft_s)
        # SLO-burn scale-up signal (r20; SCALE_UP_SLO_BURN): scale up
        # when the SLOTracker's worst fast-window burn rate reaches
        # this threshold.  0 (default) = signal off — decisions are
        # bit-identical to the pre-SLO governor (pinned).
        self.up_slo_burn = float(up_slo_burn)
        self.up_cooldown_s = float(up_cooldown_s)
        self.down_load = float(down_load)
        self.down_cooldown_s = float(down_cooldown_s)
        self._clock = clock if clock is not None else time.monotonic
        self._last_up: float | None = None
        self._low_since: float | None = None

    def decide(self, *, live: int, queued: int, active: int,
               slots: int, kv_frac: float = 0.0,
               ttft_ewma_s: float = 0.0,
               slo_burn: float = 0.0,
               free_groups: int | None = None) -> tuple[str | None, str]:
        """(direction, cause) for one governor tick.  direction is
        "up" | "down" | None; cause labels the scale-event counter
        (queue | kv | ttft | slo | min | idle | steady | no_devices).

        ``free_groups`` is the multi-chip fleet's group-carve signal:
        how many whole device groups of the fleet's default width the
        host can still seat (None — single-device fleets — leaves every
        decision unchanged).  The governor scales in units of WHOLE
        groups, so an "up" with ``free_groups == 0`` degrades to
        ``(None, "no_devices")`` — an honest stall instead of a doomed
        spawn per tick."""
        now = self._clock()
        if live <= 0:
            # Nothing alive to compare load against: the rejoin path
            # (engine/fleet.py) owns recovery, not the load policy.
            return None, "dead"
        no_seat = free_groups is not None and free_groups <= 0
        if live < self.min_r:
            return (None, "no_devices") if no_seat else ("up", "min")
        up_ready = self._last_up is None or (
            now - self._last_up >= self.up_cooldown_s
        )
        if live < self.max_r and up_ready:
            want_up = None
            if self.up_queue and queued >= self.up_queue * live:
                want_up = "queue"
            elif self.up_kv_frac and kv_frac >= self.up_kv_frac:
                want_up = "kv"
            elif self.up_ttft_s and ttft_ewma_s >= self.up_ttft_s:
                want_up = "ttft"
            elif self.up_slo_burn and slo_burn >= self.up_slo_burn:
                want_up = "slo"
            if want_up is not None:
                return (None, "no_devices") if no_seat else ("up", want_up)
        if live > self.min_r:
            survivors = live - 1
            low = (active + queued) <= self.down_load * slots * survivors
            if low:
                if self._low_since is None:
                    self._low_since = now
                elif now - self._low_since >= self.down_cooldown_s:
                    return "down", "idle"
            else:
                self._low_since = None
        else:
            self._low_since = None
        return None, "steady"

    def note_event(self, direction: str) -> None:
        """The fleet actually scaled: stamp the cooldown clocks."""
        now = self._clock()
        if direction == "up":
            self._last_up = now
        self._low_since = None

    def status(self) -> dict:
        now = self._clock()
        return {
            "min": self.min_r,
            "max": self.max_r,
            "up_cooldown_remaining_s": (
                round(max(
                    0.0, self._last_up + self.up_cooldown_s - now
                ), 3) if self._last_up is not None else 0.0
            ),
            "low_load_for_s": (
                round(now - self._low_since, 3)
                if self._low_since is not None else None
            ),
        }


class DecodeWindowGovernor:
    """Pick the fused decode-window depth W for one dispatch
    (DECODE_WINDOW; engine/streams.py, docs/decode-fusion.md).

    The tradeoff it governs is the SLA-constrained batching one
    (arXiv 2503.05248), applied to the fusion axis instead of batch
    size: a deep window divides host round-trips per token by W
    (throughput), but widens every host-visible boundary — token
    delivery, admission, preemption, prefill interleave — to W chunks
    (latency).  Policy, mirroring the queue's class split:

    - interactive streams live OR waiting → W=1 (their TBT and their
      admission/preemption cadence bind at chunk granularity — the
      acceptance bar is "interactive TBT p99 no worse than per-chunk");
    - batch-only traffic and idle backfill → fuse to the cap;
    - never fuse past the work that remains (a window covering chunks
      no live stream needs wastes device time and delays completion
      detection), rounded DOWN to a power of two so the executable set
      stays {1, 2, 4, ...} instead of one compile per remaining-budget
      value.

    ``auto=False`` always fuses to the cap (dedicated throughput lanes
    with no interactive SLA).
    """

    def __init__(self, cap: int, auto: bool = True):
        self.cap = max(1, int(cap))
        self.auto = bool(auto)
        # Optional flight recorder (wired by the decode loop): depth
        # drops land in the post-mortem ring like pacer decisions do.
        self.recorder = None
        self._last = 1

    def pick(self, max_chunks: int, interactive_live: bool,
             interactive_waiting: bool) -> int:
        if self.cap <= 1 or max_chunks <= 1:
            return 1
        if self.auto and (interactive_live or interactive_waiting):
            if self._last > 1 and self.recorder is not None:
                self.recorder.event(
                    "window_drop",
                    live=bool(interactive_live),
                    waiting=bool(interactive_waiting),
                )
            self._last = 1
            return 1
        w = min(self.cap, int(max_chunks))
        w = 1 << (w.bit_length() - 1)  # power-of-two floor
        self._last = w
        return w

    def preview(self, max_chunks: int, interactive_live: bool,
                interactive_waiting: bool) -> int:
        """``pick`` without the side effects (no ``_last`` transition,
        no recorder event): the double-buffered host prep stages the
        NEXT dispatch's window with it, so the real ``pick`` at
        dispatch time stays the single source of governor telemetry."""
        if self.cap <= 1 or max_chunks <= 1:
            return 1
        if self.auto and (interactive_live or interactive_waiting):
            return 1
        w = min(self.cap, int(max_chunks))
        return 1 << (w.bit_length() - 1)


class DeadlineQueue:
    """Bounded two-class EDF wait queue (see module docstring).

    Queued items must expose attributes ``klass`` (interactive|batch),
    ``deadline`` (absolute ``time.monotonic()`` seconds or None),
    ``started`` (True once response bytes went out: exempt from expiry
    and eviction — a preempted stream re-queued for resumption cannot
    be converted to an HTTP error anymore).  The queue stamps a private
    ``_removed`` flag for lazy heap deletion.
    """

    def __init__(self, maxsize: int, weight: int = 4, clock=None):
        self.maxsize = max(1, int(maxsize))
        self.weight = max(1, int(weight))
        self._heaps: dict[str, list] = {k: [] for k in CLASSES}
        self._count: dict[str, int] = {k: 0 for k in CLASSES}
        self._cond = threading.Condition()
        self._seq = itertools.count()
        self._streak = 0  # consecutive interactive pops while batch waits
        # Optional weighted fair share across tenants WITHIN a class
        # (tenancy/fairshare.py; set by the batcher when TENANTS is
        # configured).  None = plain EDF, bit-identical to pre-tenancy.
        self._fairshare = None
        # Injectable clock (graftlint: clock-injection) — expiry and
        # pop timeouts pin in tests without sleeping through real
        # deadlines; item deadlines stay absolute seconds on this clock.
        self._clock = clock if clock is not None else time.monotonic

    # -- introspection -------------------------------------------------

    def qsize(self) -> int:
        with self._cond:
            return sum(self._count.values())

    def waiting(self, klass: str) -> int:
        with self._cond:
            return self._count[klass]

    def waiting_started(self) -> int:
        """Checkpointed (preempted) streams still waiting to resume."""
        with self._cond:
            return sum(
                1
                for heap in self._heaps.values()
                for _, it in heap
                if not it._removed and it.started
            )

    def next_deadline(self) -> float | None:
        """Earliest expirable deadline among waiting items (idle-wake
        timer for the batcher's expiry sweep)."""
        with self._cond:
            best = None
            for heap in self._heaps.values():
                for _, it in heap:
                    if it._removed or it.started or it.deadline is None:
                        continue
                    if best is None or it.deadline < best:
                        best = it.deadline
            return best

    # -- enqueue -------------------------------------------------------

    def put(self, item, force: bool = False):
        """Enqueue; returns an evicted lower-ranked waiter (the caller
        fails it with a 503) or None.  Raises ``QueueFullError`` when
        full and the newcomer outranks nobody.  ``force`` bypasses the
        bound (re-queueing a preempted, already-started stream)."""
        with self._cond:
            victim = None
            if not force and sum(self._count.values()) >= self.maxsize:
                victim = self._pick_victim_locked(item)
                if victim is None:
                    raise QueueFullError(
                        f"queue depth {sum(self._count.values())} >= "
                        f"{self.maxsize}"
                    )
                victim._removed = True
                self._count[victim.klass] -= 1
            item._removed = False
            key = (_dl(item), next(self._seq))
            heapq.heappush(self._heaps[item.klass], (key, item))
            self._count[item.klass] += 1
            self._cond.notify()
            return victim

    def evict_for(self, incoming):
        """Shed-for-admission without enqueueing: returns (and removes)
        the victim ``incoming`` outranks, or None.  Used by callers that
        bound admission on something wider than this queue's size (the
        stream loop counts active slots too)."""
        with self._cond:
            victim = self._pick_victim_locked(incoming)
            if victim is not None:
                victim._removed = True
                self._count[victim.klass] -= 1
            return victim

    def _pick_victim_locked(self, incoming):
        """Lowest-class latest-deadline waiter that ``incoming``
        outranks: strictly lower class, or same class with a strictly
        later deadline.  Started items are never evicted."""
        for klass in reversed(CLASSES):  # lowest class first
            live = [
                it for _, it in self._heaps[klass]
                if not it._removed and not it.started
            ]
            if not live:
                continue
            victim = max(live, key=_dl)
            inc_rank = CLASSES.index(incoming.klass)
            v_rank = CLASSES.index(klass)
            if inc_rank < v_rank:
                return victim
            if inc_rank == v_rank and _dl(incoming) < _dl(victim):
                return victim
            return None
        return None

    # -- dequeue -------------------------------------------------------

    def pop_nowait(self, fits=None):
        """EDF-within-class, class-weighted-across-classes pop; returns
        None when empty (or when no waiter passes ``fits`` — the
        KV-budget admission gate)."""
        with self._cond:
            return self._pop_locked(fits)

    def pop(self, timeout: float | None = None, fits=None):
        """Blocking pop for the decode-loop thread."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            while True:
                item = self._pop_locked(fits)
                if item is not None:
                    return item
                remaining = (
                    None if deadline is None else deadline - self._clock()
                )
                if remaining is not None and remaining <= 0:
                    return None
                if not self._cond.wait(timeout=remaining):
                    return self._pop_locked(fits)

    def set_fairshare(self, fs) -> None:
        """Attach (or detach, ``None``) a ``WeightedFairShare`` ledger:
        dequeue becomes per-tenant EDF under weighted virtual time —
        within each class the tenant with the lowest virtual finish time
        is served its earliest-deadline waiter, so a heavy tenant's
        backlog cannot starve light tenants (pinned by
        tests/test_tenancy.py)."""
        with self._cond:
            self._fairshare = fs

    def prefer_interactive(self) -> None:
        """Reset the weighted-dequeue streak so the next pop serves the
        interactive class (used right after a preemption: the slot that
        was just vacated must not go back to a batch waiter)."""
        with self._cond:
            self._streak = 0

    def _pop_locked(self, fits):
        for klass in self._class_order_locked():
            item = self._pop_class_locked(klass, fits)
            if item is not None:
                if klass == INTERACTIVE and self._count[BATCH] > 0:
                    self._streak += 1
                else:
                    self._streak = 0
                return item
        return None

    def _class_order_locked(self):
        if self._count[INTERACTIVE] and self._count[BATCH]:
            if self._streak >= self.weight:
                return (BATCH, INTERACTIVE)
            return (INTERACTIVE, BATCH)
        return (INTERACTIVE, BATCH) if self._count[INTERACTIVE] else (
            BATCH, INTERACTIVE
        )

    def _pop_class_locked(self, klass: str, fits):
        if self._fairshare is not None:
            return self._pop_class_fair_locked(klass, fits, self._fairshare)
        heap = self._heaps[klass]
        stash = []
        found = None
        while heap:
            key, it = heapq.heappop(heap)
            if it._removed:
                continue
            if fits is not None and not fits(it):
                # Head-of-line doesn't fit the admission budget: look
                # past it (a smaller request may) — expiry bounds how
                # long the skipped head can languish.
                stash.append((key, it))
                continue
            it._removed = True
            self._count[klass] -= 1
            found = it
            break
        for entry in stash:
            heapq.heappush(heap, entry)
        return found

    def _pop_class_fair_locked(self, klass: str, fits, fs):
        """Weighted-fair pop: per-tenant EDF head, then the fair-share
        ledger picks which tenant is served.  O(n) scan with lazy heap
        deletion — the heap keeps EDF order for the plain path and for
        ``expire``; fairness only reorders ACROSS tenants, never within
        one (EDF-within-tenant is preserved by taking each tenant's
        heap-key minimum)."""
        heads: dict[str, tuple] = {}
        for key, it in self._heaps[klass]:
            if it._removed:
                continue
            if fits is not None and not fits(it):
                continue
            t = getattr(it, "tenant", "") or ""
            cur = heads.get(t)
            if cur is None or key < cur[0]:
                heads[t] = (key, it)
        if not heads:
            return None
        tenant = fs.pick(heads.keys())
        _, it = heads[tenant]
        it._removed = True
        self._count[klass] -= 1
        fs.charge(tenant)
        return it

    # -- expiry / shutdown --------------------------------------------

    def expire(self, now: float | None = None) -> list:
        """Remove and return every waiter whose deadline passed (the
        caller fails them with ``DeadlineExceededError`` → 504).
        Started items never expire."""
        now = self._clock() if now is None else now
        out = []
        with self._cond:
            for klass in CLASSES:
                heap = self._heaps[klass]
                repush = []
                while heap and heap[0][0][0] <= now:
                    key, it = heapq.heappop(heap)
                    if it._removed:
                        continue
                    if it.started:
                        repush.append((key, it))
                        continue
                    it._removed = True
                    self._count[klass] -= 1
                    out.append(it)
                for entry in repush:
                    heapq.heappush(heap, entry)
        return out

    def drain_all(self) -> list:
        """Remove and return everything (shutdown path)."""
        with self._cond:
            out = [
                it
                for heap in self._heaps.values()
                for _, it in heap
                if not it._removed
            ]
            for it in out:
                it._removed = True
            self._heaps = {k: [] for k in CLASSES}
            self._count = {k: 0 for k in CLASSES}
            return out
