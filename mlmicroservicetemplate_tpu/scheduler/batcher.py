"""Asyncio dynamic batcher: accumulate → dispatch → route futures.

Policy (mirrors the reference's queue, SURVEY.md §2 "Dynamic-batching
queue"): a batch closes when it reaches ``max_batch`` items or when
``batch_timeout_ms`` has elapsed since its first item arrived —
whichever comes first.  A burst that is already queued forms a full
batch with zero added wait (the fast path drains without touching a
timer).

Device dispatch happens on a single worker thread
(``run_in_executor``): JAX's blocking ``device_get`` must not stall the
event loop, which on this 1-vCPU host also runs HTTP parsing and
pre/post-processing (SURVEY.md §7.4.3).

Backpressure: beyond ``max_queue`` waiting items, ``submit`` raises
``QueueFullError`` which the API layer maps to 503 load-shed.
"""

from __future__ import annotations

import asyncio
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, AsyncIterator

import numpy as np

from ..utils import metrics

_END = object()


class QueueFullError(Exception):
    """Queue at capacity; shed load (HTTP 503)."""


class Batcher:
    def __init__(self, engine, cfg):
        self.engine = engine
        self.model = engine.bundle.name
        self.max_batch = int(cfg.max_batch)
        self.timeout_s = float(cfg.batch_timeout_ms) / 1000.0
        self.max_queue = int(cfg.max_queue)
        self._queue: asyncio.Queue = asyncio.Queue()
        # Dispatch threads = pipeline depth: batches overlap in flight
        # so the host<->device round-trip of batch N hides behind the
        # compute of batch N+1 (the engine's semaphore is the real cap).
        depth = max(1, int(getattr(cfg, "pipeline_depth", 4)))
        self._executor = ThreadPoolExecutor(
            max_workers=depth, thread_name_prefix="dispatch"
        )
        # Streams hold a worker for their whole generation, so they get
        # their own pool — a long-running stream must never starve the
        # batch dispatch path.  Beyond max_streams concurrent streams we
        # shed load rather than queue invisibly.
        self.max_streams = int(getattr(cfg, "max_streams", 8))
        self._stream_executor = ThreadPoolExecutor(
            max_workers=self.max_streams, thread_name_prefix="stream"
        )
        self._active_streams = 0
        self._task: asyncio.Task | None = None
        self._inflight: set[asyncio.Task] = set()
        self._closed = False
        # Continuous batching (default): concurrent generative streams
        # share ONE batched decode dispatch instead of holding a worker
        # each (engine/streams.py).  CONTINUOUS_BATCHING=0 falls back to
        # the per-stream path above (kept for A/B measurement).
        self._cdl = None
        if getattr(engine.bundle, "kind", None) == "seq2seq" and getattr(
            cfg, "continuous_batching", True
        ):
            from ..engine.streams import ContinuousDecodeLoop

            self._cdl = ContinuousDecodeLoop(engine, cfg)
            # MAX_STREAMS caps TOTAL concurrent generations: each side
            # counts the other's active streams in its admission check.
            self._cdl.external_active = lambda: self._active_streams

    # ------------------------------------------------------------------
    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self._closed = True
        if self._task is not None:
            self._queue.put_nowait(_END)
            await self._task
            self._task = None
        if self._inflight:
            await asyncio.gather(*self._inflight, return_exceptions=True)
        if self._cdl is not None:
            await asyncio.get_running_loop().run_in_executor(None, self._cdl.stop)
        self._executor.shutdown(wait=False)
        self._stream_executor.shutdown(wait=False)

    def warmup(self) -> None:
        """Blocking: compile the continuous-batching executables (slot
        insert, batched chunk) so the first stream pays no compiles.
        Called from the app's warmup executor, after engine.warmup."""
        if self._cdl is not None:
            self._cdl.warm()

    # ------------------------------------------------------------------
    async def submit(self, feats: dict) -> np.ndarray:
        """Enqueue one preprocessed item; resolves to its result row."""
        if self._closed:
            raise RuntimeError("batcher is stopped")
        if self._queue.qsize() >= self.max_queue:
            raise QueueFullError(f"queue depth {self._queue.qsize()} >= {self.max_queue}")
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._queue.put_nowait((feats, fut, time.monotonic()))
        metrics.QUEUE_DEPTH.labels(self.model).set(self._queue.qsize())
        return await fut

    def submit_stream(self, feats: dict) -> AsyncIterator[np.ndarray]:
        """Streaming seq2seq: bridge the engine's blocking chunk
        generator onto the event loop.  Each yielded array is one chunk
        of token ids.

        Admission is atomic: the counter check AND increment both happen
        here, synchronously in the event loop, before the generator is
        returned — so concurrent requests in the same loop window cannot
        all slip under ``max_streams``, and the caller can still return
        a 503 before any response bytes go out.  The decrement rides the
        pump future's done-callback, so an abandoned (never-iterated or
        half-consumed) generator cannot leak a slot."""
        if self._closed:
            raise RuntimeError("batcher is stopped")
        # SPEC_DECODE routes streams to the per-stream path (where the
        # speculative executables live) ONLY in the low-concurrency
        # regime it targets (< spec_max_streams active): under load,
        # one shared batched dispatch for all streams beats N
        # serialized speculative loops, so traffic falls back to the
        # continuous loop.  Sampled streams speculate via rejection-
        # sampling acceptance unless SPEC_SAMPLED=0 opted them out.
        cdl_admitted = self._cdl._admitted if self._cdl is not None else 0
        spec_route = (
            getattr(self.engine, "spec_enabled", False)
            and (
                float(feats.get("temperature", 0.0)) == 0.0
                or getattr(self.engine, "spec_sampled", False)
            )
            and (self._active_streams + cdl_admitted)
            < int(getattr(self.engine.cfg, "spec_max_streams", 1))
        )
        # SPEC_CONTINUOUS loop + SPEC_SAMPLED=0: the shared loop would
        # run rejection-sampling acceptance on sampled rows, violating
        # the opt-out's strict cross-path seed contract — those streams
        # bypass to the per-stream chunked path instead (each holds a
        # worker; the documented cost of the opt-out).
        sampled_opt_out = (
            self._cdl is not None
            and getattr(self._cdl, "spec", False)
            and not getattr(self.engine, "spec_sampled", True)
            and float(feats.get("temperature", 0.0)) > 0.0
        )
        if (
            self._cdl is not None
            and not spec_route
            and not sampled_opt_out
            and int(feats.get("length", 0)) <= self._cdl.max_prompt
        ):
            return self._cdl.submit_stream(feats)
        # Oversized prompts (longer than the largest seq bucket) cannot
        # join the shared slot batch; they keep the per-stream path —
        # but MAX_STREAMS caps TOTAL concurrent generations, so count
        # the loop's admissions too.
        cdl_active = self._cdl._admitted if self._cdl is not None else 0
        if self._active_streams + cdl_active >= self.max_streams:
            raise QueueFullError(
                f"{self._active_streams} streams active >= max_streams={self.max_streams}"
            )
        loop = asyncio.get_running_loop()
        chunks: asyncio.Queue = asyncio.Queue()
        cancelled = threading.Event()

        def pump():
            try:
                gen = self.engine.generate_stream(feats)
                try:
                    while True:
                        # Check BEFORE asking the engine for the next
                        # chunk: a disconnected client pays at most the
                        # one dispatch already in flight, never a fresh
                        # one (the generator only touches the device
                        # inside next()).
                        if cancelled.is_set():
                            return
                        try:
                            chunk = next(gen)
                        except StopIteration:
                            break
                        loop.call_soon_threadsafe(chunks.put_nowait, chunk)
                        metrics.TOKENS.labels(self.model).inc(int(chunk.size))
                finally:
                    gen.close()
                loop.call_soon_threadsafe(chunks.put_nowait, _END)
            except BaseException as e:  # propagate to the consumer
                loop.call_soon_threadsafe(chunks.put_nowait, e)

        self._active_streams += 1
        pump_fut = loop.run_in_executor(self._stream_executor, pump)

        def _release(_fut):
            self._active_streams -= 1

        pump_fut.add_done_callback(_release)

        async def gen():
            try:
                while True:
                    item = await chunks.get()
                    if item is _END:
                        break
                    if isinstance(item, BaseException):
                        raise item
                    yield item
            finally:
                # Consumer gone (client disconnect / full drain): tell
                # the pump to stop at the next chunk boundary.
                cancelled.set()

        return gen()

    # ------------------------------------------------------------------
    async def _run(self) -> None:
        while True:
            first = await self._queue.get()
            if first is _END:
                return
            # Keep the depth gauge honest on drain: pulling the last
            # queued item must drop it to 0 now, not at the next submit.
            metrics.QUEUE_DEPTH.labels(self.model).set(self._queue.qsize())
            batch = [first]
            deadline = time.monotonic() + self.timeout_s
            while len(batch) < self.max_batch:
                # Fast path: drain whatever is already queued.
                try:
                    item = self._queue.get_nowait()
                except asyncio.QueueEmpty:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    try:
                        item = await asyncio.wait_for(self._queue.get(), remaining)
                    except asyncio.TimeoutError:
                        break
                if item is _END:
                    self._spawn_dispatch(batch)
                    return
                batch.append(item)
            metrics.QUEUE_DEPTH.labels(self.model).set(self._queue.qsize())
            # Fire-and-track: the batcher immediately goes back to
            # collecting while this batch's device round-trip is in
            # flight (bounded by the engine's pipeline semaphore).
            self._spawn_dispatch(batch)

    def _spawn_dispatch(self, batch: list) -> None:
        task = asyncio.get_running_loop().create_task(self._dispatch(batch))
        self._inflight.add(task)
        task.add_done_callback(self._inflight.discard)

    async def _dispatch(self, batch: list) -> None:
        loop = asyncio.get_running_loop()
        now = time.monotonic()
        feats = [b[0] for b in batch]
        for _, _, t_in in batch:
            metrics.QUEUE_WAIT.labels(self.model).observe(now - t_in)
        metrics.BATCH_SIZE.labels(self.model).observe(len(batch))
        t0 = time.monotonic()
        try:
            rows = await loop.run_in_executor(
                self._executor, self.engine.run_batch, feats
            )
        except Exception as e:
            for _, fut, _ in batch:
                if not fut.done():
                    fut.set_exception(e)
            return
        metrics.DEVICE_TIME.labels(self.model).observe(time.monotonic() - t0)
        for (_, fut, _), row in zip(batch, rows):
            if not fut.done():
                fut.set_result(row)


def batch_results(rows: list[np.ndarray]) -> Any:
    """Helper for tests: stack row results."""
    return np.stack(rows)
